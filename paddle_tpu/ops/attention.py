"""Attention primitives.

The reference composes additive attention from primitive layers
(simple_attention, trainer_config_helpers/networks.py:1304: fc + expand +
addto + tanh + fc(1) + sequence softmax + scaling + pooling). Here they are
fused ops; dot-product attention is also provided (the building block the
ring-attention sequence parallelism in paddle_tpu/parallel uses)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.ops import linalg
from paddle_tpu.ops import sequence as seq_ops

Array = jax.Array


def additive_scores(
    enc_proj: Array,  # [B, T, A] — W_e @ encoder states (precomputed)
    dec_state: Array,  # [B, H]
    w_dec: Array,  # [H, A]
    v: Array,  # [A]
) -> Array:
    """Bahdanau scores: v^T tanh(enc_proj + W_d s) → [B, T]."""
    q = linalg.matmul(dec_state, w_dec)  # [B, A]
    e = jnp.tanh(enc_proj + q[:, None, :])
    return jnp.einsum("bta,a->bt", e, v)


def additive_attention(
    enc: Array,  # [B, T, D] encoder states
    enc_proj: Array,  # [B, T, A]
    dec_state: Array,  # [B, H]
    w_dec: Array,
    v: Array,
    lengths: Array,
) -> Tuple[Array, Array]:
    """→ (context [B, D], weights [B, T]); masked sequence softmax."""
    scores = additive_scores(enc_proj, dec_state, w_dec, v)
    weights = seq_ops.seq_softmax(scores, lengths)
    context = jnp.einsum("btd,bt->bd", enc, weights.astype(enc.dtype))
    return context, weights


def dot_product_attention(
    q: Array,  # [B, Tq, D]
    k: Array,  # [B, Tk, D]
    v: Array,  # [B, Tk, Dv]
    mask: Optional[Array] = None,  # [B, Tq, Tk] or [B, 1, Tk]
    scale: Optional[float] = None,
) -> Array:
    """Scaled dot-product attention → [B, Tq, Dv]."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if mask is not None:
        logits = jnp.where(mask.astype(jnp.bool_), logits, seq_ops.NEG_INF)
    w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bqk,bkv->bqv", w, v)
