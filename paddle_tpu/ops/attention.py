"""Attention primitives.

The reference composes additive attention from primitive layers
(simple_attention, trainer_config_helpers/networks.py:1304: fc + expand +
addto + tanh + fc(1) + sequence softmax + scaling + pooling). Here they are
fused ops; dot-product attention is also provided (the building block the
ring-attention sequence parallelism in paddle_tpu/parallel uses)."""

from __future__ import annotations

import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes
from paddle_tpu.ops import linalg
from paddle_tpu.ops import sequence as seq_ops

Array = jax.Array


def additive_scores(
    enc_proj: Array,  # [B, T, A] — W_e @ encoder states (precomputed)
    dec_state: Array,  # [B, H]
    w_dec: Array,  # [H, A]
    v: Array,  # [A]
) -> Array:
    """Bahdanau scores: v^T tanh(enc_proj + W_d s) → [B, T]. The score
    contraction is a dot boundary: its inputs cross to the ambient compute
    dtype (v is an f32 master param — without the cast it would promote the
    whole score path back to f32 under a bf16 policy)."""
    p = dtypes.current()
    q = linalg.matmul(dec_state, w_dec)  # [B, A]
    e = jnp.tanh(p.cast(enc_proj) + q[:, None, :])
    return jnp.einsum("bta,a->bt", e, p.cast(v))


def additive_attention(
    enc: Array,  # [B, T, D] encoder states
    enc_proj: Array,  # [B, T, A]
    dec_state: Array,  # [B, H]
    w_dec: Array,
    v: Array,
    lengths: Array,
) -> Tuple[Array, Array]:
    """→ (context [B, D], weights [B, T] f32); masked sequence softmax runs
    f32 (seq_softmax pin), the context contraction is a dot boundary in the
    ambient compute dtype."""
    p = dtypes.current()
    scores = additive_scores(enc_proj, dec_state, w_dec, v)
    weights = seq_ops.seq_softmax(scores, lengths)
    context = jnp.einsum("btd,bt->bd", p.cast(enc), p.cast(weights))
    return context, weights


def _attn_fuse_ok(q: Array, k: Array, v: Array, scale) -> bool:
    """Route to the fused pallas forward (ops/pallas/rnn_kernels.py
    attention_seq_fused) when the pallas dispatch policy is on, the scale is
    static (it folds into the kernel), and one batch row's working set —
    q/k/v blocks plus the [Tq, Tk] score tile that the fusion keeps in VMEM
    — fits the budget (default 2M f32 elements ≈ 8 MB of the ~16 MB VMEM;
    PADDLE_TPU_FUSED_ATTN_MAX overrides, 0 disables)."""
    if scale is not None and not isinstance(scale, (int, float)):
        return False  # traced scale: keep the jnp path
    limit = int(os.environ.get("PADDLE_TPU_FUSED_ATTN_MAX", "2000000"))
    if limit <= 0:
        return False
    b, tq, d = q.shape
    tk = k.shape[1]
    dv = v.shape[2]
    # score tile + mask block (worst case Mq == Tq: a full [Tq, Tk] mask
    # block is resident alongside the score tile) + q/k/v blocks + output
    row = 2 * tq * tk + tk * (d + dv) + tq * (d + dv)
    if row > limit:
        return False
    from paddle_tpu.ops import pallas as pal

    return pal.enabled()


def dot_product_attention(
    q: Array,  # [B, Tq, D]
    k: Array,  # [B, Tk, D]
    v: Array,  # [B, Tk, Dv]
    mask: Optional[Array] = None,  # [B, Tq, Tk] or [B, 1, Tk]
    scale: Optional[float] = None,
    fused: Optional[bool] = None,
) -> Array:
    """Scaled dot-product attention → [B, Tq, Dv].

    `fused=None` (auto) dispatches to the fused pallas forward on TPU (see
    _attn_fuse_ok); the jnp body below is the CPU oracle AND the exact
    source of the fused op's backward. Softmax runs f32 either way."""
    d = q.shape[-1]
    if fused is None:
        fused = _attn_fuse_ok(q, k, v, scale)
    if fused:
        from paddle_tpu.ops.pallas.rnn_kernels import attention_seq_fused

        s = float(scale) if scale is not None else 1.0 / math.sqrt(d)
        m = (
            jnp.ones((q.shape[0], 1, k.shape[1]), jnp.float32)
            if mask is None
            else mask
        )
        return attention_seq_fused(q, k, v, m, s)
    scale = scale if scale is not None else 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if mask is not None:
        # keep-where-positive, matching the fused kernel and its oracle
        # (rnn_kernels._attn_oracle) bit for bit — the mask contract is 0/1
        # float, and the two dispatch paths must agree even off-contract
        logits = jnp.where(mask > 0, logits, seq_ops.NEG_INF)
    w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bqk,bkv->bqv", w, v)
