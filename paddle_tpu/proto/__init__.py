"""Config message layer — the contract between the Python config DSL and the
runtime (reference: proto/ModelConfig.proto, TrainerConfig.proto,
ParameterConfig.proto, DataConfig.proto; SURVEY §2.4).

The reference compiles Python configs to protobuf and hands the bytes to C++
(`parse_config_and_serialize`, config_parser.py:4208). Here the runtime is
jax, so the wire format does not need protoc: these are plain dataclass
messages with a protobuf-text-format serializer (`to_text`) and a dict form
(`to_dict`) used by dump_config / merge_model / the C-API loader. Field names
match the reference protos so dumped configs read like the originals.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


# ---------------------------------------------------------------------------
# generic text-format serialization
# ---------------------------------------------------------------------------


def _emit(value: Any, name: str, indent: int, out: List[str]) -> None:
    pad = "  " * indent
    if value is None:
        return
    if dataclasses.is_dataclass(value):
        body: List[str] = []
        for f in dataclasses.fields(value):
            v = getattr(value, f.name)
            if v is None or (isinstance(v, (list, dict)) and not v):
                continue
            _emit(v, f.name, indent + 1, body)
        if body:
            out.append(f"{pad}{name} {{")
            out.extend(body)
            out.append(f"{pad}}}")
        else:
            out.append(f"{pad}{name} {{}}")
    elif isinstance(value, list):
        for item in value:
            _emit(item, name, indent, out)
    elif isinstance(value, dict):
        # free-form extras: emitted as key: value pairs under the field name
        body = [f"{pad}  {k}: {_scalar(v)}" for k, v in sorted(value.items())]
        out.append(f"{pad}{name} {{")
        out.extend(body)
        out.append(f"{pad}}}")
    else:
        out.append(f"{pad}{name}: {_scalar(value)}")


def _scalar(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return json.dumps(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, (tuple, list)):
        return json.dumps(list(v))
    return str(v)


def to_text(msg: Any) -> str:
    """Protobuf-text-format rendering of a message dataclass."""
    out: List[str] = []
    for f in dataclasses.fields(msg):
        v = getattr(msg, f.name)
        if v is None or (isinstance(v, (list, dict)) and not v):
            continue
        _emit(v, f.name, 0, out)
    return "\n".join(out) + "\n"


def to_dict(msg: Any) -> Dict[str, Any]:
    return dataclasses.asdict(msg)


# ---------------------------------------------------------------------------
# ParameterConfig (proto/ParameterConfig.proto:34)
# ---------------------------------------------------------------------------


@dataclass
class ParameterConfig:
    name: str = ""
    size: int = 0
    dims: List[int] = field(default_factory=list)
    learning_rate: float = 1.0
    momentum: Optional[float] = None
    decay_rate: Optional[float] = None      # L2
    decay_rate_l1: Optional[float] = None
    initial_mean: float = 0.0
    initial_std: Optional[float] = None
    is_static: bool = False
    is_sparse: bool = False
    sparse_remote_update: bool = False
    gradient_clipping_threshold: Optional[float] = None
    # TPU-native addition: logical mesh axes for pjit sharding, e.g. ["model", ""]
    sharding: List[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# ModelConfig (proto/ModelConfig.proto:637 and friends)
# ---------------------------------------------------------------------------


@dataclass
class ProjectionConfig:
    type: str = ""
    name: str = ""
    input_size: int = 0
    output_size: int = 0
    context_start: Optional[int] = None
    context_length: Optional[int] = None


@dataclass
class OperatorConfig:
    type: str = ""
    input_indices: List[int] = field(default_factory=list)
    input_sizes: List[int] = field(default_factory=list)
    output_size: int = 0


@dataclass
class LayerInputConfig:
    input_layer_name: str = ""
    input_parameter_name: Optional[str] = None
    proj_conf: Optional[ProjectionConfig] = None


@dataclass
class LayerConfig:
    name: str = ""
    type: str = ""
    size: int = 0
    active_type: Optional[str] = None
    inputs: List[LayerInputConfig] = field(default_factory=list)
    bias_parameter_name: Optional[str] = None
    drop_rate: Optional[float] = None
    shape: List[int] = field(default_factory=list)  # full output shape sans batch
    operator_confs: List[OperatorConfig] = field(default_factory=list)
    # free-form layer-specific attributes (filter_size, stride, ...)
    attrs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class EvaluatorConfig:
    name: str = ""
    type: str = ""
    input_layers: List[str] = field(default_factory=list)
    # ChunkEvaluator (ModelConfig.proto:537-540, :561)
    chunk_scheme: str = ""
    num_chunk_types: int = 0
    excluded_chunk_types: List[int] = field(default_factory=list)
    # PrecisionRecall / ClassificationError (:543-546, :566)
    classification_threshold: float = 0.5
    positive_label: int = -1
    top_k: int = 1
    # printers (:548-557)
    dict_file: str = ""
    result_file: str = ""
    num_results: int = 1
    delimited: bool = True
    # DetectionMAP (:568-574)
    overlap_threshold: float = 0.5
    background_id: int = 0
    evaluate_difficult: bool = False
    ap_type: str = "11point"


@dataclass
class SubModelConfig:
    name: str = ""
    layer_names: List[str] = field(default_factory=list)
    input_layer_names: List[str] = field(default_factory=list)
    output_layer_names: List[str] = field(default_factory=list)
    is_recurrent_layer_group: bool = False


@dataclass
class ModelConfig:
    type: str = "nn"
    layers: List[LayerConfig] = field(default_factory=list)
    parameters: List[ParameterConfig] = field(default_factory=list)
    input_layer_names: List[str] = field(default_factory=list)
    output_layer_names: List[str] = field(default_factory=list)
    evaluators: List[EvaluatorConfig] = field(default_factory=list)
    sub_models: List[SubModelConfig] = field(default_factory=list)


# ---------------------------------------------------------------------------
# OptimizationConfig / TrainerConfig (proto/TrainerConfig.proto:21/:140)
# ---------------------------------------------------------------------------


@dataclass
class OptimizationConfig:
    batch_size: int = 1
    algorithm: str = "sgd"
    learning_method: str = "momentum"
    learning_rate: float = 0.01
    momentum: float = 0.0
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_schedule: str = "constant"
    learning_rate_warmup_steps: int = 0
    l1_weight_decay: float = 0.0
    l2_weight_decay: float = 0.0
    gradient_clipping_threshold: float = 0.0
    average_window: float = 0.0
    max_average_window: int = 0
    ada_epsilon: float = 1e-6
    ada_rou: float = 0.95
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    # extra args threaded through to the optimizer constructor
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class DataConfig:
    type: str = "py2"
    files: Optional[str] = None
    load_data_module: Optional[str] = None
    load_data_object: Optional[str] = None
    load_data_args: str = ""
    async_load_data: bool = False
    # directory of the config script that declared this source: provider
    # modules and file lists resolve relative to it (PyDataProvider2.cpp
    # loads the module from the config's directory)
    config_dir: str = ""


@dataclass
class TrainerConfig:
    model_config: ModelConfig = field(default_factory=ModelConfig)
    opt_config: OptimizationConfig = field(default_factory=OptimizationConfig)
    data_config: Optional[DataConfig] = None
    test_data_config: Optional[DataConfig] = None
    save_dir: str = "./output"


__all__ = [
    "ParameterConfig", "ProjectionConfig", "OperatorConfig", "LayerInputConfig",
    "LayerConfig", "EvaluatorConfig", "SubModelConfig", "ModelConfig",
    "OptimizationConfig", "DataConfig", "TrainerConfig", "to_text", "to_dict",
]
