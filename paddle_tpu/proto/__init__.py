"""Config message layer — the contract between the Python config DSL and the
runtime (reference: proto/ModelConfig.proto, TrainerConfig.proto,
ParameterConfig.proto, DataConfig.proto; SURVEY §2.4).

The reference compiles Python configs to protobuf and hands the bytes to C++
(`parse_config_and_serialize`, config_parser.py:4208). Here the runtime is
jax, so the wire format does not need protoc: these are plain dataclass
messages with a protobuf-text-format serializer (`to_text`) and a dict form
(`to_dict`) used by dump_config / merge_model / the C-API loader. Field names
match the reference protos so dumped configs read like the originals.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


# ---------------------------------------------------------------------------
# generic text-format serialization
# ---------------------------------------------------------------------------


def _emit(value: Any, name: str, indent: int, out: List[str]) -> None:
    pad = "  " * indent
    if value is None:
        return
    if dataclasses.is_dataclass(value):
        body: List[str] = []
        for f in dataclasses.fields(value):
            v = getattr(value, f.name)
            if v is None or (isinstance(v, (list, dict)) and not v):
                continue
            _emit(v, f.name, indent + 1, body)
        if body:
            out.append(f"{pad}{name} {{")
            out.extend(body)
            out.append(f"{pad}}}")
        else:
            out.append(f"{pad}{name} {{}}")
    elif isinstance(value, list):
        for item in value:
            _emit(item, name, indent, out)
    elif isinstance(value, dict):
        # free-form extras: emitted as key: value pairs under the field name
        # (list values unrolled to repeated scalar lines, text-proto style)
        body = []
        for k, v in sorted(value.items()):
            if isinstance(v, (list, tuple)):
                body.extend(f"{pad}  {k}: {_scalar(x)}" for x in v)
            else:
                body.append(f"{pad}  {k}: {_scalar(v)}")
        out.append(f"{pad}{name} {{")
        out.extend(body)
        out.append(f"{pad}}}")
    else:
        out.append(f"{pad}{name}: {_scalar(value)}")


def _scalar(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return json.dumps(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, (tuple, list)):
        return json.dumps(list(v))
    return str(v)


def to_text(msg: Any) -> str:
    """Protobuf-text-format rendering of a message dataclass."""
    out: List[str] = []
    for f in dataclasses.fields(msg):
        v = getattr(msg, f.name)
        if v is None or (isinstance(v, (list, dict)) and not v):
            continue
        _emit(v, f.name, 0, out)
    return "\n".join(out) + "\n"


def to_dict(msg: Any) -> Dict[str, Any]:
    return dataclasses.asdict(msg)


# ---------------------------------------------------------------------------
# ParameterConfig (proto/ParameterConfig.proto:34)
# ---------------------------------------------------------------------------


@dataclass
class ParameterConfig:
    name: str = ""
    size: int = 0
    dims: List[int] = field(default_factory=list)
    learning_rate: float = 1.0
    momentum: Optional[float] = None
    decay_rate: Optional[float] = None      # L2
    decay_rate_l1: Optional[float] = None
    initial_mean: float = 0.0
    initial_std: Optional[float] = None
    is_static: bool = False
    is_sparse: bool = False
    sparse_remote_update: bool = False
    gradient_clipping_threshold: Optional[float] = None
    # TPU-native addition: logical mesh axes for pjit sharding, e.g. ["model", ""]
    sharding: List[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# ModelConfig (proto/ModelConfig.proto:637 and friends)
# ---------------------------------------------------------------------------


@dataclass
class ConvConfig:
    """proto/ModelConfig.proto:38 (x = width, y = height)."""

    filter_size: int = 0
    channels: int = 0
    stride: int = 1
    padding: int = 0
    groups: int = 1
    filter_channels: int = 0
    output_x: int = 0
    img_size: int = 0
    caffe_mode: bool = True
    filter_size_y: int = 0
    padding_y: int = 0
    stride_y: int = 1
    output_y: Optional[int] = None
    img_size_y: Optional[int] = None
    dilation: Optional[int] = None
    dilation_y: Optional[int] = None
    filter_size_z: Optional[int] = None
    padding_z: Optional[int] = None
    stride_z: Optional[int] = None
    output_z: Optional[int] = None
    img_size_z: Optional[int] = None


@dataclass
class PoolConfig:
    """proto/ModelConfig.proto:96."""

    pool_type: str = ""
    channels: int = 0
    size_x: int = 0
    stride: int = 1
    output_x: int = 0
    img_size: int = 0
    padding: int = 0
    size_y: Optional[int] = None
    stride_y: Optional[int] = None
    output_y: Optional[int] = None
    img_size_y: Optional[int] = None
    padding_y: Optional[int] = None
    size_z: Optional[int] = None
    stride_z: Optional[int] = None
    output_z: Optional[int] = None
    img_size_z: Optional[int] = None
    padding_z: Optional[int] = None


@dataclass
class NormConfig:
    """proto/ModelConfig.proto:149."""

    norm_type: str = ""
    channels: int = 0
    size: int = 0
    scale: float = 0.0
    pow: float = 0.0
    output_x: int = 0
    img_size: int = 0
    blocked: bool = False
    output_y: Optional[int] = None
    img_size_y: Optional[int] = None


@dataclass
class ImageConfig:
    """proto/ModelConfig.proto:259."""

    channels: int = 0
    img_size: int = 0
    img_size_y: Optional[int] = None
    img_size_z: Optional[int] = None


@dataclass
class BlockExpandConfig:
    """proto/ModelConfig.proto:184."""

    channels: int = 0
    stride_x: int = 0
    stride_y: int = 0
    padding_x: int = 0
    padding_y: int = 0
    block_x: int = 0
    block_y: int = 0
    output_x: int = 0
    output_y: int = 0
    img_size_x: int = 0
    img_size_y: int = 0


@dataclass
class MaxOutConfig:
    image_conf: Optional[ImageConfig] = None
    groups: int = 0


@dataclass
class SppConfig:
    image_conf: Optional[ImageConfig] = None
    pool_type: str = ""
    pyramid_height: int = 0


@dataclass
class BilinearInterpConfig:
    image_conf: Optional[ImageConfig] = None
    out_size_x: int = 0
    out_size_y: int = 0


@dataclass
class PadConfig:
    image_conf: Optional[ImageConfig] = None
    pad_c: List[int] = field(default_factory=list)
    pad_h: List[int] = field(default_factory=list)
    pad_w: List[int] = field(default_factory=list)


@dataclass
class RowConvConfig:
    context_length: int = 0


@dataclass
class ClipConfig:
    min: float = 0.0
    max: float = 0.0


@dataclass
class PriorBoxConfig:
    min_size: List[int] = field(default_factory=list)
    max_size: List[int] = field(default_factory=list)
    aspect_ratio: List[float] = field(default_factory=list)
    variance: List[float] = field(default_factory=list)


@dataclass
class MultiBoxLossConfig:
    num_classes: int = 0
    overlap_threshold: float = 0.0
    neg_pos_ratio: float = 0.0
    neg_overlap: float = 0.0
    background_id: int = 0
    input_num: int = 0
    height: Optional[int] = None
    width: Optional[int] = None


@dataclass
class DetectionOutputConfig:
    num_classes: int = 0
    nms_threshold: float = 0.0
    nms_top_k: int = 0
    background_id: int = 0
    input_num: int = 0
    keep_top_k: int = 0
    confidence_threshold: float = 0.0
    height: Optional[int] = None
    width: Optional[int] = None


@dataclass
class ReshapeConfig:
    height_axis: List[int] = field(default_factory=list)
    width_axis: List[int] = field(default_factory=list)


@dataclass
class SliceConfig:
    start: int = 0
    end: int = 0


@dataclass
class ProjectionConfig:
    type: str = ""
    name: str = ""
    input_size: int = 0
    output_size: int = 0
    context_start: Optional[int] = None
    context_length: Optional[int] = None
    trainable_padding: Optional[bool] = None
    conv_conf: Optional[ConvConfig] = None
    num_filters: Optional[int] = None
    offset: Optional[int] = None
    pool_conf: Optional[PoolConfig] = None
    slices: List[SliceConfig] = field(default_factory=list)


@dataclass
class OperatorConfig:
    type: str = ""
    input_indices: List[int] = field(default_factory=list)
    input_sizes: List[int] = field(default_factory=list)
    output_size: int = 0
    dotmul_scale: Optional[float] = None
    conv_conf: Optional[ConvConfig] = None
    num_filters: Optional[int] = None


@dataclass
class LayerInputConfig:
    """proto/ModelConfig.proto:319."""

    input_layer_name: str = ""
    input_parameter_name: Optional[str] = None
    conv_conf: Optional[ConvConfig] = None
    pool_conf: Optional[PoolConfig] = None
    norm_conf: Optional[NormConfig] = None
    proj_conf: Optional[ProjectionConfig] = None
    block_expand_conf: Optional[BlockExpandConfig] = None
    image_conf: Optional[ImageConfig] = None
    input_layer_argument: Optional[str] = None
    bilinear_interp_conf: Optional[BilinearInterpConfig] = None
    maxout_conf: Optional[MaxOutConfig] = None
    spp_conf: Optional[SppConfig] = None
    priorbox_conf: Optional[PriorBoxConfig] = None
    pad_conf: Optional[PadConfig] = None
    row_conv_conf: Optional[RowConvConfig] = None
    multibox_loss_conf: Optional[MultiBoxLossConfig] = None
    detection_output_conf: Optional[DetectionOutputConfig] = None
    clip_conf: Optional[ClipConfig] = None


@dataclass
class LayerConfig:
    """proto/ModelConfig.proto:347 — typed field set of the reference's
    LayerConfig (fields this runtime has no use for are still modeled so
    golden protostrs diff structurally; see config/protostr.py)."""

    name: str = ""
    type: str = ""
    size: int = 0
    active_type: str = ""
    inputs: List[LayerInputConfig] = field(default_factory=list)
    bias_parameter_name: Optional[str] = None
    num_filters: Optional[int] = None
    shared_biases: Optional[bool] = None
    partial_sum: Optional[int] = None
    drop_rate: Optional[float] = None
    num_classes: Optional[int] = None
    reversed: Optional[bool] = None
    active_gate_type: Optional[str] = None
    active_state_type: Optional[str] = None
    num_neg_samples: Optional[int] = None
    neg_sampling_dist: List[float] = field(default_factory=list)
    output_max_index: Optional[bool] = None
    softmax_selfnorm_alpha: Optional[float] = None
    directions: List[bool] = field(default_factory=list)
    norm_by_times: Optional[bool] = None
    coeff: Optional[float] = None
    average_strategy: Optional[str] = None
    error_clipping_threshold: Optional[float] = None
    operator_confs: List[OperatorConfig] = field(default_factory=list)
    NDCG_num: Optional[int] = None
    max_sort_size: Optional[int] = None
    slope: Optional[float] = None
    intercept: Optional[float] = None
    cos_scale: Optional[float] = None
    data_norm_strategy: Optional[str] = None
    bos_id: Optional[int] = None
    eos_id: Optional[int] = None
    beam_size: Optional[int] = None
    select_first: Optional[bool] = None
    trans_type: Optional[str] = None
    selective_fc_pass_generation: Optional[bool] = None
    has_selected_colums: Optional[bool] = None
    selective_fc_full_mul_ratio: Optional[float] = None
    use_global_stats: Optional[bool] = None
    moving_average_fraction: Optional[float] = None
    bias_size: Optional[int] = None
    user_arg: Optional[str] = None
    height: Optional[int] = None
    width: Optional[int] = None
    blank: Optional[int] = None
    seq_pool_stride: Optional[int] = None
    axis: Optional[int] = None
    offset: List[int] = field(default_factory=list)
    shape: List[int] = field(default_factory=list)  # crop layer (proto field 56)
    delta: Optional[float] = None
    depth: Optional[int] = None
    reshape_conf: Optional[ReshapeConfig] = None
    # free-form layer-specific attributes with no reference field; kept out
    # of the typed surface so protostr output stays reference-shaped
    attrs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class EvaluatorConfig:
    name: str = ""
    type: str = ""
    input_layers: List[str] = field(default_factory=list)
    # ChunkEvaluator (ModelConfig.proto:537-540, :561)
    chunk_scheme: str = ""
    num_chunk_types: int = 0
    excluded_chunk_types: List[int] = field(default_factory=list)
    # PrecisionRecall / ClassificationError (:543-546, :566)
    classification_threshold: float = 0.5
    positive_label: int = -1
    top_k: int = 1
    # printers (:548-557)
    dict_file: str = ""
    result_file: str = ""
    num_results: int = 1
    delimited: bool = True
    # DetectionMAP (:568-574)
    overlap_threshold: float = 0.5
    background_id: int = 0
    evaluate_difficult: bool = False
    ap_type: str = "11point"


@dataclass
class LinkConfig:
    layer_name: str = ""
    link_name: str = ""


@dataclass
class MemoryConfig:
    link_name: str = ""
    layer_name: str = ""
    boot_layer_name: Optional[str] = None
    boot_bias_parameter_name: Optional[str] = None
    boot_bias_active_type: Optional[str] = None
    boot_with_const_id: Optional[int] = None
    is_sequence: Optional[bool] = None


@dataclass
class SubModelConfig:
    name: str = ""
    layer_names: List[str] = field(default_factory=list)
    input_layer_names: List[str] = field(default_factory=list)
    output_layer_names: List[str] = field(default_factory=list)
    is_recurrent_layer_group: bool = False
    reversed: Optional[bool] = None
    memories: List[MemoryConfig] = field(default_factory=list)
    in_links: List[LinkConfig] = field(default_factory=list)
    out_links: List[LinkConfig] = field(default_factory=list)
    target_inlinkid: Optional[int] = None


@dataclass
class ModelConfig:
    type: str = "nn"
    layers: List[LayerConfig] = field(default_factory=list)
    parameters: List[ParameterConfig] = field(default_factory=list)
    input_layer_names: List[str] = field(default_factory=list)
    output_layer_names: List[str] = field(default_factory=list)
    evaluators: List[EvaluatorConfig] = field(default_factory=list)
    sub_models: List[SubModelConfig] = field(default_factory=list)


# ---------------------------------------------------------------------------
# OptimizationConfig / TrainerConfig (proto/TrainerConfig.proto:21/:140)
# ---------------------------------------------------------------------------


@dataclass
class OptimizationConfig:
    batch_size: int = 1
    algorithm: str = "sgd"
    learning_method: str = "momentum"
    learning_rate: float = 0.01
    momentum: float = 0.0
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_schedule: str = "constant"
    learning_rate_warmup_steps: int = 0
    l1_weight_decay: float = 0.0
    l2_weight_decay: float = 0.0
    gradient_clipping_threshold: float = 0.0
    average_window: float = 0.0
    max_average_window: int = 0
    ada_epsilon: float = 1e-6
    ada_rou: float = 0.95
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    # extra args threaded through to the optimizer constructor
    extra: Dict[str, Any] = field(default_factory=dict)


@dataclass
class DataConfig:
    type: str = "py2"
    files: Optional[str] = None
    load_data_module: Optional[str] = None
    load_data_object: Optional[str] = None
    load_data_args: str = ""
    async_load_data: bool = False
    # directory of the config script that declared this source: provider
    # modules and file lists resolve relative to it (PyDataProvider2.cpp
    # loads the module from the config's directory)
    config_dir: str = ""


@dataclass
class TrainerConfig:
    model_config: ModelConfig = field(default_factory=ModelConfig)
    opt_config: OptimizationConfig = field(default_factory=OptimizationConfig)
    data_config: Optional[DataConfig] = None
    test_data_config: Optional[DataConfig] = None
    save_dir: str = "./output"


__all__ = [
    "ParameterConfig", "ProjectionConfig", "OperatorConfig", "LayerInputConfig",
    "LayerConfig", "EvaluatorConfig", "SubModelConfig", "ModelConfig",
    "OptimizationConfig", "DataConfig", "TrainerConfig", "to_text", "to_dict",
    "ConvConfig", "PoolConfig", "NormConfig", "ImageConfig",
    "BlockExpandConfig", "MaxOutConfig", "SppConfig", "BilinearInterpConfig",
    "PadConfig", "RowConvConfig", "ClipConfig", "PriorBoxConfig",
    "MultiBoxLossConfig", "DetectionOutputConfig", "ReshapeConfig",
    "SliceConfig", "LinkConfig", "MemoryConfig",
]
