"""swig_paddle-shaped compatibility surface (paddle/api/PaddleAPI.h parity,
SURVEY §2.1 `paddle/api` + py_paddle).

The reference exposes trainer internals to Python through SWIG classes
(`GradientMachine` :720, `Arguments` :402, `SequenceGenerator` :1025). Here
those internals ARE Python; this module provides the same class shapes for
scripts/tools written against py_paddle. Heavy lifting delegates to the
layer-graph Network and the compiled-step machinery."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.nn.graph import Argument, Layer, Network


class Arguments:
    """Batch container (PaddleAPI.h:402): per-slot value/ids + sequence start
    positions. Internally a dict batch; seq start positions convert to the
    padded+lengths encoding."""

    def __init__(self, batch: Optional[Dict[str, Any]] = None):
        self._batch: Dict[str, Any] = dict(batch or {})

    @classmethod
    def createArguments(cls, _size: int = 0) -> "Arguments":
        return cls()

    def setSlotValue(self, name: str, value: np.ndarray) -> None:
        self._batch[name] = np.asarray(value)

    def setSlotIds(self, name: str, ids: np.ndarray) -> None:
        self._batch[name] = np.asarray(ids, np.int32)

    def setSlotSequenceStartPositions(self, name: str, starts: Sequence[int]) -> None:
        """v1 ragged encoding: starts [0, l0, l0+l1, ...] → pad + lengths."""
        starts = list(starts)
        lengths = np.diff(starts).astype(np.int32)
        flat = self._batch.get(name)
        if flat is None:
            raise ValueError(f"set slot {name!r} value/ids before start positions")
        flat = np.asarray(flat)
        max_len = int(lengths.max()) if len(lengths) else 1
        out = np.zeros((len(lengths), max_len) + flat.shape[1:], flat.dtype)
        for i, (s, l) in enumerate(zip(starts[:-1], lengths)):
            out[i, :l] = flat[s : s + l]
        self._batch[name] = out
        self._batch[name + ".lengths"] = lengths

    def getSlotValue(self, name: str) -> np.ndarray:
        return np.asarray(self._batch[name])

    def as_batch(self) -> Dict[str, Any]:
        return dict(self._batch)


class Evaluator:
    """makeEvaluator() result: start/finish + printStats over the streaming
    metrics package."""

    def __init__(self, machine: "GradientMachine"):
        self.machine = machine
        self._metrics: Dict[str, float] = {}

    def start(self) -> None:
        self._metrics.clear()

    def finish(self) -> None:
        pass

    def printStats(self) -> str:
        return " ".join(f"{k}={v}" for k, v in self._metrics.items())


class GradientMachine:
    """PaddleAPI.h:720: forward / backward / forwardBackward over a topology.

    `backward` returns parameter gradients (the reference mutates grad
    buffers; functionally that's the return value)."""

    def __init__(self, outputs: Sequence[Layer], seed: int = 0):
        self.network = Network(list(outputs))
        self.seed = seed
        self.params: Dict[str, jax.Array] = {}
        self.states: Dict[str, jax.Array] = {}
        self._fwd = jax.jit(
            lambda p, s, b: self.network.apply(p, s, b, train=False)[0]
        )

    # -- creation (createFromConfigProto parity: from a parsed config) ------
    @classmethod
    def createFromConfigProto(cls, parsed_config) -> "GradientMachine":
        """Accepts paddle_tpu.config.ParsedConfig (the proto's owner)."""
        return cls(parsed_config.outputs)

    def initParams(self, batch: Dict[str, Any]) -> None:
        self.params, self.states = self.network.init(
            jax.random.PRNGKey(self.seed), batch
        )

    # -- execution -----------------------------------------------------------
    def forward(self, in_args: Any, _out_args: Any = None, _pass_type: Any = None):
        batch = in_args.as_batch() if isinstance(in_args, Arguments) else in_args
        if not self.params:
            self.initParams(batch)
        outs = self._fwd(self.params, self.states, batch)
        return {k: np.asarray(v.value) for k, v in outs.items()}

    def forwardBackward(self, in_args: Any, _out=None, _pt=None):
        batch = in_args.as_batch() if isinstance(in_args, Arguments) else in_args
        if not self.params:
            self.initParams(batch)
        cost_name = self.network.outputs[0].name

        def loss(p):
            outs, _ = self.network.apply(p, self.states, batch, train=True,
                                         rng=jax.random.PRNGKey(self.seed))
            return outs[cost_name].value

        cost, grads = jax.value_and_grad(loss)(self.params)
        return float(cost), {k: np.asarray(v) for k, v in grads.items()}

    backward = forwardBackward  # the reference splits them; semantics match

    def getLayerOutput(self, name: str, in_args: Any) -> np.ndarray:
        batch = in_args.as_batch() if isinstance(in_args, Arguments) else in_args
        if not self.params:
            self.initParams(batch)
        sub = Network([self.network.layers_by_name[name]])
        outs, _ = sub.apply(self.params, self.states, batch, train=False)
        return np.asarray(outs[name].value)

    def getParameters(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}

    def setParameters(self, params: Dict[str, np.ndarray]) -> None:
        self.params = {k: jnp.asarray(v) for k, v in params.items()}

    def makeEvaluator(self) -> Evaluator:
        return Evaluator(self)


class SequenceGenerator:
    """PaddleAPI.h:1025: beam-search text generation over a graph containing a
    beam_search layer (nn/recurrent_group.BeamSearchLayer)."""

    def __init__(self, machine: GradientMachine, beam_layer: Layer,
                 dict_file: Optional[Sequence[str]] = None):
        self.machine = machine
        self.beam_layer = beam_layer
        self.vocab = list(dict_file) if dict_file else None

    def generate(self, in_args: Any) -> List[List[int]]:
        batch = in_args.as_batch() if isinstance(in_args, Arguments) else in_args
        if not self.machine.params:
            self.machine.initParams(batch)
        outs, _ = self.machine.network.apply(
            self.machine.params, self.machine.states, batch, train=False
        )
        arg: Argument = outs[self.beam_layer.name]
        ids = np.asarray(arg.value)
        lens = np.asarray(arg.lengths)
        return [list(map(int, ids[i, : lens[i]])) for i in range(len(ids))]

    def generateText(self, in_args: Any) -> List[str]:
        assert self.vocab is not None, "pass dict_file to decode text"
        return [
            " ".join(self.vocab[t] for t in seq if t < len(self.vocab))
            for seq in self.generate(in_args)
        ]
