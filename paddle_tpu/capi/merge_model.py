"""merge_model: fold config + trained parameters into ONE deployable file.

Parity: paddle/trainer/MergeModel.cpp + python/paddle/utils/merge_model.py
(SURVEY §5 "Model export"). Artifact layout (single .npz):

    __config_source__  : the config script text (re-executed at load)
    __config_args__    : config_args string
    __trainer_config__ : serialized TrainerConfig text (for inspection)
    param/<name>       : parameter arrays
    state/<name>       : non-trainable states (batch-norm moving stats)
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np


def merge_model(
    config_path: str,
    model_dir: str,
    output_path: str,
    config_args: str = "",
    pass_id: Optional[int] = None,
) -> str:
    from paddle_tpu import proto
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import checkpoint as ckpt

    with open(config_path) as f:
        source = f.read()
    pc = parse_config(config_path, config_args)

    if os.path.isdir(os.path.join(model_dir, "pass-00000")) or any(
        d.startswith("pass-") for d in os.listdir(model_dir)
    ):
        params, states, _opt, _manifest = ckpt.load_pass(model_dir, pass_id)
    else:
        # a bare pass dir (save_dir/pass-00042 passed directly)
        parent, leaf = os.path.split(model_dir.rstrip("/"))
        params, states, _opt, _manifest = ckpt.load_pass(
            parent, int(leaf.split("-")[1])
        )

    payload: Dict[str, np.ndarray] = {
        "__config_source__": np.asarray(source),
        "__config_args__": np.asarray(config_args),
        "__trainer_config__": np.asarray(proto.to_text(pc.trainer_config)),
    }
    for k, v in params.items():
        payload[f"param/{k}"] = np.asarray(v)
    for k, v in (states or {}).items():
        payload[f"state/{k}"] = np.asarray(v)

    tmp = output_path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, output_path)
    return output_path


def merge_model_v1(
    config_path: str,
    model_dir: str,
    output_path: str,
    config_args: str = "",
    pass_id: Optional[int] = None,
) -> str:
    """Reference-format merged model (MergeModel.cpp byte layout): int64
    config length + serialized TrainerConfig + every parameter written with
    its `Parameter::Header` in **config declaration order** — the stream has
    no per-parameter names, so a reference consumer binds bytes to parameters
    positionally (MergeModel.cpp iterates para_names() in config order).
    Caveat: the config header here is our protobuf-*text* rendering, so the
    reference binary cannot parse the header itself; the framing and the
    parameter byte layout are format-identical."""
    from paddle_tpu import proto
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import checkpoint as ckpt
    from paddle_tpu.trainer import v1_format

    pc = parse_config(config_path, config_args)
    if any(d.startswith("pass-") for d in os.listdir(model_dir)):
        params, _states, _opt, _m = ckpt.load_pass(model_dir, pass_id)
    else:
        parent, leaf = os.path.split(model_dir.rstrip("/"))
        params, _states, _opt, _m = ckpt.load_pass(parent, int(leaf.split("-")[1]))

    config_bytes = proto.to_text(pc.trainer_config).encode()
    # positional binding: emit in the config's parameter declaration order,
    # then any params unknown to the config (sorted, for determinism). A
    # declared parameter missing from the checkpoint would silently shift
    # every later binding — fail at merge time instead.
    declared = [p.name for p in pc.trainer_config.model_config.parameters]
    missing = [n for n in declared if n not in params]
    if missing:
        raise ValueError(
            f"merge_model_v1: config declares parameters {missing} that are "
            "not in the checkpoint — positional binding would corrupt every "
            "parameter after the first missing one"
        )
    order = declared + sorted(set(params) - set(declared))
    tmp = output_path + ".tmp"
    with open(tmp, "wb") as f:
        v1_format.write_merged(f, config_bytes, params, order=order)
    os.replace(tmp, output_path)
    return output_path
