"""InferenceMachine: serve a merged model (capi/gradient_machine.h parity).

`create_for_inference(path)` ≈ paddle_gradient_machine_create_for_inference_
with_parameters (capi/gradient_machine.h:52); `forward` ≈ :73. The reference's
shared-param thread clones (:88) are unnecessary: compiled XLA executables are
reentrant and parameters live in immutable device buffers — one machine serves
any number of threads.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Any, Dict, List, Optional, Union

import numpy as np


class InferenceMachine:
    def __init__(self, topology, params, states, feeder):
        import jax

        self.topology = topology
        self.network = topology.network
        self.params = {k: jax.numpy.asarray(v) for k, v in params.items()}
        self.states = {k: jax.numpy.asarray(v) for k, v in states.items()}
        self.feeder = feeder
        self._apply = jax.jit(
            lambda p, s, b: self.network.apply(p, s, b, train=False)[0]
        )
        # "one machine serves any number of threads" (module docstring) —
        # the compiled executables are reentrant, but the lazily-populated
        # per-layer compile cache below is plain dict mutation and needs this
        self._layer_apply: Dict[str, Any] = {}
        self._layer_lock = threading.Lock()

    @classmethod
    def from_merged(cls, path: str) -> "InferenceMachine":
        from paddle_tpu.config import parse_config

        with np.load(path, allow_pickle=False) as z:
            source = str(z["__config_source__"])
            config_args = str(z["__config_args__"])
            params = {
                k[len("param/"):]: z[k] for k in z.files if k.startswith("param/")
            }
            states = {
                k[len("state/"):]: z[k] for k in z.files if k.startswith("state/")
            }
        with tempfile.NamedTemporaryFile("w", suffix="_conf.py", delete=False) as f:
            f.write(source)
            cfg_path = f.name
        try:
            pc = parse_config(cfg_path, config_args, emit_proto=False)
        finally:
            os.unlink(cfg_path)
        return cls(pc.topology, params, states, pc.topology.make_feeder())

    # -- forward (capi/gradient_machine.h:73) -------------------------------
    def forward(
        self, batch: Any, output_layer: Optional[str] = None
    ) -> Union[Dict[str, np.ndarray], np.ndarray]:
        """batch: dict of arrays, or list of sample tuples (fed through the
        config's data layers in declaration order). Returns the bare array
        when `output_layer` is given, else {name: array} for all outputs."""
        if not isinstance(batch, dict):
            batch = self.feeder(batch)
        outs = self._apply(self.params, self.states, batch)
        if output_layer is not None:
            return np.asarray(outs[output_layer].value)
        return {name: np.asarray(a.value) for name, a in outs.items()}

    def output_names(self) -> List[str]:
        return [l.name for l in self.network.outputs]

    # -- arbitrary layer outputs (GradientMachine::getLayerOutput parity) ----
    def get_layer_output(self, layer_name: str, batch: Any) -> np.ndarray:
        """Forward and return any named layer's output (the reference exposes
        this via paddle_gradient_machine_get_layer_output,
        capi/gradient_machine.h:112). Compiles one extra executable per
        distinct layer, cached."""
        import jax

        from paddle_tpu.nn.graph import Network

        with self._layer_lock:
            # double-checked under the lock: concurrent first calls for the
            # same layer must not race the dict insert (the jit itself is
            # cheap here — tracing happens at first call, which is reentrant)
            if layer_name not in self._layer_apply:
                layer = self.topology.network.layers_by_name[layer_name]
                sub = Network([layer])
                self._layer_apply[layer_name] = jax.jit(
                    lambda p, s, b: sub.apply(p, s, b, train=False)[0][
                        layer_name
                    ].value
                )
        if not isinstance(batch, dict):
            batch = self.feeder(batch)
        return np.asarray(self._layer_apply[layer_name](self.params, self.states, batch))


def create_for_inference(merged_path: str) -> InferenceMachine:
    return InferenceMachine.from_merged(merged_path)
