"""Inference/deployment surface — paddle/capi parity (SURVEY §3.5).

Reference: paddle_gradient_machine_create_for_inference_with_parameters
(capi/gradient_machine.h:52) consumes a merged file (ModelConfig proto +
parameter blobs, produced by MergeModel.cpp). Here the merged artifact packs
the config script + serialized TrainerConfig + parameter/state arrays into one
.npz; InferenceMachine rebuilds the graph by re-running the config (the
reference likewise re-enters Python to parse configs) and serves compiled
forward passes.
"""

from paddle_tpu.capi.merge_model import merge_model
from paddle_tpu.capi.inference import InferenceMachine, create_for_inference

__all__ = ["merge_model", "InferenceMachine", "create_for_inference"]
