"""Training events, parity with python/paddle/v2/event.py:45-88.

EndIteration carries its cost/metrics LAZILY: the trainer hands it the raw
device values, and conversion to Python floats happens only when a handler
actually reads `.cost` / `.metrics`. Handlers that merely count batches (or
read the cost every N batches) therefore no longer force a device sync per
batch — the async dispatch pipeline keeps running (the reference's hot loop
never blocks on the cost either; TrainerInternal.cpp only sums it for the
log-period line)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class BeginPass:
    pass_id: int


@dataclasses.dataclass
class EndPass:
    pass_id: int
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class BeginIteration:
    pass_id: int
    batch_id: int


class EndIteration:
    """End-of-dispatch event (one per device dispatch: a single batch, or the
    whole K-batch window under train(steps_per_dispatch=K), with batch_id the
    window's LAST batch and cost its final step's cost).

    `cost` and `metrics` are fetched from the device on first access (and
    cached), so installing a handler is free unless the handler reads the
    values. Reading `.cost` is NOT free: it blocks the host until the step
    that produced it has actually executed — one read per batch re-creates
    exactly the per-step pipeline stall the lazy event exists to avoid. Read
    it sparingly (every N dispatches, or only at EndPass), and prefer the
    pass-level `EndPass.metrics["avg_cost"]`, which costs one sync per pass.

    With a divergence policy and guard_check_every > 1, a poisoned batch's
    event IS delivered (the host only learns of the divergence at the next
    guard poll) and its `.cost` reads NaN/Inf — handlers aggregating `.cost`
    should guard with isfinite, or rely on `avg_cost`, which the on-device
    accumulator already masks. At guard_check_every=1 (and unfused dispatch)
    poisoned batches are suppressed from the event stream, as before."""

    __slots__ = ("pass_id", "batch_id", "_cost", "_metrics", "_metrics_np")

    def __init__(
        self,
        pass_id: int,
        batch_id: int,
        cost: Any,
        metrics: Optional[Dict[str, Any]] = None,
    ):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self._cost = cost
        self._metrics = metrics or {}
        self._metrics_np: Optional[Dict[str, Any]] = None

    @property
    def cost(self) -> float:
        if not isinstance(self._cost, float):
            self._cost = float(self._cost)
        return self._cost

    @property
    def metrics(self) -> Dict[str, Any]:
        if self._metrics_np is None:
            import numpy as np

            self._metrics_np = {
                k: np.asarray(v) for k, v in self._metrics.items()
            }
        return self._metrics_np

    def __repr__(self) -> str:  # avoid syncing in repr-driven debugging
        return (
            f"EndIteration(pass_id={self.pass_id}, batch_id={self.batch_id}, "
            f"cost=<lazy>)"
        )
