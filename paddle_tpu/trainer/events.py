"""Training events, parity with python/paddle/v2/event.py:45-88."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class BeginPass:
    pass_id: int


@dataclasses.dataclass
class EndPass:
    pass_id: int
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class BeginIteration:
    pass_id: int
    batch_id: int


@dataclasses.dataclass
class EndIteration:
    pass_id: int
    batch_id: int
    cost: float
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)
