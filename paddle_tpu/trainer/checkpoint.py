"""Checkpoint save/load.

Parity with the reference's per-pass parameter dumps
(trainer/ParamUtil.cpp:80 saveParameters → save_dir/pass-%05d/) and the Go
pserver checkpoints that additionally persist optimizer state with integrity
checks (go/pserver/service.go:146 parameterCheckpoint, CRC + atomic write).

Format: one .npz per pytree (params / states / opt) + manifest.json with
shapes, dtypes and a CRC of each file; writes are atomic (tmp + rename)."""

from __future__ import annotations

import json
import os
import tempfile
import zlib
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


def _path_key(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def _to_numpy_tree(tree: Any) -> Dict[str, np.ndarray]:
    flat = {}
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves:
        flat[_path_key(path)] = np.asarray(leaf)
    return flat


def restore_tree(template: Any, flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild a pytree shaped like `template` from a flat path→array dict
    (inverse of _to_numpy_tree). Leaves missing from `flat` or with mismatched
    shapes keep the template's value."""
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves:
        key = _path_key(path)
        if key in flat and tuple(np.shape(flat[key])) == tuple(np.shape(leaf)):
            new_leaves.append(jnp.asarray(flat[key], dtype=leaf.dtype))
        else:
            new_leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _save_npz_atomic(path: str, arrays: Dict[str, np.ndarray]) -> int:
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    # suffix must be .npz: np.savez appends it to any other filename
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
    os.close(fd)
    try:
        np.savez(tmp, **arrays)
        with open(tmp, "rb") as f:
            crc = zlib.crc32(f.read())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return crc


def save_pass(
    save_dir: str,
    pass_id: int,
    params: Dict[str, Any],
    states: Optional[Dict[str, Any]] = None,
    opt_state: Optional[Any] = None,
    extra_meta: Optional[Dict[str, Any]] = None,
    v1_binary: bool = True,
) -> str:
    """Write save_dir/pass-%05d/{params,states,opt}.npz + manifest.json.

    v1_binary (default on) additionally writes each parameter as a
    reference-format `Parameter::save` file in the pass dir (ParamUtil layout
    — SURVEY §7 step 8 model interchange; see trainer/v1_format.py), so every
    pass dir doubles as a reference-consumable model dir."""
    pdir = os.path.join(save_dir, f"pass-{pass_id:05d}")
    os.makedirs(pdir, exist_ok=True)
    if v1_binary:
        from paddle_tpu.trainer import v1_format

        v1_format.save_model_dir(pdir, _to_numpy_tree(params))
    manifest: Dict[str, Any] = {"pass_id": pass_id, "files": {}, "version": 1}
    if extra_meta:
        manifest["extra"] = extra_meta
    for name, tree in [("params", params), ("states", states), ("opt", opt_state)]:
        if tree is None or (isinstance(tree, dict) and not tree):
            continue
        flat = _to_numpy_tree(tree)
        path = os.path.join(pdir, f"{name}.npz")
        crc = _save_npz_atomic(path, flat)
        manifest["files"][name] = {
            "crc32": crc,
            "keys": {k: [list(v.shape), str(v.dtype)] for k, v in flat.items()},
        }
    mpath = os.path.join(pdir, "manifest.json")
    fd, tmp = tempfile.mkstemp(dir=pdir, suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, mpath)
    return pdir


def is_v1_model_dir(dirname: str) -> bool:
    """True when `dirname` looks like a reference ParamUtil model directory:
    no manifest.json, and at least one regular file whose 16 leading bytes
    parse as a `Parameter::Header` (Parameter.h:263) consistent with the
    file's length (16 + 4*size bytes)."""
    from paddle_tpu.trainer import v1_format

    if not os.path.isdir(dirname) or os.path.exists(
        os.path.join(dirname, "manifest.json")
    ):
        return False
    for fn in os.listdir(dirname):
        path = os.path.join(dirname, fn)
        if not os.path.isfile(path):
            continue
        try:
            with open(path, "rb") as f:
                raw = f.read(v1_format.HEADER.size)
            if len(raw) != v1_format.HEADER.size:
                continue
            fmt, value_size, size = v1_format.HEADER.unpack(raw)
            if (
                fmt == v1_format.PARAM_FORMAT_ORIGINAL
                and value_size == 4
                and os.path.getsize(path) == v1_format.HEADER.size + 4 * size
            ):
                return True
        except OSError:
            continue
    return False


def load_pass(
    save_dir: str,
    pass_id: Optional[int] = None,
    params_template: Optional[Dict[str, Any]] = None,
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], Dict[str, np.ndarray], Dict]:
    """Load (params, states, opt_flat, manifest). pass_id=None → latest.

    Accepts three on-disk layouts, sniffed in order:
    - save_dir/pass-%05d/ with manifest.json (this repo's native format);
    - save_dir itself is a pass dir (manifest.json directly inside);
    - save_dir (or save_dir/pass-%05d) is a reference ParamUtil model
      directory of raw `Parameter::save` files (paddle/trainer/ParamUtil.cpp:50
      loadParameters) — needs `params_template` for shapes; conv filters are
      transposed from the reference flat [cin,kh,kw,cout] layout to HWIO by
      v1_format.read_param. Optimizer state/states are absent in that case
      (the reference checkpoints values only)."""
    v1_sniffed = False
    if os.path.exists(os.path.join(save_dir, "manifest.json")):
        pdir = save_dir
    elif pass_id is None and is_v1_model_dir(save_dir):
        pdir = save_dir
        v1_sniffed = True
    else:
        if pass_id is None:
            passes = sorted(
                int(d.split("-")[1])
                for d in os.listdir(save_dir)
                if d.startswith("pass-") and os.path.isdir(os.path.join(save_dir, d))
            )
            if not passes:
                raise FileNotFoundError(f"no pass-* checkpoints under {save_dir}")
            pass_id = passes[-1]
        pdir = os.path.join(save_dir, f"pass-{pass_id:05d}")
    if not os.path.exists(os.path.join(pdir, "manifest.json")) and (
        v1_sniffed or is_v1_model_dir(pdir)
    ):
        if params_template is None:
            raise ValueError(
                f"{pdir!r} is a reference-format (v1 binary) model dir; loading "
                "it needs a params_template for shapes — init the trainer state "
                "first (Trainer.load does this automatically)"
            )
        from paddle_tpu.trainer import v1_format

        params = v1_format.load_model_dir(pdir, _to_numpy_tree(params_template))
        return params, {}, {}, {"pass_id": pass_id, "v1_binary": True, "files": {}}
    with open(os.path.join(pdir, "manifest.json")) as f:
        manifest = json.load(f)
    out = {}
    for name in ("params", "states", "opt"):
        path = os.path.join(pdir, f"{name}.npz")
        if name in manifest["files"] and os.path.exists(path):
            with open(path, "rb") as f:
                crc = zlib.crc32(f.read())
            if crc != manifest["files"][name]["crc32"]:
                raise IOError(f"checkpoint {path} failed CRC check")
            with np.load(path) as z:
                out[name] = {k: z[k] for k in z.files}
        else:
            out[name] = {}
    return out["params"], out["states"], out["opt"], manifest
