"""Checkpoint save/load.

Parity with the reference's per-pass parameter dumps
(trainer/ParamUtil.cpp:80 saveParameters → save_dir/pass-%05d/) and the Go
pserver checkpoints that additionally persist optimizer state with integrity
checks (go/pserver/service.go:146 parameterCheckpoint, CRC + atomic write).

Format: one .npz per pytree (params / states / opt) + manifest.json with
shapes, dtypes and a CRC of each file; writes are atomic (tmp + rename).

Async mode (the zero-stall checkpoint path): `AsyncCheckpointer` owns ONE
background writer thread; `save_pass_async` flattens the (already
host-resident) trees on the caller's thread and hands the npz/CRC/v1-format/
manifest/retention work to the writer, double-buffered so at most one
snapshot is in flight. `wait()` is the durability barrier — the trainer
invokes it on train() exit, before load(), and in the preemption drain, so a
checkpoint path handed to a supervisor always names a completed write."""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import numpy as np

from paddle_tpu.core import faults, stats

log = logging.getLogger("paddle_tpu.checkpoint")

LATEST_FILE = "latest"


def _path_key(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def _to_numpy_tree(tree: Any) -> Dict[str, np.ndarray]:
    flat = {}
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves:
        flat[_path_key(path)] = np.asarray(leaf)
    return flat


def tree_shape_mismatches(
    template: Any, flat: Dict[str, np.ndarray]
) -> List[Tuple[str, Tuple[int, ...], Tuple[int, ...]]]:
    """(key, expected_shape, found_shape) for every `flat` entry whose shape
    disagrees with the matching `template` leaf. restore_tree silently keeps
    the template value for those — callers that must NOT lose state (the
    trainer's optimizer resume) turn a non-empty result into a hard error
    naming expected vs found shard counts instead of resuming with silently
    re-initialized slots."""
    out: List[Tuple[str, Tuple[int, ...], Tuple[int, ...]]] = []
    leaves, _ = jax.tree_util.tree_flatten_with_path(template)
    for path, leaf in leaves:
        key = _path_key(path)
        if key in flat and tuple(np.shape(flat[key])) != tuple(np.shape(leaf)):
            out.append((key, tuple(np.shape(leaf)), tuple(np.shape(flat[key]))))
    return out


def tree_missing_keys(template: Any, flat: Dict[str, np.ndarray]) -> List[str]:
    """Template leaf paths with NO entry in `flat` at all. restore_tree
    keeps the template's (freshly initialized) value for those — for state
    that must round-trip exactly (the trainer's optimizer slots), a missing
    key is the same silent wrong resume as a shape mismatch, just invisible
    to tree_shape_mismatches (which only compares keys present in both)."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(template)
    return [
        key for path, _leaf in leaves
        if (key := _path_key(path)) not in flat
    ]


def restore_tree(template: Any, flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild a pytree shaped like `template` from a flat path→array dict
    (inverse of _to_numpy_tree). Leaves missing from `flat` or with mismatched
    shapes keep the template's value."""
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves:
        key = _path_key(path)
        if key in flat and tuple(np.shape(flat[key])) == tuple(np.shape(leaf)):
            new_leaves.append(jnp.asarray(flat[key], dtype=leaf.dtype))
        else:
            new_leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _save_npz_atomic(path: str, arrays: Dict[str, np.ndarray]) -> int:
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    # suffix must be .npz: np.savez appends it to any other filename
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
    os.close(fd)
    try:
        np.savez(tmp, **arrays)
        with open(tmp, "rb") as f:
            crc = zlib.crc32(f.read())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return crc


def save_pass(
    save_dir: str,
    pass_id: int,
    params: Dict[str, Any],
    states: Optional[Dict[str, Any]] = None,
    opt_state: Optional[Any] = None,
    extra_meta: Optional[Dict[str, Any]] = None,
    v1_binary: bool = True,
    keep_last_n: Optional[int] = None,
) -> str:
    """Write save_dir/pass-%05d/{params,states,opt}.npz + manifest.json, then
    point save_dir/latest at it (tmp+rename, so the pointer is never torn).

    v1_binary (default on) additionally writes each parameter as a
    reference-format `Parameter::save` file in the pass dir (ParamUtil layout
    — SURVEY §7 step 8 model interchange; see trainer/v1_format.py), so every
    pass dir doubles as a reference-consumable model dir.

    keep_last_n (None/0 = keep all): after a successful write, delete the
    oldest pass dirs beyond the newest N — never the one just written. The
    dir is renamed aside first, so a reader never sees a half-deleted pass."""
    flats = _flatten_pass_trees(params, states, opt_state)
    return _write_pass_files(
        save_dir, pass_id, flats, extra_meta, v1_binary, keep_last_n
    )


def _flatten_pass_trees(
    params: Dict[str, Any],
    states: Optional[Dict[str, Any]],
    opt_state: Optional[Any],
) -> Dict[str, Dict[str, np.ndarray]]:
    """Flatten the three checkpoint trees to {name: {path: ndarray}} — the
    only step of a save that must see the caller's (possibly device) arrays;
    everything after it is pure file I/O."""
    flats: Dict[str, Dict[str, np.ndarray]] = {}
    for name, tree in [("params", params), ("states", states), ("opt", opt_state)]:
        if tree is None or (isinstance(tree, dict) and not tree):
            continue
        flats[name] = _to_numpy_tree(tree)
    return flats


def _write_pass_files(
    save_dir: str,
    pass_id: int,
    flats: Dict[str, Dict[str, np.ndarray]],
    extra_meta: Optional[Dict[str, Any]],
    v1_binary: bool,
    keep_last_n: Optional[int],
) -> str:
    """The file-I/O body of save_pass, runnable on an AsyncCheckpointer
    writer thread: npz + CRC + v1-format + manifest + latest pointer +
    retention. Input arrays must already be host numpy."""
    if keep_last_n is not None and keep_last_n < 0:
        raise ValueError(f"keep_last_n must be >= 0, got {keep_last_n}")
    pdir = os.path.join(save_dir, f"pass-{pass_id:05d}")
    os.makedirs(pdir, exist_ok=True)
    if v1_binary and "params" in flats:
        from paddle_tpu.trainer import v1_format

        v1_format.save_model_dir(pdir, flats["params"])
    manifest: Dict[str, Any] = {"pass_id": pass_id, "files": {}, "version": 1}
    if extra_meta:
        manifest["extra"] = extra_meta
    for name, flat in flats.items():
        path = os.path.join(pdir, f"{name}.npz")
        crc = _save_npz_atomic(path, flat)
        if faults.get().fire("ckpt_truncate"):
            # chaos hook: a torn write that defeated tmp+rename (lying fs,
            # power cut after rename) — CRC verification must catch it
            with open(path, "r+b") as f:
                f.truncate(max(os.path.getsize(path) // 2, 1))
        manifest["files"][name] = {
            "crc32": crc,
            "keys": {k: [list(v.shape), str(v.dtype)] for k, v in flat.items()},
        }
    mpath = os.path.join(pdir, "manifest.json")
    fd, tmp = tempfile.mkstemp(dir=pdir, suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, mpath)
    _write_latest(save_dir, pass_id)
    if keep_last_n:
        _prune_old_passes(save_dir, keep=keep_last_n, just_written=pdir)
    return pdir


class AsyncCheckpointer:
    """Single background writer for zero-stall checkpointing.

    Double-buffered: at most one snapshot is in flight; submitting a second
    blocks (before any new work starts) until the first lands. A writer
    failure is remembered and re-raised on the NEXT submit()/wait() so disk
    errors surface on the training thread instead of dying silently with a
    daemon thread. The thread is a daemon on purpose: a kill mid-write is
    exactly the torn-write case the manifest CRCs exist to catch."""

    def __init__(self, name: str = "paddle-tpu-ckpt-writer"):
        self._cond = threading.Condition()
        self._job: Optional[Tuple[Callable[[], Any], str]] = None
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._name = name

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=self._name
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._job is None:
                    self._cond.wait()
                fn, desc = self._job
            err: Optional[BaseException] = None
            try:
                with stats.timer("ckptWrite"):
                    fn()
            except BaseException as e:  # surfaces at the next submit()/wait()
                err = e
                log.error("async checkpoint write (%s) failed: %s", desc, e)
            with self._cond:
                if err is not None:
                    self._error = err
                self._job = None
                self._cond.notify_all()

    @property
    def in_flight(self) -> bool:
        with self._cond:
            return self._job is not None

    def submit(self, fn: Callable[[], Any], desc: str = "checkpoint") -> None:
        """Queue one write job; blocks while a previous one is in flight."""
        self._ensure_thread()
        with self._cond:
            while self._job is not None:
                self._cond.wait()
            self._raise_pending_locked()
            self._job = (fn, desc)
            self._cond.notify_all()

    def wait(self) -> None:
        """Durability barrier: returns once no write is in flight, re-raising
        the writer's exception (once) if the last write failed."""
        with self._cond:
            while self._job is not None:
                self._cond.wait()
            self._raise_pending_locked()

    def _raise_pending_locked(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def save_pass_async(
    writer: AsyncCheckpointer,
    save_dir: str,
    pass_id: int,
    params: Dict[str, Any],
    states: Optional[Dict[str, Any]] = None,
    opt_state: Optional[Any] = None,
    extra_meta: Optional[Dict[str, Any]] = None,
    v1_binary: bool = True,
    keep_last_n: Optional[int] = None,
) -> str:
    """save_pass, minus the stall: trees are flattened on the calling thread
    (pass host-resident numpy trees — the trainer pre-fetches device arrays
    with copy_to_host_async), all file I/O happens on `writer`'s thread.
    Returns the pass dir path that is durable once writer.wait() returns."""
    if keep_last_n is not None and keep_last_n < 0:
        raise ValueError(f"keep_last_n must be >= 0, got {keep_last_n}")
    flats = _flatten_pass_trees(params, states, opt_state)
    pdir = os.path.join(save_dir, f"pass-{pass_id:05d}")
    writer.submit(
        lambda: _write_pass_files(
            save_dir, pass_id, flats, extra_meta, v1_binary, keep_last_n
        ),
        desc=pdir,
    )
    return pdir


def _write_latest(save_dir: str, pass_id: int) -> None:
    fd, tmp = tempfile.mkstemp(dir=save_dir, suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        f.write(f"pass-{pass_id:05d}\n")
    os.replace(tmp, os.path.join(save_dir, LATEST_FILE))


def _list_pass_ids(save_dir: str) -> List[int]:
    try:
        names = os.listdir(save_dir)
    except OSError:
        return []
    out = []
    for d in names:
        if d.startswith("pass-") and os.path.isdir(os.path.join(save_dir, d)):
            try:
                out.append(int(d.split("-")[1]))
            except ValueError:
                continue
    return sorted(out)


def _prune_old_passes(save_dir: str, keep: int, just_written: str) -> None:
    # sweep trash left by a crash between rename-aside and rmtree in an
    # earlier run, or keep_last_n's disk bound erodes one dir per kill
    for d in os.listdir(save_dir):
        if d.startswith(".trash-pass-"):
            shutil.rmtree(os.path.join(save_dir, d), ignore_errors=True)
    passes = _list_pass_ids(save_dir)
    for pid in passes[:-keep] if keep < len(passes) else []:
        victim = os.path.join(save_dir, f"pass-{pid:05d}")
        if os.path.abspath(victim) == os.path.abspath(just_written):
            continue
        # rename aside first so a concurrent reader never opens a
        # half-deleted pass dir; the rmtree then races with nobody
        trash = os.path.join(save_dir, f".trash-pass-{pid:05d}")
        try:
            os.replace(victim, trash)
        except OSError as e:
            log.warning("checkpoint retention: cannot retire %s: %s", victim, e)
            continue
        shutil.rmtree(trash, ignore_errors=True)


def verify_pass(pdir: str) -> bool:
    """True when `pdir` holds a readable manifest and every file it lists
    exists and passes its CRC — the load_pass acceptance test, minus the
    loading."""
    mpath = os.path.join(pdir, "manifest.json")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        for name in manifest.get("files", {}):
            path = os.path.join(pdir, f"{name}.npz")
            with open(path, "rb") as f:
                if zlib.crc32(f.read()) != manifest["files"][name]["crc32"]:
                    return False
    except (OSError, ValueError, KeyError):
        return False
    return True


def find_latest_valid_pass(save_dir: str) -> Optional[int]:
    """Newest pass id under `save_dir` whose checkpoint passes CRC, or None.

    Tries the `latest` pointer first, then scans pass dirs newest-to-oldest;
    corrupt or partial pass dirs (torn npz, missing manifest — a crash
    mid-save) are skipped with a warning, so auto-resume lands on the newest
    checkpoint that can actually be trusted."""
    if not os.path.isdir(save_dir):
        return None
    candidates = _list_pass_ids(save_dir)[::-1]
    try:
        with open(os.path.join(save_dir, LATEST_FILE)) as f:
            pointed = int(f.read().strip().split("-")[1])
        candidates = [pointed] + [p for p in candidates if p != pointed]
    except (OSError, ValueError, IndexError):
        pass
    for pid in candidates:
        pdir = os.path.join(save_dir, f"pass-{pid:05d}")
        if verify_pass(pdir):
            return pid
        log.warning(
            "auto-resume: skipping corrupt/partial checkpoint %s "
            "(CRC or manifest check failed)", pdir,
        )
    return None


def pass_manifest(save_dir: str, pass_id: int) -> Dict[str, Any]:
    """The manifest of one pass dir, or {} — how auto-resume learns whether a
    checkpoint is a preemption-drain mid-pass save (extra.mid_pass +
    extra.batches_done) or a normal pass-boundary one."""
    try:
        with open(
            os.path.join(save_dir, f"pass-{pass_id:05d}", "manifest.json")
        ) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def is_v1_model_dir(dirname: str) -> bool:
    """True when `dirname` looks like a reference ParamUtil model directory:
    no manifest.json, and at least one regular file whose 16 leading bytes
    parse as a `Parameter::Header` (Parameter.h:263) consistent with the
    file's length (16 + 4*size bytes)."""
    from paddle_tpu.trainer import v1_format

    if not os.path.isdir(dirname) or os.path.exists(
        os.path.join(dirname, "manifest.json")
    ):
        return False
    for fn in os.listdir(dirname):
        path = os.path.join(dirname, fn)
        if not os.path.isfile(path):
            continue
        try:
            with open(path, "rb") as f:
                raw = f.read(v1_format.HEADER.size)
            if len(raw) != v1_format.HEADER.size:
                continue
            fmt, value_size, size = v1_format.HEADER.unpack(raw)
            if (
                fmt == v1_format.PARAM_FORMAT_ORIGINAL
                and value_size == 4
                and os.path.getsize(path) == v1_format.HEADER.size + 4 * size
            ):
                return True
        except OSError:
            continue
    return False


def load_pass(
    save_dir: str,
    pass_id: Optional[int] = None,
    params_template: Union[None, Dict[str, Any], Callable[[], Dict[str, Any]]] = None,
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray], Dict[str, np.ndarray], Dict]:
    """Load (params, states, opt_flat, manifest). pass_id=None → latest.
    `params_template` may be a zero-arg callable, resolved only if the v1
    branch needs it.

    Accepts three on-disk layouts, sniffed in order:
    - save_dir/pass-%05d/ with manifest.json (this repo's native format);
    - save_dir itself is a pass dir (manifest.json directly inside);
    - save_dir (or save_dir/pass-%05d) is a reference ParamUtil model
      directory of raw `Parameter::save` files (paddle/trainer/ParamUtil.cpp:50
      loadParameters) — needs `params_template` for shapes; conv filters are
      transposed from the reference flat [cin,kh,kw,cout] layout to HWIO by
      v1_format.read_param. Optimizer state/states are absent in that case
      (the reference checkpoints values only)."""
    v1_sniffed = False
    if os.path.exists(os.path.join(save_dir, "manifest.json")):
        pdir = save_dir
    elif pass_id is None and is_v1_model_dir(save_dir):
        pdir = save_dir
        v1_sniffed = True
    else:
        if pass_id is None:
            passes = sorted(
                int(d.split("-")[1])
                for d in os.listdir(save_dir)
                if d.startswith("pass-") and os.path.isdir(os.path.join(save_dir, d))
            )
            if not passes:
                raise FileNotFoundError(f"no pass-* checkpoints under {save_dir}")
            pass_id = passes[-1]
        pdir = os.path.join(save_dir, f"pass-{pass_id:05d}")
    if not os.path.exists(os.path.join(pdir, "manifest.json")) and (
        v1_sniffed or is_v1_model_dir(pdir)
    ):
        if callable(params_template):
            # lazy template: only the v1 branch needs the shapes, and
            # building them may be non-trivial (a zero3 trainer gathers its
            # flat-sharded params to canonical) — resolve it only here
            params_template = params_template()
        if params_template is None:
            raise ValueError(
                f"{pdir!r} is a reference-format (v1 binary) model dir; loading "
                "it needs a params_template for shapes — init the trainer state "
                "first (Trainer.load does this automatically)"
            )
        from paddle_tpu.trainer import v1_format

        params = v1_format.load_model_dir(pdir, _to_numpy_tree(params_template))
        return params, {}, {}, {"pass_id": pass_id, "v1_binary": True, "files": {}}
    with open(os.path.join(pdir, "manifest.json")) as f:
        manifest = json.load(f)
    out = {}
    for name in ("params", "states", "opt"):
        path = os.path.join(pdir, f"{name}.npz")
        if name in manifest["files"] and os.path.exists(path):
            with open(path, "rb") as f:
                crc = zlib.crc32(f.read())
            if crc != manifest["files"][name]["crc32"]:
                raise IOError(f"checkpoint {path} failed CRC check")
            with np.load(path) as z:
                out[name] = {k: z[k] for k in z.files}
        else:
            out[name] = {}
    return out["params"], out["states"], out["opt"], manifest
