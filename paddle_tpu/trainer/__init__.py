from paddle_tpu.trainer.events import (  # noqa: F401
    BeginIteration,
    BeginPass,
    EndIteration,
    EndPass,
)
from paddle_tpu.trainer.trainer import (  # noqa: F401
    DIVERGENCE_POLICIES,
    REMAT_POLICIES,
    DivergenceError,
    Preempted,
    SGDTrainer,
    TrainState,
)
from paddle_tpu.trainer.checkpoint import AsyncCheckpointer  # noqa: F401
from paddle_tpu.trainer import checkpoint as checkpoint  # noqa: F401
