"""v1 binary parameter format — byte-level interchange with the reference.

Layout (paddle/parameter/Parameter.h:263 `Parameter::Header` +
Parameter.cpp:286 `Parameter::save`): a little-endian packed
`{int32 format; uint32 valueSize; uint64 size}` header (16 bytes, no
padding) followed by `size` raw float32 values. One file per parameter named
after it in a model directory (ParamUtil), or all parameters appended to one
stream after a length-prefixed serialized config (MergeModel.cpp).

Weight memory layouts (so reference-trained models load into the NHWC/HWIO
layers here and vice-versa):
- fc / projection weights: reference `Weight(height=in, width=out)` row-major
  [in, out] — identical to ours, no conversion.
- conv filters: reference rows are (channel, kh, kw) against columns
  num_filters (ExpandConvLayer.cpp:48 `height = filterPixels * filterChannels`,
  im2col channel-major) — i.e. flat [cin, kh, kw, cout]; ours are HWIO
  [kh, kw, cin, cout]. 4-D parameters are transposed on write/read.
"""

from __future__ import annotations

import os
import struct
from typing import Any, BinaryIO, Dict, Optional

import numpy as np

HEADER = struct.Struct("<iIQ")  # format, valueSize, size
PARAM_FORMAT_ORIGINAL = 0


def _to_v1_layout(name: str, arr: np.ndarray) -> np.ndarray:
    if arr.ndim == 4:  # HWIO -> [cin, kh, kw, cout] (reference conv rows)
        return np.ascontiguousarray(np.transpose(arr, (2, 0, 1, 3)))
    return arr


def _from_v1_layout(name: str, flat: np.ndarray, shape) -> np.ndarray:
    if len(shape) == 4:  # reverse of _to_v1_layout
        kh, kw, ci, co = shape
        return np.ascontiguousarray(
            np.transpose(flat.reshape(ci, kh, kw, co), (1, 2, 0, 3))
        )
    return flat.reshape(shape)


def write_param(stream: BinaryIO, name: str, arr: np.ndarray) -> None:
    """Parameter::save byte layout. Values are written float32 (the
    reference's `real`); non-f32 params are cast."""
    data = _to_v1_layout(name, np.asarray(arr)).astype("<f4", copy=False)
    stream.write(HEADER.pack(PARAM_FORMAT_ORIGINAL, 4, data.size))
    stream.write(data.tobytes())


def read_param(stream: BinaryIO, name: str, shape) -> np.ndarray:
    raw = stream.read(HEADER.size)
    if len(raw) != HEADER.size:
        raise EOFError(f"truncated v1 parameter header for {name!r}")
    fmt, value_size, size = HEADER.unpack(raw)
    if fmt != PARAM_FORMAT_ORIGINAL:
        raise ValueError(f"unsupported v1 header format {fmt} for {name!r}")
    if value_size != 4:
        raise ValueError(f"unsupported valueSize {value_size} for {name!r}")
    want = int(np.prod(shape)) if shape else 1
    if size != want:
        raise ValueError(
            f"size mismatch for {name!r}: file has {size}, parameter wants {want}"
        )
    data = np.frombuffer(stream.read(size * 4), dtype="<f4", count=size)
    return _from_v1_layout(name, data, shape)


def save_model_dir(dirname: str, params: Dict[str, Any]) -> None:
    """ParamUtil-style model dir: one `Parameter::save` file per parameter."""
    os.makedirs(dirname, exist_ok=True)
    for name, arr in params.items():
        with open(os.path.join(dirname, name), "wb") as f:
            write_param(f, name, np.asarray(arr))


def load_model_dir(dirname: str, template: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Load a ParamUtil model dir against a shape template ({name: array})."""
    out: Dict[str, np.ndarray] = {}
    for name, arr in template.items():
        path = os.path.join(dirname, name)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"missing parameter file {path!r} while loading model"
            )
        with open(path, "rb") as f:
            out[name] = read_param(f, name, np.asarray(arr).shape)
    return out


def write_merged(
    stream: BinaryIO, config_bytes: bytes, params: Dict[str, Any],
    order: Optional[list] = None,
) -> None:
    """MergeModel.cpp layout: int64 config length + serialized config +
    parameters appended in order, each with its Parameter::Header."""
    stream.write(struct.pack("<q", len(config_bytes)))
    stream.write(config_bytes)
    for name in order or list(params):
        write_param(stream, name, np.asarray(params[name]))


def read_merged(
    stream: BinaryIO, template: Dict[str, Any], order: Optional[list] = None,
):
    """→ (config_bytes, {name: array})."""
    (n,) = struct.unpack("<q", stream.read(8))
    config_bytes = stream.read(n)
    out: Dict[str, np.ndarray] = {}
    for name in order or list(template):
        out[name] = read_param(stream, name, np.asarray(template[name]).shape)
    return config_bytes, out
