"""Config-driven generation: the reference Trainer's generation job.

Replicates what test_recurrent_machine_generation.cpp:59-88 drives by hand:
build a GradientMachine from a parsed config, loadParameters(modelDir),
forward one batch in PASS_TEST, then run the declared evaluators — the
seqtext printer writes the generated sequences to its result_file.

The reference resolves the config's relative dict_file/result_file paths
against its working directory; `base_dir` plays that role here, and
`result_file` overrides the config's destination (tests write to a tmpdir,
never next to the read-only reference tree).

`GenerationSession` is the long-lived form (ISSUE 6): the Network is built
and the checkpoint loaded ONCE, then `generate` runs any number of batches
against the same parameters — the serving runtime
(`paddle_tpu/serving/server.py` method `generate_config`) and the golden
tests exercise this same path. `run_generation` stays as the one-shot
wrapper with its original signature."""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from paddle_tpu.nn.graph import Context, Network


def _resolve(path: str, base_dir: Optional[str]) -> str:
    if base_dir is not None and path and not os.path.isabs(path):
        return os.path.join(base_dir, path)
    return path


class GenerationSession:
    """Build once, load once, generate many.

    The per-call rebuild `run_generation` used to do (fresh Network, fresh
    init, checkpoint reload on EVERY request) is hoisted into the first
    `generate` call; subsequent calls reuse the same parameter buffers, so a
    serving process pays model-load cost once per lifetime instead of once
    per request. Parameter init needs a sample batch for shape discovery,
    hence lazy build on first generate rather than in __init__."""

    def __init__(
        self,
        pc,
        model_dir: Optional[str] = None,
        base_dir: Optional[str] = None,
        rng: Optional[jax.Array] = None,
    ):
        self.pc = pc
        self.net = Network(pc.outputs)
        self.model_dir = model_dir
        self.base_dir = base_dir
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._params: Optional[Dict[str, Any]] = None
        self._states: Optional[Dict[str, Any]] = None

    @property
    def built(self) -> bool:
        return self._params is not None

    def _ensure_built(self, batch: Dict[str, Any]) -> None:
        if self._params is not None:
            return
        from paddle_tpu.trainer.checkpoint import load_pass

        params, states = self.net.init(self._rng, batch, train=False)
        if self.model_dir is not None:
            import jax.numpy as jnp

            loaded, _, _, _ = load_pass(self.model_dir, params_template=params)
            params = {k: jnp.asarray(v) for k, v in loaded.items()}
        self._params, self._states = params, states

    def generate(
        self, batch: Dict[str, Any], result_file: Optional[str] = None
    ) -> Dict[str, str]:
        """Forward one batch and write the declared seq_text_printer outputs.

        Returns {evaluator name: result file written}. The generated node is
        the config's output (`__beam_search_predict__` resolution); its
        cached beam payload (scores/all-beam histories) feeds the beam-mode
        print."""
        from paddle_tpu.metrics.evaluators import EVALUATORS

        self._ensure_built(batch)
        ctx = Context("apply", self._params, self._states, None, False)
        values = self.net._run(ctx, batch)

        printers = [
            ec for ec in self.pc.context.evaluators
            if ec.type == "seq_text_printer"
        ]
        written: Dict[str, str] = {}
        for idx, ec in enumerate(printers):
            out_name = (
                ec.input_layers[0] if ec.input_layers else self.pc.outputs[0].name
            )
            arg = values.get(out_name)
            if arg is None:
                continue
            if result_file and len(printers) > 1:
                # one override dest + several printers would silently keep
                # only the last printer's text; fan out per evaluator
                root, ext = os.path.splitext(result_file)
                dest = f"{root}.{ec.name or idx}{ext}"
            else:
                dest = result_file or _resolve(ec.result_file, self.base_dir)
            printer = EVALUATORS.get("seq_text_printer")(
                result_file=dest,
                dict_file=_resolve(ec.dict_file, self.base_dir),
                delimited=ec.delimited,
            )
            sample_ids = None
            if len(ec.input_layers) > 1:
                id_name = ec.input_layers[1]
                if id_name in batch:
                    sample_ids = np.asarray(batch[id_name])
            printer.start()
            printer.update(
                output=np.asarray(arg.value),
                sample_ids=sample_ids,
                beam=ctx.cache.get(("beam", out_name)),
                lengths=None if arg.lengths is None else np.asarray(arg.lengths),
                sub_lengths=(
                    None
                    if arg.sub_lengths is None
                    else np.asarray(arg.sub_lengths)
                ),
            )
            printer.finish()
            # unnamed printers must not collide in the result map when a
            # config declares several (the caller reads every entry's file)
            key = ec.name or (
                "seq_text_printer"
                if len(printers) == 1
                else f"seq_text_printer_{idx}"
            )
            written[key] = dest
        return written


def run_generation(
    pc,
    batch: Dict[str, Any],
    model_dir: Optional[str] = None,
    base_dir: Optional[str] = None,
    result_file: Optional[str] = None,
    rng: Optional[jax.Array] = None,
) -> Dict[str, str]:
    """One-shot generation: a thin wrapper building a GenerationSession for a
    single batch (the original API; golden tests and the serving runtime both
    land on the session path)."""
    return GenerationSession(
        pc, model_dir=model_dir, base_dir=base_dir, rng=rng
    ).generate(batch, result_file=result_file)
