"""Config-driven generation: the reference Trainer's generation job.

Replicates what test_recurrent_machine_generation.cpp:59-88 drives by hand:
build a GradientMachine from a parsed config, loadParameters(modelDir),
forward one batch in PASS_TEST, then run the declared evaluators — the
seqtext printer writes the generated sequences to its result_file.

The reference resolves the config's relative dict_file/result_file paths
against its working directory; `base_dir` plays that role here, and
`result_file` overrides the config's destination (tests write to a tmpdir,
never next to the read-only reference tree).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from paddle_tpu.nn.graph import Context, Network


def _resolve(path: str, base_dir: Optional[str]) -> str:
    if base_dir is not None and path and not os.path.isabs(path):
        return os.path.join(base_dir, path)
    return path


def run_generation(
    pc,
    batch: Dict[str, Any],
    model_dir: Optional[str] = None,
    base_dir: Optional[str] = None,
    result_file: Optional[str] = None,
    rng: Optional[jax.Array] = None,
) -> Dict[str, str]:
    """Generate with a ParsedConfig and write the printer outputs.

    Returns {evaluator name: result file written}. The generated node is the
    config's output (`__beam_search_predict__` resolution); its cached beam
    payload (scores/all-beam histories) feeds the beam-mode print.
    """
    from paddle_tpu.metrics.evaluators import EVALUATORS
    from paddle_tpu.trainer.checkpoint import load_pass

    net = Network(pc.outputs)
    params, states = net.init(
        rng if rng is not None else jax.random.PRNGKey(0), batch, train=False
    )
    if model_dir is not None:
        import jax.numpy as jnp

        loaded, _, _, _ = load_pass(model_dir, params_template=params)
        params = {k: jnp.asarray(v) for k, v in loaded.items()}

    ctx = Context("apply", params, states, None, False)
    values = net._run(ctx, batch)

    written: Dict[str, str] = {}
    for ec in pc.context.evaluators:
        if ec.type != "seq_text_printer":
            continue
        out_name = ec.input_layers[0] if ec.input_layers else pc.outputs[0].name
        arg = values.get(out_name)
        if arg is None:
            continue
        dest = result_file or _resolve(ec.result_file, base_dir)
        printer = EVALUATORS.get("seq_text_printer")(
            result_file=dest,
            dict_file=_resolve(ec.dict_file, base_dir),
            delimited=ec.delimited,
        )
        sample_ids = None
        if len(ec.input_layers) > 1:
            id_name = ec.input_layers[1]
            if id_name in batch:
                sample_ids = np.asarray(batch[id_name])
        printer.start()
        printer.update(
            output=np.asarray(arg.value),
            sample_ids=sample_ids,
            beam=ctx.cache.get(("beam", out_name)),
            lengths=None if arg.lengths is None else np.asarray(arg.lengths),
            sub_lengths=(
                None if arg.sub_lengths is None else np.asarray(arg.sub_lengths)
            ),
        )
        printer.finish()
        written[ec.name or "seq_text_printer"] = dest
    return written
