"""Training driver.

Parity with paddle/trainer: Trainer::train (Trainer.cpp:261) / trainOnePass
(:492) / TrainerInternal::trainOneBatch (TrainerInternal.cpp:66), and the v2
API SGD.train (python/paddle/v2/trainer.py:24,:124).

TPU-native design (SURVEY §7 hard-part (1)): the whole hot loop —
forward, backward, optimizer update, LR schedule, model averaging — is ONE
compiled XLA program per batch shape, with the train state donated so
parameters update in-place in device memory. The reference's per-parameter
UpdateCallback chain is folded into that program. Data parallelism: pass a
`DataParallel` config (paddle_tpu/parallel) and the same step is pjit-sharded
over the mesh data axis; gradients all-reduce over ICI — the ring of
MultiGradientMachine.h:44-157 done by the hardware."""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import dtypes, faults, preempt, stats
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace
from paddle_tpu.data.pipeline import StackedBatch
from paddle_tpu.data.pipeline import coerce_batch as _coerce_batch
from paddle_tpu.data.pipeline import is_device_batch
from paddle_tpu.nn.graph import SAMPLE_MASK_KEY, Argument, Layer, Network
from paddle_tpu.optim.optimizers import Optimizer
from paddle_tpu.optim.average import ModelAverage
from paddle_tpu.optim import schedules
from paddle_tpu.trainer import checkpoint as ckpt_mod
from paddle_tpu.trainer.events import BeginIteration, BeginPass, EndIteration, EndPass

log = logging.getLogger("paddle_tpu.trainer")

TrainState = Dict[str, Any]  # params / opt / states / avg / samples / rng

DIVERGENCE_POLICIES = ("skip_batch", "rollback", "raise")

# rematerialization policies for the compiled step's backward pass (see
# _build_step): "dots" keeps matmul/conv outputs and recomputes everything
# elementwise; "conv_only" keeps only the tagged conv/matmul outputs
# (ops/conv.py / ops/linalg.py checkpoint_name); "full" recomputes the whole
# forward. None/"none" = store every residual (jax default).
REMAT_POLICIES = (None, "none", "dots", "conv_only", "full")


SHARD_UPDATE_MODES = ("zero1", "zero2", "zero3")


def _resolve_shard_mode(shard_update) -> Optional[str]:
    """Normalize SGDTrainer(shard_update=...): bools stay the zero1 alias,
    strings name the ZeRO mode, anything else fails loudly."""
    if shard_update in (False, None, "none", "0"):
        return None
    if shard_update in (True, "true", "1"):
        return "zero1"
    if shard_update in SHARD_UPDATE_MODES:
        return shard_update
    raise ValueError(
        f"shard_update must be a bool or one of {SHARD_UPDATE_MODES}, got "
        f"{shard_update!r}"
    )


class DivergenceError(RuntimeError):
    """Raised by divergence_policy="raise" when a step cost goes NaN/Inf."""


class Preempted(RuntimeError):
    """Raised by train() after a preemption-notice drain (core/preempt):
    the in-flight step finished and — given a save_dir and remaining grace —
    a CRC-valid mid-pass checkpoint was written. The CLI maps this to exit
    code `preempt.EXIT_PREEMPTED`; a restart with auto_resume=True continues
    from exactly this batch boundary."""

    def __init__(
        self,
        pass_id: int,
        batches_done: int,
        checkpoint_dir: Optional[str],
        reason: Optional[str] = None,
    ):
        self.pass_id = pass_id
        self.batches_done = batches_done
        self.checkpoint_dir = checkpoint_dir
        self.reason = reason
        where = (
            f"checkpoint {checkpoint_dir}" if checkpoint_dir
            else "no checkpoint written"
        )
        super().__init__(
            f"preempted ({reason or 'signal'}) at pass {pass_id} after "
            f"{batches_done} batch(es); {where}"
        )


class SGDTrainer:
    """v2 `trainer.SGD` analog driving compiled train steps."""

    def __init__(
        self,
        cost: Union[Layer, Sequence[Layer]],
        optimizer: Optimizer,
        extra_outputs: Sequence[Layer] = (),
        schedule: Optional[Callable] = None,
        model_average: Optional[ModelAverage] = None,
        parallel: Optional[Any] = None,  # parallel.DataParallel or None
        updater: Optional[Any] = None,  # parallel.ParameterUpdater
        seed: int = 0,
        remat: Optional[str] = None,  # REMAT_POLICIES
        precision: Optional[str] = None,  # None (ambient) | "f32" | "bf16"
        divergence_policy: Optional[str] = None,  # skip_batch|rollback|raise
        guard_check_every: int = 16,  # steps between divergence-guard polls
        # ZeRO-sharded update over the mesh data axis: False/None = off,
        # True = "zero1" (back-compat alias), or "zero1"|"zero2"|"zero3"
        shard_update: Union[bool, str, None] = False,
        grad_compression: Optional[str] = None,  # None/none | bf16 | int8
    ):
        costs = [cost] if isinstance(cost, Layer) else list(cost)
        self.cost_names = [c.name for c in costs]
        self.extra_names = [e.name for e in extra_outputs]
        self.network = Network(costs + list(extra_outputs))
        self.optimizer = optimizer
        if remat not in REMAT_POLICIES:
            raise ValueError(
                f"remat must be one of {REMAT_POLICIES}, got {remat!r}"
            )
        self.remat = None if remat == "none" else remat
        # Mixed-precision policy (ISSUE 9): precision="bf16" makes THIS
        # trainer's compiled step cast dot/conv inputs to bfloat16 through
        # Policy.cast (ops/linalg.py, ops/conv.py) while parameters stay
        # float32 MASTERS — created f32, updated f32 by the optimizer
        # (update_one upcasts the incoming grad), stored f32 by checkpoints.
        # Gradients therefore flow bf16 through the backward network and land
        # f32 at the param leaves (the cast's transpose), so a bf16-trained
        # checkpoint resumes bitwise into an f32 trainer and vice versa.
        # Numerically-sensitive reductions stay pinned f32 regardless of the
        # policy: softmax/xent (ops/xent.py), batch-norm statistics
        # (ops/normalization.py), the pass-cost average and the divergence
        # guard's isfinite (both fed by the f32-pinned cost below).
        # None = inherit the ambient dtypes.current() global at build time
        # (init_ctx's dtype_policy flag / bench.py's set_policy).
        self._policy_override = (
            dtypes.get(precision) if precision is not None else None
        )
        # The ParameterUpdater protocol (ParameterUpdater.h:38) is the seam
        # where parallelism plugs into the trainer: the optimizer application
        # inside the compiled step goes through updater.apply, and host-side
        # pass boundaries go through start_pass/finish_pass (barriers on
        # multi-host). Default: local updater, or the ICI all-reduce updater
        # when a DataParallel mesh is configured; shard_update selects a ZeRO
        # mode (parallel/updaters.py):
        #   "zero1" (True): reduce-scatter grads over the mesh data axis →
        #       shard-local optimizer step on 1/N of the optimizer state →
        #       all-gather updated params, every step;
        #   "zero2": zero1's update fused across the K-step dispatch — the
        #       multi-step program merges the window into one shard-local
        #       K*B batch, so grads cross the wire ONCE per dispatch
        #       (gradient-accumulation semantics: one update per window);
        #   "zero3": parameters live flat data-axis-sharded in the train
        #       state (~N x less param HBM per chip) and are gathered
        #       layer-by-layer on demand inside the step, re-gathered (not
        #       stored) in the backward via remat;
        # optionally with a compressed collective payload
        # (--grad_compression; parallel/compression.py — under zero3 the
        # int8 budget moves to the on-demand param gather).
        self.shard_update = _resolve_shard_mode(shard_update)
        if (
            self.shard_update or grad_compression not in (None, "none")
        ) and (parallel is None and updater is None):
            raise ValueError(
                "shard_update/grad_compression need a DataParallel mesh "
                "(SGDTrainer(parallel=...)): there is no data axis to shard "
                "the update over"
            )
        if grad_compression not in (None, "none") and not self.shard_update:
            raise ValueError(
                "grad_compression wraps the sharded update's reduce-scatter "
                "— pass shard_update=True with it"
            )
        if updater is not None and (
            self.shard_update or grad_compression not in (None, "none")
        ):
            raise ValueError(
                "shard_update/grad_compression select the built-in "
                "ShardedUpdater and cannot combine with an explicit "
                "updater= — construct ShardedUpdater(optimizer, parallel, "
                "compression=...) yourself instead"
            )
        if updater is None:
            from paddle_tpu.parallel import (
                IciAllReduceUpdater, SgdLocalUpdater, ShardedUpdater,
                Zero2Updater, Zero3Updater,
            )

            if parallel is not None and self.shard_update:
                cls = {
                    "zero1": ShardedUpdater,
                    "zero2": Zero2Updater,
                    "zero3": Zero3Updater,
                }[self.shard_update]
                updater = cls(
                    optimizer, parallel, compression=grad_compression or "none"
                )
            elif parallel is not None:
                updater = IciAllReduceUpdater(optimizer, parallel)
            else:
                updater = SgdLocalUpdater(optimizer)
        self.updater = updater
        self.schedule = schedule or schedules.build(optimizer.learning_rate)
        self.model_average = model_average or ModelAverage(0.0)
        self.parallel = parallel
        self.seed = seed
        # Divergence guard (SURVEY §5 failure-as-common-case): with a policy
        # set, the compiled step checks jnp.isfinite(cost), hands back the
        # PRE-step state on NaN/Inf (donation-safe — the select happens inside
        # the same program), and bumps a cumulative `diverged` counter carried
        # in the train state, so DETECTION is device-resident too. The host
        # polls that counter only every `guard_check_every` steps (and at pass
        # end / before a preempt drain) and reacts per policy within that
        # bounded window — no per-step host sync. guard_check_every=1 restores
        # the old react-at-the-offending-batch latency. None = guard compiled
        # out (the step program's async dispatch behavior stays byte-identical).
        if divergence_policy is not None and divergence_policy not in DIVERGENCE_POLICIES:
            raise ValueError(
                f"divergence_policy must be one of {DIVERGENCE_POLICIES} or "
                f"None, got {divergence_policy!r}"
            )
        self.divergence_policy = divergence_policy
        if guard_check_every < 1:
            raise ValueError(
                f"guard_check_every must be >= 1, got {guard_check_every}"
            )
        self.guard_check_every = guard_check_every
        # Persistent-compile-cache opt-out for MESH step programs: jax
        # 0.4.37's CPU backend can SEGFAULT executing a DESERIALIZED
        # (persistent-cache-hit) donated multi-device program once other
        # collective-using donated programs have run in the process
        # (repro: two identical DataParallel trainings in one process with
        # jax_compilation_cache_dir set — the second dies inside the
        # deserialized executable; cache-free or donation-free runs are
        # fine). A per-trainer constant folded into the traced step changes
        # the cache key, so mesh steps always compile fresh; the in-memory
        # executable cache still amortizes within the trainer, and
        # single-device programs keep the full persistent-cache benefit.
        import os as _os

        self._cache_salt = (
            (int.from_bytes(_os.urandom(4), "big") & 0x7FFFFFFF) | 1
            if parallel is not None
            else 0
        )
        self.state: Optional[TrainState] = None
        # set by resize_to: gates the per-dispatch stale-plan check on
        # StackedBatch groups — straggler batches sharded for an old mesh
        # can only exist once a resize happened in this process
        self._resized = False
        self._step_fn = None
        self._multi_fn = None  # K-step fused dispatch (make_multi_step), lazy
        self._eval_fn = None
        # host mirror of state["diverged"] as of the last guard poll — the
        # delta on each poll is the number of new divergence events
        self._diverged_seen = 0
        # background writer for async (zero-stall) checkpointing, created on
        # the first async save; wait() on it is the durability barrier
        self._ckpt_writer: Optional[ckpt_mod.AsyncCheckpointer] = None
        # (save_dir, pass_id) of the newest checkpoint this trainer wrote or
        # loaded — lets _rollback skip a full CRC re-scan per divergence event
        self._known_good_pass: Optional[tuple] = None
        # elastic resize bookkeeping: completed-epoch log (drain/reshard/
        # resume latency split, surfaced per pass in EndPass metrics) and the
        # in-flight marker consumed by the first post-reshard dispatch
        self._resize_log: List[Dict[str, Any]] = []
        self._resize_mark: Optional[Dict[str, Any]] = None

    # -- precision policy ----------------------------------------------------
    def policy(self) -> dtypes.Policy:
        """The dtype policy this trainer's programs trace under: the explicit
        SGDTrainer(precision=...) override, else the ambient global."""
        return self._policy_override or dtypes.current()

    @property
    def precision(self) -> str:
        return self.policy().name

    # -- state ---------------------------------------------------------------
    def init_state(self, sample_batch: Dict[str, Any]) -> TrainState:
        rng = jax.random.PRNGKey(self.seed)
        params, states = self.network.init(
            rng, sample_batch, train=True, policy=self.policy()
        )
        self.optimizer.param_attrs = self.network.param_attrs
        # the updater owns the opt-state LAYOUT: canonical per-param slots by
        # default, flat [n, chunk] data-axis-sharded slots (+ error-feedback
        # residuals) under shard_update. init_opt_state also binds the flat
        # geometry, which params_from_canonical below needs: under zero3 the
        # PARAMETERS adopt the same flat sharded layout (identity otherwise),
        # and the model-average state mirrors whatever layout params use.
        opt_state = self.updater.init_opt_state(params)
        params_store = self.updater.params_from_canonical(params)
        state: TrainState = {
            "params": params_store,
            "opt": opt_state,
            "states": states,
            "avg": self.model_average.init_state(params_store),
            # int32 (not float32): float32 absorbs small increments past 2^24
            # samples, which would freeze LR schedules and the per-step rng
            "samples": jnp.zeros((), jnp.int32),
            # host-adjustable LR multiplier: the rollback divergence policy
            # halves it on every restore (the classic diverged-run response)
            "lr_scale": jnp.ones((), jnp.float32),
            # device-resident divergence flag: cumulative count of steps whose
            # cost came back NaN/Inf (the step reverts those updates in-place);
            # the host reads it only at guard-poll boundaries
            "diverged": jnp.zeros((), jnp.int32),
            # on-device pass cost accumulator (guard mode): the step adds its
            # cost here and the divergence revert masks poisoned entries, so
            # the host never issues eager masking ops — one fetch per pass
            "cost_acc": jnp.zeros((), jnp.float32),
            "rng": rng,
        }
        self._diverged_seen = 0
        if self.parallel is not None:
            # hand the discovered per-param attrs (sharding specs) to the
            # parallel plan before placing the state on the mesh
            if not self.parallel.param_attrs:
                self.parallel.param_attrs = self.network.param_attrs
            # ZeRO-sharded slot/EF leaves land DIRECTLY on their 1/n-per-chip
            # resident placement via the updater's opt_leaf_sharding rule
            # (zero3 params/averages via param_leaf_sharding likewise)
            state = self.parallel.shard_state(
                state,
                opt_sharding=self.updater.opt_leaf_sharding,
                param_sharding=self.updater.param_leaf_sharding,
            )
        self.state = state
        return state

    # -- compiled step -------------------------------------------------------
    def _build_step(self):
        """The raw (untraced) train-step function; _make_step jits it and
        make_multi_step scans it."""
        net = self.network
        cost_names = self.cost_names
        extra_names = self.extra_names
        updater = self.updater
        schedule = self.schedule
        avg = self.model_average
        policy = self.policy()  # pinned at build time, like the remat choice

        def step(state: TrainState, batch: Dict[str, Any]):
            mask = batch.get(SAMPLE_MASK_KEY)
            # padded trailing batch: the samples counter advances by the REAL
            # row count (mask sum), so LR schedules and the per-step rng match
            # the unpadded run sample-for-sample
            bs = (
                _batch_size(batch)
                if mask is None
                # cast-ok: int counter arithmetic, not a precision boundary
                else jnp.sum(mask).astype(jnp.int32)
            )
            if self._cache_salt:
                # dead term, folded to 0 by XLA AFTER the compile-cache key
                # is taken: embeds the per-trainer salt in mesh programs
                # (see __init__ — persistent-cache opt-out)
                bs = bs + jnp.asarray(self._cache_salt, jnp.int32) * 0
            # cast-ok: int32 sample counter → f32 schedule input, policy-free
            lr = schedule(state["samples"].astype(jnp.float32)) * state["lr_scale"]
            step_rng = jax.random.fold_in(state["rng"], state["samples"])

            # ZeRO-3 gather seam: a non-None resolver makes Context.param
            # rebuild each flat sharded leaf's full view AT ITS POINT OF
            # USE inside Network.apply (layer-by-layer on demand; the
            # all-gather's transpose delivers already-scattered gradients
            # to updater.apply). None for every other updater.
            resolver = updater.param_resolver(state["opt"])

            def loss_fn(params):
                outs, new_states = net.apply(
                    params, state["states"], batch, train=True,
                    rng=step_rng, policy=policy, param_resolver=resolver,
                )
                total = sum(outs[c].value for c in cost_names)
                # the pass-cost average and the divergence guard's isfinite
                # are f32 reductions REGARDLESS of the compute policy; most
                # cost layers already reduce in f32 (ops/xent.py), this pin
                # is the contract for the rest
                # cast-ok: f32 pin of a sensitive reduction, not a narrowing
                return total.astype(jnp.float32), (outs, new_states)

            if self.remat == "dots":
                # generic remat policy: keep every dot/conv output (the MXU
                # work), recompute the elementwise rest in the backward pass
                # — frees the activation residuals between matmuls so the
                # saved HBM converts to larger per-chip batch
                loss_fn = jax.checkpoint(
                    loss_fn, policy=jax.checkpoint_policies.dots_saveable
                )
            elif self.remat == "conv_only":
                # bytes lever for bandwidth-bound convnets: keep conv/matmul
                # outputs (tagged "conv_out" in ops/conv.py and ops/linalg.py),
                # recompute the cheap BN/relu/add epilogues in the backward
                # pass instead of round-tripping them through HBM
                loss_fn = jax.checkpoint(
                    loss_fn,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        "conv_out"
                    ),
                )
            elif self.remat == "full":
                loss_fn = jax.checkpoint(loss_fn)
            elif updater.mode == "zero3":
                # zero3 default (no explicit remat policy): save every
                # residual EXCEPT the gathered param views, so the backward
                # RE-GATHERS each full parameter instead of holding all of
                # them across the forward — the comms-for-memory trade that
                # makes the sharded residency real at peak, not just at
                # rest. The explicit policies above already recompute the
                # gathers (none of them saves the named views).
                loss_fn = jax.checkpoint(
                    loss_fn,
                    policy=jax.checkpoint_policies
                    .save_anything_except_these_names("zero3_gathered"),
                )

            (cost, (outs, new_states)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state["params"])
            if self.parallel is not None:
                grads, cost = self.parallel.reduce_grads(grads, cost)
            new_params, new_opt = updater.apply(
                grads, state["opt"], state["params"], lr
            )
            new_avg = avg.update(state["avg"], new_params)
            new_state = {
                "params": new_params,
                "opt": new_opt,
                "states": new_states,
                "avg": new_avg,
                "samples": state["samples"] + bs,
                "lr_scale": state["lr_scale"],
                "diverged": state["diverged"],
                "cost_acc": state["cost_acc"],
                "rng": state["rng"],
            }
            if self.divergence_policy is not None:
                # divergence guard, fully device-resident: on a NaN/Inf cost
                # every state leaf — params, opt slots, BN states, samples
                # counter, and the cost accumulator below — reverts to its
                # pre-step value, so the poisoned update never lands (and the
                # poisoned cost never joins the pass sum), while the
                # cumulative `diverged` counter ticks up. The host learns
                # about it at the next guard poll; no per-step value fetch.
                new_state["cost_acc"] = state["cost_acc"] + cost
                ok = jnp.isfinite(cost)
                new_state = jax.tree.map(
                    lambda new, old: jnp.where(ok, new, old), new_state, state
                )
                # cast-ok: int event counter, not a precision boundary
                new_state["diverged"] = state["diverged"] + jnp.where(
                    ok, 0, 1
                ).astype(jnp.int32)
            extras = {n: outs[n].value for n in extra_names}
            return new_state, cost, extras

        return step

    def _make_step(self):
        step = self._build_step()
        if self.parallel is not None:
            return self.parallel.compile_step(step)
        return jax.jit(step, donate_argnums=0)

    def make_multi_step(self):
        """K train steps per device dispatch: `multi(state, batches)` where
        every batch slot is stacked on a leading K axis, scanned with
        lax.scan inside ONE compiled program. Returns (new_state, costs[K]).
        On CPU the scan applies bitwise the same updates as K sequential
        single-step dispatches (tests/test_dispatch.py locks this in).

        This amortizes per-dispatch host latency (dominant on remote-tunnel
        or small-step workloads) and lets XLA overlap the tail of step i with
        the head of step i+1 — the TPU-native analog of the reference's
        compute/comm overlap in ConcurrentRemoteParameterUpdater
        (RemoteParameterUpdater.h:180). `train(steps_per_dispatch=K)` drives
        this program over K-batch groups from the reader (stacked by a
        DevicePrefetcher(stack_k=K) or host-side by the trainer).

        ZeRO-2 (shard_update="zero2") replaces the scan with the FUSED
        update: the K stacked batches merge into one shard-local [K*B] batch
        (each device's rows stay local — no batch reshuffle collective) and
        ONE forward/backward/update runs for the whole window, so the grad
        reduce-scatter and the param all-gather cross the wire once per
        DISPATCH instead of once per step (~K x fewer collective bytes on
        the grad leg). Semantics are classic gradient accumulation: the
        single update consumes the mean gradient over the window's real
        rows (sample masks compose exactly), parameters hold still within
        the window. Dispatch-level bookkeeping (cost accumulator, diverged
        counter) is scaled back to per-batch units inside the same program
        so pass averages and divergence accounting stay comparable to
        zero1; a poisoned window reverts and counts as K diverged steps."""
        step = self._build_step()

        if self.updater.mode == "zero2":
            guard_on = self.divergence_policy is not None
            n_data = self.parallel.data_axis_size
            batch_sharding = self.parallel._batch_sharding

            def multi(state: TrainState, batches: Dict[str, Any]):
                k = next(iter(batches.values())).shape[0]
                merged = {}
                for key, v in batches.items():
                    b = v.shape[1]
                    rest = tuple(v.shape[2:])
                    # shard-local merge [K, B] -> [K*B]: route the reshape
                    # through the data-axis split so each device's rows stay
                    # on-device (a naive k-major reshape would interleave
                    # shards and buy an all-to-all). Row order within the
                    # window changes, which a mean over the window cannot see.
                    vm = v.reshape((k, n_data, b // n_data) + rest)
                    vm = vm.transpose(
                        (1, 0, 2) + tuple(range(3, 3 + len(rest)))
                    )
                    merged[key] = jax.lax.with_sharding_constraint(
                        vm.reshape((k * b,) + rest), batch_sharding
                    )
                d0, a0 = state["diverged"], state["cost_acc"]
                new_state, cost, _ = step(state, merged)
                # one fused update stands for k batches: scale the dispatch-
                # level bookkeeping back to per-batch units (samples already
                # advanced by the window's real row count via the mask sum)
                new_state["diverged"] = d0 + (new_state["diverged"] - d0) * k
                if guard_on:
                    new_state["cost_acc"] = (
                        a0 + (new_state["cost_acc"] - a0) * k
                    )
                return new_state, jnp.broadcast_to(cost, (k,))

            return jax.jit(multi, donate_argnums=0)

        def multi(state: TrainState, batches: Dict[str, Any]):
            def body(s, b):
                s2, cost, _ = step(s, b)
                return s2, cost

            state, costs = jax.lax.scan(body, state, batches)
            return state, costs

        return jax.jit(multi, donate_argnums=0)

    def _make_eval(self):
        net = self.network
        cost_names = self.cost_names
        extra_names = self.extra_names
        avg = self.model_average
        policy = self.policy()

        updater = self.updater

        def evaluate(state: TrainState, batch: Dict[str, Any]):
            # zero3: averages share the flat layout, so averaging then
            # gathering equals gathering then averaging (it is linear)
            params = avg.averaged_params(state["avg"], state["params"])
            outs, _ = net.apply(
                params, state["states"], batch, train=False, policy=policy,
                param_resolver=updater.param_resolver(state["opt"]),
            )
            total = sum(outs[c].value for c in cost_names).astype(jnp.float32)
            extras = {n: outs[n].value for n in extra_names}
            return total, extras

        if self.parallel is not None:
            return self.parallel.compile_eval(evaluate)
        return jax.jit(evaluate)

    # -- public API ----------------------------------------------------------
    def train(
        self,
        reader: Callable,
        num_passes: int = 1,
        event_handler: Optional[Callable] = None,
        feeder: Optional[Callable] = None,
        test_reader: Optional[Callable] = None,
        save_dir: Optional[str] = None,
        log_period: int = 100,
        auto_resume: bool = False,
        keep_last_n: Optional[int] = None,
        steps_per_dispatch: int = 1,
        async_checkpoint: bool = True,
        resize_barrier: Optional[Callable] = None,
        remat: Optional[str] = None,
    ) -> TrainState:
        """reader yields batches (lists of samples if feeder given, else dicts
        of arrays). One call = `num_passes` passes (v1 --num_passes).

        auto_resume (needs save_dir): scan save_dir for the newest checkpoint
        that passes CRC — corrupt/partial pass dirs from a crashed save are
        skipped with a warning — restore params/opt/states and the pass and
        sample counters from it, and continue with the next pass. A run
        killed mid-pass and restarted this way replays the interrupted pass
        from its boundary and, with a deterministic reader, produces final
        params bitwise-identical to a never-killed run.

        steps_per_dispatch=K (>1): K consecutive same-shape batches are
        stacked and run through ONE compiled lax.scan dispatch
        (make_multi_step), amortizing per-dispatch host latency. Batches
        already stacked by a DevicePrefetcher(stack_k=K) dispatch as-is.
        Events, the recompile counter, the log line and the chaos sites
        (kill / preempt / nan_loss) all fire per-DISPATCH, not per batch:
        BeginIteration carries the first batch id of the window, EndIteration
        the last (its lazy .cost is the window's final cost; extra outputs
        are not collected on the fused path). A trailing remainder (pass end,
        shape change, reader exhaustion) runs through single-step dispatches,
        so a K-fused pass applies exactly the same updates as K=1.

        resize_barrier: fleet hook for elastic resize (see resize_to /
        runtime.master.ResizeClient). When a resize is requested
        (preempt.request_resize, set locally or by a master heartbeat
        watcher), the loop drains at the next dispatch boundary, writes a
        mid-pass checkpoint, calls `resize_barrier(req, pass_id,
        batches_done)` — which acks the master's drain barrier, blocks until
        every live trainer drained, and returns the final world size — then
        re-shards and CONTINUES the pass on the new mesh. None (default)
        resizes immediately to the requested world (single-trainer mode).

        async_checkpoint (default on): pass-boundary and preempt-drain saves
        copy the state to host with non-blocking fetches and hand all file
        I/O (npz/CRC/v1-format/manifest/retention) to a background writer
        thread, double-buffered with at most one snapshot in flight. train()
        waits for the writer before returning (and in its error path), load()
        and the preempt drain wait too, so every checkpoint path this method
        reports is durable. Writer failures re-raise on the training thread
        at the next save/wait.

        remat: re-pins the backward rematerialization policy for this and
        ALL SUBSEQUENT train() calls ("none" | "dots" | "conv_only" |
        "full" — see REMAT_POLICIES; it sticks on the trainer exactly like
        the constructor argument, pinned by test_train_remat_override_
        rebuilds_step). The recomputation replays the exact same ops, so
        switching remat changes step TIME and residual HBM, never the
        applied updates; compiled step programs are rebuilt when the policy
        changes. None (default) keeps the current setting."""
        event_handler = event_handler or (lambda e: None)
        if steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got {steps_per_dispatch}"
            )
        if remat is not None:
            # per-call remat override (train(remat="none"|"dots"|"conv_only"|
            # "full")): re-pins the backward rematerialization policy and
            # drops any step programs compiled under the previous one
            if remat not in REMAT_POLICIES:
                raise ValueError(
                    f"remat must be one of {REMAT_POLICIES}, got {remat!r}"
                )
            resolved = None if remat == "none" else remat
            if resolved != self.remat:
                self.remat = resolved
                self._step_fn = None
                self._multi_fn = None
        resume_pass: Optional[int] = None
        resume_pending = False
        resume_mid = False  # checkpoint is a preemption-drain mid-pass save
        resume_skip = 0  # batches of resume_pass already applied (mid-pass drain)
        if auto_resume and save_dir is not None:
            if self._ckpt_writer is not None:
                self._ckpt_writer.wait()  # scan must see completed writes
            resume_pass = ckpt_mod.find_latest_valid_pass(save_dir)
            if resume_pass is not None:
                extra = ckpt_mod.pass_manifest(save_dir, resume_pass).get(
                    "extra", {}
                )
                if extra.get("mid_pass"):
                    # preemption-drain checkpoint: pass resume_pass is only
                    # partially applied — replay it from the drained boundary
                    resume_mid = True
                    resume_skip = int(extra.get("batches_done", 0))
                log.info(
                    "auto-resume: restoring from %s/pass-%05d (continuing at "
                    "pass %d%s)", save_dir, resume_pass,
                    resume_pass if resume_mid else resume_pass + 1,
                    f" batch {resume_skip}" if resume_mid else "",
                )
                if self.state is not None:
                    self.load(save_dir, resume_pass)
                    self._known_good_pass = (save_dir, resume_pass)
                else:  # state shapes unknown until the first batch arrives
                    resume_pending = True
        flushed = False
        try:
            for pass_id in range(num_passes):
                if resume_pass is not None and (
                    pass_id < resume_pass
                    or (pass_id == resume_pass and not resume_mid)
                ):
                    continue  # completed by the run we are resuming
                resume_pending = self._train_one_pass(
                    reader, pass_id, event_handler, feeder, test_reader,
                    save_dir, log_period, keep_last_n, steps_per_dispatch,
                    async_checkpoint, resume_pass, resume_mid, resume_skip,
                    resume_pending, resize_barrier,
                )
            if resume_pending:
                # every requested pass was already checkpointed — nothing ran,
                # so state was never initialized; pull one batch just for
                # shapes and load the final checkpoint for the caller
                raw = next(iter(reader()), None)
                if raw is not None:
                    if isinstance(raw, StackedBatch):
                        raw = {k: v[0] for k, v in raw.items()}
                    on_device = is_device_batch(raw) and (
                        self.parallel is None
                        or self.parallel.is_sharded_batch(raw)
                    )
                    batch = (
                        raw
                        if on_device
                        else feeder(raw)
                        if feeder is not None and not isinstance(raw, dict)
                        else _coerce_batch(raw)
                    )
                    if self.parallel is not None and not on_device:
                        batch = self.parallel.shard_batch(batch)
                    self.init_state(batch)
                    self.load(save_dir, resume_pass)
                    self._known_good_pass = (save_dir, resume_pass)
            if self._ckpt_writer is not None:
                # durability barrier on the clean path: surfaces any async
                # write error and guarantees the final checkpoint is on disk
                self._ckpt_writer.wait()
            flushed = True
        finally:
            if not flushed and self._ckpt_writer is not None:
                # error path (incl. InjectedKill chaos): flush the in-flight
                # snapshot but never mask the propagating exception
                try:
                    self._ckpt_writer.wait()
                except Exception:
                    log.exception(
                        "async checkpoint flush failed during error exit"
                    )
        return self.state

    def _train_one_pass(
        self,
        reader: Callable,
        pass_id: int,
        event_handler: Callable,
        feeder: Optional[Callable],
        test_reader: Optional[Callable],
        save_dir: Optional[str],
        log_period: int,
        keep_last_n: Optional[int],
        steps_per_dispatch: int,
        async_checkpoint: bool,
        resume_pass: Optional[int],
        resume_mid: bool,
        resume_skip: int,
        resume_pending: bool,
        resize_barrier: Optional[Callable] = None,
    ) -> bool:
        """One training pass of the async execution runtime. Returns the
        (possibly cleared) resume_pending flag.

        Hot-loop discipline (enforced by tests/test_lint_hotloop.py): nothing
        in this body fetches a device value per step — cost accumulation is
        an async on-device add, divergence detection reads the carried
        `diverged` counter only at guard polls (_poll_guard), the log line is
        deferred one dispatch behind a non-blocking host copy, and avg_cost
        syncs once at pass end. Lines that DO fetch carry a `sync-ok` tag."""
        inj = faults.get()
        guard_on = self.divergence_policy is not None
        event_handler(BeginPass(pass_id))
        self.updater.start_pass()
        stats.RECOMPILES.start_pass()
        t0 = time.time()
        cost_sum_dev = None
        if guard_on and self.state is not None:
            # zero the on-device pass cost accumulator (×0 keeps the leaf's
            # sharding); one tiny dispatch per pass, not per step
            self.state["cost_acc"] = self.state["cost_acc"] * 0
        stepped = 0  # batches whose update was dispatched this pass
        # per-pass padded-batch count as a DATA_EVENTS delta (same pattern as
        # divergence_events/FT_EVENTS): padding happens EITHER on this host
        # path or on a DevicePrefetcher worker — a local counter would read 0
        # whenever the prefetcher does the padding
        pass_pad0 = stats.DATA_EVENTS.get("padded_batches")
        pass_div0 = self._diverged_seen
        pass_rz0 = len(self._resize_log)  # resize epochs completed this pass
        steps_since_poll = 0
        pending: List[tuple] = []  # [(logical batch id, feed-ready batch)]
        pending_sig: Optional[tuple] = None  # shared signature of `pending`
        pending_log: Optional[tuple] = None  # deferred (pass, batch, cost_dev)
        logical = 0  # reader position in single-batch units
        boundary = 0  # resolved prefix: every earlier batch applied/skipped

        def flush_log() -> None:
            nonlocal pending_log
            if pending_log is not None:
                p, b, c = pending_log
                pending_log = None
                # sync-ok: deferred one dispatch behind; the value was copied
                # to host asynchronously at stash time, so this float() reads
                # an (almost always) already-landed buffer instead of
                # serializing the dispatch pipeline head
                log.info("pass %d batch %d cost=%.6f", p, b, float(c))

        def dispatch(idx_first: int, idx_last: int, batch, k: int) -> None:
            """One device dispatch: a single compiled step (k=1) or the
            K-step fused scan. Chaos sites, events, telemetry and the log
            line all operate at this granularity."""
            nonlocal cost_sum_dev, stepped, steps_since_poll, pending_log
            if inj.active:
                if inj.fire("kill"):
                    raise faults.InjectedKill(
                        f"injected kill at pass {pass_id} batch {idx_first}"
                    )
                if inj.fire("preempt"):
                    # simulated preemption notice (SIGTERM analog): only sets
                    # the drain flag — this dispatch still steps, the NEXT
                    # boundary checkpoints and exits ("finish the step")
                    preempt.get().request(
                        f"injected preempt at pass {pass_id} batch {idx_first}"
                    )
                if inj.fire("nan_loss"):
                    batch = _poison_batch(batch)
            # one distinct signature = one XLA trace+compile (the stacked
            # [K, B, ...] signature is its own program); churn past the
            # threshold warns (misconfigured seq_buckets)
            stats.RECOMPILES.record(stats.batch_signature(batch))
            event_handler(BeginIteration(pass_id, idx_first))
            # REGISTER_TIMER_INFO("forwardBackward") parity
            # (TrainerInternal.cpp:94-152); enable via PADDLE_TPU_TIMER.
            # Timing is opt-in, so when enabled we sync the device inside
            # the timer — otherwise it would measure only async dispatch.
            # span-ok: one ring-buffer span per DISPATCH (constant name, int
            # attrs, no formatting) — a no-op truth test when tracing is off;
            # note it measures dispatch latency, not device time (no sync)
            with trace.span("train.dispatch", first=idx_first, k=k):
                with stats.timer("forwardBackward"):
                    if k == 1:
                        self.state, cost, extras = self._step_fn(self.state, batch)
                        costs = None
                    else:
                        if self._multi_fn is None:
                            self._multi_fn = self.make_multi_step()
                        self.state, costs = self._multi_fn(self.state, batch)
                        cost, extras = costs[-1], {}
                    if stats.GLOBAL_STATS.enabled:
                        jax.block_until_ready(cost)  # sync-ok: opt-in timing only
            if self._resize_mark is not None:
                # first dispatch on the post-resize mesh returned (compile
                # included): close the resume leg of the resize latency split
                self._note_resize_resumed()
            # pass-cost accumulation never syncs: in guard mode the compiled
            # step itself accumulates state["cost_acc"] (with the divergence
            # revert masking poisoned entries), otherwise accumulate with one
            # async on-device add per dispatch — the batch-count correction
            # for masked entries happens at pass end from the guard delta
            if not guard_on:
                contrib = costs.sum() if costs is not None else cost
                cost_sum_dev = (
                    contrib if cost_sum_dev is None else cost_sum_dev + contrib
                )
            stepped += k
            steps_since_poll += k
            suppress = False
            if guard_on and steps_since_poll >= self.guard_check_every:
                steps_since_poll = 0
                new = self._poll_guard(pass_id, idx_last, save_dir)
                # per-step polling of an unfused step: the window IS this
                # batch, so restore the old event contract — a poisoned batch
                # joins neither cost nor events nor the log line. Wider
                # windows still deliver the dispatch's event (its lazy .cost
                # may read non-finite; see events.EndIteration).
                suppress = bool(new) and k == 1 and self.guard_check_every == 1
            if suppress:
                return
            event_handler(EndIteration(pass_id, idx_last, cost, extras))
            if idx_last % log_period < k:  # window crossed a log_period mark
                flush_log()
                cost.copy_to_host_async()  # start D2H without blocking
                pending_log = (pass_id, idx_last, cost)

        def flush_pending() -> None:
            """Run buffered (ungrouped) batches through single-step
            dispatches — the trailing-remainder / shape-churn path."""
            nonlocal boundary, pending_sig
            for idx, b in pending:
                dispatch(idx, idx, b, 1)
            if pending:
                boundary = pending[-1][0] + 1
                del pending[:]
            pending_sig = None

        for raw in reader():
            k_item = raw.k if isinstance(raw, StackedBatch) else 1
            idx0 = logical
            logical += k_item
            if preempt.requested():
                # dispatch boundary: the previous step completed; drain —
                # checkpoint (mid-pass) and raise Preempted. The current raw
                # batch and any still-buffered ones are unprocessed and
                # replay after resume. Inside a replayed prefix the restored
                # state already holds resume_skip batches — never report
                # fewer, or the next resume would re-apply some of them.
                done = boundary
                if resume_mid and pass_id == resume_pass:
                    done = max(done, resume_skip)
                self._drain_preempt(
                    save_dir, pass_id, done, keep_last_n, async_checkpoint
                )
            if self.state is not None and preempt.resize_requested():
                # elastic resize at the same boundary discipline, but
                # COOPERATIVE: buffered batches flush on the old mesh first
                # (they were padded for its data axis), then _drain_resize
                # checkpoints, passes the fleet barrier, re-shards, and
                # returns — the current raw batch runs on the NEW mesh
                flush_pending()
                done = boundary
                if resume_mid and pass_id == resume_pass:
                    done = max(done, resume_skip)
                self._drain_resize(
                    save_dir, pass_id, done, keep_last_n, async_checkpoint,
                    resize_barrier,
                )
                rebind = getattr(reader, "rebind_parallel", None)
                if rebind is not None:
                    # a DevicePrefetcher keeps padding/sharding for the mesh
                    # it was built with — point it at the post-resize plan so
                    # only its <= depth in-flight batches take the straggler
                    # rebuild path, not the rest of the run (no-op when the
                    # resize was rejected or claimed elsewhere)
                    rebind(self.parallel)
                if cost_sum_dev is not None and self.parallel is not None:
                    # migrate the pass-cost accumulator: an array committed
                    # to the old mesh cannot join new-mesh computations
                    cost_sum_dev = self.parallel.replicate(cost_sum_dev)
            if (
                resume_skip
                and pass_id == resume_pass
                and idx0 + k_item <= resume_skip
            ):
                # replayed prefix of the preempted pass: these batches are
                # already folded into the restored state — consume the
                # (deterministic) reader past them without stepping
                boundary = logical
                continue
            if isinstance(raw, StackedBatch):
                # prefetcher-stacked group: device-resident [K, B, ...] slots
                if self.state is None:
                    self.init_state({k: v[0] for k, v in raw.items()})
                    if resume_pending:  # deferred auto-resume load
                        self.load(save_dir, resume_pass)
                        self._known_good_pass = (save_dir, resume_pass)
                        resume_pending = False
                if self._step_fn is None:
                    self._step_fn = self._make_step()
                flush_pending()  # keep update order = reader order
                skip = 0
                if resume_skip and pass_id == resume_pass and idx0 < resume_skip:
                    skip = resume_skip - idx0  # group straddles the boundary
                mismatched = (
                    self._resized
                    and self.parallel is not None
                    and not self.parallel.is_sharded_batches(dict(raw))
                )
                if skip or mismatched:
                    for j in range(skip, k_item):
                        b = {k: v[j] for k, v in raw.items()}
                        if mismatched:
                            # post-resize straggler from a prefetcher still
                            # bound to the OLD mesh: its slots are committed
                            # to old-mesh devices and padded to the old
                            # shard multiple — rebuild each sub-batch on
                            # host and re-pad/re-shard for the current plan
                            # instead of feeding the new compiled program
                            # incompatible arrays
                            b = {k: np.asarray(v) for k, v in b.items()}
                            b = self.parallel.maybe_pad_batch(
                                b,
                                where=f"train batch {idx0 + j} (post-resize)",
                            )
                            if b is None:
                                continue
                            b = self.parallel.shard_batch(b)
                        dispatch(idx0 + j, idx0 + j, b, 1)
                else:
                    # plain dict: the subclass is a marker, not a pytree node
                    dispatch(idx0, idx0 + k_item - 1, dict(raw), k_item)
                boundary = logical
                continue
            batch_id = idx0
            # device batches (from a DevicePrefetcher) arrive fed, sharded
            # and resident — skip the whole host prep leg; dict batches are
            # already feed-ready (e.g. from a DoubleBuffer that ran the
            # feeder on its prefetch thread). Under DataParallel the fast
            # path additionally requires the mesh batch sharding —
            # device-resident but unsharded arrays still go through
            # shard_batch below.
            on_device = is_device_batch(raw) and (
                self.parallel is None or self.parallel.is_sharded_batch(raw)
            )
            if on_device:
                batch = raw  # hostFeed/h2d were stamped by the prefetcher
            else:
                with stats.timer("hostFeed"):
                    batch = (
                        feeder(raw)
                        if feeder is not None and not isinstance(raw, dict)
                        else _coerce_batch(raw)
                    )
            if self.parallel is not None and not on_device:
                # trailing partial batch not divisible by the mesh data axis
                # pads to the next shard multiple with a 0/1 row mask (cost
                # layers zero the pad rows and normalize by the real count),
                # so the batch TRAINS and pass averages/sample counts match
                # the unsharded run — the old drop_last skip lost those
                # samples every pass; only unpaddable ragged batches drop
                batch = self.parallel.maybe_pad_batch(
                    batch, where=f"train batch {batch_id}"
                )
                if batch is None:
                    if not pending:
                        boundary = logical
                    continue
                with stats.timer("h2d"):
                    batch = self.parallel.shard_batch(batch)
            if self.state is None:
                self.init_state(batch)
                if resume_pending:  # deferred auto-resume load
                    self.load(save_dir, resume_pass)
                    self._known_good_pass = (save_dir, resume_pass)
                    resume_pending = False
            if self._step_fn is None:
                self._step_fn = self._make_step()
            if steps_per_dispatch == 1:
                dispatch(batch_id, batch_id, batch, 1)
                boundary = logical
                continue
            # K-step grouping: buffer same-shape batches until K are ready,
            # then stack them into one fused scan dispatch. A shape change
            # flushes the buffer through single steps first (stacking needs
            # homogeneous shapes, and update order must follow reader order).
            sig = stats.batch_signature(batch)
            if pending and sig != pending_sig:
                flush_pending()
            pending.append((batch_id, batch))
            pending_sig = sig
            if len(pending) == steps_per_dispatch:
                stacked = _stack_batches([b for _, b in pending])
                if self.parallel is not None:
                    stacked = self.parallel.shard_batches(stacked)
                dispatch(pending[0][0], pending[-1][0], stacked,
                         steps_per_dispatch)
                boundary = pending[-1][0] + 1
                del pending[:]
                pending_sig = None
        flush_pending()  # trailing remainder: fewer than K batches left
        if self._resize_mark is not None:
            # resize landed at the pass's last boundary — no dispatch after
            # it; close the split with the (near-zero) resume leg here
            self._note_resize_resumed()
        # final guard poll: the bounded reaction window never crosses a pass
        # boundary (the pass-end checkpoint must not absorb unexamined NaNs)
        if guard_on and self.state is not None:
            self._poll_guard(pass_id, max(logical - 1, 0), save_dir)
        flush_log()
        n_diverged = self._diverged_seen - pass_div0
        n_batches = stepped - n_diverged
        if guard_on and self.state is not None:
            cost_sum_dev = self.state["cost_acc"]  # step-accumulated, masked
        metrics: Dict[str, Any] = {
            "avg_cost": (
                # sync-ok: the single pass-end fetch of the on-device sum
                float(cost_sum_dev) / n_batches
                if n_batches and cost_sum_dev is not None
                else 0.0
            ),
            "batches": n_batches,
            "pass_seconds": time.time() - t0,
            "shape_signatures": stats.RECOMPILES.pass_signatures(),
            "divergence_events": n_diverged,
            "padded_batches": (
                stats.DATA_EVENTS.get("padded_batches") - pass_pad0
            ),
        }
        pass_resizes = self._resize_log[pass_rz0:]
        if pass_resizes:
            # elastic resize observability: epochs completed this pass and
            # their drain/re-shard/resume latency split (chaos_bench --mode
            # resize reads these; the fleet aggregate gets the same numbers
            # via obs_metrics.observe_resize on the heartbeat snapshot)
            metrics["resize_epochs"] = len(pass_resizes)
            metrics["resizes"] = pass_resizes
        if self.parallel is not None and self.state is not None:
            # memory/comms observability for the sharded update: per-chip
            # resident bytes from sharding METADATA (no device sync — hot-loop
            # discipline holds, this is pass-end bookkeeping), modeled
            # collective bytes from the updater, HBM peak where the backend
            # reports it (TPU memory_stats; {} on CPU)
            metrics["param_bytes"] = stats.per_chip_tree_bytes(
                self.state["params"]
            )
            metrics["opt_state_bytes"] = stats.per_chip_tree_bytes(
                self.state["opt"]
            )
            metrics["collective_bytes_per_step"] = (
                self.updater.collective_bytes_per_step(steps_per_dispatch)
            )
            detail = self.updater.collective_bytes_detail(steps_per_dispatch)
            if detail:
                # per-leg (scatter/gather) x mode (zero1/2/3) x dtype
                # breakdown of the modeled collective traffic
                metrics["collective_bytes_detail"] = detail
            hbm = stats.device_memory_stats()
            if hbm.get("peak_bytes_in_use"):
                metrics["peak_hbm_bytes"] = hbm["peak_bytes_in_use"]
        if stats.GLOBAL_STATS.enabled:
            log.info("pass %d %s", pass_id, stats.RECOMPILES.report())
        # span-ok: whole-pass span recorded once at pass end (ring buffer
        # write from already-measured wall-clock; no per-step work)
        trace.record_span(
            "train.pass", int(t0 * 1e6), time.time_ns() // 1000,
            attrs={"pass": pass_id, "batches": n_batches},
        )
        self.updater.finish_pass()
        if test_reader is not None:
            metrics["test_cost"] = self.test(test_reader, feeder)["cost"]
        if save_dir is not None:
            self.save(
                save_dir, pass_id, keep_last_n=keep_last_n,
                async_=async_checkpoint,
            )
            self._known_good_pass = (save_dir, pass_id)
        event_handler(EndPass(pass_id, metrics))
        return resume_pending

    def _poll_guard(
        self,
        pass_id: int,
        batch_id: int,
        save_dir: Optional[str],
        react: bool = True,
    ) -> int:
        """Divergence-guard poll: read the device-resident cumulative
        `diverged` counter (the ONE sanctioned guard sync) and react to the
        delta since the last poll. The in-step guard already reverted every
        poisoned update on device, so by the time the host learns about a
        window's divergences the state is clean — the reaction here is
        policy, not protection. Returns the number of new events."""
        with trace.span("train.guard_poll", batch=batch_id):
            d = int(self.state["diverged"])  # sync-ok: the guard-poll site
        new = d - self._diverged_seen
        self._diverged_seen = d
        if new <= 0:
            return 0
        stats.FT_EVENTS.incr("divergence", new)
        if not react:
            # preempt drain: record the events but do not rollback/raise —
            # the in-step guard already protected the checkpointed state
            log.warning(
                "divergence guard: %d non-finite step cost(s) detected while "
                "draining at pass %d batch %d — updates were reverted on "
                "device; no policy reaction during the drain",
                new, pass_id, batch_id,
            )
            return new
        if self.divergence_policy == "raise":
            raise DivergenceError(
                f"non-finite cost in {new} step(s) within the guard window "
                f"ending at pass {pass_id} batch {batch_id}; every poisoned "
                f"update was rolled back to its pre-step state on device"
            )
        if self.divergence_policy == "rollback":
            self._rollback(save_dir, pass_id, batch_id)
        else:
            log.warning(
                "divergence guard: non-finite cost in %d step(s) in the "
                "window ending at pass %d batch %d — poisoned updates were "
                "skipped on device", new, pass_id, batch_id,
            )
        return new

    def _drain_preempt(
        self,
        save_dir: Optional[str],
        pass_id: int,
        batches_done: int,
        keep_last_n: Optional[int],
        async_checkpoint: bool = False,
    ) -> None:
        """Preemption drain at a dispatch boundary: persist a mid-pass
        checkpoint (CRC-valid, `latest`-pointed) unless the grace budget is
        already spent, then raise Preempted. The save syncs the device, so
        the checkpoint holds the state AFTER the just-finished step; with
        async_checkpoint the writer is waited on before raising, so the
        exit-77 checkpoint is durable before the process dies."""
        guard = preempt.get()
        saved: Optional[str] = None
        if self.state is not None and self.divergence_policy is not None:
            # fold any unexamined guard window into telemetry before the
            # state is persisted (no policy reaction mid-drain)
            self._poll_guard(pass_id, batches_done, save_dir, react=False)
        if self.state is not None and save_dir is not None:
            if guard.deadline_passed():
                log.warning(
                    "preempt drain at pass %d batch %d: grace budget (%.1fs) "
                    "already spent — exiting WITHOUT a mid-pass checkpoint; "
                    "resume replays from the last durable one",
                    pass_id, batches_done, guard.grace_s,
                )
            else:
                saved = self.save(
                    save_dir, pass_id, keep_last_n=keep_last_n,
                    mid_pass_batches=batches_done, async_=async_checkpoint,
                )
                if self._ckpt_writer is not None:
                    self._ckpt_writer.wait()  # durable before exit 77
                self._known_good_pass = (save_dir, pass_id)
        stats.FT_EVENTS.incr("preempt_drain")
        log.warning(
            "preempt drain: stopping at pass %d batch %d (%s)",
            pass_id, batches_done,
            f"checkpointed to {saved}" if saved else "no checkpoint",
        )
        raise Preempted(pass_id, batches_done, saved, guard.reason)

    # -- elastic resize (ISSUE 8) --------------------------------------------
    def resize_to(self, world: int, devices: Optional[Sequence] = None) -> None:
        """Re-shard the LIVE train state onto a mesh whose data axis spans
        `world` chips — the elastic-resize seam. Values are preserved
        exactly: params/states/counters are replicated (placement-only move),
        and optimizer slots cross through the updater's canonical per-param
        layout (PR 5's checkpoint-portability seam) before re-flattening for
        the new shard count, so a resized run resumes bitwise from where the
        old mesh stopped. Compiled step/eval programs are dropped and rebuilt
        lazily for the new mesh. Composes with shard_update (the
        ShardedUpdater rebinds its [n, chunk] geometry) and K-step dispatch
        (the multi-step program rebuilds too)."""
        assert self.state is not None, "resize_to needs live state"
        if self.parallel is None:
            raise ValueError(
                "resize_to needs a DataParallel trainer "
                "(SGDTrainer(parallel=...)): there is no mesh to re-shape"
            )
        from paddle_tpu.core.init_ctx import detach_compilation_cache
        from paddle_tpu.parallel import DataParallel
        from paddle_tpu.parallel.mesh import resize_mesh

        old = self.parallel
        new_mesh = resize_mesh(old.mesh, old.batch_axis, world, devices)
        new_parallel = DataParallel(
            new_mesh, batch_axis=old.batch_axis, param_attrs=old.param_attrs,
            rules=old.rules,
        )
        # A resized process must never again execute a persistent-cache-
        # DESERIALIZED multi-device program: the re-shard's eager programs
        # and the train loop's small unsalted helpers (cost-sum adds) repeat
        # byte-identically across trainer generations, and on jax 0.4.37
        # CPU a deserialized one corrupts memory or segfaults (see __init__
        # _cache_salt note). Sticky by design — a scoped opt-out around the
        # re-shard alone proved insufficient.
        detach_compilation_cache("elastic resize")
        # canonical layout is the portable waypoint: gather ZeRO-flat
        # slots — and zero3's flat params — back to parameter shapes on
        # the OLD updater...
        canonical = self.updater.to_canonical(self.state["opt"])
        params_canonical = self.updater.params_to_canonical(
            self.state["params"]
        )
        # model averages mirror the param layout (flat under zero3), so
        # they cross the resize through the same seam (identity otherwise)
        avg_canonical = (
            self.updater.params_to_canonical(self.state["avg"]["avg"])
            if self.state.get("avg")
            else None
        )
        if faults.get().fire("reshard_kill"):
            # chaos hook: the process dies mid-re-shard — after the
            # drain checkpoint, before the new mesh runs; auto_resume
            # must replay the pass from the drained boundary on the new
            # world size
            raise faults.InjectedKill("injected reshard_kill (chaos)")
        # ...then re-flatten for the NEW shard count and place every
        # leaf on its new-mesh sharding (ZeRO leaves land directly
        # 1/n-resident). rebind derives geometry from CANONICAL shapes.
        new_updater = self.updater.rebind(new_parallel, params_canonical)
        state = dict(self.state)
        state["opt"] = new_updater.from_canonical(canonical)
        state["params"] = new_updater.params_from_canonical(params_canonical)
        if avg_canonical is not None:
            state["avg"] = {
                **state["avg"],
                "avg": new_updater.params_from_canonical(avg_canonical),
            }
        self.parallel = new_parallel
        self.updater = new_updater
        self.state = new_parallel.shard_state(
            state,
            opt_sharding=new_updater.opt_leaf_sharding,
            param_sharding=new_updater.param_leaf_sharding,
        )
        self._step_fn = None
        self._multi_fn = None
        self._eval_fn = None
        self._resized = True

    def _drain_resize(
        self,
        save_dir: Optional[str],
        pass_id: int,
        batches_done: int,
        keep_last_n: Optional[int],
        async_checkpoint: bool,
        barrier: Optional[Callable] = None,
    ) -> None:
        """Cooperative resize drain at a dispatch boundary (NO process exit):
        fold any open guard window, persist a durable mid-pass checkpoint (a
        crash during the re-shard resumes from exactly this boundary), pass
        the fleet drain barrier (when master-coordinated), re-shard onto the
        new world size, and return to the train loop — the interrupted pass
        continues on the new mesh with the very next batch."""
        req = preempt.get().take_resize()
        if req is None:
            return  # another poller claimed it
        if self.parallel is None:
            log.warning(
                "resize request (%s) ignored: this trainer has no "
                "DataParallel mesh to re-shape", req.reason,
            )
            return
        if self.state is not None and self.divergence_policy is not None:
            # unexamined guard window folds into telemetry before the state
            # crosses the mesh boundary (no policy reaction mid-drain)
            self._poll_guard(pass_id, batches_done, save_dir, react=False)
        saved: Optional[str] = None
        if self.state is not None and save_dir is not None:
            saved = self.save(
                save_dir, pass_id, keep_last_n=keep_last_n,
                mid_pass_batches=batches_done, async_=async_checkpoint,
            )
            if self._ckpt_writer is not None:
                self._ckpt_writer.wait()  # durable BEFORE the mesh moves
            self._known_good_pass = (save_dir, pass_id)
        if barrier is None:
            # local mode has no _drain_barrier leg, so the stall site hooks
            # here; fleet mode stalls inside the barrier itself (one hook
            # point per drain, never both)
            faults.maybe_stall("resize_drain_stall")
            world = req.world
        else:
            # fleet mode: ack `resize_drained` and block until the master's
            # go (every live trainer drained or was evicted); the returned
            # world supersedes the announced one after membership churn
            world = int(barrier(req, pass_id, batches_done))
        t_drained = time.monotonic()
        trace.span_from_monotonic(
            "train.resize.drain", req.requested_at,
            attrs={"epoch": req.epoch, "pass": pass_id, "batch": batches_done},
        )
        stats.FT_EVENTS.incr("resize_drain")
        if world == self.parallel.data_axis_size:
            # drain-only epoch (membership churn cancelled out, or the
            # fleet decided the size this trainer already runs): nothing to
            # re-shard — and no reason to pay the irreversible compile-cache
            # detach or a recompile for a no-op
            log.info(
                "resize epoch %d: already at world %d — drain-only, no "
                "re-shard", req.epoch, world,
            )
        else:
            try:
                self.resize_to(world)
            except ValueError as e:
                # a bad announce (e.g. join/evict policy counting TRAINERS
                # on a host without that many devices) must reject the
                # resize, not kill a drained-and-checkpointed trainer
                # mid-pass; training continues on the current mesh
                stats.FT_EVENTS.incr("resize_rejected")
                log.error(
                    "resize epoch %d to world=%d rejected: %s — continuing "
                    "the pass on the current %d-chip mesh",
                    req.epoch, world, e, self.parallel.data_axis_size,
                )
                return
        t_resharded = time.monotonic()
        trace.span_from_monotonic(
            "train.resize.reshard", t_drained, attrs={"world": world},
        )
        log.warning(
            "resize drain at pass %d batch %d (%s): %s; data axis now %d "
            "chip(s) (epoch %d) — resuming the interrupted pass",
            pass_id, batches_done, req.reason,
            f"checkpointed to {saved}" if saved else "no checkpoint",
            world, req.epoch,
        )
        self._resize_mark = {
            "epoch": req.epoch,
            "world": world,
            "pass": pass_id,
            "batch": batches_done,
            "drain_s": t_drained - req.requested_at,
            "reshard_s": t_resharded - t_drained,
            "t_resharded": t_resharded,
        }

    def _note_resize_resumed(self) -> None:
        """Close out an in-flight resize once the first post-re-shard
        dispatch returned (or at pass end when the resize was the pass's
        last boundary): records the resume leg of the latency split, the
        resize span/metrics, and the per-pass log entry."""
        m, self._resize_mark = self._resize_mark, None
        resume_s = time.monotonic() - m["t_resharded"]
        trace.span_from_monotonic(
            "train.resize.resume", m["t_resharded"],
            attrs={"epoch": m["epoch"], "world": m["world"]},
        )
        split = {
            "drain": m["drain_s"], "reshard": m["reshard_s"],
            "resume": resume_s,
        }
        obs_metrics.observe_resize(split)
        stats.FT_EVENTS.incr("resize_epoch")
        self._resize_log.append({
            "epoch": m["epoch"],
            "world": m["world"],
            "pass": m["pass"],
            "batch": m["batch"],
            "drain_s": round(split["drain"], 6),
            "reshard_s": round(split["reshard"], 6),
            "resume_s": round(split["resume"], 6),
        })
        log.info(
            "resize epoch %d complete: world=%d drain=%.3fs reshard=%.3fs "
            "resume=%.3fs", m["epoch"], m["world"], split["drain"],
            split["reshard"], split["resume"],
        )

    def _rollback(self, save_dir: Optional[str], pass_id: int, batch_id: int) -> None:
        """Divergence rollback: restore the newest valid checkpoint and halve
        the LR multiplier; with no checkpoint to return to, degrade to
        skip_batch (the in-step guard already protected the state)."""
        latest: Optional[int] = None
        if save_dir is not None and self._ckpt_writer is not None:
            # an async save of THIS trainer may still be in flight — the scan
            # and the load below must only ever see completed writes
            self._ckpt_writer.wait()
        if save_dir is not None:
            # last checkpoint this trainer wrote/loaded needs no CRC re-scan
            # (a stream of NaN batches would otherwise re-read the whole
            # checkpoint set once per diverged step)
            if self._known_good_pass and self._known_good_pass[0] == save_dir:
                latest = self._known_good_pass[1]
            else:
                latest = ckpt_mod.find_latest_valid_pass(save_dir)
        if latest is None:
            log.warning(
                "divergence rollback at pass %d batch %d: no valid checkpoint "
                "under %r — falling back to skipping the batch",
                pass_id, batch_id, save_dir,
            )
            return
        cur_scale = float(self.state["lr_scale"])
        try:
            self.load(save_dir, latest)
        except (OSError, ValueError):
            # the remembered checkpoint rotted on disk — fall back to a scan
            self._known_good_pass = None
            latest = ckpt_mod.find_latest_valid_pass(save_dir)
            if latest is None:
                log.warning(
                    "divergence rollback at pass %d batch %d: no valid "
                    "checkpoint under %r — falling back to skipping the batch",
                    pass_id, batch_id, save_dir,
                )
                return
            self.load(save_dir, latest)
        # halve from the LOWER of the live and checkpointed scales, so
        # back-to-back rollbacks onto the same checkpoint keep compounding
        # (0.5 → 0.25 → …) instead of resetting to the stored value
        self.state["lr_scale"] = jnp.asarray(
            min(cur_scale, float(self.state["lr_scale"])) * 0.5, jnp.float32
        )
        stats.FT_EVENTS.incr("divergence_rollback")
        log.warning(
            "divergence rollback at pass %d batch %d: restored pass-%05d, "
            "lr_scale now %g", pass_id, batch_id, latest,
            float(self.state["lr_scale"]),
        )

    def test(self, reader: Callable, feeder: Optional[Callable] = None) -> Dict[str, Any]:
        """Tester analog (paddle/trainer/Tester.cpp): average cost over a reader."""
        assert self.state is not None, "call train() or init_state() first"
        if self._eval_fn is None:
            self._eval_fn = self._make_eval()
        total, n = 0.0, 0
        for raw in reader():
            on_device = is_device_batch(raw) and (
                self.parallel is None or self.parallel.is_sharded_batch(raw)
            )
            batch = (
                raw
                if on_device
                else feeder(raw)
                if feeder is not None and not isinstance(raw, dict)
                else _coerce_batch(raw)
            )
            if self.parallel is not None and not on_device:
                # same pad+mask treatment as training: the masked cost is
                # the mean over REAL rows only
                batch = self.parallel.maybe_pad_batch(batch, where="test batch")
                if batch is None:
                    continue
                batch = self.parallel.shard_batch(batch)
            cost, _ = self._eval_fn(self.state, batch)
            if SAMPLE_MASK_KEY in batch:
                # padded batch (here or on a prefetcher worker): real rows
                # only — the masked cost is already the mean over them. The
                # sum runs as an eager device op so a mesh-sharded mask works
                # on multi-host too (the result is a replicated, addressable
                # scalar; np.asarray on the global mask would raise there)
                bs = int(jnp.sum(jnp.asarray(batch[SAMPLE_MASK_KEY])))
            else:
                bs = _batch_size(batch)
            total += float(cost) * bs
            n += bs
        return {"cost": total / max(n, 1), "samples": n}

    def save(
        self,
        save_dir: str,
        pass_id: int,
        keep_last_n: Optional[int] = None,
        mid_pass_batches: Optional[int] = None,
        async_: bool = False,
    ) -> str:
        """Raw params + optimizer + averaging state are all persisted so
        load() is a true resume; deployment-time averaged weights are
        recoverable via ModelAverage.averaged_params on the loaded state.

        mid_pass_batches marks a preemption-drain save: the pass is only
        applied through that many batches, and auto-resume replays the rest
        of it instead of skipping to the next pass.

        async_=True is the zero-stall path: the state is copied to host with
        non-blocking fetches (copy_to_host_async per leaf, so the D2H
        transfers overlap each other), then npz/CRC/v1-format/manifest/
        retention run on a background writer thread, double-buffered with at
        most one snapshot in flight. The returned path is durable only after
        checkpoint_wait(); train()/load()/the preempt drain invoke that
        barrier themselves."""
        assert self.state is not None
        # the checkpoint span covers what the TRAINING THREAD pays: the full
        # write when synchronous, only the D2H fetch + enqueue when async
        with trace.span("train.checkpoint", pass_id=pass_id, is_async=async_):
            # checkpoints always store the CANONICAL per-param layout: a
            # ShardedUpdater gathers its flat [n, chunk] slot/EF shards back
            # to parameter shapes here — and the Zero3Updater its flat
            # PARAMS too — so the same pass dir resumes under any
            # shard_update mode (and across device counts) bitwise
            params_store = self.updater.params_to_canonical(
                self.state["params"]
            )
            opt_tree = {"opt": self.updater.to_canonical(self.state["opt"])}
            if self.state["avg"]:
                opt_tree["avg"] = {
                    **self.state["avg"],
                    "avg": self.updater.params_to_canonical(
                        self.state["avg"]["avg"]
                    ),
                }
            extra_meta = {
                "samples": int(self.state["samples"]),
                "lr_scale": float(self.state["lr_scale"]),
                # world-size provenance: canonical checkpoints LOAD across
                # world sizes (the resize story), but load() uses this to
                # give a precise error when a non-canonical/foreign opt tree
                # sneaks in with the wrong shard count
                "world_size": (
                    self.parallel.data_axis_size
                    if self.parallel is not None else 1
                ),
            }
            if mid_pass_batches is not None:
                extra_meta["mid_pass"] = True
                extra_meta["batches_done"] = int(mid_pass_batches)
            if not async_:
                return ckpt_mod.save_pass(
                    save_dir,
                    pass_id,
                    params_store,
                    self.state["states"],
                    opt_tree,
                    extra_meta=extra_meta,
                    keep_last_n=keep_last_n,
                )
            if self._ckpt_writer is None:
                self._ckpt_writer = ckpt_mod.AsyncCheckpointer()
            with stats.timer("ckptFetch"):
                params_np = _fetch_host_tree(params_store)
                states_np = _fetch_host_tree(self.state["states"])
                opt_np = _fetch_host_tree(opt_tree)
            return ckpt_mod.save_pass_async(
                self._ckpt_writer,
                save_dir,
                pass_id,
                params_np,
                states_np,
                opt_np,
                extra_meta=extra_meta,
                keep_last_n=keep_last_n,
            )

    def checkpoint_wait(self) -> None:
        """Durability barrier for async saves: returns once no checkpoint
        write is in flight, re-raising any writer failure. No-op when async
        checkpointing was never used."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.wait()

    def load(self, save_dir: str, pass_id: Optional[int] = None) -> None:
        """Resume values, optimizer slots (when the structure matches) and the
        samples counter from a checkpoint — a true resume, unlike the v1
        reference which checkpoints only parameter values (SURVEY §5
        'Optimizer state ... is not checkpointed in v1')."""
        assert self.state is not None, "init_state() with a sample batch first"
        self.checkpoint_wait()  # never read a checkpoint that is mid-write
        params, states, opt_flat, manifest = ckpt_mod.load_pass(
            save_dir, pass_id,
            # lazy canonical template: only the legacy v1-binary branch needs
            # shapes, and building them under zero3 would eagerly gather the
            # flat-sharded params (a transient full-model footprint on the
            # COMMON native-format resume path otherwise)
            params_template=lambda: self.updater.params_to_canonical(
                self.state["params"]
            ),
        )
        self.state["params"] = self.updater.params_from_canonical(
            {k: jnp.asarray(v) for k, v in params.items()}
        )
        if states:
            self.state["states"] = {k: jnp.asarray(v) for k, v in states.items()}
        if opt_flat:
            # restore against the canonical layout (what save() wrote), then
            # re-flatten for a ShardedUpdater — identity for the others
            template = {"opt": self.updater.to_canonical(self.state["opt"])}
            if self.state["avg"]:
                template["avg"] = {
                    **self.state["avg"],
                    "avg": self.updater.params_to_canonical(
                        self.state["avg"]["avg"]
                    ),
                }
            # pin the cross-world-size contract: canonical checkpoints load
            # on ANY world size, so a shape mismatch here means the opt tree
            # was written as raw per-shard state (pre-canonical or foreign)
            # — restore_tree would silently keep freshly-initialized slots,
            # which is a wrong resume; fail loudly instead, naming shapes
            # and shard counts
            def _raw_shard_error(reason: str) -> ValueError:
                found_world = manifest.get("extra", {}).get("world_size")
                mine = (
                    self.parallel.data_axis_size
                    if self.parallel is not None else 1
                )
                return ValueError(
                    f"checkpoint under {save_dir!r} holds optimizer state "
                    f"that does not match this trainer's canonical layout: "
                    f"{reason}. The checkpoint records world_size="
                    f"{found_world}, this trainer runs world_size={mine}; "
                    f"canonical checkpoints are world-size-portable, so the "
                    f"opt tree was saved as raw per-shard state — re-export "
                    f"it through the updater's to_canonical seam before "
                    f"resuming"
                )

            def _clip(items):
                more = f" (+{len(items) - 4} more)" if len(items) > 4 else ""
                return items[:4], more

            mism = ckpt_mod.tree_shape_mismatches(template, opt_flat)
            if mism:
                head, more = _clip(mism)
                detail = "; ".join(
                    f"{k}: expected {exp} found {got}"
                    for k, exp, got in head
                )
                raise _raw_shard_error(f"{detail}{more}")
            missing = [
                k for k in ckpt_mod.tree_missing_keys(template, opt_flat)
                if k.startswith("opt")
            ]
            if missing:
                all_opt = ckpt_mod.tree_missing_keys(
                    {"opt": template["opt"]}, {}
                )
                head, more = _clip(missing)
                names = ", ".join(head)
                if len(missing) == len(all_opt):
                    # zero key overlap: restore_tree would restore NOTHING
                    # and the trainer would resume on entirely fresh slots
                    # — the foreign-writer / raw-per-shard failure mode the
                    # shape guard cannot see (no common key to compare)
                    raise _raw_shard_error(
                        f"no entry for {names}{more}, so restore_tree "
                        f"would silently keep freshly-initialized slots"
                    )
                # partial overlap is the documented lenient contract:
                # slots resume when the structure matches, structure new
                # since the save (e.g. momentum turned on) starts fresh —
                # say so instead of doing it silently
                log.warning(
                    "checkpoint %s: optimizer tree has no entry for %s%s; "
                    "those slots start freshly initialized, everything "
                    "else resumes",
                    save_dir, names, more,
                )
            restored = ckpt_mod.restore_tree(template, opt_flat)
            self.state["opt"] = self.updater.from_canonical(restored["opt"])
            if "avg" in restored:
                self.state["avg"] = {
                    **restored["avg"],
                    "avg": self.updater.params_from_canonical(
                        restored["avg"]["avg"]
                    ),
                }
        samples = manifest.get("extra", {}).get("samples")
        if samples is not None:
            self.state["samples"] = jnp.asarray(int(samples), jnp.int32)
        lr_scale = manifest.get("extra", {}).get("lr_scale")
        if lr_scale is not None:
            self.state["lr_scale"] = jnp.asarray(float(lr_scale), jnp.float32)
        if self.parallel is not None:
            # re-establish mesh placement (sharded head weights, replicated
            # or ZeRO-flat slots/params) — plain asarray loads land
            # unsharded otherwise
            self.state = self.parallel.shard_state(
                self.state,
                opt_sharding=self.updater.opt_leaf_sharding,
                param_sharding=self.updater.param_leaf_sharding,
            )


def _stack_batches(batches: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Stack K same-shape feed-ready batches on a new leading K axis for one
    fused scan dispatch. Host batches stack with numpy; device-resident ones
    (e.g. singles from a prefetcher) with jnp so the stack stays on device."""
    first = batches[0]
    stack = jnp.stack if is_device_batch(first) else np.stack
    return {k: stack([b[k] for b in batches]) for k in first}


def _fetch_host_tree(tree: Any) -> Any:
    """Device tree → numpy tree with overlapped D2H: every leaf's transfer is
    started non-blocking first, then the results are gathered — the training
    thread waits only for the DMA, never for file I/O.

    The gather must be a REAL copy (np.array, not np.asarray): on the CPU
    backend asarray can alias the device buffer, and the next pass's donated
    step would overwrite the "snapshot" under the async writer's feet."""
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            leaf.copy_to_host_async()
    return jax.tree.map(
        lambda leaf: np.array(leaf) if isinstance(leaf, jax.Array)
        else np.asarray(leaf),
        tree,
    )


def _batch_size(batch: Dict[str, Any]) -> int:
    for k, v in batch.items():
        if not k.endswith(".lengths"):
            return int(np.shape(v)[0])
    raise ValueError("empty batch")


def _poison_batch(batch: Dict[str, Any]) -> Dict[str, Any]:
    """nan_loss chaos hook: NaN out the first float slot (shape and dtype
    unchanged, so no recompile) — the realistic corrupt-sample fault the
    divergence guard exists for."""
    out = dict(batch)
    for k, v in batch.items():
        if not k.endswith(".lengths") and np.issubdtype(
            np.dtype(getattr(v, "dtype", np.asarray(v).dtype)), np.floating
        ):
            out[k] = v * np.float32("nan")
            return out
    raise ValueError("nan_loss fault: batch has no float slot to poison")
