"""Training driver.

Parity with paddle/trainer: Trainer::train (Trainer.cpp:261) / trainOnePass
(:492) / TrainerInternal::trainOneBatch (TrainerInternal.cpp:66), and the v2
API SGD.train (python/paddle/v2/trainer.py:24,:124).

TPU-native design (SURVEY §7 hard-part (1)): the whole hot loop —
forward, backward, optimizer update, LR schedule, model averaging — is ONE
compiled XLA program per batch shape, with the train state donated so
parameters update in-place in device memory. The reference's per-parameter
UpdateCallback chain is folded into that program. Data parallelism: pass a
`DataParallel` config (paddle_tpu/parallel) and the same step is pjit-sharded
over the mesh data axis; gradients all-reduce over ICI — the ring of
MultiGradientMachine.h:44-157 done by the hardware."""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import stats
from paddle_tpu.data.pipeline import coerce_batch as _coerce_batch
from paddle_tpu.data.pipeline import is_device_batch
from paddle_tpu.nn.graph import Argument, Layer, Network
from paddle_tpu.optim.optimizers import Optimizer
from paddle_tpu.optim.average import ModelAverage
from paddle_tpu.optim import schedules
from paddle_tpu.trainer import checkpoint as ckpt_mod
from paddle_tpu.trainer.events import BeginIteration, BeginPass, EndIteration, EndPass

log = logging.getLogger("paddle_tpu.trainer")

TrainState = Dict[str, Any]  # params / opt / states / avg / samples / rng


class SGDTrainer:
    """v2 `trainer.SGD` analog driving compiled train steps."""

    def __init__(
        self,
        cost: Union[Layer, Sequence[Layer]],
        optimizer: Optimizer,
        extra_outputs: Sequence[Layer] = (),
        schedule: Optional[Callable] = None,
        model_average: Optional[ModelAverage] = None,
        parallel: Optional[Any] = None,  # parallel.DataParallel or None
        updater: Optional[Any] = None,  # parallel.ParameterUpdater
        seed: int = 0,
        remat: Optional[str] = None,  # None | "conv_only" | "full"
    ):
        costs = [cost] if isinstance(cost, Layer) else list(cost)
        self.cost_names = [c.name for c in costs]
        self.extra_names = [e.name for e in extra_outputs]
        self.network = Network(costs + list(extra_outputs))
        self.optimizer = optimizer
        self.remat = remat
        # The ParameterUpdater protocol (ParameterUpdater.h:38) is the seam
        # where parallelism plugs into the trainer: the optimizer application
        # inside the compiled step goes through updater.apply, and host-side
        # pass boundaries go through start_pass/finish_pass (barriers on
        # multi-host). Default: local updater, or the ICI all-reduce updater
        # when a DataParallel mesh is configured.
        if updater is None:
            from paddle_tpu.parallel import IciAllReduceUpdater, SgdLocalUpdater

            updater = (
                IciAllReduceUpdater(optimizer, parallel)
                if parallel is not None
                else SgdLocalUpdater(optimizer)
            )
        self.updater = updater
        self.schedule = schedule or schedules.build(optimizer.learning_rate)
        self.model_average = model_average or ModelAverage(0.0)
        self.parallel = parallel
        self.seed = seed
        self.state: Optional[TrainState] = None
        self._step_fn = None
        self._eval_fn = None

    # -- state ---------------------------------------------------------------
    def init_state(self, sample_batch: Dict[str, Any]) -> TrainState:
        rng = jax.random.PRNGKey(self.seed)
        params, states = self.network.init(rng, sample_batch, train=True)
        self.optimizer.param_attrs = self.network.param_attrs
        state: TrainState = {
            "params": params,
            "opt": self.optimizer.init_state(params),
            "states": states,
            "avg": self.model_average.init_state(params),
            # int32 (not float32): float32 absorbs small increments past 2^24
            # samples, which would freeze LR schedules and the per-step rng
            "samples": jnp.zeros((), jnp.int32),
            "rng": rng,
        }
        if self.parallel is not None:
            # hand the discovered per-param attrs (sharding specs) to the
            # parallel plan before placing the state on the mesh
            if not self.parallel.param_attrs:
                self.parallel.param_attrs = self.network.param_attrs
            state = self.parallel.shard_state(state)
        self.state = state
        return state

    # -- compiled step -------------------------------------------------------
    def _build_step(self):
        """The raw (untraced) train-step function; _make_step jits it and
        make_multi_step scans it."""
        net = self.network
        cost_names = self.cost_names
        extra_names = self.extra_names
        updater = self.updater
        schedule = self.schedule
        avg = self.model_average

        def step(state: TrainState, batch: Dict[str, Any]):
            bs = _batch_size(batch)
            lr = schedule(state["samples"].astype(jnp.float32))
            step_rng = jax.random.fold_in(state["rng"], state["samples"])

            def loss_fn(params):
                outs, new_states = net.apply(
                    params, state["states"], batch, train=True, rng=step_rng
                )
                total = sum(outs[c].value for c in cost_names)
                return total, (outs, new_states)

            if self.remat == "conv_only":
                # bytes lever for bandwidth-bound convnets: keep conv/matmul
                # outputs (tagged "conv_out" in ops/conv.py and ops/linalg.py),
                # recompute the cheap BN/relu/add epilogues in the backward
                # pass instead of round-tripping them through HBM
                loss_fn = jax.checkpoint(
                    loss_fn,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        "conv_out"
                    ),
                )
            elif self.remat == "full":
                loss_fn = jax.checkpoint(loss_fn)

            (cost, (outs, new_states)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state["params"])
            if self.parallel is not None:
                grads, cost = self.parallel.reduce_grads(grads, cost)
            new_params, new_opt = updater.apply(
                grads, state["opt"], state["params"], lr
            )
            new_avg = avg.update(state["avg"], new_params)
            new_state = {
                "params": new_params,
                "opt": new_opt,
                "states": new_states,
                "avg": new_avg,
                "samples": state["samples"] + bs,
                "rng": state["rng"],
            }
            extras = {n: outs[n].value for n in extra_names}
            return new_state, cost, extras

        return step

    def _make_step(self):
        step = self._build_step()
        if self.parallel is not None:
            return self.parallel.compile_step(step)
        return jax.jit(step, donate_argnums=0)

    def make_multi_step(self):
        """K train steps per device dispatch: `multi(state, batches)` where
        every batch slot is stacked on a leading K axis, scanned with
        lax.scan inside ONE compiled program. Returns (new_state, costs[K]).

        This amortizes per-dispatch host latency (dominant on remote-tunnel
        or small-step workloads) and lets XLA overlap the tail of step i with
        the head of step i+1 — the TPU-native analog of the reference's
        compute/comm overlap in ConcurrentRemoteParameterUpdater
        (RemoteParameterUpdater.h:180)."""
        step = self._build_step()

        def multi(state: TrainState, batches: Dict[str, Any]):
            def body(s, b):
                s2, cost, _ = step(s, b)
                return s2, cost

            state, costs = jax.lax.scan(body, state, batches)
            return state, costs

        return jax.jit(multi, donate_argnums=0)

    def _make_eval(self):
        net = self.network
        cost_names = self.cost_names
        extra_names = self.extra_names
        avg = self.model_average

        def evaluate(state: TrainState, batch: Dict[str, Any]):
            params = avg.averaged_params(state["avg"], state["params"])
            outs, _ = net.apply(params, state["states"], batch, train=False)
            total = sum(outs[c].value for c in cost_names)
            extras = {n: outs[n].value for n in extra_names}
            return total, extras

        if self.parallel is not None:
            return self.parallel.compile_eval(evaluate)
        return jax.jit(evaluate)

    # -- public API ----------------------------------------------------------
    def train(
        self,
        reader: Callable,
        num_passes: int = 1,
        event_handler: Optional[Callable] = None,
        feeder: Optional[Callable] = None,
        test_reader: Optional[Callable] = None,
        save_dir: Optional[str] = None,
        log_period: int = 100,
    ) -> TrainState:
        """reader yields batches (lists of samples if feeder given, else dicts
        of arrays). One call = `num_passes` passes (v1 --num_passes)."""
        event_handler = event_handler or (lambda e: None)
        for pass_id in range(num_passes):
            event_handler(BeginPass(pass_id))
            self.updater.start_pass()
            stats.RECOMPILES.start_pass()
            t0 = time.time()
            cost_sum_dev, n_batches = None, 0
            for batch_id, raw in enumerate(reader()):
                # device batches (from a DevicePrefetcher) arrive fed, sharded
                # and resident — skip the whole host prep leg; dict batches
                # are already feed-ready (e.g. from a DoubleBuffer that ran
                # the feeder on its prefetch thread). Under DataParallel the
                # fast path additionally requires the mesh batch sharding —
                # device-resident but unsharded arrays still go through
                # shard_batch below.
                on_device = is_device_batch(raw) and (
                    self.parallel is None or self.parallel.is_sharded_batch(raw)
                )
                if on_device:
                    batch = raw  # hostFeed/h2d were stamped by the prefetcher
                else:
                    with stats.timer("hostFeed"):
                        batch = (
                            feeder(raw)
                            if feeder is not None and not isinstance(raw, dict)
                            else _coerce_batch(raw)
                        )
                if self.parallel is not None and not on_device:
                    if not self.parallel.batch_divisible(batch):
                        # trailing partial batch not divisible by the mesh data
                        # axis — skip it (drop_last semantics), like the
                        # per-thread batch split in MultiGradientMachine
                        log.warning(
                            "skipping batch %d: size not divisible by mesh "
                            "data axis", batch_id,
                        )
                        continue
                    with stats.timer("h2d"):
                        batch = self.parallel.shard_batch(batch)
                if self.state is None:
                    self.init_state(batch)
                if self._step_fn is None:
                    self._step_fn = self._make_step()
                # one distinct signature = one XLA trace+compile of the step;
                # churn past the threshold warns (misconfigured seq_buckets)
                stats.RECOMPILES.record(stats.batch_signature(batch))
                event_handler(BeginIteration(pass_id, batch_id))
                # REGISTER_TIMER_INFO("forwardBackward") parity
                # (TrainerInternal.cpp:94-152); enable via PADDLE_TPU_TIMER.
                # Timing is opt-in, so when enabled we sync the device inside
                # the timer — otherwise it would measure only async dispatch.
                # "forwardBackward" is the device-step segment; with the
                # "hostFeed"/"h2d" timers above it gives the input-pipeline
                # occupancy split without a chip profiler.
                with stats.timer("forwardBackward"):
                    self.state, cost, extras = self._step_fn(self.state, batch)
                    if stats.GLOBAL_STATS.enabled:
                        jax.block_until_ready(cost)
                n_batches += 1
                # accumulate the pass cost ON DEVICE (async scalar add) and
                # hand handlers a lazy event — the device is synced only when
                # a handler reads event.cost or at log_period, so the async
                # dispatch pipeline keeps running between log lines
                cost_sum_dev = cost if cost_sum_dev is None else cost_sum_dev + cost
                event_handler(EndIteration(pass_id, batch_id, cost, extras))
                if batch_id % log_period == 0:
                    log.info(
                        "pass %d batch %d cost=%.6f", pass_id, batch_id, float(cost)
                    )
            metrics: Dict[str, Any] = {
                "avg_cost": (
                    float(cost_sum_dev) / n_batches if n_batches else 0.0
                ),
                "batches": n_batches,
                "pass_seconds": time.time() - t0,
                "shape_signatures": stats.RECOMPILES.pass_signatures(),
            }
            if stats.GLOBAL_STATS.enabled:
                log.info(
                    "pass %d %s", pass_id, stats.RECOMPILES.report()
                )
            self.updater.finish_pass()
            if test_reader is not None:
                metrics["test_cost"] = self.test(test_reader, feeder)["cost"]
            if save_dir is not None:
                self.save(save_dir, pass_id)
            event_handler(EndPass(pass_id, metrics))
        return self.state

    def test(self, reader: Callable, feeder: Optional[Callable] = None) -> Dict[str, Any]:
        """Tester analog (paddle/trainer/Tester.cpp): average cost over a reader."""
        assert self.state is not None, "call train() or init_state() first"
        if self._eval_fn is None:
            self._eval_fn = self._make_eval()
        total, n = 0.0, 0
        for raw in reader():
            on_device = is_device_batch(raw) and (
                self.parallel is None or self.parallel.is_sharded_batch(raw)
            )
            batch = (
                raw
                if on_device
                else feeder(raw)
                if feeder is not None and not isinstance(raw, dict)
                else _coerce_batch(raw)
            )
            if self.parallel is not None and not on_device:
                batch = self.parallel.shard_batch(batch)
            cost, _ = self._eval_fn(self.state, batch)
            bs = _batch_size(batch)
            total += float(cost) * bs
            n += bs
        return {"cost": total / max(n, 1), "samples": n}

    def save(self, save_dir: str, pass_id: int) -> str:
        """Raw params + optimizer + averaging state are all persisted so
        load() is a true resume; deployment-time averaged weights are
        recoverable via ModelAverage.averaged_params on the loaded state."""
        assert self.state is not None
        opt_tree = {"opt": self.state["opt"]}
        if self.state["avg"]:
            opt_tree["avg"] = self.state["avg"]
        return ckpt_mod.save_pass(
            save_dir,
            pass_id,
            self.state["params"],
            self.state["states"],
            opt_tree,
            extra_meta={"samples": int(self.state["samples"])},
        )

    def load(self, save_dir: str, pass_id: Optional[int] = None) -> None:
        """Resume values, optimizer slots (when the structure matches) and the
        samples counter from a checkpoint — a true resume, unlike the v1
        reference which checkpoints only parameter values (SURVEY §5
        'Optimizer state ... is not checkpointed in v1')."""
        assert self.state is not None, "init_state() with a sample batch first"
        params, states, opt_flat, manifest = ckpt_mod.load_pass(
            save_dir, pass_id, params_template=self.state["params"]
        )
        self.state["params"] = {k: jnp.asarray(v) for k, v in params.items()}
        if states:
            self.state["states"] = {k: jnp.asarray(v) for k, v in states.items()}
        if opt_flat:
            template = {"opt": self.state["opt"]}
            if self.state["avg"]:
                template["avg"] = self.state["avg"]
            restored = ckpt_mod.restore_tree(template, opt_flat)
            self.state["opt"] = restored["opt"]
            if "avg" in restored:
                self.state["avg"] = restored["avg"]
        samples = manifest.get("extra", {}).get("samples")
        if samples is not None:
            self.state["samples"] = jnp.asarray(int(samples), jnp.int32)
        if self.parallel is not None:
            # re-establish mesh placement (sharded head weights, replicated
            # slots) — plain asarray loads land unsharded otherwise
            self.state = self.parallel.shard_state(self.state)


def _batch_size(batch: Dict[str, Any]) -> int:
    for k, v in batch.items():
        if not k.endswith(".lengths"):
            return int(np.shape(v)[0])
    raise ValueError("empty batch")
