"""Training driver.

Parity with paddle/trainer: Trainer::train (Trainer.cpp:261) / trainOnePass
(:492) / TrainerInternal::trainOneBatch (TrainerInternal.cpp:66), and the v2
API SGD.train (python/paddle/v2/trainer.py:24,:124).

TPU-native design (SURVEY §7 hard-part (1)): the whole hot loop —
forward, backward, optimizer update, LR schedule, model averaging — is ONE
compiled XLA program per batch shape, with the train state donated so
parameters update in-place in device memory. The reference's per-parameter
UpdateCallback chain is folded into that program. Data parallelism: pass a
`DataParallel` config (paddle_tpu/parallel) and the same step is pjit-sharded
over the mesh data axis; gradients all-reduce over ICI — the ring of
MultiGradientMachine.h:44-157 done by the hardware."""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import faults, preempt, stats
from paddle_tpu.data.pipeline import coerce_batch as _coerce_batch
from paddle_tpu.data.pipeline import is_device_batch
from paddle_tpu.nn.graph import Argument, Layer, Network
from paddle_tpu.optim.optimizers import Optimizer
from paddle_tpu.optim.average import ModelAverage
from paddle_tpu.optim import schedules
from paddle_tpu.trainer import checkpoint as ckpt_mod
from paddle_tpu.trainer.events import BeginIteration, BeginPass, EndIteration, EndPass

log = logging.getLogger("paddle_tpu.trainer")

TrainState = Dict[str, Any]  # params / opt / states / avg / samples / rng

DIVERGENCE_POLICIES = ("skip_batch", "rollback", "raise")


class DivergenceError(RuntimeError):
    """Raised by divergence_policy="raise" when a step cost goes NaN/Inf."""


class Preempted(RuntimeError):
    """Raised by train() after a preemption-notice drain (core/preempt):
    the in-flight step finished and — given a save_dir and remaining grace —
    a CRC-valid mid-pass checkpoint was written. The CLI maps this to exit
    code `preempt.EXIT_PREEMPTED`; a restart with auto_resume=True continues
    from exactly this batch boundary."""

    def __init__(
        self,
        pass_id: int,
        batches_done: int,
        checkpoint_dir: Optional[str],
        reason: Optional[str] = None,
    ):
        self.pass_id = pass_id
        self.batches_done = batches_done
        self.checkpoint_dir = checkpoint_dir
        self.reason = reason
        where = (
            f"checkpoint {checkpoint_dir}" if checkpoint_dir
            else "no checkpoint written"
        )
        super().__init__(
            f"preempted ({reason or 'signal'}) at pass {pass_id} after "
            f"{batches_done} batch(es); {where}"
        )


class SGDTrainer:
    """v2 `trainer.SGD` analog driving compiled train steps."""

    def __init__(
        self,
        cost: Union[Layer, Sequence[Layer]],
        optimizer: Optimizer,
        extra_outputs: Sequence[Layer] = (),
        schedule: Optional[Callable] = None,
        model_average: Optional[ModelAverage] = None,
        parallel: Optional[Any] = None,  # parallel.DataParallel or None
        updater: Optional[Any] = None,  # parallel.ParameterUpdater
        seed: int = 0,
        remat: Optional[str] = None,  # None | "conv_only" | "full"
        divergence_policy: Optional[str] = None,  # skip_batch|rollback|raise
    ):
        costs = [cost] if isinstance(cost, Layer) else list(cost)
        self.cost_names = [c.name for c in costs]
        self.extra_names = [e.name for e in extra_outputs]
        self.network = Network(costs + list(extra_outputs))
        self.optimizer = optimizer
        self.remat = remat
        # The ParameterUpdater protocol (ParameterUpdater.h:38) is the seam
        # where parallelism plugs into the trainer: the optimizer application
        # inside the compiled step goes through updater.apply, and host-side
        # pass boundaries go through start_pass/finish_pass (barriers on
        # multi-host). Default: local updater, or the ICI all-reduce updater
        # when a DataParallel mesh is configured.
        if updater is None:
            from paddle_tpu.parallel import IciAllReduceUpdater, SgdLocalUpdater

            updater = (
                IciAllReduceUpdater(optimizer, parallel)
                if parallel is not None
                else SgdLocalUpdater(optimizer)
            )
        self.updater = updater
        self.schedule = schedule or schedules.build(optimizer.learning_rate)
        self.model_average = model_average or ModelAverage(0.0)
        self.parallel = parallel
        self.seed = seed
        # Divergence guard (SURVEY §5 failure-as-common-case): with a policy
        # set, the compiled step checks jnp.isfinite(cost) and hands back the
        # PRE-step state on NaN/Inf (donation-safe — the select happens inside
        # the same program), so one poisoned batch cannot corrupt params/opt;
        # the host then reacts per policy. None = guard compiled out (the
        # step program and its async dispatch behavior stay byte-identical).
        if divergence_policy is not None and divergence_policy not in DIVERGENCE_POLICIES:
            raise ValueError(
                f"divergence_policy must be one of {DIVERGENCE_POLICIES} or "
                f"None, got {divergence_policy!r}"
            )
        self.divergence_policy = divergence_policy
        self.state: Optional[TrainState] = None
        self._step_fn = None
        self._eval_fn = None
        # (save_dir, pass_id) of the newest checkpoint this trainer wrote or
        # loaded — lets _rollback skip a full CRC re-scan per divergence event
        self._known_good_pass: Optional[tuple] = None

    # -- state ---------------------------------------------------------------
    def init_state(self, sample_batch: Dict[str, Any]) -> TrainState:
        rng = jax.random.PRNGKey(self.seed)
        params, states = self.network.init(rng, sample_batch, train=True)
        self.optimizer.param_attrs = self.network.param_attrs
        state: TrainState = {
            "params": params,
            "opt": self.optimizer.init_state(params),
            "states": states,
            "avg": self.model_average.init_state(params),
            # int32 (not float32): float32 absorbs small increments past 2^24
            # samples, which would freeze LR schedules and the per-step rng
            "samples": jnp.zeros((), jnp.int32),
            # host-adjustable LR multiplier: the rollback divergence policy
            # halves it on every restore (the classic diverged-run response)
            "lr_scale": jnp.ones((), jnp.float32),
            "rng": rng,
        }
        if self.parallel is not None:
            # hand the discovered per-param attrs (sharding specs) to the
            # parallel plan before placing the state on the mesh
            if not self.parallel.param_attrs:
                self.parallel.param_attrs = self.network.param_attrs
            state = self.parallel.shard_state(state)
        self.state = state
        return state

    # -- compiled step -------------------------------------------------------
    def _build_step(self):
        """The raw (untraced) train-step function; _make_step jits it and
        make_multi_step scans it."""
        net = self.network
        cost_names = self.cost_names
        extra_names = self.extra_names
        updater = self.updater
        schedule = self.schedule
        avg = self.model_average

        def step(state: TrainState, batch: Dict[str, Any]):
            bs = _batch_size(batch)
            lr = schedule(state["samples"].astype(jnp.float32)) * state["lr_scale"]
            step_rng = jax.random.fold_in(state["rng"], state["samples"])

            def loss_fn(params):
                outs, new_states = net.apply(
                    params, state["states"], batch, train=True, rng=step_rng
                )
                total = sum(outs[c].value for c in cost_names)
                return total, (outs, new_states)

            if self.remat == "conv_only":
                # bytes lever for bandwidth-bound convnets: keep conv/matmul
                # outputs (tagged "conv_out" in ops/conv.py and ops/linalg.py),
                # recompute the cheap BN/relu/add epilogues in the backward
                # pass instead of round-tripping them through HBM
                loss_fn = jax.checkpoint(
                    loss_fn,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        "conv_out"
                    ),
                )
            elif self.remat == "full":
                loss_fn = jax.checkpoint(loss_fn)

            (cost, (outs, new_states)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state["params"])
            if self.parallel is not None:
                grads, cost = self.parallel.reduce_grads(grads, cost)
            new_params, new_opt = updater.apply(
                grads, state["opt"], state["params"], lr
            )
            new_avg = avg.update(state["avg"], new_params)
            new_state = {
                "params": new_params,
                "opt": new_opt,
                "states": new_states,
                "avg": new_avg,
                "samples": state["samples"] + bs,
                "lr_scale": state["lr_scale"],
                "rng": state["rng"],
            }
            if self.divergence_policy is not None:
                # divergence guard: on a NaN/Inf cost every state leaf —
                # params, opt slots, BN states, samples counter — reverts to
                # its pre-step value, so the poisoned update never lands. The
                # returned (non-finite) cost is the flag the host reads.
                ok = jnp.isfinite(cost)
                new_state = jax.tree.map(
                    lambda new, old: jnp.where(ok, new, old), new_state, state
                )
            extras = {n: outs[n].value for n in extra_names}
            return new_state, cost, extras

        return step

    def _make_step(self):
        step = self._build_step()
        if self.parallel is not None:
            return self.parallel.compile_step(step)
        return jax.jit(step, donate_argnums=0)

    def make_multi_step(self):
        """K train steps per device dispatch: `multi(state, batches)` where
        every batch slot is stacked on a leading K axis, scanned with
        lax.scan inside ONE compiled program. Returns (new_state, costs[K]).

        This amortizes per-dispatch host latency (dominant on remote-tunnel
        or small-step workloads) and lets XLA overlap the tail of step i with
        the head of step i+1 — the TPU-native analog of the reference's
        compute/comm overlap in ConcurrentRemoteParameterUpdater
        (RemoteParameterUpdater.h:180)."""
        step = self._build_step()

        def multi(state: TrainState, batches: Dict[str, Any]):
            def body(s, b):
                s2, cost, _ = step(s, b)
                return s2, cost

            state, costs = jax.lax.scan(body, state, batches)
            return state, costs

        return jax.jit(multi, donate_argnums=0)

    def _make_eval(self):
        net = self.network
        cost_names = self.cost_names
        extra_names = self.extra_names
        avg = self.model_average

        def evaluate(state: TrainState, batch: Dict[str, Any]):
            params = avg.averaged_params(state["avg"], state["params"])
            outs, _ = net.apply(params, state["states"], batch, train=False)
            total = sum(outs[c].value for c in cost_names)
            extras = {n: outs[n].value for n in extra_names}
            return total, extras

        if self.parallel is not None:
            return self.parallel.compile_eval(evaluate)
        return jax.jit(evaluate)

    # -- public API ----------------------------------------------------------
    def train(
        self,
        reader: Callable,
        num_passes: int = 1,
        event_handler: Optional[Callable] = None,
        feeder: Optional[Callable] = None,
        test_reader: Optional[Callable] = None,
        save_dir: Optional[str] = None,
        log_period: int = 100,
        auto_resume: bool = False,
        keep_last_n: Optional[int] = None,
    ) -> TrainState:
        """reader yields batches (lists of samples if feeder given, else dicts
        of arrays). One call = `num_passes` passes (v1 --num_passes).

        auto_resume (needs save_dir): scan save_dir for the newest checkpoint
        that passes CRC — corrupt/partial pass dirs from a crashed save are
        skipped with a warning — restore params/opt/states and the pass and
        sample counters from it, and continue with the next pass. A run
        killed mid-pass and restarted this way replays the interrupted pass
        from its boundary and, with a deterministic reader, produces final
        params bitwise-identical to a never-killed run."""
        event_handler = event_handler or (lambda e: None)
        inj = faults.get()
        resume_pass: Optional[int] = None
        resume_pending = False
        resume_mid = False  # checkpoint is a preemption-drain mid-pass save
        resume_skip = 0  # batches of resume_pass already applied (mid-pass drain)
        if auto_resume and save_dir is not None:
            resume_pass = ckpt_mod.find_latest_valid_pass(save_dir)
            if resume_pass is not None:
                extra = ckpt_mod.pass_manifest(save_dir, resume_pass).get(
                    "extra", {}
                )
                if extra.get("mid_pass"):
                    # preemption-drain checkpoint: pass resume_pass is only
                    # partially applied — replay it from the drained boundary
                    resume_mid = True
                    resume_skip = int(extra.get("batches_done", 0))
                log.info(
                    "auto-resume: restoring from %s/pass-%05d (continuing at "
                    "pass %d%s)", save_dir, resume_pass,
                    resume_pass if resume_mid else resume_pass + 1,
                    f" batch {resume_skip}" if resume_mid else "",
                )
                if self.state is not None:
                    self.load(save_dir, resume_pass)
                    self._known_good_pass = (save_dir, resume_pass)
                else:  # state shapes unknown until the first batch arrives
                    resume_pending = True
        for pass_id in range(num_passes):
            if resume_pass is not None and (
                pass_id < resume_pass
                or (pass_id == resume_pass and not resume_mid)
            ):
                continue  # completed by the run we are resuming
            event_handler(BeginPass(pass_id))
            self.updater.start_pass()
            stats.RECOMPILES.start_pass()
            t0 = time.time()
            cost_sum_dev, n_batches, n_diverged = None, 0, 0
            for batch_id, raw in enumerate(reader()):
                if preempt.requested():
                    # batch boundary: the previous step completed; drain —
                    # checkpoint (mid-pass) and raise Preempted. The current
                    # raw batch is unprocessed and replays after resume.
                    # Inside a replayed prefix the restored state already
                    # holds resume_skip batches — never report fewer, or the
                    # next resume would re-apply some of them.
                    done = batch_id
                    if resume_mid and pass_id == resume_pass:
                        done = max(batch_id, resume_skip)
                    self._drain_preempt(save_dir, pass_id, done, keep_last_n)
                if (
                    resume_skip
                    and pass_id == resume_pass
                    and batch_id < resume_skip
                ):
                    # replayed prefix of the preempted pass: these batches are
                    # already folded into the restored state — consume the
                    # (deterministic) reader past them without stepping
                    continue
                # device batches (from a DevicePrefetcher) arrive fed, sharded
                # and resident — skip the whole host prep leg; dict batches
                # are already feed-ready (e.g. from a DoubleBuffer that ran
                # the feeder on its prefetch thread). Under DataParallel the
                # fast path additionally requires the mesh batch sharding —
                # device-resident but unsharded arrays still go through
                # shard_batch below.
                on_device = is_device_batch(raw) and (
                    self.parallel is None or self.parallel.is_sharded_batch(raw)
                )
                if on_device:
                    batch = raw  # hostFeed/h2d were stamped by the prefetcher
                else:
                    with stats.timer("hostFeed"):
                        batch = (
                            feeder(raw)
                            if feeder is not None and not isinstance(raw, dict)
                            else _coerce_batch(raw)
                        )
                if self.parallel is not None and not on_device:
                    if not self.parallel.batch_divisible(batch):
                        # trailing partial batch not divisible by the mesh data
                        # axis — skip it (drop_last semantics), like the
                        # per-thread batch split in MultiGradientMachine
                        log.warning(
                            "skipping batch %d: size not divisible by mesh "
                            "data axis", batch_id,
                        )
                        continue
                    with stats.timer("h2d"):
                        batch = self.parallel.shard_batch(batch)
                if self.state is None:
                    self.init_state(batch)
                    if resume_pending:  # deferred auto-resume load
                        self.load(save_dir, resume_pass)
                        self._known_good_pass = (save_dir, resume_pass)
                        resume_pending = False
                if self._step_fn is None:
                    self._step_fn = self._make_step()
                if inj.active:
                    if inj.fire("kill"):
                        raise faults.InjectedKill(
                            f"injected kill at pass {pass_id} batch {batch_id}"
                        )
                    if inj.fire("preempt"):
                        # simulated preemption notice (SIGTERM analog): only
                        # sets the drain flag — this batch still steps, the
                        # NEXT boundary checkpoints and exits ("finish the
                        # step" semantics)
                        preempt.get().request(
                            f"injected preempt at pass {pass_id} batch {batch_id}"
                        )
                    if inj.fire("nan_loss"):
                        batch = _poison_batch(batch)
                # one distinct signature = one XLA trace+compile of the step;
                # churn past the threshold warns (misconfigured seq_buckets)
                stats.RECOMPILES.record(stats.batch_signature(batch))
                event_handler(BeginIteration(pass_id, batch_id))
                # REGISTER_TIMER_INFO("forwardBackward") parity
                # (TrainerInternal.cpp:94-152); enable via PADDLE_TPU_TIMER.
                # Timing is opt-in, so when enabled we sync the device inside
                # the timer — otherwise it would measure only async dispatch.
                # "forwardBackward" is the device-step segment; with the
                # "hostFeed"/"h2d" timers above it gives the input-pipeline
                # occupancy split without a chip profiler.
                with stats.timer("forwardBackward"):
                    self.state, cost, extras = self._step_fn(self.state, batch)
                    if stats.GLOBAL_STATS.enabled:
                        jax.block_until_ready(cost)
                if self.divergence_policy is not None and not np.isfinite(
                    float(cost)  # forces a per-step sync — the guard's price
                ):
                    # the step already handed back the pre-step state; react
                    n_diverged += 1
                    stats.FT_EVENTS.incr("divergence")
                    if self.divergence_policy == "raise":
                        raise DivergenceError(
                            f"non-finite cost ({float(cost)}) at pass "
                            f"{pass_id} batch {batch_id}; state rolled back "
                            f"to the pre-step values"
                        )
                    if self.divergence_policy == "rollback":
                        self._rollback(save_dir, pass_id, batch_id)
                    else:
                        log.warning(
                            "divergence guard: non-finite cost at pass %d "
                            "batch %d — batch skipped", pass_id, batch_id,
                        )
                    continue  # poisoned batch joins neither cost nor events
                n_batches += 1
                # accumulate the pass cost ON DEVICE (async scalar add) and
                # hand handlers a lazy event — the device is synced only when
                # a handler reads event.cost or at log_period, so the async
                # dispatch pipeline keeps running between log lines
                cost_sum_dev = cost if cost_sum_dev is None else cost_sum_dev + cost
                event_handler(EndIteration(pass_id, batch_id, cost, extras))
                if batch_id % log_period == 0:
                    log.info(
                        "pass %d batch %d cost=%.6f", pass_id, batch_id, float(cost)
                    )
            metrics: Dict[str, Any] = {
                "avg_cost": (
                    float(cost_sum_dev) / n_batches if n_batches else 0.0
                ),
                "batches": n_batches,
                "pass_seconds": time.time() - t0,
                "shape_signatures": stats.RECOMPILES.pass_signatures(),
                "divergence_events": n_diverged,
            }
            if stats.GLOBAL_STATS.enabled:
                log.info(
                    "pass %d %s", pass_id, stats.RECOMPILES.report()
                )
            self.updater.finish_pass()
            if test_reader is not None:
                metrics["test_cost"] = self.test(test_reader, feeder)["cost"]
            if save_dir is not None:
                self.save(save_dir, pass_id, keep_last_n=keep_last_n)
                self._known_good_pass = (save_dir, pass_id)
            event_handler(EndPass(pass_id, metrics))
        if resume_pending:
            # every requested pass was already checkpointed — nothing ran, so
            # state was never initialized; pull one batch just for shapes and
            # load the final checkpoint so the caller still gets it back
            raw = next(iter(reader()), None)
            if raw is not None:
                on_device = is_device_batch(raw) and (
                    self.parallel is None or self.parallel.is_sharded_batch(raw)
                )
                batch = (
                    raw
                    if on_device
                    else feeder(raw)
                    if feeder is not None and not isinstance(raw, dict)
                    else _coerce_batch(raw)
                )
                if self.parallel is not None and not on_device:
                    batch = self.parallel.shard_batch(batch)
                self.init_state(batch)
                self.load(save_dir, resume_pass)
                self._known_good_pass = (save_dir, resume_pass)
        return self.state

    def _drain_preempt(
        self,
        save_dir: Optional[str],
        pass_id: int,
        batches_done: int,
        keep_last_n: Optional[int],
    ) -> None:
        """Preemption drain at a batch boundary: persist a mid-pass checkpoint
        (CRC-valid, `latest`-pointed) unless the grace budget is already
        spent, then raise Preempted. save() syncs the device, so the
        checkpoint holds the state AFTER the just-finished step."""
        guard = preempt.get()
        saved: Optional[str] = None
        if self.state is not None and save_dir is not None:
            if guard.deadline_passed():
                log.warning(
                    "preempt drain at pass %d batch %d: grace budget (%.1fs) "
                    "already spent — exiting WITHOUT a mid-pass checkpoint; "
                    "resume replays from the last durable one",
                    pass_id, batches_done, guard.grace_s,
                )
            else:
                saved = self.save(
                    save_dir, pass_id, keep_last_n=keep_last_n,
                    mid_pass_batches=batches_done,
                )
                self._known_good_pass = (save_dir, pass_id)
        stats.FT_EVENTS.incr("preempt_drain")
        log.warning(
            "preempt drain: stopping at pass %d batch %d (%s)",
            pass_id, batches_done,
            f"checkpointed to {saved}" if saved else "no checkpoint",
        )
        raise Preempted(pass_id, batches_done, saved, guard.reason)

    def _rollback(self, save_dir: Optional[str], pass_id: int, batch_id: int) -> None:
        """Divergence rollback: restore the newest valid checkpoint and halve
        the LR multiplier; with no checkpoint to return to, degrade to
        skip_batch (the in-step guard already protected the state)."""
        latest: Optional[int] = None
        if save_dir is not None:
            # last checkpoint this trainer wrote/loaded needs no CRC re-scan
            # (a stream of NaN batches would otherwise re-read the whole
            # checkpoint set once per diverged step)
            if self._known_good_pass and self._known_good_pass[0] == save_dir:
                latest = self._known_good_pass[1]
            else:
                latest = ckpt_mod.find_latest_valid_pass(save_dir)
        if latest is None:
            log.warning(
                "divergence rollback at pass %d batch %d: no valid checkpoint "
                "under %r — falling back to skipping the batch",
                pass_id, batch_id, save_dir,
            )
            return
        cur_scale = float(self.state["lr_scale"])
        try:
            self.load(save_dir, latest)
        except (OSError, ValueError):
            # the remembered checkpoint rotted on disk — fall back to a scan
            self._known_good_pass = None
            latest = ckpt_mod.find_latest_valid_pass(save_dir)
            if latest is None:
                log.warning(
                    "divergence rollback at pass %d batch %d: no valid "
                    "checkpoint under %r — falling back to skipping the batch",
                    pass_id, batch_id, save_dir,
                )
                return
            self.load(save_dir, latest)
        # halve from the LOWER of the live and checkpointed scales, so
        # back-to-back rollbacks onto the same checkpoint keep compounding
        # (0.5 → 0.25 → …) instead of resetting to the stored value
        self.state["lr_scale"] = jnp.asarray(
            min(cur_scale, float(self.state["lr_scale"])) * 0.5, jnp.float32
        )
        stats.FT_EVENTS.incr("divergence_rollback")
        log.warning(
            "divergence rollback at pass %d batch %d: restored pass-%05d, "
            "lr_scale now %g", pass_id, batch_id, latest,
            float(self.state["lr_scale"]),
        )

    def test(self, reader: Callable, feeder: Optional[Callable] = None) -> Dict[str, Any]:
        """Tester analog (paddle/trainer/Tester.cpp): average cost over a reader."""
        assert self.state is not None, "call train() or init_state() first"
        if self._eval_fn is None:
            self._eval_fn = self._make_eval()
        total, n = 0.0, 0
        for raw in reader():
            on_device = is_device_batch(raw) and (
                self.parallel is None or self.parallel.is_sharded_batch(raw)
            )
            batch = (
                raw
                if on_device
                else feeder(raw)
                if feeder is not None and not isinstance(raw, dict)
                else _coerce_batch(raw)
            )
            if self.parallel is not None and not on_device:
                batch = self.parallel.shard_batch(batch)
            cost, _ = self._eval_fn(self.state, batch)
            bs = _batch_size(batch)
            total += float(cost) * bs
            n += bs
        return {"cost": total / max(n, 1), "samples": n}

    def save(
        self,
        save_dir: str,
        pass_id: int,
        keep_last_n: Optional[int] = None,
        mid_pass_batches: Optional[int] = None,
    ) -> str:
        """Raw params + optimizer + averaging state are all persisted so
        load() is a true resume; deployment-time averaged weights are
        recoverable via ModelAverage.averaged_params on the loaded state.

        mid_pass_batches marks a preemption-drain save: the pass is only
        applied through that many batches, and auto-resume replays the rest
        of it instead of skipping to the next pass."""
        assert self.state is not None
        opt_tree = {"opt": self.state["opt"]}
        if self.state["avg"]:
            opt_tree["avg"] = self.state["avg"]
        extra_meta = {
            "samples": int(self.state["samples"]),
            "lr_scale": float(self.state["lr_scale"]),
        }
        if mid_pass_batches is not None:
            extra_meta["mid_pass"] = True
            extra_meta["batches_done"] = int(mid_pass_batches)
        return ckpt_mod.save_pass(
            save_dir,
            pass_id,
            self.state["params"],
            self.state["states"],
            opt_tree,
            extra_meta=extra_meta,
            keep_last_n=keep_last_n,
        )

    def load(self, save_dir: str, pass_id: Optional[int] = None) -> None:
        """Resume values, optimizer slots (when the structure matches) and the
        samples counter from a checkpoint — a true resume, unlike the v1
        reference which checkpoints only parameter values (SURVEY §5
        'Optimizer state ... is not checkpointed in v1')."""
        assert self.state is not None, "init_state() with a sample batch first"
        params, states, opt_flat, manifest = ckpt_mod.load_pass(
            save_dir, pass_id, params_template=self.state["params"]
        )
        self.state["params"] = {k: jnp.asarray(v) for k, v in params.items()}
        if states:
            self.state["states"] = {k: jnp.asarray(v) for k, v in states.items()}
        if opt_flat:
            template = {"opt": self.state["opt"]}
            if self.state["avg"]:
                template["avg"] = self.state["avg"]
            restored = ckpt_mod.restore_tree(template, opt_flat)
            self.state["opt"] = restored["opt"]
            if "avg" in restored:
                self.state["avg"] = restored["avg"]
        samples = manifest.get("extra", {}).get("samples")
        if samples is not None:
            self.state["samples"] = jnp.asarray(int(samples), jnp.int32)
        lr_scale = manifest.get("extra", {}).get("lr_scale")
        if lr_scale is not None:
            self.state["lr_scale"] = jnp.asarray(float(lr_scale), jnp.float32)
        if self.parallel is not None:
            # re-establish mesh placement (sharded head weights, replicated
            # slots) — plain asarray loads land unsharded otherwise
            self.state = self.parallel.shard_state(self.state)


def _batch_size(batch: Dict[str, Any]) -> int:
    for k, v in batch.items():
        if not k.endswith(".lengths"):
            return int(np.shape(v)[0])
    raise ValueError("empty batch")


def _poison_batch(batch: Dict[str, Any]) -> Dict[str, Any]:
    """nan_loss chaos hook: NaN out the first float slot (shape and dtype
    unchanged, so no recompile) — the realistic corrupt-sample fault the
    divergence guard exists for."""
    out = dict(batch)
    for k, v in batch.items():
        if not k.endswith(".lengths") and np.issubdtype(
            np.dtype(getattr(v, "dtype", np.asarray(v).dtype)), np.floating
        ):
            out[k] = v * np.float32("nan")
            return out
    raise ValueError("nan_loss fault: batch has no float slot to poison")
