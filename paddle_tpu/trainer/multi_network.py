"""Alternating multi-network training — the GAN demo class.

Parity: paddle/gserver/gradientmachines/MultiNetwork.cpp +
GradientMachineMode.h + the v1_api_demo/gan host loop (gan_trainer.py):
two gradient machines built from configs that share parameter NAMES, where
each phase marks the other side's parameters `is_static` (frozen), and the
host copies shared parameters between machines every iteration
(copy_shared_parameters).

TPU-native shape: each phase is its own SGDTrainer (whole phase step = one
compiled program; frozen params ride through untouched because the optimizer
honors ParamAttr.is_static). Sharing is by parameter name, exactly the v1
convention — after a phase step the updated values are copied into the other
phases' states, device-to-device."""

from __future__ import annotations

from typing import Any, Dict, Optional

from paddle_tpu.trainer.trainer import SGDTrainer


class MultiNetworkTrainer:
    """Coordinate named SGDTrainers whose networks share parameters by name.

    Usage (the gan_conf.py pattern):
        mt = MultiNetworkTrainer({"dis": dis_trainer, "gen": gen_trainer})
        mt.init_state({"dis": dis_batch, "gen": gen_batch})
        cost = mt.step("dis", dis_batch)   # trains dis_*, syncs shared params
        cost = mt.step("gen", gen_batch)   # trains gen_*, syncs shared params
    """

    def __init__(self, trainers: Dict[str, SGDTrainer]):
        assert trainers, "need at least one named trainer"
        self.trainers = dict(trainers)
        self._steps: Dict[str, Any] = {}

    def init_state(self, sample_batches: Dict[str, Any]) -> None:
        for name, tr in self.trainers.items():
            tr.init_state(sample_batches[name])
        # start from ONE consistent copy of every shared parameter: first
        # trainer that owns a name wins (the demo copies gen->dis at start)
        seen: Dict[str, Any] = {}
        for tr in self.trainers.values():
            for k, v in tr.state["params"].items():
                if k in seen:
                    tr.state["params"][k] = seen[k]
                else:
                    seen[k] = v

    def sync_shared(self, src: str) -> None:
        """copy_shared_parameters: push src's current values into every other
        trainer state holding a same-named parameter."""
        src_params = self.trainers[src].state["params"]
        for name, tr in self.trainers.items():
            if name == src:
                continue
            tgt = tr.state["params"]
            for k in tgt:
                if k in src_params:
                    tgt[k] = src_params[k]

    def step(self, phase: str, batch: Any, sync: bool = True):
        """One train step of `phase`'s network, then propagate its updated
        shared parameters to the other phases. Returns the phase cost."""
        tr = self.trainers[phase]
        if phase not in self._steps:
            self._steps[phase] = tr._make_step()
        tr.state, cost, extras = self._steps[phase](tr.state, batch)
        if sync:
            self.sync_shared(phase)
        return cost

    def state_of(self, phase: str):
        return self.trainers[phase].state
