from paddle_tpu.nn.graph import (  # noqa: F401
    Argument,
    Context,
    Layer,
    Network,
    ParamAttr,
    reset_name_scope,
)
from paddle_tpu.nn import activations as activations  # noqa: F401
from paddle_tpu.nn import layers as layers  # noqa: F401
from paddle_tpu.nn import layers3d as layers3d  # noqa: F401
from paddle_tpu.nn import costs as costs  # noqa: F401
from paddle_tpu.nn import struct_costs as struct_costs  # noqa: F401
from paddle_tpu.nn import detection_layers as detection_layers  # noqa: F401
from paddle_tpu.nn import recurrent as recurrent  # noqa: F401
from paddle_tpu.nn import seq_layers as seq_layers  # noqa: F401
from paddle_tpu.nn import attention_layers as attention_layers  # noqa: F401
from paddle_tpu.nn import projections as projections  # noqa: F401
