"""Structured-prediction losses: CTC, CRF, NCE, hierarchical softmax, lambda rank.

Parity targets in the reference:
  - CTCLayer.cpp / LinearChainCTC.cpp / WarpCTCLayer.cpp  → CTCCost (ops/ctc.py)
  - CRFLayer.cpp / LinearChainCRF.cpp                     → CRFCost
  - CRFDecodingLayer.cpp                                  → CRFDecoding
  - NCELayer.cpp (+ MultinomialSampler.cpp)               → NCECost
  - HierarchicalSigmoidLayer.cpp (+ MatrixBitCode.cpp)    → HierarchicalSigmoid
  - CostLayer.cpp LambdaCost                              → LambdaCost

All are scan/vmap formulations compiling into the jitted step — the backward
passes the reference hand-writes (e.g. LinearChainCTC::backward,
LinearChainCRF::backward) come from jax.grad here.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn import init as init_mod
from paddle_tpu.nn.graph import Argument, Context, Layer
from paddle_tpu.ops import crf as crf_ops
from paddle_tpu.ops import ctc as ctc_ops

Array = jax.Array


from paddle_tpu.nn.layers import _attr


def _mean_over_examples(ctx: Context, per_sample: Array) -> Array:
    """Mean over a per-example cost vector honoring Context.sample_mask —
    the [B] 0/1 row validity from a mesh-divisibility-padded batch
    (nn/costs._masked_mean is the dense-cost counterpart): padded rows weigh
    0 and the denominator is the real row count, so the padded batch
    reproduces the unpadded batch's cost and gradients. A per-sample vector
    that is a per-timestep flattening of [B] rows (e.g. NCE over flattened
    sequence steps) repeats the mask per step; layouts that don't divide the
    mask keep the unmasked mean (loudly unmaskable is worse than the old
    drop-the-batch behavior they replace). Without a mask this is exactly
    the jnp.mean these layers always used — bitwise-unchanged."""
    smask = getattr(ctx, "sample_mask", None)
    if smask is None:
        return jnp.mean(per_sample)
    n, b = per_sample.shape[0], smask.shape[0]
    if not b or n % b != 0:
        import logging

        logging.getLogger("paddle_tpu.costs").warning(
            "struct cost cannot apply the pad-row mask: per-sample vector "
            "of %d rows does not divide the [%d] sample mask — the padded "
            "rows join this batch's mean unmasked (duplicates of the last "
            "real row). Size batches divisibly by the mesh data axis to "
            "avoid the bias.", n, b,
        )
        return jnp.mean(per_sample)
    reps = n // b
    w = smask.astype(per_sample.dtype)
    if reps > 1:
        w = jnp.repeat(w, reps)
    denom = jnp.maximum(jnp.sum(smask.astype(jnp.float32)) * reps, 1.0)
    return jnp.sum(per_sample * w) / denom


@LAYERS.register("ctc", "warp_ctc")
class CTCCost(Layer):
    """CTC negative log-likelihood (CTCLayer.cpp; `warp_ctc` is the same math —
    the reference only swaps the kernel provider, hl_warpctc_wrap.cc).

    inputs: (logits_seq, label_seq). logits: [B, T, C]; labels: int [B, L].
    Both carry lengths. blank fixed at 0 to match CTCLayer.cpp.
    """

    is_cost = True

    type_name = "ctc"

    def __init__(
        self,
        input: Layer,
        label: Layer,
        blank: int = 0,
        norm_by_times: bool = False,
        size: Optional[int] = None,
        name: Optional[str] = None,
        coeff: float = 1.0,
    ):
        super().__init__([input, label], name=name)
        self.blank = blank
        self.norm_by_times = norm_by_times
        self.size = size  # alphabet size incl. blank (config-surface value)
        self.coeff = coeff

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        logits, labels = ins
        assert logits.is_seq and labels.is_seq, "ctc needs sequence inputs"
        nll = ctc_ops.ctc_loss(
            logits.value,
            logits.lengths,
            labels.value.astype(jnp.int32),
            labels.lengths,
            blank=self.blank,
            norm_by_times=self.norm_by_times,
        )
        return Argument(self.coeff * _mean_over_examples(ctx, nll))


@LAYERS.register("crf")
class CRFCost(Layer):
    """Linear-chain CRF NLL (CRFLayer.cpp). Parameter is the reference's packed
    (C+2, C) weight: row0 start, row1 end, rows 2.. transitions."""

    is_cost = True

    type_name = "crf"

    def __init__(
        self,
        input: Layer,
        label: Layer,
        size: Optional[int] = None,
        param_attr: Any = None,
        name: Optional[str] = None,
        coeff: float = 1.0,
    ):
        super().__init__([input, label], name=name)
        self.size = size
        self.param_attr = _attr(param_attr)
        self.coeff = coeff

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        emit, labels = ins
        assert emit.is_seq, "crf needs a sequence input"
        c = self.size or emit.value.shape[-1]
        w = ctx.param(
            self, "w", (c + 2, c), init_mod.smart_normal, self.param_attr
        )
        nll = crf_ops.crf_nll(
            emit.value, emit.lengths, labels.value.astype(jnp.int32), w
        )
        return Argument(self.coeff * _mean_over_examples(ctx, nll))


@LAYERS.register("crf_decoding")
class CRFDecoding(Layer):
    """Viterbi decode (CRFDecodingLayer.cpp). Shares the CRF weight by
    param_attr name. With a label input, outputs per-step error indicators
    (1.0 where decoded != gold), matching the reference's evaluation mode."""

    type_name = "crf_decoding"

    def __init__(
        self,
        input: Layer,
        size: Optional[int] = None,
        label: Optional[Layer] = None,
        param_attr: Any = None,
        name: Optional[str] = None,
    ):
        srcs = [input] + ([label] if label is not None else [])
        super().__init__(srcs, name=name)
        self.size = size
        self.param_attr = _attr(param_attr)
        self.has_label = label is not None

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        emit = ins[0]
        c = self.size or emit.value.shape[-1]
        w = ctx.param(
            self, "w", (c + 2, c), init_mod.smart_normal, self.param_attr
        )
        tags = crf_ops.crf_decode(emit.value, emit.lengths, w)
        if self.has_label:
            gold = ins[1].value.astype(tags.dtype)
            err = (tags != gold).astype(jnp.float32)
            return Argument(err, emit.lengths)
        return Argument(tags, emit.lengths)


@LAYERS.register("nce")
class NCECost(Layer):
    """Noise-contrastive estimation (NCELayer.cpp). Samples `num_neg_samples`
    noise classes per example (uniform, or `neg_distribution` — the reference's
    MultinomialSampler), scores them against a [num_classes, D] weight, and
    applies logistic loss with the log(k·q) correction. At eval time (no
    sampling) it computes the full softmax cross-entropy, matching the
    reference's test-time path."""

    is_cost = True

    type_name = "nce"

    def __init__(
        self,
        input: Layer,
        label: Layer,
        num_classes: int,
        num_neg_samples: int = 10,
        neg_distribution: Optional[Any] = None,
        bias: bool = True,
        param_attr: Any = None,
        weight: Optional[Layer] = None,
        name: Optional[str] = None,
    ):
        super().__init__([input, label] + ([weight] if weight is not None else []),
                         name=name)
        self.has_weight = weight is not None
        self.num_classes = num_classes
        self.num_neg_samples = num_neg_samples
        self.neg_distribution = (
            None if neg_distribution is None else jnp.asarray(neg_distribution)
        )
        self.bias = bias
        self.param_attr = _attr(param_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value  # [B, D] (sequence inputs flatten per-timestep,
        if x.ndim > 2:    # NCELayer consumes the flat Argument stream)
            x = x.reshape(-1, x.shape[-1])
        label = ins[1].value.astype(jnp.int32).reshape(-1)  # [B]
        bsz, d = x.shape
        w = ctx.param(
            self,
            "w",
            (self.num_classes, d),
            init_mod.smart_normal,
            self.param_attr,
        )
        b = (
            ctx.param(self, "b", (self.num_classes,), init_mod.zeros)
            if self.bias
            else None
        )

        sample_w = (
            ins[2].value.reshape(-1) if self.has_weight else None
        )  # per-sample cost weight (NCELayer weight input)

        def _reduce(per_sample):
            if sample_w is not None:
                per_sample = per_sample * sample_w
            return _mean_over_examples(ctx, per_sample)

        if not ctx.train:
            logits = x @ w.T + (b if b is not None else 0.0)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, label[:, None], axis=1)[:, 0]
            return Argument(_reduce(nll))

        k = self.num_neg_samples
        rng = ctx.next_rng(self.name)
        if self.neg_distribution is None:
            samples = jax.random.randint(rng, (bsz, k), 0, self.num_classes)
            logq = jnp.full((), -math.log(self.num_classes))
            logq_pos = logq
            logq_neg = logq
        else:
            dist = self.neg_distribution / jnp.sum(self.neg_distribution)
            samples = jax.random.categorical(
                rng, jnp.log(dist), shape=(bsz, k)
            )
            logq_pos = jnp.log(dist[label])
            logq_neg = jnp.log(dist[samples])

        ids = jnp.concatenate([label[:, None], samples], axis=1)  # [B, 1+k]
        w_sel = w[ids]  # [B, 1+k, D]
        s = jnp.einsum("bd,bkd->bk", x, w_sel)
        if b is not None:
            s = s + b[ids]
        logq_all = jnp.concatenate(
            [
                jnp.broadcast_to(logq_pos, (bsz,))[:, None],
                jnp.broadcast_to(logq_neg, (bsz, k)),
            ],
            axis=1,
        )
        s = s - (math.log(k) + logq_all)
        y = jnp.concatenate(
            [jnp.ones((bsz, 1)), jnp.zeros((bsz, k))], axis=1
        )
        # stable sigmoid BCE
        loss = jnp.maximum(s, 0.0) - s * y + jnp.log1p(jnp.exp(-jnp.abs(s)))
        return Argument(_reduce(jnp.sum(loss, axis=1)))


@LAYERS.register("hsigmoid")
class HierarchicalSigmoid(Layer):
    """Hierarchical sigmoid over an implicit complete binary tree
    (HierarchicalSigmoidLayer.cpp + math/MatrixBitCode.cpp). Leaf index
    `label + num_classes`; internal node j (1-based heap order) owns weight
    row j-1 of a [num_classes-1, D] matrix. Loss is the sum of binary CEs
    along the root→leaf path — O(log C) rows touched per example, all gathered
    in one static-depth vectorized pass."""

    is_cost = True

    type_name = "hsigmoid"

    def __init__(
        self,
        input: Layer,
        label: Layer,
        num_classes: int,
        bias: bool = True,
        param_attr: Any = None,
        name: Optional[str] = None,
    ):
        # multiple feature inputs (the reference sums per-input projections;
        # concatenating features with one wide weight is the same map)
        ins = list(input) if isinstance(input, (list, tuple)) else [input]
        super().__init__(ins + [label], name=name)
        self.n_feats = len(ins)
        self.num_classes = num_classes
        self.bias = bias
        self.param_attr = _attr(param_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        n = getattr(self, "n_feats", 1)
        x = jnp.concatenate([a.value for a in ins[:n]], axis=-1)  # [B, D]
        label = ins[n].value.astype(jnp.int32).reshape(-1)
        bsz, d = x.shape
        c = self.num_classes
        w = ctx.param(
            self, "w", (c - 1, d), init_mod.smart_normal, self.param_attr
        )
        b = (
            ctx.param(self, "b", (c - 1,), init_mod.zeros)
            if self.bias
            else None
        )
        depth = int(math.ceil(math.log2(max(2, c)))) + 1
        leaf = label + c  # [B], in [C, 2C)
        ds = jnp.arange(1, depth + 1)  # levels up from the leaf
        parents = leaf[:, None] >> ds[None, :]  # [B, depth]
        bits = (leaf[:, None] >> (ds[None, :] - 1)) & 1
        valid = parents >= 1
        rows = jnp.clip(parents - 1, 0, c - 2)
        w_sel = w[rows]  # [B, depth, D]
        s = jnp.einsum("bd,bkd->bk", x, w_sel)
        if b is not None:
            s = s + b[rows]
        y = bits.astype(s.dtype)
        loss = jnp.maximum(s, 0.0) - s * y + jnp.log1p(jnp.exp(-jnp.abs(s)))
        loss = jnp.where(valid, loss, 0.0)
        return Argument(_mean_over_examples(ctx, jnp.sum(loss, axis=1)))


@LAYERS.register("lambda_cost")
class LambdaCost(Layer):
    """LambdaRank listwise cost (CostLayer.cpp LambdaCost): per query-sequence,
    pairwise logistic losses weighted by |ΔNDCG| truncated at `max_sort_size`.
    The reference emits lambda gradients directly; here the loss whose gradient
    is those lambdas is materialized so jax.grad recovers them."""

    is_cost = True

    type_name = "lambda_cost"

    def __init__(
        self,
        input: Layer,
        score: Layer,
        ndcg_num: int = 5,
        name: Optional[str] = None,
        coeff: float = 1.0,
    ):
        super().__init__([input, score], name=name)
        self.ndcg_num = ndcg_num
        self.coeff = coeff

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        pred, rel = ins  # both [B, T] or [B, T, 1] sequences
        assert pred.is_seq, "lambda_cost needs sequence inputs"
        pv = pred.value
        if pv.ndim == 3 and pv.shape[-1] != 1:
            # the reference tolerates a wide feature input at parse time and
            # scores by the first column at runtime (LambdaCost reads one
            # score per doc) — keep that contract
            pv = pv[..., :1]
        s = pv.reshape(pv.shape[0], pv.shape[1])
        g = rel.value.reshape(s.shape).astype(jnp.float32)
        mask = pred.mask()  # [B, T]
        t = s.shape[1]

        # ideal DCG per sequence from top-ndcg_num relevances
        k = min(self.ndcg_num, t)
        top_g = jax.lax.top_k(jnp.where(mask > 0, g, -jnp.inf), k)[0]
        top_g = jnp.where(jnp.isfinite(top_g), top_g, 0.0)
        disc = 1.0 / jnp.log2(jnp.arange(2, k + 2).astype(jnp.float32))
        idcg = jnp.sum((jnp.exp2(top_g) - 1.0) * disc[None, :], axis=1)
        idcg = jnp.maximum(idcg, 1e-6)

        # rank positions by current score (1-based)
        order = jnp.argsort(-jnp.where(mask > 0, s, -jnp.inf), axis=1)
        ranks = jnp.zeros_like(order)
        ranks = jax.vmap(
            lambda r, o: r.at[o].set(jnp.arange(t))
        )(ranks, order) + 1  # [B, T]

        gain = jnp.exp2(g) - 1.0
        # discounts truncate at ndcg_num so pair weights match NDCG@k — pairs
        # entirely below the cutoff get zero weight, as in the reference
        dfac = jnp.where(
            ranks <= self.ndcg_num,
            1.0 / jnp.log2(1.0 + ranks.astype(jnp.float32)),
            0.0,
        )
        # |ΔNDCG| for swapping i, j
        dndcg = jnp.abs(
            (gain[:, :, None] - gain[:, None, :])
            * (dfac[:, :, None] - dfac[:, None, :])
        ) / idcg[:, None, None]

        diff = s[:, :, None] - s[:, None, :]
        pair_loss = jnp.log1p(jnp.exp(-jnp.abs(diff))) + jnp.maximum(-diff, 0.0)
        rel_gt = (g[:, :, None] > g[:, None, :]).astype(s.dtype)
        pmask = mask[:, :, None] * mask[:, None, :]
        loss = jnp.sum(dndcg * pair_loss * rel_gt * pmask, axis=(1, 2))
        return Argument(self.coeff * _mean_over_examples(ctx, loss))


class BeamInput:
    """One beam expansion for CrossEntropyOverBeam — mirrors the reference's
    trainer_config_helpers BeamInput(candidate_scores, selected_candidates,
    gold) triple (layers.py:6038)."""

    def __init__(self, candidate_scores: Layer, selected_candidates: Layer,
                 gold: Layer):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


@LAYERS.register("cross_entropy_over_beam")
class CrossEntropyOverBeam(Layer):
    """Globally normalized cross entropy over multi-step beam expansions
    (CrossEntropyOverBeam.cpp:193, learning-to-search training).

    Dense TPU encoding (the reference walks ragged nested sequences on the
    host; here every expansion is a fixed-shape tensor):
      expansion t: candidate_scores [B, N_t] (flattened over the expansion's
      subsequences), selected_candidates [B, K_t] int32 flat indices into N_t
      (-1 = pad), gold [B] int32 flat index into N_t.
    Ancestry: subsequence s of expansion t+1 descends from selected candidate
    s of expansion t (the kmax/sub_nested_seq pipeline guarantees this), so a
    candidate's parent path id is `flat_index // (N_t // K_{t-1})`-free — we
    carry path scores forward along the selection directly.

    Per sample: path scores accumulate along selections; the softmax runs
    over the beam at the expansion where gold falls off (or the last one),
    with the gold path appended as an extra candidate when it fell off —
    `-log softmax(paths)[gold]` exactly as CostForOneSequence::forward."""

    is_cost = True

    type_name = "cross_entropy_over_beam"

    def __init__(self, input: List[BeamInput], name=None):
        self.beams = list(input)
        srcs: List[Layer] = []
        for b in self.beams:
            srcs += [b.candidate_scores, b.selected_candidates, b.gold]
        super().__init__(srcs, name=name)

    def forward(self, ctx, ins):
        n_beams = len(self.beams)

        def _flat_scores(v):
            # accept [B,T], [B,T,1], nested [B,S,T(,1)] — flatten to [B, N]
            if v.ndim > 2 and v.shape[-1] == 1:
                v = v[..., 0]
            return v.reshape(v.shape[0], -1)

        scores = [_flat_scores(ins[3 * i].value) for i in range(n_beams)]
        selected = [ins[3 * i + 1].value.astype(jnp.int32) for i in range(n_beams)]
        gold = [ins[3 * i + 2].value.astype(jnp.int32).reshape(-1) for i in range(n_beams)]
        bsz = scores[0].shape[0]
        barange = jnp.arange(bsz)

        neg = jnp.asarray(-1e30, jnp.float32)
        # prefix score of the path each subsequence of expansion t descends
        # from: [B, K_{t-1}]; expansion 0 descends from the empty path.
        costs = []          # CE if gold falls off at expansion t (or last)
        gold_prefix = jnp.zeros((bsz,), jnp.float32)
        sel_prefix = None   # [B, K_prev] accumulated scores of selected paths
        gold_in = jnp.ones((bsz,), bool)  # gold survived beams 0..t-1
        first_off = jnp.full((bsz,), n_beams - 1, jnp.int32)
        for t in range(n_beams):
            sc = scores[t].astype(jnp.float32)  # [B, N]
            n = sc.shape[1]
            k_prev = 1 if sel_prefix is None else sel_prefix.shape[1]
            seg = n // k_prev  # candidates per parent subsequence
            parent = jnp.arange(n) // seg  # ancestry by position
            base = (
                jnp.zeros((bsz, n), jnp.float32)
                if sel_prefix is None
                else sel_prefix[:, parent]
            )
            path_scores = base + sc  # [B, N] total score of every candidate
            sel = selected[t]  # [B, K]
            valid = sel >= 0
            safe = jnp.maximum(sel, 0)
            sel_scores = jnp.take_along_axis(path_scores, safe, axis=1)
            sel_scores = jnp.where(valid, sel_scores, neg)
            g = gold[t]
            gold_score = gold_prefix + sc[barange, g]
            hit = jnp.any(valid & (sel == g[:, None]), axis=1)
            # beam logits at this expansion: selected paths, with the gold
            # path as an extra slot when it is not among them
            extra = jnp.where(hit, neg, gold_score)
            logits = jnp.concatenate([sel_scores, extra[:, None]], axis=1)
            lse = jax.nn.logsumexp(logits, axis=1)
            costs.append(lse - gold_score)  # = -log softmax [gold path]
            # bookkeeping for the next expansion
            fell_now = gold_in & ~hit
            first_off = jnp.where(fell_now, t, first_off)
            gold_in = gold_in & hit
            gold_prefix = gold_score
            sel_prefix = sel_scores
        cost_mat = jnp.stack(costs, axis=1)  # [B, n_beams]
        per_sample = jnp.take_along_axis(
            cost_mat, first_off[:, None], axis=1
        )[:, 0]
        return Argument(_mean_over_examples(ctx, per_sample))
