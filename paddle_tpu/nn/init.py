"""Parameter initializers.

The reference's default is N(0, 1/sqrt(fan_in)) (paddle/parameter/Parameter.cpp
randomize: initial_std defaults to 1/sqrt(dim0); config_parser.py sets
initial_strategy/initial_smart). We keep that default plus the standard menu."""

from __future__ import annotations

import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


def zeros(key: Array, shape: Sequence[int], dtype: Any = jnp.float32) -> Array:
    return jnp.zeros(shape, dtype)


def ones(key: Array, shape: Sequence[int], dtype: Any = jnp.float32) -> Array:
    return jnp.ones(shape, dtype)


def constant(value: float):
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, value, dtype)

    return init


def normal(std: float = 1.0, mean: float = 0.0):
    def init(key, shape, dtype=jnp.float32):
        return mean + std * jax.random.normal(key, shape, dtype)

    return init


def uniform(scale: float = 1.0):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -scale, scale)

    return init


def _fan_in(shape: Sequence[int]) -> int:
    if len(shape) == 1:
        return shape[0]
    if len(shape) == 2:
        return shape[0]
    # conv kernels [kh, kw, cin, cout] (NHWC/HWIO layout)
    receptive = 1
    for d in shape[:-2]:
        receptive *= d
    return receptive * shape[-2]


def smart_normal(key: Array, shape: Sequence[int], dtype: Any = jnp.float32) -> Array:
    """N(0, 1/sqrt(fan_in)) — the reference's 'initial_smart' default."""
    std = 1.0 / math.sqrt(max(1, _fan_in(shape)))
    return std * jax.random.normal(key, shape, dtype)


def xavier(key: Array, shape: Sequence[int], dtype: Any = jnp.float32) -> Array:
    fan_in = _fan_in(shape)
    fan_out = shape[-1] if len(shape) >= 2 else shape[0]
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -limit, limit)


def he_normal(key: Array, shape: Sequence[int], dtype: Any = jnp.float32) -> Array:
    std = math.sqrt(2.0 / max(1, _fan_in(shape)))
    return std * jax.random.normal(key, shape, dtype)


default_weight_init = smart_normal
default_bias_init = zeros
