"""Projections / operators for Mixed layers.

Parity with paddle/gserver/layers/Projection.h + Operator.h and their concrete
classes (FullMatrixProjection, TableProjection, DotMulProjection,
IdentityProjection, ScalingProjection, ContextProjection, TransposedFullMatrix).
A Projection is a parameterized transform of one (or two) source layers whose
results the Mixed layer sums."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax.numpy as jnp

from paddle_tpu.nn import init as init_mod
from paddle_tpu.nn.graph import Argument, Context, Layer, ParamAttr
import jax

from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import linalg
from paddle_tpu.ops import sequence as seq_ops


class Projection:
    is_operator = False  # operators (Operator.h) append after projections

    def __init__(self, sources: Sequence[Layer], param_attr: Any = None):
        self.sources: List[Layer] = list(sources)
        self.param_attr = (
            param_attr if isinstance(param_attr, (ParamAttr, type(None))) else ParamAttr(**param_attr)
        )
        self.tag: Optional[str] = None  # set by Mixed for param naming

    def apply(self, ctx: Context, owner: Layer, args: List[Argument], size):
        raise NotImplementedError

    def _pname(self, owner: Layer, base: str) -> str:
        idx = owner.projections.index(self)
        return f"proj{idx}.{base}"


class FullMatrix(Projection):
    """FullMatrixProjection: x @ W. `size` may come from the projection
    itself (full_matrix_projection(size=N) inside a size-0 mixed) or the
    enclosing mixed layer."""

    def __init__(self, input: Layer, param_attr: Any = None, size: int = 0):
        super().__init__([input], param_attr)
        self.size = size

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        out = self.size or size
        w = ctx.param(
            owner,
            self._pname(owner, "w"),
            (x.shape[-1], out),
            init_mod.smart_normal,
            self.param_attr,
        )
        return linalg.matmul(x, w, ctx.policy)


class TransposedFullMatrix(Projection):
    """TransposedFullMatrixProjection: x @ W^T (weight stored [size, in])."""

    def __init__(self, input: Layer, param_attr: Any = None):
        super().__init__([input], param_attr)

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        w = ctx.param(
            owner,
            self._pname(owner, "w"),
            (size, x.shape[-1]),
            init_mod.smart_normal,
            self.param_attr,
        )
        return linalg.matmul(x, w.T, ctx.policy)


class Identity(Projection):
    """IdentityProjection / IdentityOffsetProjection."""

    def __init__(self, input: Layer, offset: int = 0, size: Optional[int] = None):
        super().__init__([input])
        self.offset = offset
        self.slice_size = size

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        if self.offset or (self.slice_size and self.slice_size != x.shape[-1]):
            end = self.offset + (self.slice_size or size or x.shape[-1])
            return x[..., self.offset : end]
        return x


class DotMul(Projection):
    """DotMulProjection: elementwise x * w with learned w[D]."""

    def __init__(self, input: Layer, param_attr: Any = None):
        super().__init__([input], param_attr)

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        w = ctx.param(
            owner,
            self._pname(owner, "w"),
            (x.shape[-1],),
            init_mod.ones,
            self.param_attr,
        )
        return x * w


class Scaling(Projection):
    """ScalingProjection: a single learned scalar times x."""

    def __init__(self, input: Layer, param_attr: Any = None):
        super().__init__([input], param_attr)

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        w = ctx.param(
            owner, self._pname(owner, "w"), (1,), init_mod.ones, self.param_attr
        )
        return x * w[0]


class Table(Projection):
    """TableProjection: embedding lookup from int-id input."""

    def __init__(self, input: Layer, vocab_size: Optional[int] = None,
                 param_attr: Any = None, size: int = 0):
        super().__init__([input], param_attr)
        self.vocab_size = vocab_size
        self.size = size

    def apply(self, ctx, owner, args, size):
        size = self.size or size
        v = args[0].value
        vocab = self.vocab_size
        if not vocab:
            # no id slot declared: the reference sizes the table by the
            # input layer's width (config_parser TableProjection)
            vocab = int(v.shape[-1]) if v.ndim > 1 else 2
        if v.ndim > 1 and not jnp.issubdtype(v.dtype, jnp.integer):
            v = v[..., 0]  # dense slot reused as ids: first column at trace
        ids = jnp.clip(v.astype(jnp.int32), 0, vocab - 1)
        table = ctx.param(
            owner,
            self._pname(owner, "w"),
            (vocab, size),
            init_mod.smart_normal,
            self.param_attr,
        )
        return jnp.take(table, ids, axis=0)


class SliceProj(Projection):
    """SliceProjection (SliceProjection.cpp): channel ranges of an image
    input (or feature ranges of a flat one), flattened and concatenated by
    the owning mixed/concat2."""

    def __init__(self, input: Layer, slices):
        super().__init__([input])
        self.slices = [tuple(s) for s in slices]

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        parts = [x[..., s:e] for s, e in self.slices]
        out = jnp.concatenate(parts, axis=-1)
        if out.ndim > 2:
            out = out.reshape(out.shape[0], -1)
        return out


class Context_(Projection):
    """ContextProjection (paddle/function/ContextProjectionOp.cpp): sliding-window
    concat over a sequence input; optionally trainable out-of-range padding."""

    def __init__(
        self,
        input: Layer,
        context_start: int,
        context_len: int,
        trainable_padding: bool = True,
        param_attr: Any = None,
    ):
        super().__init__([input], param_attr)
        self.context_start = context_start
        self.context_len = context_len
        # boundary rows needing padding (ContextProjection.cpp beginPad_/endPad_)
        self.left_pad = max(0, -context_start)
        self.right_pad = max(0, context_start + context_len - 1)
        self.trainable_padding = trainable_padding and (
            self.left_pad + self.right_pad > 0
        )

    def apply(self, ctx, owner, args, size):
        arg = args[0]
        if not arg.is_seq:  # tolerate a non-seq slot: length-1 sequence
            v = arg.value[:, None]
            lengths = jnp.ones((v.shape[0],), jnp.int32)
            base = seq_ops.context_projection(
                v, lengths, self.context_start, self.context_len
            )
            if self.trainable_padding:
                base = base + self._pad_correction(ctx, owner, v, lengths)
            return base[:, 0]
        base = seq_ops.context_projection(
            arg.value, arg.lengths, self.context_start, self.context_len
        )
        if self.trainable_padding:
            base = base + self._pad_correction(
                ctx, owner, arg.value, arg.lengths
            )
        return base

    def _pad_correction(self, ctx, owner, x, lengths):
        """Learned boundary rows where the context window runs off either end
        (replacing the zero padding of the base projection)."""
        b, t, d = x.shape
        lp, rp = self.left_pad, self.right_pad
        w = ctx.param(
            owner,
            self._pname(owner, "w"),
            (lp + rp, d),
            init_mod.zeros,
            self.param_attr,
        )
        cols = []
        pos = jnp.arange(t)
        zero = jnp.zeros((b, t, d), x.dtype)
        for o in range(self.context_start, self.context_start + self.context_len):
            src = pos + o
            if o < 0 and lp:
                row = jnp.clip(src + lp, 0, lp - 1)
                corr = jnp.where(
                    (src < 0)[None, :, None], w[row][None], zero
                )
            elif o > 0 and rp:
                over = src[None, :] >= lengths[:, None]
                row = jnp.clip(lp + src[None, :] - lengths[:, None], lp,
                               lp + rp - 1)
                corr = jnp.where(over[:, :, None], w[row], zero)
            else:
                corr = zero
            cols.append(corr)
        return jnp.concatenate(cols, axis=-1)


class DotMulOperator(Projection):
    """DotMulOperator: elementwise product of two inputs (no params)."""

    is_operator = True

    def __init__(self, input1: Layer, input2: Layer, scale: float = 1.0):
        super().__init__([input1, input2])
        self.scale = scale

    def apply(self, ctx, owner, args, size):
        return self.scale * args[0].value * args[1].value


class ConvProj(Projection):
    """ConvProjection (math/ConvProjection.cpp): a parameterized conv applied
    inside mixed/concat. Flat [B, c*h*w] CHW inputs are viewed as NHWC;
    output flattens back to the reference's flat layout."""

    def __init__(self, input: Layer, filter_size, num_filters: int,
                 num_channels=None, stride=1, padding=0, groups: int = 1,
                 param_attr: Any = None, trans: bool = False):
        super().__init__([input], param_attr)
        self.filter_size = filter_size
        self.num_filters = num_filters
        self.num_channels = num_channels
        self.stride = stride
        self.padding = padding
        self.groups = groups
        self.trans = trans

    def _as_nhwc(self, x):
        if x.ndim == 4:
            return x
        import math as _math

        c = self.num_channels
        side = _math.isqrt(x.shape[-1] // c)
        return x.reshape(x.shape[0], c, side, side).transpose(0, 2, 3, 1)

    def apply(self, ctx, owner, args, size):
        x = self._as_nhwc(args[0].value)
        kh, kw = conv_ops._pair(self.filter_size)
        cin = x.shape[-1]
        shape = (
            (kh, kw, self.num_filters, cin)  # forward conv's HWIO (deconv)
            if self.trans
            else (kh, kw, cin // self.groups, self.num_filters)
        )
        w = ctx.param(
            owner,
            self._pname(owner, "w"),
            shape,
            init_mod.he_normal,
            self.param_attr,
        )
        if self.trans:
            y = conv_ops.conv2d_transpose(
                x, w, self.stride, self.padding, policy=ctx.policy
            )
        else:
            y = conv_ops.conv2d(
                x, w, self.stride, self.padding, 1, self.groups, ctx.policy
            )
        return y.reshape(y.shape[0], -1)

    def build(self, name: str) -> Layer:
        """Materialize as an img_conv layer (the concat_layer /
        inception-tower path, ConcatenateLayer2 with conv projections)."""
        from paddle_tpu.config.v1_layers import img_conv_layer

        return img_conv_layer(
            self.sources[0], self.filter_size, self.num_filters, name=name,
            num_channels=self.num_channels, act="linear", groups=self.groups,
            stride=self.stride, padding=self.padding, bias_attr=False,
            param_attr=self.param_attr, trans=self.trans,
        )


class ConvOperator(Projection):
    """ConvOperator (gserver ConvOperator.cpp): convolution whose filter is
    ANOTHER LAYER's output — per-sample dynamic filters, vmapped conv."""

    is_operator = True

    def __init__(self, img: Layer, filt: Layer, filter_size, num_filters: int,
                 num_channels=None, stride=1, padding=0,
                 trans: bool = False):
        super().__init__([img, filt], None)
        self.filter_size = filter_size
        self.num_filters = num_filters
        self.num_channels = num_channels
        self.stride = stride
        self.padding = padding
        self.trans = trans

    def apply(self, ctx, owner, args, size):
        import math as _math

        x = args[0].value
        if x.ndim != 4:
            c = self.num_channels
            side = _math.isqrt(x.shape[-1] // c)
            x = x.reshape(x.shape[0], c, side, side).transpose(0, 2, 3, 1)
        kh, kw = conv_ops._pair(self.filter_size)
        cin = x.shape[-1]
        if self.trans:  # filter of the equivalent forward conv (HWIO)
            w = args[1].value.reshape(-1, kh, kw, self.num_filters, cin)
        else:
            w = args[1].value.reshape(-1, kh, kw, cin, self.num_filters)
        if w.shape[0] == 1:
            w = jnp.broadcast_to(w, (x.shape[0],) + w.shape[1:])

        def one(xi, wi):
            if self.trans:
                return conv_ops.conv2d_transpose(
                    xi[None], wi, self.stride, self.padding, policy=ctx.policy
                )[0]
            return conv_ops.conv2d(
                xi[None], wi, self.stride, self.padding, 1, 1, ctx.policy
            )[0]

        y = jax.vmap(one)(x, w)
        return y.reshape(y.shape[0], -1)
