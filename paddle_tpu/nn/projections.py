"""Projections / operators for Mixed layers.

Parity with paddle/gserver/layers/Projection.h + Operator.h and their concrete
classes (FullMatrixProjection, TableProjection, DotMulProjection,
IdentityProjection, ScalingProjection, ContextProjection, TransposedFullMatrix).
A Projection is a parameterized transform of one (or two) source layers whose
results the Mixed layer sums."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax.numpy as jnp

from paddle_tpu.nn import init as init_mod
from paddle_tpu.nn.graph import Argument, Context, Layer, ParamAttr
from paddle_tpu.ops import linalg
from paddle_tpu.ops import sequence as seq_ops


class Projection:
    def __init__(self, sources: Sequence[Layer], param_attr: Any = None):
        self.sources: List[Layer] = list(sources)
        self.param_attr = (
            param_attr if isinstance(param_attr, (ParamAttr, type(None))) else ParamAttr(**param_attr)
        )
        self.tag: Optional[str] = None  # set by Mixed for param naming

    def apply(self, ctx: Context, owner: Layer, args: List[Argument], size):
        raise NotImplementedError

    def _pname(self, owner: Layer, base: str) -> str:
        idx = owner.projections.index(self)
        return f"proj{idx}.{base}"


class FullMatrix(Projection):
    """FullMatrixProjection: x @ W."""

    def __init__(self, input: Layer, param_attr: Any = None):
        super().__init__([input], param_attr)

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        w = ctx.param(
            owner,
            self._pname(owner, "w"),
            (x.shape[-1], size),
            init_mod.smart_normal,
            self.param_attr,
        )
        return linalg.matmul(x, w, ctx.policy)


class TransposedFullMatrix(Projection):
    """TransposedFullMatrixProjection: x @ W^T (weight stored [size, in])."""

    def __init__(self, input: Layer, param_attr: Any = None):
        super().__init__([input], param_attr)

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        w = ctx.param(
            owner,
            self._pname(owner, "w"),
            (size, x.shape[-1]),
            init_mod.smart_normal,
            self.param_attr,
        )
        return linalg.matmul(x, w.T, ctx.policy)


class Identity(Projection):
    """IdentityProjection / IdentityOffsetProjection."""

    def __init__(self, input: Layer, offset: int = 0, size: Optional[int] = None):
        super().__init__([input])
        self.offset = offset
        self.slice_size = size

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        if self.offset or (self.slice_size and self.slice_size != x.shape[-1]):
            end = self.offset + (self.slice_size or size or x.shape[-1])
            return x[..., self.offset : end]
        return x


class DotMul(Projection):
    """DotMulProjection: elementwise x * w with learned w[D]."""

    def __init__(self, input: Layer, param_attr: Any = None):
        super().__init__([input], param_attr)

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        w = ctx.param(
            owner,
            self._pname(owner, "w"),
            (x.shape[-1],),
            init_mod.ones,
            self.param_attr,
        )
        return x * w


class Scaling(Projection):
    """ScalingProjection: a single learned scalar times x."""

    def __init__(self, input: Layer, param_attr: Any = None):
        super().__init__([input], param_attr)

    def apply(self, ctx, owner, args, size):
        x = args[0].value
        w = ctx.param(
            owner, self._pname(owner, "w"), (1,), init_mod.ones, self.param_attr
        )
        return x * w[0]


class Table(Projection):
    """TableProjection: embedding lookup from int-id input."""

    def __init__(self, input: Layer, vocab_size: int, param_attr: Any = None):
        super().__init__([input], param_attr)
        self.vocab_size = vocab_size

    def apply(self, ctx, owner, args, size):
        ids = args[0].value.astype(jnp.int32)
        table = ctx.param(
            owner,
            self._pname(owner, "w"),
            (self.vocab_size, size),
            init_mod.smart_normal,
            self.param_attr,
        )
        return jnp.take(table, ids, axis=0)


class Context_(Projection):
    """ContextProjection (paddle/function/ContextProjectionOp.cpp): sliding-window
    concat over a sequence input; optionally trainable out-of-range padding."""

    def __init__(
        self,
        input: Layer,
        context_start: int,
        context_len: int,
        trainable_padding: bool = False,
        param_attr: Any = None,
    ):
        super().__init__([input], param_attr)
        self.context_start = context_start
        self.context_len = context_len
        self.trainable_padding = trainable_padding

    def apply(self, ctx, owner, args, size):
        arg = args[0]
        assert arg.is_seq, "context projection needs a sequence input"
        return seq_ops.context_projection(
            arg.value, arg.lengths, self.context_start, self.context_len
        )


class DotMulOperator(Projection):
    """DotMulOperator: elementwise product of two inputs (no params)."""

    def __init__(self, input1: Layer, input2: Layer, scale: float = 1.0):
        super().__init__([input1, input2])
        self.scale = scale

    def apply(self, ctx, owner, args, size):
        return self.scale * args[0].value * args[1].value
