"""Recurrent layers.

Parity with the reference recurrent stack: RecurrentLayer.cpp (vanilla),
LstmLayer.cpp + LstmCompute.cu (lstmemory: input is the 4H-wide projection,
peephole 'check' weights, gate/state activations), GatedRecurrentLayer.cpp +
GruCompute.cu (gated_unit: 3H-wide input), and the bidirectional composites
bidirectional_lstm/gru (trainer_config_helpers/networks.py). Execution is a
lax.scan over time-major padded batches (see paddle_tpu/ops/rnn.py) rather
than SequenceToBatch reordering."""

from __future__ import annotations

from typing import Any, List, Optional

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn import init as init_mod
from paddle_tpu.nn.graph import Argument, Context, Layer
from paddle_tpu.nn.layers import Fc, _attr
from paddle_tpu.ops import rnn as rnn_ops


@LAYERS.register("lstmemory")
class Lstm(Layer):
    """lstmemory (LstmLayer.cpp): input must be size 4H (pre-projected, as the
    reference requires a preceding fc/mixed layer). use_peephole matches the
    'check' weights of hl_lstm."""

    type_name = "lstmemory"

    def __init__(
        self,
        input: Layer,
        size: Optional[int] = None,
        reverse: bool = False,
        act: Any = "tanh",
        gate_act: Any = "sigmoid",
        state_act: Any = "tanh",
        use_peephole: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.reverse = reverse
        self.act = act
        self.gate_act = gate_act
        self.state_act = state_act
        self.use_peephole = use_peephole
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        arg = ins[0]
        assert arg.is_seq, f"{self.name}: lstmemory needs a sequence input"
        proj = arg.value
        hdim = self.size or proj.shape[-1] // 4
        assert proj.shape[-1] == 4 * hdim, (
            f"{self.name}: input width {proj.shape[-1]} != 4*size ({4 * hdim})"
        )
        w_hh = ctx.param(
            self, "w_hh", (hdim, 4 * hdim), init_mod.smart_normal, self.param_attr
        )
        bias = ctx.param(self, "b", (4 * hdim,), init_mod.zeros, self.bias_attr)
        checks = (None, None, None)
        if self.use_peephole:
            checks = tuple(
                ctx.param(self, f"check_{g}", (hdim,), init_mod.zeros, None)
                for g in ("i", "f", "o")
            )
        p = rnn_ops.LstmParams(w_hh, bias, *checks)
        mask = arg.mask(proj.dtype)
        hs, h_last, c_last = rnn_ops.lstm_scan(
            proj,
            mask,
            p,
            reverse=self.reverse,
            gate_act=self.gate_act,
            cell_act=self.act,
            state_act=self.state_act,
        )
        return Argument(hs, arg.lengths)


@LAYERS.register("gated_unit", "grumemory")
class Gru(Layer):
    """grumemory (GatedRecurrentLayer.cpp): input must be size 3H."""

    type_name = "grumemory"

    def __init__(
        self,
        input: Layer,
        size: Optional[int] = None,
        reverse: bool = False,
        act: Any = "tanh",
        gate_act: Any = "sigmoid",
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.reverse = reverse
        self.act = act
        self.gate_act = gate_act
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        arg = ins[0]
        assert arg.is_seq, f"{self.name}: grumemory needs a sequence input"
        proj = arg.value
        hdim = self.size or proj.shape[-1] // 3
        assert proj.shape[-1] == 3 * hdim
        w_hzr = ctx.param(
            self, "w_hzr", (hdim, 2 * hdim), init_mod.smart_normal, self.param_attr
        )
        # w_hc has a different shape than w_hzr — a shared param_attr name must
        # not collide, so derive a distinct sharing key for it
        c_attr = self.param_attr
        if c_attr is not None and c_attr.name:
            import dataclasses as _dc

            c_attr = _dc.replace(c_attr, name=c_attr.name + ".c")
        w_hc = ctx.param(
            self, "w_hc", (hdim, hdim), init_mod.smart_normal, c_attr
        )
        bias = ctx.param(self, "b", (3 * hdim,), init_mod.zeros, self.bias_attr)
        p = rnn_ops.GruParams(w_hzr, w_hc, bias)
        mask = arg.mask(proj.dtype)
        hs, h_last = rnn_ops.gru_scan(
            proj, mask, p, reverse=self.reverse,
            gate_act=self.gate_act, cand_act=self.act,
        )
        return Argument(hs, arg.lengths)


@LAYERS.register("recurrent")
class SimpleRnn(Layer):
    """Vanilla full-matrix recurrence (RecurrentLayer.cpp). Input size == H."""

    type_name = "recurrent"

    def __init__(
        self,
        input: Layer,
        act: Any = "tanh",
        reverse: bool = False,
        bias: bool = True,
        param_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.act = act
        self.reverse = reverse
        self.bias = bias
        self.param_attr = _attr(param_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        arg = ins[0]
        assert arg.is_seq
        proj = arg.value
        hdim = proj.shape[-1]
        w_hh = ctx.param(
            self, "w_hh", (hdim, hdim), init_mod.smart_normal, self.param_attr
        )
        b = ctx.param(self, "b", (hdim,), init_mod.zeros, None) if self.bias else None
        hs, _ = rnn_ops.simple_rnn_scan(
            proj, arg.mask(proj.dtype), w_hh, b, self.act, reverse=self.reverse
        )
        return Argument(hs, arg.lengths)


def simple_lstm(
    input: Layer,
    size: int,
    reverse: bool = False,
    name: str = "lstm",
    **lstm_kwargs: Any,
) -> Layer:
    """fc(4H) + lstmemory — the simple_lstm helper
    (trainer_config_helpers/networks.py:553)."""
    proj = Fc(input, 4 * size, act=None, name=f"{name}.input_proj")
    return Lstm(proj, size=size, reverse=reverse, name=name, **lstm_kwargs)


def simple_gru(
    input: Layer, size: int, reverse: bool = False, name: str = "gru", **kw: Any
) -> Layer:
    """fc(3H) + grumemory (networks.py:981 simple_gru)."""
    proj = Fc(input, 3 * size, act=None, name=f"{name}.input_proj")
    return Gru(proj, size=size, reverse=reverse, name=name, **kw)


def bidirectional_lstm(
    input: Layer, size: int, name: str = "bilstm", **kw: Any
) -> Layer:
    """Concat of forward+backward lstm (networks.py bidirectional_lstm)."""
    from paddle_tpu.nn.layers import Concat

    fwd = simple_lstm(input, size, reverse=False, name=f"{name}.fw", **kw)
    bwd = simple_lstm(input, size, reverse=True, name=f"{name}.bw", **kw)
    return Concat([fwd, bwd], name=f"{name}.cat")


def bidirectional_gru(input: Layer, size: int, name: str = "bigru", **kw: Any) -> Layer:
    from paddle_tpu.nn.layers import Concat

    fwd = simple_gru(input, size, reverse=False, name=f"{name}.fw", **kw)
    bwd = simple_gru(input, size, reverse=True, name=f"{name}.bw", **kw)
    return Concat([fwd, bwd], name=f"{name}.cat")


@LAYERS.register("mdlstmemory")
class MDLstm(Layer):
    """2-D multi-dimensional LSTM (MDLstmLayer.cpp:180). Input is the
    pre-projected grid [B, H, W, 5*size] (same convention as lstmemory's 4H:
    a preceding fc/mixed supplies x·Wx); output [B, H, W, size]. The grid is
    walked as a wavefront — see ops/mdlstm.py. directions[d]=False reverses
    dimension d (the reference's per-dim direction flags)."""

    type_name = "mdlstmemory"

    def __init__(
        self,
        input: Layer,
        size: Optional[int] = None,
        directions=(True, True),
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.directions = tuple(directions)
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        from paddle_tpu.ops import mdlstm as md_ops

        proj = ins[0].value
        assert proj.ndim == 4, (
            f"{self.name}: mdlstmemory needs a [B, H, W, 5*size] grid input"
        )
        hid = self.size or proj.shape[-1] // 5
        assert proj.shape[-1] == 5 * hid, (
            f"{self.name}: input width {proj.shape[-1]} != 5*size ({5 * hid})"
        )
        p = md_ops.MDLstmParams(
            w_h=ctx.param(self, "w_h", (hid, 5 * hid), init_mod.smart_normal,
                          self.param_attr),
            bias=ctx.param(self, "b", (5 * hid,), init_mod.zeros, self.bias_attr),
            check_i=ctx.param(self, "check_i", (hid,), init_mod.zeros, None),
            check_f=ctx.param(self, "check_f", (2, hid), init_mod.zeros, None),
            check_o=ctx.param(self, "check_o", (hid,), init_mod.zeros, None),
        )
        out = md_ops.mdlstm_2d(proj, p, self.directions)
        return ins[0].with_value(out)
