"""Recurrent layers.

Parity with the reference recurrent stack: RecurrentLayer.cpp (vanilla),
LstmLayer.cpp + LstmCompute.cu (lstmemory: input is the 4H-wide projection,
peephole 'check' weights, gate/state activations), GatedRecurrentLayer.cpp +
GruCompute.cu (gated_unit: 3H-wide input), and the bidirectional composites
bidirectional_lstm/gru (trainer_config_helpers/networks.py). Execution is a
lax.scan over time-major padded batches (see paddle_tpu/ops/rnn.py) rather
than SequenceToBatch reordering."""

from __future__ import annotations

from typing import Any, List, Optional

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn import init as init_mod
from paddle_tpu.nn.graph import Argument, Context, Layer
from paddle_tpu.nn.layers import Fc, _attr
from paddle_tpu.nn import activations as act_mod
from paddle_tpu.ops import rnn as rnn_ops


@LAYERS.register("lstmemory")
class Lstm(Layer):
    """lstmemory (LstmLayer.cpp): input must be size 4H (pre-projected, as the
    reference requires a preceding fc/mixed layer). use_peephole matches the
    'check' weights of hl_lstm."""

    type_name = "lstmemory"

    def __init__(
        self,
        input: Layer,
        size: Optional[int] = None,
        reverse: bool = False,
        act: Any = "tanh",
        gate_act: Any = "sigmoid",
        state_act: Any = "tanh",
        use_peephole: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.reverse = reverse
        self.act = act
        self.gate_act = gate_act
        self.state_act = state_act
        self.use_peephole = use_peephole
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        arg = ins[0]
        assert arg.is_seq, f"{self.name}: lstmemory needs a sequence input"
        proj = arg.value
        hdim = self.size or proj.shape[-1] // 4
        assert proj.shape[-1] == 4 * hdim, (
            f"{self.name}: input width {proj.shape[-1]} != 4*size ({4 * hdim})"
        )
        w_hh = ctx.param(
            self, "w_hh", (hdim, 4 * hdim), init_mod.smart_normal, self.param_attr
        )
        bias = ctx.param(self, "b", (4 * hdim,), init_mod.zeros, self.bias_attr)
        checks = (None, None, None)
        if self.use_peephole:
            checks = tuple(
                ctx.param(self, f"check_{g}", (hdim,), init_mod.zeros, None)
                for g in ("i", "f", "o")
            )
        p = rnn_ops.LstmParams(w_hh, bias, *checks)
        mask = arg.mask(proj.dtype)
        hs, h_last, c_last = rnn_ops.lstm_scan(
            proj,
            mask,
            p,
            reverse=self.reverse,
            gate_act=self.gate_act,
            cell_act=self.act,
            state_act=self.state_act,
        )
        return Argument(hs, arg.lengths)


@LAYERS.register("gated_unit", "grumemory")
class Gru(Layer):
    """grumemory (GatedRecurrentLayer.cpp): input must be size 3H."""

    type_name = "grumemory"

    def __init__(
        self,
        input: Layer,
        size: Optional[int] = None,
        reverse: bool = False,
        act: Any = "tanh",
        gate_act: Any = "sigmoid",
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.reverse = reverse
        self.act = act
        self.gate_act = gate_act
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        arg = ins[0]
        assert arg.is_seq, f"{self.name}: grumemory needs a sequence input"
        proj = arg.value
        hdim = self.size or proj.shape[-1] // 3
        assert proj.shape[-1] == 3 * hdim
        w_hzr = ctx.param(
            self, "w_hzr", (hdim, 2 * hdim), init_mod.smart_normal, self.param_attr
        )
        # w_hc has a different shape than w_hzr — a shared param_attr name must
        # not collide, so derive a distinct sharing key for it
        c_attr = self.param_attr
        if c_attr is not None and c_attr.name:
            import dataclasses as _dc

            c_attr = _dc.replace(c_attr, name=c_attr.name + ".c")
        w_hc = ctx.param(
            self, "w_hc", (hdim, hdim), init_mod.smart_normal, c_attr
        )
        bias = ctx.param(self, "b", (3 * hdim,), init_mod.zeros, self.bias_attr)
        p = rnn_ops.GruParams(w_hzr, w_hc, bias)
        mask = arg.mask(proj.dtype)
        hs, h_last = rnn_ops.gru_scan(
            proj, mask, p, reverse=self.reverse,
            gate_act=self.gate_act, cand_act=self.act,
        )
        return Argument(hs, arg.lengths)


@LAYERS.register("recurrent")
class SimpleRnn(Layer):
    """Vanilla full-matrix recurrence (RecurrentLayer.cpp). Input size == H."""

    type_name = "recurrent"

    def __init__(
        self,
        input: Layer,
        act: Any = "tanh",
        reverse: bool = False,
        bias: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.act = act
        self.reverse = reverse
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        arg = ins[0]
        assert arg.is_seq
        proj = arg.value
        hdim = proj.shape[-1]
        w_hh = ctx.param(
            self, "w_hh", (hdim, hdim), init_mod.smart_normal, self.param_attr
        )
        b = (
            ctx.param(self, "b", (hdim,), init_mod.zeros, self.bias_attr)
            if self.bias
            else None
        )
        hs, _ = rnn_ops.simple_rnn_scan(
            proj, arg.mask(proj.dtype), w_hh, b, self.act, reverse=self.reverse
        )
        return Argument(hs, arg.lengths)


def simple_lstm(
    input: Layer,
    size: int,
    reverse: bool = False,
    name: str = "lstm",
    **lstm_kwargs: Any,
) -> Layer:
    """fc(4H) + lstmemory — the simple_lstm helper
    (trainer_config_helpers/networks.py:553)."""
    proj = Fc(input, 4 * size, act=None, name=f"{name}.input_proj")
    return Lstm(proj, size=size, reverse=reverse, name=name, **lstm_kwargs)


def simple_gru(
    input: Layer, size: int, reverse: bool = False, name: str = "gru", **kw: Any
) -> Layer:
    """fc(3H) + grumemory (networks.py:981 simple_gru)."""
    proj = Fc(input, 3 * size, act=None, name=f"{name}.input_proj")
    return Gru(proj, size=size, reverse=reverse, name=name, **kw)


def bidirectional_lstm(
    input: Layer, size: int, name: str = "bilstm", **kw: Any
) -> Layer:
    """Concat of forward+backward lstm (networks.py bidirectional_lstm)."""
    from paddle_tpu.nn.layers import Concat

    fwd = simple_lstm(input, size, reverse=False, name=f"{name}.fw", **kw)
    bwd = simple_lstm(input, size, reverse=True, name=f"{name}.bw", **kw)
    return Concat([fwd, bwd], name=f"{name}.cat")


def bidirectional_gru(input: Layer, size: int, name: str = "bigru", **kw: Any) -> Layer:
    from paddle_tpu.nn.layers import Concat

    fwd = simple_gru(input, size, reverse=False, name=f"{name}.fw", **kw)
    bwd = simple_gru(input, size, reverse=True, name=f"{name}.bw", **kw)
    return Concat([fwd, bwd], name=f"{name}.cat")


@LAYERS.register("mdlstmemory")
class MDLstm(Layer):
    """2-D multi-dimensional LSTM (MDLstmLayer.cpp:180). Input is the
    pre-projected grid [B, H, W, 5*size] (same convention as lstmemory's 4H:
    a preceding fc/mixed supplies x·Wx); output [B, H, W, size]. The grid is
    walked as a wavefront — see ops/mdlstm.py. directions[d]=False reverses
    dimension d (the reference's per-dim direction flags)."""

    type_name = "mdlstmemory"

    def __init__(
        self,
        input: Layer,
        size: Optional[int] = None,
        directions=(True, True),
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.directions = tuple(directions)
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        from paddle_tpu.ops import mdlstm as md_ops

        proj = ins[0].value
        assert proj.ndim == 4, (
            f"{self.name}: mdlstmemory needs a [B, H, W, 5*size] grid input"
        )
        hid = self.size or proj.shape[-1] // 5
        assert proj.shape[-1] == 5 * hid, (
            f"{self.name}: input width {proj.shape[-1]} != 5*size ({5 * hid})"
        )
        p = md_ops.MDLstmParams(
            w_h=ctx.param(self, "w_h", (hid, 5 * hid), init_mod.smart_normal,
                          self.param_attr),
            bias=ctx.param(self, "b", (5 * hid,), init_mod.zeros, self.bias_attr),
            check_i=ctx.param(self, "check_i", (hid,), init_mod.zeros, None),
            check_f=ctx.param(self, "check_f", (2, hid), init_mod.zeros, None),
            check_o=ctx.param(self, "check_o", (hid,), init_mod.zeros, None),
        )
        out = md_ops.mdlstm_2d(proj, p, self.directions)
        return ins[0].with_value(out)


@LAYERS.register("lstm_step")
class LstmStep(Layer):
    """LstmStepLayer.cpp: one LSTM cell step for recurrent groups. Inputs:
    (projected [B, 4H] = Wx + Uh already mixed by the caller, cell state
    memory [B, H]). Output: h; the new cell state is published under
    `{name}::state` for StepArgOutput (the reference's two-arg output +
    get_output_layer(arg_name='state'))."""

    type_name = "lstm_step"

    def __init__(self, input: Layer, state: Layer, size: int,
                 act: Any = "tanh", gate_act: Any = "sigmoid",
                 state_act: Any = "tanh", bias: bool = True,
                 bias_attr: Any = None, name=None):
        super().__init__([input, state], name=name)
        self.size = size
        self.act = act or "tanh"
        self.gate_act = gate_act or "sigmoid"
        self.state_act = state_act or "tanh"
        self.bias = bias
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx, ins):
        m, c_prev = ins[0].value, ins[1].value
        hid = self.size
        assert m.shape[-1] == 4 * hid, (
            f"{self.name}: lstm_step input width {m.shape[-1]} != 4*size"
        )
        ci = cf = co = 0.0
        if self.bias:
            # the step layer's own parameter is the [3H] peephole block
            # (checkI/checkF/checkO — LstmStepLayer's bias in the reference;
            # the additive 4H gate bias lives in the input projection)
            b = ctx.param(self, "b", (3 * hid,), init_mod.zeros, self.bias_attr)
            ci, cf, co = b[:hid], b[hid : 2 * hid], b[2 * hid :]
        gi = act_mod.apply(self.gate_act, m[..., :hid] + ci * c_prev)
        gf = act_mod.apply(self.gate_act, m[..., hid : 2 * hid] + cf * c_prev)
        gc = act_mod.apply(self.act, m[..., 2 * hid : 3 * hid])
        c = gf * c_prev + gi * gc
        go = act_mod.apply(self.gate_act, m[..., 3 * hid :] + co * c)
        h = go * act_mod.apply(self.state_act, c)
        ctx.cache[f"{self.name}::state"] = Argument(c)
        return Argument(h)


@LAYERS.register("gru_step", "gru_step_naive")
class GruStep(Layer):
    """GruStepLayer.cpp: one GRU step. Inputs: (projected [B, 3H] = Wx,
    previous output memory [B, H])."""

    type_name = "gru_step"

    def __init__(self, input: Layer, output_mem: Layer, size: int,
                 act: Any = "tanh", gate_act: Any = "sigmoid",
                 bias: bool = True, bias_attr: Any = None,
                 param_attr: Any = None, name=None):
        super().__init__([input, output_mem], name=name)
        self.size = size
        self.act = act or "tanh"
        self.gate_act = gate_act or "sigmoid"
        self.bias = bias
        self.bias_attr = _attr(bias_attr)
        self.param_attr = _attr(param_attr)

    def forward(self, ctx, ins):
        m, h_prev = ins[0].value, ins[1].value
        hid = self.size
        assert m.shape[-1] == 3 * hid, (
            f"{self.name}: gru_step input width {m.shape[-1]} != 3*size"
        )
        # recurrent weights (GruStepLayer holds U_{z,r} and U_c)
        w_hzr = ctx.param(
            self, "w_hzr", (hid, 2 * hid), init_mod.smart_normal, self.param_attr
        )
        c_attr = self.param_attr
        if c_attr is not None and c_attr.name:
            import dataclasses as _dc

            c_attr = _dc.replace(c_attr, name=c_attr.name + ".c")
        w_hc = ctx.param(self, "w_hc", (hid, hid), init_mod.smart_normal, c_attr)
        if self.bias:
            b = ctx.param(self, "b", (3 * hid,), init_mod.zeros, self.bias_attr)
            m = m + b
        zr = m[..., : 2 * hid] + h_prev @ w_hzr
        z = act_mod.apply(self.gate_act, zr[..., :hid])
        r = act_mod.apply(self.gate_act, zr[..., hid:])
        c = act_mod.apply(self.act, m[..., 2 * hid :] + (r * h_prev) @ w_hc)
        return Argument((1.0 - z) * h_prev + z * c)


@LAYERS.register("step_arg_output")
class StepArgOutput(Layer):
    """In-step get_output_layer: reads a named auxiliary output another step
    layer published (GetOutputLayer over Argument args, gserver
    GetOutputLayer.cpp)."""

    type_name = "step_arg_output"

    def __init__(self, input: Layer, arg_name: str, name=None):
        super().__init__(input, name=name)
        self.arg_name = arg_name

    def forward(self, ctx, ins):
        key = f"{self.inputs[0].name}::{self.arg_name}"
        if key not in ctx.cache:
            raise ValueError(
                f"{self.name}: {self.inputs[0].name} published no "
                f"{self.arg_name!r} output"
            )
        return ctx.cache[key]
