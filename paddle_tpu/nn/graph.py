"""Functional layer-graph core.

The TPU-native replacement for the reference's Layer/NeuralNetwork machinery
(paddle/gserver/layers/Layer.h:62 `forward`/`backward`; NeuralNetwork.cpp:245
forward = ordered loop over layers). Key design shift (SURVEY §7 "hard parts"):
instead of eager per-layer kernel calls, layers here are *pure specs*; the whole
forward pass is one traced JAX function, so XLA sees the entire step and fuses /
schedules it for the MXU. Backward is `jax.grad` of the traced forward — there are
no hand-written backward methods (the reference's per-layer `backward` and its
gradient-check harness become `jax.grad` + numeric-check tests).

Data between layers travels as `Argument` — the analog of paddle/parameter/Argument.h:26
(value + sequenceStartPositions). Ragged sequences become padded [B, T, ...] arrays
plus a per-example `lengths` vector (segment-id style), the TPU-friendly encoding of
`Argument.sequenceStartPositions` (Argument.h:84).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import dtypes

Array = jax.Array
Initializer = Callable[[jax.Array, Sequence[int], Any], Array]

# Reserved batch slot: [B] float 0/1 row-validity mask attached when a
# trailing batch is padded up to the mesh data-axis multiple
# (DataParallel.pad_batch). Network._run strips it into Context.sample_mask;
# cost layers weight per-example costs by it and normalize by the real row
# count, so padded rows contribute nothing to cost or gradients.
SAMPLE_MASK_KEY = "__sample_mask__"


# ---------------------------------------------------------------------------
# Argument: the inter-layer value (paddle/parameter/Argument.h:26)
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Argument:
    """Value flowing between layers.

    value:   [B, ...] dense batch, or [B, T, ...] padded sequence batch.
    lengths: [B] int32 valid lengths when `value` is a sequence batch
             (replaces Argument.sequenceStartPositions, Argument.h:84).
    sub_lengths: [B, S] int32 for nested (sub-)sequences
             (replaces subSequenceStartPositions, Argument.h:91).
    """

    value: Array
    lengths: Optional[Array] = None
    sub_lengths: Optional[Array] = None

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        return (self.value, self.lengths, self.sub_lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- helpers ------------------------------------------------------------
    @property
    def is_seq(self) -> bool:
        return self.lengths is not None

    @property
    def batch_size(self) -> int:
        return self.value.shape[0]

    @property
    def max_len(self) -> int:
        assert self.is_seq
        return self.value.shape[1]

    def mask(self, dtype=jnp.float32) -> Array:
        """[B, T] validity mask from lengths."""
        assert self.lengths is not None
        t = self.value.shape[1]
        return (jnp.arange(t)[None, :] < self.lengths[:, None]).astype(dtype)

    def with_value(self, value: Array) -> "Argument":
        return Argument(value, self.lengths, self.sub_lengths)

    def as_non_seq(self) -> "Argument":
        return Argument(self.value)


# ---------------------------------------------------------------------------
# ParamAttr (python/paddle/trainer_config_helpers/attrs.py ParamAttr)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ParamAttr:
    """Per-parameter attributes: sharing name, init, LR scale, decay, staticness.

    Mirrors the reference's ParameterConfig knobs (proto/ParameterConfig.proto:34:
    learning_rate, momentum, decay_rate(l2), decay_rate_l1, initial_std/mean,
    is_static, is_sparse) minus device placement, which is a sharding concern here.
    """

    name: Optional[str] = None  # set → parameter shared by this global name
    initializer: Optional[Initializer] = None
    initial_std: Optional[float] = None
    initial_mean: float = 0.0
    learning_rate: float = 1.0
    momentum: Optional[float] = None
    l1_decay: Optional[float] = None
    l2_decay: Optional[float] = None
    is_static: bool = False
    is_sparse: bool = False
    gradient_clipping_threshold: Optional[float] = None
    # uniform init range (ParameterConfig initial_min/initial_max); wins over
    # initial_std when set
    initial_min: Optional[float] = None
    initial_max: Optional[float] = None
    # NAMED logical sharding axes resolved through the parallel rules table
    # (parallel/rules.py DEFAULT_RULES), e.g. ("embed", "mlp") — declare the
    # axis MEANING once here; which mesh axis (if any) it shards over is the
    # deployment's rules-table decision (ISSUE 12).
    logical_axes: Optional[Tuple[Optional[str], ...]] = None
    # DEPRECATED: raw mesh-axis tuples, e.g. ("model", None). Kept as a shim —
    # mesh-axis names are implicitly logical names that resolve to themselves
    # through the rules table — so old call sites translate into the table
    # rather than bypassing it. New code should use logical_axes.
    sharding: Optional[Tuple[Optional[str], ...]] = None


# ---------------------------------------------------------------------------
# Context: parameter/state plumbing through a forward trace
# ---------------------------------------------------------------------------


class Context:
    """Threaded through a single forward trace.

    mode='init'  — creates parameters/states eagerly (concrete arrays).
    mode='apply' — reads from given pytrees; collects state updates (e.g.
                   batch-norm moving stats — the functional form of the mutable
                   movingMean_/movingVar_ in BatchNormalizationLayer).
    """

    def __init__(
        self,
        mode: str,
        params: Dict[str, Array],
        states: Dict[str, Array],
        rng: Optional[Array],
        train: bool,
        policy: Optional[dtypes.Policy] = None,
        param_resolver: Optional[Callable[[str, Array], Array]] = None,
    ):
        assert mode in ("init", "apply")
        self.mode = mode
        self.params = params
        self.states = states
        self.rng = rng
        self.train = train
        self.policy = policy or dtypes.current()
        # ZeRO-3 on-demand gather seam (ISSUE 14): in apply mode, a resolver
        # rebuilds a stored parameter's full view AT ITS POINT OF USE — the
        # Zero3Updater passes the all-gather of its flat data-axis-sharded
        # leaf, so each layer's gather is emitted next to its consumer in
        # the trace (layer-by-layer, not hoisted as one bulk gather) and the
        # backward's remat re-gathers per use. Memoized per trace below so a
        # SHARED parameter gathers once. None = params are stored full.
        self.param_resolver = param_resolver
        self.state_updates: Dict[str, Array] = {}
        self.param_attrs: Dict[str, ParamAttr] = {}
        self._rng_count = 0
        # per-trace scratch for composite layers that compute several outputs
        # at once (e.g. RecurrentGroup runs one scan shared by all its output
        # nodes); keyed by (id(core), tag)
        self.cache: Dict[Any, Any] = {}
        # [B] 0/1 weights from a padded batch (SAMPLE_MASK_KEY slot): cost
        # layers zero padded rows out of the loss and normalize by the REAL
        # row count, so a mesh-divisibility-padded batch reproduces the
        # unpadded batch's cost and gradients exactly
        self.sample_mask: Optional[Array] = None

    # -- rng ---------------------------------------------------------------
    def next_rng(self, tag: str) -> Array:
        if self.rng is None:
            raise ValueError("no rng available in this context (pass rng= to apply)")
        self._rng_count += 1
        return jax.random.fold_in(jax.random.fold_in(self.rng, _stable_hash(tag)), self._rng_count)

    # -- params ------------------------------------------------------------
    def param(
        self,
        layer: "Layer",
        pname: str,
        shape: Sequence[int],
        init: Initializer,
        attr: Optional[ParamAttr] = None,
    ) -> Array:
        attr = attr or ParamAttr()
        full = attr.name or f"{layer.name}.{pname}"
        if not hasattr(self, "param_owners"):
            self.param_owners = {}
        self.param_owners.setdefault((layer.name, pname), full)
        if self.mode == "init":
            if full not in self.params:
                initializer = attr.initializer or init
                if attr.initial_max is not None and attr.initializer is None:
                    lo = attr.initial_min if attr.initial_min is not None else -attr.initial_max
                    hi = attr.initial_max
                    initializer = (
                        lambda k, s, d: jax.random.uniform(
                            k, s, d, minval=lo, maxval=hi
                        )
                    )
                elif attr.initial_std is not None and attr.initializer is None:
                    std, mean = attr.initial_std, attr.initial_mean
                    initializer = (
                        lambda k, s, d: mean + std * jax.random.normal(k, s, d)
                    )
                elif attr.initializer is None and _param_default:
                    std = _param_default.get("initial_std")
                    mean = _param_default.get("initial_mean", 0.0)
                    if std is not None:
                        initializer = (
                            lambda k, s, d: mean + std * jax.random.normal(k, s, d)
                        )
                value = initializer(
                    self.next_rng(full), tuple(shape), self.policy.param_dtype
                )
                self.params[full] = value
                self.param_attrs[full] = attr
            else:
                got = tuple(self.params[full].shape)
                if got != tuple(shape):
                    raise ValueError(
                        f"shared parameter {full!r} shape mismatch: {got} vs {tuple(shape)}"
                    )
        value = self.params[full]
        if self.mode == "apply" and self.param_resolver is not None:
            key = ("__param_resolved__", full)
            if key not in self.cache:
                self.cache[key] = self.param_resolver(full, value)
            value = self.cache[key]
        return value

    # -- state (non-trainable, updated functionally) ------------------------
    def state(
        self,
        layer: "Layer",
        sname: str,
        shape: Sequence[int],
        init_value: Union[float, Array] = 0.0,
    ) -> Array:
        full = f"{layer.name}.{sname}"
        if self.mode == "init" and full not in self.states:
            self.states[full] = jnp.full(tuple(shape), init_value, dtype=jnp.float32)
        return self.states[full]

    def update_state(self, layer: "Layer", sname: str, value: Array) -> None:
        full = f"{layer.name}.{sname}"
        self.state_updates[full] = value


def _stable_hash(s: str) -> int:
    h = 2166136261
    for ch in s.encode():
        h = ((h ^ ch) * 16777619) & 0x7FFFFFFF
    return h


# ---------------------------------------------------------------------------
# Layer base + naming
# ---------------------------------------------------------------------------

_name_lock = threading.Lock()
_name_counters: Dict[str, int] = {}

# legacy config default init policy (config_parser default_initial_std/mean);
# consumed by Context.param when a parameter has no explicit init
_param_default: Dict[str, float] = {}


def _auto_name(type_name: str) -> str:
    with _name_lock:
        idx = _name_counters.get(type_name, 0)
        _name_counters[type_name] = idx + 1
    return f"__{type_name}_{idx}__"


def reset_name_scope() -> None:
    """Reset auto-name counters (call between independently-built graphs)."""
    _param_default.clear()
    with _name_lock:
        _name_counters.clear()


_record_tls = threading.local()


@contextlib.contextmanager
def record_layers(sink: List["Layer"]):
    """Collect every Layer constructed inside the block (used by
    recurrent_group to see step-net layers that are not output ancestors,
    e.g. a last_seq serving only as a memory link target)."""
    old = getattr(_record_tls, "sink", None)
    _record_tls.sink = sink
    try:
        yield sink
    finally:
        _record_tls.sink = old


class Layer:
    """A pure layer spec node in the graph.

    Subclasses implement `forward(ctx, ins) -> Argument`. No backward: autodiff
    handles it. `type_name` doubles as the registry key (REGISTER_LAYER analog).
    """

    type_name: str = "layer"
    # cost layers (scalar training objectives) mark themselves so the trainer
    # can split a config's Outputs() into costs vs plain fetches (the
    # reference's Outputs may mix both, sample_trainer_config_qb_rnn.conf)
    is_cost: bool = False

    def __init__(
        self,
        inputs: Union[None, "Layer", Sequence["Layer"]] = None,
        name: Optional[str] = None,
        **kwargs: Any,
    ):
        if inputs is None:
            inputs = []
        elif isinstance(inputs, Layer):
            inputs = [inputs]
        else:
            inputs = list(inputs)
        for i, l in enumerate(inputs):
            if not isinstance(l, Layer):
                raise TypeError(
                    f"{type(self).__name__} input {i} is {type(l).__name__}, not a Layer"
                )
        self.inputs: List[Layer] = inputs
        self.name = name or _auto_name(self.type_name)
        self.cfg = kwargs
        sink = getattr(_record_tls, "sink", None)
        if sink is not None:
            sink.append(self)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        raise NotImplementedError

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"


# ---------------------------------------------------------------------------
# Network: topological execution of a layer DAG
# ---------------------------------------------------------------------------


class Network:
    """Compiles a layer DAG into pure init/apply functions.

    The analog of NeuralNetwork (gserver/gradientmachines/NeuralNetwork.cpp:245):
    topological order once, then `apply` evaluates each layer exactly once. Unlike
    the reference, `apply` is pure and intended to be called *inside* jit/pjit so
    the whole step compiles to one XLA program (SURVEY §7 hard-part (1))."""

    def __init__(self, outputs: Union[Layer, Sequence[Layer]]):
        if isinstance(outputs, Layer):
            outputs = [outputs]
        self.outputs: List[Layer] = list(outputs)
        self.layer_order: List[Layer] = _topo_sort(self.outputs)
        self.layers_by_name: Dict[str, Layer] = {}
        for l in self.layer_order:
            if l.name in self.layers_by_name and self.layers_by_name[l.name] is not l:
                raise ValueError(f"duplicate layer name {l.name!r}")
            self.layers_by_name[l.name] = l
        self.param_attrs: Dict[str, ParamAttr] = {}

    # -- data layer discovery ----------------------------------------------
    @property
    def data_names(self) -> List[str]:
        return [l.name for l in self.layer_order if l.type_name == "data"]

    # -- init ---------------------------------------------------------------
    def init(
        self,
        rng: Array,
        batch: Dict[str, Union[Argument, Array, np.ndarray]],
        train: bool = True,
        policy: Optional[dtypes.Policy] = None,
    ) -> Tuple[Dict[str, Array], Dict[str, Array]]:
        """Create params/states by running forward eagerly on a sample batch.

        `policy` pins the dtype policy for this trace (mixed-precision
        trainers thread SGDTrainer(precision=...) through here); None falls
        back to the ambient dtypes.current() global. The whole trace runs
        under a policy_scope so nested ops that consult the ambient global
        themselves (ops/rnn, additive attention, beam search) follow THIS
        trace's policy, not whatever the process global happens to be."""
        policy = policy or dtypes.current()
        params: Dict[str, Array] = {}
        states: Dict[str, Array] = {}
        with dtypes.policy_scope(policy):
            ctx = Context("init", params, states, rng, train, policy=policy)
            self._run(ctx, batch)
        self.param_attrs = dict(ctx.param_attrs)
        return params, states

    # -- apply --------------------------------------------------------------
    def apply(
        self,
        params: Dict[str, Array],
        states: Dict[str, Array],
        batch: Dict[str, Any],
        train: bool = False,
        rng: Optional[Array] = None,
        policy: Optional[dtypes.Policy] = None,
        param_resolver: Optional[Callable[[str, Array], Array]] = None,
    ) -> Tuple[Dict[str, Argument], Dict[str, Array]]:
        """Pure forward. Returns ({output_layer_name: Argument}, new_states).

        Like init(), the trace is wrapped in a policy_scope so every nested
        dtypes.current() fallback resolves to this trace's policy.

        `param_resolver(name, stored_value)` rebuilds a parameter's full
        view at its point of use (Context.param) — the ZeRO-3 on-demand
        gather seam; None (default) means `params` already hold full
        values."""
        policy = policy or dtypes.current()
        with dtypes.policy_scope(policy):
            ctx = Context(
                "apply", params, states, rng, train, policy=policy,
                param_resolver=param_resolver,
            )
            values = self._run(ctx, batch)
        new_states = dict(states)
        new_states.update(ctx.state_updates)
        outs = {l.name: values[l.name] for l in self.outputs}
        return outs, new_states

    def _run(self, ctx: Context, batch: Dict[str, Any]) -> Dict[str, Argument]:
        from paddle_tpu.core import stack_trace

        if SAMPLE_MASK_KEY in batch:
            # reserved slot from a mesh-divisibility-padded batch: it feeds
            # the cost layers' masking via the context, never a data layer
            ctx.sample_mask = jnp.asarray(batch[SAMPLE_MASK_KEY])
            batch = {k: v for k, v in batch.items() if k != SAMPLE_MASK_KEY}
        values: Dict[str, Argument] = {}
        for layer in self.layer_order:
            if layer.type_name == "data":
                values[layer.name] = _feed_to_argument(batch, layer)
                continue
            ins = [values[l.name] for l in layer.inputs]
            # layer-name crash context (CustomStackTrace parity,
            # NeuralNetwork.cpp:259-261)
            with stack_trace.layer_frame(layer.name):
                try:
                    out = layer.forward(ctx, ins)
                except stack_trace.LayerError:
                    raise
                except Exception as e:
                    raise stack_trace.LayerError(
                        layer.name, stack_trace.current_stack(), e
                    ) from e
            if not isinstance(out, Argument):
                raise TypeError(
                    f"layer {layer.name} forward returned {type(out).__name__}"
                )
            values[layer.name] = out
        return values


def _topo_sort(outputs: Sequence[Layer]) -> List[Layer]:
    order: List[Layer] = []
    seen: Dict[int, int] = {}  # id -> 0 visiting, 1 done

    def visit(l: Layer):
        key = id(l)
        st = seen.get(key)
        if st == 1:
            return
        if st == 0:
            raise ValueError(f"cycle in layer graph at {l.name}")
        seen[key] = 0
        for dep in l.inputs:
            visit(dep)
        seen[key] = 1
        order.append(l)

    for out in outputs:
        visit(out)
    return order


def _feed_to_argument(batch: Dict[str, Any], layer: Layer) -> Argument:
    if layer.name not in batch:
        raise KeyError(
            f"data layer {layer.name!r} missing from batch; got {sorted(batch)}"
        )
    v = batch[layer.name]
    if isinstance(v, Argument):
        return v
    v = jnp.asarray(v)
    lengths_key = layer.name + ".lengths"
    if lengths_key in batch:
        sub_key = layer.name + ".sub_lengths"
        sub = jnp.asarray(batch[sub_key]) if sub_key in batch else None
        return Argument(v, jnp.asarray(batch[lengths_key]), sub)
    return Argument(v)
