"""Attention + attention-decoder layers.

AttentionDecoder is the TPU-native replacement for the reference's
RecurrentGradientMachine-driven NMT decoder (the recurrent_group +
simple_attention + gru_step composition of demo/seq2seq; RecurrentGradientMachine.h:32
dynamic unroll): one lax.scan over target steps with teacher forcing at train
time. Generation/beam search lives in paddle_tpu/nn/beam_search.py using the
same parameters.

The jnp attention math here (and in ops/attention.py) is the CPU oracle for
the fused Pallas attention kernel (ops/pallas/rnn_kernels.attention_seq_fused,
ISSUE 9): dot_product_attention auto-dispatches to the kernel on TPU, while
the ADDITIVE (Bahdanau) per-step attention below stays the lax.scan path —
fusing it into the decoder step is a named ROADMAP item 2 lever."""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn import init as init_mod
from paddle_tpu.nn.graph import Argument, Context, Layer
from paddle_tpu.ops import attention as attn_ops
from paddle_tpu.ops import linalg
from paddle_tpu.ops import rnn as rnn_ops


@LAYERS.register("simple_attention")
class SimpleAttention(Layer):
    """simple_attention (networks.py:1304): additive attention of a decoder
    state over an encoder sequence → context vector [B, D]."""

    type_name = "simple_attention"

    def __init__(self, enc: Layer, dec_state: Layer, attention_size: int = 0, name=None):
        super().__init__([enc, dec_state], name=name)
        self.attention_size = attention_size

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        enc, dec = ins
        assert enc.is_seq
        d_enc = enc.value.shape[-1]
        d_dec = dec.value.shape[-1]
        a = self.attention_size or d_dec
        w_enc = ctx.param(self, "w_enc", (d_enc, a), init_mod.smart_normal, None)
        w_dec = ctx.param(self, "w_dec", (d_dec, a), init_mod.smart_normal, None)
        v = ctx.param(self, "v", (a,), init_mod.smart_normal, None)
        enc_proj = linalg.matmul(enc.value, w_enc)
        context, _ = attn_ops.additive_attention(
            enc.value, enc_proj, dec.value, w_dec, v, enc.lengths
        )
        return Argument(context)


class DecoderParams(NamedTuple):
    """Everything the attention-GRU decoder step needs — shared between the
    training scan and beam-search generation."""

    w_enc: jax.Array  # [De, A] attention encoder proj
    w_dec: jax.Array  # [H, A] attention decoder proj
    v: jax.Array  # [A]
    w_in: jax.Array  # [Demb+De, 3H] input projection for the GRU
    gru: rnn_ops.GruParams
    w_init: jax.Array  # [De, H] initial-state projection (from enc last/back)


@LAYERS.register("attention_decoder")
class AttentionDecoder(Layer):
    """Teacher-forced attention decoder (training path).

    inputs: [encoder_seq [B,Ts,De], target_embedding_seq [B,Tt,Demb]]
    output: decoder hidden states [B, Tt, H] (project with Fc for logits).

    Step t attends with the *previous* hidden state, then
    GRU(input=[emb_t, context_t]) — matching the reference decoder composition
    (demo seq2seq gru_decoder_with_attention)."""

    type_name = "attention_decoder"

    def __init__(
        self,
        enc: Layer,
        target_emb: Layer,
        size: int,
        attention_size: int = 0,
        name: Optional[str] = None,
    ):
        super().__init__([enc, target_emb], name=name)
        self.size = size
        self.attention_size = attention_size

    def _params(self, ctx: Context, d_enc: int, d_emb: int) -> DecoderParams:
        h = self.size
        a = self.attention_size or h
        return DecoderParams(
            w_enc=ctx.param(self, "att.w_enc", (d_enc, a), init_mod.smart_normal, None),
            w_dec=ctx.param(self, "att.w_dec", (h, a), init_mod.smart_normal, None),
            v=ctx.param(self, "att.v", (a,), init_mod.smart_normal, None),
            w_in=ctx.param(
                self, "w_in", (d_emb + d_enc, 3 * h), init_mod.smart_normal, None
            ),
            gru=rnn_ops.GruParams(
                w_hzr=ctx.param(self, "gru.w_hzr", (h, 2 * h), init_mod.smart_normal, None),
                w_hc=ctx.param(self, "gru.w_hc", (h, h), init_mod.smart_normal, None),
                bias=ctx.param(self, "gru.b", (3 * h,), init_mod.zeros, None),
            ),
            w_init=ctx.param(self, "w_init", (d_enc, h), init_mod.smart_normal, None),
        )

    def initial_state(self, p: DecoderParams, enc_value, enc_lengths):
        """h0 = tanh(W @ first-step backward encoder state) — the reference
        seeds the decoder from the encoder's first backward state."""
        from paddle_tpu.ops import sequence as seq_ops

        first = seq_ops.seq_first(enc_value)
        return jnp.tanh(linalg.matmul(first, p.w_init))

    def step(self, p: DecoderParams, enc_value, enc_proj, enc_lengths, emb_t, h):
        d_emb = emb_t.shape[-1]
        proj_emb = linalg.matmul(emb_t, p.w_in[:d_emb])
        return self._step_proj(p, enc_value, enc_proj, enc_lengths, proj_emb, h, d_emb)

    def _step_proj(self, p: DecoderParams, enc_value, enc_proj, enc_lengths,
                   proj_emb_t, h, d_emb: int):
        """One decoder step given the *pre-projected* embedding input
        (proj_emb_t = emb_t @ w_in[:Demb] — hoisted out of the training scan
        so the only in-scan matmuls are the ones that truly depend on h)."""
        context, _ = attn_ops.additive_attention(
            enc_value, enc_proj, h, p.w_dec, p.v, enc_lengths
        )
        proj = proj_emb_t + linalg.matmul(context, p.w_in[d_emb:])
        h_new = rnn_ops.gru_step(proj, h, p.gru)
        return h_new

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        import os

        enc, emb = ins
        assert enc.is_seq and emb.is_seq
        d_emb = emb.value.shape[-1]
        p = self._params(ctx, enc.value.shape[-1], d_emb)
        enc_proj = linalg.matmul(enc.value, p.w_enc)
        h0 = self.initial_state(p, enc.value, enc.lengths)
        mask = emb.mask(h0.dtype)
        # hoist the teacher-forced half of the GRU input projection: one
        # [B, T, Demb] @ [Demb, 3H] MXU matmul instead of T tiny in-scan ones
        # (r4 profile: the scan body ran at 0.4 TFLOP/s before the hoist)
        proj_emb = linalg.matmul(emb.value, p.w_in[:d_emb])

        def scan_step(h, xs):
            pe_t, m_t = xs
            h_new = self._step_proj(
                p, enc.value, enc_proj, enc.lengths, pe_t, h, d_emb
            )
            m = m_t[:, None]
            h = m * h_new + (1 - m) * h
            return h, h

        # remat the step: without it autodiff saves the per-step [B, Ts, A]
        # attention tensors (tanh scores, weights, context) to HBM for the
        # backward pass — ~50 steps × several MB, the dominant bandwidth of
        # the whole NMT step (r4 profile). Recomputing them in the backward
        # scan trades cheap VPU FLOPs for that traffic; only the [B, H]
        # carries are saved.
        if os.environ.get("PADDLE_TPU_DECODER_REMAT", "1") == "1":
            scan_step = jax.checkpoint(scan_step)
        xs = (jnp.swapaxes(proj_emb, 0, 1), jnp.swapaxes(mask, 0, 1))
        unroll = int(os.environ.get("PADDLE_TPU_DECODER_UNROLL", "1"))
        _, hs = lax.scan(scan_step, h0, xs, unroll=unroll)
        return Argument(jnp.swapaxes(hs, 0, 1), emb.lengths)
