"""The single beam-search engine (RecurrentGradientMachine.h:309 beamSearch).

Both generation entry points — the seq2seq fast path (nn/beam_search.py, an
AttentionDecoder specialization) and the generic v1 recurrent-group path
(nn/recurrent_group.py BeamSearchLayer) — wrap THIS scan so expansion,
finished-beam EOS masking, history bookkeeping, length penalty, and result
ordering live in exactly one place (VERDICT r2 weak #6: two drifting
implementations)."""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array
NEG_INF = -1e9


class BeamResult(NamedTuple):
    history: Array  # [B, K, L] token ids, beams sorted best-first
    scores: Array  # [B, K] cumulative log-probs (penalized if requested)
    lengths: Array  # [B, K] lengths up to and including EOS


def expand_beams(
    cand_logp: Array,  # [B, K, V] TOTAL candidate scores for live beams
    pre_scores: Array,  # [B, K] accumulated scores of the incoming beams
    finished: Array,  # [B, K] bool
    eos_id: int,
    k: int,
) -> Tuple[Array, Array, Array]:
    """One beam expansion — THE top-k + finished-EOS-masking step, shared by
    the generation scan below and the fluid `beam_search` op (one masking
    semantic, one NEG_INF convention). Finished beams propagate EOS at their
    unchanged score. Returns (top_scores [B,k], beam_idx [B,k], tok [B,k])."""
    b, _kk, v = cand_logp.shape
    eos_only = jnp.full((v,), NEG_INF).at[eos_id].set(0.0)
    cand = jnp.where(
        finished[:, :, None],
        pre_scores[:, :, None] + eos_only[None, None, :],
        cand_logp,
    )
    top_scores, top_idx = lax.top_k(cand.reshape(b, -1), k)
    return top_scores, top_idx // v, (top_idx % v).astype(jnp.int32)


def _gather_beams(tree: Any, idx: Array, batch: int, k: int) -> Any:
    """Select beams: every leaf [B*K, ...] (or [B, K, ...]) reindexed by
    idx [B, K']."""

    def one(x: Array) -> Array:
        flat = x.shape[0] == batch * k
        xb = x.reshape((batch, k) + x.shape[1:]) if flat else x
        sel = jax.vmap(lambda xx, ii: xx[ii])(xb, idx)
        return sel.reshape((batch * k,) + x.shape[1:]) if flat else sel

    return jax.tree.map(one, tree)


def beam_search_scan(
    step_fn: Callable[[Array, Any, Array], Tuple[Array, Any]],
    carry0: Any,
    batch: int,
    vocab: int,
    bos_id: int,
    eos_id: int,
    beam_size: int,
    max_len: int,
    length_penalty: float = 0.0,
) -> BeamResult:
    """Run beam search fully inside one lax.scan.

    step_fn(tokens [B*K] int32, carry, t) → (logp [B*K, V] float32 log-probs,
    new_carry); carry leaves are [B*K, ...] (already tiled across beams).
    Beam 0 is the only live beam at t=0 so the first expansion isn't K
    duplicates. Finished beams emit EOS with zero score delta."""
    k = beam_size
    tokens0 = jnp.full((batch, k), bos_id, jnp.int32)
    scores0 = jnp.tile(
        jnp.asarray([0.0] + [NEG_INF] * (k - 1), jnp.float32), (batch, 1)
    )
    finished0 = jnp.zeros((batch, k), bool)
    history0 = jnp.zeros((batch, k, max_len), jnp.int32)

    def body(state, t):
        tokens, scores, finished, history, carry = state
        logp, new_carry = step_fn(tokens.reshape(-1), carry, t)
        logp = logp.reshape(batch, k, vocab).astype(jnp.float32)
        top_scores, beam_idx, tok_idx = expand_beams(
            scores[:, :, None] + logp, scores, finished, eos_id, k
        )

        carry_sel = _gather_beams(new_carry, beam_idx, batch, k)
        fin_sel = jax.vmap(lambda f, i: f[i])(finished, beam_idx)
        hist_sel = jax.vmap(lambda h, i: h[i])(history, beam_idx)
        hist_new = lax.dynamic_update_index_in_dim(
            hist_sel.swapaxes(0, 2), tok_idx.swapaxes(0, 1), t, 0
        ).swapaxes(0, 2)
        new_finished = fin_sel | (tok_idx == eos_id)
        return (tok_idx, top_scores, new_finished, hist_new, carry_sel), None

    (_, scores, _, history, _), _ = lax.scan(
        body, (tokens0, scores0, finished0, history0, carry0),
        jnp.arange(max_len),
    )

    is_eos = history == eos_id
    any_eos = jnp.any(is_eos, axis=-1)
    first_eos = jnp.argmax(is_eos.astype(jnp.int32), axis=-1)
    lengths = jnp.where(any_eos, first_eos + 1, max_len).astype(jnp.int32)
    if length_penalty > 0:
        scores = scores / jnp.power(lengths.astype(jnp.float32), length_penalty)
    order = jnp.argsort(-scores, axis=-1)
    return BeamResult(
        history=jax.vmap(lambda h, o: h[o])(history, order),
        scores=jnp.take_along_axis(scores, order, -1),
        lengths=jnp.take_along_axis(lengths, order, -1),
    )
