"""3-D image layers — Conv3DLayer.cpp:21 / DeConv3DLayer.cpp / Pool3DLayer.cpp
parity, NDHWC layout (TPU-native: rank-5 XLA conv HLO on the MXU; the
reference lowers these through col2Vol/vol2Col GEMMs)."""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn import activations as act_mod
from paddle_tpu.nn import init as init_mod
from paddle_tpu.nn.graph import Argument, Context, Layer
from paddle_tpu.nn.layers import _attr
from paddle_tpu.ops import conv as conv_ops

Int3 = Union[int, Tuple[int, int, int]]


@LAYERS.register("conv3d")
class Conv3D(Layer):
    """3-D convolution over [B, D, H, W, C] (Conv3DLayer.cpp:21)."""

    type_name = "conv3d"

    def __init__(
        self,
        input: Layer,
        num_filters: int,
        filter_size: Int3,
        stride: Int3 = 1,
        padding: Int3 = 0,
        dilation: Int3 = 1,
        groups: int = 1,
        act: Any = None,
        bias: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.num_filters = num_filters
        self.filter_size = filter_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        assert x.ndim == 5, f"conv3d {self.name}: expect NDHWC input, got {x.shape}"
        kd, kh, kw = conv_ops._triple(self.filter_size)
        cin = x.shape[-1]
        w = ctx.param(
            self,
            "w",
            (kd, kh, kw, cin // self.groups, self.num_filters),
            init_mod.he_normal,
            self.param_attr,
        )
        out = conv_ops.conv3d(
            x, w, self.stride, self.padding, self.dilation, self.groups, ctx.policy
        )
        if self.bias:
            b = ctx.param(self, "b", (self.num_filters,), init_mod.zeros, self.bias_attr)
            out = out + b.astype(out.dtype)
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("deconv3d")
class Conv3DTranspose(Layer):
    """Transposed 3-D conv (DeConv3DLayer.cpp)."""

    type_name = "deconv3d"

    def __init__(
        self,
        input: Layer,
        num_filters: int,
        filter_size: Int3,
        stride: Int3 = 1,
        padding: Int3 = 0,
        act: Any = None,
        bias: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.num_filters = num_filters
        self.filter_size = filter_size
        self.stride = stride
        self.padding = padding
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        assert x.ndim == 5, f"deconv3d {self.name}: expect NDHWC input, got {x.shape}"
        kd, kh, kw = conv_ops._triple(self.filter_size)
        cin = x.shape[-1]
        w = ctx.param(
            self,
            "w",
            (kd, kh, kw, self.num_filters, cin),
            init_mod.he_normal,
            self.param_attr,
        )
        out = conv_ops.conv3d_transpose(x, w, self.stride, self.padding, ctx.policy)
        if self.bias:
            b = ctx.param(self, "b", (self.num_filters,), init_mod.zeros, self.bias_attr)
            out = out + b.astype(out.dtype)
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("pool3d")
class Pool3D(Layer):
    """3-D max/avg pooling over [B, D, H, W, C] (Pool3DLayer.cpp)."""

    type_name = "pool3d"

    def __init__(
        self,
        input: Layer,
        pool_size: Int3,
        pool_type: str = "max",
        stride: Optional[Int3] = None,
        padding: Int3 = 0,
        ceil_mode: bool = False,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.pool_size = pool_size
        self.pool_type = pool_type
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode

    def _pads(self, x):
        """ceil_mode: extra trailing padding so partial edge windows survive
        (the v1 outputSize rule, same as Pool2D._pads but over D/H/W)."""
        if not self.ceil_mode:
            return self.padding
        fs = conv_ops._triple(self.pool_size)
        ss = conv_ops._triple(self.stride if self.stride is not None else self.pool_size)
        ps = conv_ops._triple(self.padding)
        out = []
        for size, f, s, p in zip(x.shape[1:4], fs, ss, ps):
            n_out = -(-(size + 2 * p - f) // s) + 1
            extra = max(0, (n_out - 1) * s + f - size - 2 * p)
            out.append((p, p + extra))
        return tuple(out)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        assert x.ndim == 5, f"pool3d {self.name}: expect NDHWC input, got {x.shape}"
        pads = self._pads(x)
        if self.pool_type == "max":
            out = conv_ops.max_pool3d(x, self.pool_size, self.stride, pads)
        elif self.pool_type in ("avg", "average"):
            out = conv_ops.avg_pool3d(x, self.pool_size, self.stride, pads)
        else:
            raise ValueError(f"pool3d: unknown pool_type {self.pool_type!r}")
        return ins[0].with_value(out)
