"""Batched beam-search generation, fully inside jit.

Replaces RecurrentGradientMachine::generateSequence/beamSearch
(RecurrentGradientMachine.h:307/:309) and the SWIG SequenceGenerator
(api/PaddleAPI.h:1025). The reference builds a dynamic frame-net per step on
the host; TPU-native generation is a lax.scan over a fixed max length with
finished-beam masking — static shapes, one compiled program."""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.nn.attention_layers import AttentionDecoder, DecoderParams
from paddle_tpu.ops import linalg

Array = jax.Array
NEG_INF = -1e9


class BeamState(NamedTuple):
    tokens: Array  # [B, K] current tokens
    scores: Array  # [B, K] cumulative log-probs
    h: Array  # [B, K, H] decoder states
    finished: Array  # [B, K] bool
    history: Array  # [B, K, L] generated tokens


def _gather_beams(x: Array, idx: Array) -> Array:
    """x: [B, K, ...], idx: [B, K'] → [B, K', ...]."""
    return jax.vmap(lambda xb, ib: xb[ib])(x, idx)


def beam_search(
    decoder: AttentionDecoder,
    params: Dict[str, Array],
    enc_value: Array,  # [B, Ts, De]
    enc_lengths: Array,  # [B]
    embed_table: Array,  # [V, Demb]
    w_out: Array,  # [H, V]
    b_out: Array,  # [V]
    bos_id: int,
    eos_id: int,
    beam_size: int = 4,
    max_len: int = 50,
    length_penalty: float = 0.0,
) -> Tuple[Array, Array]:
    """→ (sequences [B, K, max_len], scores [B, K]) sorted best-first.

    decoder/params: a trained AttentionDecoder layer + the network params dict
    holding its weights (fetched by layer name, matching Context naming)."""

    def P(pname: str) -> Array:
        return params[f"{decoder.name}.{pname}"]

    from paddle_tpu.ops.rnn import GruParams

    dp = DecoderParams(
        w_enc=P("att.w_enc"),
        w_dec=P("att.w_dec"),
        v=P("att.v"),
        w_in=P("w_in"),
        gru=GruParams(
            w_hzr=P("gru.w_hzr"), w_hc=P("gru.w_hc"), bias=P("gru.b")
        ),
        w_init=P("w_init"),
    )

    b, ts, de = enc_value.shape
    k = beam_size
    h0 = decoder.initial_state(dp, enc_value, enc_lengths)  # [B, H]
    hdim = h0.shape[-1]

    # project once, then tile across beams → [B*K, ...] (projecting the tiled
    # array would redo the same matmul K times)
    enc_proj = linalg.matmul(enc_value, dp.w_enc)
    enc_t = jnp.repeat(enc_value, k, axis=0)
    enc_len_t = jnp.repeat(enc_lengths, k, axis=0)
    enc_proj_t = jnp.repeat(enc_proj, k, axis=0)

    init = BeamState(
        tokens=jnp.full((b, k), bos_id, jnp.int32),
        # only beam 0 is live initially so the first expansion isn't k copies
        scores=jnp.tile(
            jnp.asarray([0.0] + [NEG_INF] * (k - 1), jnp.float32), (b, 1)
        ),
        h=jnp.repeat(h0[:, None, :], k, axis=1),
        finished=jnp.zeros((b, k), bool),
        history=jnp.zeros((b, k, max_len), jnp.int32),
    )

    vocab = embed_table.shape[0]

    def step(state: BeamState, t: Array):
        emb_t = embed_table[state.tokens.reshape(-1)]  # [B*K, Demb]
        h_flat = state.h.reshape(b * k, hdim)
        h_new = decoder.step(dp, enc_t, enc_proj_t, enc_len_t, emb_t, h_flat)
        logits = linalg.matmul(h_new, w_out) + b_out
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(b, k, vocab)
        # finished beams may only emit EOS with no score change
        eos_only = jnp.full((vocab,), NEG_INF).at[eos_id].set(0.0)
        logp = jnp.where(state.finished[:, :, None], eos_only[None, None, :], logp)
        cand = state.scores[:, :, None] + logp  # [B, K, V]
        flat = cand.reshape(b, k * vocab)
        top_scores, top_idx = lax.top_k(flat, k)  # [B, K]
        beam_idx = top_idx // vocab
        tok_idx = (top_idx % vocab).astype(jnp.int32)

        h_sel = _gather_beams(h_new.reshape(b, k, hdim), beam_idx)
        fin_sel = _gather_beams(state.finished, beam_idx)
        hist_sel = _gather_beams(state.history, beam_idx)
        hist_new = lax.dynamic_update_index_in_dim(
            hist_sel.swapaxes(0, 2), tok_idx.swapaxes(0, 1), t, 0
        ).swapaxes(0, 2)
        new_finished = fin_sel | (tok_idx == eos_id)
        return (
            BeamState(tok_idx, top_scores, h_sel, new_finished, hist_new),
            None,
        )

    final, _ = lax.scan(step, init, jnp.arange(max_len))

    scores = final.scores
    if length_penalty > 0:
        lengths = jnp.sum((final.history != eos_id).astype(jnp.float32), axis=-1) + 1.0
        scores = scores / jnp.power(lengths, length_penalty)
    order = jnp.argsort(-scores, axis=-1)
    return _gather_beams(final.history, order), jnp.take_along_axis(scores, order, -1)
