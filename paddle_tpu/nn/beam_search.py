"""Batched beam-search generation, fully inside jit.

Replaces RecurrentGradientMachine::generateSequence/beamSearch
(RecurrentGradientMachine.h:307/:309) and the SWIG SequenceGenerator
(api/PaddleAPI.h:1025). The reference builds a dynamic frame-net per step on
the host; TPU-native generation is a lax.scan over a fixed max length with
finished-beam masking — static shapes, one compiled program."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.nn.attention_layers import AttentionDecoder, DecoderParams
from paddle_tpu.nn.beam_core import beam_search_scan
from paddle_tpu.ops import linalg

Array = jax.Array


def beam_search(
    decoder: AttentionDecoder,
    params: Dict[str, Array],
    enc_value: Array,  # [B, Ts, De]
    enc_lengths: Array,  # [B]
    embed_table: Array,  # [V, Demb]
    w_out: Array,  # [H, V]
    b_out: Array,  # [V]
    bos_id: int,
    eos_id: int,
    beam_size: int = 4,
    max_len: int = 50,
    length_penalty: float = 0.0,
) -> Tuple[Array, Array]:
    """→ (sequences [B, K, max_len], scores [B, K]) sorted best-first.

    decoder/params: a trained AttentionDecoder layer + the network params dict
    holding its weights (fetched by layer name, matching Context naming)."""

    def P(pname: str) -> Array:
        return params[f"{decoder.name}.{pname}"]

    from paddle_tpu.ops.rnn import GruParams

    dp = DecoderParams(
        w_enc=P("att.w_enc"),
        w_dec=P("att.w_dec"),
        v=P("att.v"),
        w_in=P("w_in"),
        gru=GruParams(
            w_hzr=P("gru.w_hzr"), w_hc=P("gru.w_hc"), bias=P("gru.b")
        ),
        w_init=P("w_init"),
    )

    b, ts, de = enc_value.shape
    k = beam_size
    h0 = decoder.initial_state(dp, enc_value, enc_lengths)  # [B, H]

    # project once, then tile across beams → [B*K, ...] (projecting the tiled
    # array would redo the same matmul K times)
    enc_proj = linalg.matmul(enc_value, dp.w_enc)
    enc_t = jnp.repeat(enc_value, k, axis=0)
    enc_len_t = jnp.repeat(enc_lengths, k, axis=0)
    enc_proj_t = jnp.repeat(enc_proj, k, axis=0)
    vocab = embed_table.shape[0]

    def step_fn(tokens_flat: Array, h_flat: Array, t: Array):
        emb_t = embed_table[tokens_flat]  # [B*K, Demb]
        h_new = decoder.step(dp, enc_t, enc_proj_t, enc_len_t, emb_t, h_flat)
        logits = linalg.matmul(h_new, w_out) + b_out
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return logp, h_new

    res = beam_search_scan(
        step_fn,
        jnp.repeat(h0, k, axis=0),
        batch=b,
        vocab=vocab,
        bos_id=bos_id,
        eos_id=eos_id,
        beam_size=k,
        max_len=max_len,
        length_penalty=length_penalty,
    )
    return res.history, res.scores
