"""recurrent_group / memory / beam_search — the v1 dynamic-unroll API.

Parity with RecurrentGradientMachine (gserver/gradientmachines/
RecurrentGradientMachine.h:32: per-timestep sub-network unrolling, memory
links, generation + beam search) and the trainer_config_helpers surface
(`recurrent_group`, `memory`, `StaticInput`, `GeneratedInput`, `beam_search`,
`get_output_layer` — layers.py).

TPU-native design: the reference builds a frame network per timestep on the
host (dynamic topology). Here the user's `step` function is traced ONCE at
graph-construction time into a static sub-graph of placeholder nodes; at
runtime the whole unroll is a single `lax.scan` over the padded time axis with
validity masking from sequence lengths — static shapes, one compiled program
(SURVEY §7 hard-part (2)). Generation replaces the host-side frame loop with a
scan carrying beam state (tokens/scores/memories), like nn/beam_search.py but
for arbitrary user step nets.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.nn.graph import (
    Argument,
    Context,
    Layer,
    ParamAttr,
    _topo_sort,
    record_layers,
)

Array = jax.Array
NEG_INF = -1e9


# ---------------------------------------------------------------------------
# step-net placeholders
# ---------------------------------------------------------------------------


class _Placeholder(Layer):
    """A node whose value is injected by the owning group each timestep."""

    type_name = "step_input"

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        raise RuntimeError(
            f"placeholder {self.name} evaluated outside its recurrent group"
        )


class MemoryLayer(_Placeholder):
    """`memory(name=X, size=...)`: value of step-layer X at t-1
    (SubModelConfig memory links, ModelConfig.proto:608)."""

    type_name = "memory"

    def __init__(
        self,
        link_name: str,
        size: int,
        boot_layer: Optional[Layer] = None,
        boot_bias: bool = False,
        is_seq: bool = False,
        name: Optional[str] = None,
    ):
        super().__init__(None, name=name)
        self.link_name = link_name
        self.size = size
        self.boot_layer = boot_layer
        self.boot_bias = boot_bias

    def set_input(self, layer: Layer) -> None:
        """Deferred link (layers.py memory().set_input idiom): point this
        memory at a step layer chosen after construction."""
        self.link_name = layer.name


class StaticInput:
    """Wrapper marking an outer-graph layer fed unchanged to every timestep
    (layers.py StaticInput). is_seq=True feeds the full padded sequence —
    the encoder-outputs-for-attention idiom."""

    def __init__(self, input: Layer, is_seq: bool = False, size: Optional[int] = None):
        self.input = input
        self.is_seq = is_seq
        self.size = size


class GeneratedInput:
    """Generation-time input: embedding of the previously generated token
    (layers.py GeneratedInput)."""

    def __init__(self, size: int, embedding_name: str, embedding_size: int):
        self.size = size  # vocabulary size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


class SubsequenceInput:
    """Wrapper marking a *nested*-sequence input iterated one subsequence per
    outer timestep (layers.py SubsequenceInput; RecurrentGradientMachine.h:32
    hierarchical unroll over Argument.subSequenceStartPositions, Argument.h:90).

    TPU encoding: the wrapped layer's Argument is padded [B, S, T, ...] with
    `lengths` = valid subsequence count [B] and `sub_lengths` = per-subsequence
    token counts [B, S]. Each outer step seeds the step net with the [B, T, ...]
    slice as a level-1 sequence (lengths = sub_lengths[:, s]), so an inner
    recurrent_group nests naturally — two stacked lax.scans."""

    def __init__(self, input: Layer):
        self.input = input


SubSequenceInput = SubsequenceInput  # both spellings appear in reference confs


# ---------------------------------------------------------------------------
# group build context: memory() must know the group being built
# ---------------------------------------------------------------------------


class _BuildCtx:
    def __init__(self):
        self.memories: List[MemoryLayer] = []


_tls = threading.local()


@contextlib.contextmanager
def _building(bctx: _BuildCtx):
    old = getattr(_tls, "bctx", None)
    _tls.bctx = bctx
    try:
        yield
    finally:
        _tls.bctx = old


def memory(
    name: str,
    size: int,
    boot_layer: Optional[Layer] = None,
    boot_bias: bool = False,
    is_seq: bool = False,
    **_compat,
) -> MemoryLayer:
    bctx = getattr(_tls, "bctx", None)
    if bctx is None:
        raise RuntimeError("memory() must be called inside a recurrent_group step")
    m = MemoryLayer(name, size, boot_layer, boot_bias, is_seq)
    m.user_named = name is not None
    bctx.memories.append(m)
    return m


# ---------------------------------------------------------------------------
# step sub-net evaluation
# ---------------------------------------------------------------------------


def _eval_subnet(
    order: List[Layer], ctx: Context, seeded: Dict[str, Argument]
) -> Dict[str, Argument]:
    values = dict(seeded)
    for layer in order:
        if layer.name in values:
            continue
        if isinstance(layer, _Placeholder):
            raise RuntimeError(f"unseeded placeholder {layer.name} in step net")
        ins = [values[l.name] for l in layer.inputs]
        values[layer.name] = layer.forward(ctx, ins)
    return values


class _GroupCore:
    """Shared machinery: traces the user's step once, owns the scan."""

    def __init__(
        self,
        step: Callable,
        inputs: Sequence[Union[Layer, StaticInput, GeneratedInput]],
        reverse: bool = False,
    ):
        self.reverse = reverse
        self.seq_inputs: List[Layer] = []
        self.sub_seq_flags: List[bool] = []  # parallel to seq_inputs
        self.static_inputs: List[StaticInput] = []
        self.generated: Optional[GeneratedInput] = None

        bctx = _BuildCtx()
        step_args: List[Any] = []
        self.placeholders: List[_Placeholder] = []
        created: List[Layer] = []
        with _building(bctx), record_layers(created):
            for item in inputs if isinstance(inputs, (list, tuple)) else [inputs]:
                if isinstance(item, StaticInput):
                    ph = _Placeholder(None)
                    ph.static = item
                    ph._v1_size = getattr(item.input, "_v1_size", None)
                    ph.src_layer = item.input
                    self.static_inputs.append(item)
                    self.placeholders.append(ph)
                    step_args.append(ph)
                elif isinstance(item, GeneratedInput):
                    ph = _Placeholder(None)
                    ph.static = None
                    self.generated = item
                    self.gen_placeholder = ph
                    self.placeholders.append(ph)
                    step_args.append(ph)
                elif isinstance(item, SubsequenceInput):
                    ph = _Placeholder(None)
                    ph.static = None
                    ph._v1_size = getattr(item.input, "_v1_size", None)
                    ph.src_layer = item.input
                    self.seq_inputs.append(item.input)
                    self.sub_seq_flags.append(True)
                    self.placeholders.append(ph)
                    step_args.append(ph)
                elif isinstance(item, Layer):
                    ph = _Placeholder(None)
                    ph.static = None
                    ph._v1_size = getattr(item, "_v1_size", None)
                    ph.src_layer = item
                    self.seq_inputs.append(item)
                    self.sub_seq_flags.append(False)
                    self.placeholders.append(ph)
                    step_args.append(ph)
                else:
                    raise TypeError(f"bad recurrent_group input: {item!r}")
            outs = step(*step_args)
        self.memories: List[MemoryLayer] = bctx.memories
        # SubsequenceInput forces nesting; a nested-sequence Argument at
        # runtime also triggers it (the reference reads nesting from the
        # provider's slot types, not the config wrapper) — mixing nested,
        # flat-sequence and non-sequence iterated inputs is allowed, matching
        # RecurrentGradientMachine's per-input sequence matching
        self.is_nested = any(self.sub_seq_flags)
        self.multi_out = not isinstance(outs, Layer)
        self.out_layers: List[Layer] = [outs] if isinstance(outs, Layer) else list(outs)

        # resolve memory links: the step layer whose output feeds t+1. The
        # link target need not be an output ancestor (e.g. a last_seq whose
        # only purpose is to carry state across outer steps in a nested
        # group) — any layer constructed inside the step counts, matching
        # the reference's name-based in-frame lookup
        # (RecurrentGradientMachine.cpp memory frame resolution).
        roots = list(self.out_layers)
        created_by_name = {l.name: l for l in created}
        for m in self.memories:
            extra = created_by_name.get(m.link_name)
            if extra is not None and extra not in roots:
                roots.append(extra)
        self.order = _topo_sort(roots)
        by_name = {l.name: l for l in self.order}
        self.links: Dict[str, Layer] = {}
        for m in self.memories:
            link = by_name.get(m.link_name)
            if link is None:
                raise ValueError(
                    f"memory links to {m.link_name!r} but no step layer has "
                    f"that name (step outputs: {[l.name for l in self.out_layers]})"
                )
            self.links[m.name] = link

    # -- helpers ------------------------------------------------------------
    def outer_inputs(self) -> List[Layer]:
        outer = list(self.seq_inputs) + [s.input for s in self.static_inputs]
        outer += [m.boot_layer for m in self.memories if m.boot_layer is not None]
        return outer

    def split_outer(self, ins: List[Argument]):
        n_seq = len(self.seq_inputs)
        n_static = len(self.static_inputs)
        seq = ins[:n_seq]
        static = ins[n_seq : n_seq + n_static]
        boots = ins[n_seq + n_static :]
        boot_map: Dict[str, Argument] = {}
        bi = 0
        for m in self.memories:
            if m.boot_layer is not None:
                boot_map[m.name] = boots[bi]
                bi += 1
        return seq, static, boot_map

    def seq_deps(self) -> Dict[str, set]:
        """layer name → indices of the iterated (seq) inputs in its step-net
        ancestry, memories included through their links (fixpoint). Drives
        per-input sequence matching when iterated inputs have different
        lengths (RecurrentGradientMachine's unequal-length contract)."""
        if getattr(self, "_seq_deps", None) is not None:
            return self._seq_deps
        seq_phs = [
            ph for ph in self.placeholders if getattr(ph, "static", None) is None
        ]
        ph_idx = {ph.name: i for i, ph in enumerate(seq_phs)}
        dep: Dict[str, set] = {}

        def of(layer) -> set:
            n = layer.name
            if n in dep:
                return dep[n]
            if n in ph_idx:
                dep[n] = {ph_idx[n]}
            elif isinstance(layer, MemoryLayer):
                dep[n] = set()  # filled by the fixpoint below
            else:
                dep[n] = set()
                for inp in getattr(layer, "inputs", []) or []:
                    dep[n] = dep[n] | of(inp)
            return dep[n]

        for l in self.order:
            of(l)
        for _ in range(len(self.memories) + 1):  # fixpoint over memory links
            changed = False
            for m in self.memories:
                link = self.links.get(m.name)
                if link is None:
                    continue
                add = dep.get(link.name, set()) - dep.get(m.name, set())
                if add:
                    dep[m.name] = dep.get(m.name, set()) | add
                    changed = True
                    for l in self.order:  # propagate downstream
                        for inp in getattr(l, "inputs", []) or []:
                            miss = dep.get(inp.name, set()) - dep.get(l.name, set())
                            if miss:
                                dep[l.name] = dep.get(l.name, set()) | miss
            if not changed:
                break
        self._seq_deps = dep
        return dep

    def seed_static(self, seeded: Dict[str, Argument], static_vals: List[Argument]):
        si = 0
        for ph in self.placeholders:
            if getattr(ph, "static", None) is not None:
                arg = static_vals[si]
                # a StaticInput of a sequence layer keeps its sequence
                # structure even without is_seq=True (the reference passes
                # the Argument through whole; is_seq only governs per-step
                # expansion of packed values)
                keep_seq = ph.static.is_seq or arg.lengths is not None
                seeded[ph.name] = arg if keep_seq else arg.as_non_seq()
                si += 1

    def init_carry(
        self, ctx: Context, batch: int, boot_map: Dict[str, Argument]
    ) -> Dict[str, Array]:
        carry: Dict[str, Array] = {}
        for m in self.memories:
            if m.name in boot_map:
                v = boot_map[m.name].value
            else:
                v = jnp.zeros((batch, m.size), jnp.float32)
            if m.boot_bias:
                b = ctx.param(
                    m, "boot_b", (m.size,), lambda k, s, d: jnp.zeros(s, d),
                    ParamAttr(),
                )
                v = v + b
            carry[m.name] = v
        return carry


# ---------------------------------------------------------------------------
# training-time group: scan over the padded time axis
# ---------------------------------------------------------------------------


class RecurrentGroup(Layer):
    type_name = "recurrent_layer_group"

    def __init__(self, core: _GroupCore, out_index: int, name: Optional[str] = None):
        super().__init__(core.outer_inputs(), name=name)
        self.core = core
        self.out_index = out_index

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        key = (id(self.core), "train")
        if key not in ctx.cache:
            ctx.cache[key] = self._run_group(ctx, ins)
        outs: Dict[str, Argument] = ctx.cache[key]
        return outs[self.core.out_layers[self.out_index].name]

    def _run_group(self, ctx: Context, ins: List[Argument]) -> Dict[str, Argument]:
        core = self.core
        seq, static, boot_map = core.split_outer(ins)
        if not seq:
            raise ValueError("recurrent_group needs at least one sequence input")
        if core.is_nested or any(
            a.sub_lengths is not None and a.value.ndim > 2 for a in seq
        ):
            return self._run_nested(ctx, seq, static, boot_map)
        anchor = next((a for a in seq if a.lengths is not None), None)
        if anchor is None:
            raise ValueError("recurrent_group inputs must be sequences")
        lengths = anchor.lengths
        batch = anchor.value.shape[0]
        # iterated inputs may have different lengths; the unroll covers the
        # longest, each memory/output masked by its own inputs' lengths
        t_max = max(
            a.value.shape[1] for a in seq if a.lengths is not None
        )
        deps = core.seq_deps()

        def dep_lengths(name: str):
            idxs = [
                i for i in deps.get(name, set())
                if seq[i].lengths is not None
            ]
            if not idxs:
                return lengths
            out = seq[idxs[0]].lengths
            for i in idxs[1:]:
                out = jnp.maximum(out, seq[i].lengths)
            return out

        seeded_static: Dict[str, Argument] = {}
        core.seed_static(seeded_static, static)
        carry0 = core.init_carry(ctx, batch, boot_map)

        seq_phs = [
            ph
            for ph in core.placeholders
            if getattr(ph, "static", None) is None
        ]

        def slice_t(a: Argument, t):
            # non-seq iterated inputs repeat every step (the reference
            # broadcasts NO_SEQUENCE args across the unroll); shorter inputs
            # clamp to their last step (masking freezes dependent state)
            if a.lengths is None:
                return a.value
            tt = jnp.minimum(t, a.value.shape[1] - 1)
            return a.value[:, tt]

        def seed_t(xs_t: List[Array]) -> Dict[str, Argument]:
            seeded = dict(seeded_static)
            for ph, x in zip(seq_phs, xs_t):
                seeded[ph.name] = Argument(x)
            return seeded

        out_names = [l.name for l in core.out_layers]

        if ctx.mode == "init":
            # one eager step creates all params; tile the result over time
            seeded = seed_t([slice_t(s, 0) for s in seq])
            for m in core.memories:
                seeded[m.name] = Argument(carry0[m.name])
            values = _eval_subnet(core.order, ctx, seeded)
            return {
                n: Argument(
                    jnp.repeat(values[n].value[:, None], t_max, axis=1), lengths
                )
                for n in out_names
            }

        # apply mode: one scan, masked carry updates on padded steps
        ts = jnp.arange(t_max - 1, -1, -1) if core.reverse else jnp.arange(t_max)
        keys0 = set(ctx.state_updates)

        def body(carry: Dict[str, Array], t: Array):
            seeded = seed_t([slice_t(s, t) for s in seq])
            for m in core.memories:
                seeded[m.name] = Argument(carry[m.name])
            values = _eval_subnet(core.order, ctx, seeded)
            new_carry = {}
            for m in core.memories:
                link_arg = values[core.links[m.name].name]
                new = link_arg.value
                old = carry[m.name]
                if new.ndim == old.ndim + 1 and link_arg.is_seq:
                    # non-seq memory of a sequence-valued step layer carries
                    # its last valid instance (RecurrentGradientMachine's
                    # scatter of the frame's last agent state)
                    from paddle_tpu.ops import sequence as _seq_ops

                    new = _seq_ops.seq_last(new, link_arg.lengths)
                valid = (t < dep_lengths(m.name))  # [B], per-memory lengths
                mask = valid.reshape((-1,) + (1,) * (new.ndim - 1))
                new_carry[m.name] = jnp.where(mask, new, old)
            return new_carry, tuple(values[n].value for n in out_names)

        _, stacked = lax.scan(body, carry0, ts)
        # drop state updates traced inside the scan body (they'd leak tracers;
        # stateful layers like BatchNorm are not supported in step nets, as in
        # the reference's recurrent layer groups)
        for k in list(ctx.state_updates):
            if k not in keys0:
                del ctx.state_updates[k]

        outs: Dict[str, Argument] = {}
        for n, ys in zip(out_names, stacked):
            ys = jnp.swapaxes(ys, 0, 1)  # [B, T, ...]
            if core.reverse:
                ys = jnp.flip(ys, axis=1)
            outs[n] = Argument(ys, dep_lengths(n))
        return outs

    def _run_nested(
        self,
        ctx: Context,
        seq: List[Argument],
        static: List[Argument],
        boot_map: Dict[str, Argument],
    ) -> Dict[str, Argument]:
        """Hierarchical unroll (SubsequenceInput): outer scan over the
        subsequence axis of [B, S, T, ...] inputs, each step seeding the step
        net with a level-1 sequence slice — an inner recurrent_group in the
        step net becomes the inner scan. Mirrors RecurrentGradientMachine's
        nested frame expansion (sequence_nest_rnn.conf idiom) as two stacked
        lax.scans over static shapes."""
        core = self.core

        def is_nested_arg(a: Argument) -> bool:
            return a.sub_lengths is not None and a.value.ndim > 2

        anchor = next((a for a in seq if is_nested_arg(a)), None)
        if anchor is None:
            raise ValueError(
                f"{self.name}: SubsequenceInput needs a nested [B, S, T, ...] "
                "Argument with sub_lengths [B, S]"
            )
        outer_len = anchor.lengths  # [B] valid subsequence counts
        sub_lengths = anchor.sub_lengths  # [B, S]
        batch, s_max = anchor.value.shape[:2]

        seeded_static: Dict[str, Argument] = {}
        core.seed_static(seeded_static, static)
        carry0 = core.init_carry(ctx, batch, boot_map)
        seq_phs = [
            ph for ph in core.placeholders if getattr(ph, "static", None) is None
        ]
        out_names = [l.name for l in core.out_layers]

        def slice_s(a: Argument, s) -> Argument:
            # per-input sequence matching (RecurrentGradientMachine): nested
            # args yield their s-th subsequence as a level-1 sequence, flat
            # sequences their s-th token, non-seq args repeat every step
            if is_nested_arg(a):
                return Argument(a.value[:, s], a.sub_lengths[:, s])
            if a.lengths is not None:
                return Argument(a.value[:, s])
            return a

        def seed_s(s) -> Dict[str, Argument]:
            seeded = dict(seeded_static)
            for ph, a in zip(seq_phs, seq):
                seeded[ph.name] = slice_s(a, s)
            return seeded

        if ctx.mode == "init":
            seeded = seed_s(0)
            for m in core.memories:
                seeded[m.name] = Argument(carry0[m.name])
            values = _eval_subnet(core.order, ctx, seeded)
            outs: Dict[str, Argument] = {}
            for n in out_names:
                v = values[n]
                tiled = jnp.repeat(v.value[:, None], s_max, axis=1)
                if v.is_seq:  # [B, S, T, ...] nested output
                    outs[n] = Argument(tiled, outer_len, sub_lengths)
                else:  # [B, S, D] level-1 sequence over subsequence index
                    outs[n] = Argument(tiled, outer_len)
            return outs

        ss = jnp.arange(s_max - 1, -1, -1) if core.reverse else jnp.arange(s_max)
        keys0_state = set(ctx.state_updates)
        keys0_cache = set(ctx.cache)
        out_is_seq: Dict[str, bool] = {}

        def body(carry: Dict[str, Array], s: Array):
            seeded = seed_s(s)
            for m in core.memories:
                seeded[m.name] = Argument(carry[m.name])
            values = _eval_subnet(core.order, ctx, seeded)
            for n in out_names:  # body traces once; record output seq-ness
                out_is_seq[n] = values[n].is_seq
            valid = (s < outer_len)  # [B]
            new_carry = {}
            for m in core.memories:
                link_arg = values[core.links[m.name].name]
                new = link_arg.value
                old = carry[m.name]
                if new.ndim == old.ndim + 1 and link_arg.is_seq:
                    # non-seq memory of a sequence-valued step layer carries
                    # its last valid instance (RecurrentGradientMachine's
                    # scatter of the frame's last agent state)
                    from paddle_tpu.ops import sequence as _seq_ops

                    new = _seq_ops.seq_last(new, link_arg.lengths)
                mask = valid.reshape((-1,) + (1,) * (new.ndim - 1))
                new_carry[m.name] = jnp.where(mask, new, old)
            # sequence-valued outputs whose lengths are *computed by the step*
            # (beam generation) stack their per-step lengths into the nested
            # sub_lengths; input-derived outputs keep dep_sub_lengths below
            lens = tuple(
                values[n].lengths
                if values[n].is_seq
                else jnp.zeros((batch,), jnp.int32)
                for n in out_names
            )
            return new_carry, (tuple(values[n].value for n in out_names), lens)

        _, (stacked, stacked_lens) = lax.scan(body, carry0, ss)
        # inner groups cache their per-trace results and state updates under
        # ctx while the body traces; those hold scan tracers — drop them
        for k in list(ctx.state_updates):
            if k not in keys0_state:
                del ctx.state_updates[k]
        for k in list(ctx.cache):
            if k not in keys0_cache:
                del ctx.cache[k]

        deps = core.seq_deps()

        def dep_sub_lengths(name: str):
            # inner lengths follow the nested inputs in the output's
            # ancestry (unequal-length multi-input groups); anchor otherwise
            idxs = [i for i in deps.get(name, set()) if is_nested_arg(seq[i])]
            if not idxs:
                return sub_lengths
            out = seq[idxs[0]].sub_lengths
            for i in idxs[1:]:
                out = jnp.maximum(out, seq[i].sub_lengths)
            return out

        outs = {}
        gen_outs = {
            l.name for l in core.out_layers if isinstance(l, BeamSearchLayer)
        }
        for n, ys, ls in zip(out_names, stacked, stacked_lens):
            ys = jnp.swapaxes(ys, 0, 1)  # [B, S, ...]
            if core.reverse:
                ys = jnp.flip(ys, axis=1)
            if out_is_seq[n]:
                # sequence-valued step output (e.g. an inner group's full
                # unroll): stacks to a nested [B, S, T, ...] Argument. A
                # generating step (beam_search) computes its own lengths —
                # those stack into sub_lengths (the reference concatenates the
                # generated inner results, RecurrentGradientMachine.cpp:536).
                if n in gen_outs:
                    sl = jnp.swapaxes(ls, 0, 1)
                    if core.reverse:
                        sl = jnp.flip(sl, axis=1)
                else:
                    sl = dep_sub_lengths(n)
                outs[n] = Argument(ys, outer_len, sl)
            else:
                # flat [B, D] step output → level-1 sequence over s
                outs[n] = Argument(ys, outer_len)
        return outs


def recurrent_group(
    step: Callable,
    input: Union[Layer, StaticInput, Sequence],
    reverse: bool = False,
    name: Optional[str] = None,
    **_compat,
) -> Layer:
    """Build the group. A step returning one layer yields one node; a step
    returning a tuple/list yields a tuple of nodes (the reference's
    multi-output recurrent_group contract — `a, b = recurrent_group(...)`).
    Extra outputs also remain reachable via get_output_layer."""
    core = _GroupCore(step, input, reverse=reverse)
    if core.generated is not None:
        raise ValueError("GeneratedInput is only valid under beam_search")
    node = RecurrentGroup(core, 0, name=name)
    node._group_core = core
    if core.multi_out:
        extra = []
        for i in range(1, len(core.out_layers)):
            n = RecurrentGroup(core, i, name=f"{node.name}.out{i}")
            n._group_core = core
            extra.append(n)
        return tuple([node] + extra)
    return node


def get_output_layer(group: Layer, out_name: str, name: Optional[str] = None) -> Layer:
    """Fetch another step-net output of a recurrent_group
    (GetOutputLayer / get_output_layer parity)."""
    core = getattr(group, "_group_core", None) or getattr(group, "core", None)
    if core is None:
        raise TypeError(f"{group!r} is not a recurrent_group output")
    names = [l.name for l in core.out_layers]
    if out_name not in names:
        raise ValueError(f"step net has outputs {names}, not {out_name!r}")
    node = RecurrentGroup(core, names.index(out_name), name=name)
    node._group_core = core
    return node


# ---------------------------------------------------------------------------
# generation: beam search over an arbitrary step net
# ---------------------------------------------------------------------------


class BeamSearchLayer(Layer):
    """v1 beam_search(): generate with the traced step net.

    Output Argument: value [B, max_length] int32 best-beam token ids,
    lengths [B] (up to and including EOS). Scores for all beams are cached
    under (id(core), "beam_scores") for SequenceGenerator-style access."""

    type_name = "beam_search"

    def __init__(
        self,
        core: _GroupCore,
        bos_id: int,
        eos_id: int,
        beam_size: int,
        max_length: int,
        num_results_per_sample: int = 1,
        name: Optional[str] = None,
    ):
        super().__init__(core.outer_inputs(), name=name)
        self.core = core
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.beam_size = beam_size
        self.max_length = max_length
        self.num_results_per_sample = min(num_results_per_sample, beam_size)

    def _embed(self, ctx: Context, tokens: Array) -> Array:
        gen = self.core.generated
        table = ctx.param(
            self,
            "emb",
            (gen.size, gen.embedding_size),
            lambda k, s, d: 0.01 * jax.random.normal(k, s, d),
            ParamAttr(name=gen.embedding_name),
        )
        return table[tokens]

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        core = self.core
        if core.generated is None:
            raise ValueError("beam_search step needs a GeneratedInput")
        seq, static, boot_map = core.split_outer(ins)
        if seq:
            raise ValueError(
                "beam_search inputs must be StaticInput/GeneratedInput only"
            )
        if static:
            batch = static[0].value.shape[0]
        elif boot_map:
            batch = next(iter(boot_map.values())).value.shape[0]
        else:
            raise ValueError("beam_search needs a static or boot input for batch size")

        k, L = self.beam_size, self.max_length
        carry0 = core.init_carry(ctx, batch, boot_map)

        if ctx.mode == "init":
            seeded: Dict[str, Argument] = {}
            core.seed_static(seeded, static)
            seeded[core.gen_placeholder.name] = Argument(
                self._embed(ctx, jnp.full((batch,), self.bos_id, jnp.int32))
            )
            for m in core.memories:
                seeded[m.name] = Argument(carry0[m.name])
            _eval_subnet(core.order, ctx, seeded)
            return Argument(
                jnp.zeros((batch, L), jnp.int32),
                jnp.ones((batch,), jnp.int32),
            )

        # tile static inputs and carries across beams → batch axis B*K
        def tile(x: Array) -> Array:
            return jnp.repeat(x, k, axis=0)

        static_tiled: Dict[str, Argument] = {}
        core.seed_static(static_tiled, static)
        static_tiled = {
            n: Argument(
                tile(a.value), None if a.lengths is None else tile(a.lengths)
            )
            for n, a in static_tiled.items()
        }
        carry_t = {n: tile(v) for n, v in carry0.items()}
        vocab = core.generated.size
        prob_layer = core.out_layers[0].name

        def step_fn(tokens_flat, carry, t):
            seeded = dict(static_tiled)
            seeded[core.gen_placeholder.name] = Argument(
                self._embed(ctx, tokens_flat)
            )
            for m in core.memories:
                seeded[m.name] = Argument(carry[m.name])
            values = _eval_subnet(core.order, ctx, seeded)
            probs = values[prob_layer].value
            logp = jnp.log(jnp.maximum(probs.astype(jnp.float32), 1e-20))
            new_carry = {
                m.name: values[core.links[m.name].name].value
                for m in core.memories
            }
            return logp, new_carry

        from paddle_tpu.nn.beam_core import beam_search_scan

        keys0 = set(ctx.state_updates)
        res = beam_search_scan(
            step_fn, carry_t, batch=batch, vocab=vocab, bos_id=self.bos_id,
            eos_id=self.eos_id, beam_size=k, max_len=L,
        )
        for kk in list(ctx.state_updates):
            if kk not in keys0:
                del ctx.state_updates[kk]

        # beams arrive sorted best-first from the shared engine
        ids = res.history[:, 0]
        lengths = res.lengths[:, 0]
        ctx.cache[(id(core), "beam_scores")] = res.scores
        # full result for the generation runner / seq_text_printer
        # (fillGenOutputs packs [len, ids..., -1] per beam + a probs matrix,
        # RecurrentGradientMachine.cpp:1301-1345; we keep the arrays)
        ctx.cache[("beam", self.name)] = {
            "history": res.history,
            "scores": res.scores,
            "lengths": res.lengths,
            "num_results": self.num_results_per_sample,
            "eos_id": self.eos_id,
        }
        return Argument(ids, lengths)


def beam_search(
    step: Callable,
    input: Sequence,
    bos_id: int,
    eos_id: int,
    beam_size: int = 4,
    max_length: int = 50,
    num_results_per_sample: int = 1,
    name: Optional[str] = None,
    **_compat,
) -> Layer:
    core = _GroupCore(step, input)
    node = BeamSearchLayer(
        core, bos_id, eos_id, beam_size, max_length,
        num_results_per_sample=num_results_per_sample, name=name,
    )
    node._group_core = core
    return node
