"""Sequence-structure layers.

Parity with the reference sequence layer family (paddle/gserver/layers/):
SequencePoolLayer (sum/avg/max/sqrt), SequenceLastInstanceLayer (+first),
MaxLayer, AverageLayer, ExpandLayer, SequenceConcatLayer, SequenceReshapeLayer,
SequenceSliceLayer, KmaxSeqScoreLayer, GetOutputLayer — on padded [B,T,...]
batches with masks (the TPU encoding of Argument.sequenceStartPositions)."""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn.graph import Argument, Context, Layer
from paddle_tpu.ops import sequence as seq_ops


def _seq_view(arg: Argument):
    """(values [B,T,...], lengths [B]) — a non-seq input is a length-1
    sequence (the reference's SequencePoolLayer tolerates NO_SEQUENCE)."""
    if arg.lengths is None:
        v = arg.value[:, None]
        return v, jnp.ones((v.shape[0],), jnp.int32)
    return arg.value, arg.lengths


def _strided_windows(x, lengths, stride: int):
    """Split [B,T,...] into fixed windows of `stride` steps →
    (windows [B,W,stride,...], per-window valid counts [B,W] clamped ≥1,
    output lengths ceil(len/stride)) — SequencePoolLayer.cpp stride mode."""
    b, t = x.shape[:2]
    n_win = -(-t // stride)
    pad = n_win * stride - t
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)) + ((0, 0),) * (x.ndim - 2))
    win = x.reshape((b, n_win, stride) + x.shape[2:])
    starts = jnp.arange(n_win) * stride
    wlen = jnp.clip(lengths[:, None] - starts[None, :], 1, stride)
    return win, wlen, -(-lengths // stride)


_POOL_FNS = {
    "sum": seq_ops.seq_sum,
    "average": seq_ops.seq_mean,
    "avg": seq_ops.seq_mean,
    "max": seq_ops.seq_max,
    "sqrt": seq_ops.seq_sqrt_pool,
}


@LAYERS.register("seq_pool")
class SeqPool(Layer):
    """SequencePoolLayer: pool over time → [B, D]. agg_level="seq"
    (AggregateLevel.TO_SEQUENCE) pools within each subsequence of a nested
    input → level-1 sequence; stride>0 pools fixed windows of `stride` steps
    → sequence of window results (SequencePoolLayer.cpp stride support)."""

    type_name = "seq_pool"

    def __init__(self, input: Layer, pool_type: str = "sum", name=None,
                 agg_level: str = "non-seq", stride: int = -1):
        super().__init__(input, name=name)
        assert pool_type in ("sum", "average", "avg", "max", "sqrt")
        self.pool_type = pool_type
        self.agg_level = agg_level or "non-seq"
        self.stride = stride if stride and stride > 0 else -1

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        arg = ins[0]
        fn = _POOL_FNS[self.pool_type]
        if self.agg_level == "seq":
            if arg.sub_lengths is not None and arg.value.ndim > 2:
                # [B,S,T,...] → pool each subsequence → [B,S,...]
                pooled = jax.vmap(fn, in_axes=1, out_axes=1)(
                    arg.value, arg.sub_lengths
                )
                return Argument(pooled, arg.lengths)
            x, lengths = _seq_view(arg)
            return Argument(fn(x, lengths)[:, None], jnp.ones_like(lengths))
        x, lengths = _seq_view(arg)
        if self.stride > 0:
            win, wlen, out_len = _strided_windows(x, lengths, self.stride)
            pooled = jax.vmap(fn, in_axes=1, out_axes=1)(win, wlen)
            return Argument(pooled, out_len)
        return Argument(fn(x, lengths))


def _last_valid_subseq(arg: Argument):
    """For a nested [B, S, T, ...] Argument: → ([B, T, ...] slice of the last
    valid subsequence, its [B] token lengths)."""
    b = arg.value.shape[0]
    s_idx = jnp.maximum(arg.lengths - 1, 0)  # [B]
    sub = arg.value[jnp.arange(b), s_idx]
    sub_len = arg.sub_lengths[jnp.arange(b), s_idx]
    return sub, sub_len


class _SeqInstance(Layer):
    """SequenceLastInstanceLayer (select_first toggles last/first).
    agg_level="seq" picks per-subsequence instances of a nested input →
    level-1 sequence; stride>0 picks one instance per fixed window →
    sequence of window instances (SequenceLastInstanceLayer.cpp)."""

    select_first = False

    def __init__(self, input: Layer, name=None, agg_level: str = "non-seq",
                 stride: int = -1):
        super().__init__(input, name=name)
        self.agg_level = agg_level or "non-seq"
        self.stride = stride if stride and stride > 0 else -1

    def _pick(self, x, lengths):
        if self.select_first:
            return seq_ops.seq_first(x)
        return seq_ops.seq_last(x, lengths)

    def forward(self, ctx, ins):
        arg = ins[0]
        if arg.sub_lengths is not None and arg.value.ndim > 2:
            if self.agg_level == "seq":
                # one instance per subsequence → [B, S, ...] sequence
                pick = jax.vmap(self._pick, in_axes=1, out_axes=1)
                return Argument(
                    pick(arg.value, arg.sub_lengths), arg.lengths
                )
            if self.select_first:
                return Argument(seq_ops.seq_first(arg.value[:, 0]))
            sub, sub_len = _last_valid_subseq(arg)
            return Argument(seq_ops.seq_last(sub, sub_len))
        x, lengths = _seq_view(arg)
        if self.agg_level == "seq":
            return Argument(self._pick(x, lengths)[:, None], jnp.ones_like(lengths))
        if self.stride > 0:
            win, wlen, out_len = _strided_windows(x, lengths, self.stride)
            pick = jax.vmap(self._pick, in_axes=1, out_axes=1)
            return Argument(pick(win, wlen), out_len)
        return Argument(self._pick(x, lengths))


@LAYERS.register("last_seq")
class LastSeq(_SeqInstance):
    """SequenceLastInstanceLayer. On a nested sequence the default (non-seq)
    aggregation spans the whole flat token stream — the last valid token of
    the last valid subsequence (SequenceLastInstanceLayer.cpp uses the outer
    sequenceStartPositions)."""

    type_name = "last_seq"
    select_first = False


@LAYERS.register("first_seq")
class FirstSeq(_SeqInstance):
    """SequenceLastInstanceLayer with select_first=True. On a nested sequence:
    first token of the first subsequence."""

    type_name = "first_seq"
    select_first = True


@LAYERS.register("expand")
class Expand(Layer):
    """ExpandLayer: broadcast [B, D] across the time axis of a reference
    sequence → [B, T, D]."""

    type_name = "expand"

    def __init__(self, input: Layer, expand_as: Layer, name=None,
                 expand_level: str = "non-seq"):
        super().__init__([input, expand_as], name=name)
        self.expand_level = expand_level or "non-seq"

    def forward(self, ctx, ins):
        x, ref = ins[0], ins[1]
        assert ref.is_seq
        if ref.sub_lengths is not None and ref.value.ndim > 2:
            s_max, t_max = ref.value.shape[1], ref.value.shape[2]
            if self.expand_level == "seq" and x.value.ndim == 3:
                # FROM_SEQUENCE onto a nested target: one value per
                # subsequence broadcast across that subsequence's tokens
                out = jnp.broadcast_to(
                    x.value[:, :, None],
                    x.value.shape[:2] + (t_max,) + x.value.shape[2:],
                )
                return Argument(out, ref.lengths, ref.sub_lengths)
            # FROM_NO_SEQUENCE onto nested: broadcast over both levels
            out = jnp.broadcast_to(
                x.value[:, None, None],
                (x.value.shape[0], s_max, t_max) + x.value.shape[1:],
            )
            return Argument(out, ref.lengths, ref.sub_lengths)
        out = seq_ops.expand_to_seq(x.value, ref.lengths, ref.max_len)
        return Argument(out, ref.lengths)


@LAYERS.register("seq_concat")
class SeqConcat(Layer):
    """SequenceConcatLayer: concatenate two sequences in time."""

    type_name = "seq_concat"

    def __init__(self, a: Layer, b: Layer, name=None):
        super().__init__([a, b], name=name)

    def forward(self, ctx, ins):
        a, b = ins
        assert a.is_seq and b.is_seq
        ta, tb = a.max_len, b.max_len
        d = a.value.shape[-1]
        bsz = a.value.shape[0]
        out_t = ta + tb
        out = jnp.zeros((bsz, out_t, d), a.value.dtype)
        out = out.at[:, :ta].set(a.value * a.mask(a.value.dtype)[:, :, None])
        # scatter b after each row's a-length
        idx = a.lengths[:, None] + jnp.arange(tb)[None, :]  # [B, tb]
        bm = b.mask(b.value.dtype)[:, :, None]
        batch_idx = jnp.arange(bsz)[:, None].repeat(tb, 1)
        out = out.at[batch_idx, idx].add(b.value * bm)
        return Argument(out, a.lengths + b.lengths)


@LAYERS.register("seq_reshape")
class SeqReshape(Layer):
    """SequenceReshapeLayer: change the feature width by regrouping time
    steps (T*D = T'*D')."""

    type_name = "seq_reshape"

    def __init__(self, input: Layer, reshape_size: int, name=None):
        super().__init__(input, name=name)
        self.reshape_size = reshape_size

    def forward(self, ctx, ins):
        arg = ins[0]
        b, t, d = arg.value.shape
        new_d = self.reshape_size
        total = t * d
        assert total % new_d == 0, f"{self.name}: {t}x{d} not divisible by {new_d}"
        new_t = total // new_d
        out = arg.value.reshape(b, new_t, new_d)
        # ceil so a ragged row whose valid element count is not divisible by
        # new_d keeps its trailing partial step (zero-padded) instead of
        # silently dropping data
        new_lengths = -((arg.lengths * d) // -new_d)
        return Argument(out, new_lengths)


@LAYERS.register("seq_slice")
class SeqSlice(Layer):
    """SequenceSliceLayer: keep the first/last k steps of each sequence
    (k mode), or cut [start, end) windows given by companion integer layers
    (SequenceSliceLayer.cpp: starts/ends hold K offsets per sequence →
    K sub-slices, a nested sequence here)."""

    type_name = "seq_slice"

    def __init__(self, input: Layer, k: Optional[int] = None,
                 from_start: bool = True, starts: Optional[Layer] = None,
                 ends: Optional[Layer] = None, name=None):
        extra = [l for l in (starts, ends) if l is not None]
        super().__init__([input] + extra, name=name)
        if k is None and not extra:
            raise ValueError(f"{name}: seq_slice needs k= or starts=/ends=")
        self.k = k
        self.from_start = from_start
        self.has_starts = starts is not None
        self.has_ends = ends is not None

    def forward(self, ctx, ins):
        arg = ins[0]
        x, lengths = arg.value, arg.lengths
        b, t = x.shape[:2]
        if self.has_starts or self.has_ends:
            nxt = 1
            if self.has_starts:
                starts = ins[nxt].value.astype(jnp.int32)
                nxt += 1
            else:
                starts = None
            ends = ins[nxt].value.astype(jnp.int32) if self.has_ends else None
            if arg.sub_lengths is not None and x.ndim > 3:
                # nested input: starts/ends index tokens within each
                # subsequence — shift every subsequence window in place
                t_sub = x.shape[2]
                sub_len = arg.sub_lengths
                if starts is None:
                    starts = jnp.zeros_like(sub_len)
                if ends is None:
                    ends = sub_len - 1
                idx = starts[:, :, None] + jnp.arange(t_sub)[None, None, :]
                idx_c = jnp.minimum(idx, t_sub - 1)
                gat = jnp.take_along_axis(
                    x, idx_c.reshape(idx_c.shape + (1,) * (x.ndim - 3)), axis=2
                )
                new_sub = jnp.clip(ends - starts + 1, 1, t_sub)
                return Argument(gat, lengths, new_sub)
            if starts is None:
                starts = jnp.zeros_like(ends)
            if ends is None:
                ends = jnp.broadcast_to(lengths[:, None], starts.shape)
            k = starts.shape[1]  # K slices per row
            # slice s of row i = x[i, starts[i,s] : ends[i,s]+? )  (inclusive
            # end per SequenceSliceLayer semantics: ends is the last index)
            idx = starts[:, :, None] + jnp.arange(t)[None, None, :]
            idx_c = jnp.minimum(idx, t - 1)
            gat = jnp.take_along_axis(
                x[:, None],
                idx_c.reshape(idx_c.shape + (1,) * (x.ndim - 2)),
                axis=2,
            )
            sub_len = jnp.clip(ends - starts + 1, 1, t)
            return Argument(
                gat, jnp.full((b,), k, jnp.int32), sub_len
            )
        k = min(self.k, t)
        new_len = jnp.minimum(lengths, k)
        if self.from_start:
            out = x[:, :k]
        else:
            # last k valid steps of each row: gather with per-row offsets
            start = jnp.maximum(lengths - k, 0)  # [B]
            idx = start[:, None] + jnp.arange(k)[None, :]
            idx = jnp.minimum(idx, t - 1)
            out = jnp.take_along_axis(
                x, idx.reshape(b, k, *([1] * (x.ndim - 2))), axis=1
            )
        return Argument(out, new_len)


@LAYERS.register("kmax_seq_score")
class KmaxSeqScore(Layer):
    """KmaxSeqScoreLayer: indices of the top-k scores within each sequence."""

    type_name = "kmax_seq_score"

    def __init__(self, input: Layer, beam_size: int, name=None):
        super().__init__(input, name=name)
        self.beam_size = beam_size

    def forward(self, ctx, ins):
        arg = ins[0]
        scores = arg.value
        if arg.sub_lengths is not None and scores.ndim >= 3:
            # nested input [B, S, T(, 1)]: top-k over the flattened valid
            # token stream (ids index into the nested sequence)
            if scores.ndim == 4:
                scores = scores[..., 0]
            b, s_max, t_max = scores.shape
            valid = (
                (jnp.arange(s_max)[None, :, None] < arg.lengths[:, None, None])
                & (jnp.arange(t_max)[None, None, :] < arg.sub_lengths[:, :, None])
            )
            flat = jnp.where(valid, scores, seq_ops.NEG_INF).reshape(b, -1)
            _, idx = jax.lax.top_k(flat, self.beam_size)
            return Argument(idx)
        if scores.ndim == 3:
            scores = scores[..., 0]
        masked = jnp.where(arg.mask(jnp.bool_), scores, seq_ops.NEG_INF)
        _, idx = jax.lax.top_k(masked, self.beam_size)
        return Argument(idx)


@LAYERS.register("sub_seq")
class SubSeq(Layer):
    """SubSequenceLayer: per-row [offset, size) windows from companion
    integer inputs."""

    type_name = "sub_seq"

    def __init__(self, input: Layer, offsets: Layer, sizes: Layer, name=None):
        super().__init__([input, offsets, sizes], name=name)

    def forward(self, ctx, ins):
        arg, off_arg, size_arg = ins
        x = arg.value
        b, t = x.shape[:2]
        offsets = off_arg.value.reshape(-1).astype(jnp.int32)
        sizes = size_arg.value.reshape(-1).astype(jnp.int32)
        idx = offsets[:, None] + jnp.arange(t)[None, :]
        idx = jnp.minimum(idx, t - 1)
        out = jnp.take_along_axis(x, idx.reshape(b, t, *([1] * (x.ndim - 2))), axis=1)
        return Argument(out, jnp.minimum(sizes, t))



@LAYERS.register("sub_nested_seq")
class SubNestedSeq(Layer):
    """SubNestedSequenceLayer.cpp:86 — trim a nested sequence to a selected
    set of subsequences (beam-training machinery, used with kmax_seq_score).

    inputs: nested [B, S, T, ...] with sub_lengths [B, S]; selected_indices
    [B, K] int32 subsequence ids (-1 = pad).
    output: [B, K, T, ...] with lengths = count of valid selections and
    sub_lengths gathered along the selection."""

    type_name = "sub_nested_seq"

    def __init__(self, input: Layer, selected_indices: Layer, name=None):
        super().__init__([input, selected_indices], name=name)

    def forward(self, ctx, ins):
        nested, sel = ins
        assert nested.sub_lengths is not None, (
            f"{self.name}: sub_nested_seq needs a nested-sequence input "
            f"(Argument.sub_lengths set)"
        )
        idx = sel.value.astype(jnp.int32)  # [B, K]
        valid = idx >= 0
        safe = jnp.maximum(idx, 0)
        gather_idx = safe.reshape(
            safe.shape + (1,) * (nested.value.ndim - 2)
        )
        out = jnp.take_along_axis(
            nested.value,
            jnp.broadcast_to(
                gather_idx, safe.shape + nested.value.shape[2:]
            ),
            axis=1,
        )
        sub_l = jnp.take_along_axis(nested.sub_lengths, safe, axis=1)
        sub_l = jnp.where(valid, sub_l, 0)
        out = out * valid.reshape(
            valid.shape + (1,) * (out.ndim - 2)
        ).astype(out.dtype)
        lengths = valid.sum(axis=1).astype(jnp.int32)
        return Argument(out, lengths=lengths, sub_lengths=sub_l)
