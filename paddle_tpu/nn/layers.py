"""Core layers (batch 1: dense / image / elementwise).

TPU-native re-implementations of the reference layer types in
paddle/gserver/layers/ (93 REGISTER_LAYER registrations, Layer.h:31). Each class
docstring cites the reference layer it matches. Layers are pure specs — see
paddle_tpu/nn/graph.py; backward is autodiff."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn import activations as act_mod
from paddle_tpu.nn import init as init_mod
from paddle_tpu.nn.graph import Argument, Context, Layer, ParamAttr
from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import linalg

Array = jax.Array


def _attr(a: Optional[Union[ParamAttr, dict]]) -> Optional[ParamAttr]:
    if a is None or isinstance(a, ParamAttr):
        return a
    return ParamAttr(**a)


@LAYERS.register("data")
class Data(Layer):
    """Input slot (DataLayer, gserver/layers/DataLayer.cpp). `shape` excludes the
    batch dim; sequence inputs additionally carry lengths in the feed dict."""

    type_name = "data"

    def __init__(self, name: str, shape: Sequence[int] = (), is_seq: bool = False):
        super().__init__(None, name=name)
        self.shape = tuple(shape)
        self.is_seq = is_seq

    def forward(self, ctx, ins):  # data layers are fed directly by Network._run
        raise AssertionError("data layer forward should not be called")


@LAYERS.register("fc")
class Fc(Layer):
    """Fully-connected (FullyConnectedLayer.cpp). Multiple inputs each get their
    own weight, summed before bias+activation — matching the reference, whose fc
    accepts several inputs. Sequence inputs are applied per-timestep."""

    type_name = "fc"

    def __init__(
        self,
        input: Union[Layer, Sequence[Layer]],
        size: int,
        act: Any = "tanh",
        bias: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        total = None
        for i, arg in enumerate(ins):
            x = arg.value
            d = x.shape[-1]
            suffix = "" if len(ins) == 1 else f".{i}"
            w = ctx.param(
                self, "w" + suffix, (d, self.size), init_mod.smart_normal, self.param_attr
            )
            y = linalg.matmul(x, w, ctx.policy)
            total = y if total is None else total + y
        if self.bias:
            b = ctx.param(self, "b", (self.size,), init_mod.zeros, self.bias_attr)
            total = total + b
        total = act_mod.apply(self.act, total)
        return ins[0].with_value(total)


@LAYERS.register("embedding")
class Embedding(Layer):
    """Embedding lookup (TableProjection + hl_table_apply row select,
    paddle/cuda/src/hl_table_apply.cu). Input carries int ids [B] or [B, T]."""

    type_name = "embedding"

    def __init__(
        self,
        input: Layer,
        size: int,
        vocab_size: Optional[int] = None,
        param_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.vocab_size = vocab_size
        self.param_attr = _attr(param_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        ids = ins[0].value
        vocab = self.vocab_size
        if vocab is None:
            src = self.inputs[0]
            vocab = getattr(src, "shape", (None,))[0]
            if vocab is None:
                raise ValueError(
                    f"embedding {self.name}: vocab_size not set and input has no shape"
                )
        table = ctx.param(
            self, "w", (vocab, self.size), init_mod.smart_normal, self.param_attr
        )
        out = jnp.take(table, ids.astype(jnp.int32), axis=0)
        return ins[0].with_value(out)


@LAYERS.register("conv")
class Conv2D(Layer):
    """2-D convolution, NHWC (ExpandConvLayer.cpp / CudnnConvBaseLayer.cpp via
    GemmConvOp; here a single XLA conv HLO on the MXU)."""

    type_name = "conv"

    def __init__(
        self,
        input: Layer,
        num_filters: int,
        filter_size: Union[int, Tuple[int, int]],
        stride: Union[int, Tuple[int, int]] = 1,
        padding: Union[int, Tuple[int, int], str] = 0,
        dilation: Union[int, Tuple[int, int]] = 1,
        groups: int = 1,
        act: Any = None,
        bias: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.num_filters = num_filters
        self.filter_size = filter_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        assert x.ndim == 4, f"conv {self.name}: expect NHWC input, got {x.shape}"
        kh, kw = conv_ops._pair(self.filter_size)
        cin = x.shape[-1]
        w = ctx.param(
            self,
            "w",
            (kh, kw, cin // self.groups, self.num_filters),
            init_mod.he_normal,
            self.param_attr,
        )
        out = conv_ops.conv2d(
            x, w, self.stride, self.padding, self.dilation, self.groups, ctx.policy
        )
        if self.bias:
            b = ctx.param(self, "b", (self.num_filters,), init_mod.zeros, self.bias_attr)
            out = out + b
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("conv_transpose")
class Conv2DTranspose(Layer):
    """Transposed 2-D conv (ExpandConvLayer with trans=True; ConvTransLayerBase)."""

    type_name = "conv_transpose"

    def __init__(
        self,
        input: Layer,
        num_filters: int,
        filter_size: Union[int, Tuple[int, int]],
        stride: Union[int, Tuple[int, int]] = 1,
        padding: Union[int, Tuple[int, int]] = 0,
        act: Any = None,
        bias: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.num_filters = num_filters
        self.filter_size = filter_size
        self.stride = stride
        self.padding = padding
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        kh, kw = conv_ops._pair(self.filter_size)
        cin = x.shape[-1]
        w = ctx.param(
            self,
            "w",
            (kh, kw, self.num_filters, cin),
            init_mod.he_normal,
            self.param_attr,
        )
        out = conv_ops.conv2d_transpose(x, w, self.stride, self.padding, ctx.policy)
        if self.bias:
            b = ctx.param(self, "b", (self.num_filters,), init_mod.zeros, self.bias_attr)
            out = out + b
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("pool")
class Pool2D(Layer):
    """Max/avg pooling, NHWC (PoolLayer.cpp / CudnnPoolLayer.cpp;
    hl_maxpool/avgpool kernels in hl_cuda_cnn.cu)."""

    type_name = "pool"

    def __init__(
        self,
        input: Layer,
        pool_size: Union[int, Tuple[int, int]],
        pool_type: str = "max",
        stride: Optional[Union[int, Tuple[int, int]]] = None,
        padding: Union[int, Tuple[int, int]] = 0,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        assert pool_type in ("max", "avg")
        self.pool_size = pool_size
        self.pool_type = pool_type
        self.stride = stride
        self.padding = padding

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        if self.pool_type == "max":
            out = conv_ops.max_pool2d(x, self.pool_size, self.stride, self.padding)
        else:
            out = conv_ops.avg_pool2d(x, self.pool_size, self.stride, self.padding)
        return ins[0].with_value(out)


@LAYERS.register("batch_norm")
class BatchNorm(Layer):
    """Batch normalization (BatchNormalizationLayer.cpp / CudnnBatchNormLayer.cpp;
    hl_batch_norm.cu). Works on [B, D] or NHWC [B, H, W, C]; moving stats are
    functional state updated only in train mode (movingAvgFraction default 0.9,
    BatchNormBaseLayer)."""

    type_name = "batch_norm"

    def __init__(
        self,
        input: Layer,
        act: Any = None,
        epsilon: float = 1e-5,
        moving_average_fraction: float = 0.9,
        use_global_stats: Optional[bool] = None,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.act = act
        self.epsilon = epsilon
        self.maf = moving_average_fraction
        self.use_global_stats = use_global_stats
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        c = x.shape[-1]
        axes = tuple(range(x.ndim - 1))
        gamma = ctx.param(self, "scale", (c,), init_mod.ones, self.param_attr)
        beta = ctx.param(self, "bias", (c,), init_mod.zeros, self.bias_attr)
        moving_mean = ctx.state(self, "moving_mean", (c,), 0.0)
        moving_var = ctx.state(self, "moving_var", (c,), 1.0)
        use_global = (
            self.use_global_stats
            if self.use_global_stats is not None
            else not ctx.train
        )
        if use_global:
            mean, var = moving_mean, moving_var
        else:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            ctx.update_state(
                self, "moving_mean", self.maf * moving_mean + (1 - self.maf) * mean
            )
            ctx.update_state(
                self, "moving_var", self.maf * moving_var + (1 - self.maf) * var
            )
        inv = jax.lax.rsqrt(var + self.epsilon) * gamma
        out = ((x.astype(jnp.float32) - mean) * inv + beta).astype(x.dtype)
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("dropout")
class Dropout(Layer):
    """Dropout (Layer.h drop_rate handling in Layer::forwardDropOut). Inverted
    dropout: scales by 1/(1-rate) at train time, identity at inference."""

    type_name = "dropout"

    def __init__(self, input: Layer, rate: float, name: Optional[str] = None):
        super().__init__(input, name=name)
        self.rate = rate

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        if not ctx.train or self.rate <= 0.0:
            return ins[0]
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(ctx.next_rng(self.name), keep, x.shape)
        return ins[0].with_value(jnp.where(mask, x / keep, 0).astype(x.dtype))


@LAYERS.register("addto")
class Addto(Layer):
    """Elementwise sum of N inputs (+bias, activation) — AddtoLayer.cpp.
    This is the residual-connection workhorse for ResNet."""

    type_name = "addto"

    def __init__(
        self,
        input: Sequence[Layer],
        act: Any = None,
        bias: bool = False,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.act = act
        self.bias = bias
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        out = ins[0].value
        for other in ins[1:]:
            out = out + other.value
        if self.bias:
            b = ctx.param(self, "b", (out.shape[-1],), init_mod.zeros, self.bias_attr)
            out = out + b
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("concat")
class Concat(Layer):
    """Feature-axis concat of N inputs (ConcatenateLayer.cpp)."""

    type_name = "concat"

    def __init__(self, input: Sequence[Layer], act: Any = None, name=None):
        super().__init__(input, name=name)
        self.act = act

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        out = jnp.concatenate([a.value for a in ins], axis=-1)
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("scaling")
class Scaling(Layer):
    """Row-wise scale: out[i] = w[i] * x[i], weight from first input
    (ScalingLayer.cpp: input[0]=weight [B,1], input[1]=data)."""

    type_name = "scaling"

    def __init__(self, weight: Layer, input: Layer, name=None):
        super().__init__([weight, input], name=name)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        w, x = ins[0].value, ins[1].value
        while w.ndim < x.ndim:
            w = w[..., None]
        return ins[1].with_value(w * x)


@LAYERS.register("slope_intercept")
class SlopeIntercept(Layer):
    """y = slope * x + intercept (SlopeInterceptLayer.cpp)."""

    type_name = "slope_intercept"

    def __init__(self, input: Layer, slope: float = 1.0, intercept: float = 0.0, name=None):
        super().__init__(input, name=name)
        self.slope = slope
        self.intercept = intercept

    def forward(self, ctx, ins):
        return ins[0].with_value(self.slope * ins[0].value + self.intercept)


@LAYERS.register("interpolation")
class Interpolation(Layer):
    """out = w*x + (1-w)*y with per-row weight (InterpolationLayer.cpp).
    inputs: [weight [B,1], x, y]."""

    type_name = "interpolation"

    def __init__(self, weight: Layer, input1: Layer, input2: Layer, name=None):
        super().__init__([weight, input1, input2], name=name)

    def forward(self, ctx, ins):
        w = ins[0].value
        x, y = ins[1].value, ins[2].value
        while w.ndim < x.ndim:
            w = w[..., None]
        return ins[1].with_value(w * x + (1.0 - w) * y)


@LAYERS.register("power")
class Power(Layer):
    """out[i] = x[i] ** p[i], per-row exponent from first input (PowerLayer.cpp)."""

    type_name = "power"

    def __init__(self, exponent: Layer, input: Layer, name=None):
        super().__init__([exponent, input], name=name)

    def forward(self, ctx, ins):
        p, x = ins[0].value, ins[1].value
        while p.ndim < x.ndim:
            p = p[..., None]
        return ins[1].with_value(jnp.power(x, p))


@LAYERS.register("dot_prod")
class DotProd(Layer):
    """Row-wise dot product of two inputs → [B, 1] (DotProdLayer.cpp)."""

    type_name = "dot_prod"

    def __init__(self, input1: Layer, input2: Layer, name=None):
        super().__init__([input1, input2], name=name)

    def forward(self, ctx, ins):
        out = jnp.sum(ins[0].value * ins[1].value, axis=-1, keepdims=True)
        return ins[0].with_value(out)


@LAYERS.register("cos_sim")
class CosSim(Layer):
    """Row-wise cosine similarity ×scale → [B, 1] (CosSimLayer.cpp,
    paddle/function/CosSimOp.cpp)."""

    type_name = "cos_sim"

    def __init__(self, input1: Layer, input2: Layer, scale: float = 1.0, name=None):
        super().__init__([input1, input2], name=name)
        self.scale = scale

    def forward(self, ctx, ins):
        a, b = ins[0].value, ins[1].value
        num = jnp.sum(a * b, axis=-1, keepdims=True)
        den = jnp.linalg.norm(a, axis=-1, keepdims=True) * jnp.linalg.norm(
            b, axis=-1, keepdims=True
        )
        return ins[0].with_value(self.scale * num / jnp.maximum(den, 1e-12))


@LAYERS.register("mixed")
class Mixed(Layer):
    """Sum of projections (MixedLayer.cpp): each input arrives via a Projection
    object (see paddle_tpu/nn/projections.py); results are summed, then
    bias+activation — matching Projection.h/Operator.h semantics."""

    type_name = "mixed"

    def __init__(
        self,
        input: Sequence["Projection"],
        size: Optional[int] = None,
        act: Any = None,
        bias: bool = False,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        from paddle_tpu.nn.projections import Projection

        self.projections = []
        srcs: List[Layer] = []
        for p in input:
            if not isinstance(p, Projection):
                raise TypeError("mixed layer inputs must be Projections")
            self.projections.append(p)
            srcs.extend(p.sources)
        super().__init__(srcs, name=name)
        self.size = size
        self.act = act
        self.bias = bias
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        out = None
        pos = 0
        first_arg = None
        for proj in self.projections:
            n = len(proj.sources)
            args = ins[pos : pos + n]
            pos += n
            if first_arg is None:
                first_arg = args[0]
            y = proj.apply(ctx, self, args, self.size)
            out = y if out is None else out + y
        if self.bias:
            b = ctx.param(self, "b", (out.shape[-1],), init_mod.zeros, self.bias_attr)
            out = out + b
        out = act_mod.apply(self.act, out)
        return first_arg.with_value(out)


@LAYERS.register("trans")
class Trans(Layer):
    """Matrix transpose of the feature block [B, M*N] viewed as MxN (TransLayer)."""

    type_name = "trans"

    def __init__(self, input: Layer, height: int, name=None):
        super().__init__(input, name=name)
        self.height = height

    def forward(self, ctx, ins):
        x = ins[0].value
        b, d = x.shape
        h = self.height
        out = x.reshape(b, h, d // h).swapaxes(1, 2).reshape(b, d)
        return ins[0].with_value(out)


@LAYERS.register("reshape")
class Reshape(Layer):
    """Feature reshape (ResizeLayer semantics: reinterpret [B, D] as [B', D'])."""

    type_name = "reshape"

    def __init__(self, input: Layer, shape: Sequence[int], name=None):
        super().__init__(input, name=name)
        self.shape = tuple(shape)

    def forward(self, ctx, ins):
        x = ins[0].value
        return Argument(x.reshape((x.shape[0],) + self.shape))
