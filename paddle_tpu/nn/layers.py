"""Core layers (batch 1: dense / image / elementwise).

TPU-native re-implementations of the reference layer types in
paddle/gserver/layers/ (93 REGISTER_LAYER registrations, Layer.h:31). Each class
docstring cites the reference layer it matches. Layers are pure specs — see
paddle_tpu/nn/graph.py; backward is autodiff."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn import activations as act_mod
from paddle_tpu.nn import init as init_mod
from paddle_tpu.nn.graph import Argument, Context, Layer, ParamAttr
from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import linalg
from paddle_tpu.ops import normalization as norm_ops

Array = jax.Array


def _attr(a: Optional[Union[ParamAttr, dict]]) -> Optional[ParamAttr]:
    if a is None or isinstance(a, ParamAttr):
        return a
    if isinstance(a, bool):  # bias_attr=True/False toggles, carries no attrs
        return None
    if isinstance(a, (list, tuple)):  # per-input attrs (multi-input fc/mixed)
        return [_attr(x) for x in a]
    return ParamAttr(**a)


@LAYERS.register("data")
class Data(Layer):
    """Input slot (DataLayer, gserver/layers/DataLayer.cpp). `shape` excludes the
    batch dim; sequence inputs additionally carry lengths in the feed dict."""

    type_name = "data"

    def __init__(self, name: str, shape: Sequence[int] = (), is_seq: bool = False):
        super().__init__(None, name=name)
        self.shape = tuple(shape)
        self.is_seq = is_seq

    def forward(self, ctx, ins):  # data layers are fed directly by Network._run
        raise AssertionError("data layer forward should not be called")


@LAYERS.register("fc")
class Fc(Layer):
    """Fully-connected (FullyConnectedLayer.cpp). Multiple inputs each get their
    own weight, summed before bias+activation — matching the reference, whose fc
    accepts several inputs. Sequence inputs are applied per-timestep."""

    type_name = "fc"

    def __init__(
        self,
        input: Union[Layer, Sequence[Layer]],
        size: int,
        act: Any = "tanh",
        bias: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        total = None
        any_seq = any(a.is_seq for a in ins)
        for i, arg in enumerate(ins):
            x = arg.value
            if not arg.is_seq and x.ndim > 2:
                # image/feature-map input: v1 fc operates on the flattened
                # vector (FullyConnectedLayer consumes the flat Argument)
                x = x.reshape(x.shape[0], -1)
            d = x.shape[-1]
            suffix = "" if len(ins) == 1 else f".{i}"
            pa = self.param_attr
            if isinstance(pa, list):
                pa = pa[i] if i < len(pa) else None
            w = ctx.param(
                self, "w" + suffix, (d, self.size), init_mod.smart_normal, pa
            )
            y = linalg.matmul(x, w, ctx.policy)
            if any_seq and y.ndim == 2:
                # flat input mixed with sequence inputs: broadcast over time
                # (the reference adds the non-seq row to every token)
                y = y[:, None]
            total = y if total is None else total + y
        if self.bias:
            b = ctx.param(self, "b", (self.size,), init_mod.zeros, self.bias_attr)
            total = total + b
        total = act_mod.apply(self.act, total)
        return ins[0].with_value(total)


@LAYERS.register("embedding")
class Embedding(Layer):
    """Embedding lookup (TableProjection + hl_table_apply row select,
    paddle/cuda/src/hl_table_apply.cu). Input carries int ids [B] or [B, T]."""

    type_name = "embedding"

    def __init__(
        self,
        input: Layer,
        size: int,
        vocab_size: Optional[int] = None,
        param_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.size = size
        self.vocab_size = vocab_size
        self.param_attr = _attr(param_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        ids = ins[0].value
        vocab = self.vocab_size
        if vocab is None:
            src = self.inputs[0]
            vocab = getattr(src, "shape", (None,))[0]
            if vocab is None:
                raise ValueError(
                    f"embedding {self.name}: vocab_size not set and input has no shape"
                )
        table = ctx.param(
            self, "w", (vocab, self.size), init_mod.smart_normal, self.param_attr
        )
        out = jnp.take(table, ids.astype(jnp.int32), axis=0)
        return ins[0].with_value(out)


@LAYERS.register("conv")
class Conv2D(Layer):
    """2-D convolution, NHWC (ExpandConvLayer.cpp / CudnnConvBaseLayer.cpp via
    GemmConvOp; here a single XLA conv HLO on the MXU)."""

    type_name = "conv"

    def __init__(
        self,
        input: Layer,
        num_filters: int,
        filter_size: Union[int, Tuple[int, int]],
        stride: Union[int, Tuple[int, int]] = 1,
        padding: Union[int, Tuple[int, int], str] = 0,
        dilation: Union[int, Tuple[int, int]] = 1,
        groups: int = 1,
        act: Any = None,
        bias: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.num_filters = num_filters
        self.filter_size = filter_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        assert x.ndim == 4, f"conv {self.name}: expect NHWC input, got {x.shape}"
        kh, kw = conv_ops._pair(self.filter_size)
        cin = x.shape[-1]
        w = ctx.param(
            self,
            "w",
            (kh, kw, cin // self.groups, self.num_filters),
            init_mod.he_normal,
            self.param_attr,
        )
        out = conv_ops.conv2d(
            x, w, self.stride, self.padding, self.dilation, self.groups, ctx.policy
        )
        if self.bias:
            b = ctx.param(self, "b", (self.num_filters,), init_mod.zeros, self.bias_attr)
            out = out + b
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("conv_transpose")
class Conv2DTranspose(Layer):
    """Transposed 2-D conv (ExpandConvLayer with trans=True; ConvTransLayerBase)."""

    type_name = "conv_transpose"

    def __init__(
        self,
        input: Layer,
        num_filters: int,
        filter_size: Union[int, Tuple[int, int]],
        stride: Union[int, Tuple[int, int]] = 1,
        padding: Union[int, Tuple[int, int]] = 0,
        act: Any = None,
        bias: bool = True,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.num_filters = num_filters
        self.filter_size = filter_size
        self.stride = stride
        self.padding = padding
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        kh, kw = conv_ops._pair(self.filter_size)
        cin = x.shape[-1]
        w = ctx.param(
            self,
            "w",
            (kh, kw, self.num_filters, cin),
            init_mod.he_normal,
            self.param_attr,
        )
        out = conv_ops.conv2d_transpose(x, w, self.stride, self.padding, ctx.policy)
        if self.bias:
            b = ctx.param(self, "b", (self.num_filters,), init_mod.zeros, self.bias_attr)
            out = out + b
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("pool")
class Pool2D(Layer):
    """Max/avg pooling, NHWC (PoolLayer.cpp / CudnnPoolLayer.cpp;
    hl_maxpool/avgpool kernels in hl_cuda_cnn.cu)."""

    type_name = "pool"

    def __init__(
        self,
        input: Layer,
        pool_size: Union[int, Tuple[int, int]],
        pool_type: str = "max",
        stride: Optional[Union[int, Tuple[int, int]]] = None,
        padding: Union[int, Tuple[int, int]] = 0,
        ceil_mode: bool = False,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        assert pool_type in ("max", "avg")
        self.pool_size = pool_size
        self.pool_type = pool_type
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode

    def _pads(self, x) -> Any:
        """ceil_mode=True (the v1 default, MathUtils outputSize with
        caffeMode=false): out = ceil((I + 2p - f) / s) + 1. Emulated with
        extra bottom/right padding so partial windows at the edge survive."""
        if not self.ceil_mode:
            return self.padding
        fh, fw = conv_ops._pair(self.pool_size)
        sh, sw = conv_ops._pair(
            self.stride if self.stride is not None else self.pool_size
        )
        ph, pw = conv_ops._pair(self.padding)
        out = []
        for size, f, s, p in ((x.shape[1], fh, sh, ph), (x.shape[2], fw, sw, pw)):
            n_out = -(-(size + 2 * p - f) // s) + 1  # ceil-div
            extra = max(0, (n_out - 1) * s + f - size - 2 * p)
            out.append((p, p + extra))
        return tuple(out)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        pads = self._pads(x)
        if self.pool_type == "max":
            out = conv_ops.max_pool2d(x, self.pool_size, self.stride, pads)
        else:
            out = conv_ops.avg_pool2d(x, self.pool_size, self.stride, pads)
        return ins[0].with_value(out)


@LAYERS.register("batch_norm")
class BatchNorm(Layer):
    """Batch normalization (BatchNormalizationLayer.cpp / CudnnBatchNormLayer.cpp;
    hl_batch_norm.cu). Works on [B, D] or NHWC [B, H, W, C]; moving stats are
    functional state updated only in train mode (movingAvgFraction default 0.9,
    BatchNormBaseLayer)."""

    type_name = "batch_norm"

    def __init__(
        self,
        input: Layer,
        act: Any = None,
        epsilon: float = 1e-5,
        moving_average_fraction: float = 0.9,
        use_global_stats: Optional[bool] = None,
        param_attr: Any = None,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.act = act
        self.epsilon = epsilon
        self.maf = moving_average_fraction
        self.use_global_stats = use_global_stats
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        c = x.shape[-1]
        gamma = ctx.param(self, "scale", (c,), init_mod.ones, self.param_attr)
        beta = ctx.param(self, "bias", (c,), init_mod.zeros, self.bias_attr)
        moving_mean = ctx.state(self, "moving_mean", (c,), 0.0)
        moving_var = ctx.state(self, "moving_var", (c,), 1.0)
        use_global = (
            self.use_global_stats
            if self.use_global_stats is not None
            else not ctx.train
        )
        if use_global:
            out = norm_ops.batch_norm_inference(
                x, gamma, beta, moving_mean, moving_var, self.epsilon
            )
        else:
            # fused one-pass stats + minimal-pass custom VJP — the profiled
            # bandwidth hot spot of conv/BN models (ops/normalization.py)
            out, mean, var = norm_ops.batch_norm_train(
                x, gamma, beta, self.epsilon
            )
            ctx.update_state(
                self, "moving_mean", self.maf * moving_mean + (1 - self.maf) * mean
            )
            ctx.update_state(
                self, "moving_var", self.maf * moving_var + (1 - self.maf) * var
            )
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("dropout")
class Dropout(Layer):
    """Dropout (Layer.h drop_rate handling in Layer::forwardDropOut). Inverted
    dropout: scales by 1/(1-rate) at train time, identity at inference."""

    type_name = "dropout"

    def __init__(self, input: Layer, rate: float, name: Optional[str] = None):
        super().__init__(input, name=name)
        self.rate = rate

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        if not ctx.train or self.rate <= 0.0:
            return ins[0]
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(ctx.next_rng(self.name), keep, x.shape)
        return ins[0].with_value(jnp.where(mask, x / keep, 0).astype(x.dtype))


@LAYERS.register("addto")
class Addto(Layer):
    """Elementwise sum of N inputs (+bias, activation) — AddtoLayer.cpp.
    This is the residual-connection workhorse for ResNet."""

    type_name = "addto"

    def __init__(
        self,
        input: Sequence[Layer],
        act: Any = None,
        bias: bool = False,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.act = act
        self.bias = bias
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        out = ins[0].value
        for other in ins[1:]:
            out = out + other.value
        if self.bias:
            b = ctx.param(self, "b", (out.shape[-1],), init_mod.zeros, self.bias_attr)
            out = out + b
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("concat")
class Concat(Layer):
    """Feature-axis concat of N inputs (ConcatenateLayer.cpp)."""

    type_name = "concat"

    def __init__(self, input: Sequence[Layer], act: Any = None, name=None):
        super().__init__(input, name=name)
        self.act = act

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        out = jnp.concatenate([a.value for a in ins], axis=-1)
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("scaling")
class Scaling(Layer):
    """Row-wise scale: out[i] = w[i] * x[i], weight from first input
    (ScalingLayer.cpp: input[0]=weight [B,1], input[1]=data)."""

    type_name = "scaling"

    def __init__(self, weight: Layer, input: Layer, name=None):
        super().__init__([weight, input], name=name)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        w, x = ins[0].value, ins[1].value
        while w.ndim < x.ndim:
            w = w[..., None]
        return ins[1].with_value(w * x)


@LAYERS.register("slope_intercept")
class SlopeIntercept(Layer):
    """y = slope * x + intercept (SlopeInterceptLayer.cpp)."""

    type_name = "slope_intercept"

    def __init__(self, input: Layer, slope: float = 1.0, intercept: float = 0.0, name=None):
        super().__init__(input, name=name)
        self.slope = slope
        self.intercept = intercept

    def forward(self, ctx, ins):
        return ins[0].with_value(self.slope * ins[0].value + self.intercept)


@LAYERS.register("interpolation")
class Interpolation(Layer):
    """out = w*x + (1-w)*y with per-row weight (InterpolationLayer.cpp).
    inputs: [weight [B,1], x, y]."""

    type_name = "interpolation"

    def __init__(self, weight: Layer, input1: Layer, input2: Layer, name=None):
        super().__init__([weight, input1, input2], name=name)

    def forward(self, ctx, ins):
        w = ins[0].value
        x, y = ins[1].value, ins[2].value
        while w.ndim < x.ndim:
            w = w[..., None]
        return ins[1].with_value(w * x + (1.0 - w) * y)


@LAYERS.register("power")
class Power(Layer):
    """out[i] = x[i] ** p[i], per-row exponent from first input (PowerLayer.cpp)."""

    type_name = "power"

    def __init__(self, exponent: Layer, input: Layer, name=None):
        super().__init__([exponent, input], name=name)

    def forward(self, ctx, ins):
        p, x = ins[0].value, ins[1].value
        while p.ndim < x.ndim:
            p = p[..., None]
        return ins[1].with_value(jnp.power(x, p))


@LAYERS.register("dot_prod")
class DotProd(Layer):
    """Row-wise dot product of two inputs → [B, 1] (DotProdLayer.cpp)."""

    type_name = "dot_prod"

    def __init__(self, input1: Layer, input2: Layer, name=None):
        super().__init__([input1, input2], name=name)

    def forward(self, ctx, ins):
        out = jnp.sum(ins[0].value * ins[1].value, axis=-1, keepdims=True)
        return ins[0].with_value(out)


@LAYERS.register("cos_sim")
class CosSim(Layer):
    """Row-wise cosine similarity ×scale → [B, 1] (CosSimLayer.cpp,
    paddle/function/CosSimOp.cpp)."""

    type_name = "cos_sim"

    def __init__(self, input1: Layer, input2: Layer, scale: float = 1.0, name=None):
        super().__init__([input1, input2], name=name)
        self.scale = scale

    def forward(self, ctx, ins):
        a, b = ins[0].value, ins[1].value
        num = jnp.sum(a * b, axis=-1, keepdims=True)
        den = jnp.linalg.norm(a, axis=-1, keepdims=True) * jnp.linalg.norm(
            b, axis=-1, keepdims=True
        )
        return ins[0].with_value(self.scale * num / jnp.maximum(den, 1e-12))


@LAYERS.register("convex_comb")
class LinearComb(Layer):
    """Per-sample weighted sum of vectors (ConvexCombinationLayer /
    linear_comb_layer, layers.py:4984): weights [B, M], vectors [B, M*N] →
    z[i] = Σ_j x[j]·y[i+N·j], i.e. z = xᵀ·Y with Y = vectors.reshape(M, N)."""

    type_name = "convex_comb"

    def __init__(self, weights: Layer, vectors: Layer, size: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__([weights, vectors], name=name)
        self.size = size

    def forward(self, ctx, ins):
        x, y = ins[0].value, ins[1].value
        b, m = x.shape
        n = self.size or y.shape[-1] // m
        assert m * n == y.shape[-1], (
            f"convex_comb {self.name}: vectors dim {y.shape[-1]} != "
            f"weights dim {m} × size {n}"
        )
        out = jnp.einsum("bm,bmn->bn", x, y.reshape(b, m, n))
        return ins[1].with_value(out)


@LAYERS.register("cos_vm")
class CosSimVecMat(Layer):
    """Cosine similarity of one vector against each row of a per-sample
    matrix (CosSimVecMatLayer.cpp): vec [B, M], mat [B, M*N] → [B, N],
    out[i] = scale · cos(vec, mat_row_i)."""

    type_name = "cos_vm"

    def __init__(self, vec: Layer, mat: Layer, size: Optional[int] = None,
                 scale: float = 1.0, name: Optional[str] = None):
        super().__init__([vec, mat], name=name)
        self.size = size
        self.scale = scale

    def forward(self, ctx, ins):
        v, m_flat = ins[0].value, ins[1].value
        b, dim = v.shape
        n = self.size or m_flat.shape[-1] // dim
        assert dim * n == m_flat.shape[-1], (
            f"cos_vm {self.name}: mat dim {m_flat.shape[-1]} != "
            f"vec dim {dim} × keys {n}"
        )
        mat = m_flat.reshape(b, n, dim)
        num = jnp.einsum("bd,bnd->bn", v, mat)
        den = jnp.linalg.norm(v, axis=-1, keepdims=True) * jnp.linalg.norm(
            mat, axis=-1
        )
        return ins[1].with_value(self.scale * num / jnp.maximum(den, 1e-12))


@LAYERS.register("mixed")
class Mixed(Layer):
    """Sum of projections (MixedLayer.cpp): each input arrives via a Projection
    object (see paddle_tpu/nn/projections.py); results are summed, then
    bias+activation — matching Projection.h/Operator.h semantics."""

    type_name = "mixed"

    def __init__(
        self,
        input: Sequence["Projection"],
        size: Optional[int] = None,
        act: Any = None,
        bias: bool = False,
        bias_attr: Any = None,
        name: Optional[str] = None,
    ):
        from paddle_tpu.nn.projections import Projection

        self.projections = []
        for p in input:
            if not isinstance(p, Projection):
                raise TypeError("mixed layer inputs must be Projections")
            self.projections.append(p)
        super().__init__([], name=name)
        self._relayout()
        self.size = size
        self.act = act
        self.bias = bias
        self.bias_attr = _attr(bias_attr)

    def _relayout(self):
        """Input-slot layout matching the reference's MixedLayer config:
        each projection/operator claims one slot in declaration order for its
        FIRST source; operators' extra sources append at the end (that is how
        the golden protostrs index operator_confs.input_indices)."""
        slots: List[Layer] = []
        arg_slots: List[List[int]] = []
        for p in self.projections:
            arg_slots.append([len(slots)])
            slots.append(p.sources[0])
        for i, p in enumerate(self.projections):
            for extra in p.sources[1:]:
                arg_slots[i].append(len(slots))
                slots.append(extra)
        self.inputs = slots
        self._arg_slots = arg_slots

    # -- incremental construction (trainer_config_helpers MixedLayerType:
    #    `with mixed_layer(size=N) as m: m += full_matrix_projection(x)`) ----
    def __iadd__(self, proj):
        from paddle_tpu.nn.projections import Projection

        if not isinstance(proj, Projection):
            raise TypeError("mixed layer inputs must be Projections")
        self.projections.append(proj)
        self._relayout()
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and not self.projections:
            raise ValueError(f"mixed layer {self.name!r} finalized with no projections")
        return False

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        out = None
        first_arg = None
        for proj, slots in zip(self.projections, self._arg_slots):
            args = [ins[j] for j in slots]
            if first_arg is None:
                first_arg = args[0]
            y = proj.apply(ctx, self, args, self.size)
            out = y if out is None else out + y
        if self.bias:
            b = ctx.param(self, "b", (out.shape[-1],), init_mod.zeros, self.bias_attr)
            out = out + b
        out = act_mod.apply(self.act, out)
        return first_arg.with_value(out)


@LAYERS.register("concat2")
class Concat2(Layer):
    """ConcatenateLayer2: apply a projection per input, concatenate results
    feature-wise (the projection-input form of concat_layer)."""

    type_name = "concat2"

    def __init__(self, input, act: Any = None, bias: bool = False,
                 bias_attr: Any = None, name: Optional[str] = None):
        from paddle_tpu.nn.projections import Projection

        self.projections = []
        srcs: List[Layer] = []
        for p in input:
            if not isinstance(p, Projection):
                raise TypeError("concat2 inputs must be Projections")
            self.projections.append(p)
            srcs.extend(p.sources)
        super().__init__(srcs, name=name)
        self.act = act
        self.bias = bias
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx, ins):
        outs = []
        pos = 0
        first_arg = None
        for proj in self.projections:
            n = len(proj.sources)
            args = ins[pos : pos + n]
            pos += n
            if first_arg is None:
                first_arg = args[0]
            outs.append(proj.apply(ctx, self, args, None))
        out = jnp.concatenate(outs, axis=-1)
        if self.bias:
            b = ctx.param(self, "b", (out.shape[-1],), init_mod.zeros, self.bias_attr)
            out = out + b
        return first_arg.with_value(act_mod.apply(self.act, out))


@LAYERS.register("trans")
class Trans(Layer):
    """TransLayer. With `height` set: transpose of the feature block
    [B, M*N] viewed as MxN. Without height: the reference transposes the
    whole batch matrix [B, D] → [D, B] (TransLayer.cpp) — shape inference
    keeps size D like the reference config parser does (a real transpose
    only round-trips when batch == D, the reference's implicit contract), so
    tracing treats it as identity and the runtime transposes."""

    type_name = "trans"

    def __init__(self, input: Layer, height: Optional[int] = None, name=None):
        super().__init__(input, name=name)
        self.height = height

    def forward(self, ctx, ins):
        x = ins[0].value
        if self.height is None:
            if ctx.mode == "init":
                return ins[0]  # config-level identity (size preserved)
            return Argument(x.T)
        b, d = x.shape
        h = self.height
        out = x.reshape(b, h, d // h).swapaxes(1, 2).reshape(b, d)
        return ins[0].with_value(out)


@LAYERS.register("reshape")
class Reshape(Layer):
    """Feature reshape (ResizeLayer semantics: reinterpret [B, D] as [B', D'])."""

    type_name = "reshape"

    def __init__(self, input: Layer, shape: Sequence[int], name=None):
        super().__init__(input, name=name)
        self.shape = tuple(shape)

    def forward(self, ctx, ins):
        x = ins[0].value
        shape = self.shape
        if -1 in shape:
            known = 1
            for d in shape:
                if d != -1:
                    known *= d
            rest = int(np.prod(x.shape[1:])) // known
            shape = tuple(rest if d == -1 else d for d in shape)
        return Argument(x.reshape((x.shape[0],) + shape))


@LAYERS.register("global_pool")
class GlobalPool(Layer):
    """Global spatial pooling NHWC → [B, C] (the reference expresses this as a
    PoolLayer with full-image kernel, e.g. resnet's pool7x7 avg)."""

    type_name = "global_pool"

    def __init__(self, input: Layer, pool_type: str = "avg", name=None):
        super().__init__(input, name=name)
        assert pool_type in ("avg", "max")
        self.pool_type = pool_type

    def forward(self, ctx, ins):
        x = ins[0].value
        if self.pool_type == "avg":
            return ins[0].with_value(jnp.mean(x, axis=(1, 2)))
        return ins[0].with_value(jnp.max(x, axis=(1, 2)))


@LAYERS.register("maxout")
class Maxout(Layer):
    """Maxout over channel groups (MaxOutLayer.cpp; hl_maxout_forward)."""

    type_name = "maxout"

    def __init__(self, input: Layer, groups: int, name=None):
        super().__init__(input, name=name)
        self.groups = groups

    def forward(self, ctx, ins):
        x = ins[0].value
        c = x.shape[-1]
        out = x.reshape(x.shape[:-1] + (c // self.groups, self.groups)).max(-1)
        return ins[0].with_value(out)


@LAYERS.register("spp")
class SpatialPyramidPool(Layer):
    """Spatial pyramid pooling (SpatialPyramidPoolLayer.cpp): concat of
    max/avg pools at pyramid levels 1,2,4,... bins → fixed-size vector."""

    type_name = "spp"

    def __init__(self, input: Layer, pyramid_height: int = 3, pool_type: str = "max", name=None):
        super().__init__(input, name=name)
        self.pyramid_height = pyramid_height
        self.pool_type = pool_type

    def forward(self, ctx, ins):
        x = ins[0].value
        b, h, w, c = x.shape
        outs = []
        for level in range(self.pyramid_height):
            bins = 2**level
            if bins > h or bins > w:
                # finer than the feature map — skip the level (input smaller
                # than the pyramid base)
                continue
            bh, bw = h // bins, w // bins
            cropped = x[:, : bh * bins, : bw * bins, :]
            tiles = cropped.reshape(b, bins, bh, bins, bw, c)
            if self.pool_type == "max":
                pooled = tiles.max(axis=(2, 4))
            else:
                pooled = tiles.mean(axis=(2, 4))
            outs.append(pooled.reshape(b, bins * bins * c))
        return Argument(jnp.concatenate(outs, axis=-1))


@LAYERS.register("lrn", "img_cmrnorm")
class CrossMapNorm(Layer):
    """Local response normalization across channels (NormProjectionLayer /
    CrossMapNormalOp, paddle/function/CrossMapNormalOp.cpp)."""

    type_name = "lrn"

    def __init__(self, input: Layer, size: int = 5, scale: float = 1e-4, power: float = 0.75, name=None):
        super().__init__(input, name=name)
        self.size = size
        self.scale = scale
        self.power = power

    def forward(self, ctx, ins):
        x = ins[0].value
        sq = jnp.square(x)
        half = self.size // 2
        # sum over a window of channels via padding + stacked slices
        padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
        c = x.shape[-1]
        acc = sum(padded[..., i : i + c] for i in range(self.size))
        denom = jnp.power(1.0 + self.scale * acc, self.power)
        return ins[0].with_value(x / denom)


@LAYERS.register("row_l2_norm")
class RowL2Norm(Layer):
    """Row-wise L2 normalization (RowL2NormLayer.cpp)."""

    type_name = "row_l2_norm"

    def forward(self, ctx, ins):
        x = ins[0].value
        return ins[0].with_value(x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12))


@LAYERS.register("cross_channel_norm")
class CrossChannelNorm(Layer):
    """Per-pixel channel L2 norm with learned per-channel scale
    (CrossChannelNormLayer.cpp, used by SSD)."""

    type_name = "cross_channel_norm"

    def forward(self, ctx, ins):
        x = ins[0].value
        c = x.shape[-1]
        scale = ctx.param(self, "scale", (c,), init_mod.ones, None)
        norm = jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
        return ins[0].with_value(x / norm * scale)


@LAYERS.register("data_norm")
class DataNorm(Layer):
    """Feature standardization with precomputed stats (DataNormLayer.cpp):
    z-score / min-max / decimal-scaling using static (non-trained) stats."""

    type_name = "data_norm"

    def __init__(self, input: Layer, strategy: str = "z-score", name=None):
        super().__init__(input, name=name)
        assert strategy in ("z-score", "min-max", "decimal-scaling")
        self.strategy = strategy

    def forward(self, ctx, ins):
        x = ins[0].value
        d = x.shape[-1]
        if self.strategy == "z-score":
            mean = ctx.state(self, "mean", (d,), 0.0)
            std = ctx.state(self, "std", (d,), 1.0)
            return ins[0].with_value((x - mean) / jnp.maximum(std, 1e-12))
        if self.strategy == "min-max":
            mn = ctx.state(self, "min", (d,), 0.0)
            mx = ctx.state(self, "max", (d,), 1.0)
            return ins[0].with_value((x - mn) / jnp.maximum(mx - mn, 1e-12))
        scale = ctx.state(self, "scale", (d,), 1.0)
        return ins[0].with_value(x / jnp.maximum(scale, 1e-12))


@LAYERS.register("bilinear_interp")
class BilinearInterp(Layer):
    """Bilinear upsampling (BilinearInterpLayer.cpp; hl_bilinear_forward)."""

    type_name = "bilinear_interp"

    def __init__(self, input: Layer, out_size: Tuple[int, int], name=None):
        super().__init__(input, name=name)
        self.out_size = out_size

    def forward(self, ctx, ins):
        from paddle_tpu.ops import conv as conv_ops

        out = conv_ops.bilinear_resize(ins[0].value, *self.out_size)
        return ins[0].with_value(out)


@LAYERS.register("pad")
class Pad(Layer):
    """Zero-padding on H/W/C axes (PadLayer.cpp, paddle/function/PadOp.cpp)."""

    type_name = "pad"

    def __init__(self, input: Layer, pad_h=(0, 0), pad_w=(0, 0), pad_c=(0, 0), name=None):
        super().__init__(input, name=name)
        self.pads = (tuple(pad_h), tuple(pad_w), tuple(pad_c))

    def forward(self, ctx, ins):
        x = ins[0].value
        ph, pw, pc = self.pads
        return ins[0].with_value(jnp.pad(x, ((0, 0), ph, pw, pc)))


@LAYERS.register("crop")
class Crop(Layer):
    """Spatial crop (CropLayer.cpp, paddle/function/CropOp.cpp)."""

    type_name = "crop"

    def __init__(self, input: Layer, offset_h: int, offset_w: int, out_h: int, out_w: int, name=None):
        super().__init__(input, name=name)
        self.offset = (offset_h, offset_w)
        self.out = (out_h, out_w)

    def forward(self, ctx, ins):
        x = ins[0].value
        oh, ow = self.offset
        h, w = self.out
        return ins[0].with_value(x[:, oh : oh + h, ow : ow + w, :])


@LAYERS.register("rotate")
class Rotate(Layer):
    """90° CCW rotation of the spatial block (RotateLayer.cpp)."""

    type_name = "rotate"

    def forward(self, ctx, ins):
        return ins[0].with_value(jnp.rot90(ins[0].value, k=1, axes=(1, 2)))


@LAYERS.register("switch_order")
class SwitchOrder(Layer):
    """NHWC ↔ NCHW reorder (SwitchOrderLayer.cpp, function/SwitchOp.cpp).
    Kept for config parity; internally everything is NHWC."""

    type_name = "switch_order"

    def __init__(self, input: Layer, to: str = "NCHW", name=None):
        super().__init__(input, name=name)
        assert to in ("NCHW", "NHWC", "NCDHW", "NDHWC")
        self.to = to

    def forward(self, ctx, ins):
        x = ins[0].value
        perm = {
            "NCHW": (0, 3, 1, 2),
            "NHWC": (0, 2, 3, 1),
            "NCDHW": (0, 4, 1, 2, 3),
            "NDHWC": (0, 2, 3, 4, 1),
        }[self.to]
        return ins[0].with_value(jnp.transpose(x, perm))


@LAYERS.register("feature_map_expand")
class FeatureMapExpand(Layer):
    """Tile a [B, D] vector across feature-map positions
    (FeatureMapExpandLayer.cpp). as_row_vector=True tiles whole rows
    [a b c a b c]; False repeats each element [a a b b c c]."""

    type_name = "feature_map_expand"

    def __init__(self, input: Layer, num_filters: int, as_row_vector: bool = True,
                 act: Any = None, name=None):
        super().__init__(input, name=name)
        self.num_filters = num_filters
        self.as_row_vector = as_row_vector
        self.act = act

    def forward(self, ctx, ins):
        x = ins[0].value
        if self.as_row_vector:
            out = jnp.repeat(x[:, None, :], self.num_filters, axis=1)
            out = out.reshape(x.shape[0], -1)
        else:
            out = jnp.repeat(x, self.num_filters, axis=-1)
        return ins[0].with_value(act_mod.apply(self.act, out))


@LAYERS.register("resize")
class Resize(Layer):
    """ResizeLayer.cpp: reinterpret the whole [B, D] buffer as
    [B*D/size, size] — batch and feature trade off."""

    type_name = "resize"

    def __init__(self, input: Layer, size: int, name=None):
        super().__init__(input, name=name)
        self.size = size

    def forward(self, ctx, ins):
        x = ins[0].value
        total = x.size
        assert total % self.size == 0, (
            f"resize {self.name}: {tuple(x.shape)} has {total} elements, "
            f"not divisible by size={self.size}"
        )
        return Argument(x.reshape(-1, self.size))


@jax.custom_vjp
def _clip_grad(x, t):
    return x


def _clip_grad_fwd(x, t):
    return x, t


def _clip_grad_bwd(t, g):
    return jnp.clip(g, -t, t), None


_clip_grad.defvjp(_clip_grad_fwd, _clip_grad_bwd)


@LAYERS.register("error_clip")
class ErrorClip(Layer):
    """ExtraLayerAttribute.error_clipping_threshold: identity forward, the
    backpropagated error clipped to ±t (Layer.cpp backwardActivation's
    errorClipping). Chained by the layer_attr seam like dropout."""

    type_name = "error_clip"

    def __init__(self, input: Layer, threshold: float, name=None):
        super().__init__(input, name=name)
        self.threshold = float(threshold)

    def forward(self, ctx, ins):
        return ins[0].with_value(_clip_grad(ins[0].value, self.threshold))


@LAYERS.register("clip")
class Clip(Layer):
    """Elementwise clip (ClipLayer.cpp)."""

    type_name = "clip"

    def __init__(self, input: Layer, min: float, max: float, name=None):
        super().__init__(input, name=name)
        self.lo, self.hi = min, max

    def forward(self, ctx, ins):
        return ins[0].with_value(jnp.clip(ins[0].value, self.lo, self.hi))


@LAYERS.register("scale_shift")
class ScaleShift(Layer):
    """y = w*x + b with scalar learned w, optional scalar b
    (ScaleShiftLayer.cpp: bias only when biasParameter is set)."""

    type_name = "scale_shift"

    def __init__(self, input: Layer, bias: bool = True, param_attr=None,
                 bias_attr=None, name=None):
        super().__init__(input, name=name)
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx, ins):
        x = ins[0].value
        w = ctx.param(self, "w", (1,), init_mod.ones, self.param_attr)
        y = w[0] * x
        if self.bias:
            b = ctx.param(self, "b", (1,), init_mod.zeros, self.bias_attr)
            y = y + b[0]
        return ins[0].with_value(y)


@LAYERS.register("prelu")
class ParameterRelu(Layer):
    """Parametric ReLU with per-partition slopes (ParameterReluLayer.cpp;
    hl_param_relu_forward)."""

    type_name = "prelu"

    def __init__(self, input: Layer, partial_sum: int = 1, param_attr=None, name=None):
        super().__init__(input, name=name)
        self.partial_sum = partial_sum
        self.param_attr = _attr(param_attr)

    def forward(self, ctx, ins):
        x = ins[0].value
        d = x.shape[-1]
        n_slope = d // self.partial_sum
        w = ctx.param(self, "w", (n_slope,), init_mod.constant(0.25), self.param_attr)
        slopes = jnp.repeat(w, self.partial_sum)
        return ins[0].with_value(jnp.where(x > 0, x, x * slopes))


@LAYERS.register("multiplex")
class Multiplex(Layer):
    """Row-wise select among N inputs by index (MultiplexLayer.cpp):
    inputs[0] = int index [B], inputs[1..N] = candidates."""

    type_name = "multiplex"

    def __init__(self, index: Layer, inputs: Sequence[Layer], name=None):
        super().__init__([index] + list(inputs), name=name)

    def forward(self, ctx, ins):
        idx = ins[0].value.astype(jnp.int32).reshape(-1)
        stacked = jnp.stack([a.value for a in ins[1:]], axis=1)  # [B, N, D]
        out = jnp.take_along_axis(stacked, idx[:, None, None], axis=1)[:, 0]
        return ins[1].with_value(out)


@LAYERS.register("outer_prod")
class OuterProd(Layer):
    """Row-wise outer product flattened (OuterProdLayer.cpp)."""

    type_name = "outer_prod"

    def __init__(self, input1: Layer, input2: Layer, name=None):
        super().__init__([input1, input2], name=name)

    def forward(self, ctx, ins):
        a, b = ins[0].value, ins[1].value
        out = jnp.einsum("bi,bj->bij", a, b).reshape(a.shape[0], -1)
        return ins[0].with_value(out)


@LAYERS.register("conv_shift")
class ConvShift(Layer):
    """Circular 1-D correlation of each row with a learned/input kernel
    (ConvShiftLayer.cpp): out[i] = sum_j b[j] * a[(i+j-half) mod D]."""

    type_name = "conv_shift"

    def __init__(self, input1: Layer, input2: Layer, name=None):
        super().__init__([input1, input2], name=name)

    def forward(self, ctx, ins):
        a, b = ins[0].value, ins[1].value
        d = a.shape[-1]
        k = b.shape[-1]
        half = k // 2
        idx = (jnp.arange(d)[:, None] + jnp.arange(k)[None, :] - half) % d
        # out[b, i] = sum_j  a[b, idx[i,j]] * b[b, j]
        gathered = a[:, idx]  # [B, D, K]
        out = jnp.einsum("bdk,bk->bd", gathered, b)
        return ins[0].with_value(out)


@LAYERS.register("sum_to_one_norm")
class SumToOneNorm(Layer):
    """Row normalize to sum 1 (SumToOneNormLayer.cpp)."""

    type_name = "sum_to_one_norm"

    def forward(self, ctx, ins):
        x = ins[0].value
        return ins[0].with_value(x / jnp.maximum(jnp.sum(x, -1, keepdims=True), 1e-12))


@LAYERS.register("tensor")
class TensorLayer(Layer):
    """Bilinear tensor product (TensorLayer.cpp): out_k = x W_k y^T."""

    type_name = "tensor"

    def __init__(self, input1: Layer, input2: Layer, size: int, act=None,
                 bias: bool = True, param_attr=None, bias_attr=None, name=None):
        super().__init__([input1, input2], name=name)
        self.size = size
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.bias_attr = _attr(bias_attr)

    def forward(self, ctx, ins):
        x, y = ins[0].value, ins[1].value
        w = ctx.param(
            self, "w", (self.size, x.shape[-1], y.shape[-1]),
            init_mod.smart_normal, self.param_attr,
        )
        out = jnp.einsum("bi,kij,bj->bk", x, w, y)
        if self.bias:
            b = ctx.param(self, "b", (self.size,), init_mod.zeros, self.bias_attr)
            out = out + b
        out = act_mod.apply(self.act, out)
        return ins[0].with_value(out)


@LAYERS.register("max_id")
class MaxId(Layer):
    """Argmax id of the last axis (MaxIdLayer.cpp); beam_size > 1 → top-k ids,
    matching the reference's beam output for generation."""

    type_name = "max_id"

    def __init__(self, input: Layer, beam_size: int = 1, name=None):
        super().__init__(input, name=name)
        self.beam_size = beam_size

    def forward(self, ctx, ins):
        x = ins[0].value
        if self.beam_size <= 1:
            out = jnp.argmax(x, axis=-1)
        else:
            out = jax.lax.top_k(x, self.beam_size)[1]
        return ins[0].with_value(out)


@LAYERS.register("sampling_id")
class SamplingId(Layer):
    """Sample an id from each row's probability distribution
    (SamplingIdLayer.cpp). Needs an rng in the apply context."""

    type_name = "sampling_id"

    def forward(self, ctx, ins):
        x = ins[0].value
        logits = jnp.log(jnp.maximum(x, 1e-30))
        ids = jax.random.categorical(ctx.next_rng(self.name), logits, axis=-1)
        return ins[0].with_value(ids)


@LAYERS.register("eos_id")
class EosIdCheck(Layer):
    """1 where the input id equals eos_id (EosIdCheckLayer.cpp)."""

    type_name = "eos_id"

    def __init__(self, input: Layer, eos_id: int, name=None):
        super().__init__(input, name=name)
        self.eos_id = eos_id

    def forward(self, ctx, ins):
        return ins[0].with_value(
            (ins[0].value == self.eos_id).astype(jnp.float32)
        )


@LAYERS.register("print")
class PrintLayer(Layer):
    """Debug-print its input during tracing/execution (PrintLayer.cpp) via
    jax.debug.print; passes the value through unchanged."""

    type_name = "print"

    def __init__(self, input: Layer, message: str = "", name=None):
        super().__init__(input, name=name)
        self.message = message

    def forward(self, ctx, ins):
        if ctx.mode == "init":  # config tracing/shape inference: stay quiet
            return ins[0]
        # escape user braces — only the {x} placeholder is a format field
        msg = self.message.replace("{", "{{").replace("}", "}}")
        jax.debug.print((msg + " {x}").lstrip(), x=ins[0].value)
        return ins[0]


@LAYERS.register("block_expand")
class BlockExpand(Layer):
    """Image → sequence of flattened blocks (BlockExpandLayer.cpp +
    paddle/function/BlockExpandOp.cpp, the im2col exposed as a layer — feeds
    OCR CRNN stacks). Input [B, H, W, C] → sequence [B, T, block_y*block_x*C]
    where T = out_h*out_w, scanned row-major like the reference."""

    type_name = "block_expand"

    def __init__(self, input: Layer, block_x: int, block_y: int,
                 stride_x: int = 0, stride_y: int = 0,
                 padding_x: int = 0, padding_y: int = 0, name=None):
        super().__init__(input, name=name)
        self.block = (block_y, block_x)
        self.stride = (stride_y or block_y, stride_x or block_x)
        self.padding = (padding_y, padding_x)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        b, h, w, c = x.shape
        (by, bx), (sy, sx), (py, px) = self.block, self.stride, self.padding
        x = jnp.pad(x, ((0, 0), (py, py), (px, px), (0, 0)))
        # XLA's patch extraction: conv_general_dilated_patches keeps it on MXU-
        # friendly layouts instead of a scalar gather loop
        patches = jax.lax.conv_general_dilated_patches(
            x, filter_shape=(by, bx), window_strides=(sy, sx), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # [B, out_h, out_w, C*by*bx]
        oh, ow = patches.shape[1], patches.shape[2]
        t = oh * ow
        seq = patches.reshape(b, t, patches.shape[-1])
        lengths = jnp.full((b,), t, jnp.int32)
        return Argument(seq, lengths)


@LAYERS.register("row_conv")
class RowConv(Layer):
    """Lookahead row convolution (RowConvLayer.cpp + function/RowConvOp.cpp,
    from DeepSpeech2): y[t] = sum_{i=0..ctx-1} x[t+i] * w[i], per feature —
    a depthwise causal-in-reverse conv done as one lax conv over time."""

    type_name = "row_conv"

    def __init__(self, input: Layer, context_len: int, act: Any = None,
                 param_attr: Any = None, name=None):
        super().__init__(input, name=name)
        self.context_len = context_len
        self.act = act
        self.param_attr = _attr(param_attr)

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        arg = ins[0]
        x = arg.value  # [B, T, D]
        b, t, d = x.shape
        w = ctx.param(self, "w", (self.context_len, d), init_mod.smart_normal,
                      self.param_attr)
        # zero-pad the future edge; mask invalid (padded) timesteps so lookahead
        # never reads beyond a sequence's true length
        if arg.lengths is not None:
            x = x * arg.mask(x.dtype)[..., None]
        xp = jnp.pad(x, ((0, 0), (0, self.context_len - 1), (0, 0)))
        windows = jnp.stack(
            [xp[:, i : i + t, :] for i in range(self.context_len)], axis=0
        )  # [ctx, B, T, D]
        out = jnp.einsum("cbtd,cd->btd", windows, w.astype(x.dtype))
        out = act_mod.apply(self.act, out)
        return arg.with_value(out)


@LAYERS.register("selective_fc")
class SelectiveFc(Layer):
    """SelectiveFullyConnectedLayer.cpp: fc where only a selected subset of
    output columns is computed/valid. TPU-native form: compute the full matmul
    (MXU-friendly dense GEMM) and mask unselected columns to -inf/0 — the
    reference's sparse column GEMM is a bandwidth trick for CPUs that the MXU
    does not need at these sizes."""

    type_name = "selective_fc"

    def __init__(self, input, size: int, act: Any = None, bias: bool = True,
                 param_attr: Any = None, pass_generation: bool = False,
                 has_selected_colums: bool = True, selection_mode: str = "mask",
                 name=None):
        ins = input if isinstance(input, (list, tuple)) else [input]
        super().__init__(list(ins), name=name)
        self.size = size
        self.act = act
        self.bias = bias
        self.param_attr = _attr(param_attr)
        self.has_select = len(self.inputs) > 1

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        x = ins[0].value
        w = ctx.param(self, "w", (x.shape[-1], self.size),
                      init_mod.smart_normal, self.param_attr)
        out = linalg.matmul(x, w, ctx.policy)
        if self.bias:
            bvec = ctx.param(self, "b", (self.size,), init_mod.zeros, None)
            out = out + bvec
        sel = ins[1].value.astype(out.dtype) if self.has_select else None
        act_name = self.act if isinstance(self.act, str) else getattr(self.act, "name", self.act)
        if sel is not None and act_name == "softmax":
            # mask pre-activation so softmax normalizes over selected cols only
            # (SelectiveFullyConnectedLayer computes softmax on the selected set)
            out = jnp.where(sel > 0, out, jnp.asarray(-1e9, out.dtype))
            out = act_mod.apply(self.act, out)
            out = out * sel
        else:
            out = act_mod.apply(self.act, out)
            if sel is not None:
                out = out * sel
        return ins[0].with_value(out)
