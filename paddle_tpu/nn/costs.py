"""Cost (loss) layers.

Parity with paddle/gserver/layers/CostLayer.cpp: multi-class cross-entropy
(+softmax fused, hl_matrix.h softmax+CE kernels), soft binary CE, squared error,
rank cost, lambda cost, huber; plus classification output. Each cost layer
outputs a per-example cost [B] (or [B,1]); the trainer averages/sums — matching
Argument::sum over the cost layer output in TrainerInternal.cpp:66."""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn.graph import Argument, Context, Layer
from paddle_tpu.ops import sequence as seq_ops
from paddle_tpu.ops import xent as xent_ops

Array = jax.Array


def _flatten_seq(value: Array, lengths: Optional[Array]):
    """[B,T,...]+lengths → flat [(B*T), ...] values and [(B*T)] weight mask; or
    pass-through for non-sequence [B, ...]."""
    if lengths is None:
        return value, None
    b, t = value.shape[0], value.shape[1]
    mask = seq_ops.mask_from_lengths(lengths, t).reshape(-1)
    flat = value.reshape((b * t,) + value.shape[2:])
    return flat, mask


def _masked_mean(ctx: Context, cost: Array, batch_rows: int, timesteps=None):
    """Mean over examples honoring Context.sample_mask — the [B] 0/1 row
    validity from a mesh-divisibility-padded batch (graph.SAMPLE_MASK_KEY).
    Padded rows weigh 0 and the denominator is the REAL row count, so the
    padded batch reproduces the unpadded batch's cost (and, through the
    backward, its gradients). Without a mask this is the plain sum/B the
    trainer always used — bitwise-unchanged for unpadded batches."""
    smask = getattr(ctx, "sample_mask", None)
    if smask is None:
        return jnp.sum(cost) / batch_rows
    w = smask.astype(cost.dtype).reshape(-1)
    if timesteps is not None:  # sequence costs flatten to [(B*T)]
        w = jnp.repeat(w, timesteps)
    denom = jnp.maximum(jnp.sum(smask.astype(jnp.float32)), 1.0)
    return jnp.sum(cost * w) / denom


class CostLayer(Layer):
    """Base for costs: handles sequence flattening + per-example weighting."""

    is_cost = True

    def __init__(self, input: Layer, label: Layer, weight: Optional[Layer] = None, name=None, coeff: float = 1.0):
        srcs = [input, label] + ([weight] if weight is not None else [])
        super().__init__(srcs, name=name)
        self.coeff = coeff
        self.has_weight = weight is not None

    def per_example(self, ctx, pred: Array, label: Array) -> Array:
        raise NotImplementedError

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        pred_arg, label_arg = ins[0], ins[1]
        if pred_arg.lengths is not None and label_arg.lengths is None:
            # sequence predictions against one label per sequence: the label
            # applies to every (valid) step, as the reference's provider
            # binding does when a non-seq label slot meets a seq cost input
            t = pred_arg.value.shape[1]
            lv = label_arg.value.reshape(label_arg.value.shape[0], -1)
            label_arg = Argument(
                jnp.broadcast_to(lv[:, :1], (lv.shape[0], t)),
                pred_arg.lengths,
            )
        pred, pmask = _flatten_seq(pred_arg.value, pred_arg.lengths)
        label, _ = _flatten_seq(label_arg.value, label_arg.lengths)
        cost = self.per_example(ctx, pred, label)
        if cost.ndim > 1:
            cost = cost.reshape(cost.shape[0], -1).sum(-1)
        if pmask is not None:
            cost = cost * pmask
        if self.has_weight:
            w = ins[2].value.reshape(-1)
            cost = cost * w
        # mean over examples (sequences count each timestep, like the reference's
        # per-instance sum normalized by batch size in Argument::sum semantics).
        t = pred_arg.value.shape[1] if pred_arg.lengths is not None else None
        total = self.coeff * _masked_mean(
            ctx, cost, pred_arg.value.shape[0], timesteps=t
        )
        return Argument(total)


@LAYERS.register("classification_cost", "multi_class_cross_entropy")
class ClassificationCost(CostLayer):
    """Softmax + multi-class cross-entropy (CostLayer.cpp
    MultiClassCrossEntropy; the v1 helper classification_cost applies softmax
    activation on the input layer — here fused via log_softmax for stability).
    Input: logits or probabilities; set `from_logits=False` if the input layer
    already applied softmax."""

    type_name = "classification_cost"

    def __init__(self, input, label, weight=None, name=None, coeff=1.0, from_logits=True):
        super().__init__(input, label, weight, name, coeff)
        self.from_logits = from_logits

    def per_example(self, ctx, pred, label):
        label = label.astype(jnp.int32).reshape(-1)
        if self.from_logits:
            # fused big-vocab path: all [N, V] tensors stay in pred's dtype,
            # reductions in f32 (ops/xent.py — r3 profile showed the f32
            # log_softmax dominating the NMT step's bandwidth)
            return xent_ops.softmax_xent_with_logits(pred, label)
        logp = jnp.log(jnp.maximum(pred.astype(jnp.float32), 1e-10))
        return -jnp.take_along_axis(logp, label[:, None], axis=-1)[:, 0]


@LAYERS.register("soft_binary_class_cross_entropy")
class SoftBinaryCrossEntropy(CostLayer):
    """Per-dimension binary CE with soft targets (SoftBinaryClassCrossEntropy)."""

    type_name = "soft_binary_class_cross_entropy"

    def per_example(self, ctx, pred, label):
        p = jnp.clip(pred.astype(jnp.float32), 1e-7, 1 - 1e-7)
        y = label.astype(jnp.float32)
        return -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p)).sum(-1)


@LAYERS.register("square_error", "mse_cost", "regression_cost")
class SquareError(CostLayer):
    """Sum-of-squares error (SumOfSquaresCostLayer): 0.5*||pred-label||^2."""

    type_name = "square_error"

    def per_example(self, ctx, pred, label):
        d = pred.astype(jnp.float32) - _dense_label(pred, label)
        return 0.5 * jnp.sum(d * d, axis=-1)


@LAYERS.register("cross_entropy_with_selfnorm")
class CrossEntropyWithSelfNorm(CostLayer):
    """MultiClassCrossEntropyWithSelfNorm: CE + alpha * log(Z)^2 self-norm."""

    type_name = "cross_entropy_with_selfnorm"

    def __init__(self, input, label, weight=None, name=None, coeff=1.0, softmax_selfnorm_alpha=0.1):
        super().__init__(input, label, weight, name, coeff)
        self.alpha = softmax_selfnorm_alpha

    def per_example(self, ctx, pred, label):
        logits = pred.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        logp = logits - logz[:, None]
        label = label.astype(jnp.int32).reshape(-1)
        ce = -jnp.take_along_axis(logp, label[:, None], axis=-1)[:, 0]
        return ce + self.alpha * logz * logz


def _dense_label(pred, label):
    """Regression costs against an id label slot (the provider binds whatever
    the cost consumes; one-hot is the dense view of ids)."""
    if label.ndim == pred.ndim - 1:
        return jax.nn.one_hot(label.astype(jnp.int32), pred.shape[-1])
    return label.astype(jnp.float32)


@LAYERS.register("huber_regression_cost")
class HuberRegression(CostLayer):
    """HuberRegressionLoss (CostLayer.cpp)."""

    type_name = "huber_regression_cost"

    def __init__(self, input, label, weight=None, name=None, coeff=1.0, delta=1.0):
        super().__init__(input, label, weight, name, coeff)
        self.delta = delta

    def per_example(self, ctx, pred, label):
        d = jnp.abs(pred.astype(jnp.float32) - _dense_label(pred, label))
        quad = jnp.minimum(d, self.delta)
        return jnp.sum(0.5 * quad * quad + self.delta * (d - quad), axis=-1)


@LAYERS.register("huber_classification_cost")
class HuberTwoClassification(CostLayer):
    """HuberTwoClassification (labels {0,1} → y∈{-1,1}, squared hinge-ish)."""

    type_name = "huber_classification_cost"

    def per_example(self, ctx, pred, label):
        y = 2.0 * label.astype(jnp.float32).reshape(-1) - 1.0
        z = pred.astype(jnp.float32).reshape(-1) * y
        return jnp.where(z < -1, -4 * z, jnp.where(z < 1, jnp.square(1 - z), 0.0))


@LAYERS.register("rank_cost")
class RankCost(Layer):
    """Pairwise ranking cost (RankingCost, CostLayer.cpp): inputs left/right
    scores + label in [0,1] preference."""

    type_name = "rank_cost"
    is_cost = True

    def __init__(self, left: Layer, right: Layer, label: Layer, weight=None, name=None, coeff=1.0):
        srcs = [left, right, label] + ([weight] if weight is not None else [])
        super().__init__(srcs, name=name)
        self.coeff = coeff
        self.has_weight = weight is not None

    def forward(self, ctx, ins):
        o = (ins[0].value - ins[1].value).astype(jnp.float32).reshape(-1)
        t = ins[2].value.astype(jnp.float32).reshape(-1)
        cost = jax.nn.softplus(o) - t * o  # log(1+e^o) - t*o
        if self.has_weight:
            cost = cost * ins[3].value.reshape(-1)
        return Argument(self.coeff * _masked_mean(ctx, cost, cost.shape[0]))


@LAYERS.register("multi_binary_label_cross_entropy")
class MultiBinaryLabelCrossEntropy(CostLayer):
    """MultiBinaryLabelCrossEntropy: sigmoid CE against multi-hot labels."""

    type_name = "multi_binary_label_cross_entropy"

    def per_example(self, ctx, pred, label):
        x = pred.astype(jnp.float32)
        y = _dense_label(pred, label)
        # stable sigmoid CE on logits
        return jnp.sum(jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x))), axis=-1)


@LAYERS.register("sum_cost")
class SumCost(Layer):
    is_cost = True
    """SumCostLayer: cost = sum of input activations."""

    type_name = "sum_cost"

    def __init__(self, input: Layer, name=None, coeff: float = 1.0):
        super().__init__(input, name=name)
        self.coeff = coeff

    def forward(self, ctx, ins):
        v = ins[0].value
        if getattr(ctx, "sample_mask", None) is None:
            return Argument(self.coeff * jnp.sum(v) / v.shape[0])
        per_row = jnp.sum(v.reshape(v.shape[0], -1), axis=-1)
        return Argument(self.coeff * _masked_mean(ctx, per_row, v.shape[0]))


@LAYERS.register("smooth_l1_cost")
class SmoothL1(CostLayer):
    """SmoothL1CostLayer."""

    type_name = "smooth_l1_cost"

    def per_example(self, ctx, pred, label):
        d = jnp.abs(pred.astype(jnp.float32) - _dense_label(pred, label))
        return jnp.sum(jnp.where(d < 1.0, 0.5 * d * d, d - 0.5), axis=-1)
