"""Activation registry.

Parity with the reference's macro-registered activations
(paddle/gserver/activations/ActivationFunction.cpp:40-63): sigmoid, softmax,
sequence_softmax, relu, brelu, tanh, stanh, softrelu, abs, square, exponential,
log, plus identity/linear. All are pure jnp functions; backward comes from
autodiff (the reference hand-codes each `backward`)."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import ACTIVATIONS

Array = jax.Array


def register(*names: str):
    return ACTIVATIONS.register(*names)


@register("linear", "identity", "")
def linear(x: Array) -> Array:
    return x


@register("sigmoid")
def sigmoid(x: Array) -> Array:
    return jax.nn.sigmoid(x)


@register("softmax")
def softmax(x: Array) -> Array:
    return jax.nn.softmax(x, axis=-1)


@register("relu")
def relu(x: Array) -> Array:
    return jax.nn.relu(x)


@register("brelu")
def brelu(x: Array) -> Array:
    # Bounded relu, clip at 24 like the reference (BReluActivation).
    return jnp.clip(x, 0.0, 24.0)


@register("tanh")
def tanh(x: Array) -> Array:
    return jnp.tanh(x)


@register("stanh")
def stanh(x: Array) -> Array:
    # Scaled tanh: 1.7159 * tanh(2/3 x) (STanhActivation).
    return 1.7159 * jnp.tanh(2.0 / 3.0 * x)


@register("softrelu")
def softrelu(x: Array) -> Array:
    # log(1 + exp(x)), input clipped to +-40 like the reference.
    return jax.nn.softplus(jnp.clip(x, -40.0, 40.0))


@register("abs")
def abs_(x: Array) -> Array:
    return jnp.abs(x)


@register("square")
def square(x: Array) -> Array:
    return jnp.square(x)


@register("exponential", "exp")
def exponential(x: Array) -> Array:
    return jnp.exp(x)


@register("log")
def log(x: Array) -> Array:
    return jnp.log(x)


ActLike = Union[None, str, Callable[[Array], Array]]


def get(act: ActLike) -> Callable[[Array], Array]:
    if act is None:
        return linear
    if callable(act):
        return act
    return ACTIVATIONS.get(act)


def apply(act: ActLike, x: Array) -> Array:
    return get(act)(x)


@register("sqrt")
def sqrt(x: Array) -> Array:
    return jnp.sqrt(x)


@register("reciprocal")
def reciprocal(x: Array) -> Array:
    return 1.0 / x
