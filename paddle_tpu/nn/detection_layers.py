"""SSD detection layers: PriorBox, MultiBoxLoss, DetectionOutput.

Parity with paddle/gserver/layers/{PriorBox,MultiBoxLossLayer,
DetectionOutputLayer}.cpp. The reference's multi-input wiring (N loc conv
outputs + N conf conv outputs + priorbox layers, appendWithPermute) becomes:
each PriorBox binds to its conv feature layer; MultiBoxLoss/DetectionOutput
take lists of (loc, conf, priorbox) triples and concatenate along the prior
axis inside the traced step.

Ground truth feeds as padded tensors: 'gt_boxes' [B, G, 4] (normalized
corners), 'gt_labels' [B, G], 'gt_valid'/lengths mask — replacing the
reference's sequence-encoded label data (getBBoxFromLabelData's
class/xmin/ymin/xmax/ymax/difficult rows)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import LAYERS
from paddle_tpu.nn.graph import Argument, Context, Layer
from paddle_tpu.ops import detection as det_ops

Array = jax.Array


@LAYERS.register("priorbox")
class PriorBox(Layer):
    """Anchor generator bound to a conv feature map (PriorBox.cpp). Output is
    a compile-time-constant [P, 8] array per the reference's layout: 4 box
    coords + 4 variances, broadcast over the batch."""

    type_name = "priorbox"

    def __init__(
        self,
        input: Layer,
        image_size: Tuple[int, int],
        min_size: Sequence[float],
        max_size: Sequence[float] = (),
        aspect_ratio: Sequence[float] = (2.0,),
        variance: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
        clip: bool = True,
        name: Optional[str] = None,
    ):
        super().__init__(input, name=name)
        self.image_size = image_size
        self.min_size = list(min_size)
        self.max_size = list(max_size)
        self.aspect_ratio = list(aspect_ratio)
        self.variance = list(variance)
        self.clip = clip

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        feat = ins[0].value  # [B, H, W, C]
        fh, fw = int(feat.shape[1]), int(feat.shape[2])
        boxes, var = det_ops.prior_boxes(
            (fh, fw),
            self.image_size,
            self.min_size,
            self.max_size,
            self.aspect_ratio,
            self.variance,
            self.clip,
        )
        packed = jnp.asarray(np.concatenate([boxes, var], axis=1))  # [P, 8]
        return Argument(packed)


def _gather_heads(
    ins: List[Argument], n: int
) -> Tuple[Array, Array, Array, Array]:
    """Split inputs [loc..., conf..., prior...] (n each) and concatenate along
    the prior axis. loc heads are conv outputs [B, H, W, 4*K] → [B, P, 4];
    conf heads [B, H, W, C*K] → [B, P, C]."""
    locs, confs, priors, variances = [], [], [], []
    for i in range(n):
        loc = ins[i].value
        b = loc.shape[0]
        locs.append(loc.reshape(b, -1, 4))
    # conf channel count differs; infer per head from prior count
    for i in range(n):
        conf = ins[n + i].value
        b = conf.shape[0]
        p_i = locs[i].shape[1]
        confs.append(conf.reshape(b, p_i, -1))
        packed = ins[2 * n + i].value  # [P_i, 8]
        priors.append(packed[:, :4])
        variances.append(packed[:, 4:])
    return (
        jnp.concatenate(locs, axis=1),
        jnp.concatenate(confs, axis=1),
        jnp.concatenate(priors, axis=0),
        jnp.concatenate(variances, axis=0),
    )


@LAYERS.register("multibox_loss")
class MultiBoxLoss(Layer):
    """SSD training loss (MultiBoxLossLayer.cpp)."""

    type_name = "multibox_loss"

    def __init__(
        self,
        loc_layers: Sequence[Layer],
        conf_layers: Sequence[Layer],
        priorbox_layers: Sequence[Layer],
        gt_boxes: Layer,
        gt_labels: Layer,
        num_classes: int,
        overlap_threshold: float = 0.5,
        neg_pos_ratio: float = 3.0,
        background_id: int = 0,
        name: Optional[str] = None,
    ):
        loc_layers = list(loc_layers)
        conf_layers = list(conf_layers)
        priorbox_layers = list(priorbox_layers)
        assert len(loc_layers) == len(conf_layers) == len(priorbox_layers)
        super().__init__(
            loc_layers + conf_layers + priorbox_layers + [gt_boxes, gt_labels],
            name=name,
        )
        self.n_heads = len(loc_layers)
        self.num_classes = num_classes
        self.overlap_threshold = overlap_threshold
        self.neg_pos_ratio = neg_pos_ratio
        self.background_id = background_id

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        n = self.n_heads
        loc, conf, priors, variances = _gather_heads(ins, n)
        gtb_arg, gtl_arg = ins[3 * n], ins[3 * n + 1]
        gtb = gtb_arg.value  # [B, G, 4]
        gtl = gtl_arg.value.astype(jnp.int32)  # [B, G]
        if gtb_arg.lengths is not None:
            g = gtb.shape[1]
            valid = jnp.arange(g)[None, :] < gtb_arg.lengths[:, None]
        else:
            # a gt row of all zeros is padding
            valid = jnp.any(gtb != 0, axis=-1)
        cost = det_ops.multibox_loss(
            loc,
            conf,
            priors,
            variances,
            gtb,
            gtl,
            valid,
            overlap_threshold=self.overlap_threshold,
            neg_pos_ratio=self.neg_pos_ratio,
            background_id=self.background_id,
        )
        return Argument(jnp.mean(cost))


@LAYERS.register("detection_output")
class DetectionOutput(Layer):
    """Decode + per-class NMS → [B, keep_top_k, 6] (DetectionOutputLayer.cpp;
    row = label, score, xmin, ymin, xmax, ymax; score==0 rows are padding)."""

    type_name = "detection_output"

    def __init__(
        self,
        loc_layers: Sequence[Layer],
        conf_layers: Sequence[Layer],
        priorbox_layers: Sequence[Layer],
        num_classes: int,
        background_id: int = 0,
        nms_threshold: float = 0.45,
        nms_top_k: int = 400,
        keep_top_k: int = 200,
        confidence_threshold: float = 0.01,
        name: Optional[str] = None,
    ):
        loc_layers = list(loc_layers)
        conf_layers = list(conf_layers)
        priorbox_layers = list(priorbox_layers)
        super().__init__(loc_layers + conf_layers + priorbox_layers, name=name)
        self.n_heads = len(loc_layers)
        self.num_classes = num_classes
        self.background_id = background_id
        self.nms_threshold = nms_threshold
        self.nms_top_k = nms_top_k
        self.keep_top_k = keep_top_k
        self.confidence_threshold = confidence_threshold

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        loc, conf, priors, variances = _gather_heads(ins, self.n_heads)
        out = det_ops.detection_output(
            loc,
            conf,
            priors,
            variances,
            num_classes=self.num_classes,
            background_id=self.background_id,
            nms_threshold=self.nms_threshold,
            nms_top_k=self.nms_top_k,
            keep_top_k=self.keep_top_k,
            confidence_threshold=self.confidence_threshold,
        )
        return Argument(out)


@LAYERS.register("multibox_loss_v1")
class MultiBoxLossV1(Layer):
    """The v1 config-surface MultiBoxLoss (multibox_loss_layer): inputs in
    the reference slot order [priorbox, label, loc..., conf...] with the
    PACKED v1 encodings — priorbox rows of 8 (4 coords + 4 variances),
    label rows of 6 (class, x1, y1, x2, y2, difficult) — unpacked here and
    routed through the same det_ops.multibox_loss as the v2 layer."""

    type_name = "multibox_loss"

    def __init__(
        self,
        input_loc: Sequence[Layer],
        input_conf: Sequence[Layer],
        priorbox: Layer,
        label: Layer,
        num_classes: int,
        overlap_threshold: float = 0.5,
        neg_pos_ratio: float = 3.0,
        neg_overlap: float = 0.5,
        background_id: int = 0,
        name: Optional[str] = None,
    ):
        locs, confs = list(input_loc), list(input_conf)
        super().__init__([priorbox, label] + locs + confs, name=name)
        self.n_heads = len(locs)
        self.num_classes = num_classes
        self.overlap_threshold = overlap_threshold
        self.neg_pos_ratio = neg_pos_ratio
        self.neg_overlap = neg_overlap
        self.background_id = background_id

    def _unpack(self, ins):
        n = self.n_heads
        packed = ins[0].value.reshape(ins[0].value.shape[0], -1, 8)[0]
        priors, variances = packed[:, :4], packed[:, 4:]
        lab = ins[1].value
        lab = lab.reshape(lab.shape[0], -1, 6)
        gtl = lab[:, :, 0].astype(jnp.int32)
        gtb = lab[:, :, 1:5]
        valid = jnp.any(lab != 0, axis=-1)
        locs = [
            ins[2 + i].value.reshape(ins[2 + i].value.shape[0], -1, 4)
            for i in range(n)
        ]
        p_total = sum(l.shape[1] for l in locs)
        confs = []
        for i in range(n):
            c = ins[2 + n + i].value
            confs.append(c.reshape(c.shape[0], locs[i].shape[1], -1))
        loc = jnp.concatenate(locs, axis=1)
        conf = jnp.concatenate(confs, axis=1)
        # the parse-level conf width may be anything; clamp/pad to num_classes
        if conf.shape[-1] < self.num_classes:
            conf = jnp.pad(
                conf, ((0, 0), (0, 0), (0, self.num_classes - conf.shape[-1]))
            )
        priors = priors[:p_total]
        variances = variances[:p_total]
        return loc, conf, priors, variances, gtb, gtl, valid

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        loc, conf, priors, variances, gtb, gtl, valid = self._unpack(ins)
        cost = det_ops.multibox_loss(
            loc, conf, priors, variances, gtb, gtl, valid,
            overlap_threshold=self.overlap_threshold,
            neg_pos_ratio=self.neg_pos_ratio,
            background_id=self.background_id,
        )
        return Argument(jnp.mean(cost))


@LAYERS.register("detection_output_v1")
class DetectionOutputV1(Layer):
    """v1 config-surface DetectionOutput: [priorbox, loc..., conf...] packed
    slots; output rows are 7 wide (image_id + label, score, box) like
    DetectionOutputLayer.cpp's getDetectionOutput."""

    type_name = "detection_output"

    def __init__(
        self,
        input_loc: Sequence[Layer],
        input_conf: Sequence[Layer],
        priorbox: Layer,
        num_classes: int,
        nms_threshold: float = 0.45,
        nms_top_k: int = 400,
        keep_top_k: int = 200,
        confidence_threshold: float = 0.01,
        background_id: int = 0,
        name: Optional[str] = None,
    ):
        locs, confs = list(input_loc), list(input_conf)
        super().__init__([priorbox] + locs + confs, name=name)
        self.n_heads = len(locs)
        self.num_classes = num_classes
        self.nms_threshold = nms_threshold
        self.nms_top_k = nms_top_k
        self.keep_top_k = keep_top_k
        self.confidence_threshold = confidence_threshold
        self.background_id = background_id

    def forward(self, ctx: Context, ins: List[Argument]) -> Argument:
        n = self.n_heads
        packed = ins[0].value.reshape(ins[0].value.shape[0], -1, 8)[0]
        locs = [
            ins[1 + i].value.reshape(ins[1 + i].value.shape[0], -1, 4)
            for i in range(n)
        ]
        p_total = sum(l.shape[1] for l in locs)
        confs = []
        for i in range(n):
            c = ins[1 + n + i].value
            confs.append(c.reshape(c.shape[0], locs[i].shape[1], -1))
        loc = jnp.concatenate(locs, axis=1)
        conf = jnp.concatenate(confs, axis=1)
        if conf.shape[-1] < self.num_classes:
            conf = jnp.pad(
                conf, ((0, 0), (0, 0), (0, self.num_classes - conf.shape[-1]))
            )
        out = det_ops.detection_output(
            loc, conf, packed[:p_total, :4], packed[:p_total, 4:],
            num_classes=self.num_classes,
            background_id=self.background_id,
            nms_threshold=self.nms_threshold,
            nms_top_k=self.nms_top_k,
            keep_top_k=self.keep_top_k,
            confidence_threshold=self.confidence_threshold,
        )  # [B, keep_top_k, 6]
        b = out.shape[0]
        img_id = jnp.broadcast_to(
            jnp.arange(b, dtype=out.dtype)[:, None, None],
            (b, out.shape[1], 1),
        )
        return Argument(jnp.concatenate([img_id, out], axis=-1))
