"""trainer_config_helpers surface — the v1 config-script DSL names.

Parity with python/paddle/trainer_config_helpers/{layers.py, activations.py,
poolings.py, attrs.py, evaluators.py, data_sources.py} (SURVEY §2.4): the
classic `*_layer` constructors, activation/pooling tag classes, ParamAttr,
evaluator declarations and `settings()`. Every constructor is the same graph
node the v2 API builds (paddle_tpu.v2.layer), so v1 config scripts and v2
programs produce identical networks.
"""

from __future__ import annotations

from typing import Any, Optional

from paddle_tpu import proto
from paddle_tpu.data import feeder as _feeder
from paddle_tpu.v2 import layer as _v2
from paddle_tpu.v2 import networks as _nets
from paddle_tpu.v2.activation import (
    Abs as AbsActivation,
    BRelu as BReluActivation,
    Exp as ExpActivation,
    Linear as LinearActivation,
    Log as LogActivation,
    Relu as ReluActivation,
    SequenceSoftmax as SequenceSoftmaxActivation,
    Sigmoid as SigmoidActivation,
    Softmax as SoftmaxActivation,
    SoftRelu as SoftReluActivation,
    Square as SquareActivation,
    STanh as STanhActivation,
    Tanh as TanhActivation,
)
from paddle_tpu.v2.attr import ExtraAttr as ExtraLayerAttribute
from paddle_tpu.v2.attr import Param as ParamAttr
from paddle_tpu.v2.pooling import Avg as AvgPooling
from paddle_tpu.v2.pooling import Max as MaxPooling
from paddle_tpu.v2.pooling import SquareRootN as SquareRootNPooling
from paddle_tpu.v2.pooling import Sum as SumPooling
from paddle_tpu.v2.pooling import CudnnAvg as CudnnAvgPooling
from paddle_tpu.v2.pooling import CudnnMax as CudnnMaxPooling
from paddle_tpu.config.optimizers import (
    AdaDeltaOptimizer,
    AdaGradOptimizer,
    AdamaxOptimizer,
    AdamOptimizer,
    DecayedAdaGradOptimizer,
    GradientClippingThreshold,
    L1Regularization,
    L2Regularization,
    ModelAverage,
    MomentumOptimizer,
    RmsPropOptimizer,
    settings,
)

from paddle_tpu.v2.activation import (  # noqa: E402
    Identity as IdentityActivation,
    Reciprocal as ReciprocalActivation,
    Sqrt as SqrtActivation,
)

ParameterAttribute = ParamAttr
ExtraAttr = ExtraLayerAttribute


class AggregateLevel:
    """layers.py:275 — pooling/aggregation level over (nested) sequences."""

    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel:
    """layers.py:1762 — expansion source level."""

    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    FROM_TIMESTEP = FROM_NO_SEQUENCE

# -- input types (PyDataProvider2.py:63-236) --------------------------------
dense_vector = _feeder.dense_vector
dense_array = _feeder.dense_array
integer_value = _feeder.integer_value
dense_vector_sequence = _feeder.dense_vector_sequence
integer_value_sequence = _feeder.integer_value_sequence
sparse_binary_vector = _feeder.sparse_binary_vector
sparse_value_slot = _feeder.sparse_value_slot

# -- layers (trainer_config_helpers/layers.py ~100 wrappers) ----------------
addto_layer = _v2.addto
seq_concat_layer = _v2.seq_concat
lstmemory = _v2.lstmemory
grumemory = _v2.grumemory
recurrent_layer = _v2.recurrent
gated_unit_layer = _v2.gated_unit
last_seq = _v2.last_seq
first_seq = _v2.first_seq
expand_layer = _v2.expand
repeat_layer = _v2.repeat
resize_layer = _v2.resize
seq_reshape_layer = _v2.seq_reshape
seq_slice_layer = _v2.seq_slice
kmax_sequence_score_layer = _v2.kmax_seq_score
sub_seq_layer = _v2.sub_seq
cos_sim = _v2.cos_sim
trans_layer = _v2.trans
scaling_layer = _v2.scaling
slope_intercept_layer = _v2.slope_intercept
interpolation_layer = _v2.interpolation
power_layer = _v2.power
dot_prod_layer = _v2.dot_prod
out_prod_layer = _v2.out_prod
conv_shift_layer = _v2.conv_shift
tensor_layer = _v2.tensor
multiplex_layer = _v2.multiplex
sampling_id_layer = _v2.sampling_id
eos_layer = _v2.eos
print_layer = _v2.print_layer
clip_layer = _v2.clip
scale_shift_layer = _v2.scale_shift
prelu_layer = _v2.prelu
maxout_layer = _v2.maxout
spp_layer = _v2.spp
sum_to_one_norm_layer = _v2.sum_to_one_norm
row_l2_norm_layer = _v2.row_l2_norm
cross_channel_norm_layer = _v2.cross_channel_norm
data_norm_layer = _v2.data_norm
bilinear_interp_layer = _v2.bilinear_interp
pad_layer = _v2.pad
crop_layer = _v2.crop
rotate_layer = _v2.rotate
switch_order_layer = _v2.switch_order
block_expand_layer = _v2.block_expand
row_conv_layer = _v2.row_conv
selective_fc_layer = _v2.selective_fc
img_conv3d_layer = _v2.img_conv3d
img_pool3d_layer = _v2.img_pool3d
linear_comb_layer = _v2.linear_comb
convex_comb_layer = _v2.convex_comb
sub_nested_seq_layer = _v2.sub_nested_seq
cross_entropy_over_beam = _v2.cross_entropy_over_beam
BeamInput = _v2.BeamInput

# mixed layer + projections/operators
mixed_layer = _v2.mixed
full_matrix_projection = _v2.full_matrix_projection
trans_full_matrix_projection = _v2.trans_full_matrix_projection
identity_projection = _v2.identity_projection
dotmul_projection = _v2.dotmul_projection
table_projection = _v2.table_projection
context_projection = _v2.context_projection
scaling_projection = _v2.scaling_projection
slice_projection = _v2.slice_projection
dotmul_operator = _v2.dotmul_operator

# costs
cross_entropy_with_selfnorm = _v2.cross_entropy_with_selfnorm_cost
multi_binary_label_cross_entropy = _v2.multi_binary_label_cross_entropy_cost
soft_binary_class_cross_entropy = _v2.soft_binary_class_cross_entropy
square_error_cost = _v2.square_error_cost
regression_cost = _v2.square_error_cost
mse_cost = _v2.square_error_cost
huber_regression_cost = _v2.huber_regression_cost
huber_classification_cost = _v2.huber_classification_cost
smooth_l1_cost = _v2.smooth_l1_cost
rank_cost = _v2.rank_cost
lambda_cost = _v2.lambda_cost
sum_cost = _v2.sum_cost
crf_layer = _v2.crf
crf_decoding_layer = _v2.crf_decoding
ctc_layer = _v2.ctc
warp_ctc_layer = _v2.warp_ctc
nce_layer = _v2.nce
hsigmoid = _v2.hsigmoid

# detection
priorbox_layer = _v2.priorbox
multibox_loss_layer = _v2.multibox_loss
detection_output_layer = _v2.detection_output

# recurrent groups (nn/recurrent_group): the v1 dynamic-unroll API
from paddle_tpu.v2.layer import (  # noqa: E402
    GeneratedInput,
    recurrent_group,
    memory,
    StaticInput,
    SubsequenceInput,
    SubSequenceInput,
    beam_search,
    get_output_layer,
)

# prebuilt networks (trainer_config_helpers/networks.py)
vgg_16_network = _nets.vgg_16_network
simple_attention = _nets.simple_attention

# -- reference-faithful v1 signatures override the bare v2 aliases ----------
# (paddle_tpu.config.v1_layers matches layers.py/networks.py signatures so
# unmodified reference config scripts run; see that module's docstring)
from paddle_tpu.config.v1_layers import (  # noqa: E402
    batch_norm_layer,
    bidirectional_gru,
    bilinear_interp_layer,
    block_expand_layer,
    bidirectional_lstm,
    classification_cost,
    concat_layer,
    conv_operator,
    conv_projection,
    crf_decoding_layer,
    crf_layer,
    cross_entropy,
    ctc_layer,
    data_layer,
    detection_output_layer,
    dropout_layer,
    embedding_layer,
    expand_layer,
    fc_layer,
    first_seq,
    gated_unit_layer,
    get_output_layer,
    gru_group,
    gru_step_layer,
    gru_step_naive_layer,
    gru_unit,
    img_cmrnorm_layer,
    img_conv3d_layer,
    img_conv_group,
    img_conv_layer,
    hsigmoid,
    img_pool3d_layer,
    img_pool_layer,
    kmax_sequence_score_layer,
    lambda_cost,
    last_seq,
    lstm_step_layer,
    lstmemory,
    lstmemory_group,
    lstmemory_unit,
    grumemory,
    maxid_layer,
    maxout_layer,
    multibox_loss_layer,
    nce_layer,
    pooling_layer,
    recurrent_group,
    recurrent_layer,
    row_conv_layer,
    spp_layer,
    seq_concat_layer,
    seq_reshape_layer,
    seq_slice_layer,
    sequence_conv_pool,
    simple_gru,
    simple_gru2,
    simple_img_conv_pool,
    simple_lstm,
    sub_nested_seq_layer,
    text_conv_pool,
    warp_ctc_layer,
)


# -- evaluator declarations (trainer_config_helpers/evaluators.py) ----------


def _declare_evaluator(etype: str, *input_layers, name: Optional[str] = None, **kw):
    from paddle_tpu.config import config_parser as cp

    if name is None:
        # config_parser names evaluators "{type}_evaluator" (uniquified)
        base = f"{etype}_evaluator"
        taken = {e.name for e in cp.g_context().evaluators}
        name = base
        i = 0
        while name in taken:
            i += 1
            name = f"{base}_{i}"
    cfg = proto.EvaluatorConfig(
        name=name,
        type=etype,
        input_layers=[l.name for l in input_layers if l is not None],
    )
    for k, v in kw.items():  # EvaluatorConfig fields (chunk_scheme, top_k, ...)
        if hasattr(cfg, k) and v is not None:
            setattr(cfg, k, v)
    cp.g_context().evaluators.append(cfg)
    return cfg


def classification_error_evaluator(input=None, label=None, weight=None,
                                   name=None, **kw):
    return _declare_evaluator("classification_error", input, label, weight,
                              name=name, **kw)


def auc_evaluator(input=None, label=None, name=None, **kw):
    return _declare_evaluator("auc", input, label, name=name, **kw)


def precision_recall_evaluator(input=None, label=None, name=None, **kw):
    return _declare_evaluator("precision_recall", input, label, name=name, **kw)


def pnpair_evaluator(input=None, label=None, query_id=None, name=None, **kw):
    return _declare_evaluator("pnpair", input, label, query_id, name=name, **kw)


def sum_evaluator(input=None, name=None, **kw):
    return _declare_evaluator("sum", input, name=name, **kw)


def column_sum_evaluator(input=None, name=None, **kw):
    return _declare_evaluator("column_sum", input, name=name, **kw)


def chunk_evaluator(input=None, label=None, chunk_scheme="IOB",
                    num_chunk_types=0, name=None, excluded_chunk_types=None,
                    **kw):
    return _declare_evaluator(
        "chunk", input, label, name=name, chunk_scheme=chunk_scheme,
        num_chunk_types=num_chunk_types or 1,
        excluded_chunk_types=excluded_chunk_types or [],
    )


def seqtext_printer_evaluator(input=None, result_file=None, id_input=None,
                              dict_file=None, delimited=None, name=None, **kw):
    """evaluators.py seqtext_printer_evaluator: dump generated sequences to
    result_file (SequenceTextPrinter) — consumed by the generation CLI."""
    return _declare_evaluator(
        "seq_text_printer", input, id_input, name=name,
        result_file=result_file or "", dict_file=dict_file or "",
        delimited=bool(delimited) if delimited is not None else True, **kw)


def value_printer_evaluator(input=None, name=None, **kw):
    """utils evaluator (Evaluator.h ValuePrinter): print layer outputs."""
    ins = input if isinstance(input, (list, tuple)) else [input]
    return _declare_evaluator("value_printer", *ins, name=name, **kw)


def gradient_printer_evaluator(input=None, name=None, **kw):
    """GradientPrinter: per-layer gradients are not materialized outside the
    compiled step here, so this prints the layer's forward value with a note
    (declared for config compatibility)."""
    return _declare_evaluator("gradient_printer", input, name=name, **kw)


def maxid_printer_evaluator(input=None, num_results=1, name=None, **kw):
    return _declare_evaluator("max_id_printer", input, name=name,
                              num_results=num_results, **kw)


def classification_error_printer_evaluator(input=None, label=None, name=None, **kw):
    return _declare_evaluator("classification_error_printer", input, label,
                              name=name, **kw)


def ctc_error_evaluator(input=None, label=None, name=None, **kw):
    return _declare_evaluator("ctc_edit_distance", input, label, name=name, **kw)


def detection_map_evaluator(input=None, label=None, name=None, **kw):
    return _declare_evaluator("detection_map", input, label, name=name, **kw)


# -- reference default naming (default_decorators.py wrap_name_default) ----
# The reference auto-names every helper's layer "__{prefix}_{n}__" with a
# per-helper counter (prefix = the decorator argument, else the helper's own
# __name__). The golden protostrs encode those names, so the DSL surface
# wraps each helper to inject the same default; counters live in the graph's
# name scope and reset with reset_name_scope().
_REF_NAME_PREFIX = {
    # explicit wrap_name_default("...") prefixes in layers.py / networks.py
    "mixed_layer": "mixed", "embedding_layer": "embedding",
    "print_layer": "print", "printer_layer": "print",
    "priorbox_layer": "priorbox", "multibox_loss_layer": "multibox_loss",
    "detection_output_layer": "detection_output",
    "cross_channel_norm_layer": "cross_channel_norm",
    "pooling_layer": "seq_pooling", "lstmemory": "lstmemory",
    "grumemory": "gru", "seq_reshape_layer": "seqreshape",
    "img_conv_layer": "conv", "img_pool_layer": "pool",
    "img_pool3d_layer": "pool3d", "spp_layer": "spp",
    "img_cmrnorm_layer": "crmnorm", "batch_norm_layer": "batch_norm",
    "addto_layer": "addto", "concat_layer": "concat",
    "seq_concat_layer": "seqconcat", "lstm_step_layer": "lstm_step",
    "gru_step_layer": "gru_step", "gru_step_naive_layer": "gru_step_naive",
    "recurrent_group": "recurrent_group", "dropout_layer": "dropout",
    "switch_order_layer": "switch_order", "clip_layer": "clip",
    "scale_shift_layer": "scale_shift", "resize_layer": "resize",
    "pad_layer": "pad", "classification_cost": "cost",
    "kmax_sequence_score_layer": "kmax_seq_score_layer",
    # networks.py composites
    "sequence_conv_pool": "sequence_conv_pooling",
    "simple_img_conv_pool": "conv_pool", "img_conv_bn_pool": "conv_bn_pool",
    "simple_lstm": "lstm", "lstmemory_unit": "lstm_unit",
    "lstmemory_group": "lstm_group", "gru_unit": "gru_unit",
    "gru_group": "gru_group", "simple_gru": "simple_gru",
    "simple_gru2": "simple_gru2", "bidirectional_gru": "bidirectional_gru",
    "bidirectional_lstm": "bidirectional_lstm",
}

# helpers auto-named by their own __name__ (wrap_name_default() bare)
_REF_NAMED_HELPERS = [
    "fc_layer", "selective_fc_layer", "last_seq", "first_seq", "expand_layer",
    "repeat_layer", "interpolation_layer", "bilinear_interp_layer",
    "power_layer", "scaling_layer", "trans_layer", "rotate_layer", "cos_sim",
    "hsigmoid", "sum_to_one_norm_layer", "row_l2_norm_layer",
    "get_output_layer", "recurrent_layer", "maxid_layer", "out_prod_layer",
    "eos_layer", "beam_search", "square_error_cost", "conv_shift_layer",
    "sampling_id_layer", "slope_intercept_layer", "linear_comb_layer",
    "block_expand_layer", "maxout_layer", "ctc_layer", "warp_ctc_layer",
    "crf_layer", "crf_decoding_layer", "nce_layer", "rank_cost",
    "lambda_cost", "cross_entropy", "cross_entropy_with_selfnorm",
    "sum_cost", "huber_regression_cost", "huber_classification_cost",
    "multi_binary_label_cross_entropy", "cross_entropy_over_beam",
    "smooth_l1_cost", "multiplex_layer", "prelu_layer", "crop_layer",
    "sub_nested_seq_layer", "seq_slice_layer", "gated_unit_layer",
    "dot_prod_layer", "tensor_layer", "convex_comb_layer", "row_conv_layer",
    "img_conv3d_layer", "data_norm_layer",
]


def _with_ref_default_name(fn, prefix):
    import functools

    from paddle_tpu.nn.graph import _auto_name

    @functools.wraps(fn)
    def named(*args, **kw):
        if kw.get("name") is None:
            kw["name"] = _auto_name(prefix)
        return fn(*args, **kw)

    return named


def _install_ref_naming():
    g = globals()
    table = dict(_REF_NAME_PREFIX)
    table.update({h: h for h in _REF_NAMED_HELPERS})
    for helper, prefix in table.items():
        fn = g.get(helper)
        if callable(fn):
            g[helper] = _with_ref_default_name(fn, prefix)


_install_ref_naming()

printer_layer = print_layer  # both spellings exist across reference versions
kmax_seq_score_layer = kmax_sequence_score_layer

# layer_math must import after the wrapped helpers exist (it resolves them
# lazily, but importing it installs the Layer arithmetic operators)
from paddle_tpu.config import layer_math  # noqa: E402

__all__ = [
    "printer_layer", "kmax_seq_score_layer", "layer_math",
    "slice_projection", "CudnnMaxPooling", "CudnnAvgPooling",
    "GeneratedInput",
    "lstmemory_group", "lstmemory_unit", "gru_group", "gru_unit",
    "lstm_step_layer", "gru_step_layer", "gru_step_naive_layer",
    "simple_gru2", "gated_unit_layer", "seq_slice_layer",
    "sub_nested_seq_layer", "seq_reshape_layer",
    "AggregateLevel", "ExpandLevel", "IdentityActivation",
    "SqrtActivation", "ReciprocalActivation",
    # attrs / activations / poolings
    "ParamAttr", "ParameterAttribute", "ExtraLayerAttribute", "ExtraAttr",
    "LinearActivation", "SigmoidActivation", "SoftmaxActivation",
    "SequenceSoftmaxActivation", "ReluActivation", "BReluActivation",
    "TanhActivation", "STanhActivation", "SoftReluActivation", "AbsActivation",
    "SquareActivation", "ExpActivation", "LogActivation",
    "MaxPooling", "AvgPooling", "SumPooling", "SquareRootNPooling",
    # input types
    "dense_vector", "dense_array", "integer_value", "dense_vector_sequence",
    "integer_value_sequence", "sparse_binary_vector", "sparse_value_slot",
    # optimizers / settings
    "settings", "MomentumOptimizer", "AdamOptimizer", "AdamaxOptimizer",
    "AdaGradOptimizer", "DecayedAdaGradOptimizer", "AdaDeltaOptimizer",
    "RmsPropOptimizer", "L1Regularization", "L2Regularization", "ModelAverage",
    "GradientClippingThreshold",
    # layers
    "data_layer", "fc_layer", "embedding_layer", "img_conv_layer",
    "img_pool_layer", "batch_norm_layer", "dropout_layer", "addto_layer",
    "concat_layer", "seq_concat_layer", "lstmemory", "grumemory",
    "recurrent_layer", "gated_unit_layer", "pooling_layer", "last_seq",
    "first_seq", "expand_layer", "repeat_layer", "resize_layer",
    "seq_reshape_layer", "seq_slice_layer", "kmax_sequence_score_layer",
    "sub_seq_layer", "cos_sim", "trans_layer", "scaling_layer",
    "slope_intercept_layer", "interpolation_layer", "power_layer",
    "dot_prod_layer", "out_prod_layer", "conv_shift_layer", "tensor_layer",
    "multiplex_layer", "maxid_layer", "sampling_id_layer", "eos_layer",
    "print_layer", "clip_layer", "scale_shift_layer", "prelu_layer",
    "maxout_layer", "spp_layer", "img_cmrnorm_layer", "sum_to_one_norm_layer",
    "row_l2_norm_layer", "cross_channel_norm_layer", "data_norm_layer",
    "bilinear_interp_layer", "pad_layer", "crop_layer", "rotate_layer",
    "switch_order_layer", "block_expand_layer", "row_conv_layer",
    "selective_fc_layer", "bidirectional_lstm", "bidirectional_gru",
    "simple_lstm", "simple_gru", "img_conv3d_layer", "img_pool3d_layer",
    "linear_comb_layer", "convex_comb_layer", "sub_nested_seq_layer",
    "cross_entropy_over_beam", "BeamInput",
    # mixed
    "mixed_layer", "full_matrix_projection", "trans_full_matrix_projection",
    "identity_projection", "dotmul_projection", "table_projection",
    "context_projection", "scaling_projection", "dotmul_operator",
    # costs
    "classification_cost", "cross_entropy", "cross_entropy_with_selfnorm",
    "multi_binary_label_cross_entropy", "soft_binary_class_cross_entropy",
    "square_error_cost", "regression_cost", "mse_cost",
    "huber_regression_cost", "huber_classification_cost", "smooth_l1_cost",
    "rank_cost", "lambda_cost", "sum_cost", "crf_layer", "crf_decoding_layer",
    "ctc_layer", "warp_ctc_layer", "nce_layer", "hsigmoid",
    # detection
    "priorbox_layer", "multibox_loss_layer", "detection_output_layer",
    # recurrent groups
    "recurrent_group", "memory", "StaticInput", "SubsequenceInput",
    "SubSequenceInput", "beam_search",
    "get_output_layer",
    # networks
    "simple_img_conv_pool", "img_conv_group", "vgg_16_network",
    "text_conv_pool", "simple_attention", "sequence_conv_pool",
    "conv_projection", "conv_operator",
    # evaluators
    "seqtext_printer_evaluator", "classification_error_evaluator", "auc_evaluator",
    "precision_recall_evaluator", "pnpair_evaluator", "sum_evaluator",
    "column_sum_evaluator", "chunk_evaluator", "ctc_error_evaluator",
    "detection_map_evaluator",
]
