"""config_parser: execute a v1 Python config script → TrainerConfig.

Parity with python/paddle/trainer/config_parser.py:4208 `parse_config` (the
function the reference's C++ trainer calls through embedded Python,
paddle/trainer/TrainerConfigHelper.cpp:34-56). The DSL names injected into the
script's namespace are the trainer_config_helpers surface
(paddle_tpu.config.helpers); layer calls build real graph nodes, so the
"compile" step is just tracing the finished graph (dump.build_model_config)
rather than a second shape-inference implementation.

`parse_config_and_serialize` keeps the reference entry-point name for
embedding parity.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from paddle_tpu import proto
from paddle_tpu.nn.graph import Layer, reset_name_scope
from paddle_tpu.v2.topology import Topology


# ---------------------------------------------------------------------------
# parsing context (the reference's g_config global, config_parser.py)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ParseContext:
    outputs: List[Layer] = dataclasses.field(default_factory=list)
    inputs: List[Layer] = dataclasses.field(default_factory=list)
    pending_output_names: List[str] = dataclasses.field(default_factory=list)
    pending_input_names: List[str] = dataclasses.field(default_factory=list)
    model_type: str = "nn"
    opt_config: Optional[proto.OptimizationConfig] = None
    data_config: Optional[proto.DataConfig] = None
    test_data_config: Optional[proto.DataConfig] = None
    config_args: Dict[str, str] = dataclasses.field(default_factory=dict)
    evaluators: List[proto.EvaluatorConfig] = dataclasses.field(default_factory=list)


_tls = threading.local()


def g_context() -> ParseContext:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        ctx = ParseContext()
        _tls.ctx = ctx
    return ctx


@contextlib.contextmanager
def fresh_context(config_args: Optional[Dict[str, str]] = None):
    old = getattr(_tls, "ctx", None)
    _tls.ctx = ParseContext(config_args=dict(config_args or {}))
    try:
        yield _tls.ctx
    finally:
        _tls.ctx = old


# ---------------------------------------------------------------------------
# DSL functions available inside config scripts
# ---------------------------------------------------------------------------


def outputs(*layers: Union[Layer, Sequence[Layer]]) -> None:
    """Declare network outputs (config_parser outputs())."""
    flat: List[Layer] = []
    for l in layers:
        if isinstance(l, Layer):
            flat.append(l)
        else:
            flat.extend(l)
    g_context().outputs.extend(flat)


def Outputs(*names: str) -> None:
    """Legacy raw-config output declaration by layer NAME
    (config_parser.py Outputs) — resolved after the script runs."""
    g_context().pending_output_names.extend(names)


def Inputs(*names: str) -> None:
    """Legacy raw-config input declaration by name (config_parser.py
    Inputs); input slots are derived from the data layers here, so this
    records intent only."""
    g_context().pending_input_names.extend(names)


def TrainData(spec, async_load_data: bool = False) -> None:
    """Legacy TrainData(ProtoData(...)/SimpleData(...)/PyData(...))."""
    if isinstance(spec, proto.DataConfig):
        spec.async_load_data = bool(async_load_data)
        g_context().data_config = spec


def TestData(spec, async_load_data: bool = False) -> None:
    if isinstance(spec, proto.DataConfig):
        g_context().test_data_config = spec


def ProtoData(files: str = "", type: str = "proto", **kw) -> proto.DataConfig:  # noqa: A002
    return proto.DataConfig(type=type, files=files)


def SimpleData(files: str = "", feat_dim: int = 0, **kw) -> proto.DataConfig:
    return proto.DataConfig(type="simple", files=files)


def PyData(files: str = "", load_data_module=None, load_data_object=None,
           load_data_args: str = "", **kw) -> proto.DataConfig:
    return proto.DataConfig(
        type="py", files=files, load_data_module=load_data_module,
        load_data_object=load_data_object, load_data_args=load_data_args,
    )


def Settings(**kw) -> None:
    """Legacy Settings(...) — maps onto the helpers' settings() keys where
    they exist."""
    from paddle_tpu.config.optimizers import settings as _settings

    known = {}
    for k in ("batch_size", "learning_rate", "learning_method",
              "learning_rate_decay_a", "learning_rate_decay_b",
              "learning_rate_schedule", "l2_weight", "l1_weight",
              "average_window", "max_average_window"):
        if k in kw:
            known[k] = kw[k]
    if known:
        try:
            _settings(**known)
        except TypeError:
            pass


def model_type(name: str) -> None:
    """Legacy model_type('recurrent_nn'/'nn') declaration."""
    g_context().model_type = str(name)


def default_initial_std(v: float) -> None:
    """Legacy global param-init default (config_parser.py) — consumed by
    Context.param when a parameter has no explicit initial_std."""
    from paddle_tpu.nn import graph as _g

    _g._param_default["initial_std"] = float(v)


def default_initial_mean(v: float) -> None:
    from paddle_tpu.nn import graph as _g

    _g._param_default["initial_mean"] = float(v)


def default_decay_rate(v: float) -> None:
    g_context().config_args.setdefault("_default_decay_rate", str(v))


def default_device(v: int) -> None:  # device placement is a sharding concern
    return None


def default_num_batches_regularization(v: int) -> None:
    return None


def inputs(*layers: Layer) -> None:
    g_context().inputs.extend(layers)


def get_config_arg(name: str, type_: type = str, default: Any = None) -> Any:
    """Read a --config_args=k=v,... argument (config_parser get_config_arg)."""
    raw = g_context().config_args.get(name)
    if raw is None:
        return default
    if type_ is bool:
        return str(raw).lower() in ("1", "true", "yes", "on")
    return type_(raw)


def define_py_data_sources2(
    train_list: Optional[str],
    test_list: Optional[str],
    module: Union[str, Sequence[str]],
    obj: Union[str, Sequence[str]],
    args: Optional[Any] = None,
) -> None:
    """Declare the @provider-based data sources
    (trainer_config_helpers/data_sources.py define_py_data_sources2)."""
    import json

    ctx = g_context()

    def mk(file_list, which) -> Optional[proto.DataConfig]:
        if file_list is None:
            return None
        mod = module[which] if isinstance(module, (list, tuple)) else module
        ob = obj[which] if isinstance(obj, (list, tuple)) else obj
        a = args[which] if isinstance(args, (list, tuple)) else args
        return proto.DataConfig(
            type="py2",
            files=file_list,
            load_data_module=mod,
            load_data_object=ob,
            load_data_args=json.dumps(a) if a is not None else "",
        )

    ctx.data_config = mk(train_list, 0)
    ctx.test_data_config = mk(test_list, 1)


# ---------------------------------------------------------------------------
# parse_config
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ParsedConfig:
    trainer_config: proto.TrainerConfig
    topology: Topology
    outputs: List[Layer]
    context: ParseContext

    @property
    def model_config(self) -> proto.ModelConfig:
        return self.trainer_config.model_config


def _dsl_namespace() -> Dict[str, Any]:
    import paddle_tpu.config.helpers as helpers

    ns: Dict[str, Any] = {}
    for name in helpers.__all__:
        ns[name] = getattr(helpers, name)
    ns.update(
        outputs=outputs,
        inputs=inputs,
        get_config_arg=get_config_arg,
        define_py_data_sources2=define_py_data_sources2,
        # legacy raw-config primitives (config_parser.py)
        Inputs=Inputs, Outputs=Outputs, TrainData=TrainData, TestData=TestData,
        ProtoData=ProtoData, SimpleData=SimpleData, PyData=PyData,
        Settings=Settings, model_type=model_type,
        xrange=range, unicode=str,  # the reference's configs are python-2 era
        default_initial_std=default_initial_std,
        default_initial_mean=default_initial_mean,
        default_decay_rate=default_decay_rate, default_device=default_device,
        default_num_batches_regularization=default_num_batches_regularization,
    )
    # the rawest Layer()/Memory()/RecurrentLayerGroupBegin name-registry DSL
    from paddle_tpu.config.raw_api import RAW_API

    ns.update(RAW_API)
    return ns


def _parse_arg_str(config_arg_str: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in (config_arg_str or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip()
    return out


def parse_config(
    config: Union[str, Callable[[], Any]],
    config_arg_str: str = "",
    emit_proto: bool = True,
) -> ParsedConfig:
    """Execute `config` (a .py file path or a zero-arg callable using the DSL)
    and return the parsed result. Mirrors parse_config(trainer_config,
    config_arg_str) → TrainerConfig proto."""
    from paddle_tpu.nn.graph import record_layers

    with fresh_context(_parse_arg_str(config_arg_str)) as ctx, record_layers(
        []
    ) as created:
        reset_name_scope()
        if callable(config):
            ret = config()
            if ret is not None and not ctx.outputs:
                outputs(ret)
        else:
            # config scripts import `paddle.trainer_config_helpers` — make
            # sure the compat namespace resolves regardless of the caller's
            # cwd (scripts are usually parsed from their own data directory)
            import os
            import sys

            repo_root = os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            )
            if repo_root not in sys.path:
                sys.path.insert(0, repo_root)
            ns = _dsl_namespace()
            ns["__file__"] = config
            with open(config) as f:
                code = compile(f.read(), config, "exec")
            exec(code, ns)
        if ctx.pending_output_names:
            by_name = {l.name: l for l in created}
            for n in ctx.pending_output_names:
                node = by_name.get(n)
                if node is None and n == "__beam_search_predict__":
                    # the reference's default beam_search output name; our
                    # generation node carries the user's group name instead.
                    # An outer recurrent_group whose step generates (the
                    # nested-generation idiom, sample_trainer_nest_rnn_gen)
                    # counts too — its output concatenates the inner beams.
                    def _generates(l) -> bool:
                        if getattr(l, "type_name", "") == "beam_search":
                            return True
                        core = getattr(l, "_group_core", None)
                        return core is not None and any(
                            getattr(o, "type_name", "") == "beam_search"
                            for o in core.out_layers
                        )

                    node = next((l for l in created if _generates(l)), None)
                if node is not None and node not in ctx.outputs:
                    ctx.outputs.append(node)
        if not ctx.outputs:
            raise ValueError(
                f"config {config!r} declared no outputs(); call outputs(cost)"
            )
        if not callable(config):
            import os

            cfg_dir = os.path.dirname(os.path.abspath(config))
            for dc in (ctx.data_config, ctx.test_data_config):
                if dc is not None and not dc.config_dir:
                    dc.config_dir = cfg_dir
        # bind the provider's declared input_types to the data layers before
        # tracing — the reference's runtime slot binding (PyDataProvider2);
        # this is where sub-sequence nesting comes from when the config
        # doesn't wrap inputs in SubsequenceInput (gserver's
        # sequence_rnn_mixed_inputs idiom). Best-effort: test configs often
        # reference providers that don't exist at parse time.
        if ctx.data_config is not None and ctx.data_config.load_data_module:
            try:
                from paddle_tpu.cli import bind_provider_types

                bind_provider_types(Topology(ctx.outputs), ctx.data_config)
            except Exception:
                pass
        # layers created by the script but unreachable from outputs() stay in
        # the config, as the reference's do (unused_layers.py golden; print
        # layers have no consumers by design) — carried as extra_layers
        reachable = {
            l.name for l in Topology(ctx.outputs).network.layer_order
        }
        dangling = []
        for l in created:
            if l.name not in reachable and l.name not in {d.name for d in dangling}:
                dangling.append(l)
        topology = Topology(ctx.outputs, extra_layers=dangling)
        # Inputs(...) fixes the provider slot order (config_parser Inputs);
        # without it the data layers' topological order stands in
        topology.declared_inputs = list(ctx.pending_input_names)
        tc = proto.TrainerConfig(
            opt_config=ctx.opt_config or proto.OptimizationConfig(),
            data_config=ctx.data_config,
            test_data_config=ctx.test_data_config,
        )
        if emit_proto:
            from paddle_tpu.config.dump import build_model_config

            tc.model_config = build_model_config(topology)
            tc.model_config.evaluators = list(ctx.evaluators)
            tc.model_config.type = ctx.model_type
        return ParsedConfig(tc, topology, list(ctx.outputs), ctx)


def parse_config_and_serialize(config: Union[str, Callable], config_arg_str: str = "") -> str:
    """Reference-named entry point: parse then serialize to text format."""
    return proto.to_text(parse_config(config, config_arg_str).trainer_config)
