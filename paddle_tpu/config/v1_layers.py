"""v1 `trainer_config_helpers` wrappers with reference-faithful signatures.

Every public function here matches the positional/keyword signature of its
namesake in the reference's python/paddle/trainer_config_helpers/layers.py
(and networks.py for the composites), including the decorator-injected
defaults (@wrap_act_default / @wrap_bias_attr_default — e.g. img_conv_layer
defaults to ReluActivation, fc_layer to TanhActivation, pooling_layer to
MaxPooling), so UNMODIFIED reference config scripts execute against this
module (the round-1 north-star gap).

v1 image-shape semantics: data layers are FLAT vectors (CHW order); image
layers carry (channels, height, width) geometry in the layer config and the
first image op infers height = width = sqrt(size / channels)
(config_parser.py parse_image / ConvConfig). Here that geometry rides on the
graph node as `_v1_geom`, and a flat input entering an image layer gets an
explicit Reshape(CHW) + SwitchOrder(NHWC) adapter — making the layout
conversion visible in the graph rather than implicit in kernels (TPU-native:
everything downstream is NHWC for the MXU).
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Union

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn import recurrent as R
from paddle_tpu.nn import seq_layers as S
from paddle_tpu.nn.graph import Layer
from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.v2 import layer as _v2
from paddle_tpu.v2.activation import resolve as _act
from paddle_tpu.v2.pooling import resolve as _pool_name

__all__ = [
    "data_layer", "fc_layer", "embedding_layer", "img_conv_layer",
    "img_pool_layer", "img_cmrnorm_layer", "batch_norm_layer",
    "dropout_layer", "concat_layer", "conv_projection", "conv_operator",
    "pooling_layer",
    "maxid_layer", "classification_cost", "cross_entropy",
    "img_conv_group", "simple_img_conv_pool", "sequence_conv_pool",
    "text_conv_pool", "simple_lstm", "simple_gru", "bidirectional_lstm",
    "bidirectional_gru", "last_seq", "first_seq", "expand_layer",
    "ctc_layer", "warp_ctc_layer", "crf_layer", "crf_decoding_layer",
    "nce_layer", "hsigmoid", "lstmemory", "grumemory", "recurrent_layer",
    "lambda_cost", "maxout_layer", "bilinear_interp_layer", "spp_layer",
    "row_conv_layer", "block_expand_layer", "img_conv3d_layer",
    "img_pool3d_layer",
    "seq_slice_layer", "kmax_sequence_score_layer", "seq_concat_layer",
    "seq_reshape_layer", "sub_nested_seq_layer", "gated_unit_layer",
    "simple_gru2", "lstm_step_layer", "gru_step_layer",
    "gru_step_naive_layer", "get_output_layer", "lstmemory_unit",
    "lstmemory_group", "gru_unit", "gru_group", "recurrent_group",
    "multibox_loss_layer", "detection_output_layer",
]


# ---------------------------------------------------------------------------
# v1 geometry bookkeeping (config_parser parse_image semantics)
# ---------------------------------------------------------------------------


def _size_of(node: Layer) -> Optional[int]:
    s = getattr(node, "_v1_size", None)
    if s is not None:
        return int(s)
    shape = getattr(node, "shape", None)  # data layers
    if shape:
        n = 1
        for d in shape:
            n *= int(d)
        return n
    if getattr(node, "size", None):
        return int(node.size)
    return None


def _annotate(node: Layer, size: Optional[int] = None, geom=None) -> Layer:
    if size is not None:
        node._v1_size = int(size)
    if geom is not None:
        node._v1_geom = tuple(int(v) for v in geom)
        c, h, w = node._v1_geom
        node._v1_size = c * h * w
    return node


def _infer_geom(input: Layer, num_channels: Optional[int]):
    """(c, h, w) of `input`, inferring square maps from the flat size the way
    parse_image does (img_size = sqrt(size / channels))."""
    geom = getattr(input, "_v1_geom", None)
    if geom is not None:
        return geom
    if num_channels is None:
        raise ValueError(
            f"layer {getattr(input, 'name', input)!r} has no image geometry; "
            f"pass num_channels= on the first image layer (v1 convention)"
        )
    size = _size_of(input)
    if size is None:
        raise ValueError(
            f"cannot infer image size of layer {getattr(input, 'name', input)!r}"
        )
    hw = size // num_channels
    # parse_image's rule (config_parser.py get_img_size): width = floor-sqrt
    # of the pixel count, height = pixels / width — square when possible,
    # rectangular otherwise, rejected when indivisible
    w = int(math.isqrt(hw))
    if w == 0 or hw % w:
        raise ValueError(
            f"input size {size} with {num_channels} channels has no "
            f"integer {{w}}x{{h}} factorization from width floor-sqrt "
            f"(parse_image would reject this too)"
        )
    return (num_channels, hw // w, w)


def _is_flat(node: Layer) -> bool:
    """True when the node's values are flat [B, c*h*w] even though image
    geometry may be declared: data layers always feed flat values (the
    provider's dense slot), and elementwise wrappers over them stay flat."""
    return (
        getattr(node, "type_name", None) == "data"
        or getattr(node, "_v1_flat", False)
    )


def _ensure_nhwc(input: Layer, num_channels: Optional[int]):
    """Returns (nhwc_node, (c, h, w)). Inserts the flat-CHW -> NHWC adapter
    when the input is not already an image-layout node. The adapter is cached
    on the input so a data layer feeding several image branches (inception
    towers) reuses one node instead of colliding on names."""
    geom = getattr(input, "_v1_geom", None)
    if geom is not None and not _is_flat(input):
        return input, geom
    cached = getattr(input, "_v1_nhwc_node", None)
    if cached is not None:
        return cached, cached._v1_geom
    c, h, w = geom if geom is not None else _infer_geom(input, num_channels)
    node = L.Reshape(input, (c, h, w), name=f"{input.name}.as_image")
    node = L.SwitchOrder(node, to="NHWC", name=f"{input.name}.to_nhwc")
    _annotate(node, geom=(c, h, w))
    input._v1_nhwc_node = node
    return node, (c, h, w)


def _annotate3d(node: Layer, geom3d) -> Layer:
    c, d, h, w = (int(v) for v in geom3d)
    node._v1_geom3d = (c, d, h, w)
    node._v1_size = c * d * h * w
    return node


def _ensure_ndhwc(input: Layer, num_channels: Optional[int]):
    """3-D analog of _ensure_nhwc: flat [B, c*d*h*w] (CDHW order) → NDHWC."""
    geom3d = getattr(input, "_v1_geom3d", None)
    if geom3d is not None and not _is_flat(input):
        return input, geom3d
    cached = getattr(input, "_v1_ndhwc_node", None)
    if cached is not None:
        return cached, cached._v1_geom3d
    if geom3d is None:
        size = _size_of(input)
        if size is None or num_channels is None:
            raise ValueError(
                f"cannot infer 3-D geometry of {getattr(input, 'name', input)!r}; "
                "declare height/width/depth on the data layer or pass num_channels"
            )
        side = round((size // num_channels) ** (1 / 3))
        geom3d = (num_channels, side, side, side)
    c, d, h, w = geom3d
    node = L.Reshape(input, (c, d, h, w), name=f"{input.name}.as_vol")
    node = L.SwitchOrder(node, to="NDHWC", name=f"{input.name}.to_ndhwc")
    _annotate3d(node, (c, d, h, w))
    input._v1_ndhwc_node = node
    return node, (c, d, h, w)


def img_conv3d_layer(input, filter_size, num_filters=None, name=None,
                     num_channels=None, act=None, groups=1, stride=1,
                     padding=0, bias_attr=None, param_attr=None,
                     shared_biases=True, layer_attr=None,
                     trans=False, layer_type=None, **_compat):
    """layers.py img_conv3d_layer — flat CDHW data gets the NDHWC adapter;
    filter/stride/padding may be scalars or (x, y, z)? no: scalars or
    [d, h, w]-style lists per the reference (one value used for all axes)."""
    ndhwc, (cin, dz, h, w) = _ensure_ndhwc(input, num_channels)
    f = filter_size if isinstance(filter_size, (list, tuple)) else (filter_size,) * 3
    s = stride if isinstance(stride, (list, tuple)) else (stride,) * 3
    p = padding if isinstance(padding, (list, tuple)) else (padding,) * 3
    node = _v2.img_conv3d(
        ndhwc, tuple(f), num_filters, stride=tuple(s), padding=tuple(p),
        groups=groups, act=_act(act),
        bias_attr=bias_attr, param_attr=_or_none(param_attr), name=name,
        trans=trans,
    )
    if trans:
        od = (dz - 1) * s[0] - 2 * p[0] + f[0]
        oh = (h - 1) * s[1] - 2 * p[1] + f[1]
        ow = (w - 1) * s[2] - 2 * p[2] + f[2]
    else:
        od = _conv_out(dz, f[0], p[0], s[0])
        oh = _conv_out(h, f[1], p[1], s[1])
        ow = _conv_out(w, f[2], p[2], s[2])
    return _with_drop(
        _annotate3d(node, (num_filters, od, oh, ow)), layer_attr
    )


def img_pool3d_layer(input, pool_size, name=None, num_channels=None,
                     pool_type=None, stride=1, padding=0, layer_attr=None,
                     ceil_mode=True, **_compat):
    ndhwc, (c, dz, h, w) = _ensure_ndhwc(input, num_channels)
    f = pool_size if isinstance(pool_size, (list, tuple)) else (pool_size,) * 3
    s = stride if isinstance(stride, (list, tuple)) else (stride,) * 3
    p = padding if isinstance(padding, (list, tuple)) else (padding,) * 3
    node = _v2.img_pool3d(ndhwc, tuple(f), pool_type=pool_type,
                          stride=tuple(s), padding=tuple(p), name=name)
    od = _pool_out(dz, f[0], p[0], s[0], ceil_mode)
    oh = _pool_out(h, f[1], p[1], s[1], ceil_mode)
    ow = _pool_out(w, f[2], p[2], s[2], ceil_mode)
    return _with_drop(_annotate3d(node, (c, od, oh, ow)), layer_attr)


def _conv_out(size: int, filt: int, pad: int, stride: int, dilation: int = 1) -> int:
    """caffeMode output size (MathUtils.cpp outputSize, caffeMode=true)."""
    eff = dilation * (filt - 1) + 1
    return (size + 2 * pad - eff) // stride + 1


def _pool_out(size: int, filt: int, pad: int, stride: int, ceil: bool) -> int:
    if ceil:  # v1 img_pool default (MathUtils outputSize caffeMode=false)
        return -(-(size + 2 * pad - filt) // stride) + 1
    return (size + 2 * pad - filt) // stride + 1


def _with_drop(node: Layer, layer_attr) -> Layer:
    out = _v2._with_drop(node, layer_attr)
    if out is not node and hasattr(node, "_v1_geom"):
        _annotate(out, geom=node._v1_geom)
    elif out is not node and _size_of(node) is not None:
        _annotate(out, size=_size_of(node))
    return out


def _or_none(attr):
    return None if isinstance(attr, bool) else attr


# ---------------------------------------------------------------------------
# core layers (layers.py signatures)
# ---------------------------------------------------------------------------


def data_layer(name, size, depth=None, height=None, width=None,
               layer_attr=None):
    """layers.py:916 — flat data slot; height/width (/depth for 3D) declare
    image geometry."""
    node = L.Data(name, shape=(int(size),), is_seq=False)
    _annotate(node, size=size)
    if height and width:
        if depth:
            ch = int(size) // (int(depth) * int(height) * int(width))
            node._v1_geom3d = (ch, int(depth), int(height), int(width))
        else:
            ch = int(size) // (int(height) * int(width))
            node._v1_geom = (ch, int(height), int(width))
    return node


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, layer_attr=None):
    """layers.py:996 — act defaults to TanhActivation (@wrap_act_default)."""
    ins = input if isinstance(input, (list, tuple)) else [input]
    node = L.Fc(
        list(ins), size, act=_act(act) or "tanh", bias=bias_attr is not False,
        param_attr=_or_none(param_attr), bias_attr=_or_none(bias_attr),
        name=name,
    )
    return _with_drop(_annotate(node, size=size), layer_attr)


def embedding_layer(input, size, name=None, param_attr=None, layer_attr=None):
    """layers.py:963 — vocab comes from the input data layer's declared size."""
    vocab = _size_of(input)
    spec = getattr(input, "data_type", None)
    if spec is not None and spec.kind in ("index", "index_seq"):
        vocab = int(spec.dim)
    elif getattr(input, "type_name", None) == "data" and spec is None:
        # v1: a data layer feeding an embedding is an id slot (TableProjection
        # consumes ids); record it so the auto feeder treats it as ids
        from paddle_tpu.data.feeder import integer_value

        input.data_type = integer_value(vocab or 0)
        input.shape = ()
    node = L.Embedding(input, size, vocab_size=vocab,
                       param_attr=_or_none(param_attr), name=name)
    return _with_drop(_annotate(node, size=size), layer_attr)


def dropout_layer(input, dropout_rate, name=None):
    node = L.Dropout(input, dropout_rate, name=name)
    if hasattr(input, "_v1_geom"):
        _annotate(node, geom=input._v1_geom)
        if _is_flat(input):  # elementwise: stays flat if the input was flat
            node._v1_flat = True
    elif _size_of(input) is not None:
        _annotate(node, size=_size_of(input))
    return node


def img_conv_layer(input, filter_size, num_filters, name=None,
                   num_channels=None, act=None, groups=1, stride=1, padding=0,
                   dilation=1, bias_attr=None, param_attr=None,
                   shared_biases=True, layer_attr=None, filter_size_y=None,
                   stride_y=None, padding_y=None, dilation_y=None,
                   trans=False, layer_type=None):
    """layers.py:2373 — act defaults to ReluActivation (@wrap_act_default);
    non-square kernels via the *_y parameters or (x, y) pairs; trans=True is
    deconv."""
    nhwc, (cin, h, w) = _ensure_nhwc(input, num_channels)
    # the reference unpacks sequence args as (x, y) pairs (layers.py:2525)
    if isinstance(filter_size, (tuple, list)):
        filter_size, filter_size_y = filter_size
    if isinstance(stride, (tuple, list)):
        stride, stride_y = stride
    if isinstance(padding, (tuple, list)):
        padding, padding_y = padding
    if isinstance(dilation, (tuple, list)):
        dilation, dilation_y = dilation
    fy = filter_size_y if filter_size_y is not None else filter_size
    sy = stride_y if stride_y is not None else stride
    py = padding_y if padding_y is not None else padding
    dy = dilation_y if dilation_y is not None else dilation
    kwargs = dict(
        num_filters=num_filters,
        filter_size=(fy, filter_size),  # (h, w): *_y is the vertical extent
        stride=(sy, stride),
        padding=(py, padding),
        act=_act(act) or "relu",
        bias=bias_attr is not False,
        param_attr=_or_none(param_attr),
        bias_attr=_or_none(bias_attr),
        name=name,
    )
    if trans:
        node = L.Conv2DTranspose(nhwc, **kwargs)
        oh = (h - 1) * sy - 2 * py + fy
        ow = (w - 1) * stride - 2 * padding + filter_size
    else:
        node = L.Conv2D(nhwc, dilation=(dy, dilation), groups=groups, **kwargs)
        oh = _conv_out(h, fy, py, sy, dy)
        ow = _conv_out(w, filter_size, padding, stride, dilation)
    return _with_drop(_annotate(node, geom=(num_filters, oh, ow)), layer_attr)


def img_pool_layer(input, pool_size, name=None, num_channels=None,
                   pool_type=None, stride=1, padding=0, layer_attr=None,
                   pool_size_y=None, stride_y=None, padding_y=None,
                   ceil_mode=True):
    """layers.py:2568 — pool_type defaults to MaxPooling; ceil_mode=True is
    the v1 default output-size rule."""
    nhwc, (c, h, w) = _ensure_nhwc(input, num_channels)
    fy = pool_size_y if pool_size_y is not None else pool_size
    sy = stride_y if stride_y is not None else stride
    py = padding_y if padding_y is not None else padding
    ptype = _pool_name(pool_type) if pool_type is not None else "max"
    if ptype not in ("max", "avg"):
        raise ValueError(f"img_pool_layer supports max/avg, got {ptype!r}")
    node = L.Pool2D(
        nhwc, (fy, pool_size), ptype, stride=(sy, stride),
        padding=(py, padding), ceil_mode=ceil_mode, name=name,
    )
    oh = _pool_out(h, fy, py, sy, ceil_mode)
    ow = _pool_out(w, pool_size, padding, stride, ceil_mode)
    return _with_drop(_annotate(node, geom=(c, oh, ow)), layer_attr)


def img_cmrnorm_layer(input, size, scale=0.0128, power=0.75, name=None,
                      num_channels=None, layer_attr=None):
    """layers.py:2931 — cross-map response normalization (AlexNet LRN)."""
    nhwc, geom = _ensure_nhwc(input, num_channels)
    node = _v2.img_cmrnorm(nhwc, size, scale=scale, power=power, name=name)
    return _with_drop(_annotate(node, geom=geom), layer_attr)


def batch_norm_layer(input, act=None, name=None, img3D=False,
                     num_channels=None, bias_attr=None, param_attr=None,
                     layer_attr=None, batch_norm_type=None,
                     epsilon=1e-5, moving_average_fraction=0.9,
                     use_global_stats=None, mean_var_names=None):
    """layers.py batch_norm_layer — on image input keeps geometry (and must
    normalize per channel, so flat image data goes through the NHWC adapter
    first, matching CudnnBatchNorm's per-channel statistics)."""
    geom = getattr(input, "_v1_geom", None)
    geom3d = getattr(input, "_v1_geom3d", None)
    node_in = input
    if img3D and (geom3d is not None or num_channels is not None):
        if geom3d is None:
            size = _size_of(input)
            side = round((size // num_channels) ** (1 / 3))
            geom3d = (num_channels, side, side, side)
        c, d, h, w = geom3d
        cached = getattr(input, "_v1_ndhwc_node", None)
        if cached is not None:
            node_in = cached
        else:
            node_in = L.Reshape(input, (c, d, h, w), name=f"{input.name}.as_vol")
            node_in = L.SwitchOrder(node_in, to="NDHWC", name=f"{input.name}.to_ndhwc")
            input._v1_ndhwc_node = node_in
    elif geom is not None or num_channels is not None:
        node_in, geom = _ensure_nhwc(input, num_channels)
    node = L.BatchNorm(
        # @wrap_act_default(act=ReluActivation()) on the reference helper
        node_in, act=_act(act) if act is not None else "relu", epsilon=epsilon,
        moving_average_fraction=moving_average_fraction,
        use_global_stats=use_global_stats, param_attr=_or_none(param_attr),
        bias_attr=_or_none(bias_attr), name=name,
    )
    if geom is not None:
        _annotate(node, geom=geom)
    elif _size_of(input) is not None:
        _annotate(node, size=_size_of(input))
    return _with_drop(node, layer_attr)


class _ConvProjSpec:
    """conv_projection (layers.py:4492): a deferred conv applied by the
    enclosing mixed/concat layer (ConvProjection in the reference)."""

    def __init__(self, input, filter_size, num_filters, num_channels,
                 stride, padding, groups, param_attr, trans):
        self.input = input
        self.filter_size = filter_size
        self.num_filters = num_filters
        self.num_channels = num_channels
        self.stride = stride
        self.padding = padding
        self.groups = groups
        self.param_attr = param_attr
        self.trans = trans

    def build(self, name: str) -> Layer:
        return img_conv_layer(
            self.input, self.filter_size, self.num_filters, name=name,
            num_channels=self.num_channels, act="linear", groups=self.groups,
            stride=self.stride, padding=self.padding, bias_attr=False,
            param_attr=self.param_attr, trans=self.trans,
        )


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, groups=1, param_attr=None,
                    trans=False, filter_size_y=None, stride_y=None,
                    padding_y=None):
    from paddle_tpu.nn.projections import ConvProj

    if filter_size_y is not None:
        filter_size = (filter_size_y, filter_size)
    if stride_y is not None:
        stride = (stride_y, stride)
    if padding_y is not None:
        padding = (padding_y, padding)

    if num_channels is None:
        geom = getattr(input, "_v1_geom", None)
        num_channels = geom[0] if geom else None
    return ConvProj(input, filter_size, num_filters,
                    num_channels=num_channels, stride=stride, padding=padding,
                    groups=groups, param_attr=_or_none(param_attr), trans=trans)


def conv_operator(img, filter, filter_size, num_filters, num_channels=None,
                  stride=1, padding=0, filter_size_y=None, stride_y=None,
                  padding_y=None, trans=False):
    from paddle_tpu.nn.projections import ConvOperator

    if num_channels is None:
        geom = getattr(img, "_v1_geom", None)
        num_channels = geom[0] if geom else None
    return ConvOperator(img, filter, filter_size, num_filters,
                        num_channels=num_channels, stride=stride,
                        padding=padding, trans=trans)


def concat_layer(input, act=None, name=None, layer_attr=None, bias_attr=None):
    """layers.py:3252 — concatenates layers, or applies projections then
    concatenates (the reference's concat2/ConcatenateLayer2 path, which is
    what GoogleNet's inception blocks use with conv_projection inputs)."""
    ins = list(input) if isinstance(input, (list, tuple)) else [input]
    built: List[Layer] = []
    for i, item in enumerate(ins):
        if not isinstance(item, Layer) and not hasattr(item, "build"):
            # plain projections → ConcatenateLayer2 applying them in place
            from paddle_tpu.nn.projections import Projection

            assert all(isinstance(x, Projection) for x in ins), (
                "concat_layer mixes projections and layers"
            )
            node = L.Concat2(ins, act=_act(act),
                             bias=bias_attr not in (None, False),
                             bias_attr=_or_none(bias_attr), name=name)
            return _with_drop(node, layer_attr)
        if hasattr(item, "build") and not isinstance(item, Layer):
            built.append(item.build(f"{name}.proj{i}" if name else None))
        elif _is_flat(item) and getattr(item, "_v1_geom", None) is not None:
            built.append(_ensure_nhwc(item, None)[0])  # channel concat needs NHWC
        else:
            built.append(item)
    geoms = [getattr(b, "_v1_geom", None) for b in built]
    node = L.Concat(built, act=None, name=name)
    out_geom = None
    if all(g is not None for g in geoms):
        c = sum(g[0] for g in geoms)
        out_geom = (c, geoms[0][1], geoms[0][2])
        _annotate(node, geom=out_geom)
    else:
        sizes = [_size_of(b) for b in built]
        if all(s is not None for s in sizes):
            _annotate(node, size=sum(sizes))
    act_name = _act(act)
    if bias_attr not in (None, False) or (act_name and act_name != "linear"):
        # concat2 semantics: shared bias + activation applied on the result
        node = L.Addto([node], act=act_name, bias=bias_attr not in (None, False),
                       bias_attr=_or_none(bias_attr),
                       name=f"{name}.out" if name else None)
        if out_geom is not None:
            _annotate(node, geom=out_geom)
    return _with_drop(node, layer_attr)


def pooling_layer(input, pooling_type=None, name=None, bias_attr=None,
                  agg_level=None, stride=-1, layer_attr=None):
    """layers.py:1343 — sequence pooling; pooling_type defaults MaxPooling.
    stride>0 pools fixed windows (SequencePoolLayer stride mode);
    agg_level=AggregateLevel.TO_SEQUENCE pools within subsequences."""
    _mark_seq_root(input)
    nm = _pool_name(pooling_type) if pooling_type is not None else "max"
    seq_kind = {"max": "max", "avg": "average", "sum": "sum", "sqrt": "sqrt"}[nm]
    node = S.SeqPool(input, seq_kind, name=name, agg_level=agg_level,
                     stride=-1 if stride is None else stride)
    if getattr(pooling_type, "output_max_index", None):
        node.output_max_index = True
    sz = _size_of(input)
    if sz is not None:
        _annotate(node, size=sz)
    return _with_drop(node, layer_attr)


def last_seq(input, agg_level=None, stride=-1, name=None, layer_attr=None):
    _mark_seq_root(input)
    node = _v2.last_seq(input, agg_level=agg_level, stride=stride, name=name)
    sz = _size_of(input)
    if sz is not None:
        _annotate(node, size=sz)
    return _with_drop(node, layer_attr)


def first_seq(input, agg_level=None, stride=-1, name=None, layer_attr=None):
    _mark_seq_root(input)
    node = _v2.first_seq(input, agg_level=agg_level, stride=stride, name=name)
    sz = _size_of(input)
    if sz is not None:
        _annotate(node, size=sz)
    return _with_drop(node, layer_attr)


def expand_layer(input, expand_as, name=None, bias_attr=None,
                 expand_level=None, layer_attr=None):
    _mark_seq_root(expand_as)
    node = _v2.expand(input, expand_as, expand_level=expand_level, name=name)
    sz = _size_of(input)
    if sz is not None:
        _annotate(node, size=sz)
    return _with_drop(node, layer_attr)


def seq_slice_layer(input, starts=None, ends=None, name=None):
    _mark_seq_root(input)
    return _v2.seq_slice(input, starts=starts, ends=ends, name=name)


def kmax_sequence_score_layer(input, name=None, beam_size=1):
    _mark_seq_root(input)
    return _v2.kmax_seq_score(input, beam_size=beam_size, name=name)


def seq_concat_layer(a, b, act=None, name=None, layer_attr=None,
                     bias_attr=None):
    _mark_seq_root(a)
    _mark_seq_root(b)
    node = S.SeqConcat(a, b, name=name)
    sz = _size_of(a)
    if sz is not None:
        _annotate(node, size=sz)
    return _with_drop(node, layer_attr)


def seq_reshape_layer(input, reshape_size, act=None, name=None,
                      layer_attr=None, bias_attr=None):
    _mark_seq_root(input)
    node = _v2.seq_reshape(input, reshape_size, name=name)
    _annotate(node, size=reshape_size)
    return _with_drop(node, layer_attr)


def sub_nested_seq_layer(input, selected_indices, name=None):
    _mark_seq_root(input, nested=True)
    return _v2.sub_nested_seq(input, selected_indices, name=name)


def maxid_layer(input, name=None, layer_attr=None):
    return _with_drop(_v2.max_id(input, name=name), layer_attr)


def lstmemory(input, name=None, size=None, reverse=False, act=None,
              gate_act=None, state_act=None, bias_attr=None, param_attr=None,
              layer_attr=None):
    """layers.py lstmemory: input is the pre-projected [4*size] mixed/fc."""
    _mark_seq_root(input)
    if size is None:
        insz = _size_of(input)
        size = insz // 4 if insz else None
    node = _v2.lstmemory(input, size=size, reverse=reverse, act=act,
                         gate_act=gate_act, state_act=state_act,
                         param_attr=_or_none(param_attr),
                         bias_attr=bias_attr, name=name)
    if size:
        _annotate(node, size=size)
    return _with_drop(node, layer_attr)


def grumemory(input, name=None, size=None, reverse=False, act=None,
              gate_act=None, bias_attr=None, param_attr=None, layer_attr=None):
    """layers.py grumemory: input is the pre-projected [3*size] mixed/fc."""
    _mark_seq_root(input)
    if size is None:
        insz = _size_of(input)
        size = insz // 3 if insz else None
    node = _v2.grumemory(input, size=size, reverse=reverse, act=act,
                         gate_act=gate_act, param_attr=_or_none(param_attr),
                         bias_attr=bias_attr, name=name)
    if size:
        _annotate(node, size=size)
    return _with_drop(node, layer_attr)


def recurrent_layer(input, act=None, bias_attr=None, param_attr=None,
                    name=None, reverse=False, layer_attr=None):
    _mark_seq_root(input)
    node = _v2.recurrent(input, act=act, reverse=reverse,
                         bias_attr=bias_attr, param_attr=_or_none(param_attr),
                         name=name)
    sz = _size_of(input)
    if sz:
        _annotate(node, size=sz)
    return _with_drop(node, layer_attr)


def maxout_layer(input, groups, num_channels=None, name=None, layer_attr=None):
    nhwc, (c, h, w) = _ensure_nhwc(input, num_channels)
    node = _v2.maxout(nhwc, groups, name=name)
    return _with_drop(_annotate(node, geom=(c // groups, h, w)), layer_attr)


def bilinear_interp_layer(input, out_size_x=None, out_size_y=None, name=None,
                          layer_attr=None):
    nhwc, (c, h, w) = _ensure_nhwc(input, None)
    node = _v2.bilinear_interp(nhwc, out_size_x, out_size_y, name=name)
    return _with_drop(_annotate(node, geom=(c, out_size_y, out_size_x)), layer_attr)


def spp_layer(input, name=None, num_channels=None, pool_type=None,
              pyramid_height=None, layer_attr=None):
    nhwc, (c, h, w) = _ensure_nhwc(input, num_channels)
    node = _v2.spp(nhwc, pyramid_height=pyramid_height, pool_type=pool_type,
                   name=name)
    bins = sum(4 ** i for i in range(pyramid_height))
    return _with_drop(_annotate(node, size=c * bins), layer_attr)


def row_conv_layer(input, context_len, act=None, name=None, param_attr=None,
                   layer_attr=None):
    _mark_seq_root(input)
    node = _v2.row_conv(input, context_len, act=act,
                        param_attr=_or_none(param_attr), name=name)
    sz = _size_of(input)
    if sz:
        _annotate(node, size=sz)
    return _with_drop(node, layer_attr)


def block_expand_layer(input, block_x=0, block_y=0, stride_x=0, stride_y=0,
                       padding_x=0, padding_y=0, num_channels=None, name=None,
                       layer_attr=None):
    nhwc, (c, h, w) = _ensure_nhwc(input, num_channels)
    node = _v2.block_expand(nhwc, block_x=block_x, block_y=block_y,
                            stride_x=stride_x or block_x,
                            stride_y=stride_y or block_y,
                            padding_x=padding_x, padding_y=padding_y,
                            name=name)
    _annotate(node, size=c * block_x * block_y)
    return _with_drop(node, layer_attr)


def multibox_loss_layer(input_loc, input_conf, priorbox, label, num_classes,
                        overlap_threshold=0.5, neg_pos_ratio=3.0,
                        neg_overlap=0.5, background_id=0, name=None):
    """layers.py multibox_loss_layer: packed v1 slots (priorbox rows of 8,
    label rows of 6) in the reference input order."""
    from paddle_tpu.nn.detection_layers import MultiBoxLossV1

    locs = input_loc if isinstance(input_loc, (list, tuple)) else [input_loc]
    confs = input_conf if isinstance(input_conf, (list, tuple)) else [input_conf]
    node = MultiBoxLossV1(
        list(locs), list(confs), priorbox, label, num_classes,
        overlap_threshold=overlap_threshold, neg_pos_ratio=neg_pos_ratio,
        neg_overlap=neg_overlap, background_id=background_id, name=name,
    )
    return _annotate(node, size=1)


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                           confidence_threshold=0.01, background_id=0,
                           name=None):
    from paddle_tpu.nn.detection_layers import DetectionOutputV1

    locs = input_loc if isinstance(input_loc, (list, tuple)) else [input_loc]
    confs = input_conf if isinstance(input_conf, (list, tuple)) else [input_conf]
    node = DetectionOutputV1(
        list(locs), list(confs), priorbox, num_classes,
        nms_threshold=nms_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, confidence_threshold=confidence_threshold,
        background_id=background_id, name=name,
    )
    return _annotate(node, size=keep_top_k * 7)


def lambda_cost(input, score, name=None, NDCG_num=5, max_sort_size=-1,
                layer_attr=None):
    """LambdaRank works on score sequences (LambdaCost.cpp)."""
    _mark_seq_root(input)
    _mark_seq_root(score)
    return _with_drop(
        _v2.lambda_cost(input, score, NDCG_num=NDCG_num, name=name),
        layer_attr,
    )


def _mark_label_as_id_seq(label: Layer) -> None:
    """Sequence-label costs (ctc/crf): the label slot is an id sequence."""
    from paddle_tpu.data.feeder import integer_value_sequence

    if getattr(label, "type_name", None) == "data" and (
        getattr(label, "data_type", None) is None
        or label.data_type.kind in ("dense", "index")
    ):
        label.data_type = integer_value_sequence(_size_of(label) or 0)
        label.shape = ()
        label.is_seq = True


def ctc_layer(input, label, size=None, name=None, norm_by_times=False,
              layer_attr=None):
    """layers.py ctc_layer: size defaults to the input layer's size (the
    alphabet incl. blank, CTCLayer.cpp)."""
    _mark_seq_root(input)
    lbl_size = _size_of(label)
    _mark_label_as_id_seq(label)
    if size is None:  # layers.py:5251: size = label dict size + 1 (blank last)
        size = (lbl_size + 1) if lbl_size else _size_of(input)
    return _with_drop(
        _v2.ctc(input, label, size=size, norm_by_times=norm_by_times, name=name),
        layer_attr,
    )


def warp_ctc_layer(input, label, size=None, name=None, blank=0,
                   norm_by_times=False, layer_attr=None):
    _mark_seq_root(input)
    lbl_size = _size_of(label)
    _mark_label_as_id_seq(label)
    if size is None:
        size = (lbl_size + 1) if lbl_size else _size_of(input)
    return _with_drop(
        _v2.warp_ctc(input, label, size=size, blank=blank,
                     norm_by_times=norm_by_times, name=name),
        layer_attr,
    )


def crf_layer(input, label, size=None, weight=None, param_attr=None,
              name=None, coeff=1.0, layer_attr=None):
    _mark_seq_root(input)
    _mark_label_as_id_seq(label)
    return _with_drop(
        _v2.crf(input, label, size=size or _size_of(input),
                param_attr=_or_none(param_attr), name=name, coeff=coeff),
        layer_attr,
    )


def crf_decoding_layer(input, size=None, label=None, param_attr=None,
                       name=None, layer_attr=None):
    _mark_seq_root(input)
    if label is not None:
        _mark_label_as_id_seq(label)
    return _with_drop(
        _v2.crf_decoding(input, size=size or _size_of(input), label=label,
                         param_attr=_or_none(param_attr), name=name),
        layer_attr,
    )


def nce_layer(input, label, num_classes=None, weight=None, num_neg_samples=10,
              neg_distribution=None, name=None, bias_attr=None,
              param_attr=None, layer_attr=None):
    """layers.py nce_layer: num_classes defaults to the label layer's size."""
    _mark_label_as_ids(label)
    if num_classes is None:
        num_classes = _size_of(label) or 0
    return _with_drop(
        _v2.nce(input, label, num_classes, weight=weight,
                num_neg_samples=num_neg_samples,
                neg_distribution=neg_distribution, bias_attr=bias_attr,
                param_attr=_or_none(param_attr), name=name),
        layer_attr,
    )


def hsigmoid(input, label, num_classes=None, name=None, bias_attr=None,
             param_attr=None, layer_attr=None):
    _mark_label_as_ids(label)
    if num_classes is None:
        num_classes = _size_of(label) or 0
    return _with_drop(
        _v2.hsigmoid(input, label, num_classes, bias_attr=bias_attr,
                     param_attr=_or_none(param_attr), name=name),
        layer_attr,
    )


def _mark_seq_root(node: Layer, nested: bool = False) -> None:
    """A sequence-consuming wrapper (seq pooling, lstm/gru, context conv)
    reveals that the data layers feeding it carry sequences — information the
    reference gets from the provider's input_types at runtime
    (PyDataProvider2 slot binding). Walk back to the data roots and mark
    them, so shape inference and auto-built feeders produce [B, T, ...]
    (nested=True → SUB_SEQUENCE slots, [B, S, T, ...])."""
    from paddle_tpu.data.feeder import (
        dense_vector_sequence,
        dense_vector_sub_sequence,
        integer_value_sequence,
        integer_value_sub_sequence,
    )

    seen = set()
    stack = [node]
    while stack:
        cur = stack.pop()
        if id(cur) in seen:
            continue
        seen.add(id(cur))
        if getattr(cur, "type_name", None) == "data":
            cur.is_seq = True
            spec = getattr(cur, "data_type", None)
            if spec is None and nested:
                cur.data_type = dense_vector_sub_sequence(_size_of(cur) or 1)
            elif spec is not None and spec.kind == "index":
                cur.data_type = (
                    integer_value_sub_sequence(int(spec.dim))
                    if nested
                    else integer_value_sequence(int(spec.dim))
                )
            elif spec is not None and spec.kind == "dense":
                cur.data_type = (
                    dense_vector_sub_sequence(spec.dim)
                    if nested
                    else dense_vector_sequence(spec.dim)
                )
            elif spec is not None and spec.kind == "dense_seq" and nested:
                cur.data_type = dense_vector_sub_sequence(spec.dim)
            elif spec is not None and spec.kind == "index_seq" and nested:
                cur.data_type = integer_value_sub_sequence(int(spec.dim))
            continue
        stack.extend(getattr(cur, "inputs", []) or [])


def _mark_label_as_ids(label: Layer) -> None:
    """v1 declares label data layers by class count (data_layer('label', 10))
    and the provider feeds integer ids; multi-class cost layers are what
    reveal the id-ness. Rewrite the data layer to an index slot so shape
    inference and auto-built feeders treat it as ids (what PyDataProvider2's
    integer_value slot binding does at runtime)."""
    if getattr(label, "type_name", None) != "data":
        return
    if getattr(label, "data_type", None) is not None:
        return
    from paddle_tpu.data.feeder import integer_value

    n = _size_of(label) or 0
    label.data_type = integer_value(n)
    label.shape = ()


def classification_cost(input, label, weight=None, name=None,
                        evaluator=None, layer_attr=None, coeff=1.):
    """layers.py:4347 — input is the (typically softmax-activated) output
    layer; declares a classification_error evaluator like the reference."""
    from paddle_tpu.config import helpers as _h

    _mark_label_as_ids(label)
    from_logits = _act(_v2.effective_act(input)) != "softmax"
    node = C.ClassificationCost(
        input, label, weight=weight, name=name, coeff=coeff,
        from_logits=from_logits,
    )
    try:  # the default evaluator declaration (reference default arg)
        if evaluator is None:
            _h.classification_error_evaluator(
                input=input, label=label, weight=weight
            )
        elif callable(evaluator):
            evaluator(input=input, label=label, weight=weight)
    except Exception:
        pass  # declaring an evaluator must never fail the parse
    return _with_drop(node, layer_attr)


def cross_entropy(input, label, name=None, coeff=1.0, weight=None,
                  layer_attr=None):
    """layers.py:5738 — input already carries its output activation."""
    _mark_label_as_ids(label)
    from_logits = _act(_v2.effective_act(input)) != "softmax"
    node = C.ClassificationCost(
        input, label, weight=weight, name=name, coeff=coeff,
        from_logits=from_logits,
    )
    return _with_drop(node, layer_attr)


# ---------------------------------------------------------------------------
# networks.py composites (reference signatures)
# ---------------------------------------------------------------------------


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0,
                   pool_stride=1, pool_type=None, param_attr=None):
    """networks.py:336 — the VGG conv block."""
    n = len(conv_num_filter)

    def bc(v, default):
        if isinstance(v, (list, tuple)):
            return list(v)
        return [v if v is not None else default] * n

    paddings = bc(conv_padding, 1)
    fsizes = bc(conv_filter_size, 3)
    acts = bc(conv_act, None)
    with_bn = bc(conv_with_batchnorm, False)
    bn_drop = bc(conv_batchnorm_drop_rate, 0)

    tmp = input
    for i in range(n):
        tmp = img_conv_layer(
            tmp, fsizes[i], conv_num_filter[i],
            num_channels=num_channels if i == 0 else None,
            padding=paddings[i],
            act="linear" if with_bn[i] else (acts[i] or "relu"),
            param_attr=param_attr,
        )
        if with_bn[i]:
            from paddle_tpu.v2.attr import ExtraAttr

            tmp = batch_norm_layer(
                tmp, act=acts[i] or "relu",
                layer_attr=ExtraAttr(drop_rate=bn_drop[i]) if bn_drop[i] else None,
            )
    return img_pool_layer(tmp, pool_size, stride=pool_stride,
                          pool_type=pool_type)


def simple_img_conv_pool(input, filter_size, num_filters, pool_size, name=None,
                         pool_type=None, act=None, groups=1, conv_stride=1,
                         conv_padding=0, bias_attr=None, num_channel=None,
                         param_attr=None, shared_bias=True, conv_layer_attr=None,
                         pool_stride=1, pool_padding=0, pool_layer_attr=None):
    """networks.py:144."""
    conv = img_conv_layer(
        input, filter_size, num_filters, name=f"{name}_conv" if name else None,
        num_channels=num_channel, act=act, groups=groups, stride=conv_stride,
        padding=conv_padding, bias_attr=bias_attr, param_attr=param_attr,
        shared_biases=shared_bias, layer_attr=conv_layer_attr,
    )
    return img_pool_layer(
        conv, pool_size, name=f"{name}_pool" if name else None,
        pool_type=pool_type, stride=pool_stride, padding=pool_padding,
        layer_attr=pool_layer_attr,
    )


def sequence_conv_pool(input, context_len, hidden_size, name=None,
                       context_start=None, pool_type=None,
                       context_proj_layer_name=None,
                       context_proj_param_attr=False, fc_layer_name=None,
                       fc_param_attr=None, fc_bias_attr=None, fc_act=None,
                       pool_bias_attr=None, fc_attr=None, context_attr=None,
                       pool_attr=None):
    """networks.py:40 — context projection + fc + sequence pooling (the
    text-CNN block used by quick_start's trainer_config.cnn.py)."""
    from paddle_tpu.nn import projections as P

    _mark_seq_root(input)
    start = context_start if context_start is not None else -(context_len // 2)
    in_size = _size_of(input)
    proj_size = (in_size or 0) * context_len
    ctxp = L.Mixed(
        [P.Context_(input, start, context_len,
                    trainable_padding=bool(context_proj_param_attr))],
        size=proj_size or None,
        name=context_proj_layer_name or (f"{name}.context" if name else None),
    )
    if in_size is not None:
        _annotate(ctxp, size=proj_size)
    fc = fc_layer(
        ctxp, hidden_size, act=fc_act or "linear",
        name=fc_layer_name or (f"{name}.fc" if name else None),
        param_attr=fc_param_attr, bias_attr=fc_bias_attr, layer_attr=fc_attr,
    )
    return pooling_layer(fc, pooling_type=pool_type, name=name,
                         bias_attr=pool_bias_attr, layer_attr=pool_attr)


def text_conv_pool(input, context_len=5, hidden_size=128, act=None, **kw):
    return sequence_conv_pool(input, context_len, hidden_size, fc_act=act, **kw)


def simple_lstm(input, size, name=None, reverse=False, mat_param_attr=None,
                bias_param_attr=None, inner_param_attr=None, act=None,
                gate_act=None, state_act=None, mixed_layer_attr=None,
                lstm_cell_attr=None):
    """networks.py:553 — fc(4H) projection + lstmemory."""
    _mark_seq_root(input)
    proj = fc_layer(
        input, size * 4, act="linear", name=f"{name}.input_proj" if name else None,
        param_attr=mat_param_attr, bias_attr=False, layer_attr=mixed_layer_attr,
    )
    node = R.Lstm(
        proj, size=size, reverse=reverse, act=_act(act) or "tanh",
        gate_act=_act(gate_act) or "sigmoid",
        state_act=_act(state_act) or "tanh",
        param_attr=_or_none(inner_param_attr),
        bias_attr=_or_none(bias_param_attr), name=name,
    )
    return _with_drop(_annotate(node, size=size), lstm_cell_attr)


def recurrent_group(step, input, reverse=False, name=None, targetInlink=None,
                    **_compat):
    """layers.py recurrent_group: marks iterated data roots as (nested)
    sequences before delegating to the scan-based group."""
    from paddle_tpu.nn import recurrent_group as rg

    items = input if isinstance(input, (list, tuple)) else [input]
    for item in items:
        if isinstance(item, (rg.SubsequenceInput,)):
            _mark_seq_root(item.input, nested=True)
        elif isinstance(item, Layer):
            _mark_seq_root(item)
    return _v2.recurrent_group(step, input, reverse=reverse, name=name)


def lstm_step_layer(input, state, size=None, act=None, gate_act=None,
                    state_act=None, bias_attr=None, name=None, layer_attr=None):
    """layers.py lstm_step_layer — one LSTM cell step inside a group."""
    if size is None:
        size = (_size_of(input) or 0) // 4
    node = R.LstmStep(
        input, state, size, act=_act(act), gate_act=_act(gate_act),
        state_act=_act(state_act), bias=bias_attr is not False,
        bias_attr=_or_none(bias_attr), name=name,
    )
    return _with_drop(_annotate(node, size=size), layer_attr)


def gru_step_layer(input, output_mem, size=None, act=None, gate_act=None,
                   bias_attr=None, param_attr=None, name=None,
                   layer_attr=None):
    if size is None:
        size = (_size_of(input) or 0) // 3
    node = R.GruStep(
        input, output_mem, size, act=_act(act), gate_act=_act(gate_act),
        bias=bias_attr is not False, bias_attr=_or_none(bias_attr),
        param_attr=_or_none(param_attr), name=name,
    )
    return _with_drop(_annotate(node, size=size), layer_attr)


gru_step_naive_layer = gru_step_layer


def get_output_layer(input, arg_name, name=None, layer_attr=None):
    """Dual-role get_output_layer: inside a step net it reads a layer's
    auxiliary output arg (GetOutputLayer); on a finished recurrent_group it
    fetches another step output sequence."""
    from paddle_tpu.nn import recurrent_group as rg

    if hasattr(input, "_group_core") or hasattr(input, "core"):
        return rg.get_output_layer(input, arg_name, name=name)
    return R.StepArgOutput(input, arg_name, name=name)


def lstmemory_unit(input, out_memory=None, name=None, size=None,
                   param_attr=None, act=None, gate_act=None, state_act=None,
                   input_proj_bias_attr=None, input_proj_layer_attr=None,
                   lstm_bias_attr=None, lstm_layer_attr=None):
    """networks.py lstmemory_unit: the in-group LSTM step — input+recurrent
    mixed projection, lstm_step, state published for the state memory."""
    if size is None:
        size = (_size_of(input) or 0) // 4
    if out_memory is None:
        out_mem = _v2.memory(name=name, size=size)
    else:
        out_mem = out_memory
    state_mem = _v2.memory(name=f"{name}_state", size=size)
    m = _v2.mixed(
        size=size * 4,
        name=f"{name}_input_recurrent",
        bias_attr=input_proj_bias_attr,
        layer_attr=input_proj_layer_attr,
        act="linear",
        input=[
            _v2.identity_projection(input=input),
            _v2.full_matrix_projection(input=out_mem, param_attr=_or_none(param_attr)),
        ],
    )
    _annotate(m, size=size * 4)
    lstm_out = lstm_step_layer(
        name=name, input=m, state=state_mem, size=size,
        bias_attr=lstm_bias_attr, act=act, gate_act=gate_act,
        state_act=state_act, layer_attr=lstm_layer_attr,
    )
    get_output_layer(name=f"{name}_state", input=lstm_out, arg_name="state")
    return lstm_out


def lstmemory_group(input, size=None, name=None, out_memory=None,
                    reverse=False, param_attr=None, act=None, gate_act=None,
                    state_act=None, input_proj_bias_attr=None,
                    input_proj_layer_attr=None, lstm_bias_attr=None,
                    lstm_layer_attr=None):
    """networks.py lstmemory_group: lstmemory_unit unrolled by
    recurrent_group (the layer-composed LSTM, vs the fused lstmemory)."""
    _mark_seq_root(input)
    if size is None:
        size = (_size_of(input) or 0) // 4

    def __lstm_step__(ipt):
        return lstmemory_unit(
            input=ipt, name=name, size=size, act=act, gate_act=gate_act,
            state_act=state_act, out_memory=out_memory,
            input_proj_bias_attr=input_proj_bias_attr,
            input_proj_layer_attr=input_proj_layer_attr,
            param_attr=param_attr, lstm_layer_attr=lstm_layer_attr,
            lstm_bias_attr=lstm_bias_attr,
        )

    node = _v2.recurrent_group(
        name=f"{name}_recurrent_group", step=__lstm_step__, reverse=reverse,
        input=input,
    )
    if size is None:
        size = (_size_of(input) or 0) // 4
    return _annotate(node, size=size)


def gru_unit(input, memory_boot=None, size=None, name=None, gru_bias_attr=None,
             gru_param_attr=None, act=None, gate_act=None, gru_layer_attr=None,
             naive=False):
    """networks.py gru_unit: in-group GRU step with its output memory."""
    if size is None:
        size = (_size_of(input) or 0) // 3
    out_mem = _v2.memory(name=name, size=size, boot_layer=memory_boot)
    return gru_step_layer(
        name=name, input=input, output_mem=out_mem, size=size,
        bias_attr=gru_bias_attr, param_attr=gru_param_attr, act=act,
        gate_act=gate_act, layer_attr=gru_layer_attr,
    )


def gru_group(input, memory_boot=None, size=None, name=None, reverse=False,
              gru_bias_attr=None, gru_param_attr=None, act=None,
              gate_act=None, gru_layer_attr=None, naive=False):
    """networks.py gru_group: gru_unit unrolled by recurrent_group."""
    _mark_seq_root(input)
    if size is None:
        size = (_size_of(input) or 0) // 3

    def __gru_step__(ipt):
        return gru_unit(
            input=ipt, memory_boot=memory_boot, name=name, size=size,
            gru_bias_attr=gru_bias_attr, gru_param_attr=gru_param_attr,
            act=act, gate_act=gate_act, gru_layer_attr=gru_layer_attr,
            naive=naive,
        )

    node = _v2.recurrent_group(
        name=f"{name}_recurrent_group", step=__gru_step__, reverse=reverse,
        input=input,
    )
    if size is None:
        size = (_size_of(input) or 0) // 3
    return _annotate(node, size=size)


def gated_unit_layer(input, size, act=None, name=None, gate_attr=None,
                     gate_param_attr=None, gate_bias_attr=True,
                     inproj_attr=None, inproj_param_attr=None,
                     inproj_bias_attr=True, layer_attr=None):
    """layers.py gated_unit_layer: input_proj fc ⊙ sigmoid gate fc via a
    dot_mul-operator mixed (GLU)."""
    input_proj = fc_layer(
        input=input, name=f"{name}_input_proj", size=size,
        act=act if act is not None else "linear",
        layer_attr=inproj_attr, param_attr=inproj_param_attr,
        bias_attr=inproj_bias_attr,
    )
    gate = fc_layer(
        input=input, name=f"{name}_gate", size=size, act="sigmoid",
        layer_attr=gate_attr, param_attr=gate_param_attr,
        bias_attr=gate_bias_attr,
    )
    node = _v2.mixed(
        size=size,
        input=_v2.dotmul_operator(input_proj, gate),
        name=f"{name}_gated_act", layer_attr=layer_attr,
    )
    return _annotate(node, size=size)


def _gru_transform(input, size, name, param_attr, bias_attr, layer_attr):
    """The `%s_transform` mixed(3H) projection both simple_gru variants
    share (networks.py simple_gru/simple_gru2)."""
    _mark_seq_root(input)
    m = _v2.mixed(
        size=size * 3,
        input=[_v2.full_matrix_projection(input, param_attr=_or_none(param_attr))],
        bias_attr=bias_attr,
        name=f"{name}_transform" if name else None,
        layer_attr=layer_attr,
    )
    return _annotate(m, size=size * 3)


def simple_gru(input, size, name=None, reverse=False, mixed_param_attr=None,
               mixed_bias_param_attr=None, mixed_layer_attr=None,
               gru_param_attr=None, gru_bias_attr=None, act=None,
               gate_act=None, gru_layer_attr=None, naive=False):
    """networks.py:981 — `%s_transform` mixed(3H) + gru cell (the reference
    routes through gru_group; the fused grumemory computes the same math)."""
    m = _gru_transform(input, size, name, mixed_param_attr,
                       mixed_bias_param_attr, mixed_layer_attr)
    return gru_group(input=m, size=size, name=name, reverse=reverse,
                     gru_bias_attr=gru_bias_attr, gru_param_attr=gru_param_attr,
                     act=act, gate_act=gate_act, gru_layer_attr=gru_layer_attr,
                     naive=naive)


def simple_gru2(input, size, name=None, reverse=False, mixed_param_attr=None,
                mixed_bias_attr=None, gru_param_attr=None, gru_bias_attr=None,
                act=None, gate_act=None, mixed_layer_attr=None,
                gru_cell_attr=None):
    """networks.py simple_gru2: `%s_transform` mixed(3H) + grumemory."""
    m = _gru_transform(input, size, name, mixed_param_attr, mixed_bias_attr,
                       mixed_layer_attr)
    return grumemory(m, name=name, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, bias_attr=gru_bias_attr,
                     param_attr=gru_param_attr, layer_attr=gru_cell_attr)


def bidirectional_lstm(input, size, name=None, return_seq=False, **kw):
    """networks.py:1214 — concat of forward and backward simple_lstm."""
    fwd = simple_lstm(input, size, name=f"{name}_fw" if name else None)
    bwd = simple_lstm(input, size, name=f"{name}_bw" if name else None,
                      reverse=True)
    if return_seq:
        node = L.Concat([fwd, bwd], name=name)
        return _annotate(node, size=size * 2)
    last_f = S.LastSeq(fwd, name=f"{name}_fw_last" if name else None)
    first_b = S.FirstSeq(bwd, name=f"{name}_bw_first" if name else None)
    node = L.Concat([last_f, first_b], name=name)
    return _annotate(node, size=size * 2)


def bidirectional_gru(input, size, name=None, return_seq=False, **kw):
    """networks.py bidirectional_gru: two simple_gru2 passes + concat
    (fwd_/bwd_ prefixed attrs route to the respective pass)."""
    fwd_kw = {k[4:]: v for k, v in kw.items() if k.startswith("fwd_")}
    bwd_kw = {k[4:]: v for k, v in kw.items() if k.startswith("bwd_")}
    fwd = simple_gru2(input, size, name=f"{name}_fw", **fwd_kw)
    bwd = simple_gru2(input, size, name=f"{name}_bw", reverse=True, **bwd_kw)
    if return_seq:
        node = L.Concat([fwd, bwd], act=_act(kw.get("concat_act")), name=name)
        return _annotate(node, size=size * 2)
    last_f = last_seq(fwd, name=f"{name}_fw_last")
    first_b = first_seq(bwd, name=f"{name}_bw_last")
    node = L.Concat([last_f, first_b], act=_act(kw.get("concat_act")), name=name)
    return _annotate(node, size=size * 2)
