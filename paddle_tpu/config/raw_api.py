"""The rawest v1 config surface: Layer() and friends, by NAME registry.

The oldest reference configs (chunking.conf, sample_trainer_config_rnn.conf,
sample_trainer_config_qb_rnn.conf, compare_sparse) skip trainer_config_helpers
entirely and call the low-level @config_func DSL of
python/paddle/trainer/config_parser.py directly: `Layer(name=..., type=...,
inputs=[...])` registering into a global name map, projections referencing
layers by name, and RecurrentLayerGroupBegin/End + Memory
(config_parser.py:367,2863) bracketing a step sub-net.

Here those primitives are a thin shim over the same builders the
trainer_config_helpers surface uses: names resolve through a per-parse
registry, and a recurrent layer group records its Layer()/Memory() calls as
deferred thunks replayed inside the step function of a recurrent_group — the
declarative bracketing becomes our traced scan."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from paddle_tpu.config import config_parser as cp


# ---------------------------------------------------------------------------
# per-parse state
# ---------------------------------------------------------------------------


def _state():
    ctx = cp.g_context()
    if not hasattr(ctx, "raw_layer_map"):
        ctx.raw_layer_map = {}
        ctx.raw_group_stack = []
    return ctx


def _register(name: str, node) -> None:
    st = _state()
    if st.raw_group_stack:
        st.raw_group_stack[-1]["local_names"].append(name)
    st.raw_layer_map[name] = node


def _resolve(ref, local: Optional[Dict[str, Any]] = None):
    """A layer reference: an actual node, or a name looked up in the replay
    overlay then the registry."""
    if not isinstance(ref, str):
        return ref
    if local is not None and ref in local:
        return local[ref]
    st = _state()
    if ref in st.raw_layer_map:
        return st.raw_layer_map[ref]
    raise KeyError(f"Layer() references unknown layer name {ref!r}")


# ---------------------------------------------------------------------------
# attribute wrappers
# ---------------------------------------------------------------------------


def _param_attr(parameter_name=None, initial_std=None, initial_mean=None,
                learning_rate=None, decay_rate=None, decay_rate_l1=None,
                momentum=None, initial_smart=False, is_static=False,
                sparse_update=False, sparse_remote_update=False, **_kw):
    from paddle_tpu.nn.graph import ParamAttr

    pa = ParamAttr(
        name=parameter_name,
        initial_std=initial_std,
        initial_mean=initial_mean if initial_mean is not None else 0.0,
        learning_rate=learning_rate if learning_rate is not None else 1.0,
        momentum=momentum,
        l2_decay=decay_rate,
        l1_decay=decay_rate_l1,
        is_static=bool(is_static),
        is_sparse=bool(sparse_update or sparse_remote_update),
    )
    if initial_smart:
        # initial_smart overrides default_initial_std with 1/sqrt(fan_in)
        # (reference Parameter(), config_parser.py:3893) — an explicit
        # initializer wins over both initial_std and the global default
        from paddle_tpu.nn import init as init_mod

        pa.initial_std = None
        pa.initializer = init_mod.smart_normal
    return pa


class Input:
    """Input(layer_name, parameter_name=..., ...) — a weighted input slot."""

    def __init__(self, layer_name, **kw):
        self.layer_name = layer_name
        self.attr = _param_attr(**kw)


def Bias(**kw):
    return _param_attr(**kw)


class _RawProjection:
    def __init__(self, kind: str, layer_name, kw: Dict[str, Any]):
        self.kind = kind
        self.layer_name = layer_name
        self.kw = kw

    def build(self, local=None):
        from paddle_tpu.v2 import layer as v2

        src = _resolve(self.layer_name, local)
        attr = _param_attr(**self.kw)
        if self.kind == "fullmatrix":
            return v2.full_matrix_projection(src, param_attr=attr)
        if self.kind == "table":
            return v2.table_projection(src, param_attr=attr)
        if self.kind == "identity":
            return v2.identity_projection(src)
        if self.kind == "transposedfullmatrix":
            return v2.trans_full_matrix_projection(src, param_attr=attr)
        if self.kind == "dotmul":
            return v2.dotmul_projection(src, param_attr=attr)
        raise ValueError(f"unknown raw projection kind {self.kind}")


def FullMatrixProjection(layer_name, **kw):
    return _RawProjection("fullmatrix", layer_name, kw)


def TableProjection(layer_name, **kw):
    return _RawProjection("table", layer_name, kw)


def IdentityProjection(layer_name, **kw):
    return _RawProjection("identity", layer_name, kw)


def TransposedFullMatrixProjection(layer_name, **kw):
    return _RawProjection("transposedfullmatrix", layer_name, kw)


def DotMulProjection(layer_name, **kw):
    return _RawProjection("dotmul", layer_name, kw)


# ---------------------------------------------------------------------------
# activation mapping (raw active_type strings)
# ---------------------------------------------------------------------------

_ACT = {
    "": None, "linear": "linear", "tanh": "tanh", "sigmoid": "sigmoid",
    "relu": "relu", "softmax": "softmax", "exponential": "exp",
    "square": "square", "abs": "abs", "softrelu": "softrelu", "brelu": "brelu",
    "stanh": "stanh",
}


def _act_obj(active_type: Optional[str]):
    from paddle_tpu.v2 import activation as A

    name = _ACT.get(active_type or "", active_type)
    if name is None:
        return None
    table = {
        "linear": A.Linear, "tanh": A.Tanh, "sigmoid": A.Sigmoid,
        "relu": A.Relu, "softmax": A.Softmax, "exp": A.Exp,
        "square": A.Square, "abs": A.Abs, "softrelu": A.SoftRelu,
        "brelu": A.BRelu, "stanh": A.STanh,
    }
    return table[name]()


# ---------------------------------------------------------------------------
# Layer() dispatch
# ---------------------------------------------------------------------------


def _normalize_inputs(inputs) -> List[Any]:
    if inputs is None:
        return []
    if not isinstance(inputs, (list, tuple)):
        return [inputs]
    return list(inputs)


def _split_input(item):
    """→ (layer_ref, ParamAttr or None)."""
    if isinstance(item, Input):
        return item.layer_name, item.attr
    return item, None


def _build_layer(spec: Dict[str, Any], local=None):
    import paddle_tpu.config.v1_layers as v1
    from paddle_tpu.v2 import layer as v2

    from paddle_tpu.v2 import activation as A

    name = spec["name"]
    ltype = spec["type"]
    size = spec.get("size", 0)
    # raw LayerBase defaults active_type='' = LINEAR (config_parser.py), not
    # the trainer_config_helpers per-layer defaults (fc would get tanh there)
    act = _act_obj(spec.get("active_type", "")) or A.Linear()
    bias = spec.get("bias", None)
    bias_attr: Any
    if bias is False:
        bias_attr = False
    elif bias is None or bias is True:
        bias_attr = None
    else:
        bias_attr = bias  # a Bias(...) ParamAttr
    raw_inputs = _normalize_inputs(spec.get("inputs"))

    if ltype == "data":
        return v1.data_layer(name, size)

    if ltype == "mixed":
        projs = [
            item.build(local) if isinstance(item, _RawProjection) else item
            for item in raw_inputs
        ]
        return v2.mixed(size=size, input=projs, act=act,
                        bias_attr=bias_attr, name=name)

    if ltype == "fc":
        refs, attrs = zip(*(_split_input(i) for i in raw_inputs))
        nodes = [_resolve(r, local) for r in refs]
        # per-input parameters: fc over multiple inputs is a mixed of
        # full-matrix projections in the reference (FullyConnectedLayer
        # holds one weight per input)
        if len(nodes) == 1:
            return v1.fc_layer(nodes[0], size, act=act, name=name,
                               param_attr=attrs[0], bias_attr=bias_attr)
        projs = [
            v2.full_matrix_projection(n, param_attr=a)
            for n, a in zip(nodes, attrs)
        ]
        return v2.mixed(size=size, input=projs, act=act,
                        bias_attr=bias_attr if bias_attr is not None else None,
                        name=name)

    if ltype == "recurrent":
        (ref, attr), = [_split_input(i) for i in raw_inputs]
        return v1.recurrent_layer(
            _resolve(ref, local), act=act, name=name,
            bias_attr=bias_attr, param_attr=attr,
        )

    if ltype == "seqlastins":
        (ref, _), = [_split_input(i) for i in raw_inputs]
        return v1.last_seq(_resolve(ref, local), name=name)

    if ltype == "seqfirstins":
        (ref, _), = [_split_input(i) for i in raw_inputs]
        return v1.first_seq(_resolve(ref, local), name=name)

    if ltype in ("average", "max"):
        (ref, _), = [_split_input(i) for i in raw_inputs]
        pool = "avg" if ltype == "average" else "max"
        return v1.pooling_layer(
            _resolve(ref, local), pooling_type=pool, name=name
        )

    if ltype == "rank-cost":
        refs = [_split_input(i)[0] for i in raw_inputs]
        left, right, label = (_resolve(r, local) for r in refs)
        return v2.rank_cost(left, right, label, name=name)

    if ltype == "crf":
        items = [_split_input(i) for i in raw_inputs]
        inp = _resolve(items[0][0], local)
        label = _resolve(items[1][0], local)
        return v1.crf_layer(inp, label, size=size, name=name,
                            param_attr=items[0][1])

    if ltype == "crf_decoding":
        items = [_split_input(i) for i in raw_inputs]
        inp = _resolve(items[0][0], local)
        label = _resolve(items[1][0], local) if len(items) > 1 else None
        return v1.crf_decoding_layer(inp, size=size, label=label, name=name,
                                     param_attr=items[0][1])

    if ltype == "multi-class-cross-entropy":
        refs = [_split_input(i)[0] for i in raw_inputs]
        inp, label = (_resolve(r, local) for r in refs)
        return v2.classification_cost(inp, label, name=name)

    raise NotImplementedError(f"raw Layer type {ltype!r} not supported yet")


def Layer(name: str, type: str, **kw) -> str:  # noqa: A002
    """config_parser.py Layer(): build (or defer, inside a group) and
    register under `name`. Returns the name, as the reference does."""
    st = _state()
    spec = dict(kw, name=name, type=type)
    if st.raw_group_stack:
        st.raw_group_stack[-1]["thunks"].append(
            lambda local: local.__setitem__(name, _build_layer(spec, local))
        )
        st.raw_group_stack[-1]["local_names"].append(name)
        return name
    node = _build_layer(spec)
    _register(name, node)
    return name


# ---------------------------------------------------------------------------
# recurrent layer groups
# ---------------------------------------------------------------------------


def Memory(name: str, size: int, is_sequence: bool = False,
           boot_layer: Optional[str] = None, boot_bias: bool = False,
           **_kw) -> str:
    """config_parser.py:2863 — returns the agent name '{name}+delay1' which
    later projections reference; the actual memory node is created at
    replay time inside the step trace."""
    st = _state()
    if not st.raw_group_stack:
        raise ValueError("Memory() outside RecurrentLayerGroupBegin")
    agent_name = name + "+delay1"
    boot_node = _resolve(boot_layer) if boot_layer else None

    def thunk(local):
        from paddle_tpu.nn.recurrent_group import memory as _memory

        local[agent_name] = _memory(
            name=name, size=size, boot_layer=boot_node, boot_bias=boot_bias,
            is_seq=is_sequence,
        )

    st.raw_group_stack[-1]["thunks"].append(thunk)
    st.raw_group_stack[-1]["local_names"].append(agent_name)
    return agent_name


def RecurrentLayerGroupBegin(name: str, in_links: Sequence[str],
                             out_links: Sequence[str],
                             generator=None, target_inlinkname: str = "",
                             seq_reversed: bool = False) -> None:
    if generator is not None:
        raise NotImplementedError(
            "raw generator groups: use beam_search via trainer_config_helpers"
        )
    st = _state()
    st.raw_group_stack.append({
        "name": name,
        "in_links": list(in_links),
        "out_links": list(out_links),
        "seq_reversed": bool(seq_reversed),
        "thunks": [],
        "local_names": [],
    })


def RecurrentLayerGroupEnd(name: str) -> None:
    from paddle_tpu.nn.recurrent_group import recurrent_group

    st = _state()
    if not st.raw_group_stack or st.raw_group_stack[-1]["name"] != name:
        raise ValueError(f"RecurrentLayerGroupEnd({name!r}) does not match")
    g = st.raw_group_stack.pop()
    in_nodes = [_resolve(n) for n in g["in_links"]]

    def step(*args):
        local: Dict[str, Any] = dict(zip(g["in_links"], args))
        for thunk in g["thunks"]:
            thunk(local)
        outs = tuple(local[n] for n in g["out_links"])
        return outs if len(outs) > 1 else outs[0]

    result = recurrent_group(
        step=step, input=in_nodes, reverse=g["seq_reversed"], name=name
    )
    nodes = result if isinstance(result, tuple) else (result,)
    # out-link layers become visible in the parent by their step-net names
    # (GatherAgentLayer in the parent submodel, config_parser.py:402-409)
    for link, node in zip(g["out_links"], nodes):
        _register(link, node)


# ---------------------------------------------------------------------------
# Evaluator()
# ---------------------------------------------------------------------------

_RAW_EVAL_TYPES = {
    "sum": "sum",
    "classification_error": "classification_error",
    "chunk": "chunk",
    "last-column-sum": "column_sum",
    "last-column-auc": "auc",
    "precision_recall": "precision_recall",
}


def Evaluator(name: str, type: str, inputs, chunk_scheme: Optional[str] = None,  # noqa: A002
              num_chunk_types: Optional[int] = None, **kw) -> None:
    from paddle_tpu.config.helpers import _declare_evaluator

    refs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    nodes = [_resolve(r) for r in refs]
    extra = {}
    if chunk_scheme is not None:
        extra["chunk_scheme"] = chunk_scheme
    if num_chunk_types is not None:
        extra["num_chunk_types"] = num_chunk_types
    _declare_evaluator(
        _RAW_EVAL_TYPES.get(type, type), *nodes, name=name, **extra
    )


RAW_API = {
    "Layer": Layer,
    "Input": Input,
    "Bias": Bias,
    "Memory": Memory,
    "RecurrentLayerGroupBegin": RecurrentLayerGroupBegin,
    "RecurrentLayerGroupEnd": RecurrentLayerGroupEnd,
    "Evaluator": Evaluator,
    "FullMatrixProjection": FullMatrixProjection,
    "TableProjection": TableProjection,
    "IdentityProjection": IdentityProjection,
    "TransposedFullMatrixProjection": TransposedFullMatrixProjection,
    "DotMulProjection": DotMulProjection,
}
