"""v1 optimizer DSL: `settings()` + *Optimizer classes
(trainer_config_helpers/optimizers.py; settings() → OptimizationConfig,
config_parser.py `Settings`).

The classes are thin tags over the v2 optimizer bundles (which already fold
schedule/regularization/averaging into the compiled step); `settings()`
records the active OptimizationConfig into the parsing context so
parse_config can emit it and the CLI can build the real optimizer.
"""

from __future__ import annotations

from typing import Any, Optional

from paddle_tpu import proto
from paddle_tpu.v2 import optimizer as v2opt

# re-exported v1 names
BaseSGDOptimizer = v2opt._V2Optimizer


class MomentumOptimizer(v2opt.Momentum):
    learning_method = "momentum"


class AdamOptimizer(v2opt.Adam):
    learning_method = "adam"


class AdamaxOptimizer(v2opt.AdaMax):
    learning_method = "adamax"


class AdaGradOptimizer(v2opt.AdaGrad):
    learning_method = "adagrad"


class DecayedAdaGradOptimizer(v2opt.DecayedAdaGrad):
    learning_method = "decayed_adagrad"


class AdaDeltaOptimizer(v2opt.AdaDelta):
    learning_method = "adadelta"


class RmsPropOptimizer(v2opt.RMSProp):
    learning_method = "rmsprop"


L2Regularization = v2opt.L2Regularization
L1Regularization = v2opt.L1Regularization
ModelAverage = v2opt.ModelAverageCfg


class GradientClippingThreshold:
    def __init__(self, threshold: float):
        self.threshold = threshold


_METHODS = {
    "momentum": MomentumOptimizer,
    "sgd": MomentumOptimizer,
    "adam": AdamOptimizer,
    "adamax": AdamaxOptimizer,
    "adagrad": AdaGradOptimizer,
    "decayed_adagrad": DecayedAdaGradOptimizer,
    "adadelta": AdaDeltaOptimizer,
    "rmsprop": RmsPropOptimizer,
}


def build_optimizer(oc: proto.OptimizationConfig) -> v2opt._V2Optimizer:
    """OptimizationConfig → v2 optimizer bundle (optimizer+schedule+avg)."""
    cls = _METHODS.get(oc.learning_method, MomentumOptimizer)
    reg = None
    if oc.l2_weight_decay:
        reg = L2Regularization(oc.l2_weight_decay)
    elif oc.l1_weight_decay:
        reg = L1Regularization(oc.l1_weight_decay)
    kwargs: dict = dict(oc.extra)
    if cls is MomentumOptimizer:
        kwargs.setdefault("momentum", oc.momentum)
    return cls(
        learning_rate=oc.learning_rate,
        learning_rate_decay_a=oc.learning_rate_decay_a,
        learning_rate_decay_b=oc.learning_rate_decay_b,
        learning_rate_schedule=oc.learning_rate_schedule,
        regularization=reg,
        gradient_clipping_threshold=oc.gradient_clipping_threshold or None,
        model_average=(
            ModelAverage(oc.average_window, oc.max_average_window or None)
            if oc.average_window
            else None
        ),
        **kwargs,
    )


def settings(
    batch_size: int = 1,
    learning_rate: float = 0.01,
    learning_method: Optional[Any] = None,
    regularization: Optional[Any] = None,
    gradient_clipping_threshold: Optional[float] = None,
    model_average: Optional[Any] = None,
    learning_rate_decay_a: float = 0.0,
    learning_rate_decay_b: float = 0.0,
    learning_rate_schedule: str = "constant",
    learning_rate_warmup_steps: int = 0,
    average_window: float = 0.0,
    max_average_window: int = 0,
    **extra,
) -> proto.OptimizationConfig:
    """The v1 `settings()` call. Records into the active parsing context
    (config_parser.g_context) and returns the OptimizationConfig."""
    from paddle_tpu.config import config_parser as cp

    oc = proto.OptimizationConfig(
        batch_size=batch_size,
        learning_rate=learning_rate,
        learning_rate_decay_a=learning_rate_decay_a,
        learning_rate_decay_b=learning_rate_decay_b,
        learning_rate_schedule=learning_rate_schedule,
        learning_rate_warmup_steps=learning_rate_warmup_steps,
        average_window=average_window,
        max_average_window=max_average_window,
    )
    if learning_method is not None:
        oc.learning_method = getattr(
            learning_method, "learning_method",
            str(getattr(learning_method, "name", learning_method)),
        )
        for k in ("momentum", "beta1", "beta2", "epsilon", "rho", "nesterov"):
            if hasattr(learning_method, "optimizer") and hasattr(
                learning_method.optimizer, k
            ):
                v = getattr(learning_method.optimizer, k)
                if k == "momentum":
                    oc.momentum = v
                else:
                    oc.extra[k] = v
    if isinstance(regularization, (L1Regularization, L2Regularization)):
        oc.l1_weight_decay = regularization.l1 or 0.0
        oc.l2_weight_decay = regularization.l2 or 0.0
    if isinstance(model_average, ModelAverage):
        oc.average_window = model_average.average_window
        oc.max_average_window = model_average.max_average_window or 0
    if isinstance(gradient_clipping_threshold, GradientClippingThreshold):
        gradient_clipping_threshold = gradient_clipping_threshold.threshold
    if gradient_clipping_threshold:
        oc.gradient_clipping_threshold = float(gradient_clipping_threshold)
    oc.extra.update({k: v for k, v in extra.items() if isinstance(v, (int, float, str, bool))})
    cp.g_context().opt_config = oc
    return oc
