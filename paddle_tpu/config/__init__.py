"""The v1 config pipeline: config scripts → TrainerConfig (SURVEY §2.4).

- config_parser: parse_config / parse_config_and_serialize (the entry points
  the reference's C++ trainer calls via embedded Python,
  paddle/trainer/TrainerConfigHelper.cpp:34-56)
- helpers: the trainer_config_helpers DSL surface injected into config scripts
- optimizers: settings() and the *Optimizer classes
- dump: layer graph → ModelConfig text (dump_config parity)
"""

from paddle_tpu.config.config_parser import (
    ParsedConfig,
    get_config_arg,
    outputs,
    parse_config,
    parse_config_and_serialize,
)
from paddle_tpu.config.dump import build_model_config, dump_config
from paddle_tpu.config.optimizers import build_optimizer, settings

__all__ = [
    "ParsedConfig", "parse_config", "parse_config_and_serialize",
    "get_config_arg", "outputs", "settings", "build_optimizer",
    "build_model_config", "dump_config",
]
