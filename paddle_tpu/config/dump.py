"""Layer graph → ModelConfig emission (dump_config parity).

The reference's config_parser builds the protobuf as the DSL executes, doing
shape inference per @config_layer class. Here the graph nodes already carry
full shape-inference logic in their `forward`, so the emitter traces the
network once on a synthetic batch (Topology.sample_batch) and reads every
layer's concrete output shape and created parameters — one source of truth
instead of two (python/paddle/utils/dump_config.py, config_parser.py:4208).

Emission is typed against the reference field set (proto/ModelConfig.proto:347
LayerConfig and the per-input sub-confs at :319) so the output structurally
diffs against the reference's 51 golden protostrs
(trainer_config_helpers/tests/configs/protostr/ — see config/protostr.py).
Geometry conventions follow the reference: x = width, y = height; image
tensors here are NHWC, so input shape [B, H, W, C] maps to
img_size_y=H, img_size=W, channels=C.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from paddle_tpu import proto
from paddle_tpu.nn.graph import Argument, Context, Layer, Network
from paddle_tpu.v2.topology import Topology

# our registry name → the reference's REGISTER_LAYER wire name, where they
# differ (gserver/layers/*.cpp registrations)
_TYPE_ALIAS = {
    "conv": "exconv",
    "conv_transpose": "exconvt",
    "cos_sim": "cos",
    "smooth_l1_cost": "smooth_l1",
    "lrn": "norm",
    "outer_prod": "out_prod",
    "last_seq": "seqlastins",
    "first_seq": "seqlastins",
    "feature_map_expand": "featmap_expand",
    "seq_concat": "seqconcat",
    "seq_reshape": "seqreshape",
    "classification_cost": "multi-class-cross-entropy",
    "grumemory": "gated_recurrent",
    "block_expand": "blockexpand",
    "square_error": "square_error",
    "rank_cost": "rank-cost",
    "huber_regression_cost": "huber_regression",
    "huber_classification_cost": "huber_classification",
    "cross_entropy": "multi-class-cross-entropy",
    "cross_entropy_with_selfnorm": "multi_class_cross_entropy_with_selfnorm",
    "soft_binary_class_cross_entropy": "soft_binary_class_cross_entropy",
    "step_arg_output": "get_output",
}

_SKIP_ATTRS = {
    "name", "type_name", "inputs", "cfg", "act", "param_attr", "bias_attr",
    "data_type", "rate", "core", "bias",
}


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1]), int(v[2])
    return int(v), int(v), int(v)


def _act_name(layer: Layer) -> str:
    a = getattr(layer, "act", None)
    if not isinstance(a, str) or a in ("linear", "identity"):
        return ""
    return a


def _geom(arg: Argument) -> Optional[Tuple[int, int, int, int]]:
    """(D, H, W, C) of an NHWC/NDHWC argument (D=1 for 2-D images)."""
    shape = arg.value.shape
    feat = shape[2:] if arg.is_seq else shape[1:]
    if len(feat) == 3:
        return 1, int(feat[0]), int(feat[1]), int(feat[2])
    if len(feat) == 4:
        return int(feat[0]), int(feat[1]), int(feat[2]), int(feat[3])
    return None


def _hw(arg: Argument) -> Optional[Tuple[int, int, int]]:
    """(H, W, C) of an NHWC argument ([B,H,W,C] or seq [B,T,H,W,C])."""
    g = _geom(arg)
    if g is None or g[0] != 1:
        return None
    return g[1], g[2], g[3]


def _image_conf(arg: Argument) -> Optional[proto.ImageConfig]:
    g = _geom(arg)
    if g is None:
        return None
    d, h, w, c = g
    ic = proto.ImageConfig(channels=c, img_size=w, img_size_y=h)
    if d != 1:
        ic.img_size_z = d
    return ic


# ---------------------------------------------------------------------------
# per-type typed emitters: fill LayerConfig fields + input sub-confs
# ---------------------------------------------------------------------------

_EMITTERS: Dict[str, Callable[[Layer, List[Argument], Argument, proto.LayerConfig], None]] = {}


def _emitter(*types: str):
    def deco(fn):
        for t in types:
            _EMITTERS[t] = fn
        return fn

    return deco


def _set_hw(lc: proto.LayerConfig, out: Argument) -> None:
    g = _geom(out)
    if g is not None:
        lc.height, lc.width = g[1], g[2]
        if g[0] != 1:
            lc.depth = g[0]


@_emitter("conv", "conv_transpose")
def _emit_conv(layer, ins, out, lc):
    kh, kw = _pair(layer.filter_size)
    sh, sw = _pair(layer.stride)
    pad = layer.padding
    ph, pw = _pair(pad) if not isinstance(pad, str) else (0, 0)
    dh, dw = _pair(getattr(layer, "dilation", 1))
    ihwc, ohwc = _hw(ins[0]), _hw(out)
    cin = ihwc[2] if ihwc else 0
    groups = getattr(layer, "groups", 1)
    trans = layer.type_name == "conv_transpose"
    cc = proto.ConvConfig(
        filter_size=kw, filter_size_y=kh,
        channels=cin,
        stride=sw, stride_y=sh,
        padding=pw, padding_y=ph,
        groups=groups,
        # exconvt describes the equivalent forward conv: filter_channels is
        # the conv's input-channel count = this deconv's num_filters
        filter_channels=(layer.num_filters if trans else cin) // max(groups, 1),
        caffe_mode=True,
    )
    if dh != 1 or dw != 1:
        cc.dilation, cc.dilation_y = dw, dh
    if trans:  # img/output swap likewise (parse_conv trans=True)
        if ohwc:
            cc.img_size, cc.img_size_y = ohwc[1], ohwc[0]
        if ihwc:
            cc.output_x, cc.output_y = ihwc[1], ihwc[0]
    else:
        if ihwc:
            cc.img_size, cc.img_size_y = ihwc[1], ihwc[0]
        if ohwc:
            cc.output_x, cc.output_y = ohwc[1], ohwc[0]
    lc.inputs[0].conv_conf = cc
    lc.num_filters = layer.num_filters
    lc.shared_biases = True
    _set_hw(lc, out)


@_emitter("conv3d", "deconv3d")
def _emit_conv3d(layer, ins, out, lc):
    fd, fh, fw = _triple(layer.filter_size)
    sd, sh, sw = _triple(layer.stride)
    pd, ph, pw = _triple(layer.padding)
    ig, og = _geom(ins[0]), _geom(out)
    trans = layer.type_name == "deconv3d"
    groups = getattr(layer, "groups", 1)
    cin = ig[3] if ig else 0
    cc = proto.ConvConfig(
        filter_size=fw, filter_size_y=fh, filter_size_z=fd,
        channels=cin,
        stride=sw, stride_y=sh, stride_z=sd,
        padding=pw, padding_y=ph, padding_z=pd,
        groups=groups,
        filter_channels=(layer.num_filters if trans else cin) // max(groups, 1),
        caffe_mode=True,
    )
    src, dst = (og, ig) if trans else (ig, og)
    if src:
        cc.img_size, cc.img_size_y, cc.img_size_z = src[2], src[1], src[0]
    if dst:
        cc.output_x, cc.output_y, cc.output_z = dst[2], dst[1], dst[0]
    lc.inputs[0].conv_conf = cc
    lc.num_filters = layer.num_filters
    lc.shared_biases = True
    _set_hw(lc, out)


@_emitter("pool3d")
def _emit_pool3d(layer, ins, out, lc):
    fd, fh, fw = _triple(layer.pool_size)
    sd, sh, sw = _triple(layer.stride if layer.stride is not None else layer.pool_size)
    pd, ph, pw = _triple(getattr(layer, "padding", 0))
    ig, og = _geom(ins[0]), _geom(out)
    pc = proto.PoolConfig(
        pool_type=f"{layer.pool_type}-projection",
        channels=ig[3] if ig else 0,
        size_x=fw, size_y=fh, size_z=fd,
        stride=sw, stride_y=sh, stride_z=sd,
        padding=pw, padding_y=ph, padding_z=pd,
    )
    if ig:
        pc.img_size, pc.img_size_y, pc.img_size_z = ig[2], ig[1], ig[0]
    if og:
        pc.output_x, pc.output_y, pc.output_z = og[2], og[1], og[0]
    lc.inputs[0].pool_conf = pc
    _set_hw(lc, out)


@_emitter("pool")
def _emit_pool(layer, ins, out, lc):
    kh, kw = _pair(layer.pool_size)
    sh, sw = _pair(layer.stride if layer.stride is not None else layer.pool_size)
    ph, pw = _pair(layer.padding)
    ihwc, ohwc = _hw(ins[0]), _hw(out)
    pc = proto.PoolConfig(
        pool_type=f"{layer.pool_type}-projection",
        channels=ihwc[2] if ihwc else 0,
        size_x=kw, size_y=kh,
        stride=sw, stride_y=sh,
        padding=pw, padding_y=ph,
    )
    if ihwc:
        pc.img_size, pc.img_size_y = ihwc[1], ihwc[0]
    if ohwc:
        pc.output_x, pc.output_y = ohwc[1], ohwc[0]
    lc.inputs[0].pool_conf = pc
    _set_hw(lc, out)


@_emitter("batch_norm")
def _emit_bn(layer, ins, out, lc):
    ic = _image_conf(ins[0])
    if ic is None:
        feat = ins[0].value.shape[1:]
        ic = proto.ImageConfig(channels=int(feat[-1]) if feat else 1, img_size=1, img_size_y=1)
    lc.inputs[0].image_conf = ic
    lc.moving_average_fraction = getattr(layer, "maf", 0.9)
    ugs = getattr(layer, "use_global_stats", None)
    if ugs is not None:
        lc.use_global_stats = bool(ugs)
    _set_hw(lc, out)


@_emitter("sampling_id")
def _emit_sampling_id(layer, ins, out, lc):
    # SamplingIdLayer keeps its input's size in the config even though the
    # forward emits one sampled id per row (SamplingIdLayer.cpp)
    feat = ins[0].value.shape[1:]
    lc.size = int(np.prod(feat)) if feat else 1


@_emitter("lrn")
def _emit_norm(layer, ins, out, lc):
    ihwc = _hw(ins[0])
    size = getattr(layer, "size", 0)
    nc = proto.NormConfig(
        norm_type="cmrnorm-projection",
        channels=ihwc[2] if ihwc else 0,
        size=size,
        # config_parser stores scale/size (parse_norm); the kernel multiplies
        # by the window sum so the product is the user's scale
        scale=getattr(layer, "scale", 0.0) / max(size, 1),
        pow=getattr(layer, "power", 0.0),
        blocked=False,
    )
    if ihwc:
        nc.img_size, nc.img_size_y = ihwc[1], ihwc[0]
        nc.output_x, nc.output_y = ihwc[1], ihwc[0]
    lc.inputs[0].norm_conf = nc
    _set_hw(lc, out)


@_emitter("clip")
def _emit_clip(layer, ins, out, lc):
    lc.inputs[0].clip_conf = proto.ClipConfig(
        min=getattr(layer, "lo", 0.0), max=getattr(layer, "hi", 0.0)
    )


@_emitter("pad")
def _emit_pad(layer, ins, out, lc):
    ic = _image_conf(ins[0])
    pc = proto.PadConfig(image_conf=ic)
    pad_c = getattr(layer, "pad_c", None)
    pad_h = getattr(layer, "pad_h", None)
    pad_w = getattr(layer, "pad_w", None)
    if pad_c is not None:
        pc.pad_c = list(pad_c)
    if pad_h is not None:
        pc.pad_h = list(pad_h)
    if pad_w is not None:
        pc.pad_w = list(pad_w)
    lc.inputs[0].pad_conf = pc
    _set_hw(lc, out)


@_emitter("maxout")
def _emit_maxout(layer, ins, out, lc):
    lc.inputs[0].maxout_conf = proto.MaxOutConfig(
        image_conf=_image_conf(ins[0]), groups=getattr(layer, "groups", 0)
    )
    _set_hw(lc, out)


@_emitter("spp")
def _emit_spp(layer, ins, out, lc):
    lc.inputs[0].spp_conf = proto.SppConfig(
        image_conf=_image_conf(ins[0]),
        pool_type=f"{getattr(layer, 'pool_type', 'max')}-projection",
        pyramid_height=getattr(layer, "pyramid_height", 0),
    )


@_emitter("bilinear_interp")
def _emit_bilinear(layer, ins, out, lc):
    ohwc = _hw(out)
    lc.inputs[0].bilinear_interp_conf = proto.BilinearInterpConfig(
        image_conf=_image_conf(ins[0]),
        out_size_x=ohwc[1] if ohwc else 0,
        out_size_y=ohwc[0] if ohwc else 0,
    )
    _set_hw(lc, out)


@_emitter("row_conv")
def _emit_row_conv(layer, ins, out, lc):
    lc.inputs[0].row_conv_conf = proto.RowConvConfig(
        context_length=getattr(layer, "context_length", None)
        or getattr(layer, "context_len", 0)
    )


@_emitter("block_expand")
def _emit_block_expand(layer, ins, out, lc):
    ihwc = _hw(ins[0])
    by, bx = _pair(getattr(layer, "block", (0, 0)))  # stored (y, x)
    sy, sx = _pair(getattr(layer, "stride", (1, 1)))
    py, px = _pair(getattr(layer, "padding", (0, 0)))
    bc = proto.BlockExpandConfig(
        channels=ihwc[2] if ihwc else 0,
        block_x=bx, block_y=by,
        stride_x=sx, stride_y=sy,
        padding_x=px, padding_y=py,
    )
    # img_size_x/y and output_x/y intentionally omitted: the reference
    # leaves them 0 at parse time (computed by the runtime kernel)
    lc.inputs[0].block_expand_conf = bc


@_emitter("multibox_loss")
def _emit_multibox(layer, ins, out, lc):
    lc.size = 1
    lc.inputs[0].multibox_loss_conf = proto.MultiBoxLossConfig(
        num_classes=layer.num_classes,
        overlap_threshold=layer.overlap_threshold,
        neg_pos_ratio=layer.neg_pos_ratio,
        neg_overlap=getattr(layer, "neg_overlap", 0.5),
        background_id=layer.background_id,
        input_num=layer.n_heads,
    )


@_emitter("detection_output")
def _emit_detection_output(layer, ins, out, lc):
    lc.inputs[0].detection_output_conf = proto.DetectionOutputConfig(
        num_classes=layer.num_classes,
        nms_threshold=layer.nms_threshold,
        nms_top_k=layer.nms_top_k,
        background_id=layer.background_id,
        input_num=layer.n_heads,
        keep_top_k=layer.keep_top_k,
        confidence_threshold=layer.confidence_threshold,
    )


@_emitter("dropout")
def _emit_dropout(layer, ins, out, lc):
    lc.drop_rate = getattr(layer, "rate", None)


@_emitter("embedding")
def _emit_embedding(layer, ins, out, lc):
    # the reference's embedding_layer is a mixed + table projection
    # (layers.py embedding_layer → mixed_layer(table_projection))
    lc.type = "mixed"
    vocab = getattr(layer, "vocab_size", None)
    if not vocab:
        src = layer.inputs[0]
        spec = getattr(src, "data_type", None)
        vocab = int(spec.dim) if spec is not None and spec.dim else 0
    lc.inputs[0].proj_conf = proto.ProjectionConfig(
        type="table", name=None, input_size=vocab or 0,
        output_size=getattr(layer, "size", lc.size),
    )


@_emitter("last_seq", "first_seq")
def _emit_seq_ins(layer, ins, out, lc):
    lc.select_first = layer.type_name == "first_seq"
    lc.trans_type = getattr(layer, "agg_level", None) or "non-seq"
    lc.seq_pool_stride = getattr(layer, "stride", -1) or -1


@_emitter("recurrent")
def _emit_recurrent(layer, ins, out, lc):
    lc.reversed = bool(getattr(layer, "reverse", False))


@_emitter("lstmemory")
def _emit_lstm(layer, ins, out, lc):
    lc.reversed = bool(getattr(layer, "reverse", False))
    lc.active_gate_type = getattr(layer, "gate_act", "sigmoid")
    lc.active_state_type = getattr(layer, "state_act", "tanh")


@_emitter("gated_recurrent")
def _emit_gru(layer, ins, out, lc):
    lc.reversed = bool(getattr(layer, "reverse", False))
    lc.active_gate_type = getattr(layer, "gate_act", "sigmoid")


@_emitter("crop")
def _emit_crop(layer, ins, out, lc):
    lc.axis = getattr(layer, "axis", 2)
    off = getattr(layer, "offset", None)
    shp = getattr(layer, "crop_shape", None) or getattr(layer, "shape_arg", None)
    if off:
        lc.offset = list(off)
    if shp:
        lc.shape = list(shp)


@_emitter("prelu")
def _emit_prelu(layer, ins, out, lc):
    lc.partial_sum = getattr(layer, "partial_sum", 1)


@_emitter("slope_intercept")
def _emit_slope(layer, ins, out, lc):
    lc.slope = getattr(layer, "slope", 1.0)
    lc.intercept = getattr(layer, "intercept", 0.0)


@_emitter("cos_sim", "cos_vm")
def _emit_cos(layer, ins, out, lc):
    lc.cos_scale = getattr(layer, "scale", 1.0)


@_emitter("crf", "crf_decoding")
def _emit_crf(layer, ins, out, lc):
    if getattr(layer, "size", None):
        lc.size = layer.size


@_emitter("ctc", "warp_ctc")
def _emit_ctc(layer, ins, out, lc):
    lc.norm_by_times = bool(getattr(layer, "norm_by_times", False))
    lc.blank = getattr(layer, "blank", 0)
    if getattr(layer, "size", None):
        lc.size = layer.size


@_emitter("nce")
def _emit_nce(layer, ins, out, lc):
    lc.num_classes = getattr(layer, "num_classes", None)
    lc.num_neg_samples = getattr(layer, "num_neg_samples", 10)
    lc.active_type = "sigmoid"  # NCELayer's fixed activation


@_emitter("hsigmoid")
def _emit_hsigmoid(layer, ins, out, lc):
    lc.num_classes = getattr(layer, "num_classes", None)


@_emitter("expand")
def _emit_expand(layer, ins, out, lc):
    lc.trans_type = getattr(layer, "expand_level", "non-seq")


@_emitter("seq_pool", "global_pool")
def _emit_seqpool(layer, ins, out, lc):
    lc.trans_type = getattr(layer, "agg_level", None) or "non-seq"
    lc.seq_pool_stride = getattr(layer, "stride", -1) or -1
    if getattr(layer, "output_max_index", None):
        lc.output_max_index = True
    # MaxLayer is its own type; everything else is AverageLayer + strategy
    pt = getattr(layer, "pool_type", "sum")
    if pt == "max":
        lc.type = "max"
    else:
        lc.type = "average"
        lc.average_strategy = {
            "avg": "average", "average": "average", "sum": "sum",
            "sqrt": "squarerootn",
        }.get(pt, pt)


_PROJ_TYPES = {
    "FullMatrix": "fc",
    "TransposedFullMatrix": "trans_fc",
    "Identity": "identity",
    "DotMul": "dot_mul",
    "Scaling": "scaling",
    "Table": "table",
    "Context_": "context",
    "ConvProj": "conv",
}


@_emitter("mixed", "concat2")
def _emit_mixed(layer, ins, out, lc):
    out_feat = out.value.shape[2:] if out.is_seq else out.value.shape[1:]
    out_size = int(np.prod(out_feat)) if out_feat else 1
    slot_lists = getattr(
        layer, "_arg_slots",
        None,
    )
    if slot_lists is None:  # concat2 keeps plain sequential slots
        slot_lists, pos = [], 0
        for proj in getattr(layer, "projections", []):
            slot_lists.append(list(range(pos, pos + len(proj.sources))))
            pos += len(proj.sources)
    for proj, slots in zip(getattr(layer, "projections", []), slot_lists):
        arg = ins[slots[0]]
        lic = lc.inputs[slots[0]]
        cls = type(proj).__name__
        ptype = _PROJ_TYPES.get(cls)
        if ptype == "conv" and getattr(proj, "trans", False):
            ptype = "convt"
        if ptype is None:
            if cls in ("DotMulOperator", "ConvOperator"):
                oc = proto.OperatorConfig(
                    type="dot_mul" if cls == "DotMulOperator" else "conv",
                    input_indices=list(slots),
                    output_size=out_size,
                )
                if cls == "ConvOperator":
                    oc.num_filters = proj.num_filters
                lc.operator_confs.append(oc)
            continue
        feat = arg.value.shape[2:] if arg.is_seq else arg.value.shape[1:]
        in_size = int(np.prod(feat)) if feat else 1
        if ptype == "table":  # input is ids; input_size is the vocab
            in_size = getattr(proj, "vocab_size", None) or in_size
        psize = out_size
        if layer.type_name == "concat2" or ptype == "identity":
            psize = in_size  # each projection contributes its own width
        pc = proto.ProjectionConfig(
            type=ptype, name=None, input_size=in_size, output_size=psize
        )
        if ptype == "context":
            pc.context_start = getattr(proj, "context_start", None)
            pc.context_length = getattr(proj, "context_length", None)
        lic.proj_conf = pc


_COST_TYPES = {
    "multi-class-cross-entropy", "mse", "square_error", "rank-cost",
    "lambda_cost", "sum_cost", "huber_regression", "huber_classification",
    "smooth_l1_cost", "multi_binary_label_cross_entropy", "cross_entropy",
    "soft_binary_class_cross_entropy", "cross_entropy_with_selfnorm",
}


# ---------------------------------------------------------------------------


def _layer_attrs(layer: Layer, consumed: set) -> Dict[str, object]:
    """Scalar/int-tuple hyperparameters with no typed field (kept under
    `attrs`, emitted as repeated scalars)."""
    out: Dict[str, object] = {}
    for k, v in sorted(vars(layer).items()):
        if k.startswith("_") or k in _SKIP_ATTRS or k in consumed:
            continue
        if isinstance(v, (bool, int, float, str)):
            out[k] = v
        elif isinstance(v, (tuple, list)) and v and all(
            isinstance(x, (int, float)) for x in v
        ):
            out[k] = list(v)
    return out


def _v1_size_of(layer: Layer) -> int:
    s = getattr(layer, "_v1_size", None)
    if s:
        return int(s)
    shape = getattr(layer, "shape", None)
    if shape:
        n = 1
        for d in shape:
            n *= int(d)
        return n
    sz = getattr(layer, "size", None)
    if isinstance(sz, int):
        return sz
    return 0


def _emit_recurrent_group(layer, mc, by_layer, alias, seen_cores) -> None:
    """Expand a RecurrentGroup node the way config_parser's
    RecurrentLayerGroup{Begin,End} do: a `recurrent_layer_group` marker, one
    scatter_agent per in-link, one `+delay1` agent per memory, the step net's
    layers suffixed `@{group}`, and a gather_agent per step output exposed
    under the step layer's own name. The group node itself aliases to its
    output's gather agent, so downstream inputs read like the reference."""
    from paddle_tpu.nn.recurrent_group import MemoryLayer, _Placeholder

    core = layer.core
    group = None
    # the group marker carries the *group* name; our nodes carry it directly
    group = layer.name
    out_layer = core.out_layers[layer.out_index]
    alias[layer.name] = out_layer.name
    if id(core) in seen_cores:
        return
    seen_cores[id(core)] = group

    mc.layers.append(
        proto.LayerConfig(name=group, type="recurrent_layer_group")
    )
    sub = proto.SubModelConfig(
        name=group, is_recurrent_layer_group=True,
        reversed=bool(core.reverse),
    )
    sub.layer_names.append(group)

    def in_group(n: str) -> str:
        return f"{n}@{group}"

    ph_names: Dict[str, str] = {}
    for ph in core.placeholders:
        src = getattr(ph, "src_layer", None)
        if src is None:
            continue
        agent = in_group(src.name)
        ph_names[ph.name] = agent
        mc.layers.append(
            proto.LayerConfig(
                name=agent, type="scatter_agent", size=_v1_size_of(ph)
            )
        )
        sub.layer_names.append(agent)
        sub.in_links.append(
            proto.LinkConfig(layer_name=agent, link_name=src.name)
        )
    for m in core.memories:
        link = core.links[m.name]
        # named memories surface as "{name}+delay1"; anonymous ones keep
        # their auto "__memory_N__" name (config_parser Memory naming)
        if getattr(m, "user_named", True):
            agent = f"{link.name}+delay1@{group}"
        else:
            agent = in_group(m.name)
        ph_names[m.name] = agent
        mc.layers.append(
            proto.LayerConfig(name=agent, type="agent", size=m.size or 0)
        )
        sub.layer_names.append(agent)
        memc = proto.MemoryConfig(
            link_name=in_group(link.name), layer_name=agent
        )
        if m.boot_layer is not None:
            memc.boot_layer_name = m.boot_layer.name
        sub.memories.append(memc)

    for step_l in core.order:
        if isinstance(step_l, (_Placeholder, MemoryLayer)):
            continue
        lc = proto.LayerConfig(
            name=in_group(step_l.name),
            type=_TYPE_ALIAS.get(step_l.type_name, step_l.type_name),
            size=_v1_size_of(step_l),
            active_type=_act_name(step_l),
        )
        owned = dict(by_layer.get(step_l.name, {}))
        for bias_key in ("b", "bias"):
            if bias_key in owned:
                lc.bias_parameter_name = owned.pop(bias_key)
                break
        weight_names = sorted(owned.values())
        for i, inp in enumerate(step_l.inputs):
            lic = proto.LayerInputConfig(
                input_layer_name=ph_names.get(inp.name, in_group(inp.name))
            )
            if i < len(weight_names):
                lic.input_parameter_name = weight_names[i]
            lc.inputs.append(lic)
        # typed sub-confs from annotations (no traced values in-group)
        if step_l.type_name in ("mixed", "concat2"):
            _emit_ingroup_mixed(step_l, lc, ph_names, group)
        mc.layers.append(lc)
        sub.layer_names.append(in_group(step_l.name))

    for out_l in core.out_layers:
        mc.layers.append(
            proto.LayerConfig(
                name=out_l.name, type="gather_agent", size=_v1_size_of(out_l)
            )
        )
        sub.layer_names.append(out_l.name)
        sub.out_links.append(
            proto.LinkConfig(layer_name=in_group(out_l.name), link_name=out_l.name)
        )
    mc.sub_models.append(sub)


def _emit_ingroup_mixed(step_l, lc, ph_names, group) -> None:
    slot_lists = getattr(step_l, "_arg_slots", [])
    out_size = _v1_size_of(step_l)
    for proj, slots in zip(getattr(step_l, "projections", []), slot_lists):
        cls = type(proj).__name__
        ptype = _PROJ_TYPES.get(cls)
        if ptype is None:
            continue
        src = proj.sources[0]
        in_size = _v1_size_of(src)
        if ptype == "identity" and not in_size:
            in_size = out_size
        lc.inputs[slots[0]].proj_conf = proto.ProjectionConfig(
            type=ptype, name=None,
            input_size=in_size,
            output_size=in_size if ptype == "identity" else out_size,
        )


def build_model_config(
    topology: Union[Topology, Layer, Sequence[Layer]],
    batch_size: int = 2,
    seq_len: int = 8,
) -> proto.ModelConfig:
    if not isinstance(topology, Topology):
        topology = Topology(topology)
    net = topology.network

    import jax

    ctx = Context("init", {}, {}, jax.random.PRNGKey(0), train=False)
    values = net._run(ctx, topology.sample_batch(batch_size, seq_len))

    # group created parameters by owning layer; Context.param records the
    # (layer, slot) → parameter-name binding, which survives sharing via
    # ParamAttr.name (a shared global name binds to every consuming layer)
    by_layer: Dict[str, Dict[str, str]] = {}
    for (lname, pname), full in getattr(ctx, "param_owners", {}).items():
        by_layer.setdefault(lname, {})[pname] = full

    mc = proto.ModelConfig()
    # ExtraAttr drop_rate chains an explicit "{x}.drop" Dropout node here;
    # the reference folds it into the wrapped layer's drop_rate field —
    # merge on emission so configs read like the originals
    alias: Dict[str, str] = {}
    lc_by_name: Dict[str, proto.LayerConfig] = {}
    seen_cores: Dict[int, str] = {}
    for layer in net.layer_order:
        if hasattr(layer, "core") and layer.type_name == "recurrent_layer_group":
            _emit_recurrent_group(layer, mc, by_layer, alias, seen_cores)
            continue
        if (
            layer.type_name == "dropout"
            and layer.name.endswith(".drop")
            and len(layer.inputs) == 1
            and alias.get(layer.inputs[0].name, layer.inputs[0].name) in lc_by_name
        ):
            parent = alias.get(layer.inputs[0].name, layer.inputs[0].name)
            lc_by_name[parent].drop_rate = getattr(layer, "rate", None)
            alias[layer.name] = parent
            continue
        if (
            layer.type_name == "error_clip"
            and layer.name.endswith(".eclip")
            and len(layer.inputs) == 1
            and layer.inputs[0].name in lc_by_name
        ):
            lc_by_name[layer.inputs[0].name].error_clipping_threshold = (
                layer.threshold
            )
            alias[layer.name] = layer.inputs[0].name
            continue
        arg = values[layer.name]
        shape = tuple(int(d) for d in arg.value.shape)
        if arg.is_seq and arg.sub_lengths is not None and len(shape) > 3:
            feat = shape[3:]  # nested [B, S, T, ...]
        elif arg.is_seq:
            feat = shape[2:]
        else:
            feat = shape[1:]
        size = int(np.prod(feat)) if feat else 1

        lc = proto.LayerConfig(
            name=layer.name,
            type=_TYPE_ALIAS.get(layer.type_name, layer.type_name),
            size=size,
            active_type=_act_name(layer),
        )
        lc_by_name[layer.name] = lc
        owned = by_layer.get(layer.name, {})
        for bias_key in ("b", "bias"):  # batch_norm names its beta "bias"
            if bias_key in owned:
                lc.bias_parameter_name = owned.pop(bias_key)
                break
        weight_names = sorted(owned.values())
        in_args: List[Argument] = []
        for i, inp in enumerate(layer.inputs):
            lic = proto.LayerInputConfig(
                input_layer_name=alias.get(inp.name, inp.name)
            )
            if i < len(weight_names):
                lic.input_parameter_name = weight_names[i]
            lc.inputs.append(lic)
            in_args.append(values[inp.name])
        if layer.type_name in _COST_TYPES or layer.type_name.endswith("cost"):
            lc.coeff = getattr(layer, "coeff", 1.0)
        emitter = _EMITTERS.get(layer.type_name)
        if emitter is not None and lc.inputs:
            emitter(layer, in_args, arg, lc)
        # remaining layer-specific scalars with no reference field
        consumed = _emitted_attr_names(layer.type_name)
        lc.attrs = _layer_attrs(layer, consumed)
        mc.layers.append(lc)

        if layer.type_name == "data":
            mc.input_layer_names.append(layer.name)
            _set_hw(lc, arg)
            spec = getattr(layer, "data_type", None)
            if spec is not None and spec.kind.startswith("index") and spec.dim:
                lc.size = int(spec.dim)  # id slots keep their declared range
            # v1 data slots are flat; declared image geometry rides on the node
            g3 = getattr(layer, "_v1_geom3d", None)
            g2 = getattr(layer, "_v1_geom", None)
            if g3 is not None:
                _, lc.depth, lc.height, lc.width = g3
            elif g2 is not None and lc.height is None:
                _, lc.height, lc.width = g2

    declared = getattr(topology, "declared_outputs", None)
    mc.output_layer_names = [
        alias.get(l.name, l.name) for l in (declared or net.outputs)
    ]
    mc.sub_models.append(
        proto.SubModelConfig(
            name="root",
            layer_names=[l.name for l in net.layer_order],
            input_layer_names=list(mc.input_layer_names),
            output_layer_names=list(mc.output_layer_names),
            is_recurrent_layer_group=False,
        )
    )

    for full, value in ctx.params.items():
        attr = ctx.param_attrs.get(full)
        pc = proto.ParameterConfig(
            name=full,
            size=int(np.prod(value.shape)),
            dims=[int(d) for d in value.shape],
        )
        if attr is not None:
            pc.learning_rate = attr.learning_rate
            pc.momentum = attr.momentum
            pc.decay_rate = attr.l2_decay
            pc.decay_rate_l1 = attr.l1_decay
            pc.initial_mean = attr.initial_mean
            pc.initial_std = attr.initial_std
            pc.is_static = attr.is_static
            pc.is_sparse = attr.is_sparse
            pc.gradient_clipping_threshold = attr.gradient_clipping_threshold
            if attr.sharding:
                pc.sharding = [a or "" for a in attr.sharding]
        mc.parameters.append(pc)
    return mc


# attr names consumed by each typed emitter (kept out of the attrs block so
# the same fact is not emitted twice)
_EMITTED_ATTRS = {
    "conv": {"filter_size", "stride", "padding", "dilation", "groups", "num_filters"},
    "conv_transpose": {"filter_size", "stride", "padding", "dilation", "groups", "num_filters"},
    "pool": {"pool_size", "pool_type", "stride", "padding", "ceil_mode"},
    "batch_norm": {"maf", "use_global_stats", "epsilon"},
    "lrn": {"size", "scale", "power"},
    "clip": {"lo", "hi"},
    "pad": {"pad_c", "pad_h", "pad_w"},
    "maxout": {"groups"},
    "spp": {"pool_type", "pyramid_height"},
    "row_conv": {"context_length"},
    "block_expand": {"block", "stride", "padding"},
    "dropout": {"rate"},
    "last_seq": {"stride"},
    "first_seq": {"stride"},
    "recurrent": {"reverse"},
    "lstmemory": {"reverse", "gate_act", "state_act"},
    "gated_recurrent": {"reverse", "gate_act"},
    "crop": {"axis", "offset", "crop_shape", "shape_arg"},
    "prelu": {"partial_sum"},
    "slope_intercept": {"slope", "intercept"},
    "cos_sim": {"scale"},
    "cos_vm": {"scale"},
    "ctc": {"norm_by_times", "blank"},
    "warp_ctc": {"norm_by_times", "blank"},
    "nce": {"num_classes", "num_neg_samples"},
    "hsigmoid": {"num_classes"},
    "expand": {"expand_level"},
    "seq_pool": {"agg_level", "pool_type"},
    "global_pool": {"agg_level", "pool_type"},
}


def _emitted_attr_names(type_name: str) -> set:
    return _EMITTED_ATTRS.get(type_name, set())


def dump_config(
    topology: Union[Topology, Layer, Sequence[Layer]],
    batch_size: int = 2,
    seq_len: int = 8,
) -> str:
    """Text-format ModelConfig (python/paddle/utils/dump_config.py parity)."""
    return proto.to_text(build_model_config(topology, batch_size, seq_len))
