"""Layer graph → ModelConfig emission (dump_config parity).

The reference's config_parser builds the protobuf as the DSL executes, doing
shape inference per @config_layer class. Here the graph nodes already carry
full shape-inference logic in their `forward`, so the emitter simply traces
the network once on a synthetic batch (Topology.sample_batch) and reads every
layer's concrete output shape and created parameters — one source of truth
instead of two (python/paddle/utils/dump_config.py, config_parser.py:4208).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu import proto
from paddle_tpu.nn.graph import Context, Layer, Network
from paddle_tpu.v2.topology import Topology


_SKIP_ATTRS = {
    "name", "type_name", "inputs", "cfg", "act", "param_attr", "bias_attr",
    "data_type", "rate", "core",
}


def _scalar_attr(layer: Layer, *names: str):
    for n in names:
        v = getattr(layer, n, None)
        if isinstance(v, (str, int, float, bool)):
            return v
    return None


def _layer_attrs(layer: Layer) -> Dict[str, object]:
    """Scalar/int-tuple hyperparameters from the spec's instance attributes
    (layer constructors store e.g. filter_size/stride/padding as attributes)."""
    out: Dict[str, object] = {}
    for k, v in sorted(vars(layer).items()):
        if k.startswith("_") or k in _SKIP_ATTRS:
            continue
        if isinstance(v, (bool, int, float, str)):
            out[k] = v
        elif isinstance(v, (tuple, list)) and v and all(
            isinstance(x, (int, float)) for x in v
        ):
            out[k] = list(v)
    return out


def build_model_config(
    topology: Union[Topology, Layer, Sequence[Layer]],
    batch_size: int = 2,
    seq_len: int = 8,
) -> proto.ModelConfig:
    if not isinstance(topology, Topology):
        topology = Topology(topology)
    net = topology.network

    import jax

    ctx = Context("init", {}, {}, jax.random.PRNGKey(0), train=False)
    values = net._run(ctx, topology.sample_batch(batch_size, seq_len))

    # group created parameters by owning layer (Context.param names them
    # "{layer}.{pname}" unless shared via ParamAttr.name)
    by_layer: Dict[str, Dict[str, str]] = {}
    for full in ctx.params:
        if "." in full:
            lname, pname = full.rsplit(".", 1)
            by_layer.setdefault(lname, {})[pname] = full

    mc = proto.ModelConfig()
    for layer in net.layer_order:
        arg = values[layer.name]
        shape = tuple(int(d) for d in arg.value.shape)
        feat = shape[2:] if arg.is_seq else shape[1:]
        size = int(np.prod(feat)) if feat else 1

        lc = proto.LayerConfig(
            name=layer.name,
            type=layer.type_name,
            size=size,
            shape=list(feat),
            active_type=_scalar_attr(layer, "act"),
            drop_rate=_scalar_attr(layer, "rate", "dropout_rate"),
        )
        owned = by_layer.get(layer.name, {})
        if "b" in owned:
            lc.bias_parameter_name = owned.pop("b")
        weight_names = sorted(owned.values())
        for i, inp in enumerate(layer.inputs):
            lic = proto.LayerInputConfig(input_layer_name=inp.name)
            if i < len(weight_names):
                lic.input_parameter_name = weight_names[i]
            lc.inputs.append(lic)
        # layer-specific scalars (filter_size, stride, ...): introspected from
        # the spec's instance attributes — layer constructors store their
        # hyperparameters as plain attributes, not via cfg kwargs
        lc.attrs = _layer_attrs(layer)
        mc.layers.append(lc)

        if layer.type_name == "data":
            mc.input_layer_names.append(layer.name)

    mc.output_layer_names = [l.name for l in net.outputs]

    for full, value in ctx.params.items():
        attr = ctx.param_attrs.get(full)
        pc = proto.ParameterConfig(
            name=full,
            size=int(np.prod(value.shape)),
            dims=[int(d) for d in value.shape],
        )
        if attr is not None:
            pc.learning_rate = attr.learning_rate
            pc.momentum = attr.momentum
            pc.decay_rate = attr.l2_decay
            pc.decay_rate_l1 = attr.l1_decay
            pc.initial_mean = attr.initial_mean
            pc.initial_std = attr.initial_std
            pc.is_static = attr.is_static
            pc.is_sparse = attr.is_sparse
            pc.gradient_clipping_threshold = attr.gradient_clipping_threshold
            if attr.sharding:
                pc.sharding = [a or "" for a in attr.sharding]
        mc.parameters.append(pc)
    return mc


def dump_config(
    topology: Union[Topology, Layer, Sequence[Layer]],
    batch_size: int = 2,
    seq_len: int = 8,
) -> str:
    """Text-format ModelConfig (python/paddle/utils/dump_config.py parity)."""
    return proto.to_text(build_model_config(topology, batch_size, seq_len))
