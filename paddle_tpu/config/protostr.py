"""Golden-protostr interchange: parse protobuf text format and structurally
compare ModelConfigs.

The reference proves its config DSL against 51 golden protostr files
(python/paddle/trainer_config_helpers/tests/configs/protostr/, emitted by
generate_protostr.sh from the configs in the same dir). This module makes
that corpus consumable here: `parse_text_proto` reads a golden (or our own
`dump_config` output) into plain dicts, `summarize` reduces a ModelConfig
dict to its structural core, and `diff` reports discrepancies between a
reference summary and ours.

Structural equivalence, not byte equality: the graph here is TPU-native, so
a handful of systematic differences are *expected* and normalized instead of
flagged — documented on `diff` below.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


# ---------------------------------------------------------------------------
# protobuf text-format parser (subset: messages, repeated fields, scalars)
# ---------------------------------------------------------------------------

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<open>\{)
      | (?P<close>\})
      | (?P<name>[A-Za-z_][A-Za-z0-9_]*)\s*(?P<colon>:)?
      | (?P<string>"(?:\\.|[^"\\])*")
      | (?P<scalar>[^\s{}]+)
    )""",
    re.VERBOSE,
)


def _tokens(text: str):
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None or m.end() == pos:
            break
        pos = m.end()
        yield m


def _coerce(s: str) -> Any:
    if s.startswith('"'):
        return s[1:-1].encode().decode("unicode_escape")
    if s in ("true", "True"):
        return True
    if s in ("false", "False"):
        return False
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        return s


def parse_text_proto(text: str) -> Dict[str, Any]:
    """Parse protobuf text format into nested dicts. Every field becomes a
    LIST (canonical repeated form) so goldens and our dumps compare uniformly
    regardless of optional-vs-repeated declarations."""
    root: Dict[str, Any] = {}
    stack: List[Dict[str, Any]] = [root]
    pending: Optional[str] = None
    it = _tokens(text)
    for m in it:
        if m.group("open"):
            child: Dict[str, Any] = {}
            stack[-1].setdefault(pending, []).append(child)
            stack.append(child)
            pending = None
        elif m.group("close"):
            stack.pop()
            if not stack:
                raise ValueError("unbalanced braces in text proto")
        elif m.group("name"):
            name = m.group("name")
            if m.group("colon"):
                v = next(it)
                val = _coerce(v.group("string") or v.group("scalar") or v.group("name") or "")
                stack[-1].setdefault(name, []).append(val)
            else:
                pending = name  # message field: `name {` (brace next)
        elif m.group("string") or m.group("scalar"):
            raise ValueError(f"unexpected bare value {m.group(0)!r}")
    if len(stack) != 1:
        raise ValueError("unterminated message in text proto")
    return root


def _one(d: Dict[str, Any], key: str, default: Any = None) -> Any:
    v = d.get(key)
    return v[0] if v else default


# ---------------------------------------------------------------------------
# structural summary
# ---------------------------------------------------------------------------


@dataclass
class LayerSummary:
    name: str
    type: str
    size: int
    active_type: str
    inputs: List[str]
    input_params: List[Optional[str]]
    bias_param: Optional[str]
    # typed per-input sub-conf dicts we model (conv/pool/norm/image/proj/...)
    sub_confs: List[Dict[str, Any]] = field(default_factory=list)
    fields: Dict[str, Any] = field(default_factory=dict)  # scalar LayerConfig fields


@dataclass
class ModelSummary:
    layers: Dict[str, LayerSummary]
    layer_order: List[str]
    parameters: Dict[str, List[int]]  # name -> dims
    input_layer_names: List[str]
    output_layer_names: List[str]
    evaluators: List[Tuple[str, str, Tuple[str, ...]]] = field(default_factory=list)


_SCALAR_FIELDS = (
    # LayerConfig scalar fields we compare when both sides emit them
    "num_filters", "shared_biases", "drop_rate", "num_classes", "reversed",
    "active_gate_type", "active_state_type", "num_neg_samples",
    "output_max_index", "norm_by_times", "coeff", "average_strategy",
    "slope", "intercept", "cos_scale", "bos_id", "eos_id", "beam_size",
    "select_first", "trans_type", "use_global_stats",
    "moving_average_fraction", "bias_size", "height", "width", "blank",
    "seq_pool_stride", "axis", "delta", "depth", "group_name",
)

_SUBCONF_FIELDS = (
    "conv_conf", "pool_conf", "norm_conf", "image_conf", "proj_conf",
    "block_expand_conf", "bilinear_interp_conf", "maxout_conf", "spp_conf",
    "pad_conf", "row_conv_conf", "clip_conf", "multibox_loss_conf",
    "detection_output_conf",
)


def summarize(mc: Dict[str, Any]) -> ModelSummary:
    if "model_config" in mc and "layers" not in mc:
        mc = mc["model_config"][0]  # TrainerConfig dump: descend
    layers: Dict[str, LayerSummary] = {}
    order: List[str] = []
    for l in mc.get("layers", []):
        ins, ps, subs = [], [], []
        for i in l.get("inputs", []):
            ins.append(_one(i, "input_layer_name", ""))
            ps.append(_one(i, "input_parameter_name"))
            sc = {}
            for f in _SUBCONF_FIELDS:
                if f in i:
                    sc[f] = i[f][0]
            subs.append(sc)
        fields = {f: _one(l, f) for f in _SCALAR_FIELDS if f in l}
        ls = LayerSummary(
            name=_one(l, "name", ""),
            type=_one(l, "type", ""),
            size=int(_one(l, "size", 0) or 0),
            active_type=_one(l, "active_type", "") or "",
            inputs=ins,
            input_params=ps,
            bias_param=_one(l, "bias_parameter_name"),
            sub_confs=subs,
            fields=fields,
        )
        layers[ls.name] = ls
        order.append(ls.name)
    params = {}
    for p in mc.get("parameters", []):
        dims = [int(d) for d in p.get("dims", [])]
        if not dims and _one(p, "size") is not None:
            dims = [int(_one(p, "size"))]  # older goldens omit dims
        params[_one(p, "name", "")] = dims
    evals = [
        (
            _one(e, "name", ""),
            _one(e, "type", ""),
            tuple(e.get("input_layers", [])),
        )
        for e in mc.get("evaluators", [])
    ]
    return ModelSummary(
        layers=layers,
        layer_order=order,
        parameters=params,
        input_layer_names=list(mc.get("input_layer_names", [])),
        output_layer_names=list(mc.get("output_layer_names", [])),
        evaluators=evals,
    )


# ---------------------------------------------------------------------------
# structural diff
# ---------------------------------------------------------------------------

# our graph inserts explicit layout adapters where the reference's kernels
# work on flat CHW buffers implicitly; hopping through them is not a
# topology difference (v1_layers module docstring)
_ADAPTER_TYPES = {"reshape", "switch_order"}

# parameter-name convention: reference `_<layer>.w0` / `_<layer>.wbias`
# (config_parser.py Parameter naming) vs ours `<layer>.w.<i>` / `<layer>.b`
_REF_PARAM = re.compile(r"^_(?P<layer>.+)\.(?:w(?P<idx>\d+)|(?P<bias>wbias)|(?P<raw>w))$")


def normalize_ref_param(name: str) -> str:
    # in-group parameters carry the "@<group>" suffix on the owning layer
    # (RecurrentLayerGroup name mangling); our params use the plain step name
    name = re.sub(r"@[^.]+", "", name)
    m = _REF_PARAM.match(name)
    if m is None:
        return name
    if m.group("bias"):
        return f"{m.group('layer')}.b"
    if m.group("raw"):
        return f"{m.group('layer')}.w.0"
    return f"{m.group('layer')}.w.{m.group('idx')}"


def normalize_our_param(name: str) -> str:
    """Canonicalize this repo's parameter names to the same role form:
    `X.w` (single weight) → `X.w.0`; batch_norm's `X.scale` → `X.w.0`."""
    m = re.search(r"\.proj(\d+)\.(w|b)$", name)
    if m is not None:  # mixed-layer projection params ({owner}.projN.w)
        base = name[: m.start()]
        return f"{base}.w.{m.group(1)}" if m.group(2) == "w" else f"{base}.b"
    if name.endswith(".w_hzr"):  # GRU recurrent weight, z/r block
        return name[: -len(".w_hzr")] + ".w.0"
    if name.endswith(".w_hc"):  # GRU candidate block (fused into w0 in ref)
        return name[: -len(".w_hc")] + ".w.0.c"
    if name.endswith(".w_hh"):  # LSTM recurrent weight
        return name[: -len(".w_hh")] + ".w.0"
    if name.endswith(".w"):
        return name + ".0"
    if name.endswith(".scale"):
        return name[: -len(".scale")] + ".w.0"
    if name.endswith(".bias"):
        return name[: -len(".bias")] + ".b"
    return name


def _resolve_through_adapters(name: str, ours: ModelSummary) -> str:
    """Follow our single-input adapter layers back to their source so edges
    compare against the reference's flat topology."""
    seen = set()
    while name in ours.layers and name not in seen:
        seen.add(name)
        l = ours.layers[name]
        if l.type in _ADAPTER_TYPES and len(l.inputs) == 1:
            name = l.inputs[0]
        else:
            break
    return name


def diff(
    ref: ModelSummary,
    ours: ModelSummary,
    check_sizes: bool = True,
) -> List[str]:
    """Structural comparison; returns human-readable discrepancy lines
    (empty = structurally matching).

    Checked: every reference layer exists with the same type, size,
    active_type and input topology; parameter existence + dims;
    input/output_layer_names; scalar LayerConfig fields and per-input
    sub-confs (conv/pool/...) where both sides emit them.

    Normalized (expected, never flagged):
    - our extra reshape/switch_order layout adapters (edges resolve through
      them);
    - parameter naming convention (`_X.w0` → `X.w.0`, `_X.wbias` → `X.b`);
    - conv filter dims: reference stores flat [cin*kh*kw/groups * ...] rows,
      ours HWIO — compared by element count;
    - active_type "" vs "linear" (both mean identity).
    """
    errs: List[str] = []

    def act(a: str) -> str:
        return "" if a in ("linear", "identity") else a

    for name in ref.layer_order:
        rl = ref.layers[name]
        ol = ours.layers.get(name)
        if ol is None:
            errs.append(f"layer missing: {name} (type {rl.type})")
            continue
        if rl.type != ol.type:
            errs.append(f"layer {name}: type {ol.type!r} != ref {rl.type!r}")
        if check_sizes and rl.size and ol.size and rl.size != ol.size:
            errs.append(f"layer {name}: size {ol.size} != ref {rl.size}")
        if act(rl.active_type) != act(ol.active_type):
            errs.append(
                f"layer {name}: active_type {ol.active_type!r} != ref {rl.active_type!r}"
            )
        rins = [_resolve_through_adapters(i, ref) for i in rl.inputs]
        oins = [_resolve_through_adapters(i, ours) for i in ol.inputs]
        if rl.type == "batch_norm":
            # the reference threads the same input thrice (value + the two
            # static moving-stat parameter slots, BatchNormBaseLayer); the
            # moving stats here are functional state, not extra edges
            rins = rins[:1]
        if rins != oins:
            errs.append(f"layer {name}: inputs {oins} != ref {rins}")
        if (rl.bias_param is None) != (ol.bias_param is None):
            errs.append(
                f"layer {name}: bias {'present' if ol.bias_param else 'absent'}"
                f" != ref {'present' if rl.bias_param else 'absent'}"
            )
        for f, rv in rl.fields.items():
            ov = ol.fields.get(f)
            if ov is not None and ov != rv:
                errs.append(f"layer {name}: {f} {ov!r} != ref {rv!r}")
        for k, (rsc, osc) in enumerate(zip(rl.sub_confs, ol.sub_confs)):
            for cf, rcv in rsc.items():
                ocv = osc.get(cf)
                if ocv is None:
                    errs.append(f"layer {name} input {k}: missing {cf}")
                    continue
                for fk, fv in rcv.items():
                    if fk in ("caffe_mode",):  # impl detail of ref im2col
                        continue
                    v = ocv.get(fk)
                    if v is not None and v != fv:
                        errs.append(
                            f"layer {name} input {k} {cf}.{fk}: {v} != ref {fv}"
                        )

    def _count(dims: List[int]) -> int:
        n = 1
        for d in dims:
            n *= d
        return n

    def _owner_of(pname: str, summary: ModelSummary) -> Optional[str]:
        best = None
        for ln in summary.layers:
            if pname.startswith(ln + ".") and (best is None or len(ln) > len(best)):
                best = ln
        return best

    ref_params = {normalize_ref_param(n): d for n, d in ref.parameters.items()}
    our_params = {normalize_our_param(n): d for n, d in ours.parameters.items()}
    # recurrent memories factor their weights differently (one fused ref
    # matrix vs per-gate blocks here, RNN ops design) — compare per-layer
    # aggregate element counts instead of per-name
    _AGGREGATE_TYPES = {"lstmemory", "gated_recurrent", "recurrent"}
    # DeConv3DLayer allocates its weight by the forward-conv formula with
    # channels<->filters swapped (a reference-side layout quirk); element
    # counts legitimately differ from the math's k^3*cin*cout
    _SKIP_PARAM_TYPES = {"deconv3d"}
    agg_checked = set()
    for pname, rdims in ref_params.items():
        lname, _, role = pname.rpartition(".")
        lname = lname[:-2] if lname.endswith(".w") else lname
        owner = ref.layers.get(lname) or ref.layers.get(_owner_of(pname, ref) or "")
        if owner is not None and owner.type == "batch_norm" and pname.endswith(
            (".w.1", ".w.2")
        ):
            continue  # moving mean/var: functional state here, not parameters
        if owner is not None and owner.type in _SKIP_PARAM_TYPES:
            continue
        if owner is not None and owner.type in _AGGREGATE_TYPES:
            if owner.name in agg_checked:
                continue
            agg_checked.add(owner.name)
            rn = sum(
                _count(d)
                for n, d in ref_params.items()
                if _owner_of(n, ref) == owner.name
            )
            on = sum(
                _count(d)
                for n, d in our_params.items()
                if _owner_of(n, ours) == owner.name
            )
            if rn != on:
                errs.append(
                    f"layer {owner.name}: total parameter elements {on} != ref {rn}"
                )
            continue
        odims = our_params.get(pname)
        if odims is None:
            errs.append(f"parameter missing: {pname} (ref dims {rdims})")
            continue
        rn, on = _count(rdims), _count(odims)
        if rn != on and f"{pname}.c" in our_params:
            # shared GRU weights split [H,2H]+[H,H] here vs one fused [H,3H]
            # (nn/recurrent.py GruStep derives a ".c" sharing key)
            on += _count(our_params[f"{pname}.c"])
        if rn != on:
            errs.append(f"parameter {pname}: {on} elements != ref {rn} ({odims} vs {rdims})")
    # ref input names must all be declared here; extras on our side are fine
    # (the reference config_parser drops some auxiliary data slots, e.g.
    # seq_slice starts/ends, from input_layer_names)
    missing_inputs = set(ref.input_layer_names) - set(ours.input_layer_names)
    if missing_inputs:
        errs.append(
            f"input_layer_names missing {sorted(missing_inputs)} "
            f"(ours {sorted(ours.input_layer_names)})"
        )
    if sorted(ref.output_layer_names) != sorted(ours.output_layer_names):
        errs.append(
            f"output_layer_names {sorted(ours.output_layer_names)} != "
            f"ref {sorted(ref.output_layer_names)}"
        )
    for ev in ref.evaluators:
        if ev not in ours.evaluators:
            errs.append(f"evaluator missing: {ev}")
    return errs


def diff_files(golden_path: str, our_text: str) -> List[str]:
    with open(golden_path) as f:
        ref = summarize(parse_text_proto(f.read()))
    return diff(ref, summarize(parse_text_proto(our_text)))
