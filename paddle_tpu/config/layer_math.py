"""`layer_math` DSL namespace + arithmetic operators on graph layers
(trainer_config_helpers/layer_math.py): unary math as mixed layers with the
matching activation, and +,-,* overloads building slope_intercept / scaling /
identity-projection-sum subgraphs — so `1 + layer_math.exp(x) * z` in a config
script builds the same layer graph as the reference."""

from __future__ import annotations

from typing import Optional

from paddle_tpu.nn.graph import Layer, _auto_name

__all__ = []


def _helpers():
    from paddle_tpu.config import helpers

    return helpers


def _size_of(node: Layer) -> Optional[int]:
    from paddle_tpu.config.v1_layers import _size_of as sz

    return sz(node)


def _keep_size(node: Layer, src: Layer) -> Layer:
    s = _size_of(src)
    if s is not None:
        node._v1_size = s
    return node


def _unary(op_name: str, act_name: str):
    def op(input, name=None):
        h = _helpers()
        node = h.mixed_layer(
            input=[h.identity_projection(input=input)],
            name=name or _auto_name(op_name),
            act=act_name,
        )
        return _keep_size(node, input)

    op.__name__ = op_name
    globals()[op_name] = op
    __all__.append(op_name)


for _op, _act in (
    ("exp", "exponential"), ("log", "log"), ("abs", "abs"),
    ("sigmoid", "sigmoid"), ("tanh", "tanh"), ("square", "square"),
    ("relu", "relu"), ("sqrt", "sqrt"), ("reciprocal", "reciprocal"),
):
    _unary(_op, _act)


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def add(layer, other):
    h = _helpers()
    if _is_number(other):
        return _keep_size(
            h.slope_intercept_layer(input=layer, intercept=other), layer
        )
    if not isinstance(other, Layer):
        raise TypeError("a layer can only be added to another layer or a number")
    a, b = layer, other
    sa, sb = _size_of(a), _size_of(b)
    if sa != sb:
        if sb != 1 and sa != 1:
            raise ValueError(
                f"layer addition needs equal sizes or a size-1 side ({sa} vs {sb})"
            )
        if sa == 1:
            a, b, sa = b, a, sb
        b = _keep_size(h.repeat_layer(b, sa), a)
    return _keep_size(
        h.mixed_layer(
            input=[h.identity_projection(input=a), h.identity_projection(input=b)]
        ),
        a,
    )


def sub(layer, other):
    h = _helpers()
    if _is_number(other):
        # NOTE: reference layer_math.sub passes intercept=+other (its goldens
        # encode y-2 as intercept: 2); kept verbatim for config parity
        return _keep_size(
            h.slope_intercept_layer(input=layer, intercept=other), layer
        )
    if not isinstance(other, Layer):
        raise TypeError("a layer can only be subtracted by another layer or a number")
    return add(layer, _keep_size(
        h.slope_intercept_layer(input=other, slope=-1.0), other
    ))


def rsub(layer, other):
    h = _helpers()
    return add(_keep_size(
        h.slope_intercept_layer(input=layer, slope=-1.0), layer
    ), other)


def mul(layer, other):
    h = _helpers()
    if _is_number(other):
        return _keep_size(
            h.slope_intercept_layer(input=layer, slope=other), layer
        )
    if not isinstance(other, Layer):
        raise TypeError("a layer can only be multiplied by another layer or a number")
    if _size_of(layer) == 1:
        return _keep_size(h.scaling_layer(input=other, weight=layer), other)
    if _size_of(other) == 1:
        return _keep_size(h.scaling_layer(input=layer, weight=other), layer)
    raise ValueError("'*' needs a number or a size-1 layer on one side")


# the reference patches these straight onto LayerOutput; same move here
Layer.__add__ = add
Layer.__radd__ = add
Layer.__sub__ = sub
Layer.__rsub__ = rsub
Layer.__mul__ = mul
Layer.__rmul__ = mul

__all__ += ["add", "sub", "rsub", "mul"]
