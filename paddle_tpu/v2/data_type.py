"""paddle.v2.data_type analog — input type declarations used by layer.data.

Maps to the PyDataProvider2 input-type system
(python/paddle/trainer/PyDataProvider2.py:63-236) via paddle_tpu.data.feeder
InputSpec. Names follow the reference exactly so v2 scripts port verbatim.
"""

from __future__ import annotations

from paddle_tpu.data.feeder import (  # noqa: F401
    InputSpec,
    dense_array,
    dense_vector,
    dense_vector_sequence,
    integer_value,
    integer_value_sequence,
    sparse_binary_vector,
    sparse_value_slot,
)

# reference aliases (PyDataProvider2.py)
sparse_float_vector = sparse_value_slot
sparse_vector = sparse_value_slot


def sparse_binary_vector_sequence(dim: int) -> InputSpec:
    # padded [B, T, dim] sequence of multi-hot rows (feeder kind sparse_binary_seq)
    return InputSpec("sparse_binary_seq", dim)
