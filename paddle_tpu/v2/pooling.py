"""paddle.v2.pooling analog (trainer_config_helpers/poolings.py)."""

from __future__ import annotations


class BasePoolingType:
    name = "max"


class Max(BasePoolingType):
    """poolings.py MaxPooling; output_max_index mirrors MaxLayer's
    argmax-output mode (accepted; the index output is maxid semantics)."""

    name = "max"

    def __init__(self, output_max_index=None):
        self.output_max_index = output_max_index


class Avg(BasePoolingType):
    STRATEGY_AVG = "average"
    STRATEGY_SUM = "sum"
    STRATEGY_SQROOTN = "squarerootn"

    name = "avg"

    def __init__(self, strategy=STRATEGY_AVG):
        self.name = {"average": "avg", "sum": "sum", "squarerootn": "sqrt"}[strategy]


class Sum(Avg):
    name = "sum"

    def __init__(self):
        super().__init__(Avg.STRATEGY_SUM)


class SquareRootN(Avg):
    name = "sqrt"

    def __init__(self):
        super().__init__(Avg.STRATEGY_SQROOTN)


# cuDNN variants in the reference are just kernels for the same math
CudnnMax = Max
CudnnAvg = Avg


def resolve(p) -> str:
    if p is None:
        return "max"
    if isinstance(p, str):
        return p
    if isinstance(p, BasePoolingType) or (
        isinstance(p, type) and issubclass(p, BasePoolingType)
    ):
        return p.name
    raise TypeError(f"not a pooling type: {p!r}")
