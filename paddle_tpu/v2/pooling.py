"""paddle.v2.pooling analog (trainer_config_helpers/poolings.py)."""

from __future__ import annotations


class BasePoolingType:
    name = "max"


class Max(BasePoolingType):
    name = "max"


class Avg(BasePoolingType):
    name = "avg"


class Sum(BasePoolingType):
    name = "sum"


class SquareRootN(BasePoolingType):
    name = "sqrt"


# cuDNN variants in the reference are just kernels for the same math
CudnnMax = Max
CudnnAvg = Avg


def resolve(p) -> str:
    if p is None:
        return "max"
    if isinstance(p, str):
        return p
    if isinstance(p, BasePoolingType) or (
        isinstance(p, type) and issubclass(p, BasePoolingType)
    ):
        return p.name
    raise TypeError(f"not a pooling type: {p!r}")
