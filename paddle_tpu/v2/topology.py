"""paddle.v2.topology analog (python/paddle/v2/topology.py:27 Topology).

In the reference, Topology wraps the protobuf emitted by config_parser. Here
the layer DAG *is* the model config; Topology adds the v2 conveniences on top:
data-layer discovery (`data_layers`), the automatic feeding order, and a
serialized form (for inference.py / merge_model parity) produced by
paddle_tpu.config.dump when the graph came from the config DSL.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from paddle_tpu.data.feeder import DataFeeder, InputSpec
from paddle_tpu.nn.graph import Layer, Network


class Topology:
    def __init__(self, layers: Union[Layer, Sequence[Layer]], extra_layers: Sequence[Layer] = ()):
        if isinstance(layers, Layer):
            layers = [layers]
        # extra_layers ride along in the graph (reference: unused/print layers
        # stay in the config) but are not declared outputs
        self.declared_outputs: List[Layer] = list(layers)
        self.output_layers: List[Layer] = list(layers) + list(extra_layers)
        self.network = Network(self.output_layers)

    # -- data layers --------------------------------------------------------
    def data_layers(self) -> Dict[str, Layer]:
        """name → data Layer, in topological order (v2 Topology.data_layers)."""
        return {
            l.name: l
            for l in self.network.layer_order
            if l.type_name == "data"
        }

    def data_type(self) -> List:
        """[(name, InputSpec)] for layers built via v2.layer.data."""
        out = []
        for name, l in self.data_layers().items():
            spec = getattr(l, "data_type", None)
            if spec is None:
                spec = _infer_spec(l)
            out.append((name, spec))
        return out

    def get_layer(self, name: str) -> Layer:
        return self.network.layers_by_name[name]

    # -- feeding ------------------------------------------------------------
    def make_feeder(self, feeding: Optional[Dict[str, int]] = None) -> DataFeeder:
        """Build a DataFeeder whose column order follows `feeding`
        (name → sample-tuple index, the v2 convention) or data-layer order."""
        pairs = self.data_type()
        if feeding:
            names = {n for n, _ in pairs}
            unknown = set(feeding) - names
            if unknown:
                raise ValueError(f"feeding refers to unknown data layers: {unknown}")
            not_fed = names - set(feeding)
            if not_fed:
                raise ValueError(
                    f"feeding is missing required data layers: {sorted(not_fed)}"
                )
            pairs = sorted(pairs, key=lambda kv: feeding[kv[0]])
        return DataFeeder({n: s for n, s in pairs})

    # -- sample batch for shape-driven init ---------------------------------
    def sample_batch(self, batch_size: int = 2, seq_len: int = 8) -> Dict[str, np.ndarray]:
        batch: Dict[str, np.ndarray] = {}
        for name, l in self.data_layers().items():
            spec = getattr(l, "data_type", None)
            shape = tuple(l.shape)
            is_seq = getattr(l, "is_seq", False)
            if spec is not None and spec.kind in ("index", "index_seq"):
                hi = max(int(spec.dim), 2)
                if spec.kind == "index_seq":
                    batch[name] = np.zeros((batch_size, seq_len), np.int32)
                    batch[name + ".lengths"] = np.full((batch_size,), seq_len, np.int32)
                else:
                    batch[name] = np.zeros((batch_size,), np.int32)
                _ = hi
            elif spec is not None and spec.kind in ("dense_subseq", "index_subseq"):
                # subsequence count == seq_len so per-subsequence outputs
                # align with level-1 sequence slots in the same synthetic
                # batch (a seq label per subsequence is the common pairing)
                s_max = max(seq_len, 1)
                if spec.kind == "dense_subseq":
                    batch[name] = np.zeros(
                        (batch_size, s_max, seq_len) + shape, np.float32
                    )
                else:
                    batch[name] = np.zeros((batch_size, s_max, seq_len), np.int32)
                batch[name + ".lengths"] = np.full((batch_size,), s_max, np.int32)
                batch[name + ".sub_lengths"] = np.full(
                    (batch_size, s_max), seq_len, np.int32
                )
            elif is_seq:
                batch[name] = np.zeros((batch_size, seq_len) + shape, np.float32)
                batch[name + ".lengths"] = np.full((batch_size,), seq_len, np.int32)
            else:
                batch[name] = np.zeros((batch_size,) + shape, np.float32)
        return batch


def _infer_spec(l: Layer) -> InputSpec:
    shape = tuple(l.shape)
    if getattr(l, "is_seq", False):
        kind = "index_seq" if not shape else "dense_seq"
        return InputSpec(kind, shape or 0)
    if not shape:
        return InputSpec("index", 0, np.int32)
    return InputSpec("dense", shape if len(shape) > 1 else shape[0])
