"""paddle.v2.plot analog (python/paddle/v2/plot/plot.py Ploter): live cost
curves during training. Falls back to appending to an in-memory series when
matplotlib is unavailable or headless (the reference disables itself outside
notebooks via DISABLE_PLOT)."""

from __future__ import annotations

import os
from typing import Dict, List


class PlotData:
    def __init__(self):
        self.step: List[float] = []
        self.value: List[float] = []

    def append(self, step: float, value: float) -> None:
        self.step.append(step)
        self.value.append(value)

    def reset(self) -> None:
        self.step, self.value = [], []


class Ploter:
    def __init__(self, *args: str):
        self.titles = list(args)
        self.data: Dict[str, PlotData] = {t: PlotData() for t in args}
        self._disabled = bool(os.environ.get("DISABLE_PLOT"))
        self._plt = None
        if not self._disabled:
            try:
                import matplotlib

                # headless environments get Agg (save-only); interactive
                # sessions keep their backend so plot() can display live
                if not os.environ.get("DISPLAY") and not os.environ.get(
                    "MPLBACKEND"
                ):
                    matplotlib.use("Agg")
                import matplotlib.pyplot as plt

                self._plt = plt
            except Exception:
                self._plt = None

    def append(self, title: str, step: float, value: float) -> None:
        self.data[title].append(step, value)

    def plot(self, path: str = None) -> None:
        """Redraw; saves to `path`, or displays when interactive. Headless
        with no path is a no-op (nothing could be shown or kept)."""
        if self._plt is None:
            return
        plt = self._plt
        interactive = plt.get_backend().lower() != "agg"
        if path is None and not interactive:
            return
        plt.figure(figsize=(6, 4))
        for title in self.titles:
            d = self.data[title]
            if d.step:
                plt.plot(d.step, d.value, label=title)
        plt.legend()
        plt.xlabel("step")
        plt.ylabel("value")
        if path:
            plt.savefig(path)
        elif interactive:
            plt.show()
        plt.close()

    def reset(self) -> None:
        for d in self.data.values():
            d.reset()
