"""paddle.v2.event analog (python/paddle/v2/event.py:45-88)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from paddle_tpu.trainer.events import (  # noqa: F401
    BeginIteration,
    BeginPass,
    EndIteration,
    EndPass,
)


@dataclasses.dataclass
class TestResult:
    """Result of a test-period evaluation (v2/event.py TestResult)."""

    pass_id: int
    cost: float
    metrics: Optional[Dict[str, Any]] = None
