"""paddle.v2.activation analog (trainer_config_helpers/activations.py).

Each class is a lightweight tag whose ``name`` matches the registry key in
paddle_tpu.nn.activations (the ActivationFunction registry analog,
paddle/gserver/activations/ActivationFunction.cpp:40-63). Layer wrappers accept
either these tag instances or plain strings.
"""

from __future__ import annotations


class BaseActivation:
    name: str = "linear"

    def __repr__(self):
        return f"<activation {self.name}>"


def _make(nm: str):
    cls = type(nm.capitalize() + "Activation", (BaseActivation,), {"name": nm})
    return cls


Linear = _make("linear")
Sigmoid = _make("sigmoid")
Softmax = _make("softmax")
SequenceSoftmax = _make("softmax")  # sequence-aware variant resolved by the layer
Relu = _make("relu")
BRelu = _make("brelu")
Tanh = _make("tanh")
STanh = _make("stanh")
SoftRelu = _make("softrelu")
Abs = _make("abs")
Square = _make("square")
Exp = _make("exponential")
Log = _make("log")
Sqrt = _make("sqrt")
Reciprocal = _make("reciprocal")
Identity = Linear  # IdentityActivation is the reference's alias for linear


def resolve(act) -> str:
    """Activation tag | string | None → registry name or None."""
    if act is None:
        return None
    if isinstance(act, str):
        return act
    if isinstance(act, BaseActivation) or (
        isinstance(act, type) and issubclass(act, BaseActivation)
    ):
        return act.name
    raise TypeError(f"not an activation: {act!r}")
