"""paddle.v2.networks analog (trainer_config_helpers/networks.py): prebuilt
composites — simple_img_conv_pool (:144), vgg_16_network (:468), simple_lstm
(:553), simple_gru (:981), simple_attention (:1304), text_conv_pool,
bidirectional_lstm."""

from __future__ import annotations

from typing import Any, Optional, Sequence

from paddle_tpu.nn import layers as L
from paddle_tpu.nn import recurrent as R
from paddle_tpu.nn import seq_layers as S
from paddle_tpu.nn.attention_layers import SimpleAttention
from paddle_tpu.v2.activation import resolve as _act
from paddle_tpu.v2.pooling import resolve as _pool


def simple_img_conv_pool(
    input, filter_size, num_filters, pool_size, pool_stride=None,
    act=None, pool_type=None, num_channel=None, param_attr=None, name=None, **_compat,
):
    conv = L.Conv2D(
        input, num_filters, filter_size, padding=(filter_size - 1) // 2,
        act=_act(act) or "relu", param_attr=param_attr,
        name=(name + "_conv") if name else None,
    )
    return L.Pool2D(conv, pool_size, _pool(pool_type), stride=pool_stride or pool_size,
                    name=(name + "_pool") if name else None)


def img_conv_group(
    input, conv_num_filter: Sequence[int], pool_size, conv_filter_size=3,
    conv_act=None, conv_with_batchnorm=False, pool_stride=None, pool_type=None, **_compat,
):
    x = input
    for i, nf in enumerate(conv_num_filter):
        x = L.Conv2D(x, nf, conv_filter_size, padding=(conv_filter_size - 1) // 2,
                     act=None if conv_with_batchnorm else (_act(conv_act) or "relu"))
        if conv_with_batchnorm:
            x = L.BatchNorm(x, act=_act(conv_act) or "relu")
    return L.Pool2D(x, pool_size, _pool(pool_type), stride=pool_stride or pool_size)


def vgg_16_network(input_image, num_channels=3, num_classes=1000):
    """vgg_16_network (networks.py:468)."""
    x = input_image
    for nf, reps in ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3)):
        x = img_conv_group(x, [nf] * reps, pool_size=2, conv_with_batchnorm=True)
    x = L.Fc(x, 4096, act="relu")
    x = L.Dropout(x, 0.5)
    x = L.Fc(x, 4096, act="relu")
    x = L.Dropout(x, 0.5)
    return L.Fc(x, num_classes, act="softmax")


def simple_lstm(input, size, reverse=False, mat_param_attr=None,
                lstm_cell_attr=None, act=None, gate_act=None, state_act=None, **_compat):
    return R.simple_lstm(input, size, reverse=reverse)


def simple_gru(input, size, reverse=False, **_compat):
    return R.simple_gru(input, size, reverse=reverse)


def bidirectional_lstm(input, size, return_seq=False, **_compat):
    out = R.bidirectional_lstm(input, size)
    if return_seq:
        return out
    return S.LastSeq(out)


def text_conv_pool(input, context_len=5, hidden_size=128, act=None, **_compat):
    """sequence_conv_pool: context window projection → fc → max-pool over time."""
    from paddle_tpu.nn import projections as P

    ctx = L.Mixed([P.Context_(input, -(context_len // 2), context_len)],
                  size=input.cfg.get("size", hidden_size) if hasattr(input, "cfg") else hidden_size)
    h = L.Fc(ctx, hidden_size, act=_act(act) or "tanh")
    return S.SeqPool(h, "max")


sequence_conv_pool = text_conv_pool


def simple_attention(encoded_sequence, encoded_proj=None, decoder_state=None,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, name=None, **_compat):
    """simple_attention (networks.py:1304) — additive attention composed from
    the same primitive ops the reference uses (the encoded_proj transform is
    computed internally from encoded_sequence)."""
    return SimpleAttention(encoded_sequence, decoder_state, name=name)
