"""paddle.v2.attr analog (trainer_config_helpers/attrs.py: ParamAttr/ExtraAttr)."""

from __future__ import annotations

from typing import Any, Optional

from paddle_tpu.nn.graph import ParamAttr as _GraphParamAttr


def Param(
    name: Optional[str] = None,
    is_static: bool = False,
    initial_std: Optional[float] = None,
    initial_mean: float = 0.0,
    learning_rate: float = 1.0,
    momentum: Optional[float] = None,
    l1_rate: Optional[float] = None,
    l2_rate: Optional[float] = None,
    sparse_update: bool = False,
    gradient_clipping_threshold: Optional[float] = None,
    sharding: Any = None,
    initializer: Any = None,
    initial_min: Optional[float] = None,
    initial_max: Optional[float] = None,
) -> _GraphParamAttr:
    """ParameterAttribute factory keeping the reference's knob names."""
    return _GraphParamAttr(
        name=name,
        initializer=initializer,
        initial_min=initial_min,
        initial_max=initial_max,
        initial_std=initial_std,
        initial_mean=initial_mean,
        learning_rate=learning_rate,
        momentum=momentum,
        l1_decay=l1_rate,
        l2_decay=l2_rate,
        is_static=is_static,
        is_sparse=sparse_update,
        gradient_clipping_threshold=gradient_clipping_threshold,
        sharding=tuple(sharding) if sharding is not None else None,
    )


ParamAttr = Param


class ExtraAttr:
    """ExtraLayerAttribute: drop_rate and error-clipping knobs."""

    def __init__(
        self,
        error_clipping_threshold: Optional[float] = None,
        drop_rate: Optional[float] = None,
        device: Optional[int] = None,
    ):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device  # accepted for compat; sharding replaces devices


ExtraLayerAttribute = ExtraAttr
