"""paddle.v2.minibatch analog."""

from paddle_tpu.data.reader import batch  # noqa: F401
