"""paddle.v2.optimizer analog (python/paddle/v2/optimizer.py +
trainer_config_helpers/optimizers.py settings()).

Each class bundles the gradient rule with the v1 `settings()` knobs: LR decay
schedule (learning_rate_decay_a/b + schedule name, LearningRateScheduler.cpp:30),
regularization, gradient clipping, and model averaging — all of which fold into
the single compiled update step.
"""

from __future__ import annotations

from typing import Any, Optional

from paddle_tpu.optim import optimizers as opt_mod
from paddle_tpu.optim import schedules as sched_mod
from paddle_tpu.optim.average import ModelAverage


class _V2Optimizer:
    """Bundles an optim.Optimizer with schedule + averaging settings."""

    opt_cls = opt_mod.SGD
    opt_kwargs = ()

    def __init__(
        self,
        learning_rate: float = 1e-3,
        learning_rate_decay_a: float = 0.0,
        learning_rate_decay_b: float = 0.0,
        learning_rate_schedule: str = "constant",
        regularization: Optional[Any] = None,
        gradient_clipping_threshold: Optional[float] = None,
        model_average: Optional[Any] = None,
        batch_size: Optional[int] = None,  # accepted for settings() compat
        **extra,
    ):
        self.learning_rate = learning_rate
        kwargs = {k: extra.pop(k) for k in list(extra) if k in self.opt_kwargs}
        l1, l2 = None, None
        if regularization is not None:
            l1 = getattr(regularization, "l1", None)
            l2 = getattr(regularization, "l2", None)
        self.optimizer = self.opt_cls(
            learning_rate=learning_rate,
            l1_rate=l1 or 0.0,
            l2_rate=l2 or 0.0,
            gradient_clipping_threshold=gradient_clipping_threshold,
            **kwargs,
        )
        self.schedule = sched_mod.build(
            learning_rate,
            schedule=learning_rate_schedule,
            decay_a=learning_rate_decay_a,
            decay_b=learning_rate_decay_b,
        )
        avg_window = getattr(model_average, "average_window", model_average) or 0.0
        self.model_average = ModelAverage(float(avg_window))


class Momentum(_V2Optimizer):
    opt_cls = opt_mod.SGD
    opt_kwargs = ("momentum", "nesterov")

    def __init__(self, momentum=0.0, sparse=False, **kw):
        # sparse-update flag is a pserver-era storage knob; row-sparse grads
        # are handled by the sharded-embedding path (paddle_tpu.parallel)
        super().__init__(momentum=momentum, **kw)


class Adam(_V2Optimizer):
    opt_cls = opt_mod.Adam
    opt_kwargs = ("beta1", "beta2", "epsilon")

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(beta1=beta1, beta2=beta2, epsilon=epsilon, **kw)


class AdaMax(_V2Optimizer):
    opt_cls = opt_mod.AdaMax
    opt_kwargs = ("beta1", "beta2")

    def __init__(self, beta1=0.9, beta2=0.999, **kw):
        super().__init__(beta1=beta1, beta2=beta2, **kw)


class AdaGrad(_V2Optimizer):
    opt_cls = opt_mod.AdaGrad
    opt_kwargs = ("epsilon",)


class DecayedAdaGrad(_V2Optimizer):
    opt_cls = opt_mod.DecayedAdaGrad
    opt_kwargs = ("rho", "epsilon")

    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(rho=rho, epsilon=epsilon, **kw)


class AdaDelta(_V2Optimizer):
    opt_cls = opt_mod.AdaDelta
    opt_kwargs = ("rho", "epsilon")

    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(rho=rho, epsilon=epsilon, **kw)


class RMSProp(_V2Optimizer):
    opt_cls = opt_mod.RMSProp
    opt_kwargs = ("rho", "epsilon")

    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(rho=rho, epsilon=epsilon, **kw)


class L2Regularization:
    def __init__(self, rate: float):
        self.l1 = None
        self.l2 = rate


class L1Regularization:
    def __init__(self, rate: float):
        self.l1 = rate
        self.l2 = None


class ModelAverageCfg:
    def __init__(self, average_window: float, max_average_window: Optional[int] = None):
        self.average_window = average_window
        self.max_average_window = max_average_window
