"""paddle.v2.layer analog — functional layer constructors.

Mirrors python/paddle/v2/layer.py + trainer_config_helpers/layers.py names
(fc_layer → fc, img_conv_layer → img_conv, ...), returning paddle_tpu.nn Layer
specs directly (the v2 reference wraps config_parser; here the graph IS the
config — SURVEY §7: layer-graph capture replaces proto round-trip, while the
classic proto pipeline lives in paddle_tpu.config for v1 parity).

Every constructor accepts and returns graph nodes, so v2 scripts like

    images = paddle.layer.data(name='pixel', type=paddle.data_type.dense_vector(784))
    h = paddle.layer.fc(input=images, size=200, act=paddle.activation.Tanh())
    cost = paddle.layer.classification_cost(input=out, label=lbl)

work verbatim.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import numpy as np

from paddle_tpu.data.feeder import InputSpec
from paddle_tpu.nn import costs as C
from paddle_tpu.nn import detection_layers as D
from paddle_tpu.nn import layers as L
from paddle_tpu.nn import projections as P
from paddle_tpu.nn import recurrent as R
from paddle_tpu.nn import seq_layers as S
from paddle_tpu.nn import struct_costs as SC
from paddle_tpu.nn.graph import Layer
from paddle_tpu.v2.activation import resolve as _act
from paddle_tpu.v2.pooling import resolve as _pool

__all__ = [
    "data", "fc", "embedding", "img_conv", "img_pool", "batch_norm", "dropout",
    "addto", "concat", "seq_concat", "lstmemory", "grumemory", "recurrent",
    "pool", "last_seq", "first_seq", "expand", "max_id", "eos",
    "cross_entropy_cost", "classification_cost", "square_error_cost",
    "cos_sim", "trans", "scaling", "slope_intercept", "interpolation",
    "power", "dot_prod", "mixed", "full_matrix_projection",
    "identity_projection", "dotmul_projection", "table_projection",
    "context_projection", "scaling_projection", "trans_full_matrix_projection",
    "dotmul_operator", "crf", "crf_decoding", "ctc", "warp_ctc", "nce",
    "hsigmoid", "rank_cost", "lambda_cost", "sum_cost", "huber_regression_cost",
    "huber_classification_cost", "smooth_l1_cost", "multi_binary_label_cross_entropy_cost",
    "cross_entropy_with_selfnorm_cost", "soft_binary_class_cross_entropy",
    "maxout", "spp", "img_cmrnorm", "sum_to_one_norm", "row_l2_norm",
    "cross_channel_norm", "data_norm", "bilinear_interp", "pad", "crop",
    "rotate", "switch_order", "featmap_expand", "clip", "scale_shift", "prelu",
    "multiplex", "out_prod", "conv_shift", "tensor", "sampling_id",
    "seq_reshape", "seq_slice", "kmax_seq_score", "sub_seq", "print_layer",
    "priorbox", "multibox_loss", "detection_output", "bidirectional_lstm",
    "bidirectional_gru", "simple_lstm", "simple_gru", "repeat", "resize",
    "block_expand", "row_conv", "selective_fc", "gated_unit",
    "img_conv3d", "img_pool3d", "linear_comb", "convex_comb", "mdlstm",
    "sub_nested_seq", "cross_entropy_over_beam", "BeamInput",
]


# -- data ------------------------------------------------------------------


def data(name: str, type: InputSpec, height: int = 0, width: int = 0) -> Layer:
    """data_layer. Shape derives from the InputSpec; the spec is attached to
    the node so Topology can build the DataFeeder automatically."""
    spec = type
    if spec.kind == "dense":
        if height and width:
            shape: Sequence[int] = (height, width, int(spec.dim) // (height * width))
        elif isinstance(spec.dim, tuple):
            shape = spec.dim
        else:
            shape = (int(spec.dim),)
        is_seq = False
    elif spec.kind == "index":
        shape, is_seq = (), False
    elif spec.kind == "dense_seq":
        shape = spec.dim if isinstance(spec.dim, tuple) else (int(spec.dim),)
        is_seq = True
    elif spec.kind == "index_seq":
        shape, is_seq = (), True
    elif spec.kind == "dense_subseq":
        shape = spec.dim if isinstance(spec.dim, tuple) else (int(spec.dim),)
        is_seq = True
    elif spec.kind == "index_subseq":
        shape, is_seq = (), True
    elif spec.kind in ("sparse_binary", "sparse_value"):
        shape, is_seq = (int(spec.dim),), False
    elif spec.kind == "sparse_binary_seq":
        shape, is_seq = (int(spec.dim),), True
    else:
        raise ValueError(f"unknown input kind {spec.kind}")
    node = L.Data(name, shape=shape, is_seq=is_seq)
    node.data_type = spec
    return node


# -- core ------------------------------------------------------------------


def fc(input, size, act=None, name=None, param_attr=None, bias_attr=None, layer_attr=None):
    bias = bias_attr is not False
    return _with_drop(
        L.Fc(input, size, act=_act(act) or "tanh", bias=bias,
             param_attr=param_attr, bias_attr=_or_none(bias_attr), name=name),
        layer_attr,
    )


def embedding(input, size, param_attr=None, name=None, layer_attr=None):
    # id range comes from the data layer's declared type (integer_value*(range))
    spec = getattr(input, "data_type", None)
    vocab = int(spec.dim) if spec is not None and spec.kind in ("index", "index_seq") else None
    return _with_drop(
        L.Embedding(input, size, vocab_size=vocab, param_attr=param_attr, name=name),
        layer_attr,
    )


def img_conv(
    input, filter_size, num_filters, num_channels=None, stride=1, padding=0,
    dilation=1, groups=1, act=None, bias_attr=None, param_attr=None, name=None,
    trans=False, layer_attr=None, **_compat,
):
    cls = L.Conv2DTranspose if trans else L.Conv2D
    kwargs = dict(
        num_filters=num_filters, filter_size=filter_size, stride=stride,
        padding=padding, act=_act(act), bias=bias_attr is not False,
        param_attr=param_attr, bias_attr=_or_none(bias_attr), name=name,
    )
    if not trans:
        kwargs.update(dilation=dilation, groups=groups)
    return _with_drop(cls(input, **kwargs), layer_attr)


def img_pool(
    input, pool_size, pool_type=None, stride=None, padding=0, name=None,
    layer_attr=None, **_compat,
):
    return _with_drop(
        L.Pool2D(input, pool_size, _pool(pool_type), stride=stride, padding=padding, name=name),
        layer_attr,
    )


def batch_norm(
    input, act=None, name=None, moving_average_fraction=0.9, epsilon=1e-5,
    use_global_stats=None, param_attr=None, bias_attr=None, layer_attr=None, **_compat,
):
    return _with_drop(
        L.BatchNorm(
            input, act=_act(act), epsilon=epsilon,
            moving_average_fraction=moving_average_fraction,
            use_global_stats=use_global_stats, param_attr=param_attr,
            bias_attr=_or_none(bias_attr), name=name,
        ),
        layer_attr,
    )


def dropout(input, dropout_rate, name=None):
    return L.Dropout(input, dropout_rate, name=name)


def addto(input, act=None, bias_attr=False, name=None, layer_attr=None):
    ins = input if isinstance(input, (list, tuple)) else [input]
    return _with_drop(
        L.Addto(ins, act=_act(act), bias=bias_attr is not False,
                bias_attr=_or_none(bias_attr), name=name),
        layer_attr,
    )


def concat(input, act=None, name=None, layer_attr=None):
    return _with_drop(L.Concat(list(input), act=_act(act), name=name), layer_attr)


def seq_concat(a, b, name=None):
    return S.SeqConcat(a, b, name=name)


# -- recurrent -------------------------------------------------------------


def lstmemory(input, size=None, reverse=False, act=None, gate_act=None,
              state_act=None, param_attr=None, bias_attr=None, name=None, **_compat):
    return R.Lstm(
        input, size=size, reverse=reverse, act=_act(act) or "tanh",
        gate_act=_act(gate_act) or "sigmoid", state_act=_act(state_act) or "tanh",
        param_attr=param_attr, bias_attr=_or_none(bias_attr), name=name,
    )


def grumemory(input, size=None, reverse=False, act=None, gate_act=None,
              param_attr=None, bias_attr=None, name=None, **_compat):
    return R.Gru(
        input, size=size, reverse=reverse, act=_act(act) or "tanh",
        gate_act=_act(gate_act) or "sigmoid", param_attr=param_attr,
        bias_attr=_or_none(bias_attr), name=name,
    )


def recurrent(input, act=None, reverse=False, bias_attr=None, param_attr=None, name=None):
    return R.SimpleRnn(input, act=_act(act) or "tanh", reverse=reverse,
                       bias=bias_attr is not False, param_attr=param_attr,
                       bias_attr=None if bias_attr in (None, True, False) else bias_attr,
                       name=name)


simple_lstm = R.simple_lstm
simple_gru = R.simple_gru
bidirectional_lstm = R.bidirectional_lstm
bidirectional_gru = R.bidirectional_gru


def gated_unit(input, size, act=None, gate_param_attr=None, name=None, **_compat):
    """gated_unit_layer: act(fc(x)) * sigmoid(fc(x)) — composed exactly like the
    reference helper (mixed + dotmul_operator)."""
    proj = L.Fc(input, size, act=_act(act), bias=True,
                name=(name + ".proj") if name else None)
    gate = L.Fc(input, size, act="sigmoid", param_attr=gate_param_attr,
                name=(name + ".gate") if name else None)
    return L.Mixed([P.DotMulOperator(proj, gate)], size=size, name=name)


# -- sequence --------------------------------------------------------------


def pool(input, pooling_type=None, name=None, **_compat):
    return S.SeqPool(input, _pool_seq(pooling_type), name=name)


def _pool_seq(p) -> str:
    nm = _pool(p)
    return {"max": "max", "avg": "average", "sum": "sum", "sqrt": "sqrt"}[nm]


def last_seq(input, agg_level=None, stride=-1, name=None, **_compat):
    return S.LastSeq(input, agg_level=agg_level, stride=stride, name=name)


def first_seq(input, agg_level=None, stride=-1, name=None, **_compat):
    return S.FirstSeq(input, agg_level=agg_level, stride=stride, name=name)


def expand(input, expand_as, expand_level=None, name=None, **_compat):
    return S.Expand(input, expand_as, expand_level=expand_level, name=name)


def repeat(input, num_repeats, as_row_vector=True, act=None, name=None, **_compat):
    return L.FeatureMapExpand(input, num_repeats, as_row_vector=as_row_vector,
                              act=_act(act), name=name)


def seq_reshape(input, reshape_size, name=None):
    return S.SeqReshape(input, reshape_size, name=name)


def seq_slice(input, k=None, from_start=True, starts=None, ends=None, name=None):
    starts = None if starts is False else starts
    ends = None if ends is False else ends
    return S.SeqSlice(input, k, from_start=from_start, starts=starts,
                      ends=ends, name=name)


def kmax_seq_score(input, beam_size=1, name=None):
    return S.KmaxSeqScore(input, beam_size, name=name)


def sub_seq(input, offsets, sizes, name=None):
    return S.SubSeq(input, offsets, sizes, name=name)


# -- elementwise / misc ----------------------------------------------------


def img_conv3d(input, filter_size, num_filters, num_channels=None, stride=1,
               padding=0, dilation=1, groups=1, act=None, bias_attr=None,
               param_attr=None, name=None, trans=False, layer_attr=None,
               **_compat):
    """img_conv3d_layer (layers.py:6770) — NDHWC input."""
    from paddle_tpu.nn import layers3d as L3

    cls = L3.Conv3DTranspose if trans else L3.Conv3D
    kwargs = dict(
        num_filters=num_filters, filter_size=filter_size, stride=stride,
        padding=padding, act=_act(act), bias=bias_attr is not False,
        param_attr=param_attr, bias_attr=_or_none(bias_attr), name=name,
    )
    if not trans:
        kwargs.update(dilation=dilation, groups=groups)
    return _with_drop(cls(input, **kwargs), layer_attr)


def img_pool3d(input, pool_size, pool_type=None, stride=None, padding=0,
               ceil_mode=True, name=None, layer_attr=None, **_compat):
    """img_pool3d_layer (layers.py:2695) — ceil_mode=True is the v1
    output-size default, like img_pool_layer."""
    from paddle_tpu.nn import layers3d as L3

    return _with_drop(
        L3.Pool3D(input, pool_size, _pool(pool_type), stride=stride,
                  padding=padding, ceil_mode=ceil_mode, name=name),
        layer_attr,
    )


def linear_comb(weights, vectors, size=None, name=None, **_compat):
    """linear_comb_layer / convex_comb_layer (layers.py:4984)."""
    return L.LinearComb(weights, vectors, size=size, name=name)


convex_comb = linear_comb


def mdlstm(input, size=None, directions=(True, True), param_attr=None,
           bias_attr=None, name=None, **_compat):
    """mdlstmemory (config_parser.py:3621) — 2-D multi-dimensional LSTM over
    a pre-projected [B, H, W, 5*size] grid."""
    return R.MDLstm(input, size=size, directions=directions,
                    param_attr=param_attr, bias_attr=_or_none(bias_attr),
                    name=name)


def sub_nested_seq(input, selected_indices, name=None):
    """sub_nested_seq_layer (layers.py:6582)."""
    return S.SubNestedSeq(input, selected_indices, name=name)


def cross_entropy_over_beam(input, name=None):
    """cross_entropy_over_beam (layers.py:6038); input is a list of
    BeamInput(candidate_scores, selected_candidates, gold)."""
    return SC.CrossEntropyOverBeam(input, name=name)


BeamInput = SC.BeamInput


def cos_sim(a, b, scale=1.0, size=1, name=None):
    """cos_sim_layer (layers.py:2228): size=1 is row-wise cosine; size=N>1 is
    the vector-vs-matrix form (cos_vm, CosSimVecMatLayer.cpp)."""
    if size and size > 1:
        return L.CosSimVecMat(a, b, size=size, scale=scale, name=name)
    return _cos_sim_rowwise(a, b, scale=scale, name=name)


def _cos_sim_rowwise(a, b, scale=1.0, name=None):
    return L.CosSim(a, b, scale=scale, name=name)


def trans(input, height=None, name=None):
    return L.Trans(input, height, name=name)


def scaling(input, weight, name=None):
    return L.Scaling(weight, input, name=name)


def slope_intercept(input, slope=1.0, intercept=0.0, name=None):
    return L.SlopeIntercept(input, slope=slope, intercept=intercept, name=name)


def interpolation(input, weight, name=None):
    a, b = input
    return L.Interpolation(weight, a, b, name=name)


def power(input, weight, name=None):
    return L.Power(weight, input, name=name)


def dot_prod(a, b, name=None):
    return L.DotProd(a, b, name=name)


def out_prod(a, b, name=None):
    return L.OuterProd(a, b, name=name)


def conv_shift(a, b, name=None):
    return L.ConvShift(a, b, name=name)


def tensor(a, b, size, act=None, param_attr=None, bias_attr=None, name=None, **_compat):
    return L.TensorLayer(a, b, size, act=_act(act), bias=bias_attr is not False,
                         param_attr=param_attr, bias_attr=bias_attr, name=name)


def multiplex(input, name=None):
    ins = list(input)
    return L.Multiplex(ins[0], ins[1:], name=name)


def max_id(input, name=None):
    return L.MaxId(input, name=name)


def sampling_id(input, name=None):
    return L.SamplingId(input, name=name)


def eos(input, eos_id, name=None):
    return L.EosIdCheck(input, eos_id=eos_id, name=name)


def print_layer(input, format=None, name=None):
    return L.PrintLayer(input, message=format or "", name=name)


def clip(input, min, max, name=None):
    return L.Clip(input, min=min, max=max, name=name)


def scale_shift(input, param_attr=None, bias_attr=None, name=None):
    return L.ScaleShift(input, bias=bias_attr is not False,
                        param_attr=param_attr, bias_attr=bias_attr, name=name)


def prelu(input, partial_sum=1, param_attr=None, name=None):
    return L.ParameterRelu(input, partial_sum=partial_sum, param_attr=param_attr, name=name)


# -- image misc ------------------------------------------------------------


def maxout(input, groups, name=None, **_compat):
    return L.Maxout(input, groups, name=name)


def spp(input, pyramid_height=3, pool_type=None, name=None, **_compat):
    return L.SpatialPyramidPool(input, pyramid_height, _pool(pool_type), name=name)


def img_cmrnorm(input, size, scale=0.0128, power=0.75, name=None, **_compat):
    return L.CrossMapNorm(input, size=size, scale=scale, power=power, name=name)


def sum_to_one_norm(input, name=None):
    return L.SumToOneNorm(input, name=name)


def row_l2_norm(input, name=None):
    return L.RowL2Norm(input, name=name)


def cross_channel_norm(input, param_attr=None, name=None):
    return L.CrossChannelNorm(input, name=name)


def data_norm(input, name=None, **_compat):
    return L.DataNorm(input, name=name)


def bilinear_interp(input, out_size_x, out_size_y, name=None):
    return L.BilinearInterp(input, (out_size_y, out_size_x), name=name)


def pad(input, pad_c=None, pad_h=None, pad_w=None, name=None):
    return L.Pad(input, pad_c=pad_c or [0, 0], pad_h=pad_h or [0, 0],
                 pad_w=pad_w or [0, 0], name=name)


def crop(input, offset, shape, name=None, **_compat):
    off_h, off_w = (offset if isinstance(offset, (list, tuple)) else (offset, offset))
    out_h, out_w = (shape if isinstance(shape, (list, tuple)) else (shape, shape))
    return L.Crop(input, off_h, off_w, out_h, out_w, name=name)


def rotate(input, name=None):
    return L.Rotate(input, name=name)


def switch_order(input, to="NCHW", name=None, **_compat):
    return L.SwitchOrder(input, to=to, name=name)


def featmap_expand(input, num_filters, name=None):
    return L.FeatureMapExpand(input, num_filters, name=name)


def resize(input, size, name=None):
    return L.Resize(input, size, name=name)


def block_expand(input, block_x, block_y, stride_x=None, stride_y=None,
                 padding_x=0, padding_y=0, num_channels=None, name=None):
    return L.BlockExpand(input, block_x=block_x, block_y=block_y,
                         stride_x=stride_x or block_x, stride_y=stride_y or block_y,
                         padding_x=padding_x, padding_y=padding_y, name=name)


def row_conv(input, context_len, act=None, param_attr=None, name=None):
    return L.RowConv(input, context_len, act=_act(act), param_attr=param_attr, name=name)


def selective_fc(input, size, select=None, act=None, param_attr=None,
                 bias_attr=None, name=None, **_compat):
    return L.SelectiveFc(
        [input, select] if select is not None else input, size, act=_act(act),
        bias=bias_attr is not False, param_attr=param_attr, name=name,
    )


# -- mixed / projections ---------------------------------------------------


def mixed(size=0, input=None, act=None, bias_attr=False, name=None, layer_attr=None):
    # MixedLayer adds a bias only when bias_attr is explicitly truthy
    # (layers.py mixed_layer: default False; None also means no bias)
    bias = bias_attr is not False and bias_attr is not None
    if input is None:
        # context-manager form: `with mixed_layer(size=N) as m: m += proj`
        return L.Mixed([], size=size, act=_act(act),
                       bias=bias, bias_attr=bias_attr, name=name)
    from paddle_tpu.nn.projections import Projection

    if isinstance(input, Projection):
        input = [input]
    return _with_drop(
        L.Mixed(list(input), size=size, act=_act(act),
                bias=bias, bias_attr=bias_attr, name=name),
        layer_attr,
    )


def full_matrix_projection(input, size=0, param_attr=None):
    # size may be given here or by the enclosing mixed() at apply time
    return P.FullMatrix(input, param_attr=param_attr, size=size)


def trans_full_matrix_projection(input, size=0, param_attr=None):
    return P.TransposedFullMatrix(input, param_attr=param_attr)


def identity_projection(input, offset=None, size=None):
    return P.Identity(input, offset=offset or 0, size=size)


def dotmul_projection(input, param_attr=None):
    return P.DotMul(input, param_attr=param_attr)


def slice_projection(input, slices, **_compat):
    return P.SliceProj(input, slices)


def table_projection(input, size=0, param_attr=None, vocab_size=None):
    if vocab_size is None:
        spec = getattr(input, "data_type", None)
        if spec is not None and spec.kind.startswith("index"):
            vocab_size = int(spec.dim) or None
        elif getattr(input, "_v1_size", None):
            vocab_size = int(input._v1_size)
        elif getattr(input, "shape", None):
            n = 1
            for d in input.shape:
                n *= int(d)
            vocab_size = n or None
    """vocab_size: the id range (the reference infers it from the data layer's
    dim; explicit here because data layers carry shapes, not ranges)."""
    if vocab_size is None:
        spec = getattr(input, "data_type", None)
        vocab_size = int(spec.dim) if spec is not None else 0
    return P.Table(input, vocab_size=vocab_size, param_attr=param_attr, size=size)


_PADDING_ATTR_UNSET = object()


def context_projection(input, context_len, context_start=None,
                       padding_attr=_PADDING_ATTR_UNSET, **_compat):
    start = -(context_len // 2) if context_start is None else context_start
    # wrap_bias_attr_default semantics (reference layers.py:719-755, VERDICT
    # item 2): the decorator substitutes a ParamAttr whenever the caller
    # passed nothing, None or True — so padding is TRAINABLE in all those
    # cases — and only an EXPLICIT False (or a non-trainable attr the caller
    # built) yields non-trainable zero padding. The previous
    # `padding_attr is not None` inverted both the None and the False case.
    if padding_attr is _PADDING_ATTR_UNSET or padding_attr is None or padding_attr is True:
        trainable, attr = True, None  # default-substituted ParamAttr
    elif padding_attr is False:
        trainable, attr = False, None
    else:  # a ParameterAttribute: honored, trainable
        trainable, attr = True, padding_attr
    return P.Context_(input, start, context_len,
                      trainable_padding=trainable, param_attr=attr)


def scaling_projection(input, param_attr=None):
    return P.Scaling(input, param_attr=param_attr)


def dotmul_operator(a, b, scale=1.0):
    return P.DotMulOperator(a, b, scale=scale)


# -- costs -----------------------------------------------------------------


def effective_act(node):
    """The activation the cost layer actually sees, looking through
    activation-less passthrough wrappers (dropout) — a drop_rate layer_attr
    must not hide a softmax-activated layer from the cost."""
    while node is not None:
        a = getattr(node, "act", None)
        if a is not None:
            return a
        if getattr(node, "type_name", None) == "dropout":
            node = node.inputs[0]
            continue
        return None
    return None


def classification_cost(input, label, weight=None, name=None, coeff=1.0, **_compat):
    # The standard idiom feeds a softmax-activated layer; the cost must then
    # consume probabilities, not re-softmax (layers.py:4347 applies softmax as
    # the *input layer's* activation, so the cost itself is plain CE).
    from_logits = effective_act(input) != "softmax"
    return C.ClassificationCost(input, label, weight=weight, name=name,
                                coeff=coeff, from_logits=from_logits)


cross_entropy_cost = classification_cost


def square_error_cost(input, label, weight=None, name=None, coeff=1.0):
    return C.SquareError(input, label, weight=weight, name=name, coeff=coeff)


mse_cost = square_error_cost
regression_cost = square_error_cost


def soft_binary_class_cross_entropy(input, label, name=None, coeff=1.0):
    return C.SoftBinaryCrossEntropy(input, label, name=name, coeff=coeff)


def cross_entropy_with_selfnorm_cost(input, label, name=None, coeff=1.0, softmax_selfnorm_alpha=0.1):
    return C.CrossEntropyWithSelfNorm(input, label, name=name, coeff=coeff,
                                      softmax_selfnorm_alpha=softmax_selfnorm_alpha)


def multi_binary_label_cross_entropy_cost(input, label, name=None, coeff=1.0):
    return C.MultiBinaryLabelCrossEntropy(input, label, name=name, coeff=coeff)


def huber_regression_cost(input, label, name=None, delta=1.0, coeff=1.0):
    return C.HuberRegression(input, label, name=name, delta=delta, coeff=coeff)


def huber_classification_cost(input, label, name=None, coeff=1.0):
    return C.HuberTwoClassification(input, label, name=name, coeff=coeff)


def smooth_l1_cost(input, label, name=None, coeff=1.0):
    return C.SmoothL1(input, label, name=name, coeff=coeff)


def rank_cost(left, right, label, weight=None, name=None, coeff=1.0):
    return C.RankCost(left, right, label, weight=weight, name=name, coeff=coeff)


def lambda_cost(input, score, NDCG_num=5, name=None, coeff=1.0, **_compat):
    return SC.LambdaCost(input, score, ndcg_num=NDCG_num, name=name, coeff=coeff)


def sum_cost(input, name=None):
    return C.SumCost(input, name=name)


def crf(input, label, size=None, param_attr=None, name=None, coeff=1.0, **_compat):
    return SC.CRFCost(input, label, size=size, param_attr=param_attr, name=name, coeff=coeff)


def crf_decoding(input, size=None, label=None, param_attr=None, name=None):
    return SC.CRFDecoding(input, size=size, label=label, param_attr=param_attr, name=name)


def ctc(input, label, size=None, blank=None, norm_by_times=False, name=None, **_compat):
    # reference convention: blank = size-1 (the alphabet's last id); size is
    # inferred from the input layer when omitted, like config_parser does
    if blank is None:
        inferred = size or getattr(input, "size", None) or (
            input.cfg.get("size") if hasattr(input, "cfg") else None)
        if inferred is None:
            raise ValueError("ctc: pass size= (or blank=) — cannot infer the "
                             "alphabet size from this input layer")
        blank = int(inferred) - 1
    return SC.CTCCost(input, label, blank=blank, norm_by_times=norm_by_times,
                      size=size or blank + 1, name=name)


def warp_ctc(input, label, size=None, blank=0, norm_by_times=False, name=None, **_compat):
    """warp_ctc_layer: same loss, XLA-native implementation (no warp-ctc dlopen;
    reference paddle/cuda/src/hl_warpctc_wrap.cc)."""
    node = SC.CTCCost(input, label, blank=blank, norm_by_times=norm_by_times,
                      size=size, name=name)
    node.type_name = "warp_ctc"  # same math, distinct wire type
    return node


def nce(input, label, num_classes, weight=None, num_neg_samples=10,
        neg_distribution=None, bias_attr=None, param_attr=None, name=None,
        **_compat):
    return SC.NCECost(input, label, num_classes, num_neg_samples=num_neg_samples,
                      neg_distribution=neg_distribution, bias=bias_attr is not False,
                      param_attr=param_attr, weight=weight, name=name)


def hsigmoid(input, label, num_classes, bias_attr=None, param_attr=None, name=None, **_compat):
    return SC.HierarchicalSigmoid(input, label, num_classes,
                                  bias=bias_attr is not False,
                                  param_attr=param_attr, name=name)


# -- detection -------------------------------------------------------------


def priorbox(input, image_size, min_size, max_size=(), aspect_ratio=(2.0,),
             variance=(0.1, 0.1, 0.2, 0.2), clip=True, name=None):
    if isinstance(image_size, int):
        image_size = (image_size, image_size)
    return D.PriorBox(input, image_size=image_size, min_size=min_size,
                      max_size=max_size, aspect_ratio=aspect_ratio,
                      variance=variance, clip=clip, name=name)


def multibox_loss(input_loc, input_conf, priorbox, label, num_classes,
                  overlap_threshold=0.5, neg_pos_ratio=3.0, background_id=0,
                  name=None, **_compat):
    """label = (gt_boxes_layer, gt_labels_layer) — the reference packs both in
    one LoD slot; padded arrays keep them as two feeds."""
    gt_boxes, gt_labels = label
    return D.MultiBoxLoss(_as_list(input_loc), _as_list(input_conf),
                          _as_list(priorbox), gt_boxes, gt_labels,
                          num_classes=num_classes,
                          overlap_threshold=overlap_threshold,
                          neg_pos_ratio=neg_pos_ratio,
                          background_id=background_id, name=name)


def detection_output(input_loc, input_conf, priorbox, num_classes,
                     nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                     confidence_threshold=0.01, background_id=0, name=None):
    return D.DetectionOutput(_as_list(input_loc), _as_list(input_conf),
                             _as_list(priorbox),
                             num_classes=num_classes, nms_threshold=nms_threshold,
                             nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                             confidence_threshold=confidence_threshold,
                             background_id=background_id, name=name)


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


# -- helpers ---------------------------------------------------------------


def _or_none(attr):
    return None if isinstance(attr, bool) else attr


def _with_drop(node: Layer, layer_attr) -> Layer:
    """Apply ExtraAttr knobs by chaining nodes (the reference applies both
    inside Layer::forward/backwardActivation when set): drop_rate → Dropout,
    error_clipping_threshold → identity-forward/clipped-backward."""
    if layer_attr is not None and getattr(
        layer_attr, "error_clipping_threshold", None
    ):
        node = L.ErrorClip(
            node, layer_attr.error_clipping_threshold, name=node.name + ".eclip"
        )
    if layer_attr is not None and getattr(layer_attr, "drop_rate", None):
        return L.Dropout(node, layer_attr.drop_rate, name=node.name + ".drop")
    return node


# -- recurrent groups / generation (RecurrentGradientMachine parity) -------

from paddle_tpu.nn.recurrent_group import (  # noqa: E402
    GeneratedInput,
    StaticInput,
    SubsequenceInput,
    SubSequenceInput,
    beam_search,
    get_output_layer,
    memory,
    recurrent_group,
)

__all__ += [
    "recurrent_group", "memory", "StaticInput", "GeneratedInput",
    "SubsequenceInput", "SubSequenceInput",
    "beam_search", "get_output_layer",
]
