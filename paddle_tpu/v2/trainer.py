"""paddle.v2.trainer analog (python/paddle/v2/trainer.py:24 SGD, .train :124).

SGD here drives the compiled-step SGDTrainer (paddle_tpu.trainer); the v2
reader/event/feeding protocol is preserved exactly: reader yields minibatches
(lists of sample tuples), `feeding` maps data-layer names to tuple positions,
and `event_handler` receives BeginPass/EndIteration/EndPass (+ TestResult via
`test()`).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

from paddle_tpu.core.init_ctx import flags
from paddle_tpu.trainer.trainer import SGDTrainer
from paddle_tpu.v2.event import TestResult
from paddle_tpu.v2.parameters import Parameters
from paddle_tpu.v2.topology import Topology


class SGD:
    def __init__(
        self,
        cost,
        parameters: Optional[Parameters] = None,
        update_equation=None,
        extra_layers: Sequence = (),
        is_local: bool = True,
        **_compat,
    ):
        from paddle_tpu.v2 import optimizer as v2opt

        if update_equation is None:
            update_equation = v2opt.Momentum(learning_rate=0.01)
        self.topology = Topology(cost, extra_layers=extra_layers)
        self.parameters = parameters
        self._update = update_equation

        parallel = None
        tc = flags().trainer_count
        if tc and tc > 1:
            from paddle_tpu.parallel import DataParallel, make_mesh

            parallel = DataParallel(make_mesh({"data": tc}))

        costs = cost if isinstance(cost, (list, tuple)) else [cost]
        self._trainer = SGDTrainer(
            list(costs),
            update_equation.optimizer,
            extra_outputs=list(extra_layers),
            schedule=update_equation.schedule,
            model_average=update_equation.model_average,
            parallel=parallel,
            seed=flags().seed,
        )

    # -- API -----------------------------------------------------------------
    def train(
        self,
        reader: Callable,
        num_passes: int = 1,
        event_handler: Optional[Callable] = None,
        feeding: Optional[Dict[str, int]] = None,
    ):
        feeder = self.topology.make_feeder(feeding)
        if self.parameters is not None and self._trainer.state is None:
            self._seed_state_from_parameters(reader, feeder)
        state = self._trainer.train(
            reader,
            num_passes=num_passes,
            event_handler=event_handler,
            feeder=feeder,
        )
        self._sync_parameters_out()
        return state

    def test(self, reader: Callable, feeding: Optional[Dict[str, int]] = None) -> TestResult:
        feeder = self.topology.make_feeder(feeding)
        if self._trainer.state is None:
            if self.parameters is None or not len(self.parameters):
                raise ValueError(
                    "test() before train(): pass trained Parameters to SGD(...) "
                    "(e.g. Parameters.from_tar) or call train() first"
                )
            self._seed_state_from_parameters(reader, feeder)
        res = self._trainer.test(reader, feeder)
        return TestResult(pass_id=-1, cost=res["cost"], metrics=res)

    def save_parameter_to_tar(self, f) -> None:
        self._sync_parameters_out()
        assert self.parameters is not None
        self.parameters.to_tar(f)

    # -- internals -----------------------------------------------------------
    def _seed_state_from_parameters(self, reader, feeder) -> None:
        """Initialize trainer state, then overwrite values with user-provided
        Parameters (supports warm start / from_tar)."""
        first = next(iter(reader()))
        batch = feeder(first)
        if self._trainer.parallel is not None:
            batch = self._trainer.parallel.shard_batch(batch)
        self._trainer.init_state(batch)
        if self.parameters is not None and len(self.parameters):
            import jax.numpy as jnp

            params = dict(self._trainer.state["params"])
            for k in params:
                if k in self.parameters:
                    params[k] = jnp.asarray(self.parameters.get(k))
            self._trainer.state["params"] = params
            if self._trainer.parallel is not None:
                # pass the updater's placement seam so ZeRO flat optimizer
                # slots stay resident-sharded (a bare shard_state would
                # re-place them replicated — the full-opt-state peak
                # shard_update exists to avoid)
                self._trainer.state = self._trainer.parallel.shard_state(
                    self._trainer.state,
                    opt_sharding=self._trainer.updater.opt_leaf_sharding,
                )

    def _sync_parameters_out(self) -> None:
        if self._trainer.state is None:
            return
        if self.parameters is None:
            self.parameters = Parameters()
        for k, v in self._trainer.state["params"].items():
            self.parameters.set(k, np.asarray(v))
