"""paddle.v2.inference analog (python/paddle/v2/inference.py).

infer() compiles the output sub-graph once per batch shape and streams input
chunks through it — the deployment path that replaces
paddle_gradient_machine_forward (capi/gradient_machine.h:73).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import jax
import numpy as np

from paddle_tpu.v2.parameters import Parameters
from paddle_tpu.v2.topology import Topology


class Inference:
    def __init__(self, output_layer, parameters: Parameters):
        outputs = output_layer if isinstance(output_layer, (list, tuple)) else [output_layer]
        self.topology = Topology(list(outputs))
        self.output_names = [l.name for l in outputs]
        self.network = self.topology.network
        self._params = {k: np.asarray(v) for k, v in parameters.as_dict().items()}
        self._states: Dict[str, Any] = {}
        self._apply = jax.jit(self._forward)
        self._states_ready = False

    def _forward(self, params, states, batch):
        outs, _ = self.network.apply(params, states, batch, train=False)
        return [outs[n].value for n in self.output_names]

    def _ensure_states(self, batch) -> None:
        if self._states_ready:
            return
        # batch-norm moving stats etc. default-initialize when the Parameters
        # tar carries only trainable values
        params, states = self.network.init(jax.random.PRNGKey(0), batch, train=False)
        for k in params:
            if k not in self._params:
                self._params[k] = np.asarray(params[k])
        self._states = {k: np.asarray(v) for k, v in states.items()}
        self._states_ready = True

    def infer(
        self,
        input: Union[List, Iterable],
        feeding: Optional[Dict[str, int]] = None,
        field: Union[str, Sequence[str]] = "value",
        batch_size: int = 128,
    ):
        fields = [field] if isinstance(field, str) else list(field)
        for f in fields:
            if f not in ("value", "id"):
                raise ValueError(f"unsupported infer field {f!r} (value|id)")
        feeder = self.topology.make_feeder(feeding)
        samples = list(input)
        chunks: List[List[np.ndarray]] = []
        for i in range(0, len(samples), batch_size):
            batch = feeder(samples[i : i + batch_size])
            self._ensure_states(batch)
            vals = self._apply(self._params, self._states, batch)
            chunks.append([np.asarray(v) for v in vals])
        per_output = [_concat_chunks([c[j] for c in chunks])
                      for j in range(len(self.output_names))]
        results = []
        for f in fields:
            for out in per_output:
                results.append(np.argmax(out, axis=-1) if f == "id" else out)
        if len(results) == 1:
            return results[0]
        return results


def _concat_chunks(chunks):
    """Concatenate per-batch outputs; sequence outputs may be padded to
    different bucket lengths per chunk — zero-pad to the common max first."""
    if len(chunks) == 1:
        return chunks[0]
    if chunks[0].ndim >= 2:
        max_t = max(c.shape[1] for c in chunks)
        if any(c.shape[1] != max_t for c in chunks):
            chunks = [
                np.pad(c, [(0, 0), (0, max_t - c.shape[1])] + [(0, 0)] * (c.ndim - 2))
                for c in chunks
            ]
    return np.concatenate(chunks, axis=0)


def infer(output_layer, parameters: Parameters, input, feeding=None,
          field="value", batch_size: int = 128):
    return Inference(output_layer, parameters).infer(
        input, feeding=feeding, field=field, batch_size=batch_size
    )
