"""paddle.v2.parameters analog (python/paddle/v2/parameters.py).

Parameters is a numpy-facing dict view over model parameters with tar-style
(de)serialization. In the reference it mirrors C++ Parameter buffers through
SWIG; here it holds the canonical pytree leaves handed to/collected from the
compiled train step.
"""

from __future__ import annotations

import io
import os
import tarfile
from typing import Dict, Iterator, Optional

import numpy as np


class Parameters:
    def __init__(self):
        self._params: Dict[str, np.ndarray] = {}

    # -- creation -----------------------------------------------------------
    @staticmethod
    def from_topology(topology, seed: int = 0) -> "Parameters":
        """v2 `paddle.parameters.create(cost)` analog: init by tracing the
        graph once on a synthetic batch."""
        import jax

        params, _ = topology.network.init(
            jax.random.PRNGKey(seed), topology.sample_batch(), train=True
        )
        p = Parameters()
        for k, v in params.items():
            p._params[k] = np.asarray(v)
        return p

    @staticmethod
    def from_dict(d: Dict[str, np.ndarray]) -> "Parameters":
        p = Parameters()
        for k, v in d.items():
            p._params[k] = np.asarray(v)
        return p

    # -- dict protocol -------------------------------------------------------
    def names(self):
        return list(self._params.keys())

    def keys(self):
        return self._params.keys()

    def has_key(self, key: str) -> bool:
        return key in self._params

    def __contains__(self, key: str) -> bool:
        return key in self._params

    def __iter__(self) -> Iterator[str]:
        return iter(self._params)

    def __len__(self) -> int:
        return len(self._params)

    def get(self, key: str) -> np.ndarray:
        return self._params[key]

    __getitem__ = get

    def set(self, key: str, value: np.ndarray) -> None:
        if key in self._params and self._params[key].shape != np.shape(value):
            raise ValueError(
                f"shape mismatch for {key!r}: {self._params[key].shape} vs {np.shape(value)}"
            )
        self._params[key] = np.asarray(value)

    __setitem__ = set

    def get_shape(self, key: str):
        return self._params[key].shape

    def as_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._params)

    # -- (de)serialization: tar of .npy members (v2 to_tar/from_tar) ---------
    def to_tar(self, f) -> None:
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name, arr in sorted(self._params.items()):
                buf = io.BytesIO()
                np.save(buf, arr, allow_pickle=False)
                data = buf.getvalue()
                info = tarfile.TarInfo(name=name + ".npy")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))

    @staticmethod
    def from_tar(f) -> "Parameters":
        p = Parameters()
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                if not member.name.endswith(".npy"):
                    continue
                buf = tar.extractfile(member)
                assert buf is not None
                p._params[member.name[: -len(".npy")]] = np.load(
                    io.BytesIO(buf.read()), allow_pickle=False
                )
        return p

    def save_to_file(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            self.to_tar(f)
        os.replace(tmp, path)

    @staticmethod
    def load_from_file(path: str) -> "Parameters":
        with open(path, "rb") as f:
            return Parameters.from_tar(f)


def create(layers, seed: int = 0) -> Parameters:
    """paddle.parameters.create(cost) — accepts output layer(s) or Topology."""
    from paddle_tpu.v2.topology import Topology

    topo = layers if isinstance(layers, Topology) else Topology(layers)
    return Parameters.from_topology(topo, seed=seed)
