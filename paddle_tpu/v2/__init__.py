"""paddle_tpu.v2 — the user-facing v2-style API.

Parity surface: python/paddle/v2/__init__.py (layer, activation, pooling, attr,
data_type, networks, optimizer, trainer.SGD, event, reader, minibatch, dataset,
parameters, inference.infer, topology.Topology). The implementation beneath is
the TPU-native layer graph (paddle_tpu.nn) + compiled-step trainer — not SWIG
into a C++ GradientMachine — but user scripts written against the reference v2
API shape work unchanged.
"""

from __future__ import annotations

from paddle_tpu.v2 import activation as activation  # noqa: F401
from paddle_tpu.v2 import attr as attr  # noqa: F401
from paddle_tpu.v2 import data_type as data_type  # noqa: F401
from paddle_tpu.v2 import event as event  # noqa: F401
from paddle_tpu.v2 import inference as inference  # noqa: F401
from paddle_tpu.v2 import layer as layer  # noqa: F401
from paddle_tpu.v2 import networks as networks  # noqa: F401
from paddle_tpu.v2 import optimizer as optimizer  # noqa: F401
from paddle_tpu.v2 import parameters as parameters  # noqa: F401
from paddle_tpu.v2 import pooling as pooling  # noqa: F401
from paddle_tpu.v2 import topology as topology  # noqa: F401
from paddle_tpu.v2 import trainer as trainer  # noqa: F401
from paddle_tpu.v2 import plot as plot  # noqa: F401
from paddle_tpu.v2.inference import infer as infer  # noqa: F401
from paddle_tpu.v2.minibatch import batch as batch  # noqa: F401

from paddle_tpu.data import reader as reader  # noqa: F401
from paddle_tpu.data import datasets as dataset  # noqa: F401
from paddle_tpu.data import image as image  # noqa: F401


def init(use_gpu: bool = False, trainer_count: int = 1, seed: int = 0, **kwargs):
    """paddle.init analog (python/paddle/v2/__init__.py:65).

    `use_gpu` is accepted for script compatibility and ignored (the backend is
    whatever jax picks: TPU on TPU hosts, CPU elsewhere). `trainer_count` maps
    to the data-parallel mesh size; it is recorded and consumed by trainer.SGD.
    """
    import paddle_tpu.core.init_ctx as ctx

    ctx.init(trainer_count=trainer_count, seed=seed, **kwargs)
