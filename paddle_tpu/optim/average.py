"""Polyak/window parameter averaging.

Parity with paddle/parameter/AverageOptimizer.h:23/100: maintains an averaged
copy of the parameters alongside the optimizer (average_window in v1 settings);
at test/save time the averaged values substitute for the raw ones."""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array
Params = Dict[str, Array]


class ModelAverage:
    def __init__(self, average_window: float = 0.0, max_average_window: int = 0):
        # v1: average over the most recent `average_window * pass_length`
        # updates, capped at max_average_window. We implement the standard
        # incremental mean with a growing-then-capped window weight.
        self.average_window = average_window
        self.max_average_window = max_average_window or 2**31 - 1
        self.enabled = average_window > 0

    def init_state(self, params: Params) -> Dict[str, Any]:
        if not self.enabled:
            return {}
        return {
            "avg": jax.tree.map(lambda p: p.astype(jnp.float32), params),
            "n": jnp.zeros((), jnp.float32),
        }

    def update(self, state: Dict[str, Any], params: Params) -> Dict[str, Any]:
        if not self.enabled:
            return state
        n = jnp.minimum(state["n"] + 1.0, float(self.max_average_window))
        w = 1.0 / n
        avg = jax.tree.map(
            lambda a, p: (1.0 - w) * a + w * p.astype(jnp.float32), state["avg"], params
        )
        return {"avg": avg, "n": n}

    def averaged_params(self, state: Dict[str, Any], params: Params) -> Params:
        if not self.enabled or not state:
            return params
        return jax.tree.map(lambda a, p: a.astype(p.dtype), state["avg"], params)
