"""Learning-rate schedules.

Parity with paddle/parameter/LearningRateScheduler.cpp:30+ registrations:
constant, poly, caffe_poly, exp, discexp, linear_decay, manual, pass_manual.
Each is a pure fn of the global sample/pass counter so it can live inside the
compiled step (num_samples_processed drives v1 schedules)."""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax.numpy as jnp

from paddle_tpu.core.registry import LR_SCHEDULES

Schedule = Callable[[jnp.ndarray], jnp.ndarray]  # samples_processed -> lr factor*base


def build(
    learning_rate: float,
    schedule: Optional[str] = None,
    decay_a: float = 0.0,
    decay_b: float = 0.0,
    warmup_samples: float = 0.0,
) -> Schedule:
    """Returns lr(t) where t = num samples processed (v1 semantics)."""
    name = schedule or "constant"
    fn = LR_SCHEDULES.get(name)
    base = fn(learning_rate, decay_a, decay_b)
    if warmup_samples > 0:

        def warmed(t):
            w = jnp.minimum(t / warmup_samples, 1.0)
            return w * base(t)

        return warmed
    return base


@LR_SCHEDULES.register("constant")
def _constant(lr, a, b):
    return lambda t: jnp.asarray(lr, jnp.float32)


@LR_SCHEDULES.register("poly")
def _poly(lr, a, b):
    # lr * (1 + a*t)^(-b)   (LearningRateScheduler.cpp poly)
    return lambda t: lr * jnp.power(1.0 + a * t, -b)


@LR_SCHEDULES.register("caffe_poly")
def _caffe_poly(lr, a, b):
    # lr * (1 - t/a)^b, clipped at 0 once t >= a
    return lambda t: lr * jnp.power(jnp.maximum(1.0 - t / a, 0.0), b)


@LR_SCHEDULES.register("exp")
def _exp(lr, a, b):
    # lr * a^(t/b)
    return lambda t: lr * jnp.power(a, t / b)


@LR_SCHEDULES.register("discexp")
def _discexp(lr, a, b):
    # lr * a^floor(t/b)
    return lambda t: lr * jnp.power(a, jnp.floor(t / b))


@LR_SCHEDULES.register("linear")
@LR_SCHEDULES.register("linear_decay")
def _linear(lr, a, b):
    # max(lr - a*t, b)
    return lambda t: jnp.maximum(lr - a * t, b)


def manual(lr: float, segments: Sequence[Tuple[float, float]]) -> Schedule:
    """'manual' schedule: list of (boundary_samples, lr_factor) segments
    (LearningRateScheduler.cpp ManualLearningRate)."""
    bounds = jnp.asarray([s[0] for s in segments], jnp.float32)
    rates = jnp.asarray([s[1] for s in segments], jnp.float32)

    def fn(t):
        idx = jnp.sum((t >= bounds).astype(jnp.int32))
        idx = jnp.clip(idx, 0, len(segments) - 1)
        return lr * rates[idx]

    return fn
