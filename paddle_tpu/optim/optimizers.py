"""First-order optimizers.

Parity with paddle/parameter/FirstOrderOptimizer.h (SGD :24, AdaGrad :111,
AdaDelta :141, RMSProp :167, DecayedAdaGrad :210, Adam :255, AdaMax :290) and
the device kernels in paddle/math/TrainingAlgorithmOp.h:38-114. Per-parameter
attributes (learning-rate scale, L1/L2 decay, static, clipping) follow
ParameterConfig semantics (proto/ParameterConfig.proto:34; Regularizer.h:36-100;
gradient clipping wrapper FirstOrderOptimizer.h:346).

Design: each optimizer is pure — `init_state(params)` builds a state pytree and
`update(grads, state, params, lr)` returns (new_params, new_state). The whole
update runs inside the compiled train step (the reference's UpdateCallback folded
into the XLA program; SURVEY §7 hard-part (1))."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.nn.graph import ParamAttr

Array = jax.Array
Params = Dict[str, Array]


def _zeros_like_tree(params: Params) -> Params:
    return jax.tree.map(jnp.zeros_like, params)


@dataclasses.dataclass
class Optimizer:
    """Base: handles per-param lr scale, L1/L2 decay, clipping, static params.

    learning_rate here is the *base* lr; schedules scale it per step outside.
    """

    learning_rate: float = 0.01
    # Global regularization defaults (settings(regularization=...) in v1);
    # per-param attrs override (OptimizerWithRegularizer.cpp).
    l1_rate: float = 0.0
    l2_rate: float = 0.0
    gradient_clipping_threshold: Optional[float] = None
    # Populated by the trainer from Network.param_attrs.
    param_attrs: Dict[str, ParamAttr] = dataclasses.field(default_factory=dict)

    # -- subclass interface -------------------------------------------------
    def init_param_state(self, p: Array) -> Tuple[Array, ...]:
        return ()

    def apply_param(
        self, g: Array, s: Tuple[Array, ...], p: Array, lr: Array
    ) -> Tuple[Array, Tuple[Array, ...]]:
        raise NotImplementedError

    # -- public -------------------------------------------------------------
    def init_state(self, params: Params) -> Dict[str, Any]:
        return {
            "slots": {k: self.init_param_state(p) for k, p in params.items()},
            "t": jnp.zeros((), jnp.int32),  # step counter (Adam bias correction)
        }

    def update_one(
        self, name: str, g: Array, s: Tuple[Array, ...], p: Array, lr: Array
    ) -> Tuple[Array, Tuple[Array, ...]]:
        """One parameter's update with its ParamAttr semantics (static,
        clipping, L1/L2 decay, per-param lr scale). Every op here is
        elementwise, so callers may pass RESHAPED views of the parameter —
        the ZeRO-style ShardedUpdater (parallel/updaters.py) runs this on the
        flat [n_shards, chunk] layout and gets the same math per element.
        Requires `self._t` to be set (bias correction) before the call."""
        attr = self.param_attrs.get(name) or ParamAttr()
        if attr.is_static:
            return p, s
        # the master-update boundary of mixed precision (ISSUE 9): whatever
        # dtype the gradient flowed in (bf16 under precision="bf16"), the
        # optimizer math and every slot run f32 against the f32 master — the
        # "f32 masters" half of the bf16-compute contract lives on this line
        g = g.astype(jnp.float32)
        clip = attr.gradient_clipping_threshold or self.gradient_clipping_threshold
        if clip:
            g = jnp.clip(g, -clip, clip)
        # L2 decay folded into the gradient (Regularizer.h L2Regularizer).
        l2 = attr.l2_decay if attr.l2_decay is not None else self.l2_rate
        if l2:
            g = g + l2 * p
        plr = lr * attr.learning_rate
        new_p, new_s = self.apply_param(g, s, p, plr)
        # L1 decay applied as post-update shrinkage (L1Regularizer::update).
        l1 = attr.l1_decay if attr.l1_decay is not None else self.l1_rate
        if l1:
            shrink = plr * l1
            new_p = jnp.sign(new_p) * jnp.maximum(jnp.abs(new_p) - shrink, 0.0)
        return new_p, new_s

    def update(
        self, grads: Params, state: Dict[str, Any], params: Params, lr: Array
    ) -> Tuple[Params, Dict[str, Any]]:
        t = state["t"] + 1
        new_params: Params = {}
        new_slots: Dict[str, Tuple[Array, ...]] = {}
        self._t = t  # visible to apply_param for bias correction
        for k, p in params.items():
            new_params[k], new_slots[k] = self.update_one(
                k, grads[k], state["slots"][k], p, lr
            )
        return new_params, {"slots": new_slots, "t": t}


@dataclasses.dataclass
class SGD(Optimizer):
    """Plain / momentum / nesterov SGD (SgdOptimizer; sgdUpdate in
    parameter/ParameterUpdateFunctions.h:33)."""

    momentum: float = 0.0
    nesterov: bool = False

    def init_param_state(self, p):
        if self.momentum:
            return (jnp.zeros_like(p),)
        return ()

    def apply_param(self, g, s, p, lr):
        if not self.momentum:
            return p - lr * g, ()
        (v,) = s
        v = self.momentum * v - lr * g
        if self.nesterov:
            step = self.momentum * v - lr * g
        else:
            step = v
        return p + step, (v,)


Momentum = SGD


@dataclasses.dataclass
class AdaGrad(Optimizer):
    """AdaGradOptimizer (FirstOrderOptimizer.h:111; adagradApply
    TrainingAlgorithmOp.h)."""

    epsilon: float = 1e-6

    def init_param_state(self, p):
        return (jnp.zeros_like(p),)

    def apply_param(self, g, s, p, lr):
        (accum,) = s
        accum = accum + g * g
        return p - lr * g / (jnp.sqrt(accum) + self.epsilon), (accum,)


@dataclasses.dataclass
class DecayedAdaGrad(Optimizer):
    """DecayedAdagradOptimizer (FirstOrderOptimizer.h:210): leaky accumulator."""

    rho: float = 0.95
    epsilon: float = 1e-6

    def init_param_state(self, p):
        return (jnp.zeros_like(p),)

    def apply_param(self, g, s, p, lr):
        (accum,) = s
        accum = self.rho * accum + (1 - self.rho) * g * g
        return p - lr * g / (jnp.sqrt(accum) + self.epsilon), (accum,)


@dataclasses.dataclass
class AdaDelta(Optimizer):
    """AdaDeltaOptimizer (FirstOrderOptimizer.h:141; adadeltaApply)."""

    rho: float = 0.95
    epsilon: float = 1e-6

    def init_param_state(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p))

    def apply_param(self, g, s, p, lr):
        accum_g, accum_x = s
        accum_g = self.rho * accum_g + (1 - self.rho) * g * g
        step = -jnp.sqrt((accum_x + self.epsilon) / (accum_g + self.epsilon)) * g
        accum_x = self.rho * accum_x + (1 - self.rho) * step * step
        return p + lr * step, (accum_g, accum_x)


@dataclasses.dataclass
class RMSProp(Optimizer):
    """RMSPropOptimizer (FirstOrderOptimizer.h:167; rmspropApply — note the
    reference keeps both E[g^2] and E[g] (centered variant))."""

    rho: float = 0.95
    epsilon: float = 1e-6
    momentum: float = 0.0

    def init_param_state(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p), jnp.zeros_like(p))

    def apply_param(self, g, s, p, lr):
        ms, mg, mom = s
        ms = self.rho * ms + (1 - self.rho) * g * g
        mg = self.rho * mg + (1 - self.rho) * g
        denom = jnp.sqrt(ms - mg * mg + self.epsilon)
        mom = self.momentum * mom + lr * g / denom
        return p - mom, (ms, mg, mom)


@dataclasses.dataclass
class Adam(Optimizer):
    """AdamOptimizer (FirstOrderOptimizer.h:255; adamApply)."""

    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_param_state(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p))

    def apply_param(self, g, s, p, lr):
        m, v = s
        t = self._t.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g
        v = self.beta2 * v + (1 - self.beta2) * g * g
        mhat = m / (1 - jnp.power(self.beta1, t))
        vhat = v / (1 - jnp.power(self.beta2, t))
        return p - lr * mhat / (jnp.sqrt(vhat) + self.epsilon), (m, v)


@dataclasses.dataclass
class AdaMax(Optimizer):
    """AdamaxOptimizer (FirstOrderOptimizer.h:290; adamaxApply)."""

    beta1: float = 0.9
    beta2: float = 0.999

    def init_param_state(self, p):
        return (jnp.zeros_like(p), jnp.zeros_like(p))

    def apply_param(self, g, s, p, lr):
        m, u = s
        t = self._t.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        return p - (lr / (1 - jnp.power(self.beta1, t))) * m / (u + 1e-12), (m, u)
