from paddle_tpu.optim.optimizers import (  # noqa: F401
    Adam,
    AdaMax,
    AdaGrad,
    AdaDelta,
    DecayedAdaGrad,
    Momentum,
    Optimizer,
    RMSProp,
    SGD,
)
from paddle_tpu.optim import schedules as schedules  # noqa: F401
from paddle_tpu.optim.average import ModelAverage  # noqa: F401
