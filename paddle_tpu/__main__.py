import sys

from paddle_tpu.cli import main

sys.exit(main())
