"""`python -m paddle_tpu.obs` — operator CLI for the observability plane.

    python -m paddle_tpu.obs export [--endpoint host:port] [--out FILE]
        Prometheus text: from a running master/serving server's `metrics`
        RPC (--endpoint, failover lists accepted), or from this process's
        local registry without one.

    python -m paddle_tpu.obs trace [--endpoint host:port ...] [--out FILE]
        Chrome trace JSON (Perfetto-loadable): local ring buffer merged
        with every --endpoint's `trace_export` RPC — one file, spans
        stitched on trace_id across processes.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _rpc(endpoint: str, method: str) -> dict:
    from paddle_tpu.runtime.master import MasterClient

    client = MasterClient(endpoint, retries=2, timeout=10.0)
    try:
        return client.call(method)
    finally:
        client.close()


def cmd_export(args: argparse.Namespace) -> int:
    from paddle_tpu.obs import metrics

    if args.endpoint:
        resp = _rpc(args.endpoint, "metrics")
        if "err" in resp:
            print(f"metrics RPC failed: {resp['err']}", file=sys.stderr)
            return 1
        text = resp.get("text", "")
    else:
        text = metrics.to_prometheus_text()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(args.out)
    else:
        sys.stdout.write(text)
    return 0


def cmd_trace(args: argparse.Namespace) -> int:
    from paddle_tpu.obs import trace

    traces = [trace.export_chrome()]
    for ep in args.endpoint or []:
        resp = _rpc(ep, "trace_export")
        if "err" in resp:
            print(f"trace_export RPC to {ep} failed: {resp['err']}",
                  file=sys.stderr)
            return 1
        traces.append(resp.get("chrome_trace") or {})
    merged = trace.merge_chrome(traces, path=args.out)
    problems = trace.validate_chrome(merged)
    if problems:
        print("trace format problems: " + "; ".join(problems), file=sys.stderr)
        return 1
    if args.out:
        print(args.out)
    else:
        json.dump(merged, sys.stdout)
        sys.stdout.write("\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="paddle_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_exp = sub.add_parser("export", help="Prometheus metrics text")
    p_exp.add_argument(
        "--endpoint", default=None,
        help="master/serving server to query (host:port, failover list ok); "
             "omitted = this process's local registry",
    )
    p_exp.add_argument("--out", default=None, help="write to file (default stdout)")
    p_exp.set_defaults(fn=cmd_export)

    p_tr = sub.add_parser("trace", help="Chrome trace JSON (Perfetto)")
    p_tr.add_argument(
        "--endpoint", action="append", default=None,
        help="server(s) whose span buffers to merge in (repeatable)",
    )
    p_tr.add_argument("--out", default=None, help="write to file (default stdout)")
    p_tr.set_defaults(fn=cmd_trace)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
