"""Profile-driven HLO cost reporting (`--profile pass:N`).

ROADMAP item 2 asks for "a profile-driven pass over the top-3 HLO cost
buckets" — which first needs the buckets. Two hooks deliver them:

  * `PassProfiler` — watches the trainer's event stream and captures a
    `jax.profiler` trace of exactly one pass (start at BeginPass N, stop at
    EndPass N) into `logdir`, via the idempotent `stats.profiler_start/stop`
    so a crashed pass or a double-wrapped handler cannot wedge the tracer.
  * `compiled_cost_report` / `trainer_cost_report` — lower+compile the step
    program(s) and rank XLA's `cost_analysis()` entries into top-k FLOP/byte
    buckets, the machine-readable target list that lands in the bench JSON
    (bench.py, `--job=time --profile`, and the `--profile` report file).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "PassProfiler",
    "compiled_cost_report",
    "parse_profile_spec",
    "trainer_cost_report",
]


def parse_profile_spec(spec: str) -> Tuple[str, int]:
    """'pass:N' → ("pass", N). The shape is extensible ('step:N' later);
    anything else is a ValueError naming the accepted form."""
    kind, sep, arg = (spec or "").partition(":")
    if kind != "pass" or not sep:
        raise ValueError(
            f"bad --profile spec {spec!r}: expected 'pass:N' "
            f"(capture a jax.profiler trace of pass N)"
        )
    try:
        n = int(arg)
    except ValueError:
        raise ValueError(f"bad --profile spec {spec!r}: N must be an integer")
    if n < 0:
        raise ValueError(f"bad --profile spec {spec!r}: N must be >= 0")
    return kind, n


class PassProfiler:
    """Wraps a trainer event handler; profiles exactly one pass."""

    def __init__(self, pass_id: int, logdir: str):
        self.pass_id = int(pass_id)
        self.logdir = logdir
        self.captured = False
        self._active = False

    @classmethod
    def from_spec(cls, spec: str, logdir: str) -> "PassProfiler":
        _, n = parse_profile_spec(spec)
        return cls(n, logdir)

    def wrap(self, handler: Callable) -> Callable:
        from paddle_tpu.trainer.events import BeginPass, EndPass

        def wrapped(event):
            if isinstance(event, BeginPass) and event.pass_id == self.pass_id:
                self.start()
            try:
                handler(event)
            finally:
                if isinstance(event, EndPass) and self._active:
                    self.stop()

        return wrapped

    def start(self) -> None:
        from paddle_tpu.core import stats

        os.makedirs(self.logdir, exist_ok=True)
        stats.profiler_start(self.logdir)
        self._active = True

    def stop(self) -> None:
        from paddle_tpu.core import stats

        stats.profiler_stop()
        self._active = False
        self.captured = True


# -- HLO cost buckets --------------------------------------------------------


def _normalize_cost(ca: Any) -> Dict[str, float]:
    """cost_analysis() returns a dict on recent jax, a [dict] on older ones
    (one entry per module); normalize to one flat {key: number} dict."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    out: Dict[str, float] = {}
    for k, v in (ca or {}).items():
        if isinstance(v, (int, float)):
            out[str(k)] = float(v)
    return out


def compiled_cost_report(compiled: Any, top_k: int = 3) -> Dict[str, Any]:
    """One executable's cost analysis, ranked: headline flops / bytes
    accessed, plus the top-k remaining buckets (per-operand bytes,
    utilization entries — whatever the backend reports) by magnitude."""
    cost = _normalize_cost(compiled.cost_analysis())
    headline_keys = ("flops", "bytes accessed")
    buckets = sorted(
        (
            {"bucket": k, "value": v}
            for k, v in cost.items()
            if k not in headline_keys and v > 0
        ),
        key=lambda b: (-b["value"], b["bucket"]),
    )[: max(0, int(top_k))]
    return {
        "flops": cost.get("flops", 0.0),
        "bytes_accessed": cost.get("bytes accessed", 0.0),
        "top_buckets": buckets,
    }


def trainer_cost_report(
    trainer: Any, batch: Dict[str, Any], top_k: int = 3
) -> Dict[str, Any]:
    """Per-executable HLO cost buckets for a trainer's compiled step
    program(s) against `batch` (a feed-ready batch of the trained shape).
    Lowering + AOT compile only — nothing executes, state is not donated."""
    assert trainer.state is not None, "init_state()/train() first"
    reports: Dict[str, Any] = {}
    step_fn = trainer._step_fn
    if step_fn is None:
        step_fn = trainer._step_fn = trainer._make_step()
    reports["train_step"] = compiled_cost_report(
        step_fn.lower(trainer.state, batch).compile(), top_k
    )
    if trainer._eval_fn is not None:
        reports["eval_step"] = compiled_cost_report(
            trainer._eval_fn.lower(trainer.state, batch).compile(), top_k
        )
    return {
        "top_k": top_k,
        "generated_unix_s": int(time.time()),
        "executables": reports,
    }


def write_report(report: Dict[str, Any], path: str) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path
