"""Fleet metrics: one registry over every counter the runtime already keeps.

The repo grew its telemetry organically — `StatSet` timers, the
`FT_EVENTS`/`DATA_EVENTS`/`SERVING_EVENTS` EventCounters, `RecompileStats`,
ad-hoc `stats()` dicts on the master/allocator/serving server. This module
puts ONE read path over all of them:

  * `MetricsRegistry` — counter / gauge / histogram primitives for new
    instrumentation, plus `register_collector()` hooks that absorb the
    existing stats objects without moving them (they self-register via
    `stats.EVENT_COUNTERS`; their hot-path increment cost is unchanged).
  * `snapshot()` — a flat {dotted.name: value} dict, small enough to
    piggyback on a master heartbeat; `FleetMetrics` aggregates the
    per-trainer snapshots server-side so `MasterServer.stats()` answers for
    the whole fleet, not one process.
  * `to_prometheus_text()` — the standard exposition format, served by the
    `metrics` RPC on the master and serving servers and by
    `python -m paddle_tpu.obs export`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Mapping, NamedTuple, Optional, Tuple

__all__ = [
    "Counter",
    "FleetMetrics",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "Sample",
    "aggregate_snapshots",
    "observe_deadline_miss",
    "observe_engine_restart",
    "observe_pages_recycled",
    "observe_prefix_cow",
    "observe_prefix_evictions",
    "observe_prefix_hit",
    "observe_shed",
    "snapshot",
    "to_prometheus_text",
]


class Sample(NamedTuple):
    name: str
    mtype: str  # counter | gauge | histogram-derived
    value: float
    labels: Tuple[Tuple[str, str], ...] = ()


def _labels(kw: Mapping[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in kw.items()))


class Counter:
    """Monotonic counter; one value per label set."""

    mtype = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._lock = threading.Lock()
        self._vals: Dict[tuple, float] = {}

    def inc(self, n: float = 1.0, **labels: Any) -> None:
        key = _labels(labels)
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + n

    def value(self, **labels: Any) -> float:
        with self._lock:
            return self._vals.get(_labels(labels), 0.0)

    def samples(self) -> Iterable[Sample]:
        with self._lock:
            items = list(self._vals.items())
        for key, v in items or [((), 0.0)]:
            yield Sample(self.name, self.mtype, v, key)


class Gauge(Counter):
    """Last-write-wins value; `set()` replaces, `inc()` still adjusts."""

    mtype = "gauge"

    def set(self, v: float, **labels: Any) -> None:
        with self._lock:
            self._vals[_labels(labels)] = float(v)


class Histogram:
    """Fixed-bucket histogram (cumulative counts, Prometheus convention)."""

    mtype = "histogram"
    DEFAULT_BUCKETS = (
        0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    )

    def __init__(self, name: str, help: str = "", buckets: Optional[Iterable[float]] = None):
        self.name, self.help = name, help
        self.buckets = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._n = 0

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._n += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def samples(self) -> Iterable[Sample]:
        with self._lock:
            counts, total, n = list(self._counts), self._sum, self._n
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            yield Sample(
                f"{self.name}_bucket", "counter", float(cum), (("le", repr(b)),)
            )
        yield Sample(f"{self.name}_bucket", "counter", float(n), (("le", "+Inf"),))
        yield Sample(f"{self.name}_sum", "counter", total)
        yield Sample(f"{self.name}_count", "counter", float(n))


class MetricsRegistry:
    """Named metrics + pluggable collectors over pre-existing stats objects."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}
        self._collectors: List[Callable[[], Iterable[Sample]]] = []

    def _get(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "", buckets=None) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def register_collector(self, fn: Callable[[], Iterable[Sample]]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def collect(self) -> List[Sample]:
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        out: List[Sample] = []
        for m in metrics:
            out.extend(m.samples())
        for fn in collectors:
            out.extend(fn())
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


def _stats_collector() -> Iterable[Sample]:
    """Absorb core/stats.py state: every registered EventCounter group, the
    StatSet timers, and the recompile/compile-cache telemetry."""
    from paddle_tpu.core import stats

    for group, ec in stats.EVENT_COUNTERS.items():
        for event, n in sorted(ec.as_dict().items()):
            yield Sample(
                "paddle_tpu_events_total", "counter", float(n),
                (("event", event), ("group", group)),
            )
    for name, d in sorted(stats.GLOBAL_STATS.as_dict().items()):
        yield Sample(
            "paddle_tpu_timer_ms_total", "counter", float(d["total_ms"]),
            (("name", name),),
        )
        yield Sample(
            "paddle_tpu_timer_calls_total", "counter", float(d["count"]),
            (("name", name),),
        )
    rc = stats.RECOMPILES
    yield Sample(
        "paddle_tpu_shape_signatures", "gauge", float(rc.total_signatures())
    )
    yield Sample(
        "paddle_tpu_compile_cache_hits_total", "counter", float(rc.cache_hits)
    )
    yield Sample(
        "paddle_tpu_compile_cache_misses_total", "counter",
        float(rc.cache_misses),
    )


def _trace_collector() -> Iterable[Sample]:
    from paddle_tpu.obs import trace

    yield Sample(
        "paddle_tpu_trace_spans_recorded_total", "counter",
        float(trace.TRACER.recorded),
    )
    yield Sample(
        "paddle_tpu_trace_spans_dropped_total", "counter",
        float(trace.TRACER.dropped),
    )


REGISTRY = MetricsRegistry()
REGISTRY.register_collector(_stats_collector)
REGISTRY.register_collector(_trace_collector)


def observe_resize(phase_seconds: Mapping[str, float]) -> None:
    """Record one completed elastic-resize epoch on this process's registry:
    bumps `paddle_tpu_resize_epochs_total` and adds each phase's seconds to
    `paddle_tpu_resize_latency_seconds_total{phase=drain|reshard|resume}`.
    Counters (not gauges) on purpose: trainer heartbeats piggyback
    `snapshot()` and the master sums snapshots key-by-key, so the fleet
    aggregate reads as total epochs and total seconds per phase (mean =
    seconds/epochs) instead of a meaningless summed last-value."""
    REGISTRY.counter(
        "paddle_tpu_resize_epochs_total",
        "completed elastic resize epochs",
    ).inc()
    lat = REGISTRY.counter(
        "paddle_tpu_resize_latency_seconds_total",
        "elastic resize wall-clock by phase",
    )
    for phase, s in phase_seconds.items():
        lat.inc(float(s), phase=phase)


# -- serving resilience (ISSUE 10) -------------------------------------------
#
# One naming authority for the serving failure-path counters, so the
# scheduler/session/server increment the same metrics chaos_bench and the
# `metrics` RPC read back. All counters (never gauges): they ride heartbeat
# snapshots and fleet aggregation sums them key-by-key.


def observe_deadline_miss(kind: str) -> None:
    """One request missed a deadline; kind is 'ttft' (first token landed
    late — the client-hedging signal) or 'total' (request cancelled)."""
    REGISTRY.counter(
        "paddle_tpu_serving_deadline_misses_total",
        "serving requests past a deadline, by kind (ttft|total)",
    ).inc(kind=kind)


def observe_shed(reason: str) -> None:
    """One request rejected by load shedding (queue bound, already-expired
    deadline, or load-aware overload check) — the named reason matches the
    QuotaExceeded the caller saw."""
    REGISTRY.counter(
        "paddle_tpu_serving_shed_total",
        "serving requests shed at admission, by named reason",
    ).inc(reason=reason)


def observe_engine_restart(cause: str) -> None:
    """The serving supervisor restarted the decode engine; cause is 'fault'
    (engine thread raised) or 'stall' (no step progress past the watchdog)."""
    REGISTRY.counter(
        "paddle_tpu_serving_engine_restarts_total",
        "serving engine restarts by the session supervisor, by cause",
    ).inc(cause=cause)


def observe_pages_recycled(n: int) -> None:
    """KV pages returned to the free list by a cancellation (deadline expiry
    or client abandonment), as opposed to normal retirement — the leak-watch
    counter the serving chaos drill gates on."""
    REGISTRY.counter(
        "paddle_tpu_serving_pages_recycled_on_cancel_total",
        "KV pages recycled from cancelled (not normally retired) requests",
    ).inc(n)


def observe_prefix_hit(pages: int) -> None:
    """An admission aliased `pages` cached prefix pages into a new slot's
    block table (ISSUE 19) — each page is prefill work the request skipped."""
    REGISTRY.counter(
        "paddle_tpu_serving_prefix_pages_shared_total",
        "KV pages aliased from the shared-prefix cache into new slots",
    ).inc(pages)


def observe_prefix_cow(n: int) -> None:
    """Prefix lookups that stopped at a genuine divergence (the chain had
    cached continuations, just not this prompt's) — the copy-on-write
    boundary where the request switches to a private page."""
    REGISTRY.counter(
        "paddle_tpu_serving_prefix_cow_total",
        "prefix-cache lookups ending at a copy-on-write divergence",
    ).inc(n)


def observe_prefix_evictions(n: int) -> None:
    """Unreferenced cached prefix pages LRU-evicted — under pool pressure at
    reserve time, or by the --prefix_cache_pages cap at registration."""
    REGISTRY.counter(
        "paddle_tpu_serving_prefix_evictions_total",
        "prefix-cache pages evicted (pool pressure or cache-size cap)",
    ).inc(n)


# -- router tier (ISSUE 15 multi-replica serving) -----------------------------


def observe_takeover(plane: str) -> None:
    """A warm standby took over a dead control plane (runtime/election.py);
    plane is 'master', 'router' or 'autoscaler'. Paired with the
    `<plane>_takeover` FT_EVENTS key — this is the labeled cross-plane
    counter the HA chaos drill gates on."""
    REGISTRY.counter(
        "paddle_tpu_takeovers_total",
        "control-plane standby takeovers, by plane",
    ).inc(plane=plane)


def observe_replica_evicted(cause: str) -> None:
    """The router evicted a replica lease; cause is 'lease' (heartbeats
    stopped — death or a self-fenced wedge), 'conn' (dispatch/pump
    connections dead), 'deregister' or 'drain_timeout'."""
    REGISTRY.counter(
        "paddle_tpu_router_replica_evictions_total",
        "serving replicas evicted from the router fleet, by cause",
    ).inc(cause=cause)


def observe_replica_failover(cause: str) -> None:
    """One in-flight request re-submitted to a survivor after its replica
    was lost — re-execution is token-identical (pinned per-request seed)."""
    REGISTRY.counter(
        "paddle_tpu_router_failovers_total",
        "in-flight requests failed over to a surviving replica, by cause",
    ).inc(cause=cause)


def observe_router_hedge() -> None:
    """A token-less request past its TTFT hedge was duplicated onto a second
    replica (first token wins, loser cancelled server-side)."""
    REGISTRY.counter(
        "paddle_tpu_router_hedges_total",
        "cross-replica TTFT hedges launched by the router",
    ).inc()


def observe_late_result_dropped() -> None:
    """A partitioned-then-healed replica answered a request the router had
    already failed over: the late winner was dropped by the fleet dedup map
    — the exactly-once counter the router chaos drill gates on."""
    REGISTRY.counter(
        "paddle_tpu_router_late_results_dropped_total",
        "late replica results dropped by the fleet (tenant, request) dedup",
    ).inc()


def observe_router_shed(reason: str) -> None:
    """The router shed a submit fleet-wide ('no_replicas', or 'overload'
    when every live replica shed/was saturated) — always with the tightest
    retry_after_ms any replica offered, never a hang."""
    REGISTRY.counter(
        "paddle_tpu_router_shed_total",
        "submits shed by the router fleet-wide, by named reason",
    ).inc(reason=reason)


def observe_scale_decision(lever: str, direction: str) -> None:
    """The autoscaler admitted one scale action past its hysteresis /
    cooldown / flap gates; lever is 'serving' (spawn/drain) or 'train'
    (resize epoch), direction 'grow' or 'shrink'. Counters (not gauges) on
    purpose: decisions accumulate, and the controller's own process is
    expendable — rates come from deltas, not last-values."""
    REGISTRY.counter(
        "paddle_tpu_autoscaler_decisions_total",
        "autoscaler scale actions admitted, by lever and direction",
    ).inc(lever=lever, direction=direction)


def observe_scale_suppressed(reason: str) -> None:
    """The decision engine wanted an action but a rate-limit gate held it:
    reason is 'startup' (post-restart quiet period), 'cooldown',
    'flap' (direction reversal inside the flap window) or 'backoff'
    (after a rejected/timed-out resize)."""
    REGISTRY.counter(
        "paddle_tpu_autoscaler_suppressed_total",
        "autoscaler actions suppressed by rate-limit gates, by reason",
    ).inc(reason=reason)


def observe_scale_rejected(lever: str) -> None:
    """A pulled lever refused the order (resize rejected by the master's
    one-epoch-at-a-time rule, or timed out) — the backoff trigger."""
    REGISTRY.counter(
        "paddle_tpu_autoscaler_rejected_total",
        "autoscaler lever pulls rejected or timed out, by lever",
    ).inc(lever=lever)


# -- heartbeat snapshots + fleet aggregation ---------------------------------


def _flat_key(s: Sample) -> str:
    if not s.labels:
        return s.name
    return s.name + "{" + ",".join(f"{k}={v}" for k, v in s.labels) + "}"


def snapshot(registry: Optional[MetricsRegistry] = None) -> Dict[str, float]:
    """Flat {key: value} view of every sample — the payload a trainer
    piggybacks on its master heartbeat (a few hundred bytes of line-JSON)."""
    return {
        _flat_key(s): s.value for s in (registry or REGISTRY).collect()
    }


def aggregate_snapshots(snaps: Iterable[Mapping[str, float]]) -> Dict[str, float]:
    """Sum per-trainer snapshots key-by-key. Counters sum exactly; summed
    gauges read as fleet totals (per-trainer values stay visible in the raw
    snapshots a caller can keep)."""
    out: Dict[str, float] = {}
    for snap in snaps:
        for k, v in snap.items():
            try:
                out[k] = out.get(k, 0.0) + float(v)
            except (TypeError, ValueError):
                continue  # a garbled value must not poison the aggregate
    return out


class FleetMetrics:
    """Server-side store of per-trainer heartbeat snapshots (master plane).

    Entries expire after `ttl_s` without a fresh heartbeat (a dead trainer's
    last numbers must not inflate the fleet forever) and are dropped eagerly
    on deregister/eviction alongside the membership lease."""

    def __init__(self, ttl_s: float = 60.0):
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._by_id: Dict[str, Tuple[float, Dict[str, float]]] = {}

    def update(self, trainer_id: str, snap: Mapping[str, Any]) -> None:
        if not trainer_id or not isinstance(snap, Mapping):
            return
        clean = {
            str(k): float(v)
            for k, v in snap.items()
            if isinstance(v, (int, float))
        }
        with self._lock:
            self._by_id[trainer_id] = (time.monotonic(), clean)

    def drop(self, trainer_id: Optional[str]) -> None:
        if not trainer_id:
            return
        with self._lock:
            self._by_id.pop(trainer_id, None)

    def aggregate(self) -> Dict[str, Any]:
        cutoff = time.monotonic() - self.ttl_s
        with self._lock:
            live = {
                tid: snap
                for tid, (seen, snap) in self._by_id.items()
                if seen >= cutoff
            }
        return {
            "reporting_trainers": len(live),
            "counters": aggregate_snapshots(live.values()),
        }


# -- Prometheus exposition ---------------------------------------------------


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt(v: float) -> str:
    """Exposition-format a sample value losslessly: %g truncates to 6
    significant digits, which corrupts large counters (1234567 → 1.23457e+06
    = 1234570) and breaks rate() over long-running servers. Integral values
    print as integers, the rest with full float precision."""
    f = float(v)
    if f.is_integer() and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def to_prometheus_text(
    registry: Optional[MetricsRegistry] = None,
    fleet: Optional[Mapping[str, Any]] = None,
    extra: Optional[Mapping[str, float]] = None,
) -> str:
    """Render the registry (plus an optional fleet aggregate and flat extra
    gauges) in the Prometheus text exposition format."""
    samples = (registry or REGISTRY).collect()
    by_name: Dict[str, List[Sample]] = {}
    types: Dict[str, str] = {}
    for s in samples:
        by_name.setdefault(s.name, []).append(s)
        types.setdefault(s.name, "counter" if s.mtype == "counter" else s.mtype)
    lines: List[str] = []
    for name in sorted(by_name):
        lines.append(f"# TYPE {name} {types[name]}")
        for s in by_name[name]:
            if s.labels:
                lab = ",".join(f'{k}="{_escape(v)}"' for k, v in s.labels)
                lines.append(f"{name}{{{lab}}} {_fmt(s.value)}")
            else:
                lines.append(f"{name} {_fmt(s.value)}")
    if extra:
        for k, v in sorted(extra.items()):
            lines.append(f"# TYPE {k} gauge")
            lines.append(f"{k} {_fmt(v)}")
    if fleet:
        n = int(fleet.get("reporting_trainers", 0) or 0)
        lines.append("# TYPE paddle_tpu_fleet_reporting_trainers gauge")
        lines.append(f"paddle_tpu_fleet_reporting_trainers {n}")
        counters = fleet.get("counters") or {}
        if counters:
            lines.append("# TYPE paddle_tpu_fleet gauge")
            for k, v in sorted(counters.items()):
                lines.append(
                    f'paddle_tpu_fleet{{key="{_escape(str(k))}"}} '
                    f"{_fmt(v)}"
                )
    return "\n".join(lines) + "\n"
