"""Unified observability plane (ISSUE 7): tracing, metrics, profiling.

Three pillars over every subsystem (trainer, data pipeline, master RPC
plane, serving):

  * ``obs.trace``   — structured spans in a bounded per-process ring buffer,
                      near-zero cost when disabled (PADDLE_TPU_TRACE gate,
                      same discipline as PADDLE_TPU_TIMER), trace context
                      piggybacked on the line-JSON RPC frames, exported as
                      Perfetto-loadable Chrome trace-event JSON.
  * ``obs.metrics`` — counter/gauge/histogram registry absorbing the
                      existing StatSet/EventCounter telemetry; trainer
                      snapshots ride on master heartbeats into a fleet-wide
                      aggregate; Prometheus text via the `metrics` RPC and
                      ``python -m paddle_tpu.obs export``.
  * ``obs.profile`` — ``--profile pass:N`` jax.profiler capture of one pass
                      plus per-executable HLO cost buckets (the ROADMAP
                      item-2 target list) in the bench JSON.

README "Observability" has the operator-facing walkthrough."""

from paddle_tpu.obs import metrics, trace  # noqa: F401
from paddle_tpu.obs.metrics import REGISTRY  # noqa: F401
from paddle_tpu.obs.trace import (  # noqa: F401
    TRACER,
    enable_tracing,
    export_chrome,
    record_span,
    span,
)

__all__ = [
    "REGISTRY",
    "TRACER",
    "enable_tracing",
    "export_chrome",
    "metrics",
    "record_span",
    "span",
    "trace",
]
