"""Structured tracing: spans in a bounded per-process ring buffer.

One request or one training step crosses several threads (RPC handler,
serving engine, prefetch worker) and several PROCESSES (trainer → master →
standby; serving client → server); the per-subsystem timers in core/stats.py
cannot say "this 40 ms belonged to THAT request". A span fixes that: a named
interval carrying (trace_id, span_id, parent_id, wall-clock, attrs), recorded
into a fixed-size ring so a long-lived server never grows, and exported as
Chrome trace-event JSON loadable in Perfetto (chrome://tracing).

Gating discipline matches PADDLE_TPU_TIMER (core/stats.py): tracing is off
unless PADDLE_TPU_TRACE is set / enable_tracing() is called, and a disabled
`span()` costs one attribute lookup + a truth test — it returns a shared
no-op context manager, builds no strings, and takes no locks. Hot loops
(train dispatch, serving decode) therefore stamp spans unconditionally; the
lint in tests/test_lint_hotloop.py pins those sites and bans file I/O and
unconditional string formatting inside them.

Cross-process correlation: `wire_context()` serializes the current span as a
tiny {"t": trace_id, "s": span_id} dict that rides on the line-JSON RPC
frames (runtime/master.py, serving/server.py); the receiving side re-enters
it with `activate()`, so its spans join the caller's trace id. Each process
exports its own ring (`export_chrome()` / the `trace_export` RPC) and the
events stitch on trace_id — same trace, different pid rows in Perfetto."""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "TRACER",
    "Tracer",
    "activate",
    "current_context",
    "enable_tracing",
    "export_chrome",
    "merge_chrome",
    "record_span",
    "reset",
    "span",
    "wire_context",
]

# wall-clock microseconds: Chrome trace `ts` unit, and shared across processes
# so client/server spans of one RPC line up on a common axis
_now_us = lambda: time.time_ns() // 1000  # noqa: E731

_REQUIRED_EVENT_KEYS = ("ph", "ts", "pid", "tid", "name")  # golden-format keys


class Tracer:
    """Span recorder: enabled flag + ring buffer + per-thread context stack."""

    def __init__(self, capacity: Optional[int] = None):
        self.enabled = os.environ.get("PADDLE_TPU_TRACE", "").lower() not in (
            "", "0", "false", "off",
        )
        self.capacity = capacity or int(
            os.environ.get("PADDLE_TPU_TRACE_BUF", "8192")
        )
        self._lock = threading.Lock()
        self._ring: List[Optional[tuple]] = [None] * self.capacity
        self._head = 0  # next write index
        self._recorded = 0  # total spans ever recorded (ring may have dropped)
        self._tls = threading.local()
        # span ids are "<pid hex>.<n>": unique within a trace even when a
        # client and a forked server both mint ids
        self._ids = itertools.count(1)
        self._pid_tag = f"{os.getpid():x}"

    # -- context stack (thread-local) ---------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> Optional[Tuple[str, str]]:
        """(trace_id, span_id) of the innermost open span on this thread."""
        st = getattr(self._tls, "stack", None)
        return st[-1] if st else None

    def new_span_id(self) -> str:
        return f"{self._pid_tag}.{next(self._ids)}"

    def new_trace_id(self) -> str:
        return os.urandom(8).hex()

    # -- recording ----------------------------------------------------------
    def record(
        self,
        name: str,
        t0_us: int,
        dur_us: int,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        attrs: Optional[Dict[str, Any]],
    ) -> None:
        row = (
            name, int(t0_us), int(dur_us), trace_id, span_id, parent_id,
            attrs, threading.get_ident(),
        )
        with self._lock:
            self._ring[self._head] = row
            self._head = (self._head + 1) % self.capacity
            self._recorded += 1

    def snapshot(self) -> List[tuple]:
        """Buffered spans, oldest first (ring order)."""
        with self._lock:
            if self._recorded < self.capacity:
                return [r for r in self._ring[: self._head] if r is not None]
            return [
                r
                for r in self._ring[self._head:] + self._ring[: self._head]
                if r is not None
            ]

    @property
    def recorded(self) -> int:
        return self._recorded

    @property
    def dropped(self) -> int:
        return max(0, self._recorded - self.capacity)

    def reset(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._head = 0
            self._recorded = 0


TRACER = Tracer()


def enable_tracing(on: bool = True) -> None:
    TRACER.enabled = on


def reset() -> None:
    TRACER.reset()


# -- span APIs ---------------------------------------------------------------


class _NullSpan:
    """Shared no-op context manager: the entire disabled-path cost."""

    __slots__ = ()
    trace_id = span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    __slots__ = ("name", "attrs", "trace_id", "span_id", "parent_id", "_t0")

    def __init__(self, name: str, attrs: Optional[Dict[str, Any]]):
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> "_LiveSpan":
        parent = TRACER.current()
        if parent is None:
            self.trace_id, self.parent_id = TRACER.new_trace_id(), None
        else:
            self.trace_id, self.parent_id = parent[0], parent[1]
        self.span_id = TRACER.new_span_id()
        TRACER._stack().append((self.trace_id, self.span_id))
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = _now_us()
        st = TRACER._stack()
        # unwind to our own entry: a span leaked open by an exception below
        # us must not poison this thread's context stack forever
        want = (self.trace_id, self.span_id)
        while st:
            if st.pop() == want:
                break
        TRACER.record(
            self.name, self._t0, t1 - self._t0, self.trace_id, self.span_id,
            self.parent_id, self.attrs,
        )
        return False


def span(name: str, **attrs: Any):
    """`with span("train.dispatch", k=4): ...` — records one complete span.

    Disabled: returns a shared no-op CM (one truth test; `attrs` should
    therefore be cheap literals, never formatted strings — the hot-loop lint
    enforces this for the train/decode loops)."""
    if not TRACER.enabled:
        return _NULL_SPAN
    return _LiveSpan(name, attrs or None)


def record_span(
    name: str,
    t0_us: int,
    t1_us: int,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    attrs: Optional[Dict[str, Any]] = None,
) -> None:
    """Record a span whose interval was measured externally (queue waits,
    time-to-first-token, pass durations). Inherits the thread's current
    context when trace_id is not given. No-op when disabled."""
    if not TRACER.enabled:
        return
    if trace_id is None:
        cur = TRACER.current()
        if cur is not None:
            trace_id, parent_id = cur[0], parent_id or cur[1]
        else:
            trace_id = TRACER.new_trace_id()
    TRACER.record(
        name, t0_us, max(0, int(t1_us) - int(t0_us)), trace_id,
        TRACER.new_span_id(), parent_id, attrs,
    )


def span_from_monotonic(
    name: str,
    started_monotonic: float,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    attrs: Optional[Dict[str, Any]] = None,
) -> None:
    """Record [started_monotonic, now] measured on time.monotonic (the
    scheduler's clock) as a wall-clock span ending now."""
    if not TRACER.enabled:
        return
    t1 = _now_us()
    dur_us = int((time.monotonic() - started_monotonic) * 1e6)
    record_span(name, t1 - max(0, dur_us), t1, trace_id, parent_id, attrs)


# -- cross-process context ---------------------------------------------------


def current_context() -> Optional[Tuple[str, str]]:
    return TRACER.current()


def wire_context() -> Optional[Dict[str, str]]:
    """The current span as the tiny dict that piggybacks on line-JSON RPC
    frames (`"_trace": {"t": ..., "s": ...}`); None when disabled/no span."""
    if not TRACER.enabled:
        return None
    cur = TRACER.current()
    if cur is None:
        return None
    return {"t": cur[0], "s": cur[1]}


class _Activation:
    __slots__ = ("ctx", "_pushed")

    def __init__(self, ctx):
        self.ctx = ctx
        self._pushed = False

    def __enter__(self):
        if self.ctx is not None:
            TRACER._stack().append(self.ctx)
            self._pushed = True
        return self

    def __exit__(self, *exc):
        if self._pushed:
            st = TRACER._stack()
            while st:
                if st.pop() == self.ctx:
                    break
        return False


def activate(ctx) -> _Activation:
    """Re-enter a foreign span context so spans opened inside join its trace.

    `ctx` is a wire dict ({"t": ..., "s": ...}), a (trace_id, span_id)
    tuple, or None (no-op). Disabled tracing is also a no-op."""
    if not TRACER.enabled or ctx is None:
        return _Activation(None)
    if isinstance(ctx, dict):
        t, s = ctx.get("t"), ctx.get("s")
        if not t:
            return _Activation(None)
        return _Activation((str(t), str(s or "")))
    return _Activation((ctx[0], ctx[1]))


def server_span(name: str, wire_ctx, **attrs: Any):
    """RPC-handler helper: adopt the caller's wire context (when present) and
    open a span under it — `with server_span("rpc.get_task", req.get("_trace"))`.
    Disabled: the shared no-op CM."""
    if not TRACER.enabled:
        return _NULL_SPAN
    return _ServerSpan(name, wire_ctx, attrs or None)


class _ServerSpan:
    __slots__ = ("_act", "_span")

    def __init__(self, name, wire_ctx, attrs):
        self._act = activate(wire_ctx)
        self._span = _LiveSpan(name, attrs)

    def __enter__(self):
        self._act.__enter__()
        return self._span.__enter__()

    def __exit__(self, *exc):
        try:
            return self._span.__exit__(*exc)
        finally:
            self._act.__exit__(*exc)


# -- export ------------------------------------------------------------------


def _to_event(row: tuple, pid: int) -> Dict[str, Any]:
    name, t0, dur, trace_id, span_id, parent_id, attrs, tid = row
    args: Dict[str, Any] = {"trace_id": trace_id, "span_id": span_id}
    if parent_id:
        args["parent_id"] = parent_id
    if attrs:
        args.update(attrs)
    return {
        "ph": "X",
        "cat": "paddle_tpu",
        "name": name,
        "ts": t0,
        "dur": max(0, dur),
        "pid": pid,
        "tid": tid,
        "args": args,
    }


def export_chrome(path: Optional[str] = None) -> Dict[str, Any]:
    """Buffered spans as a Chrome trace-event JSON object (Perfetto /
    chrome://tracing loadable): {"traceEvents": [...complete events...]}.
    Every event carries ph/ts/dur/pid/tid/name plus trace/span ids in args.
    With `path`, also writes the JSON file."""
    pid = os.getpid()
    out = {
        "displayTimeUnit": "ms",
        "traceEvents": [_to_event(r, pid) for r in TRACER.snapshot()],
        "otherData": {"dropped_spans": TRACER.dropped},
    }
    if path:
        with open(path, "w") as f:
            json.dump(out, f)
    return out


def merge_chrome(traces: Iterable[Dict[str, Any]], path: Optional[str] = None) -> Dict[str, Any]:
    """Merge per-process exports (local + `trace_export` RPC results) into
    one loadable trace; events keep their origin pid rows."""
    events: List[Dict[str, Any]] = []
    dropped = 0
    for t in traces:
        if not t:
            continue
        events.extend(t.get("traceEvents", []))
        dropped += int(t.get("otherData", {}).get("dropped_spans", 0) or 0)
    events.sort(key=lambda e: e.get("ts", 0))
    out = {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "otherData": {"dropped_spans": dropped},
    }
    if path:
        with open(path, "w") as f:
            json.dump(out, f)
    return out


def validate_chrome(trace_obj: Dict[str, Any]) -> List[str]:
    """Golden-format check used by tests and the export CLI: returns the
    list of problems (empty = loadable shape with the required keys)."""
    problems: List[str] = []
    events = trace_obj.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    for i, ev in enumerate(events):
        for k in _REQUIRED_EVENT_KEYS:
            if k not in ev:
                problems.append(f"event {i} missing {k!r}")
    try:
        json.dumps(trace_obj)
    except (TypeError, ValueError) as e:
        problems.append(f"not JSON-serializable: {e}")
    return problems
