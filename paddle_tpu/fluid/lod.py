"""LoDTensor and SelectedRows — the fluid ragged/sparse value types.

Parity: paddle/framework/lod_tensor.h:80 (level-of-detail offsets over a
packed value tensor) and framework/selected_rows.h (row-sparse gradients).

TPU encoding: the packed data stays packed ([sum_len, D] with int32 offset
vectors per level, exactly the reference's Vector<size_t> lod) and ops use
segment ids derived from the offsets — static shapes, dynamic *values*, so
everything stays jit-compatible. Conversion helpers to/from the padded
[B, T, D]+lengths encoding used by paddle_tpu.nn round-trip losslessly."""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class LoDTensor:
    """data: [N, ...] packed values; lod: tuple of offset vectors, coarsest
    level first (lod[-1] segments individual sequences of rows)."""

    data: Array
    lod: Tuple[Array, ...] = ()

    def tree_flatten(self):
        return (self.data, self.lod), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, lod = children
        return cls(data, tuple(lod))

    @property
    def num_sequences(self) -> int:
        return len(self.lod[-1]) - 1 if self.lod else self.data.shape[0]

    def seq_lengths(self) -> Array:
        off = jnp.asarray(self.lod[-1])
        return off[1:] - off[:-1]

    def segment_ids(self) -> Array:
        """[N] int32: which (finest-level) sequence each row belongs to."""
        off = jnp.asarray(self.lod[-1])
        n = self.data.shape[0]
        return jnp.searchsorted(off, jnp.arange(n), side="right") - 1


def lod_from_lengths(lengths: Sequence[int]) -> Array:
    return jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(jnp.asarray(lengths, jnp.int32))]
    )


def to_padded(t: LoDTensor, max_len: int) -> Tuple[Array, Array]:
    """packed → ([B, T, ...] padded, [B] lengths); max_len static."""
    off = jnp.asarray(t.lod[-1])
    lengths = off[1:] - off[:-1]
    b = len(off) - 1
    idx = off[:-1, None] + jnp.arange(max_len)[None, :]
    idx = jnp.minimum(idx, t.data.shape[0] - 1)
    padded = t.data[idx.reshape(-1)].reshape((b, max_len) + t.data.shape[1:])
    mask = jnp.arange(max_len)[None, :] < lengths[:, None]
    padded = padded * mask.reshape(mask.shape + (1,) * (padded.ndim - 2)).astype(
        padded.dtype
    )
    return padded, lengths.astype(jnp.int32)


def from_padded(padded: np.ndarray, lengths: np.ndarray) -> LoDTensor:
    """host-side: padded [B, T, ...] + lengths → packed LoDTensor."""
    rows = [np.asarray(padded)[i, : int(l)] for i, l in enumerate(np.asarray(lengths))]
    data = np.concatenate(rows, 0) if rows else np.zeros((0,) + padded.shape[2:])
    return LoDTensor(jnp.asarray(data), (lod_from_lengths([len(r) for r in rows]),))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class SelectedRows:
    """Row-sparse value (selected_rows.h): `value[i]` belongs to row
    `rows[i]` of a dense [height, D] tensor. Duplicated rows allowed
    (grad accumulation is a scatter-add)."""

    rows: Array  # [K] int32
    value: Array  # [K, D]
    height: int

    def tree_flatten(self):
        return (self.rows, self.value), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        rows, value = children
        return cls(rows, value, height)

    def to_dense(self) -> Array:
        out = jnp.zeros((self.height,) + self.value.shape[1:], self.value.dtype)
        return out.at[self.rows].add(self.value)
