"""append_backward — framework/backward.cc parity.

The reference walks the forward ops in reverse, appending each op's
registered grad op (op-level transposition). The TPU-native equivalent
appends ONE `backward` region op that records (loss var, parameter list,
forward op count); the Executor differentiates the traced forward region
with jax autodiff, producing `<param>@GRAD` vars with identical semantics —
and, under jit, a backward that XLA schedules jointly with the forward."""

from __future__ import annotations

from typing import List, Optional, Sequence

from paddle_tpu.fluid.framework import Program, Variable


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence[Variable]] = None,
) -> List[tuple]:
    """Returns [(param, grad_var)] like the reference's append_backward."""
    program: Program = loss.block.program
    block = program.global_block()
    params = list(parameter_list) if parameter_list else [
        v
        for v in block.vars.values()
        if v.persistable
        and v.desc.trainable  # explicit registry, not name-substring matching
        and not v.name.endswith("@GRAD")
    ]
    n_fwd = len(block.desc.ops)
    grad_vars = []
    for p in params:
        g = block.create_var(p.name + "@GRAD", shape=p.desc.shape, dtype=p.desc.dtype)
        grad_vars.append((p, g))
    block.append_op(
        "backward",
        inputs={"Loss": loss, "Params": [p for p, _ in grad_vars]},
        outputs={"Grads": [g for _, g in grad_vars]},
        attrs={
            "loss": loss.name,
            "params": [p.name for p, _ in grad_vars],
            "fwd_op_count": n_fwd,
        },
    )
    return grad_vars


