"""Fluid-equivalent graph runtime (SURVEY §2.3): ProgramDesc/Block/OpDesc,
Scope, op registry, Executor (whole-block jit), append_backward, layers API,
optimizers. The reference's embryonic next-gen stack, rebuilt jax-native."""

from paddle_tpu.fluid import layers, optimizer
from paddle_tpu.fluid.backward import append_backward
from paddle_tpu.fluid.executor import Executor
from paddle_tpu.fluid.framework import (
    Block,
    OpDesc,
    Program,
    Scope,
    VarDesc,
    Variable,
)
from paddle_tpu.fluid.layers import default_main_program, reset_default_program
from paddle_tpu.fluid.ops import OPS

__all__ = [
    "Program", "Block", "Variable", "VarDesc", "OpDesc", "Scope", "Executor",
    "append_backward", "layers", "optimizer", "OPS",
    "default_main_program", "reset_default_program",
]
