"""Fluid operator registry (SURVEY §2.3 paddle/operators: 97 REGISTER_OP
triples). Each op is a pure jax-traceable function `fn(ctx, ins, attrs) ->
{slot: array}` keyed by the reference's op type names and input/output slot
names (X/Y/Out, Input/Filter/Output, Param/Grad/ParamOut...), so programs
written against the reference's op vocabulary execute unchanged.

No per-op backward implementations: append_backward (backward.py) transposes
whole traced regions with jax autodiff — the TPU-native replacement of
framework/backward.cc's op-level transposition."""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import Registry

OPS = Registry("fluid op")

Ins = Dict[str, List[Any]]


class OpContext:
    """Per-execution context: rng + training flag."""

    def __init__(self, rng=None, train: bool = True):
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._count = 0
        self.train = train

    def next_rng(self):
        self._count += 1
        return jax.random.fold_in(self._rng, self._count)


def op(name: str, **meta):
    def deco(fn):
        fn.op_meta = meta
        OPS.register(name)(fn)
        return fn

    return deco


def _one(ins: Ins, slot: str):
    v = ins.get(slot, [])
    return v[0] if v else None


def _bcast(x, y, axis: int):
    """The reference's elementwise broadcast: Y's shape must match a
    contiguous suffix/infix of X starting at `axis` (elementwise_op.h)."""
    if axis == -1 or x.ndim == y.ndim:
        return y
    shape = [1] * x.ndim
    for i, d in enumerate(y.shape):
        shape[axis + i] = d
    return y.reshape(shape)


# -- elementwise ------------------------------------------------------------

for _nm, _f in [
    ("elementwise_add", jnp.add), ("elementwise_sub", jnp.subtract),
    ("elementwise_mul", jnp.multiply), ("elementwise_div", jnp.divide),
    ("elementwise_max", jnp.maximum), ("elementwise_min", jnp.minimum),
    ("elementwise_pow", jnp.power),
]:
    def _mk(f):
        def fn(ctx, ins, attrs):
            x, y = _one(ins, "X"), _one(ins, "Y")
            return {"Out": f(x, _bcast(x, y, attrs.get("axis", -1)))}
        return fn
    op(_nm)(_mk(_f))


# -- activations ------------------------------------------------------------

for _nm, _f in [
    ("relu", jax.nn.relu), ("sigmoid", jax.nn.sigmoid), ("tanh", jnp.tanh),
    ("sqrt", jnp.sqrt), ("abs", jnp.abs), ("exp", jnp.exp), ("log", jnp.log),
    ("square", jnp.square), ("reciprocal", lambda x: 1.0 / x),
    ("softsign", lambda x: x / (1 + jnp.abs(x))),
    ("soft_relu", lambda x: jnp.log1p(jnp.exp(jnp.clip(x, -40, 40)))),
]:
    def _mka(f):
        def fn(ctx, ins, attrs):
            return {"Y": f(_one(ins, "X"))}
        return fn
    op(_nm)(_mka(_f))


@op("brelu")
def _brelu(ctx, ins, attrs):
    return {"Y": jnp.clip(_one(ins, "X"), attrs.get("t_min", 0.0), attrs.get("t_max", 24.0))}


@op("leaky_relu")
def _leaky(ctx, ins, attrs):
    a = attrs.get("alpha", 0.02)
    x = _one(ins, "X")
    return {"Y": jnp.where(x >= 0, x, a * x)}


# -- linear algebra ---------------------------------------------------------


@op("mul")
def _mul(ctx, ins, attrs):
    """X [flattened to 2D at x_num_col_dims] @ Y (mul_op.cc)."""
    x, y = _one(ins, "X"), _one(ins, "Y")
    xd = attrs.get("x_num_col_dims", 1)
    yd = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape(int(np.prod(xs[:xd])), -1)
    y2 = y.reshape(int(np.prod(ys[:yd])), -1)
    out = x2 @ y2
    return {"Out": out.reshape(xs[:xd] + ys[yd:])}


@op("matmul")
def _matmul(ctx, ins, attrs):
    x, y = _one(ins, "X"), _one(ins, "Y")
    if attrs.get("transpose_X"):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("transpose_Y"):
        y = jnp.swapaxes(y, -1, -2)
    return {"Out": x @ y}


# -- shape ops --------------------------------------------------------------


@op("reshape")
def _reshape(ctx, ins, attrs):
    return {"Out": _one(ins, "X").reshape(attrs["shape"])}


@op("transpose")
def _transpose(ctx, ins, attrs):
    return {"Out": jnp.transpose(_one(ins, "X"), attrs["axis"])}


@op("concat")
def _concat(ctx, ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))}


@op("split")
def _split(ctx, ins, attrs):
    x = _one(ins, "X")
    axis = attrs.get("axis", 0)
    if "sections" in attrs and attrs["sections"]:
        idx = np.cumsum(attrs["sections"])[:-1]
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, attrs["num"], axis=axis)
    return {"Out": list(parts)}


@op("slice")
def _slice(ctx, ins, attrs):
    x = _one(ins, "X")
    sl = [slice(None)] * x.ndim
    for ax, st, en in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        sl[ax] = slice(st, en)
    return {"Out": x[tuple(sl)]}


@op("cast")
def _cast(ctx, ins, attrs):
    return {"Out": _one(ins, "X").astype(attrs["dtype"])}


@op("scale")
def _scale(ctx, ins, attrs):
    return {"Out": _one(ins, "X") * attrs.get("scale", 1.0)}


# -- reductions / metrics ---------------------------------------------------


@op("mean")
def _mean(ctx, ins, attrs):
    return {"Out": jnp.mean(_one(ins, "X"))}


@op("sum")
def _sum(ctx, ins, attrs):
    out = ins["X"][0]
    for x in ins["X"][1:]:
        out = out + x
    return {"Out": out}


@op("reduce_sum")
def _rsum(ctx, ins, attrs):
    return {"Out": jnp.sum(_one(ins, "X"), axis=attrs.get("dim"),
                           keepdims=attrs.get("keep_dim", False))}


@op("reduce_mean")
def _rmean(ctx, ins, attrs):
    return {"Out": jnp.mean(_one(ins, "X"), axis=attrs.get("dim"),
                            keepdims=attrs.get("keep_dim", False))}


@op("top_k")
def _topk(ctx, ins, attrs):
    vals, idx = jax.lax.top_k(_one(ins, "X"), attrs.get("k", 1))
    return {"Out": vals, "Indices": idx.astype(jnp.int32)}


@op("accuracy")
def _accuracy(ctx, ins, attrs):
    """Top-k accuracy: label anywhere in the Indices columns counts
    (accuracy_op semantics)."""
    pred = _one(ins, "Indices")
    if pred is None:
        pred = _one(ins, "Out")
    label = _one(ins, "Label").reshape(-1)
    if pred.ndim == 1:
        pred = pred[:, None]
    hit = jnp.any(pred == label[:, None], axis=-1)
    return {"Accuracy": jnp.mean(hit.astype(jnp.float32))}


# -- nn ---------------------------------------------------------------------


@op("softmax")
def _softmax(ctx, ins, attrs):
    return {"Y": jax.nn.softmax(_one(ins, "X"), axis=-1)}


@op("cross_entropy")
def _xent(ctx, ins, attrs):
    x = _one(ins, "X")  # probabilities [N, C] (the reference takes probs)
    label = _one(ins, "Label")
    if attrs.get("soft_label"):
        return {"Y": -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), -1, keepdims=True)}
    idx = label.reshape(-1).astype(jnp.int32)
    picked = jnp.take_along_axis(x, idx[:, None], axis=-1)
    return {"Y": -jnp.log(jnp.maximum(picked, 1e-20))}


@op("softmax_with_cross_entropy")
def _smxent(ctx, ins, attrs):
    logits = _one(ins, "Logits")
    label = _one(ins, "Label")
    logp = jax.nn.log_softmax(logits, axis=-1)
    idx = label.reshape(-1).astype(jnp.int32)
    loss = -jnp.take_along_axis(logp, idx[:, None], axis=-1)
    return {"Loss": loss, "Softmax": jnp.exp(logp)}


@op("conv2d")
def _conv2d(ctx, ins, attrs):
    """NCHW conv (conv_op.cc). Lowered to lax.conv_general_dilated — XLA maps
    it onto the MXU; the reference's im2col+gemm is a GPU idiom."""
    x = _one(ins, "Input")
    w = _one(ins, "Filter")  # [O, I/g, kH, kW]
    strides = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0])
    groups = attrs.get("groups", 1)
    dil = attrs.get("dilations", [1, 1])
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return {"Output": out}


@op("pool2d")
def _pool2d(ctx, ins, attrs):
    x = _one(ins, "X")
    ksize = attrs.get("ksize", [2, 2])
    strides = attrs.get("strides", ksize)
    pads = attrs.get("paddings", [0, 0])
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling"):
        ksize = list(x.shape[2:])
        strides, pads = ksize, [0, 0]
    window = (1, 1, *ksize)
    stride = (1, 1, *strides)
    padding = ((0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1]))
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, stride, padding)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride, padding)
        out = s / float(np.prod(ksize))
    return {"Out": out}


@op("batch_norm")
def _batch_norm(ctx, ins, attrs):
    x = _one(ins, "X")  # NCHW or NC
    scale, bias = _one(ins, "Scale"), _one(ins, "Bias")
    mean, var = _one(ins, "Mean"), _one(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    axes = tuple(i for i in range(x.ndim) if i != 1)
    shape = [1, -1] + [1] * (x.ndim - 2)
    if ctx.train and not attrs.get("is_test", False):
        bm = jnp.mean(x, axis=axes)
        bv = jnp.var(x, axis=axes)
        y = (x - bm.reshape(shape)) / jnp.sqrt(bv.reshape(shape) + eps)
        new_mean = momentum * mean + (1 - momentum) * bm
        new_var = momentum * var + (1 - momentum) * bv
        out = {"Y": y * scale.reshape(shape) + bias.reshape(shape),
               "MeanOut": new_mean, "VarianceOut": new_var,
               "SavedMean": bm, "SavedVariance": bv}
    else:
        y = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
        out = {"Y": y * scale.reshape(shape) + bias.reshape(shape),
               "MeanOut": mean, "VarianceOut": var,
               "SavedMean": mean, "SavedVariance": var}
    return out


@op("dropout")
def _dropout(ctx, ins, attrs):
    x = _one(ins, "X")
    p = attrs.get("dropout_prob", 0.5)
    if not ctx.train or attrs.get("is_test", False) or p == 0.0:
        return {"Out": x, "Mask": jnp.ones_like(x)}
    keep = jax.random.bernoulli(ctx.next_rng(), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype) / (1.0 - p)
    return {"Out": x * mask, "Mask": mask}


@op("lookup_table")
def _lookup(ctx, ins, attrs):
    w = _one(ins, "W")
    ids = _one(ins, "Ids")
    # the reference feeds ids as [N, 1] (LoD column); squeeze only that case
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    return {"Out": w[ids]}


@op("layer_norm")
def _layer_norm(ctx, ins, attrs):
    x = _one(ins, "X")
    scale, bias = _one(ins, "Scale"), _one(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) / jnp.sqrt(var + eps)
    if scale is not None:
        y = y * scale
    if bias is not None:
        y = y + bias
    return {"Y": y, "Mean": mu.squeeze(-1), "Variance": var.squeeze(-1)}


# -- fills / random ---------------------------------------------------------


@op("fill_constant")
def _fill(ctx, ins, attrs):
    return {"Out": jnp.full(attrs["shape"], attrs.get("value", 0.0),
                            dtype=attrs.get("dtype", jnp.float32))}


@op("uniform_random")
def _uniform(ctx, ins, attrs):
    lo, hi = attrs.get("min", -1.0), attrs.get("max", 1.0)
    return {"Out": jax.random.uniform(ctx.next_rng(), tuple(attrs["shape"]),
                                      minval=lo, maxval=hi)}


@op("gaussian_random")
def _gauss(ctx, ins, attrs):
    return {"Out": attrs.get("mean", 0.0) + attrs.get("std", 1.0)
            * jax.random.normal(ctx.next_rng(), tuple(attrs["shape"]))}


# -- control-flow helpers ---------------------------------------------------


@op("less_than")
def _less(ctx, ins, attrs):
    return {"Out": _one(ins, "X") < _one(ins, "Y")}


@op("increment")
def _incr(ctx, ins, attrs):
    return {"Out": _one(ins, "X") + attrs.get("step", 1.0)}


# -- optimizer ops (sgd_op.cc, momentum_op.cc, adam_op.cc ...) --------------


@op("sgd")
def _sgd(ctx, ins, attrs):
    p, g, lr = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "LearningRate")
    return {"ParamOut": p - lr * g}


@op("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "Velocity")
    lr = _one(ins, "LearningRate")
    mu = attrs.get("mu", 0.9)
    v_new = mu * v + g
    if attrs.get("use_nesterov"):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {"ParamOut": p_new, "VelocityOut": v_new}


@op("adam")
def _adam(ctx, ins, attrs):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    m, v = _one(ins, "Moment1"), _one(ins, "Moment2")
    b1p, b2p = _one(ins, "Beta1Pow"), _one(ins, "Beta2Pow")
    lr = _one(ins, "LearningRate")
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    mhat = m_new / (1 - b1p)
    vhat = v_new / (1 - b2p)
    p_new = p - lr * mhat / (jnp.sqrt(vhat) + eps)
    return {"ParamOut": p_new, "Moment1Out": m_new, "Moment2Out": v_new,
            "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, mom = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "Moment")
    lr = _one(ins, "LearningRate")
    eps = attrs.get("epsilon", 1e-6)
    mom_new = mom + g * g
    return {"ParamOut": p - lr * g / (jnp.sqrt(mom_new) + eps),
            "MomentOut": mom_new}


@op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    ms, mom = _one(ins, "MeanSquare"), _one(ins, "Moment")
    lr = _one(ins, "LearningRate")
    rho = attrs.get("decay", 0.9)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    ms_new = rho * ms + (1 - rho) * g * g
    mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": p - mom_new, "MeanSquareOut": ms_new, "MomentOut": mom_new}


# ---------------------------------------------------------------------------
# round-3 breadth: the remaining reference operator families
# (paddle/operators/*.cc — elementwise/math, losses, sparse/sequence/LoD,
# rnn units, more optimizers). Control flow (cond/while/recurrent) lives in
# the Executor, which owns sub-block tracing.
# ---------------------------------------------------------------------------


@op("minus")
def _minus(ctx, ins, attrs):
    return {"Out": _one(ins, "X") - _one(ins, "Y")}


@op("sign")
def _sign(ctx, ins, attrs):
    return {"Out": jnp.sign(_one(ins, "X"))}


@op("clip")
def _clip(ctx, ins, attrs):
    return {"Out": jnp.clip(_one(ins, "X"), attrs.get("min"), attrs.get("max"))}


@op("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    return {"Y": jnp.zeros_like(_one(ins, "X"))}


@op("fill_constant_batch_size_like")
def _fill_cbsl(ctx, ins, attrs):
    x = _one(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    return {"Out": jnp.full(shape, attrs.get("value", 0.0),
                            dtype=attrs.get("dtype", jnp.float32))}


@op("gather")
def _gather(ctx, ins, attrs):
    return {"Out": _one(ins, "X")[_one(ins, "Index").astype(jnp.int32)]}


@op("scatter")
def _scatter(ctx, ins, attrs):
    ref, idx, upd = _one(ins, "Ref"), _one(ins, "Index"), _one(ins, "Updates")
    return {"Out": ref.at[idx.astype(jnp.int32)].add(upd)}


@op("multiplex")
def _multiplex(ctx, ins, attrs):
    idx = ins["Ids"][0].astype(jnp.int32).reshape(-1)
    stacked = jnp.stack(ins["X"], axis=0)  # [N, B, D]
    return {"Out": stacked[idx, jnp.arange(stacked.shape[1])]}


@op("pad")
def _pad(ctx, ins, attrs):
    x = _one(ins, "X")
    p = attrs["paddings"]  # flat [lo0, hi0, lo1, hi1, ...]
    cfg = [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, cfg, constant_values=attrs.get("pad_value", 0.0))}


@op("crop")
def _crop(ctx, ins, attrs):
    x = _one(ins, "X")
    offsets = attrs.get("offsets", [0] * x.ndim)
    shape = attrs.get("shape") or _one(ins, "Y").shape
    sl = tuple(slice(int(o), int(o) + int(s)) for o, s in zip(offsets, shape))
    return {"Out": x[sl]}


@op("prelu")
def _prelu(ctx, ins, attrs):
    x, alpha = _one(ins, "X"), _one(ins, "Alpha")
    return {"Out": jnp.where(x > 0, x, alpha * x)}


@op("conv_shift")
def _conv_shift(ctx, ins, attrs):
    x, y = _one(ins, "X"), _one(ins, "Y")  # [B, M], [B, N] (N odd, N<=M)
    n = y.shape[1]
    half = n // 2
    m = x.shape[1]
    idx = (jnp.arange(m)[:, None] + jnp.arange(-half, half + 1)[None, :]) % m
    return {"Out": jnp.einsum("bmn,bn->bm", x[:, idx], y)}


@op("cos_sim")
def _cos_sim(ctx, ins, attrs):
    x, y = _one(ins, "X"), _one(ins, "Y")
    nx = jnp.linalg.norm(x, axis=-1, keepdims=True)
    ny = jnp.linalg.norm(y, axis=-1, keepdims=True)
    out = jnp.sum(x * y, -1, keepdims=True) / jnp.maximum(nx * ny, 1e-12)
    return {"Out": out, "XNorm": nx, "YNorm": ny}


@op("lrn")
def _lrn(ctx, ins, attrs):
    x = _one(ins, "X")  # NCHW in the reference; accept channels-last too
    n = attrs.get("n", 5)
    alpha, beta, k = attrs.get("alpha", 1e-4), attrs.get("beta", 0.75), attrs.get("k", 2.0)
    sq = jnp.square(x)
    half = n // 2
    # channel axis 1 (reference layout)
    pads = [(0, 0)] * x.ndim
    pads[1] = (half, half)
    padded = jnp.pad(sq, pads)
    acc = sum(
        jax.lax.slice_in_dim(padded, i, i + x.shape[1], axis=1) for i in range(n)
    )
    mid = k + alpha * acc
    return {"Out": x / jnp.power(mid, beta), "MidOut": mid}


@op("pool_with_index")
def _pool_with_index(ctx, ins, attrs):
    x = _one(ins, "X")  # NCHW
    ks, st = attrs["ksize"], attrs.get("strides", attrs["ksize"])
    b, c, h, w = x.shape
    oh = (h - ks[0]) // st[0] + 1
    ow = (w - ks[1]) // st[1] + 1
    ii = (jnp.arange(oh) * st[0])[:, None, None, None] + jnp.arange(ks[0])[None, None, :, None]
    jj = (jnp.arange(ow) * st[1])[None, :, None, None] + jnp.arange(ks[1])[None, None, None, :]
    win = x[:, :, ii, jj]  # [B, C, oh, ow, kh, kw]
    flat = win.reshape(b, c, oh, ow, -1)
    arg = flat.argmax(-1)
    out = jnp.take_along_axis(flat, arg[..., None], -1)[..., 0]
    ki, kj = arg // ks[1], arg % ks[1]
    rows = (jnp.arange(oh) * st[0])[None, None, :, None] + ki
    cols = (jnp.arange(ow) * st[1])[None, None, None, :] + kj
    return {"Out": out, "Mask": rows * w + cols}


# -- losses -----------------------------------------------------------------


@op("huber_loss")
def _huber_loss(ctx, ins, attrs):
    x, y = _one(ins, "X"), _one(ins, "Y")
    d = attrs.get("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    out = jnp.where(a <= d, 0.5 * r * r, d * (a - 0.5 * d))
    return {"Out": out, "Residual": r}


@op("modified_huber_loss")
def _modified_huber_loss(ctx, ins, attrs):
    x, y = _one(ins, "X"), _one(ins, "Y")  # y in {0,1}
    s = 2.0 * y - 1.0
    m = x.reshape(s.shape) * s
    out = jnp.where(m < -1, -4.0 * m, jnp.square(jnp.maximum(1.0 - m, 0.0)))
    return {"Out": out.reshape(x.shape), "IntermediateVal": m.reshape(x.shape)}


@op("rank_loss")
def _rank_loss(ctx, ins, attrs):
    lab = _one(ins, "Label")
    l, r = _one(ins, "Left"), _one(ins, "Right")
    d = l - r
    return {"Out": jnp.logaddexp(0.0, d) - lab * d}


@op("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    lab = _one(ins, "Label")
    x1, x2 = _one(ins, "X1"), _one(ins, "X2")
    margin = attrs.get("margin", 0.0)
    act = jnp.maximum(0.0, -lab * (x1 - x2) + margin)
    return {"Out": act, "Activated": (act > 0).astype(x1.dtype)}


@op("smooth_l1_loss")
def _smooth_l1_loss(ctx, ins, attrs):
    x, y = _one(ins, "X"), _one(ins, "Y")
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    d = x - y
    iw, ow = _one(ins, "InsideWeight"), _one(ins, "OutsideWeight")
    if iw is not None:
        d = d * iw
    a = jnp.abs(d)
    val = jnp.where(a < 1.0 / s2, 0.5 * s2 * d * d, a - 0.5 / s2)
    if ow is not None:
        val = val * ow
    return {"Out": val.reshape(x.shape[0], -1).sum(-1, keepdims=True), "Diff": d}


@op("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    x, y = _one(ins, "X"), _one(ins, "Y")
    d = x - y
    return {"Out": jnp.sum(jnp.square(d), -1, keepdims=True), "sub_result": d}


@op("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    return {"Out": jnp.sum(jnp.square(_one(ins, "X"))).reshape(1)}


@op("l1_norm")
def _l1_norm(ctx, ins, attrs):
    return {"Out": jnp.sum(jnp.abs(_one(ins, "X"))).reshape(1)}


@op("sigmoid_cross_entropy_with_logits")
def _sce_logits(ctx, ins, attrs):
    x, lab = _one(ins, "X"), _one(ins, "Label")
    return {"Out": jnp.maximum(x, 0) - x * lab + jnp.logaddexp(0.0, -jnp.abs(x))}


@op("linear_chain_crf")
def _linear_chain_crf(ctx, ins, attrs):
    from paddle_tpu.ops import crf as crf_ops

    emission, transition = _one(ins, "Emission"), _one(ins, "Transition")
    label = _one(ins, "Label")
    # packed single-sequence form: [T, n_tags] emission, [T] labels
    em = emission[None] if emission.ndim == 2 else emission
    lb = label.reshape(1, -1) if label.ndim <= 1 else label
    lengths = jnp.full((em.shape[0],), em.shape[1], jnp.int32)
    ll = crf_ops.crf_log_likelihood(em, lb.astype(jnp.int32), lengths, transition)
    return {"LogLikelihood": -ll}


# -- rnn units --------------------------------------------------------------


@op("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    x, c_prev = _one(ins, "X"), _one(ins, "C_prev")  # x: [B, 4H]
    f_bias = attrs.get("forget_bias", 0.0)
    i, f, o, j = jnp.split(x, 4, -1)
    c = c_prev * jax.nn.sigmoid(f + f_bias) + jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jnp.tanh(c) * jax.nn.sigmoid(o)
    return {"C": c, "H": h}


@op("gru_unit")
def _gru_unit(ctx, ins, attrs):
    from paddle_tpu.ops import rnn as rnn_ops

    x, h_prev = _one(ins, "Input"), _one(ins, "HiddenPrev")  # x: [B, 3H]
    w, b = _one(ins, "Weight"), _one(ins, "Bias")
    hdim = h_prev.shape[-1]
    if b is not None:
        x = x + b.reshape(1, -1)
    p = rnn_ops.GruParams(w_hzr=w[:, : 2 * hdim], w_hc=w[:, 2 * hdim:],
                          bias=jnp.zeros((3 * hdim,), x.dtype))
    h = rnn_ops.gru_step(x, h_prev, p)
    return {"Hidden": h}


@op("lstm")
def _lstm(ctx, ins, attrs):
    """Whole-sequence LSTM over a padded [B, T, 4H] projection (lstm_op.cc;
    the packed-LoD form feeds through sequence feeds)."""
    from paddle_tpu.ops import rnn as rnn_ops

    proj = _one(ins, "Input")
    w, b = _one(ins, "Weight"), _one(ins, "Bias")
    hdim = proj.shape[-1] // 4
    lengths = _one(ins, "SeqLengths")
    mask = (
        jnp.arange(proj.shape[1])[None, :] < lengths[:, None]
        if lengths is not None
        else jnp.ones(proj.shape[:2])
    ).astype(proj.dtype)
    p = rnn_ops.LstmParams(w_hh=w, bias=b if b is not None else jnp.zeros((4 * hdim,)))
    # the reference lstm_op emits the FULL cell-state sequence in 'Cell'
    # (lstm_op.cc BatchCellPreAct/Cell outputs) — return_cell_seq collects it
    hs, cs, h_last = rnn_ops.lstm_scan(
        proj, mask, p, reverse=attrs.get("is_reverse", False), return_cell_seq=True
    )
    return {"Hidden": hs, "Cell": cs, "LastH": h_last}


@op("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    from paddle_tpu.ops import conv as conv_ops

    x, w = _one(ins, "Input"), _one(ins, "Filter")  # NCHW, [Cin, Cout, kh, kw]
    xs = jnp.transpose(x, (0, 2, 3, 1))
    wt = jnp.transpose(w, (2, 3, 1, 0))  # -> [kh, kw, Cout, Cin]
    st = attrs.get("strides", [1, 1])
    pd = attrs.get("paddings", [0, 0])
    out = conv_ops.conv2d_transpose(xs, wt, tuple(st), tuple(pd))
    return {"Output": jnp.transpose(out, (0, 3, 1, 2))}


# -- sequence / LoD ops ------------------------------------------------------


def _lod_of(x):
    from paddle_tpu.fluid.lod import LoDTensor

    assert isinstance(x, LoDTensor), "sequence op needs a LoDTensor input"
    return x


@op("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    from paddle_tpu.fluid.lod import LoDTensor

    x = _lod_of(_one(ins, "X"))
    seg = x.segment_ids()
    n_seq = x.num_sequences
    pt = attrs.get("pooltype", attrs.get("pool_type", "AVERAGE")).upper()
    data = x.data
    if pt == "SUM":
        out = jax.ops.segment_sum(data, seg, n_seq)
    elif pt == "AVERAGE":
        s = jax.ops.segment_sum(data, seg, n_seq)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],)), seg, n_seq)
        out = s / jnp.maximum(cnt, 1.0)[:, None]
    elif pt == "SQRT":
        s = jax.ops.segment_sum(data, seg, n_seq)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],)), seg, n_seq)
        out = s / jnp.sqrt(jnp.maximum(cnt, 1.0))[:, None]
    elif pt == "MAX":
        out = jax.ops.segment_max(data, seg, n_seq)
    elif pt == "LAST":
        off = jnp.asarray(x.lod[-1])
        out = data[jnp.maximum(off[1:] - 1, 0)]
    elif pt == "FIRST":
        out = data[jnp.asarray(x.lod[-1])[:-1]]
    else:
        raise ValueError(f"sequence_pool: unknown pooltype {pt}")
    return {"Out": out}


@op("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    from paddle_tpu.fluid.lod import LoDTensor

    x = _lod_of(_one(ins, "X"))
    seg = x.segment_ids()
    n = x.num_sequences
    v = x.data.reshape(-1)
    mx = jax.ops.segment_max(v, seg, n)
    e = jnp.exp(v - mx[seg])
    den = jax.ops.segment_sum(e, seg, n)
    return {"Out": LoDTensor((e / den[seg]).reshape(x.data.shape), x.lod)}


@op("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    """Concat same-#sequences LoD tensors along time (sequence_concat_op.cc
    axis=0 level=0): result sequence i = concat of every input's sequence i.
    Jit-compatible: output row positions are computed arithmetically from the
    lod offsets and written with one scatter (static total row count)."""
    from paddle_tpu.fluid.lod import LoDTensor

    xs = [_lod_of(v) for v in ins["X"]]
    lens = [x.seq_lengths() for x in xs]  # each [S]
    new_lens = sum(lens[1:], lens[0])
    new_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(new_lens).astype(jnp.int32)]
    )
    total = sum(int(x.data.shape[0]) for x in xs)
    out = jnp.zeros((total,) + xs[0].data.shape[1:], xs[0].data.dtype)
    prior = jnp.zeros_like(lens[0])  # lengths already placed per sequence
    for x, ln in zip(xs, lens):
        seg = x.segment_ids()
        off = jnp.asarray(x.lod[-1])
        local = jnp.arange(x.data.shape[0]) - off[seg]
        target = new_off[seg] + prior[seg] + local
        out = out.at[target].set(x.data)
        prior = prior + ln
    return {"Out": LoDTensor(out, (new_off,))}


@op("seq_expand")
def _seq_expand(ctx, ins, attrs):
    """seq_expand_op.cc: repeat each row/sequence of X to match Y's lod."""
    from paddle_tpu.fluid.lod import LoDTensor

    x, y = _one(ins, "X"), _lod_of(_one(ins, "Y"))
    seg = y.segment_ids()
    data = x.data if isinstance(x, LoDTensor) else x
    return {"Out": LoDTensor(data[seg], y.lod)}


@op("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """Context-window projection over each sequence (sequence_conv_op.cc):
    im2col with context_length rows around each position, then a GEMM."""
    from paddle_tpu.fluid.lod import LoDTensor

    x = _lod_of(_one(ins, "X"))
    w = _one(ins, "Filter")  # [ctx_len * D, M]
    ctx_len = attrs.get("contextLength", attrs.get("context_length", 3))
    start = attrs.get("contextStart", attrs.get("context_start", -(ctx_len // 2)))
    data = x.data
    n, d = data.shape
    seg = x.segment_ids()
    cols = []
    idx = jnp.arange(n)
    for o in range(ctx_len):
        j = idx + start + o
        valid = (j >= 0) & (j < n)
        jc = jnp.clip(j, 0, n - 1)
        same = seg[jc] == seg  # stay inside the sequence
        cols.append(jnp.where((valid & same)[:, None], data[jc], 0.0))
    im2col = jnp.concatenate(cols, -1)  # [N, ctx_len*D]
    return {"Out": LoDTensor(im2col @ w, x.lod)}


# -- sparse (SelectedRows) ---------------------------------------------------


@op("sgd_sparse")
def _sgd_sparse(ctx, ins, attrs):
    """SGD accepting a SelectedRows gradient (sgd_op.cc's SelectedRows
    branch): scatter-add the sparse rows scaled by -lr."""
    from paddle_tpu.fluid.lod import SelectedRows

    p, g, lr = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "LearningRate")
    assert isinstance(g, SelectedRows)
    return {"ParamOut": p.at[g.rows].add(-lr * g.value)}


# -- more optimizers ---------------------------------------------------------


@op("adamax")
def _adamax(ctx, ins, attrs):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    m, u = _one(ins, "Moment"), _one(ins, "InfNorm")
    lr, b1pow = _one(ins, "LearningRate"), _one(ins, "Beta1Pow")
    b1, b2, eps = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999), attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    u_new = jnp.maximum(b2 * u, jnp.abs(g))
    p_new = p - (lr / (1 - b1pow)) * m_new / (u_new + eps)
    return {"ParamOut": p_new, "MomentOut": m_new, "InfNormOut": u_new}


@op("adadelta")
def _adadelta(ctx, ins, attrs):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    avg_sq, avg_upd = _one(ins, "AvgSquaredGrad"), _one(ins, "AvgSquaredUpdate")
    rho, eps = attrs.get("rho", 0.95), attrs.get("epsilon", 1e-6)
    sq = rho * avg_sq + (1 - rho) * g * g
    upd = jnp.sqrt(avg_upd + eps) / jnp.sqrt(sq + eps) * g
    return {
        "ParamOut": p - upd,
        "AvgSquaredGradOut": sq,
        "AvgSquaredUpdateOut": rho * avg_upd + (1 - rho) * upd * upd,
    }


@op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    p, g, m = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "Moment")
    lr = _one(ins, "LearningRate")
    decay, eps = attrs.get("decay", 0.95), attrs.get("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * g * g
    return {"ParamOut": p - lr * g / (jnp.sqrt(m_new) + eps), "MomentOut": m_new}


@op("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    p, g, lr = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "LearningRate")
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    prox = p - lr * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    return {"ParamOut": p_new}


@op("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    p, g, m = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "Moment")
    lr = _one(ins, "LearningRate")
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    m_new = m + g * g
    alr = lr / jnp.sqrt(m_new + 1e-12)
    prox = p - alr * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - alr * l1, 0.0) / (1.0 + alr * l2)
    return {"ParamOut": p_new, "MomentOut": m_new}


@op("auc")
def _auc(ctx, ins, attrs):
    from paddle_tpu.metrics.evaluators import AucEvaluator  # host-side math

    out, lab = _one(ins, "Out"), _one(ins, "Label")
    # discretized AUC fully in-graph (the reference op is also batch-local)
    p = out[:, 1] if out.ndim == 2 and out.shape[1] == 2 else out.reshape(-1)
    y = lab.reshape(-1)
    bins = 1024
    idx = jnp.clip((p * bins).astype(jnp.int32), 0, bins - 1)
    pos = jnp.zeros(bins).at[idx].add((y == 1).astype(jnp.float32))
    neg = jnp.zeros(bins).at[idx].add((y != 1).astype(jnp.float32))
    tp = jnp.cumsum(pos[::-1])
    fp = jnp.cumsum(neg[::-1])
    tpr = jnp.concatenate([jnp.zeros(1), tp / jnp.maximum(tp[-1], 1.0)])
    fpr = jnp.concatenate([jnp.zeros(1), fp / jnp.maximum(fp[-1], 1.0)])
    return {"AUC": jnp.trapezoid(tpr, fpr).reshape(1)}


@op("precision_recall")
def _precision_recall(ctx, ins, attrs):
    pred, lab = _one(ins, "MaxProbs"), _one(ins, "Labels")
    ids = _one(ins, "Indices")
    cls = attrs.get("class_number", int(jnp.asarray(ids).max()) + 1 if ids is not None else 2)
    p = (ids if ids is not None else pred.argmax(-1)).reshape(-1)
    y = lab.reshape(-1)
    onehot_p = jax.nn.one_hot(p, cls)
    onehot_y = jax.nn.one_hot(y, cls)
    tp = (onehot_p * onehot_y).sum(0)
    fp = (onehot_p * (1 - onehot_y)).sum(0)
    fn = ((1 - onehot_p) * onehot_y).sum(0)
    prec = tp / jnp.maximum(tp + fp, 1e-12)
    rec = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * prec * rec / jnp.maximum(prec + rec, 1e-12)
    macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
    return {"BatchMetrics": jnp.concatenate([macro, prec, rec, f1]),
            "AccumStatesInfo": jnp.stack([tp, fp, fn], 1)}


# -- IO ops (feed_op.cc / fetch_op.cc / save_op / load_op) -------------------
# The reference's executor prepends feed ops reading a FeedHolder vector and
# appends fetch ops writing a FetchHolder; save/load stream a single variable
# to/from disk on the host. Here feed/fetch move values between a python-list
# holder and program vars (the jit path passes the holder contents as traced
# args), and save/load do host IO — under tracing, `save` routes through
# io_callback and `load` materializes the file at trace time (it becomes a
# compile-time constant, the TPU-native reading of "load once at startup").


@op("feed")
def _feed(ctx, ins, attrs):
    holder = _one(ins, "X")  # python list (FeedHolder role)
    return {"Out": holder[attrs.get("col", 0)]}


@op("fetch")
def _fetch(ctx, ins, attrs):
    x = _one(ins, "X")
    holder = _one(ins, "Holder")
    if isinstance(holder, list):  # FetchHolder role, eager path
        col = attrs.get("col", 0)
        while len(holder) <= col:
            holder.append(None)
        holder[col] = x
    return {"Out": x}


@op("save")
def _save(ctx, ins, attrs):
    import os

    x = _one(ins, "X")
    path = attrs["file_path"]
    # np.save appends '.npy' when the path lacks it — guard the on-disk name
    disk_path = path if path.endswith(".npy") else path + ".npy"
    if not attrs.get("overwrite", True) and os.path.exists(disk_path):
        raise RuntimeError(f"save op: {disk_path} exists and overwrite=False")

    def host_save(arr):
        # re-check at execution time: under the cached-jit path the trace-time
        # check above runs once against pre-run state only
        if not attrs.get("overwrite", True) and os.path.exists(disk_path):
            raise RuntimeError(f"save op: {disk_path} exists and overwrite=False")
        np.save(path, np.asarray(arr))
        return np.zeros((), np.int32)

    if isinstance(x, jax.core.Tracer):
        from jax.experimental import io_callback

        done = io_callback(host_save, jax.ShapeDtypeStruct((), jnp.int32), x)
    else:
        done = host_save(x)
    return {"Out": done}


@op("load")
def _load(ctx, ins, attrs):
    path = attrs["file_path"]
    if not path.endswith(".npy"):
        path = path + ".npy"
    return {"Out": jnp.asarray(np.load(path))}


# -- beam search ops (beam_search_op.cc / beam_search_decode_op.cc) ----------
# Dense-tensor redesign of the reference's LoD-based beams: a fixed beam
# width K per source sentence, so every step is a static [B, K*V] top-k on
# device (beam_search_op.cc walks candidate lists on the host per step).


@op("beam_search")
def _beam_search(ctx, ins, attrs):
    """One expansion step. ins: pre_ids [B*K,1], pre_scores [B*K,1],
    scores [B*K,V] — accumulated log-probs when is_accumulated (the
    reference's default, beam_search_op.cc), else per-step probabilities
    that get log()ed and added to pre_scores here. outs:
    selected_ids/selected_scores [B*K,1], parent_idx [B*K] (absolute row
    into the pre-beam). Expansion + finished-EOS masking delegate to
    nn/beam_core.expand_beams — the single beam engine."""
    from paddle_tpu.nn.beam_core import expand_beams

    k = attrs["beam_size"]
    end_id = attrs.get("end_id", 1)
    pre_ids = _one(ins, "pre_ids").reshape(-1)
    pre_scores = _one(ins, "pre_scores").reshape(-1).astype(jnp.float32)
    scores = _one(ins, "scores")
    bk, v = scores.shape
    b = bk // k
    logp = (
        scores.astype(jnp.float32)
        if attrs.get("is_accumulated", True)
        else jnp.log(jnp.maximum(scores.astype(jnp.float32), 1e-20))
        + pre_scores[:, None]
    )
    top_scores, beam_idx, tok = expand_beams(
        logp.reshape(b, k, v),
        pre_scores.reshape(b, k),
        (pre_ids == end_id).reshape(b, k),
        end_id,
        k,
    )
    parent = (beam_idx + jnp.arange(b)[:, None] * k).reshape(-1)
    return {
        "selected_ids": tok.reshape(-1, 1),
        "selected_scores": top_scores.reshape(-1, 1),
        "parent_idx": parent,
    }


@op("beam_search_decode")
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack per-step selections into whole sequences. ins: Ids [T, B*K]
    (or [T, B*K, 1]), ParentIdx [T, B*K] absolute rows, Scores [B*K] final
    accumulated scores. outs: SentenceIds [B, K, T] (end_id-padded),
    SentenceScores [B, K]."""
    k = attrs["beam_size"]
    ids = _one(ins, "Ids")
    parents = _one(ins, "ParentIdx")
    scores = _one(ins, "Scores").reshape(-1)
    ids = ids.reshape(ids.shape[0], -1)  # [T, B*K]
    parents = parents.reshape(parents.shape[0], -1)
    t, bk = ids.shape
    b = bk // k

    def back(ptr, step):
        id_t, par_t = step
        tok = id_t[ptr]
        ptr_new = par_t[ptr]
        return ptr_new, tok

    ptr0 = jnp.arange(bk)
    _, toks = jax.lax.scan(back, ptr0, (ids[::-1], parents[::-1]))
    seq = toks[::-1].T  # [B*K, T]
    return {
        "SentenceIds": seq.reshape(b, k, t),
        "SentenceScores": scores.reshape(b, k),
    }
