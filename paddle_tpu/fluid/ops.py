"""Fluid operator registry (SURVEY §2.3 paddle/operators: 97 REGISTER_OP
triples). Each op is a pure jax-traceable function `fn(ctx, ins, attrs) ->
{slot: array}` keyed by the reference's op type names and input/output slot
names (X/Y/Out, Input/Filter/Output, Param/Grad/ParamOut...), so programs
written against the reference's op vocabulary execute unchanged.

No per-op backward implementations: append_backward (backward.py) transposes
whole traced regions with jax autodiff — the TPU-native replacement of
framework/backward.cc's op-level transposition."""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import Registry

OPS = Registry("fluid op")

Ins = Dict[str, List[Any]]


class OpContext:
    """Per-execution context: rng + training flag."""

    def __init__(self, rng=None, train: bool = True):
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._count = 0
        self.train = train

    def next_rng(self):
        self._count += 1
        return jax.random.fold_in(self._rng, self._count)


def op(name: str, **meta):
    def deco(fn):
        fn.op_meta = meta
        OPS.register(name)(fn)
        return fn

    return deco


def _one(ins: Ins, slot: str):
    v = ins.get(slot, [])
    return v[0] if v else None


def _bcast(x, y, axis: int):
    """The reference's elementwise broadcast: Y's shape must match a
    contiguous suffix/infix of X starting at `axis` (elementwise_op.h)."""
    if axis == -1 or x.ndim == y.ndim:
        return y
    shape = [1] * x.ndim
    for i, d in enumerate(y.shape):
        shape[axis + i] = d
    return y.reshape(shape)


# -- elementwise ------------------------------------------------------------

for _nm, _f in [
    ("elementwise_add", jnp.add), ("elementwise_sub", jnp.subtract),
    ("elementwise_mul", jnp.multiply), ("elementwise_div", jnp.divide),
    ("elementwise_max", jnp.maximum), ("elementwise_min", jnp.minimum),
    ("elementwise_pow", jnp.power),
]:
    def _mk(f):
        def fn(ctx, ins, attrs):
            x, y = _one(ins, "X"), _one(ins, "Y")
            return {"Out": f(x, _bcast(x, y, attrs.get("axis", -1)))}
        return fn
    op(_nm)(_mk(_f))


# -- activations ------------------------------------------------------------

for _nm, _f in [
    ("relu", jax.nn.relu), ("sigmoid", jax.nn.sigmoid), ("tanh", jnp.tanh),
    ("sqrt", jnp.sqrt), ("abs", jnp.abs), ("exp", jnp.exp), ("log", jnp.log),
    ("square", jnp.square), ("reciprocal", lambda x: 1.0 / x),
    ("softsign", lambda x: x / (1 + jnp.abs(x))),
    ("soft_relu", lambda x: jnp.log1p(jnp.exp(jnp.clip(x, -40, 40)))),
]:
    def _mka(f):
        def fn(ctx, ins, attrs):
            return {"Y": f(_one(ins, "X"))}
        return fn
    op(_nm)(_mka(_f))


@op("brelu")
def _brelu(ctx, ins, attrs):
    return {"Y": jnp.clip(_one(ins, "X"), attrs.get("t_min", 0.0), attrs.get("t_max", 24.0))}


@op("leaky_relu")
def _leaky(ctx, ins, attrs):
    a = attrs.get("alpha", 0.02)
    x = _one(ins, "X")
    return {"Y": jnp.where(x >= 0, x, a * x)}


# -- linear algebra ---------------------------------------------------------


@op("mul")
def _mul(ctx, ins, attrs):
    """X [flattened to 2D at x_num_col_dims] @ Y (mul_op.cc)."""
    x, y = _one(ins, "X"), _one(ins, "Y")
    xd = attrs.get("x_num_col_dims", 1)
    yd = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape(int(np.prod(xs[:xd])), -1)
    y2 = y.reshape(int(np.prod(ys[:yd])), -1)
    out = x2 @ y2
    return {"Out": out.reshape(xs[:xd] + ys[yd:])}


@op("matmul")
def _matmul(ctx, ins, attrs):
    x, y = _one(ins, "X"), _one(ins, "Y")
    if attrs.get("transpose_X"):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("transpose_Y"):
        y = jnp.swapaxes(y, -1, -2)
    return {"Out": x @ y}


# -- shape ops --------------------------------------------------------------


@op("reshape")
def _reshape(ctx, ins, attrs):
    return {"Out": _one(ins, "X").reshape(attrs["shape"])}


@op("transpose")
def _transpose(ctx, ins, attrs):
    return {"Out": jnp.transpose(_one(ins, "X"), attrs["axis"])}


@op("concat")
def _concat(ctx, ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))}


@op("split")
def _split(ctx, ins, attrs):
    x = _one(ins, "X")
    axis = attrs.get("axis", 0)
    if "sections" in attrs and attrs["sections"]:
        idx = np.cumsum(attrs["sections"])[:-1]
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, attrs["num"], axis=axis)
    return {"Out": list(parts)}


@op("slice")
def _slice(ctx, ins, attrs):
    x = _one(ins, "X")
    sl = [slice(None)] * x.ndim
    for ax, st, en in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        sl[ax] = slice(st, en)
    return {"Out": x[tuple(sl)]}


@op("cast")
def _cast(ctx, ins, attrs):
    return {"Out": _one(ins, "X").astype(attrs["dtype"])}


@op("scale")
def _scale(ctx, ins, attrs):
    return {"Out": _one(ins, "X") * attrs.get("scale", 1.0)}


# -- reductions / metrics ---------------------------------------------------


@op("mean")
def _mean(ctx, ins, attrs):
    return {"Out": jnp.mean(_one(ins, "X"))}


@op("sum")
def _sum(ctx, ins, attrs):
    out = ins["X"][0]
    for x in ins["X"][1:]:
        out = out + x
    return {"Out": out}


@op("reduce_sum")
def _rsum(ctx, ins, attrs):
    return {"Out": jnp.sum(_one(ins, "X"), axis=attrs.get("dim"),
                           keepdims=attrs.get("keep_dim", False))}


@op("reduce_mean")
def _rmean(ctx, ins, attrs):
    return {"Out": jnp.mean(_one(ins, "X"), axis=attrs.get("dim"),
                            keepdims=attrs.get("keep_dim", False))}


@op("top_k")
def _topk(ctx, ins, attrs):
    vals, idx = jax.lax.top_k(_one(ins, "X"), attrs.get("k", 1))
    return {"Out": vals, "Indices": idx.astype(jnp.int32)}


@op("accuracy")
def _accuracy(ctx, ins, attrs):
    """Top-k accuracy: label anywhere in the Indices columns counts
    (accuracy_op semantics)."""
    pred = _one(ins, "Indices")
    if pred is None:
        pred = _one(ins, "Out")
    label = _one(ins, "Label").reshape(-1)
    if pred.ndim == 1:
        pred = pred[:, None]
    hit = jnp.any(pred == label[:, None], axis=-1)
    return {"Accuracy": jnp.mean(hit.astype(jnp.float32))}


# -- nn ---------------------------------------------------------------------


@op("softmax")
def _softmax(ctx, ins, attrs):
    return {"Y": jax.nn.softmax(_one(ins, "X"), axis=-1)}


@op("cross_entropy")
def _xent(ctx, ins, attrs):
    x = _one(ins, "X")  # probabilities [N, C] (the reference takes probs)
    label = _one(ins, "Label")
    if attrs.get("soft_label"):
        return {"Y": -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), -1, keepdims=True)}
    idx = label.reshape(-1).astype(jnp.int32)
    picked = jnp.take_along_axis(x, idx[:, None], axis=-1)
    return {"Y": -jnp.log(jnp.maximum(picked, 1e-20))}


@op("softmax_with_cross_entropy")
def _smxent(ctx, ins, attrs):
    logits = _one(ins, "Logits")
    label = _one(ins, "Label")
    logp = jax.nn.log_softmax(logits, axis=-1)
    idx = label.reshape(-1).astype(jnp.int32)
    loss = -jnp.take_along_axis(logp, idx[:, None], axis=-1)
    return {"Loss": loss, "Softmax": jnp.exp(logp)}


@op("conv2d")
def _conv2d(ctx, ins, attrs):
    """NCHW conv (conv_op.cc). Lowered to lax.conv_general_dilated — XLA maps
    it onto the MXU; the reference's im2col+gemm is a GPU idiom."""
    x = _one(ins, "Input")
    w = _one(ins, "Filter")  # [O, I/g, kH, kW]
    strides = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0])
    groups = attrs.get("groups", 1)
    dil = attrs.get("dilations", [1, 1])
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return {"Output": out}


@op("pool2d")
def _pool2d(ctx, ins, attrs):
    x = _one(ins, "X")
    ksize = attrs.get("ksize", [2, 2])
    strides = attrs.get("strides", ksize)
    pads = attrs.get("paddings", [0, 0])
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling"):
        ksize = list(x.shape[2:])
        strides, pads = ksize, [0, 0]
    window = (1, 1, *ksize)
    stride = (1, 1, *strides)
    padding = ((0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1]))
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, stride, padding)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride, padding)
        out = s / float(np.prod(ksize))
    return {"Out": out}


@op("batch_norm")
def _batch_norm(ctx, ins, attrs):
    x = _one(ins, "X")  # NCHW or NC
    scale, bias = _one(ins, "Scale"), _one(ins, "Bias")
    mean, var = _one(ins, "Mean"), _one(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    axes = tuple(i for i in range(x.ndim) if i != 1)
    shape = [1, -1] + [1] * (x.ndim - 2)
    if ctx.train and not attrs.get("is_test", False):
        bm = jnp.mean(x, axis=axes)
        bv = jnp.var(x, axis=axes)
        y = (x - bm.reshape(shape)) / jnp.sqrt(bv.reshape(shape) + eps)
        new_mean = momentum * mean + (1 - momentum) * bm
        new_var = momentum * var + (1 - momentum) * bv
        out = {"Y": y * scale.reshape(shape) + bias.reshape(shape),
               "MeanOut": new_mean, "VarianceOut": new_var,
               "SavedMean": bm, "SavedVariance": bv}
    else:
        y = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
        out = {"Y": y * scale.reshape(shape) + bias.reshape(shape),
               "MeanOut": mean, "VarianceOut": var,
               "SavedMean": mean, "SavedVariance": var}
    return out


@op("dropout")
def _dropout(ctx, ins, attrs):
    x = _one(ins, "X")
    p = attrs.get("dropout_prob", 0.5)
    if not ctx.train or attrs.get("is_test", False) or p == 0.0:
        return {"Out": x, "Mask": jnp.ones_like(x)}
    keep = jax.random.bernoulli(ctx.next_rng(), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype) / (1.0 - p)
    return {"Out": x * mask, "Mask": mask}


@op("lookup_table")
def _lookup(ctx, ins, attrs):
    w = _one(ins, "W")
    ids = _one(ins, "Ids")
    # the reference feeds ids as [N, 1] (LoD column); squeeze only that case
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    return {"Out": w[ids]}


@op("layer_norm")
def _layer_norm(ctx, ins, attrs):
    x = _one(ins, "X")
    scale, bias = _one(ins, "Scale"), _one(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) / jnp.sqrt(var + eps)
    if scale is not None:
        y = y * scale
    if bias is not None:
        y = y + bias
    return {"Y": y, "Mean": mu.squeeze(-1), "Variance": var.squeeze(-1)}


# -- fills / random ---------------------------------------------------------


@op("fill_constant")
def _fill(ctx, ins, attrs):
    return {"Out": jnp.full(attrs["shape"], attrs.get("value", 0.0),
                            dtype=attrs.get("dtype", jnp.float32))}


@op("uniform_random")
def _uniform(ctx, ins, attrs):
    lo, hi = attrs.get("min", -1.0), attrs.get("max", 1.0)
    return {"Out": jax.random.uniform(ctx.next_rng(), tuple(attrs["shape"]),
                                      minval=lo, maxval=hi)}


@op("gaussian_random")
def _gauss(ctx, ins, attrs):
    return {"Out": attrs.get("mean", 0.0) + attrs.get("std", 1.0)
            * jax.random.normal(ctx.next_rng(), tuple(attrs["shape"]))}


# -- control-flow helpers ---------------------------------------------------


@op("less_than")
def _less(ctx, ins, attrs):
    return {"Out": _one(ins, "X") < _one(ins, "Y")}


@op("increment")
def _incr(ctx, ins, attrs):
    return {"Out": _one(ins, "X") + attrs.get("step", 1.0)}


# -- optimizer ops (sgd_op.cc, momentum_op.cc, adam_op.cc ...) --------------


@op("sgd")
def _sgd(ctx, ins, attrs):
    p, g, lr = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "LearningRate")
    return {"ParamOut": p - lr * g}


@op("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "Velocity")
    lr = _one(ins, "LearningRate")
    mu = attrs.get("mu", 0.9)
    v_new = mu * v + g
    if attrs.get("use_nesterov"):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {"ParamOut": p_new, "VelocityOut": v_new}


@op("adam")
def _adam(ctx, ins, attrs):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    m, v = _one(ins, "Moment1"), _one(ins, "Moment2")
    b1p, b2p = _one(ins, "Beta1Pow"), _one(ins, "Beta2Pow")
    lr = _one(ins, "LearningRate")
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    mhat = m_new / (1 - b1p)
    vhat = v_new / (1 - b2p)
    p_new = p - lr * mhat / (jnp.sqrt(vhat) + eps)
    return {"ParamOut": p_new, "Moment1Out": m_new, "Moment2Out": v_new,
            "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, mom = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "Moment")
    lr = _one(ins, "LearningRate")
    eps = attrs.get("epsilon", 1e-6)
    mom_new = mom + g * g
    return {"ParamOut": p - lr * g / (jnp.sqrt(mom_new) + eps),
            "MomentOut": mom_new}


@op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    ms, mom = _one(ins, "MeanSquare"), _one(ins, "Moment")
    lr = _one(ins, "LearningRate")
    rho = attrs.get("decay", 0.9)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    ms_new = rho * ms + (1 - rho) * g * g
    mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": p - mom_new, "MeanSquareOut": ms_new, "MomentOut": mom_new}
