"""Fluid Executor (framework/executor.cc:81 Executor::Run) — TPU-native.

The reference interprets OpDescs one-by-one, each op launching device
kernels. Here `run` traces the whole block into a single jax function
(feed + persistable state in, fetches + new state out) and jit-compiles it
once per feed-shape signature — the op sequence becomes one fused XLA
program. `use_jit=False` falls back to eager op-by-op interpretation, the
debugging path that matches the reference's execution model exactly."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.fluid import ops as ops_mod
from paddle_tpu.fluid.framework import Block, OpDesc, Program, Scope, VarDesc, Variable


def _init_value(vd: VarDesc, key) -> jax.Array:
    init = vd.initializer
    shape = tuple(vd.shape or ())
    if isinstance(init, np.ndarray):
        return jnp.asarray(init)
    if isinstance(init, tuple):
        kind = init[0]
        if kind == "constant":
            return jnp.full(shape, init[1], dtype=vd.dtype)
        if kind == "uniform":
            return jax.random.uniform(
                key, shape, minval=init[1], maxval=init[2]
            ).astype(vd.dtype)
        if kind == "normal":
            return (init[1] + init[2] * jax.random.normal(key, shape)).astype(vd.dtype)
        raise ValueError(f"unknown initializer {init!r} for {vd.name}")
    return jnp.zeros(shape, dtype=vd.dtype)


def _stable_key(name: str, seed: int):
    h = 2166136261
    for ch in name.encode():
        h = ((h ^ ch) * 16777619) & 0x7FFFFFFF
    return jax.random.fold_in(jax.random.PRNGKey(seed), h)


class Executor:
    """Executor(place).run(program, feed, fetch_list) parity. `place` is
    accepted for API fidelity; device choice belongs to jax."""

    def __init__(self, place: Any = None, seed: int = 0):
        self.place = place
        self.seed = seed
        self._compiled: Dict[Tuple, Any] = {}
        self._run_count = 0  # per-run rng fold so dropout masks differ

    # -- startup (the reference's startup ProgramDesc role) -----------------
    def initialize(self, program: Program, scope: Scope) -> None:
        for name, vd in program.global_block().desc.vars.items():
            if vd.persistable and not scope.has(name):
                scope.set(name, _init_value(vd, _stable_key(name, self.seed)))

    # -- eager interpretation ------------------------------------------------
    def _run_ops(
        self,
        block: Block,
        values: Dict[str, Any],
        ctx: ops_mod.OpContext,
        upto: Optional[int] = None,
    ) -> Dict[str, Any]:
        for i, op in enumerate(block.desc.ops):
            if upto is not None and i >= upto:
                break
            if op.type == "backward":
                self._run_backward(block, op, values, ctx)
                continue
            if op.type == "recurrent":
                self._run_recurrent(block, op, values, ctx)
                continue
            if op.type == "while":
                self._run_while(block, op, values, ctx)
                continue
            if op.type == "cond":
                self._run_cond(block, op, values, ctx)
                continue
            fn = ops_mod.OPS.get(op.type)
            ins = {
                slot: [values[n] for n in names]
                for slot, names in op.inputs.items()
                if all(n in values for n in names)
            }
            outs = fn(ctx, ins, op.attrs)
            for slot, names in op.outputs.items():
                got = outs.get(slot)
                if got is None:
                    continue
                if isinstance(got, list):
                    for n, v in zip(names, got):
                        values[n] = v
                else:
                    values[names[0]] = got
        return values

    def _run_backward(
        self, block: Block, op: OpDesc, values: Dict[str, Any], ctx: ops_mod.OpContext
    ) -> None:
        """The append_backward region: grads of `loss` w.r.t. params via jax
        autodiff over a re-trace of ops [0, fwd_op_count) (backward.cc's
        op-transposition done by the AD system)."""
        loss_name = op.attrs["loss"]
        params = op.attrs["params"]
        n_fwd = op.attrs["fwd_op_count"]
        base = {k: v for k, v in values.items()}

        def loss_fn(pvals: Dict[str, Any]):
            local = dict(base)
            local.update(pvals)
            # fresh ctx with the same key: dropout masks replay identically
            replay = ops_mod.OpContext(rng=ctx._rng, train=ctx.train)
            local = self._run_ops(block, local, replay, upto=n_fwd)
            return jnp.sum(local[loss_name])

        grads = jax.grad(loss_fn)({p: values[p] for p in params})
        for p in params:
            values[p + "@GRAD"] = grads[p]

    # -- control flow (cond_op.cc:231 / recurrent_op.cc:222 / while) ---------
    # The reference interprets sub-scopes per step on the host; here each
    # sub-block is traced once and driven by the matching lax primitive, so
    # control flow compiles into the same XLA program as everything else.

    def _sub_block(self, block: Block, idx: int) -> Block:
        return block.program.blocks[idx]

    def _run_recurrent(self, block, op, values, ctx) -> None:
        """recurrent_op.cc:222 → one lax.scan. Attrs:
        sub_block: int; seq_ins: {block_var: parent_seq_var} ([B, T, ...],
        sliced per step as [B, ...]); states: {block_pre_state: (boot_var,
        block_state)}; seq_outs: {parent_out: block_var} (stacked [B, T, ...]).
        """
        sub = self._sub_block(block, op.attrs["sub_block"])
        seq_ins: Dict[str, str] = op.attrs.get("seq_ins", {})
        states: Dict[str, Any] = op.attrs.get("states", {})
        seq_outs: Dict[str, str] = op.attrs.get("seq_outs", {})
        reverse = bool(op.attrs.get("reverse", False))

        base = {
            k: v for k, v in values.items()
            if k not in seq_ins.values()
        }
        carry0 = {pre: values[boot] for pre, (boot, _st) in states.items()}
        xs = {bv: jnp.swapaxes(values[pv], 0, 1) for bv, pv in seq_ins.items()}

        def body(carry, x_t):
            local = dict(base)
            local.update(x_t)
            local.update(carry)
            local = self._run_ops(sub, local, ctx)
            new_carry = {pre: local[st] for pre, (_b, st) in states.items()}
            outs = {pv: local[bv] for pv, bv in seq_outs.items()}
            return new_carry, outs

        carry, stacked = jax.lax.scan(body, carry0, xs, reverse=reverse)
        for pv, seq in stacked.items():
            values[pv] = jnp.swapaxes(seq, 0, 1)
        for pre, (_b, st) in states.items():
            values[f"{op.attrs.get('name', 'recurrent')}.{st}@LAST"] = carry[pre]

    def _run_while(self, block, op, values, ctx) -> None:
        """while op → lax.while_loop. Attrs: sub_block, cond (scalar bool var
        recomputed by the sub-block each iteration), carry (var names carried
        across iterations; shapes must be loop-invariant)."""
        sub = self._sub_block(block, op.attrs["sub_block"])
        carry_names = list(op.attrs["carry"])
        cond_name = op.attrs["cond"]
        base = {k: v for k, v in values.items() if k not in carry_names}

        def cond_fun(carry):
            return jnp.asarray(carry[cond_name]).reshape(()).astype(bool)

        def body_fun(carry):
            local = dict(base)
            local.update(carry)
            local = self._run_ops(sub, local, ctx)
            return {n: local[n] for n in {cond_name, *carry_names}}

        carry0 = {n: values[n] for n in {cond_name, *carry_names}}
        out = jax.lax.while_loop(cond_fun, body_fun, carry0)
        values.update(out)

    def _run_cond(self, block, op, values, ctx) -> None:
        """cond_op.cc:231. Scalar condition → lax.cond over the two
        sub-blocks; vector (per-sample) condition → both branches run on the
        full batch and outputs are mask-selected (the TPU-native equivalent
        of the reference's scope split/merge — identical results for pure
        subnets, no dynamic shapes)."""
        cond = values[op.attrs["cond"]]
        true_b = self._sub_block(block, op.attrs["true_block"])
        false_b = (
            self._sub_block(block, op.attrs["false_block"])
            if op.attrs.get("false_block") is not None
            else None
        )
        out_names = list(op.attrs["outs"])
        base = dict(values)

        def run_block(sub):
            local = self._run_ops(sub, dict(base), ctx)
            return [local[n] for n in out_names]

        if false_b is None:
            missing = [n for n in out_names if n not in base]
            if missing:
                raise KeyError(
                    f"cond op: outputs {missing} are not defined outside the "
                    "true block, so there is no passthrough value for the "
                    "false branch — provide a false_block"
                )

        def run_false():
            if false_b is not None:
                return run_block(false_b)
            # passthrough branch: both lax.cond branches must return
            # identical avals, so align the outer values to the true
            # branch's output shapes/dtypes
            t_avals = jax.eval_shape(lambda: run_block(true_b))
            return [
                jnp.broadcast_to(jnp.asarray(base[n]), av.shape).astype(av.dtype)
                for n, av in zip(out_names, t_avals)
            ]

        cond_arr = jnp.asarray(cond)
        if cond_arr.ndim == 0 or cond_arr.size == 1:
            outs = jax.lax.cond(
                cond_arr.reshape(()).astype(bool),
                lambda: run_block(true_b),
                run_false,
            )
        else:
            t_outs = run_block(true_b)
            f_outs = run_false()
            mask = cond_arr.reshape(-1).astype(bool)
            outs = [
                jnp.where(mask.reshape((-1,) + (1,) * (t.ndim - 1)), t, f)
                for t, f in zip(t_outs, f_outs)
            ]
        values.update(dict(zip(out_names, outs)))

    # -- public API ----------------------------------------------------------
    def run(
        self,
        program: Program,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Sequence[Union[str, Variable]] = (),
        scope: Optional[Scope] = None,
        train: bool = True,
        use_jit: bool = True,
        rng: Optional[jax.Array] = None,
    ) -> List[Any]:
        scope = scope if scope is not None else getattr(self, "_scope", None)
        if scope is None:
            scope = self._scope = Scope()
        self.initialize(program, scope)
        feed = {k: jnp.asarray(v) for k, v in (feed or {}).items()}
        fetch_names = [f.name if isinstance(f, Variable) else f for f in fetch_list]
        block = program.global_block()
        persist = sorted(
            n for n, vd in block.desc.vars.items()
            if vd.persistable and scope.has(n)
        )
        self._run_count += 1
        if rng is None:
            rng = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._run_count)

        if not use_jit:
            ctx = ops_mod.OpContext(rng=rng, train=train)
            values = {n: scope.find(n) for n in persist}
            values.update(feed)
            values = self._run_ops(block, values, ctx)
            for n in persist:
                scope.set(n, values[n])
            return [np.asarray(values[n]) for n in fetch_names]

        key = (
            id(program), len(block.desc.ops), train, tuple(fetch_names),
            tuple(persist),
            tuple(sorted((k, v.shape, str(v.dtype)) for k, v in feed.items())),
        )
        if key not in self._compiled:

            def compiled(feed_vals, persist_vals, rng_in):
                ctx = ops_mod.OpContext(rng=rng_in, train=train)
                values = dict(persist_vals)
                values.update(feed_vals)
                values = self._run_ops(block, values, ctx)
                return (
                    [values[n] for n in fetch_names],
                    {n: values[n] for n in persist},
                )

            self._compiled[key] = jax.jit(compiled, donate_argnums=1)
        fetches, new_persist = self._compiled[key](
            feed, {n: scope.find(n) for n in persist}, rng
        )
        for n, v in new_persist.items():
            scope.set(n, v)
        return [np.asarray(v) for v in fetches]
