"""Fluid optimizers (python/paddle/v2/framework/optimizer.py parity):
`minimize(loss)` appends the backward region + per-parameter optimizer ops
(sgd_op/momentum_op/adam_op...) to the program."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.fluid.backward import append_backward
from paddle_tpu.fluid.framework import Variable


class Optimizer:
    op_type = "sgd"

    def __init__(self, learning_rate: float = 0.01):
        self.learning_rate = learning_rate

    def _lr_var(self, block):
        name = f"{self.op_type}_lr"
        if name in block.vars:
            return block.vars[name]
        return block.create_parameter(
            name, shape=[], initializer=("constant", self.learning_rate),
            trainable=False,
        )

    def _slots(self, block, param: Variable) -> dict:
        return {}

    def _extra_attrs(self) -> dict:
        return {}

    def _io(self, param, grad, lr, slots) -> Tuple[dict, dict]:
        return (
            {"Param": param, "Grad": grad, "LearningRate": lr},
            {"ParamOut": param},
        )

    def minimize(
        self, loss: Variable, parameter_list: Optional[Sequence[Variable]] = None
    ) -> List[tuple]:
        block = loss.block.program.global_block()
        pg = append_backward(loss, parameter_list)
        lr = self._lr_var(block)
        for param, grad in pg:
            slots = self._slots(block, param)
            ins, outs = self._io(param, grad, lr, slots)
            block.append_op(self.op_type, ins, outs, self._extra_attrs())
        return pg


class SGDOptimizer(Optimizer):
    op_type = "sgd"


class MomentumOptimizer(Optimizer):
    op_type = "momentum"

    def __init__(self, learning_rate=0.01, momentum=0.9, use_nesterov=False):
        super().__init__(learning_rate)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _slots(self, block, param):
        v = block.create_parameter(
            f"{param.name}_velocity", shape=param.desc.shape,
            initializer=("constant", 0.0), trainable=False,
        )
        return {"Velocity": v}

    def _extra_attrs(self):
        return {"mu": self.momentum, "use_nesterov": self.use_nesterov}

    def _io(self, param, grad, lr, slots):
        return (
            {"Param": param, "Grad": grad, "LearningRate": lr,
             "Velocity": slots["Velocity"]},
            {"ParamOut": param, "VelocityOut": slots["Velocity"]},
        )


class AdagradOptimizer(Optimizer):
    op_type = "adagrad"

    def __init__(self, learning_rate=0.01, epsilon=1e-6):
        super().__init__(learning_rate)
        self.epsilon = epsilon

    def _slots(self, block, param):
        m = block.create_parameter(
            f"{param.name}_moment", shape=param.desc.shape,
            initializer=("constant", 0.0), trainable=False,
        )
        return {"Moment": m}

    def _extra_attrs(self):
        return {"epsilon": self.epsilon}

    def _io(self, param, grad, lr, slots):
        return (
            {"Param": param, "Grad": grad, "LearningRate": lr,
             "Moment": slots["Moment"]},
            {"ParamOut": param, "MomentOut": slots["Moment"]},
        )


class AdamOptimizer(Optimizer):
    op_type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _slots(self, block, param):
        mk = lambda tag, val=0.0, shape=None: block.create_parameter(
            f"{param.name}_{tag}",
            shape=param.desc.shape if shape is None else shape,
            initializer=("constant", val), trainable=False,
        )
        return {
            "Moment1": mk("moment1"),
            "Moment2": mk("moment2"),
            "Beta1Pow": mk("beta1_pow", self.beta1, []),
            "Beta2Pow": mk("beta2_pow", self.beta2, []),
        }

    def _extra_attrs(self):
        return {"beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon}

    def _io(self, param, grad, lr, slots):
        ins = {"Param": param, "Grad": grad, "LearningRate": lr, **slots}
        outs = {
            "ParamOut": param,
            "Moment1Out": slots["Moment1"],
            "Moment2Out": slots["Moment2"],
            "Beta1PowOut": slots["Beta1Pow"],
            "Beta2PowOut": slots["Beta2Pow"],
        }
        return ins, outs
