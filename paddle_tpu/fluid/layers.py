"""Fluid Python layers API (python/paddle/v2/framework/layers.py parity):
each helper creates vars + appends OpDescs to the default Program."""

from __future__ import annotations

import math
import threading
from typing import Any, Optional, Sequence

import numpy as np

from paddle_tpu.fluid.framework import Program, Variable

_tls = threading.local()


def default_main_program() -> Program:
    prog = getattr(_tls, "main_program", None)
    if prog is None:
        prog = _tls.main_program = Program()
    return prog


def reset_default_program() -> Program:
    _tls.main_program = Program()
    return _tls.main_program


def _block():
    return default_main_program().current_block()


# -- inputs -----------------------------------------------------------------


def data(name: str, shape: Sequence[int], dtype=np.float32, lod_level: int = 0) -> Variable:
    """Batch axis is implicit (the reference uses -1 leading dim)."""
    return _block().create_var(
        name, shape=list(shape), dtype=dtype, is_data=True, lod_level=lod_level
    )


# -- layers -----------------------------------------------------------------


def fc(
    input: Variable,
    size: int,
    act: Optional[str] = None,
    bias_attr: bool = True,
    name: Optional[str] = None,
    num_flatten_dims: int = 1,
) -> Variable:
    block = _block()
    prog = block.program
    name = name or prog.unique_name("fc")
    # ignore batch markers (-1/None) when sizing the weight
    known = [
        d for d in (input.desc.shape or [])[num_flatten_dims - 1 :]
        if d is not None and d > 0
    ]
    in_dim = int(np.prod(known)) if known else None
    bound = 1.0 / math.sqrt(in_dim) if in_dim else 0.1
    w = block.create_parameter(
        f"{name}.w", shape=[in_dim, size], initializer=("uniform", -bound, bound)
    )
    out = block.create_var(f"{name}.mul_out", shape=list(input.desc.shape[:num_flatten_dims - 1]) + [size])
    block.append_op(
        "mul", {"X": input, "Y": w}, {"Out": out},
        {"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
    )
    if bias_attr:
        b = block.create_parameter(
            f"{name}.b", shape=[size], initializer=("constant", 0.0)
        )
        out2 = block.create_var(f"{name}.bias_out", shape=out.desc.shape)
        # axis=-1: bias broadcasts over trailing feature dim
        block.append_op("elementwise_add", {"X": out, "Y": b}, {"Out": out2}, {"axis": -1})
        out = out2
    return _activation(out, act, name)


def _activation(x: Variable, act: Optional[str], name: str) -> Variable:
    if act is None:
        return x
    block = _block()
    out = block.create_var(f"{name}.{act}", shape=x.desc.shape)
    block.append_op(act, {"X": x}, {"Y": out}, {})
    return out


def embedding(input: Variable, size: Sequence[int], name: Optional[str] = None) -> Variable:
    block = _block()
    name = name or block.program.unique_name("embedding")
    w = block.create_parameter(
        f"{name}.w", shape=list(size), initializer=("uniform", -0.05, 0.05)
    )
    out = block.create_var(f"{name}.out", shape=[None, size[1]])
    block.append_op("lookup_table", {"W": w, "Ids": input}, {"Out": out}, {})
    return out


def conv2d(
    input: Variable,
    num_filters: int,
    filter_size: int,
    stride: int = 1,
    padding: int = 0,
    groups: int = 1,
    act: Optional[str] = None,
    name: Optional[str] = None,
) -> Variable:
    block = _block()
    name = name or block.program.unique_name("conv2d")
    in_c = input.desc.shape[0] if len(input.desc.shape) == 3 else input.desc.shape[-3]
    fan_in = in_c * filter_size * filter_size
    w = block.create_parameter(
        f"{name}.w",
        shape=[num_filters, in_c // groups, filter_size, filter_size],
        initializer=("normal", 0.0, math.sqrt(2.0 / fan_in)),
    )
    # spatial dims are data-dependent; channel count is what downstream
    # layers (batch_norm) need statically
    out = block.create_var(f"{name}.out", shape=[num_filters, None, None])
    block.append_op(
        "conv2d", {"Input": input, "Filter": w}, {"Output": out},
        {"strides": [stride, stride], "paddings": [padding, padding], "groups": groups},
    )
    return _activation(out, act, name)


def pool2d(
    input: Variable,
    pool_size: int = 2,
    pool_type: str = "max",
    pool_stride: Optional[int] = None,
    pool_padding: int = 0,
    global_pooling: bool = False,
    name: Optional[str] = None,
) -> Variable:
    block = _block()
    name = name or block.program.unique_name("pool2d")
    out = block.create_var(f"{name}.out", shape=input.desc.shape)
    block.append_op(
        "pool2d", {"X": input}, {"Out": out},
        {"ksize": [pool_size, pool_size], "pooling_type": pool_type,
         "strides": [pool_stride or pool_size] * 2,
         "paddings": [pool_padding, pool_padding],
         "global_pooling": global_pooling},
    )
    return out


def batch_norm(input: Variable, act: Optional[str] = None, name: Optional[str] = None) -> Variable:
    block = _block()
    name = name or block.program.unique_name("batch_norm")
    c = input.desc.shape[-3] if len(input.desc.shape) >= 3 else input.desc.shape[-1]
    scale = block.create_parameter(f"{name}.scale", shape=[c], initializer=("constant", 1.0))
    bias = block.create_parameter(f"{name}.bias", shape=[c], initializer=("constant", 0.0))
    mean = block.create_parameter(
        f"{name}_mean", shape=[c], initializer=("constant", 0.0), trainable=False
    )
    var = block.create_parameter(
        f"{name}_variance", shape=[c], initializer=("constant", 1.0), trainable=False
    )
    out = block.create_var(f"{name}.out", shape=input.desc.shape)
    block.append_op(
        "batch_norm",
        {"X": input, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var},
        # MeanOut/VarianceOut write back into the same running-stat vars
        {"Y": out, "MeanOut": mean, "VarianceOut": var},
        {},
    )
    return _activation(out, act, name)


def dropout(input: Variable, dropout_prob: float, name: Optional[str] = None) -> Variable:
    block = _block()
    name = name or block.program.unique_name("dropout")
    out = block.create_var(f"{name}.out", shape=input.desc.shape)
    mask = block.create_var(f"{name}.mask", shape=input.desc.shape)
    block.append_op("dropout", {"X": input}, {"Out": out, "Mask": mask},
                    {"dropout_prob": dropout_prob})
    return out


def softmax(input: Variable, name: Optional[str] = None) -> Variable:
    return _activation(input, "softmax", name or _block().program.unique_name("sm"))


def cross_entropy(input: Variable, label: Variable, soft_label: bool = False) -> Variable:
    block = _block()
    out = block.create_var(block.program.unique_name("xent"))
    block.append_op("cross_entropy", {"X": input, "Label": label}, {"Y": out},
                    {"soft_label": soft_label})
    return out


def softmax_with_cross_entropy(logits: Variable, label: Variable) -> Variable:
    block = _block()
    loss = block.create_var(block.program.unique_name("loss"))
    sm = block.create_var(block.program.unique_name("softmax"))
    block.append_op("softmax_with_cross_entropy", {"Logits": logits, "Label": label},
                    {"Loss": loss, "Softmax": sm}, {})
    return loss


def mean(x: Variable) -> Variable:
    block = _block()
    out = block.create_var(block.program.unique_name("mean"), shape=[])
    block.append_op("mean", {"X": x}, {"Out": out}, {})
    return out


def accuracy(input: Variable, label: Variable, k: int = 1) -> Variable:
    block = _block()
    topk = block.create_var(block.program.unique_name("topk"))
    idx = block.create_var(block.program.unique_name("topk_idx"))
    block.append_op("top_k", {"X": input}, {"Out": topk, "Indices": idx}, {"k": k})
    acc = block.create_var(block.program.unique_name("acc"), shape=[])
    block.append_op("accuracy", {"Indices": idx, "Label": label}, {"Accuracy": acc}, {})
    return acc


def concat(inputs: Sequence[Variable], axis: int = 0) -> Variable:
    block = _block()
    out = block.create_var(block.program.unique_name("concat"))
    block.append_op("concat", {"X": list(inputs)}, {"Out": out}, {"axis": axis})
    return out


def reshape(x: Variable, shape: Sequence[int]) -> Variable:
    block = _block()
    out = block.create_var(block.program.unique_name("reshape"), shape=list(shape))
    block.append_op("reshape", {"X": x}, {"Out": out}, {"shape": list(shape)})
    return out
