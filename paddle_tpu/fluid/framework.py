"""Fluid-equivalent program representation (SURVEY §2.3 paddle/framework):
ProgramDesc → blocks → OpDesc/VarDesc, Scope, and the Program/Block/Variable
Python handles (framework.proto; python/paddle/v2/framework/framework.py).

Design shift for TPU: the reference's Executor interprets ops one-by-one on
device; here the program is a *description* that the Executor traces into one
jittable jax function per (feed-shapes) signature — the whole block compiles
to a single XLA program (SURVEY §7 hard-part (1)), while the desc layer keeps
the reference's introspectable graph structure."""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np


@dataclass
class VarDesc:
    name: str
    shape: Optional[Sequence[int]] = None  # None → inferred at first write
    dtype: Any = np.float32
    persistable: bool = False  # parameters & optimizer slots
    # False for optimizer slots (moments/lr) and BN moving stats: persistable
    # state that must not receive gradients. An explicit registry — gradient
    # filtering must never rely on name-substring heuristics.
    trainable: bool = True
    is_data: bool = False
    lod_level: int = 0  # kept for LoDTensor parity (ragged inputs)
    initializer: Optional[Any] = None  # ("uniform", lo, hi) | ("constant", v) | ndarray


@dataclass
class OpDesc:
    type: str
    inputs: Dict[str, List[str]] = field(default_factory=dict)
    outputs: Dict[str, List[str]] = field(default_factory=dict)
    attrs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class BlockDesc:
    idx: int
    parent_idx: int = -1
    vars: Dict[str, VarDesc] = field(default_factory=dict)
    ops: List[OpDesc] = field(default_factory=list)


class Variable:
    """Python handle to a VarDesc inside a block (framework.py Variable)."""

    def __init__(self, block: "Block", desc: VarDesc):
        self.block = block
        self.desc = desc

    @property
    def name(self) -> str:
        return self.desc.name

    @property
    def shape(self):
        return tuple(self.desc.shape) if self.desc.shape is not None else None

    @property
    def dtype(self):
        return self.desc.dtype

    @property
    def persistable(self) -> bool:
        return self.desc.persistable

    def __repr__(self):
        return f"<Variable {self.name} shape={self.desc.shape}>"


class Block:
    def __init__(self, program: "Program", desc: BlockDesc):
        self.program = program
        self.desc = desc
        self.vars: Dict[str, Variable] = {}

    @property
    def idx(self) -> int:
        return self.desc.idx

    def create_var(self, name: Optional[str] = None, **kw) -> Variable:
        name = name or self.program.unique_name("tmp")
        if name in self.vars:
            return self.vars[name]
        desc = VarDesc(name=name, **kw)
        self.desc.vars[name] = desc
        v = Variable(self, desc)
        self.vars[name] = v
        return v

    def create_parameter(self, name: Optional[str] = None, **kw) -> Variable:
        kw.setdefault("persistable", True)
        name = name or self.program.unique_name("param")
        return self.create_var(name, **kw)

    def var(self, name: str) -> Variable:
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = (
                self.program.blocks[b.desc.parent_idx]
                if b.desc.parent_idx >= 0
                else None
            )
        raise KeyError(f"variable {name!r} not found in block {self.idx}")

    def append_op(
        self,
        type: str,  # noqa: A002
        inputs: Optional[Dict[str, Any]] = None,
        outputs: Optional[Dict[str, Any]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> OpDesc:
        def names(d):
            out = {}
            for k, v in (d or {}).items():
                vs = v if isinstance(v, (list, tuple)) else [v]
                out[k] = [x.name if isinstance(x, Variable) else str(x) for x in vs]
            return out

        op = OpDesc(type=type, inputs=names(inputs), outputs=names(outputs),
                    attrs=dict(attrs or {}))
        self.desc.ops.append(op)
        return op


class Program:
    """ProgramDesc handle (framework/program_desc.h; framework.py Program)."""

    def __init__(self):
        self.blocks: List[Block] = []
        self._counter = 0
        root = BlockDesc(idx=0)
        self.blocks.append(Block(self, root))
        self._current = 0

    def unique_name(self, prefix: str) -> str:
        self._counter += 1
        return f"{prefix}_{self._counter}"

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current]

    def create_block(self) -> Block:
        desc = BlockDesc(idx=len(self.blocks), parent_idx=self._current)
        b = Block(self, desc)
        self.blocks.append(b)
        self._current = b.idx
        return b

    def rollback(self) -> None:
        if self._current == 0:
            raise RuntimeError("rollback() on the root block")
        self._current = self.blocks[self._current].desc.parent_idx

    # -- introspection -------------------------------------------------------
    def parameters(self) -> List[Variable]:
        return [v for v in self.global_block().vars.values() if v.persistable]

    def prune(self, targets: Sequence[Any]) -> "Program":
        """Inference-program extraction (framework/prune.cc Prune): keep only
        the ops whose outputs (transitively) feed `targets` — variable names
        or Variables — walking each block backwards; sub-blocks referenced by
        surviving control-flow ops survive whole."""
        want = {
            t.name if isinstance(t, Variable) else str(t) for t in targets
        }
        keep_blocks: Dict[int, List] = {}
        needed_by_block: Dict[int, set] = {0: set(want)}

        def prune_block(idx: int, needed: set) -> None:
            block = self.blocks[idx]
            kept = []
            for op in reversed(block.desc.ops):
                outs = {n for ns in op.outputs.values() for n in ns}
                if outs & needed or op.type in ("feed", "print"):
                    kept.append(op)
                    for ns in op.inputs.values():
                        needed.update(ns)
                    subs = [op.attrs.get(k) for k in
                            ("sub_block", "true_block", "false_block")]
                    for sb in subs:
                        bidx = getattr(sb, "idx", sb)
                        if isinstance(bidx, int) and bidx not in keep_blocks:
                            inner_needed = {
                                n for ns in op.inputs.values() for n in ns
                            } | needed
                            prune_block(bidx, set(inner_needed))
            kept.reverse()
            keep_blocks[idx] = kept

        prune_block(0, needed_by_block[0])

        pruned = Program.__new__(Program)
        pruned.blocks = []
        pruned._counter = self._counter
        pruned._current = 0
        for b in self.blocks:
            desc = BlockDesc(idx=b.idx, parent_idx=b.desc.parent_idx)
            desc.vars = dict(b.desc.vars)
            desc.ops = list(keep_blocks.get(b.idx, b.desc.ops))
            nb = Block(pruned, desc)
            nb.vars = dict(b.vars)
            pruned.blocks.append(nb)
        return pruned

    def to_string(self) -> str:
        lines = []
        for b in self.blocks:
            lines.append(f"block {b.idx} (parent {b.desc.parent_idx}):")
            for name, vd in b.desc.vars.items():
                tag = " param" if vd.persistable else (" data" if vd.is_data else "")
                lines.append(f"  var {name} shape={vd.shape}{tag}")
            for op in b.desc.ops:
                lines.append(
                    f"  op {op.type}({op.inputs}) -> {op.outputs} {op.attrs}"
                )
        return "\n".join(lines)


class Scope:
    """Name → value store with parent chain (framework/scope.h:38)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.values: Dict[str, Any] = {}

    def find(self, name: str) -> Any:
        s: Optional[Scope] = self
        while s is not None:
            if name in s.values:
                return s.values[name]
            s = s.parent
        raise KeyError(f"variable {name!r} not in scope")

    def has(self, name: str) -> bool:
        try:
            self.find(name)
            return True
        except KeyError:
            return False

    def set(self, name: str, value: Any) -> None:
        self.values[name] = value

    def new_child(self) -> "Scope":
        return Scope(parent=self)
