"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
legacy PaddlePaddle (YangXS/Paddle), rebuilt idiomatically on JAX/XLA/Pallas.

Structure mirrors the reference's capability surface (see /root/repo/SURVEY.md),
not its implementation:

- ``paddle_tpu.ops``      — XLA/Pallas compute ops (replaces paddle/cuda hl_* +
                            paddle/math + paddle/function; SURVEY §2.1).
- ``paddle_tpu.nn``       — layer graph + 90-odd layer types (paddle/gserver/layers).
- ``paddle_tpu.optim``    — optimizers/schedules/regularizers (paddle/parameter).
- ``paddle_tpu.trainer``  — training drivers + updaters (paddle/trainer).
- ``paddle_tpu.parallel`` — mesh/sharding/collectives (MultiGradientMachine ring,
                            pserver sync, NCCL ops → ICI/DCN collectives).
- ``paddle_tpu.data``     — readers/providers/datasets (python/paddle/v2/reader,
                            gserver/dataproviders).
- ``paddle_tpu.metrics``  — evaluators (paddle/gserver/evaluators).
- ``paddle_tpu.models``   — model zoo for the BASELINE configs.
- ``paddle_tpu.v2``       — the user-facing v2-style API (python/paddle/v2).
- ``paddle_tpu.config``   — the v1 config-script pipeline (config_parser,
                            trainer_config_helpers; SURVEY §2.4).
- ``paddle_tpu.proto``    — config messages (proto/ parity).
- ``paddle_tpu.fluid``    — ProgramDesc/Executor graph runtime (SURVEY §2.3).
- ``paddle_tpu.runtime``  — native C++ runtime via ctypes: allocator, recordio,
                            elastic task master, host optimizer lib (csrc/).
- ``paddle_tpu.capi``     — merged-model inference (paddle/capi).
- ``paddle_tpu.utils``    — tooling (diagrams, model inspection).
"""

__version__ = "0.1.0"

from paddle_tpu.core import dtypes  # noqa: F401
from paddle_tpu.core.init_ctx import init as init  # noqa: F401
