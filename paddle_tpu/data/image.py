"""Image preprocessing utilities (python/paddle/v2/image.py parity:
load/resize/center-crop/random-crop/flip/to_chw/simple_transform) in pure
numpy — the host-side feed path; device-side augmentation belongs in jax."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the short edge equals `size`. Always returns float32 HWC
    (grayscale gets a channel axis) so batched pipelines see one dtype/rank
    regardless of which inputs already matched the target size."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, int(round(w * size / h))
    else:
        nh, nw = int(round(h * size / w)), size
    return _bilinear_resize(im, nh, nw)


def _bilinear_resize(im: np.ndarray, nh: int, nw: int) -> np.ndarray:
    h, w = im.shape[:2]
    if (h, w) == (nh, nw):
        out = im.astype(np.float32)
        return out[:, :, None] if out.ndim == 2 else out
    ys = (np.arange(nh) + 0.5) * h / nh - 0.5
    xs = (np.arange(nw) + 0.5) * w / nw - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    im = im.astype(np.float32)
    if im.ndim == 2:
        im = im[:, :, None]
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(np.float32)


def center_crop(im: np.ndarray, size: int) -> np.ndarray:
    h, w = im.shape[:2]
    y = max(0, (h - size) // 2)
    x = max(0, (w - size) // 2)
    return im[y : y + size, x : x + size]


def random_crop(im: np.ndarray, size: int, rng: Optional[np.random.RandomState] = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    y = rng.randint(0, max(h - size, 0) + 1)
    x = rng.randint(0, max(w - size, 0) + 1)
    return im[y : y + size, x : x + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def to_chw(im: np.ndarray) -> np.ndarray:
    """HWC → CHW (the reference's layout; our layers are NHWC — use only for
    interchange with reference-formatted data)."""
    return np.transpose(im, (2, 0, 1))


def simple_transform(
    im: np.ndarray,
    resize_size: int,
    crop_size: int,
    is_train: bool,
    mean: Optional[np.ndarray] = None,
    rng: Optional[np.random.RandomState] = None,
) -> np.ndarray:
    """The reference's standard pipeline: resize-short → crop (+flip when
    training) → float32 → mean-subtract. Returns HWC float32."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng)
        if (rng or np.random).rand() > 0.5:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = im.astype(np.float32)
    if mean is not None:
        im = im - mean
    return im
