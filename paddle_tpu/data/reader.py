"""Reader creators and combinators.

API parity with python/paddle/v2/reader (decorator.py: map_readers, buffered,
compose, chain, shuffle, firstn, xmap_readers; creator.py). A reader is a
zero-arg callable returning an iterable of samples — identical contract to the
reference, so user data pipelines port unchanged."""

from __future__ import annotations

import itertools
import queue as _queue
import random
import threading
from typing import Any, Callable, Iterable, Iterator, List

Reader = Callable[[], Iterable[Any]]


class ComposeNotAligned(ValueError):
    pass


def map_readers(func, *readers: Reader) -> Reader:
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader: Reader, buf_size: int) -> Reader:
    def shuffled():
        buf: List[Any] = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return shuffled


def chain(*readers: Reader) -> Reader:
    def chained():
        for r in readers:
            for sample in r():
                yield sample

    return chained


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def composed():
        rs = [r() for r in readers]
        if check_alignment:
            for items in itertools.zip_longest(*rs):
                if any(i is None for i in items):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned in compose()"
                    )
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())

    return composed


def buffered(reader: Reader, size: int) -> Reader:
    """Double-buffering in a producer thread — the analog of the async
    DoubleBuffer in gserver/dataproviders/DataProvider.h:249."""

    end = object()

    def buffered_reader():
        q: _queue.Queue = _queue.Queue(maxsize=size)
        err: List[BaseException] = []

        def produce():
            try:
                for sample in reader():
                    q.put(sample)
            except BaseException as e:  # re-raised on the consumer side
                err.append(e)
            finally:
                q.put(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            sample = q.get()
            if sample is end:
                if err:
                    raise err[0]
                return
            yield sample

    return buffered_reader


def firstn(reader: Reader, n: int) -> Reader:
    def rd():
        return itertools.islice(reader(), n)

    return rd


def cache(reader: Reader) -> Reader:
    """CacheType.CACHE_PASS_IN_MEM analog (PyDataProvider2.py): materialize the
    first pass, replay from memory afterwards."""
    store: List[Any] = []
    filled = [False]

    def cached():
        if filled[0]:
            for s in store:
                yield s
            return
        # fill a fresh list; only publish it if the pass was fully consumed
        # (a partially-consumed pass must not poison the cache)
        tmp: List[Any] = []
        for s in reader():
            tmp.append(s)
            yield s
        store[:] = tmp
        filled[0] = True

    return cached


def batch(reader: Reader, batch_size: int, drop_last: bool = False) -> Reader:
    """paddle.batch: group samples into lists of batch_size."""

    def batched():
        b: List[Any] = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batched


def xmap_readers(mapper, reader: Reader, process_num: int, buffer_size: int, order: bool = False) -> Reader:
    """Parallel map over samples with worker threads (reader/decorator.py
    xmap_readers). Thread-based (JAX host work releases the GIL for numpy)."""

    end = object()

    def xreader():
        in_q: _queue.Queue = _queue.Queue(buffer_size)
        out_q: _queue.Queue = _queue.Queue(buffer_size)
        err: List[BaseException] = []

        def feed():
            try:
                for i, s in enumerate(reader()):
                    in_q.put((i, s))
            except BaseException as e:
                err.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        return
                    i, s = item
                    out_q.put((i, mapper(s)))
            except BaseException as e:
                err.append(e)
            finally:
                out_q.put(end)  # always deliver the sentinel, even on error

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        if err:
            raise err[0]
        if order:
            for i in sorted(pending):
                yield pending[i]

    return xreader
