"""Deterministic multi-host data sharding (SURVEY §5 "deterministic data
sharding by step" — the non-elastic half of the Go master's role; the elastic
half is paddle_tpu.runtime.master.cluster_reader).

Every host runs the same reader and keeps samples where
`index % num_shards == shard_id` — no coordination, deterministic under
restart, and exactly the v2 cluster_files_reader / recordio-dispatch
semantics when pointed at the same file list."""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional, Sequence


def shard_reader(
    reader: Callable[[], Iterator[Any]],
    num_shards: Optional[int] = None,
    shard_id: Optional[int] = None,
) -> Callable[[], Iterator[Any]]:
    """Round-robin sample sharding. Defaults to jax process topology."""
    import jax

    n = num_shards if num_shards is not None else jax.process_count()
    i = shard_id if shard_id is not None else jax.process_index()
    if not 0 <= i < n:
        raise ValueError(f"shard_id {i} out of range for {n} shards")

    def sharded() -> Iterator[Any]:
        for idx, sample in enumerate(reader()):
            if idx % n == i:
                yield sample

    return sharded


def shard_file_list(
    files: Sequence[str],
    num_shards: Optional[int] = None,
    shard_id: Optional[int] = None,
) -> list:
    """File-granular sharding (cluster_files_reader parity,
    python/paddle/v2/dataset/common.py): host i takes files i, i+n, ..."""
    import jax

    n = num_shards if num_shards is not None else jax.process_count()
    i = shard_id if shard_id is not None else jax.process_index()
    if not 0 <= i < n:
        raise ValueError(f"shard_id {i} out of range for {n} shards")
    return [f for idx, f in enumerate(files) if idx % n == i]
