"""DataFeeder: user samples → padded device batches.

Replaces py_paddle/dataprovider_converter.py (numpy/scipy → C++ Arguments) and
the PyDataProvider2 input-type system (python/paddle/trainer/PyDataProvider2.py:63-236:
dense_vector, integer_value, *_sequence variants, sparse_binary_vector).

TPU shift: ragged sequences become padded [B, T, ...] + lengths, and batches are
padded/bucketed to a small set of shapes so XLA re-compiles rarely (SURVEY §7
hard-part (2))."""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np


@dataclasses.dataclass
class InputSpec:
    """Type descriptor for one input slot."""

    kind: str  # dense | index | dense_seq | index_seq | sparse_binary | sparse_value
    dim: Union[int, Sequence[int]] = 0
    dtype: Any = np.float32
    seq_bucket: Optional[Sequence[int]] = None  # pad-to-bucket lengths


def dense_vector(dim: int, dtype=np.float32) -> InputSpec:
    return InputSpec("dense", dim, dtype)


def dense_array(shape: Sequence[int], dtype=np.float32) -> InputSpec:
    return InputSpec("dense", tuple(shape), dtype)


def integer_value(value_range: int = 0) -> InputSpec:
    return InputSpec("index", value_range, np.int32)


def dense_vector_sequence(dim: int, dtype=np.float32) -> InputSpec:
    return InputSpec("dense_seq", dim, dtype)


def integer_value_sequence(value_range: int = 0) -> InputSpec:
    return InputSpec("index_seq", value_range, np.int32)


def dense_vector_sub_sequence(dim: int, dtype=np.float32) -> InputSpec:
    """Nested sequence of dense vectors (PyDataProvider2
    dense_vector_sub_sequence): each sample is a list of subsequences, each a
    list of dim-vectors → padded [B, S, T, dim] + lengths + sub_lengths."""
    return InputSpec("dense_subseq", dim, dtype)


def integer_value_sub_sequence(value_range: int = 0) -> InputSpec:
    return InputSpec("index_subseq", value_range, np.int32)


def sparse_binary_vector(dim: int) -> InputSpec:
    return InputSpec("sparse_binary", dim, np.float32)


def sparse_value_slot(dim: int) -> InputSpec:
    return InputSpec("sparse_value", dim, np.float32)


def _bucket_len(n: int, buckets: Optional[Sequence[int]]) -> int:
    if buckets:
        for b in buckets:
            if n <= b:
                return b
        # longer than the largest bucket: sequences get truncated to it
        return buckets[-1]
    # default: round up to next power of two (min 8) to bound recompiles
    return max(8, 1 << int(math.ceil(math.log2(max(n, 1)))))


class DataFeeder:
    """feeding: {slot_name: InputSpec}; converts a list of sample dicts or
    tuples (ordered like `feeding` keys, v1-style) into a batch dict for
    Network.apply."""

    def __init__(self, feeding: Dict[str, InputSpec]):
        self.feeding = feeding
        self.names = list(feeding.keys())

    def __call__(self, samples: List[Any]) -> Dict[str, np.ndarray]:
        return self.feed(samples)

    def feed(self, samples: List[Any]) -> Dict[str, np.ndarray]:
        cols: Dict[str, List[Any]] = {n: [] for n in self.names}
        for s in samples:
            if isinstance(s, dict):
                for n in self.names:
                    cols[n].append(s[n])
            else:
                if len(s) != len(self.names):
                    raise ValueError(
                        f"sample has {len(s)} fields, feeding expects {len(self.names)}"
                    )
                for n, v in zip(self.names, s):
                    cols[n].append(v)
        batch: Dict[str, np.ndarray] = {}
        for n in self.names:
            spec = self.feeding[n]
            vals = cols[n]
            if spec.kind == "dense":
                arr = np.asarray(vals, dtype=spec.dtype)
                if isinstance(spec.dim, tuple):
                    arr = arr.reshape((len(vals),) + tuple(spec.dim))
                batch[n] = arr
            elif spec.kind == "index":
                batch[n] = np.asarray(vals, dtype=np.int32)
            elif spec.kind in ("dense_seq", "index_seq"):
                lengths = np.asarray([len(v) for v in vals], np.int32)
                max_len = _bucket_len(int(lengths.max()) if len(vals) else 1, spec.seq_bucket)
                if spec.kind == "dense_seq":
                    dim = spec.dim if isinstance(spec.dim, tuple) else (spec.dim,)
                    out = np.zeros((len(vals), max_len) + dim, spec.dtype)
                else:
                    out = np.zeros((len(vals), max_len), np.int32)
                for i, v in enumerate(vals):
                    v = np.asarray(v, out.dtype)[:max_len]  # truncate outliers
                    out[i, : len(v)] = v.reshape((len(v),) + out.shape[2:])
                batch[n] = out
                batch[n + ".lengths"] = np.minimum(lengths, max_len)
            elif spec.kind in ("dense_subseq", "index_subseq"):
                # vals[i] = list of subsequences, each a list of tokens/vectors
                # → [B, S, T, ...] + lengths [B] (subseq counts) + sub_lengths
                # [B, S] (the padded encoding of subSequenceStartPositions)
                for i, subs in enumerate(vals):
                    if any(len(sub) == 0 for sub in subs):
                        raise ValueError(
                            f"{n}: sample {i} contains an empty subsequence; "
                            "the reference rejects zero-length subsequences "
                            "(subSequenceStartPositions must be strictly "
                            "increasing)"
                        )
                s_counts = np.asarray([len(v) for v in vals], np.int32)
                s_max = _bucket_len(
                    max(int(s_counts.max()) if len(vals) else 1, 1),
                    spec.seq_bucket,
                )
                t_raw = max(
                    (len(sub) for v in vals for sub in v), default=1
                )
                t_max = _bucket_len(t_raw, spec.seq_bucket)
                sub_lengths = np.ones((len(vals), s_max), np.int32)
                if spec.kind == "dense_subseq":
                    dim = spec.dim if isinstance(spec.dim, tuple) else (spec.dim,)
                    out = np.zeros((len(vals), s_max, t_max) + dim, spec.dtype)
                else:
                    out = np.zeros((len(vals), s_max, t_max), np.int32)
                for i, subs in enumerate(vals):
                    for s, sub in enumerate(subs[:s_max]):
                        sub = np.asarray(sub, out.dtype)[:t_max]
                        out[i, s, : len(sub)] = sub.reshape(
                            (len(sub),) + out.shape[3:]
                        )
                        sub_lengths[i, s] = max(len(sub), 1)
                batch[n] = out
                batch[n + ".lengths"] = np.minimum(s_counts, s_max)
                batch[n + ".sub_lengths"] = sub_lengths
            elif spec.kind == "sparse_binary":
                out = np.zeros((len(vals), spec.dim), np.float32)
                for i, idxs in enumerate(vals):
                    out[i, np.asarray(idxs, np.int64)] = 1.0
                batch[n] = out
            elif spec.kind == "sparse_binary_seq":
                # vals[i] is a list of per-timestep index lists
                lengths = np.asarray([len(v) for v in vals], np.int32)
                max_len = _bucket_len(int(lengths.max()) if len(vals) else 1, spec.seq_bucket)
                out = np.zeros((len(vals), max_len, spec.dim), np.float32)
                for i, steps in enumerate(vals):
                    for t, idxs in enumerate(steps[:max_len]):
                        out[i, t, np.asarray(idxs, np.int64)] = 1.0
                batch[n] = out
                batch[n + ".lengths"] = np.minimum(lengths, max_len)
            elif spec.kind == "sparse_value":
                out = np.zeros((len(vals), spec.dim), np.float32)
                for i, pairs in enumerate(vals):
                    for j, v in pairs:
                        out[i, j] = v
                batch[n] = out
            else:
                raise ValueError(f"unknown input kind {spec.kind}")
        return batch
