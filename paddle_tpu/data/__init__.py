from paddle_tpu.data import reader as reader  # noqa: F401
from paddle_tpu.data.pipeline import DevicePrefetcher, is_device_batch  # noqa: F401
from paddle_tpu.data.feeder import DataFeeder, InputSpec  # noqa: F401
from paddle_tpu.data.feeder import dense_vector, integer_value  # noqa: F401
from paddle_tpu.data.feeder import dense_array, integer_value_sequence  # noqa: F401
from paddle_tpu.data.feeder import dense_vector_sequence, sparse_binary_vector  # noqa: F401
from paddle_tpu.data.feeder import dense_vector_sub_sequence, integer_value_sub_sequence  # noqa: F401
