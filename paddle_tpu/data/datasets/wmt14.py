"""WMT-14 fr→en seq2seq readers (python/paddle/v2/dataset/wmt14.py).

Record schema: (src_ids, trg_ids_with_<s>, trg_ids_with_<e>) — the NMT
teacher-forcing triple. Special ids: <s>=0, <e>=1, <unk>=2 (wmt14.py constants).
"""

from __future__ import annotations

import tarfile
from typing import Dict, Tuple

from paddle_tpu.data.datasets import common

URL_TRAIN = "http://paddlepaddle.cdn.bcebos.com/demo/wmt_shrinked_data/wmt14.tgz"
MD5_TRAIN = "0791583d57d5beb693b9414c5b36798c"

START = "<s>"
END = "<e>"
UNK = "<unk>"
START_ID, END_ID, UNK_ID = 0, 1, 2


def _synth_dicts(dict_size: int) -> Tuple[Dict[str, int], Dict[str, int]]:
    src = {START: 0, END: 1, UNK: 2}
    trg = {START: 0, END: 1, UNK: 2}
    for i in range(3, dict_size):
        src[f"f{i}"] = i
        trg[f"e{i}"] = i
    return src, trg


def _synthetic_reader(dict_size: int, n: int, tag: str):
    def reader():
        rs = common.rng("wmt14." + tag)
        for _ in range(n):
            length = int(rs.randint(4, 20))
            src = rs.randint(3, dict_size, length).tolist()
            # learnable mapping: target token = src token shifted by 1 mod vocab
            trg = [3 + ((t - 3 + 1) % (dict_size - 3)) for t in src]
            yield src, [START_ID] + trg, trg + [END_ID]

    return reader


def _real_reader(tar_file: str, file_name: str, dict_size: int):
    src_dict, trg_dict = _load_dicts(tar_file, dict_size)

    def reader():
        with tarfile.open(tar_file) as tar:
            for member in tar.getmembers():
                if file_name not in member.name:
                    continue
                f = tar.extractfile(member)
                assert f is not None
                for line in f.read().decode("latin1").splitlines():
                    cols = line.split("\t")
                    if len(cols) != 2:
                        continue
                    src = [src_dict.get(w, UNK_ID) for w in cols[0].split()]
                    trg = [trg_dict.get(w, UNK_ID) for w in cols[1].split()]
                    if not src or not trg:
                        continue
                    yield src, [START_ID] + trg, trg + [END_ID]

    return reader


def _load_dicts(tar_file: str, dict_size: int):
    src_dict: Dict[str, int] = {}
    trg_dict: Dict[str, int] = {}
    with tarfile.open(tar_file) as tar:
        for member in tar.getmembers():
            target = src_dict if "src.dict" in member.name else (
                trg_dict if "trg.dict" in member.name else None)
            if target is None:
                continue
            f = tar.extractfile(member)
            assert f is not None
            for i, line in enumerate(f.read().decode("latin1").splitlines()):
                if i >= dict_size:
                    break
                target[line.split()[0]] = i
    return src_dict, trg_dict


def train(dict_size: int = 30000):
    return common.fetch_or_synthetic(
        lambda: _real_reader(common.download(URL_TRAIN, "wmt14", MD5_TRAIN), "train/train", dict_size),
        lambda: _synthetic_reader(dict_size, 4096, "train"),
        "wmt14.train",
    )


def test(dict_size: int = 30000):
    return common.fetch_or_synthetic(
        lambda: _real_reader(common.download(URL_TRAIN, "wmt14", MD5_TRAIN), "test/test", dict_size),
        lambda: _synthetic_reader(dict_size, 256, "test"),
        "wmt14.test",
    )


def get_dict(dict_size: int = 30000):
    def fetch():
        path = common.download(URL_TRAIN, "wmt14", MD5_TRAIN)
        src, trg = _load_dicts(path, dict_size)
        return {i: w for w, i in src.items()}, {i: w for w, i in trg.items()}

    def synth():
        src, trg = _synth_dicts(dict_size)
        return {i: w for w, i in src.items()}, {i: w for w, i in trg.items()}

    return common.fetch_or_synthetic(fetch, synth, "wmt14.get_dict")
