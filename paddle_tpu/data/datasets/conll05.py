"""CoNLL-2005 SRL readers (python/paddle/v2/dataset/conll05.py).

Record schema (v2 test()): (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
verb_ids, mark_ids, label_ids) — 8 feature sequences + BIO label sequence,
matching the demo/semantic_role_labeling pipeline.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from paddle_tpu.data.datasets import common

WORD_DICT_LEN = 44068
LABEL_DICT_LEN = 106
PRED_DICT_LEN = 3162


def get_dict():
    """(word_dict, verb_dict, label_dict) — synthetic-stable id spaces when
    the LDC-licensed corpus is unavailable (it always is offline)."""
    def synth():
        word_dict = {f"w{i}": i for i in range(2000)}
        verb_dict = {f"v{i}": i for i in range(200)}
        label_dict = {}
        labels = ["O"]
        for tag in ("A0", "A1", "A2", "A3", "A4", "AM-TMP", "AM-LOC", "AM-MNR", "V"):
            labels += ["B-" + tag, "I-" + tag]
        for i, l in enumerate(labels):
            label_dict[l] = i
        return word_dict, verb_dict, label_dict

    return common.fetch_or_synthetic(
        lambda: (_ for _ in ()).throw(common.DownloadUnavailable("conll05 is LDC-licensed")),
        synth,
        "conll05.get_dict",
    )


def get_embedding():
    raise common.DownloadUnavailable("pretrained emb requires network access")


def test():
    word_dict, verb_dict, label_dict = get_dict()
    n_labels = len(label_dict)
    v = len(word_dict)

    def reader():
        rs = common.rng("conll05.test")
        for _ in range(512):
            length = int(rs.randint(5, 30))
            words = rs.randint(0, v, length).tolist()
            verb_pos = int(rs.randint(0, length))
            verb = [words[verb_pos] % len(verb_dict)] * length
            mark = [1 if i == verb_pos else 0 for i in range(length)]

            def ctx(off):
                return [words[min(max(i + off, 0), length - 1)] for i in range(length)]

            # BIO-consistent label path
            labels: List[int] = []
            state = 0
            for i in range(length):
                if i == verb_pos:
                    labels.append(label_dict.get("B-V", 1))
                    state = 0
                elif state == 0 and rs.rand() < 0.3:
                    labels.append(1 + 2 * int(rs.randint(0, (n_labels - 1) // 2)) % (n_labels - 1))
                    state = 1
                else:
                    labels.append(0)
                    state = 0
            yield (words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2), verb, mark, labels)

    return reader
