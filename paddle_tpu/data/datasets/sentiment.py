"""NLTK movie-reviews sentiment readers (python/paddle/v2/dataset/sentiment.py).

Records: (word_ids, label 0/1).
"""

from __future__ import annotations

from typing import Dict

from paddle_tpu.data.datasets import common

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000
_VOCAB = 4000


def get_word_dict() -> Dict[str, int]:
    def synth():
        return {f"w{i}": i for i in range(_VOCAB)}

    return common.fetch_or_synthetic(
        lambda: (_ for _ in ()).throw(common.DownloadUnavailable("nltk corpus fetch needs network")),
        synth,
        "sentiment.word_dict",
    )


def _synthetic(n: int, tag: str):
    def reader():
        rs = common.rng("sentiment." + tag)
        for _ in range(n):
            label = int(rs.randint(0, 2))
            length = int(rs.randint(10, 60))
            ids = rs.randint(100, _VOCAB, length).tolist()
            cue_base = 10 if label == 0 else 50
            for _k in range(max(2, length // 10)):
                ids[int(rs.randint(0, length))] = cue_base + int(rs.randint(0, 30))
            yield ids, label

    return reader


def train():
    return common.fetch_or_synthetic(
        lambda: (_ for _ in ()).throw(common.DownloadUnavailable("nltk corpus fetch needs network")),
        lambda: _synthetic(NUM_TRAINING_INSTANCES, "train"),
        "sentiment.train",
    )


def test():
    return common.fetch_or_synthetic(
        lambda: (_ for _ in ()).throw(common.DownloadUnavailable("nltk corpus fetch needs network")),
        lambda: _synthetic(NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES, "test"),
        "sentiment.test",
    )
