"""Oxford-102 flowers readers (python/paddle/v2/dataset/flowers.py).

Records: (image float32[3,224,224] CHW in [0,1], label int in [0,102)).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

NUM_CLASSES = 102
IMAGE_SHAPE = (3, 224, 224)


def _synthetic(n: int, tag: str):
    def reader():
        rs = common.rng("flowers." + tag)
        for _ in range(n):
            label = int(rs.randint(0, NUM_CLASSES))
            img = rs.rand(*IMAGE_SHAPE).astype(np.float32) * 0.5
            ch = label % 3
            img[ch] = np.minimum(img[ch] + 0.3 + 0.002 * label, 1.0)
            yield img, label

    return reader


def train(mapper=None, buffered_size: int = 1024, use_xmap: bool = True):
    r = common.fetch_or_synthetic(
        lambda: (_ for _ in ()).throw(common.DownloadUnavailable("flowers tarball needs network")),
        lambda: _synthetic(1024, "train"),
        "flowers.train",
    )
    return _maybe_map(r, mapper)


def test(mapper=None, buffered_size: int = 1024, use_xmap: bool = True):
    r = common.fetch_or_synthetic(
        lambda: (_ for _ in ()).throw(common.DownloadUnavailable("flowers tarball needs network")),
        lambda: _synthetic(256, "test"),
        "flowers.test",
    )
    return _maybe_map(r, mapper)


def valid(mapper=None, **kw):
    return test(mapper, **kw)


def _maybe_map(reader, mapper):
    if mapper is None:
        return reader
    from paddle_tpu.data.reader import map_readers

    return map_readers(mapper, reader)
