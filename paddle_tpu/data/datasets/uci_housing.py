"""UCI Boston housing readers (python/paddle/v2/dataset/uci_housing.py).

Records: (features: float32[13] normalized, price: float32[1]).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

URL = "https://archive.ics.uci.edu/ml/machine-learning-databases/housing/housing.data"
MD5 = "d4accdce7a25600298819f8e28e8d593"
FEATURE_NUM = 13


def _load(path: str):
    data = np.loadtxt(path)
    feats, prices = data[:, :FEATURE_NUM], data[:, FEATURE_NUM:]
    maxs, mins, avgs = feats.max(0), feats.min(0), feats.mean(0)
    feats = (feats - avgs) / (maxs - mins + 1e-8)
    return feats.astype(np.float32), prices.astype(np.float32)


def _synthetic(n: int, tag: str):
    rs = common.rng("uci_housing." + tag)
    w = common.rng("uci_housing.w").randn(FEATURE_NUM).astype(np.float32)
    feats = rs.randn(n, FEATURE_NUM).astype(np.float32)
    prices = (feats @ w + 0.1 * rs.randn(n)).astype(np.float32)[:, None] + 22.0
    return feats, prices


def _make(split: str):
    def fetch():
        feats, prices = _load(common.download(URL, "uci_housing", MD5))
        return _reader(feats, prices, split)

    def synth():
        feats, prices = _synthetic(506, "all")
        return _reader(feats, prices, split)

    return common.fetch_or_synthetic(fetch, synth, f"uci_housing.{split}")


def _reader(feats, prices, split: str):
    n = len(feats)
    cut = int(n * 0.8)
    lo, hi = (0, cut) if split == "train" else (cut, n)

    def reader():
        for i in range(lo, hi):
            yield feats[i], prices[i]

    return reader


def train():
    return _make("train")


def test():
    return _make("test")
