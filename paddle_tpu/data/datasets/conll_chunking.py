"""CoNLL-2000 chunking text → DataFormat proto shards.

Behavioral port of the reference's data generator
(paddle/trainer/tests/gen_proto_data.py): context-window feature patterns
over the (word, POS) columns, frequency-cutoff dictionaries, and one
VECTOR_SPARSE_NON_VALUE feature slot followed by INDEX slots for the three
original columns. Feeding chunking.conf requires the exact same dictionary
sizes (features 4339 / word 478 / pos 45 / chunk 23 on the in-tree
train.txt); id assignment order differs from the py2 generator's dict order,
which only permutes feature ids, never the dimensionality."""

from __future__ import annotations

import os
from typing import Dict, List, Sequence, Tuple

from paddle_tpu.data.proto_data import (
    INDEX,
    VECTOR_SPARSE_NON_VALUE,
    DataSample,
    SlotDef,
    VectorSlot,
    write_shard,
)

OOV_POLICY_IGNORE = 0
OOV_POLICY_USE = 1
OOV_POLICY_ERROR = 2

NUM_ORIGINAL_COLUMNS = 3

# context feature combination patterns (gen_proto_data.py:35): [offset, column]
PATTERNS: List[List[Tuple[int, int]]] = [
    [(-2, 0)], [(-1, 0)], [(0, 0)], [(1, 0)], [(2, 0)],
    [(-1, 0), (0, 0)], [(0, 0), (1, 0)],
    [(-2, 1)], [(-1, 1)], [(0, 1)], [(1, 1)], [(2, 1)],
    [(-2, 1), (-1, 1)], [(-1, 1), (0, 1)], [(0, 1), (1, 1)],
    [(1, 1), (2, 1)],
    [(-2, 1), (-1, 1), (0, 1)], [(-1, 1), (0, 1), (1, 1)],
    [(0, 1), (1, 1), (2, 1)],
]

CHUNK_DICT = {
    "B-ADJP": 0, "I-ADJP": 1, "B-ADVP": 2, "I-ADVP": 3, "B-CONJP": 4,
    "I-CONJP": 5, "B-INTJ": 6, "I-INTJ": 7, "B-LST": 8, "I-LST": 9,
    "B-NP": 10, "I-NP": 11, "B-PP": 12, "I-PP": 13, "B-PRT": 14,
    "I-PRT": 15, "B-SBAR": 16, "I-SBAR": 17, "B-UCP": 18, "I-UCP": 19,
    "B-VP": 20, "I-VP": 21, "O": 22,
}


def _iter_sequences(path: str):
    seq: List[List[str]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                if seq:
                    yield seq
                seq = []
                continue
            seq.append(line.split(" "))
    if seq:
        yield seq


def make_features(sequence: List[List[str]]) -> None:
    """Append one combined feature per pattern to every timestep (boundary
    tokens #B{n}/#E{n}, gen_proto_data.py:60)."""
    length = len(sequence)
    num = len(sequence[0])

    def get(pos: int) -> List[str]:
        if pos < 0:
            return [f"#B{-pos}"] * num
        if pos >= length:
            return [f"#E{pos - length + 1}"] * num
        return sequence[pos]

    for i in range(length):
        for pattern in PATTERNS:
            sequence[i].append(
                "/".join(get(i + off)[col] for off, col in pattern)
            )


def create_dictionaries(
    path: str, cutoff: Sequence[int], oov_policy: Sequence[int]
) -> List[Dict[str, int]]:
    counts: List[Dict[str, int]] = [dict() for _ in cutoff]
    for seq in _iter_sequences(path):
        make_features(seq)
        for features in seq:
            assert len(features) == len(counts)
            for i, feat in enumerate(features):
                counts[i][feat] = counts[i].get(feat, 0) + 1
    dicts: List[Dict[str, int]] = []
    for i, cnt in enumerate(counts):
        n = 1 if oov_policy[i] == OOV_POLICY_USE else 0
        d: Dict[str, int] = {}
        for k, v in cnt.items():
            if v >= cutoff[i]:
                d[k] = n
                n += 1
        if oov_policy[i] == OOV_POLICY_USE:
            d["#OOV#"] = 0
        dicts.append(d)
    return dicts


def default_dicts(train_path: str) -> List[Dict[str, int]]:
    """The generator's __main__ defaults: cutoffs [3,1,0]+[3]*19, chunk
    labels pinned to the fixed 23-tag dict (gen_proto_data.py:269-276)."""
    cutoff = [3, 1, 0] + [3] * len(PATTERNS)
    oov = [OOV_POLICY_IGNORE, OOV_POLICY_ERROR, OOV_POLICY_ERROR]
    oov += [OOV_POLICY_IGNORE] * len(PATTERNS)
    dicts = create_dictionaries(train_path, cutoff, oov)
    dicts[2] = dict(CHUNK_DICT)
    return dicts


def gen_proto_shard(
    input_file: str,
    dicts: List[Dict[str, int]],
    oov_policy: Sequence[int],
    output_file: str,
) -> Tuple[int, List[int]]:
    """→ (feature_dim, index_dims); writes the shard (gen_proto_file)."""
    feature_dim = sum(
        len(dicts[i]) for i in range(NUM_ORIGINAL_COLUMNS, len(dicts))
    )
    slot_defs = [SlotDef(VECTOR_SPARSE_NON_VALUE, feature_dim)]
    index_dims = [len(dicts[i]) for i in range(NUM_ORIGINAL_COLUMNS)]
    slot_defs += [SlotDef(INDEX, d) for d in index_dims]

    samples: List[DataSample] = []
    for seq in _iter_sequences(input_file):
        make_features(seq)
        beginning = True
        for features in seq:
            s = DataSample(is_beginning=beginning)
            beginning = False
            for i in range(NUM_ORIGINAL_COLUMNS):
                fid = dicts[i].get(features[i], -1)
                if fid != -1:
                    s.id_slots.append(fid)
                elif oov_policy[i] == OOV_POLICY_IGNORE:
                    s.id_slots.append(0xFFFFFFFF)
                elif oov_policy[i] == OOV_POLICY_ERROR:
                    raise ValueError(f"unknown token {features[i]!r}")
                else:
                    s.id_slots.append(0)
            vec = VectorSlot()
            dim = 0
            for i in range(NUM_ORIGINAL_COLUMNS, len(dicts)):
                fid = dicts[i].get(features[i], -1)
                if fid != -1:
                    vec.ids.append(dim + fid)
                elif oov_policy[i] == OOV_POLICY_ERROR:
                    raise ValueError(f"unknown feature {features[i]!r}")
                elif oov_policy[i] != OOV_POLICY_IGNORE:
                    vec.ids.append(dim)
                dim += len(dicts[i])
            s.vector_slots.append(vec)
            samples.append(s)
    write_shard(output_file, slot_defs, samples)
    return feature_dim, index_dims


def build_chunking_shards(
    train_txt: str, test_txt: str, out_dir: str
) -> Dict[str, object]:
    """Generate train/test shards + file lists the way the reference test
    setup does (CMake runs gen_proto_data.py before test_Trainer)."""
    os.makedirs(out_dir, exist_ok=True)
    dicts = default_dicts(train_txt)
    oov = [OOV_POLICY_IGNORE, OOV_POLICY_ERROR, OOV_POLICY_ERROR]
    oov += [OOV_POLICY_IGNORE] * len(PATTERNS)
    train_bin = os.path.join(out_dir, "trainer", "tests", "train_proto.bin")
    test_bin = os.path.join(out_dir, "trainer", "tests", "test_proto.bin")
    os.makedirs(os.path.dirname(train_bin), exist_ok=True)
    feature_dim, index_dims = gen_proto_shard(train_txt, dicts, oov, train_bin)
    gen_proto_shard(test_txt, dicts, oov, test_bin)
    for lst, target in (
        ("train_files.txt", "trainer/tests/train_proto.bin"),
        ("test_files.txt", "trainer/tests/test_proto.bin"),
    ):
        with open(os.path.join(out_dir, "trainer", "tests", lst), "w") as f:
            f.write(target + "\n")
    return {
        "dir": out_dir,
        "feature_dim": feature_dim,
        "index_dims": index_dims,
    }
