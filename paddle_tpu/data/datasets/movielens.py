"""MovieLens-1M readers (python/paddle/v2/dataset/movielens.py).

Record schema (v2): (user_id, gender_id, age_id, job_id, movie_id,
category_ids[list], title_ids[list], rating float).
"""

from __future__ import annotations

import re
import zipfile
from typing import Dict, List

from paddle_tpu.data.datasets import common

URL = "https://files.grouplens.org/datasets/movielens/ml-1m.zip"
MD5 = "c4d9eecfca2ab87c1945afe126590906"

AGES = [1, 18, 25, 35, 45, 50, 56]
MAX_USER = 6040
MAX_MOVIE = 3952
CATEGORIES = [
    "Action", "Adventure", "Animation", "Children's", "Comedy", "Crime",
    "Documentary", "Drama", "Fantasy", "Film-Noir", "Horror", "Musical",
    "Mystery", "Romance", "Sci-Fi", "Thriller", "War", "Western",
]
_TITLE_VOCAB = 5000


def max_user_id() -> int:
    return MAX_USER


def max_movie_id() -> int:
    return MAX_MOVIE


def max_job_id() -> int:
    return 20


def age_table() -> List[int]:
    return list(AGES)


def movie_categories() -> List[str]:
    return list(CATEGORIES)


def _parse(path: str):
    users: Dict[int, tuple] = {}
    movies: Dict[int, tuple] = {}
    title_vocab: Dict[str, int] = {}
    with zipfile.ZipFile(path) as z:
        with z.open("ml-1m/users.dat") as f:
            for line in f.read().decode("latin1").splitlines():
                uid, gender, age, job, _zip = line.split("::")
                users[int(uid)] = (
                    0 if gender == "M" else 1,
                    AGES.index(int(age)),
                    int(job),
                )
        with z.open("ml-1m/movies.dat") as f:
            for line in f.read().decode("latin1").splitlines():
                mid, title, cats = line.split("::")
                title_words = re.findall(r"[A-Za-z0-9]+", title.lower())
                for w in title_words:
                    title_vocab.setdefault(w, len(title_vocab))
                movies[int(mid)] = (
                    [CATEGORIES.index(c) for c in cats.split("|") if c in CATEGORIES],
                    [title_vocab[w] for w in title_words],
                )
        ratings = []
        with z.open("ml-1m/ratings.dat") as f:
            for line in f.read().decode("latin1").splitlines():
                uid, mid, rating, _ts = line.split("::")
                ratings.append((int(uid), int(mid), float(rating)))
    return users, movies, ratings


def _real_reader(split: str):
    path = common.download(URL, "movielens", MD5)
    users, movies, ratings = _parse(path)
    cut = int(len(ratings) * 0.9)
    part = ratings[:cut] if split == "train" else ratings[cut:]

    def reader():
        for uid, mid, rating in part:
            if uid not in users or mid not in movies:
                continue
            g, a, j = users[uid]
            cats, title = movies[mid]
            yield uid, g, a, j, mid, cats, title, rating

    return reader


def _synthetic_reader(split: str, n: int):
    def reader():
        rs = common.rng("movielens." + split)
        for _ in range(n):
            uid = int(rs.randint(1, MAX_USER + 1))
            mid = int(rs.randint(1, MAX_MOVIE + 1))
            g = uid % 2
            a = uid % len(AGES)
            j = uid % 21
            cats = sorted(set(int(c) for c in rs.randint(0, len(CATEGORIES), 2)))
            title = rs.randint(0, _TITLE_VOCAB, size=int(rs.randint(2, 6))).tolist()
            rating = float((uid * 7 + mid * 3) % 5 + 1)
            yield uid, g, a, j, mid, cats, title, rating

    return reader


def train():
    return common.fetch_or_synthetic(
        lambda: _real_reader("train"), lambda: _synthetic_reader("train", 4096),
        "movielens.train",
    )


def test():
    return common.fetch_or_synthetic(
        lambda: _real_reader("test"), lambda: _synthetic_reader("test", 512),
        "movielens.test",
    )
