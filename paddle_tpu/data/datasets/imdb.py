"""IMDB sentiment readers (python/paddle/v2/dataset/imdb.py).

word_dict() → {word: idx}; train(word_idx)/test(word_idx) yield
([word_ids...], label 0/1) — the v2 record schema for text classification.
"""

from __future__ import annotations

import re
import tarfile
from typing import Dict

from paddle_tpu.data.datasets import common

URL = "https://ai.stanford.edu/~amaas/data/sentiment/aclImdb_v1.tar.gz"
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"

_WORDS = re.compile(r"[a-z]+")

# deterministic synthetic vocabulary: positive/negative cue words + filler
_SYN_VOCAB = 5000
_SYN_POS = list(range(10, 60))
_SYN_NEG = list(range(60, 110))


def _tokenize(text: str):
    return _WORDS.findall(text.lower())


def _build_dict_from_tar(path: str, pattern: str, cutoff: int = 150) -> Dict[str, int]:
    freq: Dict[str, int] = {}
    pat = re.compile(pattern)
    with tarfile.open(path) as tar:
        for member in tar.getmembers():
            if not pat.match(member.name):
                continue
            f = tar.extractfile(member)
            if f is None:
                continue
            for w in _tokenize(f.read().decode("latin1")):
                freq[w] = freq.get(w, 0) + 1
    words = [w for w, c in freq.items() if c > cutoff]
    words.sort(key=lambda w: (-freq[w], w))
    return {w: i for i, w in enumerate(words)}


def word_dict() -> Dict[str, int]:
    def fetch():
        path = common.download(URL, "imdb", MD5)
        return _build_dict_from_tar(path, r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")

    def synth():
        return {f"w{i}": i for i in range(_SYN_VOCAB)}

    return common.fetch_or_synthetic(lambda: fetch(), lambda: synth(), "imdb.word_dict")


def _reader_from_tar(word_idx: Dict[str, int], pattern_pos: str, pattern_neg: str):
    path = common.download(URL, "imdb", MD5)
    unk = len(word_idx)

    def read_label(pattern, label):
        pat = re.compile(pattern)
        with tarfile.open(path) as tar:
            for member in tar.getmembers():
                if not pat.match(member.name):
                    continue
                f = tar.extractfile(member)
                if f is None:
                    continue
                ids = [word_idx.get(w, unk) for w in _tokenize(f.read().decode("latin1"))]
                if ids:
                    yield ids, label

    def reader():
        yield from read_label(pattern_pos, 0)
        yield from read_label(pattern_neg, 1)

    return reader


def _synthetic_reader(word_idx: Dict[str, int], n: int, tag: str):
    def reader():
        rs = common.rng("imdb." + tag)
        v = max(len(word_idx), 200)
        for _ in range(n):
            label = int(rs.randint(0, 2))
            length = int(rs.randint(20, 120))
            ids = rs.randint(110, v, size=length).tolist()
            cues = _SYN_POS if label == 0 else _SYN_NEG
            for _k in range(max(3, length // 8)):
                ids[int(rs.randint(0, length))] = int(cues[rs.randint(0, len(cues))])
            yield ids, label

    return reader


def train(word_idx: Dict[str, int]):
    return common.fetch_or_synthetic(
        lambda: _reader_from_tar(word_idx, r"aclImdb/train/pos/.*\.txt$", r"aclImdb/train/neg/.*\.txt$"),
        lambda: _synthetic_reader(word_idx, 1024, "train"),
        "imdb.train",
    )


def test(word_idx: Dict[str, int]):
    return common.fetch_or_synthetic(
        lambda: _reader_from_tar(word_idx, r"aclImdb/test/pos/.*\.txt$", r"aclImdb/test/neg/.*\.txt$"),
        lambda: _synthetic_reader(word_idx, 256, "test"),
        "imdb.test",
    )
