"""Dataset cache/download plumbing (python/paddle/v2/dataset/common.py).

DATA_HOME caching + md5-checked download, with one deliberate divergence: in
airgapped environments (no egress) every dataset falls back to a deterministic
synthetic sample generator with the exact same record schema, clearly flagged
via the SYNTHETIC global and a log line — training pipelines stay runnable
end-to-end without network access.
"""

from __future__ import annotations

import hashlib
import logging
import os
from typing import Callable, Optional

import numpy as np

log = logging.getLogger("paddle_tpu.dataset")

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset")
)

# Set to True the first time a download fails and a synthetic fallback engages.
SYNTHETIC = False


def data_path(module_name: str, filename: str) -> str:
    d = os.path.join(DATA_HOME, module_name)
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, filename)


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: Optional[str] = None) -> str:
    """Fetch url into the cache; raises DownloadUnavailable when offline."""
    filename = data_path(module_name, url.split("/")[-1])
    if os.path.exists(filename) and (md5sum is None or md5file(filename) == md5sum):
        return filename
    try:
        import urllib.request

        tmp = filename + ".part"
        urllib.request.urlretrieve(url, tmp)  # nosec - dataset mirror fetch
        if md5sum is not None and md5file(tmp) != md5sum:
            os.remove(tmp)
            raise DownloadUnavailable(f"md5 mismatch for {url}")
        os.replace(tmp, filename)
        return filename
    except DownloadUnavailable:
        raise
    except Exception as e:  # no egress, DNS failure, 403, ...
        raise DownloadUnavailable(f"cannot fetch {url}: {e}") from e


class DownloadUnavailable(RuntimeError):
    pass


def fetch_or_synthetic(fetch: Callable[[], Callable], synth: Callable[[], Callable], what: str):
    """Return fetch() if the real data can be obtained, else synth().

    Both arguments are thunks returning reader creators."""
    global SYNTHETIC
    try:
        return fetch()
    except (DownloadUnavailable, OSError) as e:
        SYNTHETIC = True
        log.warning("%s: real dataset unavailable (%s); using deterministic "
                    "synthetic data with the same schema", what, e)
        return synth()


def rng(seed_tag: str) -> np.random.RandomState:
    """Deterministic per-dataset RandomState (stable across runs/processes)."""
    h = int(hashlib.md5(seed_tag.encode()).hexdigest()[:8], 16)
    return np.random.RandomState(h)
