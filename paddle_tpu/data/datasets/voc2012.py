"""Pascal VOC2012 segmentation readers (python/paddle/v2/dataset/voc2012.py).

Records: (image float32[3,H,W] in [0,1], label int32[H,W] class map).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

NUM_CLASSES = 21
IMG = (3, 128, 128)  # synthetic fallback size; real data is variable-size


def _synthetic(n: int, tag: str):
    def reader():
        rs = common.rng("voc2012." + tag)
        for _ in range(n):
            img = rs.rand(*IMG).astype(np.float32)
            label = np.zeros(IMG[1:], np.int32)
            # a rectangle of one class per image
            c = int(rs.randint(1, NUM_CLASSES))
            y0, x0 = rs.randint(0, IMG[1] // 2, 2)
            h, w = rs.randint(16, IMG[1] // 2, 2)
            label[y0 : y0 + h, x0 : x0 + w] = c
            img[0, y0 : y0 + h, x0 : x0 + w] += 0.01 * c
            yield np.clip(img, 0, 1), label

    return reader


def train(mapper=None):
    return common.fetch_or_synthetic(
        lambda: (_ for _ in ()).throw(common.DownloadUnavailable("VOC tarball needs network")),
        lambda: _synthetic(512, "train"),
        "voc2012.train",
    )


def test(mapper=None):
    return common.fetch_or_synthetic(
        lambda: (_ for _ in ()).throw(common.DownloadUnavailable("VOC tarball needs network")),
        lambda: _synthetic(128, "test"),
        "voc2012.test",
    )


def val(mapper=None):
    return test(mapper)
