"""PTB (imikolov) language-model readers (python/paddle/v2/dataset/imikolov.py).

build_dict() → vocab; train(word_idx, n)/test(word_idx, n) yield n-gram tuples
(w0, ..., wn-1) of word ids — the word2vec / n-gram LM schema.
"""

from __future__ import annotations

import tarfile
from typing import Dict

from paddle_tpu.data.datasets import common

URL = "http://www.fit.vutbr.cz/~imikolov/rnnlm/simple-examples.tgz"
MD5 = "30177ea32e27c525793142b6bf2c8e2d"

TRAIN_FILE = "./simple-examples/data/ptb.train.txt"
TEST_FILE = "./simple-examples/data/ptb.valid.txt"


def _lines_from_tar(fname: str):
    path = common.download(URL, "imikolov", MD5)
    with tarfile.open(path) as tar:
        f = tar.extractfile(fname)
        assert f is not None
        for line in f.read().decode().splitlines():
            yield line.strip().split()


def build_dict(min_word_freq: int = 50) -> Dict[str, int]:
    def fetch():
        freq: Dict[str, int] = {}
        for words in _lines_from_tar(TRAIN_FILE):
            for w in words:
                freq[w] = freq.get(w, 0) + 1
        freq.pop("<unk>", None)
        kept = [w for w, c in freq.items() if c > min_word_freq]
        kept.sort(key=lambda w: (-freq[w], w))
        d = {w: i for i, w in enumerate(kept)}
        d["<unk>"] = len(d)
        return d

    def synth():
        d = {f"w{i}": i for i in range(2000)}
        d["<unk>"] = len(d)
        return d

    return common.fetch_or_synthetic(fetch, synth, "imikolov.build_dict")


def _ngram_reader(word_idx: Dict[str, int], n: int, fname: str):
    common.download(URL, "imikolov", MD5)  # fail fast here, not inside the generator
    unk = word_idx["<unk>"]
    eos = word_idx.get("<e>", unk)  # sentence end maps to UNK like the reference

    def reader():
        for words in _lines_from_tar(fname):
            ids = [word_idx.get(w, unk) for w in words] + [eos]
            for i in range(n, len(ids) + 1):
                yield tuple(ids[i - n : i])

    return reader


def _synthetic_ngrams(word_idx: Dict[str, int], n: int, count: int, tag: str):
    v = len(word_idx)

    def reader():
        rs = common.rng("imikolov." + tag)
        # markov-ish stream: next word depends on previous (learnable signal)
        w = int(rs.randint(0, v))
        buf = [w]
        for _ in range(count + n):
            w = (w * 31 + int(rs.randint(0, 7))) % v
            buf.append(w)
            if len(buf) >= n:
                yield tuple(buf[-n:])

    return reader


def train(word_idx: Dict[str, int], n: int):
    return common.fetch_or_synthetic(
        lambda: _ngram_reader(word_idx, n, TRAIN_FILE),
        lambda: _synthetic_ngrams(word_idx, n, 4096, "train"),
        "imikolov.train",
    )


def test(word_idx: Dict[str, int], n: int):
    return common.fetch_or_synthetic(
        lambda: _ngram_reader(word_idx, n, TEST_FILE),
        lambda: _synthetic_ngrams(word_idx, n, 512, "test"),
        "imikolov.test",
    )
