"""MQ2007 learning-to-rank readers (python/paddle/v2/dataset/mq2007.py).

Two formats, as in the reference:
- format="pointwise": (feature[46], relevance)
- format="pairwise":  (feature_hi[46], feature_lo[46]) with rel(hi)>rel(lo)
- format="listwise":  (query_list_of_features, query_list_of_scores)
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.data.datasets import common

FEATURE_DIM = 46


def _synthetic_queries(n_queries: int, tag: str):
    rs = common.rng("mq2007." + tag)
    w = common.rng("mq2007.w").randn(FEATURE_DIM).astype(np.float32)
    queries = []
    for _ in range(n_queries):
        n_docs = int(rs.randint(5, 20))
        feats = rs.randn(n_docs, FEATURE_DIM).astype(np.float32)
        scores = feats @ w + 0.05 * rs.randn(n_docs)
        rel = np.digitize(scores, np.percentile(scores, [60, 85])).astype(np.int32)
        queries.append((feats, rel))
    return queries


def _make(split: str, fmt: str):
    def synth():
        queries = _synthetic_queries(300 if split == "train" else 60, split)

        def pointwise():
            for feats, rel in queries:
                for i in range(len(rel)):
                    yield feats[i], int(rel[i])

        def pairwise():
            rs = common.rng(f"mq2007.pair.{split}")
            for feats, rel in queries:
                idx = np.argsort(-rel)
                for a in range(len(idx)):
                    for b in range(a + 1, len(idx)):
                        if rel[idx[a]] > rel[idx[b]]:
                            if rs.rand() < 0.25:  # subsample pairs
                                yield feats[idx[a]], feats[idx[b]]

        def listwise():
            for feats, rel in queries:
                yield feats, rel.astype(np.float32)

        return {"pointwise": pointwise, "pairwise": pairwise, "listwise": listwise}[fmt]

    return common.fetch_or_synthetic(
        lambda: (_ for _ in ()).throw(common.DownloadUnavailable("MQ2007 mirror needs network")),
        synth,
        f"mq2007.{split}",
    )


def train(format: str = "pairwise"):
    return _make("train", format)


def test(format: str = "pairwise"):
    return _make("test", format)
