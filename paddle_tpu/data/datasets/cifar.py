"""CIFAR-10/100 readers (python/paddle/v2/dataset/cifar.py).

Records: (image: float32[3072] in [0,1] CHW-flattened, label: int).
"""

from __future__ import annotations

import pickle
import tarfile

import numpy as np

from paddle_tpu.data.datasets import common

URL10 = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
MD5_10 = "c58f30108f718f92721af3b95e74349a"
URL100 = "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz"
MD5_100 = "eb9058c3a382ffc7106e4002c42a8d85"


def _reader_from_tar(path: str, sub_name: str, label_key: str):
    def reader():
        with tarfile.open(path) as tar:
            for member in tar.getmembers():
                if sub_name not in member.name:
                    continue
                f = tar.extractfile(member)
                assert f is not None
                batch = pickle.load(f, encoding="latin1")
                data = np.asarray(batch["data"], np.float32) / 255.0
                labels = batch[label_key]
                for i in range(len(labels)):
                    yield data[i], int(labels[i])

    return reader


def _synthetic(n: int, classes: int, tag: str):
    def reader():
        rs = common.rng("cifar." + tag)
        for _ in range(n):
            label = int(rs.randint(0, classes))
            img = rs.rand(3072).astype(np.float32) * 0.5
            img[label :: classes] = np.minimum(img[label :: classes] + 0.4, 1.0)
            yield img, label

    return reader


def train10():
    return common.fetch_or_synthetic(
        lambda: _reader_from_tar(common.download(URL10, "cifar", MD5_10), "data_batch", "labels"),
        lambda: _synthetic(2048, 10, "train10"),
        "cifar.train10",
    )


def test10():
    return common.fetch_or_synthetic(
        lambda: _reader_from_tar(common.download(URL10, "cifar", MD5_10), "test_batch", "labels"),
        lambda: _synthetic(512, 10, "test10"),
        "cifar.test10",
    )


def train100():
    return common.fetch_or_synthetic(
        lambda: _reader_from_tar(common.download(URL100, "cifar", MD5_100), "train", "fine_labels"),
        lambda: _synthetic(2048, 100, "train100"),
        "cifar.train100",
    )


def test100():
    return common.fetch_or_synthetic(
        lambda: _reader_from_tar(common.download(URL100, "cifar", MD5_100), "test", "fine_labels"),
        lambda: _synthetic(512, 100, "test100"),
        "cifar.test100",
    )
