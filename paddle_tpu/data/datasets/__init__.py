"""Dataset readers (python/paddle/v2/dataset/*).

Every module follows the reference record schemas; when a download is
impossible (airgapped TPU pods), each falls back to deterministic synthetic
data with the same schema (see common.fetch_or_synthetic)."""

from paddle_tpu.data.datasets import cifar as cifar  # noqa: F401
from paddle_tpu.data.datasets import common as common  # noqa: F401
from paddle_tpu.data.datasets import conll05 as conll05  # noqa: F401
from paddle_tpu.data.datasets import flowers as flowers  # noqa: F401
from paddle_tpu.data.datasets import imdb as imdb  # noqa: F401
from paddle_tpu.data.datasets import imikolov as imikolov  # noqa: F401
from paddle_tpu.data.datasets import mnist as mnist  # noqa: F401
from paddle_tpu.data.datasets import movielens as movielens  # noqa: F401
from paddle_tpu.data.datasets import mq2007 as mq2007  # noqa: F401
from paddle_tpu.data.datasets import sentiment as sentiment  # noqa: F401
from paddle_tpu.data.datasets import uci_housing as uci_housing  # noqa: F401
from paddle_tpu.data.datasets import voc2012 as voc2012  # noqa: F401
from paddle_tpu.data.datasets import wmt14 as wmt14  # noqa: F401
