"""MNIST readers (python/paddle/v2/dataset/mnist.py).

train()/test() yield (image: float32[784] in [-1,1], label: int) — the exact
v2 record schema.
"""

from __future__ import annotations

import gzip
import struct

import numpy as np

from paddle_tpu.data.datasets import common

URL_PREFIX = "https://storage.googleapis.com/cvdf-datasets/mnist/"
TRAIN_IMAGES = ("train-images-idx3-ubyte.gz", "f68b3c2dcbeaaa9fbdd348bbdeb94873")
TRAIN_LABELS = ("train-labels-idx1-ubyte.gz", "d53e105ee54ea40749a09fcbcd1e9432")
TEST_IMAGES = ("t10k-images-idx3-ubyte.gz", "9fb629c4189551a2d022fa330f9573f3")
TEST_LABELS = ("t10k-labels-idx1-ubyte.gz", "ec29112dd5afa0611ce80d1b7f02629c")


def _reader_from_idx(img_file: str, lbl_file: str):
    def reader():
        with gzip.open(img_file, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051
            images = np.frombuffer(f.read(n * rows * cols), np.uint8)
            images = images.reshape(n, rows * cols).astype(np.float32)
            images = images / 255.0 * 2.0 - 1.0
        with gzip.open(lbl_file, "rb") as f:
            magic, n2 = struct.unpack(">II", f.read(8))
            assert magic == 2049 and n2 == n
            labels = np.frombuffer(f.read(n), np.uint8).astype(np.int64)
        for i in range(n):
            yield images[i], int(labels[i])

    return reader


def _synthetic(n: int, tag: str):
    def reader():
        rs = common.rng("mnist." + tag)
        for _ in range(n):
            label = int(rs.randint(0, 10))
            img = rs.randn(784).astype(np.float32) * 0.25
            # class-dependent blob so models can actually learn from it
            img[label * 70 : label * 70 + 70] += 1.0
            yield np.clip(img, -1, 1), label

    return reader


def train():
    return common.fetch_or_synthetic(
        lambda: _reader_from_idx(
            common.download(URL_PREFIX + TRAIN_IMAGES[0], "mnist", TRAIN_IMAGES[1]),
            common.download(URL_PREFIX + TRAIN_LABELS[0], "mnist", TRAIN_LABELS[1]),
        ),
        lambda: _synthetic(2048, "train"),
        "mnist.train",
    )


def test():
    return common.fetch_or_synthetic(
        lambda: _reader_from_idx(
            common.download(URL_PREFIX + TEST_IMAGES[0], "mnist", TEST_IMAGES[1]),
            common.download(URL_PREFIX + TEST_LABELS[0], "mnist", TEST_LABELS[1]),
        ),
        lambda: _synthetic(512, "test"),
        "mnist.test",
    )
