"""DataFormat.proto binary shards: reader, writer, and builtin provider.

The reference stores training data as varint-length-delimited protobuf
messages (gserver/dataproviders/ProtoReader.h:96 read): one DataHeader
followed by DataSamples until EOF (ProtoDataProvider.cpp:210 loadDataFile),
schema in proto/DataFormat.proto. Only varint / length-delimited / fixed32
wire types occur, so the messages are decoded by hand here — no protobuf
codegen — letting the reference's in-tree shards (mnist_bin_part,
data_bin_part, compare_sparse_data) feed trainers unmodified.

Provider semantics mirror the two registered C++ providers:
- `proto` (ProtoDataProvider): instances grouped into sequences by
  DataSample.is_beginning; every-sample-is-a-sequence degrades to iid
  (ProtoDataProvider.cpp:59-69).
- `proto_sequence` (ProtoSequenceDataProvider): iid only; each sample IS a
  sequence — SPARSE_NON_VALUE ids are the tokens, INDEX is the per-sequence
  label (ProtoDataProvider.cpp:750-906; an empty token slot yields the
  reference's single -1 placeholder, :834-840).
"""

from __future__ import annotations

import gzip
import os
import random
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

# SlotDef.SlotType (proto/DataFormat.proto:50)
VECTOR_DENSE = 0
VECTOR_SPARSE_NON_VALUE = 1
VECTOR_SPARSE_VALUE = 2
INDEX = 3
VAR_MDIM_DENSE = 4
VAR_MDIM_INDEX = 5
STRING = 6


# ---------------------------------------------------------------------------
# protobuf wire primitives
# ---------------------------------------------------------------------------


def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _write_varint(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _iter_fields(buf: memoryview) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, payload). Payload is int for varint /
    fixed32, memoryview for length-delimited."""
    pos = 0
    end = len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        fnum, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
            yield fnum, wire, v
        elif wire == 2:
            n, pos = _read_varint(buf, pos)
            if pos + n > end:
                raise ValueError(
                    f"truncated length-delimited field {fnum}: need {n} "
                    f"bytes, {end - pos} left"
                )
            yield fnum, wire, buf[pos : pos + n]
            pos += n
        elif wire == 5:
            if pos + 4 > end:
                raise ValueError(
                    f"truncated fixed32 field {fnum}: {end - pos} bytes left"
                )
            yield fnum, wire, struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        elif wire == 1:
            if pos + 8 > end:
                raise ValueError(
                    f"truncated fixed64 field {fnum}: {end - pos} bytes left"
                )
            yield fnum, wire, struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _packed_varints(payload: Any, wire: int) -> List[int]:
    """A `repeated uint32 [packed=true]` field: packed block (wire 2) or a
    single unpacked element (wire 0) — both legal on the wire."""
    if wire == 0:
        return [payload]
    out: List[int] = []
    pos = 0
    while pos < len(payload):
        v, pos = _read_varint(payload, pos)
        out.append(v)
    return out


def _packed_floats(payload: Any, wire: int) -> np.ndarray:
    if wire == 5:
        return np.frombuffer(struct.pack("<I", payload), np.float32)
    return np.frombuffer(bytes(payload), "<f4")


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------


@dataclass
class SlotDef:
    type: int = VECTOR_DENSE
    dim: int = 0


@dataclass
class VectorSlot:
    values: np.ndarray = field(default_factory=lambda: np.zeros(0, np.float32))
    ids: List[int] = field(default_factory=list)
    dims: List[int] = field(default_factory=list)
    strs: List[str] = field(default_factory=list)


@dataclass
class SubseqSlot:
    slot_id: int = 0
    lens: List[int] = field(default_factory=list)


@dataclass
class DataSample:
    is_beginning: bool = True
    vector_slots: List[VectorSlot] = field(default_factory=list)
    id_slots: List[int] = field(default_factory=list)
    var_id_slots: List[VectorSlot] = field(default_factory=list)
    subseq_slots: List[SubseqSlot] = field(default_factory=list)


def _parse_slot_def(buf: memoryview) -> SlotDef:
    sd = SlotDef()
    for fnum, _w, v in _iter_fields(buf):
        if fnum == 1:
            sd.type = v
        elif fnum == 2:
            sd.dim = v
    return sd


def _parse_vector_slot(buf: memoryview) -> VectorSlot:
    vs = VectorSlot()
    vals: List[np.ndarray] = []
    for fnum, w, v in _iter_fields(buf):
        if fnum == 1:
            vals.append(_packed_floats(v, w))
        elif fnum == 2:
            vs.ids.extend(_packed_varints(v, w))
        elif fnum == 3:
            vs.dims.extend(_packed_varints(v, w))
        elif fnum == 4:
            vs.strs.append(bytes(v).decode("utf-8"))
    if vals:
        vs.values = np.concatenate(vals) if len(vals) > 1 else vals[0]
    return vs


def _parse_subseq_slot(buf: memoryview) -> SubseqSlot:
    ss = SubseqSlot()
    for fnum, w, v in _iter_fields(buf):
        if fnum == 1:
            ss.slot_id = v
        elif fnum == 2:
            ss.lens.extend(_packed_varints(v, w))
    return ss


def parse_header(buf: memoryview) -> List[SlotDef]:
    return [
        _parse_slot_def(v) for fnum, _w, v in _iter_fields(buf) if fnum == 1
    ]


def parse_sample(buf: memoryview) -> DataSample:
    s = DataSample()
    for fnum, w, v in _iter_fields(buf):
        if fnum == 1:
            s.is_beginning = bool(v)
        elif fnum == 2:
            s.vector_slots.append(_parse_vector_slot(v))
        elif fnum == 3:
            s.id_slots.extend(_packed_varints(v, w))
        elif fnum == 4:
            s.var_id_slots.append(_parse_vector_slot(v))
        elif fnum == 5:
            s.subseq_slots.append(_parse_subseq_slot(v))
    return s


def read_shard(path: str) -> Tuple[List[SlotDef], List[DataSample]]:
    """One shard file → (slot_defs, samples). `.gz` handled like the
    reference (ProtoReader GzipInputStream). A truncated or corrupt shard
    raises ValueError naming the file — the reference's ProtoReader fails on
    ParseFromZeroCopyStream too, rather than training on partial samples."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read()
    buf = memoryview(raw)
    try:
        pos = 0
        n, pos = _read_varint(buf, pos)
        if pos + n > len(buf):
            raise ValueError("truncated header")
        header = parse_header(buf[pos : pos + n])
        pos += n
        samples: List[DataSample] = []
        while pos < len(buf):
            n, pos = _read_varint(buf, pos)
            if pos + n > len(buf):
                raise ValueError(
                    f"truncated sample {len(samples)}: need {n} bytes, "
                    f"{len(buf) - pos} left"
                )
            samples.append(parse_sample(buf[pos : pos + n]))
            pos += n
    except (ValueError, struct.error, IndexError) as e:
        raise ValueError(f"corrupt proto data shard {path!r}: {e}") from e
    return header, samples


# ---------------------------------------------------------------------------
# writer (gen_proto_data.py / ProtoWriter parity; also the round-trip oracle)
# ---------------------------------------------------------------------------


def _emit_field(out: bytearray, fnum: int, wire: int, payload: Any) -> None:
    _write_varint(out, (fnum << 3) | wire)
    if wire == 0:
        _write_varint(out, payload)
    elif wire == 2:
        _write_varint(out, len(payload))
        out.extend(payload)


def _emit_packed_varints(out: bytearray, fnum: int, vals: Sequence[int]) -> None:
    if not vals:
        return
    body = bytearray()
    for v in vals:
        _write_varint(body, v)
    _emit_field(out, fnum, 2, body)


def _encode_slot_def(sd: SlotDef) -> bytes:
    out = bytearray()
    _emit_field(out, 1, 0, sd.type)
    _emit_field(out, 2, 0, sd.dim)
    return bytes(out)


def _encode_vector_slot(vs: VectorSlot) -> bytes:
    out = bytearray()
    if len(vs.values):
        _emit_field(
            out, 1, 2, np.asarray(vs.values, "<f4").tobytes()
        )
    _emit_packed_varints(out, 2, vs.ids)
    _emit_packed_varints(out, 3, vs.dims)
    for s in vs.strs:
        _emit_field(out, 4, 2, s.encode("utf-8"))
    return bytes(out)


def _encode_sample(s: DataSample) -> bytes:
    out = bytearray()
    if not s.is_beginning:  # default true; the reference always writes it,
        _emit_field(out, 1, 0, 0)  # but omitting the default is wire-equal
    else:
        _emit_field(out, 1, 0, 1)
    for vs in s.vector_slots:
        _emit_field(out, 2, 2, _encode_vector_slot(vs))
    _emit_packed_varints(out, 3, s.id_slots)
    for vs in s.var_id_slots:
        _emit_field(out, 4, 2, _encode_vector_slot(vs))
    for ss in s.subseq_slots:
        body = bytearray()
        _emit_field(body, 1, 0, ss.slot_id)
        _emit_packed_varints(body, 2, ss.lens)
        _emit_field(out, 5, 2, bytes(body))
    return bytes(out)


def write_shard(
    path: str, slot_defs: Sequence[SlotDef], samples: Sequence[DataSample]
) -> None:
    out = bytearray()
    header = bytearray()
    for sd in slot_defs:
        _emit_field(header, 1, 2, _encode_slot_def(sd))
    _write_varint(out, len(header))
    out.extend(header)
    for s in samples:
        enc = _encode_sample(s)
        _write_varint(out, len(enc))
        out.extend(enc)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(bytes(out))


# ---------------------------------------------------------------------------
# builtin providers (DataConfig type "proto" / "proto_sequence")
# ---------------------------------------------------------------------------


def resolve_data_path(path: Optional[str], config_dir: str) -> Optional[str]:
    """The reference resolves data paths against its run directory; configs
    name them relative to the source root (e.g. 'trainer/tests/x'). Try the
    path itself, then the config dir and its ancestors. None when nothing
    exists (or when no path was configured — DataConfig.files defaults to
    None). Shared by the shard loader and the cli's file-list resolution."""
    if not path:
        return None
    cands = [path]
    d = config_dir
    for _ in range(4):
        if d:
            cands.append(os.path.join(d, path))
            d = os.path.dirname(d)
    return next((c for c in cands if os.path.exists(c)), None)


def _resolve_files(files: Sequence[str], config_dir: str) -> List[str]:
    out = []
    for f in files:
        hit = resolve_data_path(f, config_dir)
        if hit is None:
            raise FileNotFoundError(f"proto data shard {f!r} not found")
        out.append(hit)
    return out


class ProtoProvider:
    """Builtin provider with the PyDataProvider2 object surface the cli's
    reader/binder expect: make_settings() declares input_types, __call__
    yields sample tuples, calc_batch_size counts instances per sequence (the
    reference batches by instance count, ProtoDataProvider.cpp:395
    sequenceLoop)."""

    can_over_batch_size = True

    def __init__(self, seq_mode: bool, config_dir: str = "", seed: int = 0):
        self.seq_mode = seq_mode
        self.config_dir = config_dir
        self.seed = seed
        self._slot_defs: Optional[List[SlotDef]] = None
        self._sequences: Optional[List[List[DataSample]]] = None
        self._iid = True
        self._epoch = 0  # reshuffles differently each training pass

    # -- loading ------------------------------------------------------------
    def _load(self, file_list: Sequence[str]) -> None:
        if self._sequences is not None:
            return
        slot_defs: Optional[List[SlotDef]] = None
        samples: List[DataSample] = []
        seq_starts: List[int] = []
        for path in _resolve_files(file_list, self.config_dir):
            header, shard = read_shard(path)
            if slot_defs is None:
                slot_defs = header
            else:
                assert len(slot_defs) == len(header) and all(
                    a.type == b.type and a.dim == b.dim
                    for a, b in zip(slot_defs, header)
                ), "inconsistent shard headers"
            for s in shard:
                if s.is_beginning:
                    seq_starts.append(len(samples))
                samples.append(s)
        if slot_defs is None:
            raise ValueError(
                "no proto data shards given — is DataConfig.files set and "
                "resolvable from the config directory?"
            )
        self._slot_defs = slot_defs
        self._iid = len(seq_starts) == len(samples)
        seq_starts.append(len(samples))
        self._sequences = [
            samples[a:b] for a, b in zip(seq_starts, seq_starts[1:])
        ]

    # -- input types --------------------------------------------------------
    def _input_types(self):
        from paddle_tpu.v2 import data_type as dt

        assert self._slot_defs is not None
        grouped = not self.seq_mode and not self._iid
        types = []
        for sd in self._slot_defs:
            if sd.type == VECTOR_DENSE:
                types.append(
                    dt.dense_vector_sequence(sd.dim)
                    if grouped
                    else dt.dense_vector(sd.dim)
                )
            elif sd.type == VECTOR_SPARSE_NON_VALUE:
                if self.seq_mode:
                    # tokens of the sequence (ids over time)
                    types.append(dt.integer_value_sequence(sd.dim))
                elif grouped:
                    types.append(dt.sparse_binary_vector_sequence(sd.dim))
                else:
                    types.append(dt.sparse_binary_vector(sd.dim))
            elif sd.type == VECTOR_SPARSE_VALUE:
                types.append(dt.sparse_value_slot(sd.dim))
            elif sd.type == INDEX:
                types.append(
                    dt.integer_value_sequence(sd.dim)
                    if grouped
                    else dt.integer_value(sd.dim)
                )
            else:
                raise NotImplementedError(
                    f"proto slot type {sd.type} not supported by the provider"
                )
        return types

    def make_settings(self, obj=None, file_list: Sequence[str] = (), **_kw):
        from paddle_tpu.data.provider import Settings

        self._load(file_list)
        return Settings(input_types=self._input_types())

    # -- batching cost ------------------------------------------------------
    def calc_batch_size(self, sample) -> int:
        if self.seq_mode or self._iid:
            return 1
        first = sample[0]
        return len(first) if isinstance(first, (list, tuple)) else 1

    # -- iteration ----------------------------------------------------------
    def _instance_fields(self, s: DataSample) -> List[Any]:
        assert self._slot_defs is not None
        fields: List[Any] = []
        vec_i = 0
        idx_i = 0
        for sd in self._slot_defs:
            if sd.type == VECTOR_DENSE:
                fields.append(np.asarray(s.vector_slots[vec_i].values, np.float32))
                vec_i += 1
            elif sd.type == VECTOR_SPARSE_NON_VALUE:
                fields.append([int(x) for x in s.vector_slots[vec_i].ids])
                vec_i += 1
            elif sd.type == VECTOR_SPARSE_VALUE:
                vs = s.vector_slots[vec_i]
                fields.append(list(zip([int(x) for x in vs.ids], vs.values)))
                vec_i += 1
            elif sd.type == INDEX:
                v = int(s.id_slots[idx_i])
                # the generator writes OOV-ignored ids as 0xffffffff; the
                # reference's int32 IVector holds that as -1 (gen_proto_data
                # OOV_POLICY_IGNORE) — keep the signed view
                fields.append(v - (1 << 32) if v >= (1 << 31) else v)
                idx_i += 1
        return fields

    def __call__(self, obj=None, file_list=None, is_train=True, **_kw):
        self._load(file_list or ())
        assert self._sequences is not None
        sequences = self._sequences
        if is_train:
            # ProtoDataProvider::reset() shuffles sequence order every
            # training pass (ProtoDataProvider.cpp:372-385); seeded per pass
            # so runs stay reproducible. Test/generation readers keep file
            # order. Shuffles a copy — the loaded corpus stays pristine.
            self._epoch += 1
            rnd = random.Random(self.seed * 1000003 + self._epoch)
            sequences = list(sequences)
            rnd.shuffle(sequences)
        for seq in sequences:
            if self.seq_mode:
                # each sample is one sequence: token ids per sparse slot,
                # one label per INDEX slot (an empty token slot yields the
                # reference's -1 placeholder)
                for s in seq:
                    fields = self._instance_fields(s)
                    out = []
                    for sd, fv in zip(self._slot_defs, fields):
                        if sd.type == VECTOR_SPARSE_NON_VALUE:
                            out.append(fv if fv else [-1])
                        else:
                            out.append(fv)
                    yield tuple(out)
            elif self._iid:
                for s in seq:
                    yield tuple(self._instance_fields(s))
            else:
                # one yielded sample per sequence; each slot a list over time
                cols = [self._instance_fields(s) for s in seq]
                yield tuple(list(col) for col in zip(*cols))


def make_proto_provider(dc) -> ProtoProvider:
    """DataConfig (type proto / proto_sequence / *_group) → builtin provider.
    The per-pass shuffle seed follows the global --seed flag."""
    from paddle_tpu.core.init_ctx import flags

    seq_mode = "sequence" in (dc.type or "")
    return ProtoProvider(
        seq_mode, config_dir=dc.config_dir or "", seed=flags().seed
    )
