"""Device-prefetching input pipeline.

The reference hides host input prep behind device execution with DoubleBuffer
(gserver/dataproviders/DataProvider.h:249) — a background thread that keeps
converted batches ahead of the GPU. On TPU two more host-side costs sit on
the step's critical path: batch sharding (the `DataParallel` placement) and
the H2D transfer itself. `DevicePrefetcher` moves all three off the hot loop:
a worker thread runs the feeder, applies the committed sharding, and
`jax.device_put`s up to `prefetch_depth` batches ahead, so host prep and H2D
overlap the donated compiled step ("RPC Considered Harmful" host/device
overlap discipline — chip-independent, it pays off on the CPU oracle too).

Composition: `DevicePrefetcher` subsumes `DoubleBuffer` (feeder + transfer on
one thread); it also accepts any reader that already yields feed-ready dict
batches — including a `DoubleBuffer` — and then only adds the device leg.
`SGDTrainer.train`/`test` recognize the already-on-device batches via
`is_device_batch` and skip their own coerce/shard work.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from paddle_tpu.core import faults, stats
from paddle_tpu.obs import trace

log = logging.getLogger("paddle_tpu.pipeline")

_STOP = object()
SKIP = object()  # prepare() return value meaning "drop this item"


class StackedBatch(dict):
    """K feed-ready batches stacked on a leading K axis — the payload of one
    fused K-step dispatch (SGDTrainer.train(steps_per_dispatch=K) feeds it
    straight to the lax.scan driver). Still a plain dict of device arrays,
    so is_device_batch() holds; `k` is the scan width."""

    k: int = 1


class _Group(list):
    """Marker: a stack_k-sized run of raw reader items (worker-side only)."""


class _Singles(list):
    """Marker: prepared single batches the consumer yields one by one — the
    degraded path for trailing remainders, shape churn inside a group, or
    groups that lost members to the divisibility filter."""


def iter_async(
    reader: Callable,
    prepare: Callable[[Any], Any],
    capacity: int,
    name: str = "paddle-tpu-async-producer",
    retries: int = 0,
    stall_warn_s: Optional[float] = None,
):
    """Shared background-producer loop (DoubleBuffer + DevicePrefetcher):
    a worker thread runs `prepare(raw)` over `reader()` and keeps up to
    `capacity` results ahead of the consumer. Items come out in reader
    order; `prepare` returning SKIP drops the item; worker exceptions
    re-raise in the consumer with the worker's original traceback attached;
    abandoning the generator (break/GeneratorExit) retires the worker via
    the bounded put's stop poll.

    retries: transient `prepare` exceptions (flaky storage, a hiccuping
    remote feeder) are retried that many times on the same item — with a
    short growing backoff — before the error propagates. reader() errors are
    never retried: the iterator's position is gone.

    stall_warn_s (default $PADDLE_TPU_STALL_WARN_S or 30; <= 0 disables):
    the consumer logs a warning whenever it has been starved that long
    waiting on the producer — the watchdog that distinguishes "feeder
    wedged" from "training slow"."""
    if stall_warn_s is None:
        stall_warn_s = float(os.environ.get("PADDLE_TPU_STALL_WARN_S", "30"))
    if stall_warn_s <= 0:  # disabled: plain blocking get, no watchdog
        stall_warn_s = None
    q: "queue.Queue" = queue.Queue(maxsize=capacity)
    err: List[BaseException] = []
    stop = threading.Event()

    def put(item) -> bool:
        # bounded put that notices consumer abandonment
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def prepare_with_retry(raw):
        for attempt in range(retries + 1):
            try:
                faults.get().maybe_raise("feeder_raise")  # chaos hook
                return prepare(raw)
            except Exception as e:
                if attempt >= retries:
                    raise
                stats.FT_EVENTS.incr("feeder_retry")
                log.warning(
                    "%s: prepare failed (%s: %s) — retry %d/%d",
                    name, type(e).__name__, e, attempt + 1, retries,
                )
                time.sleep(min(0.05 * 2 ** attempt, 1.0))

    def work():
        try:
            for raw in reader():
                item = prepare_with_retry(raw)
                if item is SKIP:
                    continue
                if not put(item):
                    return
        except BaseException as e:  # surface worker errors to the consumer
            err.append(e)
        finally:
            put(_STOP)

    t = threading.Thread(target=work, daemon=True, name=name)
    t.start()
    try:
        while True:
            try:
                item = q.get(timeout=stall_warn_s)
            except queue.Empty:  # starved, not done: watchdog, then keep waiting
                stats.FT_EVENTS.incr("pipeline_stall")
                log.warning(
                    "%s: consumer starved for > %.1fs waiting on the producer "
                    "thread (feeder wedged or reader stalled?)",
                    name, stall_warn_s,
                )
                continue
            if item is _STOP:
                break
            yield item
        t.join()
        if err:
            # the exception object still carries the worker's traceback, so
            # the failing feeder frame surfaces here, not just this loop
            # (locked in by test_worker_traceback_reaches_consumer)
            raise err[0]
    finally:
        stop.set()  # unblock and retire the producer on early exit


def is_device_batch(batch: Any) -> bool:
    """True when `batch` is a dict whose every slot already lives on device
    (committed jax.Arrays) — the trainer skips _coerce_batch/shard_batch."""
    return (
        isinstance(batch, dict)
        and bool(batch)
        and all(isinstance(v, jax.Array) for v in batch.values())
    )


def coerce_batch(batch: Dict[str, Any]) -> Dict[str, Any]:
    """numpy-ify a dict batch, failing fast on ragged/object slots instead of
    letting the jitted step produce an opaque shape error. Shared by the
    prefetcher worker and the trainer's synchronous path."""
    out: Dict[str, Any] = {}
    for k, v in batch.items():
        if isinstance(v, (np.ndarray, jax.Array)):
            out[k] = v
            continue
        arr = np.asarray(v)
        if arr.dtype == object:
            raise ValueError(
                f"batch slot {k!r} is ragged or non-numeric; feed it through "
                f"a DataFeeder (which pads sequences) instead of a raw dict"
            )
        out[k] = arr
    return out


class DevicePrefetcher:
    """Async host-feed + H2D pipeline in front of the compiled train step.

    reader: callable returning an iterator of raw batches (sample lists when
        `feeder` is given, else feed-ready dict batches — e.g. a DoubleBuffer).
    feeder: optional DataFeeder applied on the worker thread.
    parallel: optional parallel.DataParallel — batches are placed with its
        committed batch sharding (indivisible trailing batches are padded to
        the shard multiple with a row mask — DataParallel.pad_batch — so the
        sample stream matches the unsharded reader; only unpaddable ragged
        batches are dropped); without it, batches go to `device` (default:
        jax's default device) via plain device_put.
    prefetch_depth: how many device-resident batches to run ahead (N+1 are in
        flight counting the one the consumer holds). 2 hides a feeder that is
        as slow as the step; deeper only buys burst tolerance at the cost of
        device memory.
    feed_retries: transient worker exceptions (feeder/coerce/H2D) are retried
        this many times per batch before propagating (see iter_async);
        deterministic feeder bugs still surface — they just fail every retry.
    stack_k: >1 groups K consecutive batches on the worker thread, feeds each
        on host, stacks them into ONE [K, B, ...] array per slot and does ONE
        device put (shard_batches under DataParallel) — a StackedBatch the
        trainer runs as a single fused K-step dispatch
        (train(steps_per_dispatch=K)). Groups that cannot stack — trailing
        remainder, shape churn inside the group, members dropped by the
        divisibility filter — degrade to ordinary single device batches, so
        the sample stream is identical either way. The h2d_delay chaos site
        then fires once per GROUP (per-dispatch granularity).

    One iteration = one pass. Worker exceptions surface in the consumer;
    abandoning the iterator (break / GeneratorExit) retires the worker.

    Timers (PADDLE_TPU_TIMER): worker time lands in `hostFeed` (feeder +
    coerce) and `h2d` (device_put dispatch), the same names the synchronous
    trainer path stamps — the report shows where input time went either way.
    """

    def __init__(
        self,
        reader: Callable,
        feeder: Optional[Callable] = None,
        parallel: Optional[Any] = None,
        prefetch_depth: int = 2,
        device: Optional[Any] = None,
        feed_retries: int = 2,
        stack_k: int = 1,
    ):
        if prefetch_depth < 1:
            raise ValueError(f"prefetch_depth must be >= 1, got {prefetch_depth}")
        if stack_k < 1:
            raise ValueError(f"stack_k must be >= 1, got {stack_k}")
        self.reader = reader
        self.feeder = feeder
        self.parallel = parallel
        self.prefetch_depth = prefetch_depth
        self.device = device
        self.feed_retries = feed_retries
        self.stack_k = stack_k

    def __call__(self):
        return iter(self)

    def rebind_parallel(self, parallel: Optional[Any]) -> None:
        """Point FUTURE batches at a new DataParallel plan (elastic resize).
        The swap is one attribute store and each worker batch captures the
        plan exactly once at preparation start, so no batch is ever padded
        for one mesh and sharded for another — batches already prepared (or
        mid-flight) under the old plan surface to the consumer as old-mesh
        stragglers, which the trainer rebuilds host-side for the current
        plan. At most prefetch_depth + 1 batches take that slow path; the
        rest of the run lands directly on the new mesh."""
        self.parallel = parallel

    def _feed(self, raw: Any) -> Dict[str, Any]:
        """Raw reader item → feed-ready host batch (the hostFeed leg).
        Span + timer stamp the same interval: the timer aggregates, the span
        shows THIS batch's feed on the worker-thread row of the trace."""
        with trace.span("pipeline.hostFeed"):
            with stats.timer("hostFeed"):
                return (
                    self.feeder(raw)
                    if self.feeder is not None and not isinstance(raw, dict)
                    else coerce_batch(raw)
                )

    def _device_put(
        self, batch: Dict[str, Any], par: Optional[Any], stacked: bool = False
    ) -> Any:
        """Feed-ready batch → device-resident batch (the h2d leg) under the
        plan `par` the caller captured at preparation start (rebind_parallel
        may have swapped self.parallel since). stacked places a [K, B, ...]
        group with the scan-axis sharding; the chaos sleep fires once per
        call either way = once per dispatch."""
        faults.get().sleep("h2d_delay")  # chaos hook: slow transfer leg
        with trace.span("pipeline.h2d", stacked=stacked):
            if par is not None:
                put = par.shard_batches if stacked else par.shard_batch
                return put(batch)
            if self.device is not None:
                return {k: jax.device_put(v, self.device) for k, v in batch.items()}
            return {k: jax.device_put(v) for k, v in batch.items()}

    def _prepare(self, raw: Any) -> Any:
        """Raw reader item → device-resident batch (SKIP = drop)."""
        par = self.parallel  # one capture: pad and shard under ONE plan
        batch = self._feed(raw)
        with stats.timer("h2d"):
            if par is not None:
                # pad to the shard multiple with a row mask instead of
                # dropping (cost layers zero pad rows; see
                # DataParallel.pad_batch) — the sample stream now matches
                # the unsharded reader exactly; only unpaddable ragged
                # batches drop
                batch = par.maybe_pad_batch(batch, where="prefetcher")
                if batch is None:
                    return SKIP
            return self._device_put(batch, par)

    def _grouped_reader(self):
        buf: List[Any] = []
        for raw in self.reader():
            buf.append(raw)
            if len(buf) == self.stack_k:
                yield _Group(buf)
                buf = []
        if buf:
            yield _Group(buf)  # trailing remainder; degrades to singles

    def _prepare_group(self, group: "_Group") -> Any:
        """A run of stack_k raw items → one StackedBatch (the fast path: one
        np.stack + one device put covering K steps), or _Singles/SKIP when
        the group cannot stack as a whole."""
        par = self.parallel  # one capture: the whole group under ONE plan
        batches = [self._feed(raw) for raw in group]
        if par is not None:
            # a padded batch gains a mask slot → its signature differs →
            # the group degrades to singles below
            batches = [
                b
                for b in (
                    par.maybe_pad_batch(b, where="prefetcher group")
                    for b in batches
                )
                if b is not None
            ]
        if not batches:
            return SKIP
        stackable = (
            len(batches) == self.stack_k
            and len({stats.batch_signature(b) for b in batches}) == 1
        )
        with stats.timer("h2d"):
            if not stackable:
                return _Singles(self._device_put(b, par) for b in batches)
            stacked = {
                k: np.stack([np.asarray(b[k]) for b in batches])
                for k in batches[0]
            }
            out = self._device_put(stacked, par, stacked=True)
        sb = StackedBatch(out)
        sb.k = self.stack_k
        return sb

    def __iter__(self):
        if self.stack_k <= 1:
            return iter_async(
                self.reader, self._prepare, self.prefetch_depth,
                name="paddle-tpu-device-prefetch", retries=self.feed_retries,
            )
        return self._iter_stacked()

    def _iter_stacked(self):
        for item in iter_async(
            self._grouped_reader, self._prepare_group, self.prefetch_depth,
            name="paddle-tpu-device-prefetch", retries=self.feed_retries,
        ):
            if isinstance(item, _Singles):
                # degraded group: hand the batches over one by one — the
                # trainer re-buffers or single-steps them as appropriate
                for b in item:
                    yield b
            else:
                yield item
