"""Device-prefetching input pipeline.

The reference hides host input prep behind device execution with DoubleBuffer
(gserver/dataproviders/DataProvider.h:249) — a background thread that keeps
converted batches ahead of the GPU. On TPU two more host-side costs sit on
the step's critical path: batch sharding (the `DataParallel` placement) and
the H2D transfer itself. `DevicePrefetcher` moves all three off the hot loop:
a worker thread runs the feeder, applies the committed sharding, and
`jax.device_put`s up to `prefetch_depth` batches ahead, so host prep and H2D
overlap the donated compiled step ("RPC Considered Harmful" host/device
overlap discipline — chip-independent, it pays off on the CPU oracle too).

Composition: `DevicePrefetcher` subsumes `DoubleBuffer` (feeder + transfer on
one thread); it also accepts any reader that already yields feed-ready dict
batches — including a `DoubleBuffer` — and then only adds the device leg.
`SGDTrainer.train`/`test` recognize the already-on-device batches via
`is_device_batch` and skip their own coerce/shard work.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from paddle_tpu.core import stats

log = logging.getLogger("paddle_tpu.pipeline")

_STOP = object()
SKIP = object()  # prepare() return value meaning "drop this item"


def iter_async(
    reader: Callable,
    prepare: Callable[[Any], Any],
    capacity: int,
    name: str = "paddle-tpu-async-producer",
):
    """Shared background-producer loop (DoubleBuffer + DevicePrefetcher):
    a worker thread runs `prepare(raw)` over `reader()` and keeps up to
    `capacity` results ahead of the consumer. Items come out in reader
    order; `prepare` returning SKIP drops the item; worker exceptions
    re-raise in the consumer; abandoning the generator (break/GeneratorExit)
    retires the worker via the bounded put's stop poll."""
    q: "queue.Queue" = queue.Queue(maxsize=capacity)
    err: List[BaseException] = []
    stop = threading.Event()

    def put(item) -> bool:
        # bounded put that notices consumer abandonment
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def work():
        try:
            for raw in reader():
                item = prepare(raw)
                if item is SKIP:
                    continue
                if not put(item):
                    return
        except BaseException as e:  # surface worker errors to the consumer
            err.append(e)
        finally:
            put(_STOP)

    t = threading.Thread(target=work, daemon=True, name=name)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _STOP:
                break
            yield item
        t.join()
        if err:
            raise err[0]
    finally:
        stop.set()  # unblock and retire the producer on early exit


def is_device_batch(batch: Any) -> bool:
    """True when `batch` is a dict whose every slot already lives on device
    (committed jax.Arrays) — the trainer skips _coerce_batch/shard_batch."""
    return (
        isinstance(batch, dict)
        and bool(batch)
        and all(isinstance(v, jax.Array) for v in batch.values())
    )


def coerce_batch(batch: Dict[str, Any]) -> Dict[str, Any]:
    """numpy-ify a dict batch, failing fast on ragged/object slots instead of
    letting the jitted step produce an opaque shape error. Shared by the
    prefetcher worker and the trainer's synchronous path."""
    out: Dict[str, Any] = {}
    for k, v in batch.items():
        if isinstance(v, (np.ndarray, jax.Array)):
            out[k] = v
            continue
        arr = np.asarray(v)
        if arr.dtype == object:
            raise ValueError(
                f"batch slot {k!r} is ragged or non-numeric; feed it through "
                f"a DataFeeder (which pads sequences) instead of a raw dict"
            )
        out[k] = arr
    return out


class DevicePrefetcher:
    """Async host-feed + H2D pipeline in front of the compiled train step.

    reader: callable returning an iterator of raw batches (sample lists when
        `feeder` is given, else feed-ready dict batches — e.g. a DoubleBuffer).
    feeder: optional DataFeeder applied on the worker thread.
    parallel: optional parallel.DataParallel — batches are placed with its
        committed batch sharding (indivisible trailing batches are dropped,
        matching the trainer's drop_last semantics); without it, batches go to
        `device` (default: jax's default device) via plain device_put.
    prefetch_depth: how many device-resident batches to run ahead (N+1 are in
        flight counting the one the consumer holds). 2 hides a feeder that is
        as slow as the step; deeper only buys burst tolerance at the cost of
        device memory.

    One iteration = one pass. Worker exceptions surface in the consumer;
    abandoning the iterator (break / GeneratorExit) retires the worker.

    Timers (PADDLE_TPU_TIMER): worker time lands in `hostFeed` (feeder +
    coerce) and `h2d` (device_put dispatch), the same names the synchronous
    trainer path stamps — the report shows where input time went either way.
    """

    def __init__(
        self,
        reader: Callable,
        feeder: Optional[Callable] = None,
        parallel: Optional[Any] = None,
        prefetch_depth: int = 2,
        device: Optional[Any] = None,
    ):
        if prefetch_depth < 1:
            raise ValueError(f"prefetch_depth must be >= 1, got {prefetch_depth}")
        self.reader = reader
        self.feeder = feeder
        self.parallel = parallel
        self.prefetch_depth = prefetch_depth
        self.device = device

    def __call__(self):
        return iter(self)

    def _prepare(self, raw: Any) -> Any:
        """Raw reader item → device-resident batch (SKIP = drop)."""
        with stats.timer("hostFeed"):
            batch = (
                self.feeder(raw)
                if self.feeder is not None and not isinstance(raw, dict)
                else coerce_batch(raw)
            )
        with stats.timer("h2d"):
            if self.parallel is not None:
                if not self.parallel.batch_divisible(batch):
                    log.warning(
                        "prefetcher dropping batch: size not divisible by "
                        "the mesh data axis"
                    )
                    return SKIP
                return self.parallel.shard_batch(batch)
            if self.device is not None:
                return {k: jax.device_put(v, self.device) for k, v in batch.items()}
            return {k: jax.device_put(v) for k, v in batch.items()}

    def __iter__(self):
        return iter_async(
            self.reader, self._prepare, self.prefetch_depth,
            name="paddle-tpu-device-prefetch",
        )
